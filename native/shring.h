/* shring.h — the shared-memory pipe ring (worker <-> guest shim).
 *
 * Reference analog: upstream Shadow's shared-memory data channel
 * (SURVEY.md §2 "Shmem allocator" / shim-side syscall service, §3.3
 * latency budget): the byte buffer behind an emulated pipe lives in a
 * memfd mapped into BOTH the Python worker and the guest process, so the
 * shim services non-blocking pipe reads/writes entirely locally — zero
 * worker round trips — and only blocking edges (empty read, full or
 * atomic-split write, EOF/EPIPE) forward to the worker.
 *
 * Concurrency: none needed. Strict turn-taking means exactly one of
 * {worker, any guest thread} runs at any instant, globally; all fields
 * are plain loads/stores (volatile keeps the compiler honest across the
 * blocking boundaries).
 *
 * Layout: one 4 KiB header page + SHRING_CAP data bytes. rpos/wpos are
 * free-running u64 byte counters (data index = pos % SHRING_CAP).
 */
#ifndef SHRING_H
#define SHRING_H

#include <stdint.h>

#define SHRING_MAGIC 0x53524E47u /* "SRNG" */
#define SHRING_CAP 65536
#define SHRING_PIPE_BUF 4096 /* POSIX atomic-write bound (worker twin) */

struct shring {
  volatile uint32_t magic;
  volatile uint32_t cap; /* == SHRING_CAP (layout check) */
  volatile uint64_t rpos;
  volatile uint64_t wpos;
  /* maintained by the worker (end refcounts; EPIPE/EOF decisions) */
  volatile uint32_t readers;
  volatile uint32_t writers;
  /* worker sets when a thread/poller parks on this pipe; the shim then
   * marks dirty on every local op so the worker's wake scan is O(dirty) */
  volatile uint32_t has_waiters;
  volatile uint32_t dirty;
  /* worker gate: 0 disables shim-local service (strace mode,
   * model_unblocked_syscall_latency, teardown) */
  volatile uint32_t fast_ok;
  uint32_t pad0;
  /* shim-local ops on THIS ring (worker folds into per-pipe stats) */
  volatile uint64_t shim_ops;
};

#define SHRING_HDR 4096
#define SHRING_SIZE (SHRING_HDR + SHRING_CAP)
#define SHRING_DATA(h) ((volatile uint8_t *)(h) + SHRING_HDR)

/* clock-page extension: slot [2] counts shim-local fast ops process-wide
 * (the worker compares it against its last fold to decide whether any
 * ring needs a wake scan; doubles as the serviced-syscall count delta).
 * Slots [0]=emulated ns, [1]=virtual pid (native/identity.py). */
#define SHIM_PAGE_FASTOPS 2

#endif /* SHRING_H */
