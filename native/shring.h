/* shring.h — the shared-memory ring (worker <-> guest shim).
 *
 * Reference analog: upstream Shadow's shared-memory data channel
 * (SURVEY.md §2 "Shmem allocator" / shim-side syscall service, §3.3
 * latency budget): the byte buffer behind an emulated pipe OR an
 * ESTABLISHED stream socket lives in a memfd mapped into BOTH the Python
 * worker and the guest process, so the shim services non-blocking
 * reads/writes entirely locally — zero worker round trips — and only
 * blocking edges (empty read, over-budget write, errors) forward to the
 * worker.
 *
 * Concurrency: none needed. Strict turn-taking means exactly one of
 * {worker, any guest thread} runs at any instant, globally; all fields
 * are plain loads/stores (volatile keeps the compiler honest across the
 * blocking boundaries).
 *
 * Layout: one 4 KiB header page + cap data bytes. rpos/wpos are
 * free-running u64 byte counters (data index = pos % cap). cap is a
 * power of two chosen by the worker: SHRING_CAP for pipes, the
 * connection's next_pow2(max(recv_buffer, send_buffer)) for sockets.
 */
#ifndef SHRING_H
#define SHRING_H

#include <stddef.h>
#include <stdint.h>

#define SHRING_MAGIC 0x53524E47u /* "SRNG" */
#define SHRING_CAP 65536
#define SHRING_PIPE_BUF 4096 /* POSIX atomic-write bound (worker twin) */
/* parameterized caps: any power of two in [MIN, MAX] is a valid ring */
#define SHRING_CAP_MIN 4096
#define SHRING_CAP_MAX (1 << 24)

/* flags bits (worker-written; shim read-only) */
#define SHRING_F_HUP 1u  /* peer closed / EOF once drained (sockets) */
#define SHRING_F_ERR 2u  /* socket error pending: shim must forward */
#define SHRING_F_SOCK 4u /* ring backs a stream socket, not a pipe */

struct shring {
  volatile uint32_t magic;
  volatile uint32_t cap; /* power of two (layout check + modulo base) */
  volatile uint64_t rpos;
  volatile uint64_t wpos;
  /* maintained by the worker (end refcounts; EPIPE/EOF decisions) */
  volatile uint32_t readers;
  volatile uint32_t writers;
  /* worker sets when a thread/poller parks on this pipe; the shim then
   * marks dirty on every local op so the worker's wake scan is O(dirty) */
  volatile uint32_t has_waiters;
  volatile uint32_t dirty;
  /* worker gate: 0 disables shim-local service (strace mode,
   * model_unblocked_syscall_latency, overflow fallback, teardown) */
  volatile uint32_t fast_ok;
  volatile uint32_t flags; /* SHRING_F_* */
  /* shim-local ops on THIS ring (worker folds into per-pipe stats) */
  volatile uint64_t shim_ops;
  /* TX-role socket rings only: sender budget = send_buffer - buffered,
   * refreshed by the worker before every service reply (the TX ring is
   * drained by the fold that precedes servicing, so the budget is exact
   * for the whole guest turn — transport state is frozen mid-turn). */
  volatile uint64_t wbudget;
};

/* worker-twin offsets (shadow_tpu/native/managed.py packs by these) */
#define SHRING_OFF_MAGIC 0
#define SHRING_OFF_CAP 4
#define SHRING_OFF_RPOS 8
#define SHRING_OFF_WPOS 16
#define SHRING_OFF_READERS 24
#define SHRING_OFF_WRITERS 28
#define SHRING_OFF_HAS_WAITERS 32
#define SHRING_OFF_DIRTY 36
#define SHRING_OFF_FAST_OK 40
#define SHRING_OFF_FLAGS 44
#define SHRING_OFF_SHIM_OPS 48
#define SHRING_OFF_WBUDGET 56

_Static_assert(offsetof(struct shring, magic) == SHRING_OFF_MAGIC, "abi");
_Static_assert(offsetof(struct shring, cap) == SHRING_OFF_CAP, "abi");
_Static_assert(offsetof(struct shring, rpos) == SHRING_OFF_RPOS, "abi");
_Static_assert(offsetof(struct shring, wpos) == SHRING_OFF_WPOS, "abi");
_Static_assert(offsetof(struct shring, readers) == SHRING_OFF_READERS, "abi");
_Static_assert(offsetof(struct shring, writers) == SHRING_OFF_WRITERS, "abi");
_Static_assert(offsetof(struct shring, has_waiters) == SHRING_OFF_HAS_WAITERS,
               "abi");
_Static_assert(offsetof(struct shring, dirty) == SHRING_OFF_DIRTY, "abi");
_Static_assert(offsetof(struct shring, fast_ok) == SHRING_OFF_FAST_OK, "abi");
_Static_assert(offsetof(struct shring, flags) == SHRING_OFF_FLAGS, "abi");
_Static_assert(offsetof(struct shring, shim_ops) == SHRING_OFF_SHIM_OPS,
               "abi");
_Static_assert(offsetof(struct shring, wbudget) == SHRING_OFF_WBUDGET, "abi");

#define SHRING_HDR 4096
#define SHRING_SIZE (SHRING_HDR + SHRING_CAP)
#define SHRING_DATA(h) ((volatile uint8_t *)(h) + SHRING_HDR)

/* -- clock-page extension (the per-process 4 KiB SHADOW_TIME_SHM map) --
 *
 * u64 words (worker twin: shadow_tpu/native/managed.py):
 *   [0] emulated wall ns   [1] virtual pid (native/identity.py)
 *   [2] shim-local fast-op total (worker folds the delta into the
 *       "syscalls" + "shim_fast_syscalls" counters)
 *   [3] worker fold cursor for [2]
 *   [4] flags: bit0 = fast plane enabled (worker-written at page birth;
 *       0 under strace mode, model_unblocked_syscall_latency, or the
 *       SHADOW_TPU_SHIM_FASTPATH=0 escape hatch)
 *   [5..9] per-class fast-op counts (shim increments, worker reads then
 *       zeroes at fold): time, identity, ring read, ring write, readiness
 *   [15] oplog entry count (shim appends, worker zeroes after replay)
 *
 * bytes [256..1024): per-vfd readiness bytes, index = vfd - SHIM_VFD_BASE
 *   (worker publishes for WATCHED, non-ring-backed vfds only; the shim
 *   computes ring-backed fds' readiness from live ring state instead).
 *
 * bytes [1024..4088): socket-ring oplog — one u64 per in-shim socket op,
 *   low 32 bits = byte count, high 32 = (op << 24) | (vfd - VFD_BASE);
 *   op 1 = RECV (ring consume), 2 = SEND (ring append). The worker
 *   replays these IN ORDER at the next fold so the simulated transport
 *   sees the exact slow-path call sequence. A full oplog forces the shim
 *   to forward (never drop an entry).
 */
#define SHIM_PAGE_FASTOPS 2
#define SHIM_PAGE_CURSOR 3
#define SHIM_PAGE_FLAGS 4
#define SHIM_PAGE_CLS_TIME 5
#define SHIM_PAGE_CLS_IDENT 6
#define SHIM_PAGE_CLS_RING_R 7
#define SHIM_PAGE_CLS_RING_W 8
#define SHIM_PAGE_CLS_READY 9
#define SHIM_PAGE_OPLOG_N 15

#define SHIM_PAGE_F_FAST 1u

#define SHIM_READY_OFF 256
#define SHIM_READY_LEN 768
#define SHIM_READY_VALID 1u
#define SHIM_READY_IN 2u
#define SHIM_READY_OUT 4u
#define SHIM_READY_HUP 8u
#define SHIM_READY_ERR 16u

#define SHIM_OPLOG_OFF 1024
#define SHIM_OPLOG_MAX 383 /* (4088 - 1024) / 8 */
#define SHIM_OP_RECV 1
#define SHIM_OP_SEND 2

#endif /* SHRING_H */
