/* mt_workers — multi-threaded guest test program. Exercises the managed
 * thread machinery end to end:
 *   - two "ping-pong" threads alternate incrementing a shared counter to
 *     2*ROUNDS under a pthread_mutex + two condvars (futex WAIT/WAKE
 *     handoff between threads that are both parked at the worker);
 *   - one transfer thread fetches <nbytes> from the tgen server protocol
 *     over the (simulated or real) network;
 *   - main pthread_joins all three and reports totals plus elapsed time.
 *
 *   usage: mt_workers <ip> <port> <nbytes>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define ROUNDS 50

static pthread_mutex_t lock = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t cv = PTHREAD_COND_INITIALIZER;
static int counter;

static void *pinger(void *arg) {
  long parity = (long)arg;
  for (int i = 0; i < ROUNDS; i++) {
    pthread_mutex_lock(&lock);
    while ((counter & 1) != parity)
      pthread_cond_wait(&cv, &lock);
    counter++;
    pthread_cond_broadcast(&cv);
    pthread_mutex_unlock(&lock);
  }
  return (void *)(long)counter;
}

struct xfer { const char *ip; int port; long want; long got; };

static void *transfer(void *arg) {
  struct xfer *x = arg;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return (void *)-1L;
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons((unsigned short)x->port);
  inet_pton(AF_INET, x->ip, &dst.sin_addr);
  if (connect(fd, (struct sockaddr *)&dst, sizeof dst) != 0) return (void *)-2L;
  char req[9];
  snprintf(req, sizeof req, "%8ld", x->want);
  if (send(fd, req, 8, 0) != 8) return (void *)-3L;
  char buf[65536];
  while (x->got < x->want) {
    long r = recv(fd, buf, sizeof buf, 0);
    if (r <= 0) break;
    x->got += r;
  }
  close(fd);
  return (void *)x->got;
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <ip> <port> <nbytes>\n", argv[0]);
    return 2;
  }
  struct timespec t0, t1;
  clock_gettime(CLOCK_REALTIME, &t0);

  struct xfer x = {argv[1], atoi(argv[2]), atol(argv[3]), 0};
  pthread_t a, b, c;
  if (pthread_create(&a, NULL, pinger, (void *)0L) != 0) return 1;
  if (pthread_create(&b, NULL, pinger, (void *)1L) != 0) return 1;
  if (pthread_create(&c, NULL, transfer, &x) != 0) return 1;

  void *ra, *rb, *rc;
  pthread_join(a, &ra);
  pthread_join(b, &rb);
  pthread_join(c, &rc);

  clock_gettime(CLOCK_REALTIME, &t1);
  long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;

  if (counter != 2 * ROUNDS) {
    fprintf(stderr, "counter=%d want=%d\n", counter, 2 * ROUNDS);
    return 1;
  }
  if ((long)rc != x.want) {
    fprintf(stderr, "transfer got=%ld want=%ld\n", (long)rc, x.want);
    return 1;
  }
  printf("mt-complete counter=%d bytes=%ld elapsed_ms=%ld\n",
         counter, (long)rc, ms);
  return 0;
}
