/* tgen_srv — a real TCP server test program (dual-run oracle, like
 * tgen_cli.c but the accept side): serve <nconns> tgen-format requests
 * (8-byte decimal byte count -> that many bytes back), then exit 0.
 *
 *   usage: tgen_srv <port> <nconns>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <port> <nconns>\n", argv[0]);
    return 2;
  }
  int nconns = atoi(argv[2]);
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) { perror("socket"); return 1; }
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((unsigned short)atoi(argv[1]));
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind(srv, (struct sockaddr *)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 16) != 0) { perror("listen"); return 1; }

  static char buf[65536];
  memset(buf, 'x', sizeof buf);
  long served = 0;
  for (int i = 0; i < nconns; i++) {
    struct sockaddr_in peer;
    socklen_t plen = sizeof peer;
    int conn = accept(srv, (struct sockaddr *)&peer, &plen);
    if (conn < 0) { perror("accept"); return 1; }
    char req[9] = {0};
    long got = 0;
    while (got < 8) {
      long n = recv(conn, req + got, 8 - got, 0);
      if (n <= 0) { perror("recv"); return 1; }
      got += n;
    }
    long want = atol(req), sent = 0;
    while (sent < want) {
      long k = want - sent > (long)sizeof buf ? (long)sizeof buf : want - sent;
      long n = send(conn, buf, k, 0);
      if (n <= 0) { perror("send"); return 1; }
      sent += n;
    }
    close(conn);
    served += sent;
  }
  close(srv);
  printf("served=%d bytes=%ld\n", nconns, served);
  return 0;
}
