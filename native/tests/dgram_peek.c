/* dgram_peek — UDP MSG_PEEK test program: peeks a datagram (must not
 * consume), then reads it for real, then confirms the queue advanced.
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 3) { fprintf(stderr, "usage: %s ip port\n", argv[0]); return 2; }
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons((unsigned short)atoi(argv[2]));
  inet_pton(AF_INET, argv[1], &dst.sin_addr);
  sendto(fd, "one", 3, 0, (struct sockaddr *)&dst, sizeof dst);
  sendto(fd, "two", 3, 0, (struct sockaddr *)&dst, sizeof dst);
  char a[8] = {0}, b[8] = {0}, c[8] = {0};
  long r1 = recv(fd, a, sizeof a, MSG_PEEK); /* echo of "one" */
  long r2 = recv(fd, b, sizeof b, 0);
  long r3 = recv(fd, c, sizeof c, 0);
  if (r1 != 3 || r2 != 3 || r3 != 3 ||
      memcmp(a, "one", 3) || memcmp(b, "one", 3) || memcmp(c, "two", 3)) {
    fprintf(stderr, "peek: %ld/%s %ld/%s %ld/%s\n", r1, a, r2, b, r3, c);
    return 1;
  }
  printf("dgram-peek-ok\n");
  return 0;
}
