#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>
int main(void) {
  int sv[2];
  socketpair(AF_UNIX, SOCK_STREAM, 0, sv);
  pid_t c = fork();
  if (c == 0) { write(sv[1], "peekaboo", 8); _exit(0); }
  char a[16] = {0}, b[16] = {0};
  long r1 = recv(sv[0], a, 4, MSG_PEEK);
  long r2 = recv(sv[0], b, 8, 0);
  waitpid(c, 0, 0);
  if (r1 != 4 || memcmp(a, "peek", 4) || r2 != 8 || memcmp(b, "peekaboo", 8)) {
    fprintf(stderr, "peek broken: %ld %ld %s %s\n", r1, r2, a, b);
    return 1;
  }
  printf("peek-ok\n");
  return 0;
}
