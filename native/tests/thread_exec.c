/* thread_exec — execve from a NON-MAIN thread (the old magic-envp exec
 * only supported the main thread; the worker-mediated respawn supports
 * any): a pthread exec's the given program, replacing the whole process. */
#include <pthread.h>
#include <stdio.h>
#include <unistd.h>

static char **g_argv;

static void *execer(void *arg) {
  (void)arg;
  execv(g_argv[1], g_argv + 1);
  perror("execv");
  _exit(127);
}

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <path> [args...]\n", argv[0]);
    return 2;
  }
  g_argv = argv;
  pthread_t th;
  pthread_create(&th, NULL, execer, NULL);
  pthread_join(th, NULL);  /* never returns: exec replaces the process */
  fprintf(stderr, "exec did not happen\n");
  return 1;
}
