/* timer_tick — timerfd + eventfd + epoll event-loop test program.
 *
 * Arms a 100 ms periodic timerfd, epoll-waits, prints each tick with the
 * elapsed clock time; a self-eventfd injects one extra wakeup. Also prints
 * getpid(), which under the simulator must be a deterministic virtual pid.
 *
 *   usage: timer_tick <nticks>
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/timerfd.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
  int nticks = argc > 1 ? atoi(argv[1]) : 5;
  int tfd = timerfd_create(CLOCK_REALTIME, 0);
  int efd = eventfd(0, 0);
  if (tfd < 0 || efd < 0) { perror("fd create"); return 1; }
  int ep = epoll_create1(0);
  struct epoll_event ev = {EPOLLIN, {.fd = tfd}};
  epoll_ctl(ep, EPOLL_CTL_ADD, tfd, &ev);
  ev.data.fd = efd;
  epoll_ctl(ep, EPOLL_CTL_ADD, efd, &ev);

  unsigned long long one = 7;
  if (write(efd, &one, 8) != 8) { perror("eventfd write"); return 1; }

  struct timespec t0;
  clock_gettime(CLOCK_REALTIME, &t0);
  struct itimerspec its = {{0, 100 * 1000 * 1000}, {0, 100 * 1000 * 1000}};
  timerfd_settime(tfd, 0, &its, NULL);

  int ticks = 0, evt = 0;
  while (ticks < nticks) {
    struct epoll_event out[4];
    int n = epoll_wait(ep, out, 4, 2000);
    if (n <= 0) { fprintf(stderr, "epoll_wait %d\n", n); return 1; }
    for (int i = 0; i < n; i++) {
      unsigned long long val;
      if (read(out[i].data.fd, &val, 8) != 8) { perror("read"); return 1; }
      if (out[i].data.fd == efd) {
        evt += (int)val;
      } else {
        ticks += (int)val;
        struct timespec t1;
        clock_gettime(CLOCK_REALTIME, &t1);
        long ms = (t1.tv_sec - t0.tv_sec) * 1000
                  + (t1.tv_nsec - t0.tv_nsec) / 1000000;
        printf("tick %d at %ld ms\n", ticks, ms);
      }
    }
  }
  printf("done ticks=%d evt=%d pid=%d\n", ticks, evt, (int)getpid());
  return 0;
}
