/* sleep_clock — time/sleep semantics test program (dual-run oracle).
 *
 * Sleeps 250 ms three times, printing the clock before and after; the
 * elapsed time reported must be >= the requested sleep. Natively the Linux
 * kernel enforces that; in the simulator the emulated clock must.
 *
 *   usage: sleep_clock
 */
#include <stdio.h>
#include <time.h>

int main(void) {
  for (int i = 0; i < 3; i++) {
    struct timespec a, b, d = {0, 250 * 1000 * 1000};
    clock_gettime(CLOCK_REALTIME, &a);
    nanosleep(&d, NULL);
    clock_gettime(CLOCK_REALTIME, &b);
    long ms = (b.tv_sec - a.tv_sec) * 1000 + (b.tv_nsec - a.tv_nsec) / 1000000;
    printf("sleep %d elapsed_ms=%ld\n", i, ms);
    if (ms < 250) {
      printf("FAIL: clock went too fast\n");
      return 1;
    }
  }
  printf("ok\n");
  return 0;
}
