/* kill_child — signal-between-guests test program: forks a child that
 * sleeps forever; the parent waits 50 ms (sim time), SIGTERMs it by pid,
 * and verifies the wait status reports death by SIGTERM.
 */
#include <signal.h>
#include <stdio.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  pid_t child = fork();
  if (child < 0) { perror("fork"); return 1; }
  if (child == 0) {
    for (;;) {
      struct timespec ts = {3600, 0};
      nanosleep(&ts, NULL);
    }
  }
  struct timespec ts = {0, 50000000};
  nanosleep(&ts, NULL);
  if (kill(child, SIGTERM) != 0) { perror("kill"); return 1; }
  int status = 0;
  if (waitpid(child, &status, 0) != child) { perror("waitpid"); return 1; }
  if (!WIFSIGNALED(status) || WTERMSIG(status) != SIGTERM) {
    fprintf(stderr, "bad status %x\n", status);
    return 1;
  }
  printf("kill-ok child=%d sig=%d\n", (int)child, WTERMSIG(status));
  return 0;
}
