/* crash_null — negative-path test program: dereferences an unmapped page
 * with NO handler installed. Natively and under the simulator alike this
 * must DIE with SIGSEGV (the shim's TSC-trap handler must not swallow or
 * loop on a genuine fault it doesn't own).
 */
#include <stdio.h>

int main(void) {
  volatile int *bad;
  __asm__ volatile("mov $8, %0" : "=r"(bad));
  printf("about-to-crash\n");
  fflush(stdout);
  (void)*bad;
  printf("survived\n"); /* must never print */
  return 0;
}
