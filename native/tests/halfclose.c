/* halfclose — socketpair shutdown(SHUT_WR) test program: the parent
 * writes a request, half-closes its write side, and reads the reply
 * stream to EOF; the child reads to EOF (the parent's half-close),
 * replies, and exits. The classic request/response-over-one-connection
 * idiom.
 */
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) { perror("sp"); return 1; }
  pid_t c = fork();
  if (c == 0) {
    close(sv[0]);
    char buf[256];
    long total = 0, r;
    while ((r = read(sv[1], buf + total, sizeof buf - total)) > 0)
      total += r;  /* to EOF: parent's SHUT_WR */
    if (total != 11 || memcmp(buf, "request-abc", 11)) _exit(9);
    if (write(sv[1], "reply-xyz", 9) != 9) _exit(8);
    close(sv[1]);
    _exit(0);
  }
  close(sv[1]);
  if (send(sv[0], "request-abc", 11, 0) != 11) { perror("send"); return 1; }
  if (shutdown(sv[0], SHUT_WR) != 0) { perror("shutdown"); return 1; }
  char buf[256];
  long total = 0, r;
  while ((r = read(sv[0], buf + total, sizeof buf - total)) > 0)
    total += r;
  int status;
  waitpid(c, &status, 0);
  if (total != 9 || memcmp(buf, "reply-xyz", 9)) {
    fprintf(stderr, "bad reply %ld\n", total);
    return 1;
  }
  printf("halfclose-ok\n");
  return 0;
}
