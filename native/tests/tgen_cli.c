/* tgen_cli — a real, unmodified-style TCP client test program.
 *
 * Used BOTH natively (against a real TCP server, the Linux kernel as the
 * test oracle — SURVEY.md §4's dual-run trick) and as a managed process
 * inside the simulator. Behavior: connect to <ip> <port>, send the 8-byte
 * decimal byte-count request (the tgen wire format), read exactly that
 * many bytes back, print a summary line, exit 0.
 *
 *   usage: tgen_cli <ip> <port> <nbytes>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <ip> <port> <nbytes>\n", argv[0]);
    return 2;
  }
  long want = atol(argv[3]);

  struct timespec t0, t1;
  clock_gettime(CLOCK_REALTIME, &t0);

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }

  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((unsigned short)atoi(argv[2]));
  if (inet_pton(AF_INET, argv[1], &addr.sin_addr) != 1) {
    fprintf(stderr, "bad ip %s\n", argv[1]);
    return 2;
  }
  if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
    perror("connect");
    return 1;
  }

  char req[9];
  snprintf(req, sizeof req, "%8ld", want);
  if (send(fd, req, 8, 0) != 8) { perror("send"); return 1; }

  long got = 0;
  char buf[65536];
  while (got < want) {
    long n = recv(fd, buf, sizeof buf, 0);
    if (n <= 0) { perror("recv"); return 1; }
    got += n;
  }
  close(fd);

  clock_gettime(CLOCK_REALTIME, &t1);
  long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;
  printf("transfer-complete bytes=%ld elapsed_ms=%ld\n", got, ms);
  return 0;
}
