/* spair_echo — socketpair(2) test program: parent and forked child share
 * a duplex AF_UNIX pair; the child sleeps 30 ms (sim time under the
 * shim), uppercases what it reads, and sends it back; the parent
 * verifies the echo and the round-trip timing.
 */
#include <ctype.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
    perror("socketpair");
    return 1;
  }
  pid_t child = fork();
  if (child < 0) { perror("fork"); return 1; }
  if (child == 0) {
    close(sv[0]);
    char buf[64];
    long r = read(sv[1], buf, sizeof buf);
    if (r <= 0) _exit(9);
    struct timespec ts = {0, 30000000};
    nanosleep(&ts, NULL);
    for (long i = 0; i < r; i++) buf[i] = (char)toupper(buf[i]);
    if (write(sv[1], buf, r) != r) _exit(8);
    close(sv[1]);
    _exit(0);
  }
  close(sv[1]);
  struct timespec t0, t1;
  clock_gettime(CLOCK_REALTIME, &t0);
  if (send(sv[0], "hello-spair", 11, 0) != 11) { perror("send"); return 1; }
  char buf[64];
  long r = recv(sv[0], buf, sizeof buf, 0);
  clock_gettime(CLOCK_REALTIME, &t1);
  if (r != 11 || memcmp(buf, "HELLO-SPAIR", 11) != 0) {
    fprintf(stderr, "bad echo %ld\n", r);
    return 1;
  }
  int status;
  waitpid(child, &status, 0);
  long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;
  printf("spair-ok rtt_ms=%ld\n", ms);
  return 0;
}
