/* sysbreadth — dual-run exercise of the round-5 syscall families:
 * rlimits, sigaltstack, sendfile, signalfd, splice/tee, inotify.
 *
 * Prints a deterministic transcript; the native run is the oracle for
 * the program's own logic (kernel semantics), the managed run must
 * produce the same transcript from the emulated surface (the rlimit
 * VALUES differ native-vs-managed, so those lines print only invariants
 * that hold under both: set-then-get round trips). */
#define _GNU_SOURCE
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/inotify.h>
#include <sys/resource.h>
#include <sys/sendfile.h>
#include <sys/signalfd.h>
#include <sys/stat.h>
#include <unistd.h>

#define CHECK(x)                                                        \
  do {                                                                  \
    if (!(x)) {                                                         \
      fprintf(stderr, "FAIL %s:%d %s\n", __FILE__, __LINE__, #x);       \
      return 1;                                                         \
    }                                                                   \
  } while (0)

static const char *mask_name(uint32_t m) {
  if (m & IN_CREATE) return "CREATE";
  if (m & IN_MODIFY) return "MODIFY";
  if (m & IN_MOVED_FROM) return "MOVED_FROM";
  if (m & IN_MOVED_TO) return "MOVED_TO";
  if (m & IN_DELETE) return "DELETE";
  return "?";
}

int main(void) {
  /* 1. rlimits: set-then-get round trip */
  struct rlimit rl;
  CHECK(getrlimit(RLIMIT_NOFILE, &rl) == 0);
  CHECK(rl.rlim_cur > 0);
  struct rlimit want = {512, rl.rlim_max};
  CHECK(setrlimit(RLIMIT_NOFILE, &want) == 0);
  CHECK(getrlimit(RLIMIT_NOFILE, &rl) == 0);
  printf("rlimit-roundtrip=%lu\n", (unsigned long)rl.rlim_cur);

  /* 2. sigaltstack round trip */
  static char stk[16384];
  stack_t ss = {.ss_sp = stk, .ss_flags = 0, .ss_size = sizeof stk};
  CHECK(sigaltstack(&ss, NULL) == 0);
  stack_t old;
  CHECK(sigaltstack(NULL, &old) == 0);
  CHECK(old.ss_size == sizeof stk);
  printf("altstack-ok size=%zu\n", old.ss_size);

  /* 3. sendfile: file -> pipe, with and without explicit offset */
  int fd = open("sf.dat", O_CREAT | O_TRUNC | O_RDWR, 0644);
  CHECK(fd >= 0);
  char pat[1000];
  for (int i = 0; i < 1000; i++) pat[i] = (char)('a' + i % 26);
  for (int i = 0; i < 60; i++) CHECK(write(fd, pat, sizeof pat) == 1000);
  CHECK(lseek(fd, 0, SEEK_SET) == 0);
  int p[2];
  CHECK(pipe(p) == 0);
  long sent = sendfile(p[1], fd, NULL, 50000);
  CHECK(sent > 0);
  unsigned long sum = 0;
  long got = 0;
  char buf[4096];
  while (got < sent) {
    long r = read(p[0], buf, sizeof buf);
    CHECK(r > 0);
    for (long i = 0; i < r; i++) sum += (unsigned char)buf[i];
    got += r;
  }
  printf("sendfile=%ld sum=%lu\n", sent, sum);
  off_t off = 5;
  long s2 = sendfile(p[1], fd, &off, 10);
  CHECK(s2 == 10);
  CHECK(off == 15);
  CHECK(read(p[0], buf, 10) == 10);
  buf[10] = 0;
  printf("sendfile-off=%s\n", buf);

  /* 4. signalfd: blocked SIGUSR1 captured and read back */
  sigset_t m;
  sigemptyset(&m);
  sigaddset(&m, SIGUSR1);
  CHECK(sigprocmask(SIG_BLOCK, &m, NULL) == 0);
  int sfd = signalfd(-1, &m, 0);
  CHECK(sfd >= 0);
  CHECK(kill(getpid(), SIGUSR1) == 0);
  struct signalfd_siginfo si;
  CHECK(read(sfd, &si, sizeof si) == sizeof si);
  CHECK(si.ssi_signo == SIGUSR1);
  CHECK(si.ssi_pid == (uint32_t)getpid());
  printf("signalfd-ok signo=%u\n", si.ssi_signo);

  /* 5. splice + tee between pipes */
  int a[2], b[2], c[2];
  CHECK(pipe(a) == 0 && pipe(b) == 0 && pipe(c) == 0);
  CHECK(write(a[1], "hello-splice", 12) == 12);
  long t = tee(a[0], c[1], 12, 0);
  CHECK(t == 12);
  long sp = splice(a[0], NULL, b[1], NULL, 12, 0);
  CHECK(sp == 12);
  memset(buf, 0, sizeof buf);
  CHECK(read(b[0], buf, 12) == 12);
  CHECK(memcmp(buf, "hello-splice", 12) == 0);
  memset(buf, 0, sizeof buf);
  CHECK(read(c[0], buf, 12) == 12);
  CHECK(memcmp(buf, "hello-splice", 12) == 0);
  printf("splice-tee-ok\n");

  /* 6. inotify: directory watch sees create/modify/move/delete */
  CHECK(mkdir("watched", 0755) == 0);
  int ifd = inotify_init1(0);
  CHECK(ifd >= 0);
  int wd = inotify_add_watch(
      ifd, "watched",
      IN_CREATE | IN_MODIFY | IN_MOVED_FROM | IN_MOVED_TO | IN_DELETE);
  CHECK(wd > 0);
  int f = open("watched/f1", O_CREAT | O_WRONLY, 0644);
  CHECK(f >= 0);
  CHECK(write(f, "x", 1) == 1);
  close(f);
  CHECK(rename("watched/f1", "watched/f2") == 0);
  CHECK(unlink("watched/f2") == 0);
  char evbuf[2048];
  long n = read(ifd, evbuf, sizeof evbuf);
  CHECK(n > 0);
  printf("ino=");
  for (long o = 0; o < n;) {
    struct inotify_event *ev = (struct inotify_event *)(evbuf + o);
    printf("%s:%s ", mask_name(ev->mask), ev->len ? ev->name : "");
    o += sizeof(struct inotify_event) + ev->len;
  }
  printf("\n");
  printf("sysbreadth-ok\n");
  return 0;
}
