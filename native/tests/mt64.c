/* mt64 — 48 concurrent pthreads (beyond the old 31-slot channel window):
 * each sleeps a staggered sim duration and bumps a counter under a mutex.
 * Dual-run: native Linux oracle + managed (worker-emulated futexes, one
 * channel per thread in the widened [932, 995] fd window). */
#include <errno.h>
#include <pthread.h>
#include <stdio.h>
#include <string.h>
#include <time.h>

#define N 48
static int done;
static pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;

static void *worker(void *arg) {
  long i = (long)arg;
  struct timespec ts = {0, (long)(1000000 * (1 + i % 7))};
  nanosleep(&ts, NULL);
  pthread_mutex_lock(&mu);
  done++;
  pthread_mutex_unlock(&mu);
  return NULL;
}

int main(void) {
  pthread_t th[N];
  for (long i = 0; i < N; i++) {
    int rc = pthread_create(&th[i], NULL, worker, (void *)i);
    if (rc != 0) {
      fprintf(stderr, "create %ld failed: %s\n", i, strerror(rc));
      return 1;
    }
  }
  for (int i = 0; i < N; i++) pthread_join(th[i], NULL);
  printf("mt64 done=%d\n", done);
  return done == N ? 0 : 1;
}
