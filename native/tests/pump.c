/* pump — syscall-dense managed guest for the IPC-rate benchmark.
 *
 * argv: [iters] [chunk]
 * Does `iters` write+read round trips of `chunk` bytes through a pipe to
 * itself (both ends emulated vfds, so every call is a full shim->worker
 * round trip), then prints a checksum. Measures the steady-state syscall
 * service rate without network or spawn costs (VERDICT r3 item #5's
 * managed_50 critique: 19 syscalls/process measures spawn, not IPC). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

int main(int argc, char **argv) {
  long iters = argc > 1 ? atol(argv[1]) : 10000;
  size_t chunk = argc > 2 ? (size_t)atol(argv[2]) : 512;
  if (chunk > 4096) chunk = 4096;
  int p[2];
  if (pipe(p) != 0) {
    perror("pipe");
    return 1;
  }
  char *buf = malloc(chunk);
  memset(buf, 0x5a, chunk);
  unsigned long sum = 0;
  for (long i = 0; i < iters; i++) {
    buf[0] = (char)(i & 0xFF);
    if (write(p[1], buf, chunk) != (ssize_t)chunk) {
      perror("write");
      return 1;
    }
    ssize_t r = read(p[0], buf, chunk);
    if (r != (ssize_t)chunk) {
      perror("read");
      return 1;
    }
    sum += (unsigned char)buf[0];
  }
  printf("pump-ok iters=%ld chunk=%zu sum=%lu\n", iters, chunk, sum);
  return 0;
}
