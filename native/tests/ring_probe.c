/* ring_probe — drives the shim's socket fast plane on purpose.
 *
 * Connects to a tgen server, requests <nbytes>, then drains the reply in
 * deliberately SMALL odd-sized recvs so that a delivered burst sits in
 * the connection's shared ring across many consecutive recv calls (each
 * completing in-shim, zero worker round trips). Before every recv it
 * issues a zero-timeout poll (served from ring state / the readiness
 * page once granted), and after the payload it drains to EOF — against a
 * server that closes after serving, the final recv returns 0 IN-SHIM
 * from the ring's HUP flag. Finishes with a raw (non-libc-interposed)
 * clock_gettime via syscall(2) to exercise the in-shim raw time service.
 *
 *   usage: ring_probe <ip> <port> <nbytes>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <ip> <port> <nbytes>\n", argv[0]);
    return 2;
  }
  long want = atol(argv[3]);

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((unsigned short)atoi(argv[2]));
  if (inet_pton(AF_INET, argv[1], &addr.sin_addr) != 1) {
    fprintf(stderr, "bad ip %s\n", argv[1]);
    return 2;
  }
  struct timespec t0; /* fetch epoch: raw clock, same service as below */
  syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &t0);
  if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
    perror("connect");
    return 1;
  }

  char req[9];
  snprintf(req, sizeof req, "%8ld", want);
  if (send(fd, req, 8, 0) != 8) { perror("send"); return 1; }

  long got = 0, recvs = 0, polls = 0, ready = 0;
  char buf[997]; /* small + odd: many ring reads per delivered burst */
  while (got < want) {
    struct pollfd p = {fd, POLLIN, 0};
    int pr = poll(&p, 1, 0);
    polls++;
    if (pr > 0) ready++;
    long n = recv(fd, buf, sizeof buf, 0);
    if (n < 0) { perror("recv"); return 1; }
    if (n == 0) break; /* early EOF: report what we got */
    got += n;
    recvs++;
  }
  long n, eof_zero = 0;
  while ((n = recv(fd, buf, sizeof buf, 0)) > 0) got += n;
  if (n == 0) eof_zero = 1; /* server closed: clean EOF */

  struct timespec ts;
  syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &ts);
  close(fd);
  /* fetch_ns: connect -> request -> payload -> EOF drain, measured BY
   * THE GUEST through the virtualized monotonic clock — i.e. the fetch
   * latency the real binary itself observes in simulated time (the
   * model-fidelity audit in bench.py compares this against the Python
   * tgen twin's completion_times on the same topology) */
  printf("ring-probe bytes=%ld recvs=%ld polls=%ld ready=%ld eof=%ld "
         "mono_s=%ld fetch_ns=%lld\n",
         got, recvs, polls, ready, eof_zero, (long)ts.tv_sec,
         (long long)(ts.tv_sec - t0.tv_sec) * 1000000000LL +
             (long long)(ts.tv_nsec - t0.tv_nsec));
  return 0;
}
