import subprocess
import time

t0 = time.monotonic()
r = subprocess.run(["/root/repo/native/build/sleep_clock"],
                   capture_output=True, text=True, timeout=300)
elapsed_ms = int((time.monotonic() - t0) * 1000)
assert r.returncode == 0, (r.returncode, r.stderr)
assert "ok" in r.stdout, r.stdout
lines = [l for l in r.stdout.splitlines() if "elapsed_ms=250" in l]
print(f"child-lines={len(lines)} parent_elapsed_ms={elapsed_ms}")
print("ok")
