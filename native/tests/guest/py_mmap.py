"""Dual-run guest: mmap over files served by the virtual file surface.

Runs unmodified both against the real kernel and as a managed process;
stdout must be byte-identical (tests/test_vfs.py). Under the simulator the
open() returns a vfd and the trapped mmap round-trips through the worker
(SCM_RIGHTS real-fd reply; managed.py::_mmap_vfd)."""

import hashlib
import mmap

data = bytes(range(256)) * 512  # 128 KiB
with open("blob.bin", "wb") as f:
    f.write(data)
with open("blob.bin", "rb") as f:
    m = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    print("len", len(m))
    print("sha", hashlib.sha256(m[:]).hexdigest())
    print("head", m[:8].hex(), "tail", m[-8:].hex())
    m.close()

# shared writable mapping: stores must land in the backing file
with open("rw.bin", "wb") as f:
    f.write(b"\0" * 4096)
with open("rw.bin", "r+b") as f:
    m = mmap.mmap(f.fileno(), 4096)
    m[0:5] = b"HELLO"
    m[4091:4096] = b"WORLD"
    m.flush()
    m.close()
back = open("rw.bin", "rb").read()
print("rw", back[:5].decode(), back[-5:].decode(), len(back))

# a synthesized file maps too (memfd snapshot); content matches read()
hosts_read = open("/etc/hosts", "rb").read()
with open("/etc/hosts", "rb") as f:
    m = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
    print("synth_match", bytes(m[:]) == hosts_read)
    m.close()
print("done")
