"""A real Python program run as a managed guest: fetches a URL with
urllib over the simulated network and reports timing from the simulated
clock. usage: http_fetch.py <url> <expect_bytes>"""
import sys
import time
import urllib.request

url, want = sys.argv[1], int(sys.argv[2])
t0 = time.time()
with urllib.request.urlopen(url, timeout=30) as r:
    body = r.read()
dt_ms = int((time.time() - t0) * 1000)
assert len(body) == want, (len(body), want)
print(f"fetched {len(body)} bytes in {dt_ms} ms status={r.status}")
