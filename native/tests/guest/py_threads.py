import threading
import time

results = []
lock = threading.Lock()

def worker(i):
    time.sleep(0.05 * (i + 1))
    with lock:
        results.append((i, time.monotonic()))

t0 = time.monotonic_ns()
threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
for t in threads:
    t.start()
for t in threads:
    t.join()
elapsed_ms = (time.monotonic_ns() - t0) // 1_000_000
order = [i for i, _ in sorted(results, key=lambda x: x[1])]
print(f"order={order} n={len(results)} elapsed_ms={elapsed_ms}")
print("ok")
