"""File-surface exercise guest: runs unmodified natively AND under the
simulator; stdout must be byte-identical (the dual-run oracle)."""
import os

os.makedirs("data/sub", exist_ok=True)
with open("data/a.txt", "w") as f:
    f.write("hello\n")
with open("data/a.txt", "a") as f:
    f.write("world\n")
os.rename("data/a.txt", "data/b.txt")
with open("data/sub/c.bin", "wb") as f:
    f.write(bytes(range(64)) * 100)

print("read:", open("data/b.txt").read().strip().replace("\n", "|"))
print("listdir:", sorted(os.listdir("data")))
st = os.stat("data/sub/c.bin")
print("size:", st.st_size)
print("isfile:", os.path.isfile("data/b.txt"),
      os.path.isdir("data/sub"), os.path.exists("data/nope"))
with open("data/sub/c.bin", "rb") as f:
    f.seek(100)
    print("seek-read:", f.read(8).hex())
fd = os.open("data/sub/c.bin", os.O_RDWR)
print("pread:", os.pread(fd, 6, 64).hex())
os.pwrite(fd, b"ZZ", 10)
print("after-pwrite:", os.pread(fd, 4, 9).hex())
os.close(fd)
os.unlink("data/b.txt")
print("after-unlink:", sorted(os.listdir("data")))
os.rmdir("data/sub") if not os.listdir("data/sub") else None
print("cwd-tail:", os.path.basename(os.getcwd()) != "")
print("ok")
