import socket, os
print("hostname:", socket.gethostname())
print("nodename:", os.uname().nodename)
