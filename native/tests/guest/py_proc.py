"""Managed-only guest: the synthesized /proc must present the virtual
machine identity (1 CPU, 2 GB, simulated uptime, vpid) regardless of the
real host (tests/test_vfs.py asserts the printed invariants)."""

import os

cpu = open("/proc/cpuinfo").read()
print("ncpu", cpu.count("processor\t:"))
print([ln for ln in cpu.splitlines() if ln.startswith("model name")][0])
print(open("/proc/meminfo").read().splitlines()[0])
st = open("/proc/self/status").read().splitlines()
print([ln for ln in st if ln.split(":")[0] in ("Name", "PPid", "Threads")])
stat = open("/proc/self/stat").read().split()
print("stat_pid_is_getpid", int(stat[0]) == os.getpid())
print("comm", stat[1])
up = float(open("/proc/uptime").read().split()[0])
print("uptime_is_sim", 0.0 <= up < 100.0)
maps = open("/proc/self/maps").read()
print("maps_has_stack_heap", "[stack]" in maps and "[heap]" in maps)
print("cpu_count", os.cpu_count())
