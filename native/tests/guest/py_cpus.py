import os
print("cpus:", os.cpu_count(), len(os.sched_getaffinity(0)))
