/* fork_pipe — multi-process guest test program: parent pipes, forks; the
 * child sleeps 50 ms (simulated time under the shim), writes a message
 * through the pipe, and exits with code 7; the parent reads to EOF,
 * reaps with waitpid, and verifies the exit status and elapsed time.
 *
 * Exercises: fork (shim-side real fork + worker adoption), cross-process
 * pipes, wait4 emulation, exit_group code capture, fd-table snapshot
 * refcounts (each side closes its unused end).
 */
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  int pfd[2];
  if (pipe(pfd) != 0) {
    perror("pipe");
    return 1;
  }
  struct timespec t0, t1;
  clock_gettime(CLOCK_REALTIME, &t0);
  pid_t child = fork();
  if (child < 0) {
    perror("fork");
    return 1;
  }
  if (child == 0) {
    close(pfd[0]);
    struct timespec ts = {0, 50000000}; /* 50 ms */
    nanosleep(&ts, NULL);
    char msg[64];
    int n = snprintf(msg, sizeof msg, "hello-from-child pid=%d\n", getpid());
    if (write(pfd[1], msg, n) != n) _exit(9);
    close(pfd[1]);
    _exit(7);
  }
  close(pfd[1]);
  char buf[256];
  int got = 0;
  for (;;) {
    long r = read(pfd[0], buf + got, sizeof buf - 1 - got);
    if (r < 0) { perror("read"); return 1; }
    if (r == 0) break;
    got += r;
  }
  buf[got] = 0;
  close(pfd[0]);
  int status = 0;
  pid_t reaped = waitpid(child, &status, 0);
  clock_gettime(CLOCK_REALTIME, &t1);
  long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;
  if (reaped != child) {
    fprintf(stderr, "waitpid: %d != %d\n", reaped, child);
    return 1;
  }
  if (!WIFEXITED(status) || WEXITSTATUS(status) != 7) {
    fprintf(stderr, "bad status %x\n", status);
    return 1;
  }
  if (strncmp(buf, "hello-from-child pid=", 21) != 0) {
    fprintf(stderr, "bad msg: %s\n", buf);
    return 1;
  }
  printf("fork-complete child=%d msg_bytes=%d elapsed_ms=%ld\n",
         (int)child, got, ms);
  printf("ok\n");
  return 0;
}
