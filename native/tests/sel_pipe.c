/* sel_pipe — select(2) test program: parent pipes+forks; the child sleeps
 * 100 ms then writes; the parent dup2's the read end to fd 0 and selects
 * on it with a 1 s timeout — select must wake on data (not timeout), and
 * the measured wait is SIMULATED time under the shim.
 */
#include <stdio.h>
#include <string.h>
#include <sys/select.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(void) {
  int pfd[2];
  if (pipe(pfd) != 0) { perror("pipe"); return 1; }
  pid_t child = fork();
  if (child < 0) { perror("fork"); return 1; }
  if (child == 0) {
    close(pfd[0]);
    struct timespec ts = {0, 100000000};
    nanosleep(&ts, NULL);
    if (write(pfd[1], "ping\n", 5) != 5) _exit(9);
    _exit(0);
  }
  close(pfd[1]);
  dup2(pfd[0], 0);
  close(pfd[0]);
  struct timespec t0, t1;
  clock_gettime(CLOCK_REALTIME, &t0);
  fd_set rfds;
  FD_ZERO(&rfds);
  FD_SET(0, &rfds);
  struct timeval tv = {1, 0};
  int n = select(1, &rfds, NULL, NULL, &tv);
  clock_gettime(CLOCK_REALTIME, &t1);
  long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;
  if (n != 1 || !FD_ISSET(0, &rfds)) {
    fprintf(stderr, "select: n=%d\n", n);
    return 1;
  }
  char buf[16];
  long r = read(0, buf, sizeof buf);
  if (r != 5 || memcmp(buf, "ping\n", 5) != 0) {
    fprintf(stderr, "read: %ld\n", r);
    return 1;
  }
  int status;
  waitpid(child, &status, 0);
  printf("select-ok waited_ms=%ld\n", ms);
  return 0;
}
