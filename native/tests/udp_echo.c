/* udp_echo — UDP datagram client test program: sends <count> datagrams to
 * an echo server and verifies each reply round-trips.
 *
 *   usage: udp_echo <ip> <port> <count>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <ip> <port> <count>\n", argv[0]);
    return 2;
  }
  int count = atoi(argv[3]);
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons((unsigned short)atoi(argv[2]));
  inet_pton(AF_INET, argv[1], &dst.sin_addr);

  for (int i = 0; i < count; i++) {
    char msg[64], reply[64];
    struct timespec t0, t1;
    clock_gettime(CLOCK_REALTIME, &t0);
    int n = snprintf(msg, sizeof msg, "ping-%d", i);
    if (sendto(fd, msg, n, 0, (struct sockaddr *)&dst, sizeof dst) != n) {
      perror("sendto");
      return 1;
    }
    struct sockaddr_in src;
    socklen_t slen = sizeof src;
    long r = recvfrom(fd, reply, sizeof reply, 0, (struct sockaddr *)&src, &slen);
    if (r != n || memcmp(msg, reply, n) != 0) {
      fprintf(stderr, "bad echo %d: %ld\n", i, r);
      return 1;
    }
    clock_gettime(CLOCK_REALTIME, &t1);
    long ms = (t1.tv_sec - t0.tv_sec) * 1000 + (t1.tv_nsec - t0.tv_nsec) / 1000000;
    printf("echo %d rtt_ms=%ld\n", i, ms);
  }
  printf("ok count=%d\n", count);
  return 0;
}
