/* Connected-UDP semantics (ADVICE r2: connect(2) on SOCK_DGRAM must be
 * instant and record a default peer) + recvmsg(MSG_PEEK) on datagrams +
 * monotonic-clock origin sanity. Self-contained dual-run test: socket A
 * is a manual echo responder, socket B is the connected client.
 * argv[1] = the address A is reachable at (127.0.0.1 natively, the host's
 * simulated IP under the simulator). */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define PORT 9001

static int fail(const char *what) {
  fprintf(stderr, "FAIL: %s\n", what);
  return 1;
}

int main(int argc, char **argv) {
  const char *ip = argc > 1 ? argv[1] : "127.0.0.1";

  /* connect(2) on a dgram socket must complete instantly: no handshake
   * traffic exists for UDP, so a wall/sim-time stall here is a bug. */
  struct timespec c0, c1;
  clock_gettime(CLOCK_MONOTONIC, &c0);

  int a = socket(AF_INET, SOCK_DGRAM, 0);
  int b = socket(AF_INET, SOCK_DGRAM, 0);
  if (a < 0 || b < 0) return fail("socket");
  struct sockaddr_in sa;
  memset(&sa, 0, sizeof sa);
  sa.sin_family = AF_INET;
  sa.sin_port = htons(PORT);
  sa.sin_addr.s_addr = INADDR_ANY;
  if (bind(a, (struct sockaddr *)&sa, sizeof sa) != 0) return fail("bind");
  sa.sin_addr.s_addr = inet_addr(ip);
  if (connect(b, (struct sockaddr *)&sa, sizeof sa) != 0)
    return fail("connect");

  clock_gettime(CLOCK_MONOTONIC, &c1);
  long conn_ms = (c1.tv_sec - c0.tv_sec) * 1000 +
                 (c1.tv_nsec - c0.tv_nsec) / 1000000;
  if (conn_ms > 1000) return fail("dgram connect stalled");
  /* monotonic origin is boot-ish, not the UNIX epoch (< ~10 years) */
  if (c1.tv_sec > 3650L * 86400) return fail("monotonic epoch-based");

  /* send() and write() both use the connected peer */
  if (send(b, "ping1", 5, 0) != 5) return fail("send");
  if (write(b, "ping2", 5) != 5) return fail("write");

  /* A answers each ping to its source */
  char buf[64];
  struct sockaddr_in src;
  for (int i = 0; i < 2; i++) {
    socklen_t slen = sizeof src;
    ssize_t n = recvfrom(a, buf, sizeof buf, 0,
                         (struct sockaddr *)&src, &slen);
    if (n != 5 || memcmp(buf, "ping", 4) != 0) return fail("recvfrom A");
    char pong[6] = "pongX";
    pong[4] = buf[4];
    if (sendto(a, pong, 5, 0, (struct sockaddr *)&src, slen) != 5)
      return fail("sendto A");
  }

  /* recvmsg(MSG_PEEK) must copy without consuming */
  struct iovec iov = {buf, sizeof buf};
  struct msghdr mh;
  memset(&mh, 0, sizeof mh);
  mh.msg_iov = &iov;
  mh.msg_iovlen = 1;
  if (recvmsg(b, &mh, MSG_PEEK) != 5 || memcmp(buf, "pong1", 5) != 0)
    return fail("recvmsg peek");
  memset(buf, 0, sizeof buf);
  if (recvmsg(b, &mh, 0) != 5 || memcmp(buf, "pong1", 5) != 0)
    return fail("recvmsg consume");
  /* read(2) works on a connected dgram socket and sees the NEXT datagram */
  memset(buf, 0, sizeof buf);
  if (read(b, buf, sizeof buf) != 5 || memcmp(buf, "pong2", 5) != 0)
    return fail("read next dgram");

  close(a);
  close(b);
  printf("udp-conn-ok\n");
  return 0;
}
