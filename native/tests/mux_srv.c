/* mux_srv — an event-loop TCP server (poll or epoll), the I/O-multiplexing
 * test program. Nonblocking listener + connections; serves tgen-format
 * requests (8-byte decimal count -> counted bytes back) to many clients
 * CONCURRENTLY — the interleaving proves readiness notification works.
 *
 *   usage: mux_srv <port> <nconns> <poll|epoll>
 */
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#define MAXC 64

struct conn {
  int fd;
  long want, sent;
  int got_req;
  char req[8];
  int reqn;
};

static struct conn conns[MAXC];
static int nconn;
static char buf[32768];

static void set_nonblock(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <port> <nconns> <poll|epoll>\n", argv[0]);
    return 2;
  }
  int total = atoi(argv[2]);
  if (total > MAXC) {
    fprintf(stderr, "nconns > %d unsupported\n", MAXC);
    return 2;
  }
  int use_epoll = strcmp(argv[3], "epoll") == 0;
  memset(buf, 'y', sizeof buf);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((unsigned short)atoi(argv[1]));
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (bind(srv, (struct sockaddr *)&addr, sizeof addr) != 0 ||
      listen(srv, 16) != 0) {
    perror("bind/listen");
    return 1;
  }
  set_nonblock(srv);

  int epfd = -1;
  if (use_epoll) {
    epfd = epoll_create1(0);
    struct epoll_event ev = {EPOLLIN, {.u64 = (unsigned long)-1}};
    epoll_ctl(epfd, EPOLL_CTL_ADD, srv, &ev);
  }

  int done = 0, accepted = 0;
  long total_bytes = 0;
  while (done < total) {
    /* build interest sets */
    if (!use_epoll) {
      struct pollfd pfds[MAXC + 1];
      int n = 0;
      pfds[n].fd = srv;
      pfds[n].events = accepted < total ? POLLIN : 0;
      n++;
      for (int i = 0; i < nconn; i++) {
        if (conns[i].fd < 0) continue;
        pfds[n].fd = conns[i].fd;
        pfds[n].events = conns[i].got_req ? POLLOUT : POLLIN;
        n++;
      }
      if (poll(pfds, n, 5000) < 0) { perror("poll"); return 1; }
    } else {
      struct epoll_event evs[MAXC];
      if (epoll_wait(epfd, evs, MAXC, 5000) < 0) { perror("epoll"); return 1; }
    }
    /* accept */
    for (;;) {
      int fd = accept(srv, NULL, NULL);
      if (fd < 0) break;
      set_nonblock(fd);
      conns[nconn].fd = fd;
      conns[nconn].want = -1;
      if (use_epoll) {
        /* EPOLLIN only until we have something to write: registering
         * EPOLLOUT on an idle writable socket would make epoll_wait
         * level-trigger forever (a busy-loop under any kernel — and a
         * sim-time livelock under the simulator) */
        struct epoll_event ev = {EPOLLIN, {.u64 = (unsigned)nconn}};
        epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
      }
      nconn++;
      accepted++;
    }
    /* service every connection that is ready (level-triggered) */
    for (int i = 0; i < nconn; i++) {
      struct conn *c = &conns[i];
      if (c->fd < 0) continue;
      if (!c->got_req) {
        long n = recv(c->fd, c->req + c->reqn, 8 - c->reqn, 0);
        if (n > 0) c->reqn += (int)n;
        if (c->reqn == 8) {
          char tmp[9];
          memcpy(tmp, c->req, 8);
          tmp[8] = 0;
          c->want = atol(tmp);
          c->got_req = 1;
          if (use_epoll) {
            struct epoll_event ev = {EPOLLOUT, {.u64 = (unsigned)i}};
            epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
      }
      if (c->got_req && c->sent < c->want) {
        long k = c->want - c->sent;
        if (k > (long)sizeof buf) k = sizeof buf;
        long n = send(c->fd, buf, k, 0);
        if (n > 0) {
          c->sent += n;
          total_bytes += n;
        }
      }
      if (c->got_req && c->sent >= c->want) {
        if (use_epoll) epoll_ctl(epfd, EPOLL_CTL_DEL, c->fd, NULL);
        close(c->fd);
        c->fd = -1;
        done++;
      }
    }
  }
  printf("served=%d bytes=%ld mode=%s\n", done, total_bytes, argv[3]);
  return 0;
}
