/* tsc_clock — raw TSC timing test program: reads rdtsc/rdtscp around a
 * 100 ms nanosleep and reports the cycle delta. Natively the delta is
 * whatever the hardware counter says (positive, frequency-dependent);
 * under the simulator PR_SET_TSC traps both instructions and the shim
 * serves simulated nanoseconds at a nominal 1 GHz, so the delta is
 * EXACTLY 100000000 — the definitive "even the TSC follows sim time".
 */
#include <stdint.h>
#include <stdio.h>
#include <time.h>

static inline uint64_t rdtsc(void) {
  uint32_t lo, hi;
  __asm__ volatile("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtscp(void) {
  uint32_t lo, hi, aux;
  __asm__ volatile("rdtscp" : "=a"(lo), "=d"(hi), "=c"(aux));
  return ((uint64_t)hi << 32) | lo;
}

int main(void) {
  uint64_t t0 = rdtsc();
  struct timespec ts = {0, 100000000};
  nanosleep(&ts, NULL);
  uint64_t t1 = rdtscp();
  if (t1 <= t0) {
    fprintf(stderr, "non-monotonic tsc: %llu -> %llu\n",
            (unsigned long long)t0, (unsigned long long)t1);
    return 1;
  }
  printf("delta_cycles=%llu\n", (unsigned long long)(t1 - t0));
  printf("ok\n");
  return 0;
}
