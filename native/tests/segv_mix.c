/* segv_mix — SIGSEGV-handler coexistence test program: installs its own
 * SIGSEGV handler (sigaction, SA_SIGINFO), recovers from a deliberate bad
 * dereference via siglongjmp, and then reads the TSC around a 100 ms
 * nanosleep. Natively this just works; under the simulator the shim must
 * chain the genuine fault to this handler while KEEPING rdtsc
 * virtualization active afterward (delta exactly 100000000 at 1 GHz).
 */
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <time.h>

static sigjmp_buf env;
static volatile int caught;

static void on_segv(int sig, siginfo_t *info, void *ctx) {
  (void)sig;
  (void)info;
  (void)ctx;
  caught = 1;
  siglongjmp(env, 1);
}

static inline uint64_t rdtsc(void) {
  uint32_t lo, hi;
  __asm__ volatile("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
}

int main(void) {
  struct sigaction sa;
  sa.sa_sigaction = on_segv;
  sa.sa_flags = SA_SIGINFO;
  sigemptyset(&sa.sa_mask);
  if (sigaction(SIGSEGV, &sa, NULL) != 0) {
    perror("sigaction");
    return 1;
  }

  if (sigsetjmp(env, 1) == 0) {
    /* opaque so the compiler can't prove the dereference is out of bounds */
    volatile int *bad;
    __asm__ volatile("mov $8, %0" : "=r"(bad));
    (void)*bad; /* unmapped page */
    fprintf(stderr, "fault did not fire\n");
    return 1;
  }
  if (!caught) {
    fprintf(stderr, "handler not reached\n");
    return 1;
  }
  printf("fault-recovered\n");

  uint64_t t0 = rdtsc();
  struct timespec ts = {0, 100000000};
  nanosleep(&ts, NULL);
  uint64_t t1 = rdtsc();
  if (t1 <= t0) {
    fprintf(stderr, "non-monotonic tsc after recovery\n");
    return 1;
  }
  printf("delta_cycles=%llu\n", (unsigned long long)(t1 - t0));
  printf("ok\n");
  return 0;
}
