/* spair_pump — socketpair analog of pump.c for the shring fast path. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
  long iters = argc > 1 ? atol(argv[1]) : 10000;
  size_t chunk = argc > 2 ? (size_t)atol(argv[2]) : 512;
  if (chunk > 4096) chunk = 4096;
  int sv[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
    perror("socketpair");
    return 1;
  }
  char *buf = malloc(chunk);
  memset(buf, 0x5a, chunk);
  unsigned long sum = 0;
  for (long i = 0; i < iters; i++) {
    buf[0] = (char)(i & 0xFF);
    if (write(sv[0], buf, chunk) != (ssize_t)chunk) { perror("write"); return 1; }
    if (read(sv[1], buf, chunk) != (ssize_t)chunk) { perror("read"); return 1; }
    sum += (unsigned char)buf[0];
  }
  printf("spair-pump-ok iters=%ld chunk=%zu sum=%lu\n", iters, chunk, sum);
  return 0;
}
