/* iov_msg — scatter-gather socket IO test program: sends a request with
 * sendmsg (two iovecs), reads the reply with recvmsg (three iovecs) and
 * readv, and reports via writev to stdout. Uses the same 8-byte-decimal
 * request protocol as tgen_srv, so it runs against either the real kernel
 * loopback (oracle) or the simulated network (managed).
 *
 *   usage: iov_msg <ip> <port> <nbytes>
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <ip> <port> <nbytes>\n", argv[0]);
    return 2;
  }
  long want = atol(argv[3]);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof dst);
  dst.sin_family = AF_INET;
  dst.sin_port = htons((unsigned short)atoi(argv[2]));
  inet_pton(AF_INET, argv[1], &dst.sin_addr);
  if (connect(fd, (struct sockaddr *)&dst, sizeof dst) != 0) {
    perror("connect");
    return 1;
  }

  /* request: "   NNNNN" split across two iovecs via sendmsg */
  char req[9];
  snprintf(req, sizeof req, "%8ld", want);
  struct iovec siov[2] = {{req, 3}, {req + 3, 5}};
  struct msghdr mh;
  memset(&mh, 0, sizeof mh);
  mh.msg_iov = siov;
  mh.msg_iovlen = 2;
  long sent = 0;
  while (sent < 8) {
    long k = sendmsg(fd, &mh, 0);
    if (k <= 0) { perror("sendmsg"); return 1; }
    sent += k;
    /* advance the iovec cursor for short sends */
    struct iovec *v = mh.msg_iov;
    long adv = k;
    while (adv > 0 && mh.msg_iovlen > 0) {
      if ((long)v->iov_len <= adv) {
        adv -= v->iov_len;
        v++;
        mh.msg_iov = v;
        mh.msg_iovlen--;
      } else {
        v->iov_base = (char *)v->iov_base + adv;
        v->iov_len -= adv;
        adv = 0;
      }
    }
  }

  /* reply: alternate recvmsg (3 iovecs) and readv (2 iovecs); verify the
   * byte pattern the server sends ('x' fill) survives the scatter. */
  char b0[1000], b1[3000], b2[7000];
  long got = 0;
  int use_recvmsg = 1;
  while (got < want) {
    long r;
    if (use_recvmsg) {
      struct iovec riov[3] = {{b0, sizeof b0}, {b1, sizeof b1}, {b2, sizeof b2}};
      struct msghdr rh;
      memset(&rh, 0, sizeof rh);
      rh.msg_iov = riov;
      rh.msg_iovlen = 3;
      r = recvmsg(fd, &rh, 0);
    } else {
      struct iovec riov[2] = {{b1, sizeof b1}, {b2, sizeof b2}};
      r = readv(fd, riov, 2);
    }
    if (r < 0) { perror("recv"); return 1; }
    if (r == 0) break;
    /* spot-check the fill byte in every buffer region touched */
    long c = r;
    const struct { char *p; long n; } regs[3] = {
        {use_recvmsg ? b0 : b1, use_recvmsg ? (long)sizeof b0 : (long)sizeof b1},
        {use_recvmsg ? b1 : b2, use_recvmsg ? (long)sizeof b1 : (long)sizeof b2},
        {b2, (long)sizeof b2}};
    for (int i = 0; i < 3 && c > 0; i++) {
      long k = c < regs[i].n ? c : regs[i].n;
      for (long j = 0; j < k; j += 997)
        if (regs[i].p[j] != 'x') { fprintf(stderr, "corrupt @%ld\n", j); return 1; }
      c -= k;
    }
    got += r;
    use_recvmsg = !use_recvmsg;
  }
  if (got != want) {
    fprintf(stderr, "short: got=%ld want=%ld\n", got, want);
    return 1;
  }

  char line[64];
  int n = snprintf(line, sizeof line, "iov-complete bytes=%ld\n", got);
  struct iovec out[2] = {{line, 4}, {line + 4, n - 4}};
  if (writev(1, out, 2) != n) return 1;
  close(fd);
  return 0;
}
