/* ftool — a file-configured transfer tool, run unmodified both natively
 * and under the simulator (the VERDICT r2 item #3 "Done" shape): it reads
 * its whole job from a CONFIG FILE, performs the transfers over TCP (the
 * tgen wire format: 8-byte decimal byte-count request, then the payload),
 * and writes a TRANSFER LOG file — so the dual-run comparison covers the
 * virtual file surface end to end (openat/read on the config, stat,
 * open/write/fsync/rename on the log) on top of the socket surface.
 *
 *   usage: ftool <config-file>
 *   config line: <ip> <port> <nbytes> <count>
 *   log: transfer i bytes=N        (one line per completed transfer)
 *        done transfers=K total=M
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/socket.h>
#include <unistd.h>

static long fetch(const char *ip, int port, long want) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((unsigned short)port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1) { close(fd); return -1; }
  if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
    close(fd);
    return -1;
  }
  char req[9];
  snprintf(req, sizeof req, "%8ld", want);
  if (send(fd, req, 8, 0) != 8) { close(fd); return -1; }
  long got = 0;
  char buf[65536];
  while (got < want) {
    long n = recv(fd, buf, sizeof buf, 0);
    if (n <= 0) { close(fd); return -1; }
    got += n;
  }
  close(fd);
  return got;
}

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <config-file>\n", argv[0]);
    return 2;
  }
  struct stat st;
  if (stat(argv[1], &st) != 0 || st.st_size <= 0) {
    perror("stat config");
    return 1;
  }
  FILE *cf = fopen(argv[1], "r");
  if (!cf) { perror("open config"); return 1; }
  char ip[64];
  int port = 0;
  long nbytes = 0;
  int count = 0;
  if (fscanf(cf, "%63s %d %ld %d", ip, &port, &nbytes, &count) != 4) {
    fprintf(stderr, "bad config\n");
    return 1;
  }
  fclose(cf);

  /* write-then-rename: exercises creat/write/fsync/rename on the vfs */
  FILE *lg = fopen("transfer.log.tmp", "w");
  if (!lg) { perror("open log"); return 1; }
  long total = 0;
  int done = 0;
  for (int i = 0; i < count; i++) {
    long got = fetch(ip, port, nbytes);
    if (got != nbytes) {
      fprintf(lg, "transfer %d FAILED\n", i);
      continue;
    }
    fprintf(lg, "transfer %d bytes=%ld\n", i, got);
    done++;
    total += got;
  }
  fprintf(lg, "done transfers=%d total=%ld\n", done, total);
  fflush(lg);
  fsync(fileno(lg));
  fclose(lg);
  if (rename("transfer.log.tmp", "transfer.log") != 0) {
    perror("rename");
    return 1;
  }
  printf("ftool-ok transfers=%d\n", done);
  return done == count ? 0 : 1;
}
