/* exec_chain — fork+exec test program: forks, the child execve's the
 * given command (argv[1..]), the parent waits and reports the child's
 * exit status. The classic process-spawning idiom, run unmodified.
 *
 *   usage: exec_chain <path> [args...]
 */
#include <stdio.h>
#include <sys/wait.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <path> [args...]\n", argv[0]);
    return 2;
  }
  pid_t child = fork();
  if (child < 0) {
    perror("fork");
    return 1;
  }
  if (child == 0) {
    execv(argv[1], argv + 1);
    perror("execv");
    _exit(127);
  }
  int status = 0;
  if (waitpid(child, &status, 0) != child) {
    perror("waitpid");
    return 1;
  }
  if (!WIFEXITED(status)) {
    fprintf(stderr, "child not exited: %x\n", status);
    return 1;
  }
  printf("exec-chain child=%d status=%d\n", (int)child, WEXITSTATUS(status));
  return WEXITSTATUS(status);
}
