/* libshadow_shim.so — the managed-process side of phase 4.
 *
 * Reference analog: the LD_PRELOAD shim + seccomp SIGSYS trap of
 * SURVEY.md §2 "Shim" / §3.2-3.3, re-designed around a deliberately DUMB
 * shim: it knows nothing about syscall semantics. Every trapped syscall is
 * forwarded verbatim ({nr, args[6]}) over a fixed-fd socketpair to the
 * Python worker, which owns all emulation state and reads/writes this
 * process's memory directly via process_vm_readv/writev (the MemoryManager
 * equivalent, shadow_tpu/native/memory.py). Strict turn-taking falls out
 * of the blocking request/reply protocol: exactly one of {worker, managed
 * thread} runs at a time.
 *
 * v1 interposition set (documented in shadow_tpu/native/managed.py): the
 * seccomp filter TRAPS the simulation-relevant syscalls (time, sleep,
 * sockets, stdio writes, virtual fds, getrandom) and ALLOWS everything
 * else natively (memory management, dynamic linking, real file IO below
 * the virtual-fd base). This is inverted from upstream Shadow's trap-all
 * stance — chosen so unknown syscalls degrade to native behavior instead
 * of crashing — and is tightened per-family as emulation coverage grows.
 *
 * Time has a fast path: the worker maintains an mmap'd page holding the
 * emulated clock (ns since the UNIX epoch), updated before every turn
 * grant; interposed clock_gettime/gettimeofday/time read it without a
 * context switch (and without the vDSO, which seccomp cannot intercept).
 *
 * Wire protocol (host byte order, x86-64):
 *   request : uint64 nr; uint64 args[6];          (56 bytes)
 *   response: int64 ret;                          (8 bytes, -errno on error)
 *   handshake: request with nr = SHIM_HELLO, arg0 = getpid()
 */
#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <pthread.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <stddef.h>
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <time.h>
#include <ucontext.h>

/* pre-5.9 glibc headers lack the close_range number; it is ABI-stable */
#ifndef SYS_close_range
#define SYS_close_range 436
#endif
#include <unistd.h>

#define SHIM_IPC_FD 995          /* worker dup2()s the socketpair here   */
#define SHIM_IPC_LOW 932         /* per-thread channels live in [LOW, 995] */
#define SHIM_VFD_BASE 0x100000   /* fds >= this are simulated sockets    */
#define SHIM_HELLO 0xFFFFFFFFu
/* thread-management pseudo-syscalls (worker analogs in native/managed.py) */
#define SHIM_SPAWN_THREAD 0xFFFFFFF0u
#define SHIM_THREAD_HELLO 0xFFFFFFF1u
#define SHIM_THREAD_JOIN 0xFFFFFFF2u
#define SHIM_THREAD_EXIT 0xFFFFFFF3u
#define SHIM_FORK_INTENT 0xFFFFFFF4u
#define SHIM_FORK_COMMIT 0xFFFFFFF5u
#define SHIM_RESOLVE 0xFFFFFFF6u /* arg0 = name ptr -> IPv4 as host u32 */
#define SHIM_AUDIT_NOTE 0xFFFFFFF7u /* arg0 = first-use unemulated nr */
/* worker reply sentinel: "re-issue this syscall natively through the
 * gadget" — the virtual-FS passthrough for paths the worker does not
 * virtualize (outside the errno range, so unambiguous) */
#define SHIM_RET_NATIVE (-1000000)

struct shim_req { uint64_t nr; uint64_t args[6]; };

static volatile int64_t *shim_time_page; /* [0] emulated ns since UNIX
  epoch; [1] this process's virtual pid (identity fast path — INVALID in
  forked children, which share the parent's page; see shim_is_fork) */
static int shim_is_fork; /* set in the child after the fork replay */
static int shim_active;
static long shim_real_pid, shim_real_tid; /* cached pre-seccomp: the trapped
                                             getpid/gettid return vpids */
/* each guest thread talks to the worker over its own channel (strict
 * turn-taking needs per-thread wakeups); main uses the spawn-time fd */
static __thread int shim_tls_fd = SHIM_IPC_FD;
/* a freshly cloned thread runs glibc bootstrap (set_robust_list, rseq)
 * BEFORE the shim trampoline pins its own channel; until then it must not
 * write on the (main thread's) default channel */
static __thread int shim_tls_ready;

/* ---- the syscall gadget -------------------------------------------------
 *
 * Audit mode (SHADOW_AUDIT=1, experimental.native_audit) inverts the trap
 * policy: the seccomp filter ALLOWS syscalls only when the reported
 * instruction pointer lies inside one fixed executable page — the shim's
 * syscall gadget — and TRAPS everything the guest issues itself, so every
 * natively-passed syscall number is observed and counted exactly once
 * (VERDICT r2 item #5: instrument the reality boundary). The page sits at
 * a fixed address so the BPF constants are
 * compile-time; it holds one stub translating the function-call ABI to
 * the syscall ABI:  gadget(nr, a1..a6) -> syscall(nr, a1..a6).
 * Outside audit mode the gadget is still used (one indirect call per raw
 * syscall) but the filter never consults the IP.
 *
 * COOPERATIVE-GUEST ASSUMPTION: the gadget page's address is fixed and
 * both filters ALLOW any syscall issued from it, so code that KNOWS the
 * address can jump there directly and bypass every trap. Audit mode's
 * "every native passthrough is observed" guarantee therefore holds for
 * guests that go through libc/the vDSO (everything we run), not for
 * adversarial code hunting the gadget. Upstream Shadow's shim has the
 * same property (its shim text is at a knowable address and its filter
 * must allow the shim's own raw syscalls); a simulator is not a sandbox.
 * Randomizing the page per process (and passing the address into the
 * BPF at install time) would narrow this to guessing, at the cost of a
 * filter rebuild per process — documented, deliberately not done. */
#define SHIM_GADGET_ADDR ((void *)0x5D5E00000000ul)
typedef long (*shim_gadget_fn)(long, long, long, long, long, long, long);
static shim_gadget_fn shim_gadget; /* == SHIM_GADGET_ADDR once mapped */
static int shim_audit_on;
static uint8_t shim_audit_seen[64]; /* nrs already reported (once each) */

/* raw syscalls only — the shim must not recurse through libc wrappers */
static long raw3(long nr, long a, long b, long c) {
  long ret;
  if (shim_gadget) return shim_gadget(nr, a, b, c, 0, 0, 0);
  __asm__ volatile("syscall"
                   : "=a"(ret)
                   : "a"(nr), "D"(a), "S"(b), "d"(c)
                   : "rcx", "r11", "memory");
  return ret;
}

static long raw5(long nr, long a, long b, long c, long d, long e) {
  long ret;
  if (shim_gadget) return shim_gadget(nr, a, b, c, d, e, 0);
  register long r10 __asm__("r10") = d;
  register long r8 __asm__("r8") = e;
  __asm__ volatile("syscall"
                   : "=a"(ret)
                   : "a"(nr), "D"(a), "S"(b), "d"(c), "r"(r10), "r"(r8)
                   : "rcx", "r11", "memory");
  return ret;
}

/* mov rax,rdi; mov rdi,rsi; mov rsi,rdx; mov rdx,rcx; mov r10,r8;
 * mov r8,r9; mov r9,[rsp+8]; syscall; ret */
static const uint8_t shim_gadget_stub[] = {
    0x48, 0x89, 0xf8, 0x48, 0x89, 0xf7, 0x48, 0x89, 0xd6,
    0x48, 0x89, 0xca, 0x4d, 0x89, 0xc2, 0x4d, 0x89, 0xc8,
    0x4c, 0x8b, 0x4c, 0x24, 0x08, 0x0f, 0x05, 0xc3};

/* 6-arg inline-asm fallback for the rare no-gadget case (sentinel
 * re-issues must still work; the filters allow these nrs by default when
 * no gadget page could be mapped — non-audit mode only) */
static long raw6_asm(long nr, long a, long b, long c, long d, long e,
                     long f) {
  long ret;
  register long r10 __asm__("r10") = d;
  register long r8 __asm__("r8") = e;
  register long r9 __asm__("r9") = f;
  __asm__ volatile("syscall"
                   : "=a"(ret)
                   : "a"(nr), "D"(a), "S"(b), "d"(c), "r"(r10), "r"(r8),
                     "r"(r9)
                   : "rcx", "r11", "memory");
  return ret;
}

static int shim_map_gadget(void) {
  void *page = mmap(SHIM_GADGET_ADDR, 4096, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED_NOREPLACE, -1, 0);
  if (page != SHIM_GADGET_ADDR) {
    /* EEXIST: the page survived a fork (ctors do not re-run there, but a
     * dlopen-style reload could land here) — reuse it if it is ours */
    if (memcmp(SHIM_GADGET_ADDR, shim_gadget_stub,
               sizeof shim_gadget_stub) == 0) {
      shim_gadget = (shim_gadget_fn)SHIM_GADGET_ADDR;
      return 0;
    }
    return -1;
  }
  memcpy(page, shim_gadget_stub, sizeof shim_gadget_stub);
  if (mprotect(page, 4096, PROT_READ | PROT_EXEC) != 0) return -1;
  shim_gadget = (shim_gadget_fn)page;
  return 0;
}

static int write_all(const void *buf, size_t n) {
  const char *p = buf;
  while (n) {
    long r = raw3(SYS_write, shim_tls_fd, (long)p, (long)n);
    if (r < 0) { if (r == -EINTR) continue; return -1; }
    p += r; n -= (size_t)r;
  }
  return 0;
}

static int read_all(void *buf, size_t n) {
  char *p = buf;
  while (n) {
    long r = raw3(SYS_read, shim_tls_fd, (long)p, (long)n);
    if (r < 0) { if (r == -EINTR) continue; return -1; }
    if (r == 0) raw3(SYS_exit_group, 125, 0, 0); /* worker vanished */
    p += r; n -= (size_t)r;
  }
  return 0;
}

static int shim_poll_streak_reset(void); /* defined with the ring plane */

static int64_t forward(uint64_t nr, uint64_t a0, uint64_t a1, uint64_t a2,
                       uint64_t a3, uint64_t a4, uint64_t a5) {
  struct shim_req rq = {nr, {a0, a1, a2, a3, a4, a5}};
  int64_t ret = -ENOSYS;
  shim_poll_streak_reset();
  if (write_all(&rq, sizeof rq) != 0) return -EPIPE;
  if (read_all(&ret, sizeof ret) != 0) return -EPIPE;
  return ret;
}

/* receive one 8-byte reply carrying an SCM_RIGHTS fd on the caller's
 * channel; returns the fd (or -1) and stores the payload in *val_out */
static int shim_recv_fd(int64_t *val_out) {
  char cbuf[CMSG_SPACE(sizeof(int))];
  struct iovec iov = {val_out, 8};
  struct msghdr mh;
  memset(&mh, 0, sizeof mh);
  mh.msg_iov = &iov;
  mh.msg_iovlen = 1;
  mh.msg_control = cbuf;
  mh.msg_controllen = sizeof cbuf;
  long r = raw3(SYS_recvmsg, shim_tls_fd, (long)&mh, 0);
  if (r != 8) return -1;
  struct cmsghdr *c = CMSG_FIRSTHDR(&mh);
  if (!c || c->cmsg_type != SCM_RIGHTS) return -1;
  int fd;
  memcpy(&fd, CMSG_DATA(c), sizeof fd);
  return fd;
}

/* ---- shared-memory rings (native/shring.h) -----------------------------
 * The worker backs emulated pipes AND established stream sockets with a
 * memfd ring mapped here on first use (SHIM_RET_MAPRING reply +
 * SCM_RIGHTS; sockets get a pair, role 0 = RX, role 1 = TX). Non-blocking
 * reads/writes are then served entirely locally — zero worker round
 * trips; blocking edges (empty read, full/atomic-split/over-budget write,
 * EPIPE, errors) forward as before. Every in-shim SOCKET op is appended
 * to the clock page's oplog so the worker can replay the exact call
 * sequence against the simulated transport at the next fold — bit
 * determinism does not depend on the fast plane being on.
 * Strict turn-taking makes the shared state race-free. The buffer
 * pointer the guest passed is dereferenced directly (a bad pointer that
 * the kernel would EFAULT faults here instead — cooperative guests). */
#include "../shring.h"
/* The simulation boots at 2000-01-01T00:00:00Z (shadow_tpu/core/time.py
 * EMULATED_EPOCH); monotonic-family clocks originate at boot == sim
 * start (used by both the libc interposition and the raw SIGSYS path). */
#define SHIM_EMULATED_EPOCH_NS 946684800000000000LL
#define SHIM_RET_MAPRING (-1000001)
#define SHIM_RING_MAX 128

struct shim_ring_ent {
  long vfd;
  int role; /* 0 = read end, 1 = write end */
  volatile struct shring *h;
};
static struct shim_ring_ent shim_rings[SHIM_RING_MAX];

static volatile struct shring *shim_ring_find(long fd, int role) {
  for (int i = 0; i < SHIM_RING_MAX; i++)
    if (shim_rings[i].h && shim_rings[i].vfd == fd &&
        shim_rings[i].role == role)
      return shim_rings[i].h;
  return NULL;
}

static void shim_ring_unmap(int i) {
  raw3(SYS_munmap, (long)shim_rings[i].h,
       (long)(SHRING_HDR + shim_rings[i].h->cap), 0);
  shim_rings[i].h = NULL;
}

static void shim_ring_drop(long fd) {
  for (int i = 0; i < SHIM_RING_MAX; i++)
    if (shim_rings[i].h && shim_rings[i].vfd == fd) shim_ring_unmap(i);
}

static long raw6_asm(long, long, long, long, long, long, long);

static void shim_ring_install(long vfd, int role, int mfd) {
  shim_gadget_fn m = shim_gadget ? shim_gadget : raw6_asm;
  /* cap is parameterized (pipes: SHRING_CAP; sockets: the connection's
   * buffer size): learn the map size from the memfd itself */
  char st[144];
  long sz = 0;
  if (raw3(SYS_fstat, mfd, (long)st, 0) == 0)
    memcpy(&sz, st + 48, sizeof sz); /* struct stat.st_size (x86_64) */
  if (sz < SHRING_HDR + SHRING_CAP_MIN ||
      sz > SHRING_HDR + (long)SHRING_CAP_MAX) {
    raw3(SYS_close, mfd, 0, 0);
    return;
  }
  long p = m(9 /* mmap */, 0, sz, 3 /* RW */, 1 /* SHARED */, mfd, 0);
  raw3(SYS_close, mfd, 0, 0);
  if (p <= 0) return;
  volatile struct shring *h = (volatile struct shring *)p;
  uint32_t cap = h->cap;
  if (h->magic != SHRING_MAGIC || cap < SHRING_CAP_MIN ||
      cap > SHRING_CAP_MAX || (cap & (cap - 1)) != 0 ||
      (long)cap + SHRING_HDR != sz) {
    raw3(SYS_munmap, p, sz, 0);
    return;
  }
  int slot = -1;
  for (int i = 0; i < SHIM_RING_MAX; i++) {
    if (shim_rings[i].h && shim_rings[i].vfd == vfd &&
        shim_rings[i].role == role) {
      /* post-fork/duplicate re-offer: replace the inherited mapping */
      shim_ring_unmap(i);
      slot = i;
      break;
    }
    if (!shim_rings[i].h && slot < 0) slot = i;
  }
  if (slot < 0) { raw3(SYS_munmap, p, sz, 0); return; } /* full */
  shim_rings[slot].vfd = vfd;
  shim_rings[slot].role = role;
  shim_rings[slot].h = h;
}

static int shim_page_rw; /* the clock page mapped writable (counter slot) */

/* worker-granted master switch for the poll/time/socket fast paths
 * (0 under strace, syscall-latency modeling, SHADOW_TPU_SHIM_FASTPATH=0) */
static int shim_page_fast(void) {
  return shim_page_rw &&
         ((uint64_t)shim_time_page[SHIM_PAGE_FLAGS] & SHIM_PAGE_F_FAST);
}

static void shim_count_class(int word) {
  if (shim_page_rw) {
    shim_time_page[SHIM_PAGE_FASTOPS]++;
    shim_time_page[word]++;
  }
}

static void shim_ring_mark(volatile struct shring *h, int cls_word) {
  h->shim_ops++;
  h->dirty = 1; /* worker's wake scan is gated on the page counter */
  shim_count_class(cls_word);
}

/* append one socket op to the clock-page oplog (replayed by the worker,
 * in order, at the next fold). 0 = log full: caller must forward. */
static int shim_oplog_append(int op, long fd, uint64_t nbytes) {
  if (!shim_page_rw) return 0;
  uint64_t cnt = (uint64_t)shim_time_page[SHIM_PAGE_OPLOG_N];
  if (cnt >= SHIM_OPLOG_MAX) return 0;
  uint64_t idx = (uint64_t)fd - SHIM_VFD_BASE;
  shim_time_page[SHIM_OPLOG_OFF / 8 + cnt] =
      (int64_t)(nbytes | ((((uint64_t)op << 24) | idx) << 32));
  shim_time_page[SHIM_PAGE_OPLOG_N] = (int64_t)(cnt + 1);
  return 1;
}

/* local service; INT64_MIN = not serviceable here, forward to worker */
static int64_t shim_ring_read(long fd, uint64_t buf, uint64_t count,
                              int peek) {
  volatile struct shring *h = shim_ring_find(fd, 0);
  /* without a writable counter slot the worker cannot observe local
   * activity (wake scans would starve parked peers): forward everything */
  if (!h || !h->fast_ok || !shim_page_rw) return INT64_MIN;
  int sock = (h->flags & SHRING_F_SOCK) != 0;
  if (sock && (shim_is_fork || (h->flags & SHRING_F_ERR) ||
               !shim_page_fast()))
    return INT64_MIN; /* fork children / error state: worker owns it */
  if (!sock && peek) return INT64_MIN; /* MSG_PEEK on a plain pipe end */
  uint64_t avail = h->wpos - h->rpos;
  if (avail == 0) {
    if (sock && (h->flags & SHRING_F_HUP)) {
      /* drained + peer closed: EOF, exactly the worker's _vfd_recv */
      shim_ring_mark(h, SHIM_PAGE_CLS_RING_R);
      return 0;
    }
    return INT64_MIN; /* EOF / park / EAGAIN: worker's call */
  }
  uint64_t k = count < avail ? count : avail;
  if (k == 0) return 0;
  uint64_t cap = h->cap;
  uint64_t off = h->rpos % cap;
  uint64_t first = cap - off;
  if (first > k) first = k;
  memcpy((void *)buf, (const void *)(SHRING_DATA(h) + off), first);
  if (k > first)
    memcpy((void *)(buf + first), (const void *)SHRING_DATA(h), k - first);
  if (!peek) {
    if (sock && !shim_oplog_append(SHIM_OP_RECV, fd, k))
      return INT64_MIN; /* oplog full: rpos untouched, worker re-serves */
    h->rpos += k;
  }
  shim_ring_mark(h, SHIM_PAGE_CLS_RING_R);
  return (int64_t)k;
}

static int64_t shim_ring_write(long fd, uint64_t buf, uint64_t count) {
  volatile struct shring *h = shim_ring_find(fd, 1);
  if (!h || !h->fast_ok || !shim_page_rw) return INT64_MIN;
  int sock = (h->flags & SHRING_F_SOCK) != 0;
  if (sock) {
    /* HUP: the worker's _vfd_send returns EPIPE on peer_closed — forward.
     * Budget: only FULL writes complete locally (partial accepts and
     * parking are the worker's call); wbudget is exact for the whole
     * turn because transport state is frozen while the guest runs. */
    if (shim_is_fork || (h->flags & (SHRING_F_ERR | SHRING_F_HUP)) ||
        !shim_page_fast())
      return INT64_MIN;
    if (count == 0) return 0;
    if (h->wbudget < count) return INT64_MIN;
  } else {
    if (h->readers == 0) return INT64_MIN; /* EPIPE + SIGPIPE: worker */
    if (count == 0) return 0;
  }
  uint64_t cap = h->cap;
  uint64_t room = cap - (h->wpos - h->rpos);
  if (room < count) return INT64_MIN; /* partial/atomic/park: worker */
  if (sock && !shim_oplog_append(SHIM_OP_SEND, fd, count))
    return INT64_MIN; /* oplog full: nothing written yet, forward */
  uint64_t off = h->wpos % cap;
  uint64_t first = cap - off;
  if (first > count) first = count;
  memcpy((void *)(SHRING_DATA(h) + off), (const void *)buf, first);
  if (count > first)
    memcpy((void *)SHRING_DATA(h), (const void *)(buf + first),
           count - first);
  h->wpos += count;
  if (sock) h->wbudget -= count;
  shim_ring_mark(h, SHIM_PAGE_CLS_RING_W);
  return (int64_t)count;
}

/* ---- in-shim poll/ppoll over live ring state + the readiness page ------
 *
 * Mirrors the worker's _revents EXACTLY or forwards. Per entry:
 *   - ring-backed fds (a mapping exists for the needed role) use live
 *     ring state — the page bytes would be stale for fds the shim itself
 *     mutates between round trips;
 *   - everything else needs a VALID readiness byte (published by the
 *     worker on every service reply for watched, non-ring-backed vfds).
 * Any entry it cannot evaluate forwards the WHOLE call. Only a ready
 * result (n > 0) or a zero-timeout zero-ready result completes locally;
 * a would-block poll with a real timeout must park at the worker. */
#define SHIM_POLLIN 0x001
#define SHIM_POLLOUT 0x004
#define SHIM_POLLERR 0x008
#define SHIM_POLLHUP 0x010

/* consecutive in-shim polls without any worker round trip; forward after
 * a bound so a guest spinning on poll() still reaches the worker's spin
 * detector (reset inside forward()) */
static int shim_poll_streak;

static int shim_poll_streak_reset(void) {
  shim_poll_streak = 0;
  return 0;
}

static int64_t shim_poll_local(uint64_t fds_ptr, uint64_t nfds,
                               uint64_t t_arg, int is_ppoll) {
  if (shim_is_fork || !shim_page_fast() || nfds > 64 ||
      (nfds && !fds_ptr))
    return INT64_MIN;
  if (++shim_poll_streak > 1000) return INT64_MIN;
  int zero_timeout;
  if (is_ppoll) { /* timespec*; NULL = infinite; sigmask is ignored by
                     the worker twin, so it is ignored here too */
    if (t_arg == 0) {
      zero_timeout = 0;
    } else {
      int64_t sec, nsec;
      memcpy(&sec, (const void *)t_arg, 8);
      memcpy(&nsec, (const void *)(t_arg + 8), 8);
      zero_timeout = (sec == 0 && nsec == 0);
    }
  } else { /* poll: signed ms, negative = infinite */
    zero_timeout = ((int)t_arg == 0);
  }
  int16_t revs[64];
  int n = 0;
  for (uint64_t i = 0; i < nfds; i++) {
    int32_t fd;
    int16_t want;
    memcpy(&fd, (const void *)(fds_ptr + 8 * i), 4);
    memcpy(&want, (const void *)(fds_ptr + 8 * i + 4), 2);
    if (fd < 0) { revs[i] = 0; continue; } /* poll(2): ignored entry */
    volatile struct shring *h0 = shim_ring_find(fd, 0);
    volatile struct shring *h1 = shim_ring_find(fd, 1);
    int16_t r = 0;
    if (h0 || h1) {
      if ((h0 && !h0->fast_ok) || (h1 && !h1->fast_ok)) return INT64_MIN;
      uint32_t fl = h0 ? h0->flags : h1->flags;
      if (fl & SHRING_F_SOCK) {
        if (fl & SHRING_F_ERR) return INT64_MIN; /* POLLERR: worker */
        int hup = (fl & SHRING_F_HUP) != 0;
        /* _readable: rxbuf or peer_closed; _writable: budget, never
         * when peer closed (connect_err stays 0 while fast_ok holds) */
        if ((want & SHIM_POLLIN) &&
            (hup || (h0 && h0->wpos - h0->rpos > 0)))
          r |= SHIM_POLLIN;
        if ((want & SHIM_POLLOUT) && !hup && h1 && h1->wbudget > 0)
          r |= SHIM_POLLOUT;
        if (hup) r |= SHIM_POLLHUP;
        if ((want & SHIM_POLLIN) && !h0 && !hup)
          return INT64_MIN; /* RX ring not offered yet: cannot know */
        if ((want & SHIM_POLLOUT) && !h1 && !hup) return INT64_MIN;
      } else {
        /* pipe flavor: need the ring for each polled direction (a
         * missing role cannot be told apart from a wrong-direction
         * end, whose answer is a constant false — the worker knows) */
        if (want & SHIM_POLLIN) {
          if (!h0) return INT64_MIN;
          if (h0->wpos - h0->rpos > 0 || h0->writers == 0)
            r |= SHIM_POLLIN;
        }
        if (want & SHIM_POLLOUT) {
          if (!h1) return INT64_MIN;
          if (h1->cap - (h1->wpos - h1->rpos) > 0 || h1->readers == 0)
            r |= SHIM_POLLOUT;
        }
      }
    } else {
      /* readiness byte: VALID only for watched vfds with NO ring-capable
       * backing anywhere in the process (worker-maintained invariant) */
      long idx = (long)fd - SHIM_VFD_BASE;
      if (idx < 0 || idx >= SHIM_READY_LEN) return INT64_MIN;
      uint8_t b = ((volatile uint8_t *)shim_time_page)[SHIM_READY_OFF +
                                                       idx];
      if (!(b & SHIM_READY_VALID)) return INT64_MIN;
      if ((want & SHIM_POLLIN) && (b & SHIM_READY_IN)) r |= SHIM_POLLIN;
      if ((want & SHIM_POLLOUT) && (b & SHIM_READY_OUT))
        r |= SHIM_POLLOUT;
      if (b & SHIM_READY_HUP) r |= SHIM_POLLHUP;
      if (b & SHIM_READY_ERR) r |= SHIM_POLLERR;
    }
    if (r) n++;
    revs[i] = r;
  }
  if (n == 0 && !zero_timeout) return INT64_MIN; /* park at the worker */
  for (uint64_t i = 0; i < nfds; i++)
    memcpy((void *)(fds_ptr + 8 * i + 6), &revs[i], 2);
  shim_count_class(SHIM_PAGE_CLS_READY);
  return n;
}

/* the child re-reads its real pid from /proc (getpid is trapped and would
 * return the VIRTUAL pid; the cached parent ids are wrong post-fork).
 * raw3 rides the gadget, so this open is IP-allowed native and reads the
 * REAL kernel /proc — the worker's synthesized /proc/self/stat (vpid)
 * only serves guest-issued opens. The inline-asm no-gadget fallback
 * would trap here; that degraded mode predates the file surface and is
 * not used when the gadget page maps (it always does in practice). */
static void shim_refresh_real_ids(void) {
  int fd = (int)raw3(SYS_open, (long)"/proc/self/stat", 0, 0);
  if (fd < 0) return;
  char buf[64];
  long n = raw3(SYS_read, fd, (long)buf, (long)sizeof buf - 1);
  raw3(SYS_close, fd, 0, 0);
  if (n <= 0) return;
  buf[n] = 0;
  long pid = 0;
  for (char *p = buf; *p >= '0' && *p <= '9'; p++) pid = pid * 10 + (*p - '0');
  if (pid > 0) { shim_real_pid = pid; shim_real_tid = pid; }
}

/* ---- execve: worker-mediated respawn -----------------------------------
 *
 * Reference analog: managed processes exec'ing other binaries (SURVEY.md
 * §3.2 — Shadow keeps children managed across exec). Round 3 replaced the
 * old in-place re-exec (a magic-envp seccomp gate) because it cannot
 * coexist with the virtual file surface: the new image's dynamic linker
 * would trap on openat under the INHERITED filter before any SIGSYS
 * handler exists. Instead execve is forwarded like any syscall; the
 * worker spawns a REPLACEMENT managed process (fresh filter stack, same
 * process record / vpid / vfd table / stdio captures) and kills this one
 * while it blocks in the forward's read — a successful execve therefore
 * never returns, exactly like the real thing. Works from any thread and
 * under audit mode. */
static long shim_do_exec(const char *path, char **argv, char **envp) {
  return (long)forward(SYS_execve, (uint64_t)path, (uint64_t)argv,
                       (uint64_t)envp, 0, 0, 0);
}

/* Reference analog: managed-process fork (SURVEY.md §3.2 sibling path).
 * The worker mints the child's channel (FORK_INTENT -> SCM_RIGHTS fd),
 * the REAL fork runs here in the guest, the child rebinds the fresh
 * channel at the main slot and parks for its first turn, and the parent
 * reports the real child pid (FORK_COMMIT) in exchange for the child's
 * virtual pid. */
static long shim_do_fork(uint64_t nr, greg_t *g) {
  struct shim_req rq = {SHIM_FORK_INTENT, {0, 0, 0, 0, 0, 0}};
  if (write_all(&rq, sizeof rq) != 0) return -EAGAIN;
  int64_t eid = -1;
  int newfd = shim_recv_fd(&eid);
  if (newfd < 0 || eid < 0) return -EAGAIN;
  /* replay the clone through the GADGET (IP-allowed by both filters):
   * the old CLONE_IO marker allowance is gone, so a guest can no longer
   * mint an unmanaged child by setting that flag itself — every
   * fork-style clone from guest code traps into this protocol. Original
   * ctid/ptid args are preserved for glibc's TCB fixup. */
  long child;
  if (nr == SYS_clone)
    child = raw5(SYS_clone, (long)g[REG_RDI], (long)g[REG_RSI],
                 (long)g[REG_RDX], (long)g[REG_R10], (long)g[REG_R8]);
  else /* raw SYS_fork callers: synthesize fork-flavored clone flags */
    child = raw5(SYS_clone, 17 /*SIGCHLD*/, 0, 0, 0, 0);
  if (child < 0) {
    raw3(SYS_close, newfd, 0, 0);
    return child; /* worker-side embryo is reclaimed at process exit */
  }
  if (child == 0) {
    /* child: own fd table — rebind the fresh channel to the main slot,
     * and sever inherited per-thread channels by dup2'ing /dev/null over
     * them (close() on the IPC window is trapped — the worker must not
     * see channel traffic from this thread before its HELLO) */
    shim_is_fork = 1; /* the shared clock page's vpid is the parent's */
    /* socket rings are per-OWNER-process (the worker's oplog replay map
     * and wbudget refresh only track the page owner's fds): drop the
     * inherited mappings so every child socket op forwards. Pipe rings
     * stay — their state lives in the ring itself and is shared. */
    for (int i = 0; i < SHIM_RING_MAX; i++)
      if (shim_rings[i].h && (shim_rings[i].h->flags & SHRING_F_SOCK))
        shim_ring_unmap(i);
    raw3(SYS_dup2, newfd, SHIM_IPC_FD, 0);
    if (newfd != SHIM_IPC_FD) raw3(SYS_close, newfd, 0, 0);
    int nullfd = (int)raw3(SYS_open, (long)"/dev/null", 2 /*O_RDWR*/, 0);
    if (nullfd >= 0) {
      for (int fd = SHIM_IPC_LOW; fd < SHIM_IPC_FD; fd++)
        raw3(SYS_dup2, nullfd, fd, 0);
      raw3(SYS_close, nullfd, 0, 0);
    }
    shim_tls_fd = SHIM_IPC_FD;
    shim_tls_ready = 1;
    /* per-process audit: the child's boundary record starts empty (the
     * inherited bitmap would silently suppress its first-use notes) */
    memset(shim_audit_seen, 0, sizeof shim_audit_seen);
    shim_refresh_real_ids();
    forward(SHIM_THREAD_HELLO, 0, 0, 0, 0, 0, 0); /* first turn grant */
    return 0;
  }
  raw3(SYS_close, newfd, 0, 0);
  return forward(SHIM_FORK_COMMIT, (uint64_t)eid, (uint64_t)child,
                 0, 0, 0, 0); /* -> the child's virtual pid */
}

/* BEGIN GENERATED EMU BITMAP (tools/gen_bpf.py) */
static const uint8_t shim_emu_bitmap[64] = {
    0xd4, 0x40, 0xe0, 0x00, 0x8a, 0xff, 0xff, 0xef,
    0x00, 0x90, 0xbd, 0x02, 0x1f, 0x40, 0x00, 0x00,
    0x08, 0x00, 0x00, 0x00, 0x01, 0x00, 0x00, 0x04,
    0x00, 0x16, 0x20, 0x00, 0xf0, 0x03, 0x00, 0xe0,
    0xc6, 0xe9, 0x18, 0xde, 0x7f, 0x40, 0x00, 0x50,
    0x00, 0x10, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x98, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
};
/* END GENERATED EMU BITMAP */

/* would the worker emulate this trapped syscall? (mirrors the standard
 * filter's trap conditions; fd-conditional numbers check the vfd/IPC
 * ranges like the BPF does) */
static int shim_nr_emulated(long nr, const greg_t *g) {
  uint64_t a0 = (uint64_t)g[REG_RDI];
  int vfd = a0 >= SHIM_VFD_BASE && a0 < 0xFFFFF000u;
  switch (nr) {
  case SYS_read: case SYS_readv:
    return a0 == 0 || vfd;
  case SYS_write: case SYS_writev:
    return a0 <= 2 || vfd;
  case SYS_close:
    return vfd || (a0 >= SHIM_IPC_LOW && a0 <= SHIM_IPC_FD);
  case 9: { /* mmap: fd rides arg4; MAP_ANONYMOUS fd=-1 stays native */
    uint64_t a4 = (uint64_t)g[REG_R8];
    return a4 >= SHIM_VFD_BASE && a4 < 0xFFFFF000u;
  }
  /* BEGIN GENERATED VFD CASES (tools/gen_bpf.py) */
  case 16: case 72: case 32: case 5: case 8: case 217: case 77: case 74: case 75: case 81: case 17: case 18:  /* ioctl fcntl dup fstat lseek getdents64 ftruncate fsync fdatasync fchdir pread64 pwrite64 */
  /* END GENERATED VFD CASES */
    return vfd;
  default:
    return nr >= 0 && nr < 512 &&
           ((shim_emu_bitmap[nr >> 3] >> (nr & 7)) & 1);
  }
}

static void shim_audit_note(long nr) {
  if (!shim_tls_ready) return; /* pre-registration thread bootstrap:
                                  no channel to report on (uncounted) */
  if (nr >= 0 && nr < 512) {
    if ((shim_audit_seen[nr >> 3] >> (nr & 7)) & 1) return;
    shim_audit_seen[nr >> 3] |= (uint8_t)(1u << (nr & 7));
  } /* out-of-range (x32 etc.): the worker's per-process set dedups */
  forward(SHIM_AUDIT_NOTE, (uint64_t)nr, 0, 0, 0, 0, 0);
}

static void sigsys_handler(int signo, siginfo_t *info, void *vctx) {
  (void)signo;
  ucontext_t *ctx = vctx;
  greg_t *g = ctx->uc_mcontext.gregs;
  if (info->si_syscall == SYS_rt_sigprocmask) {
    /* handled purely locally (see below) — safe at ANY thread stage */
    goto sigprocmask;
  }
  if (!shim_tls_ready) {
    /* a freshly cloned thread runs glibc bootstrap BEFORE the trampoline
     * pins its own channel; its thread-local channel fd still points at
     * the MAIN thread's, so forwarding would interleave with (and steal
     * replies from) the spawner's own request stream — the race that
     * intermittently broke the 10th pthread_create of a burst. These are
     * glibc-internal setup calls: run them natively via the gadget. */
    g[REG_RAX] = (greg_t)shim_gadget(info->si_syscall, (long)g[REG_RDI],
                                     (long)g[REG_RSI], (long)g[REG_RDX],
                                     (long)g[REG_R10], (long)g[REG_R8],
                                     (long)g[REG_R9]);
    return;
  }
  if (info->si_syscall == SYS_fork ||
      (info->si_syscall == SYS_clone && !(g[REG_RDI] & 0x10000))) {
    if (info->si_syscall == SYS_clone && (g[REG_RDI] & 0x100 /*CLONE_VM*/)) {
      g[REG_RAX] = (greg_t)-ENOSYS; /* vfork-style shared-VM clone */
      return;
    }
    g[REG_RAX] = (greg_t)shim_do_fork((uint64_t)info->si_syscall, g);
    return;
  }
  if (info->si_syscall == SYS_execve) {
    g[REG_RAX] = (greg_t)shim_do_exec((const char *)g[REG_RDI],
                                      (char **)g[REG_RSI],
                                      (char **)g[REG_RDX]);
    return;
  }
  if (info->si_syscall == SYS_exit_group) {
    /* report the true code, then exit this thread for real; the worker
     * SIGKILLs any remaining threads (exit_group semantics) */
    forward(SYS_exit_group, (uint64_t)g[REG_RDI], 0, 0, 0, 0, 0);
    raw3(SYS_exit, (long)g[REG_RDI], 0, 0);
  }
  if (info->si_syscall == SYS_rt_sigprocmask) {
  sigprocmask:;
    /* Emulated SHIM-SIDE by editing the signal frame's uc_sigmask (the
     * mask sigreturn restores) — never with a real syscall, which would
     * re-trap forever. Crucially SIGSYS/SIGSEGV are ALWAYS left unblocked:
     * glibc's pthread_create blocks every signal around clone, and a
     * seccomp trap while SIGSYS is blocked force-kills the process. */
    uint64_t how = g[REG_RDI], set = g[REG_RSI], old = g[REG_RDX];
    uint64_t cur;
    memcpy(&cur, &ctx->uc_sigmask, 8);
    if (old) memcpy((void *)old, &cur, 8);
    if (set) {
      uint64_t m;
      memcpy(&m, (const void *)set, 8);
      if (how == SIG_BLOCK) cur |= m;
      else if (how == SIG_UNBLOCK) cur &= ~m;
      else if (how == SIG_SETMASK) cur = m;
      else { g[REG_RAX] = (greg_t)-EINVAL; return; }
      cur &= ~((1ULL << (SIGSYS - 1)) | (1ULL << (SIGSEGV - 1)));
      memcpy(&ctx->uc_sigmask, &cur, 8);
    }
    g[REG_RAX] = 0;
    return;
  }
  if (shim_audit_on && !shim_nr_emulated(info->si_syscall, g)) {
    /* reality boundary: the worker does not emulate this call. Report it
     * (once per number) and run it against the host kernel via the
     * gadget, exactly what the standard filter's default-ALLOW did —
     * except now it is observed. */
    shim_audit_note(info->si_syscall);
    g[REG_RAX] = (greg_t)shim_gadget(info->si_syscall, (long)g[REG_RDI],
                                     (long)g[REG_RSI], (long)g[REG_RDX],
                                     (long)g[REG_R10], (long)g[REG_R8],
                                     (long)g[REG_R9]);
    return;
  }
  /* identity fast path (shared clock page, no worker round trip):
   * getpid/gettid return the page's vpid (the worker's emulation returns
   * vpid for both), getppid is the constant 1 ("init of the simulated
   * world"). Forked children share the parent's page, so they forward. */
  if (info->si_syscall == SYS_getpid || info->si_syscall == SYS_gettid) {
    if (!shim_is_fork && shim_time_page && shim_time_page[1] > 0) {
      g[REG_RAX] = (greg_t)shim_time_page[1];
      shim_count_class(SHIM_PAGE_CLS_IDENT);
      return;
    }
  } else if (info->si_syscall == SYS_getppid) {
    g[REG_RAX] = 1;
    shim_count_class(SHIM_PAGE_CLS_IDENT);
    return;
  }
  /* raw time-family syscalls (static binaries / raw-syscall guests that
   * bypass the libc interposition) served from the clock page. The
   * monotonic-clock set and the sec/nsec split mirror the worker's
   * _service exactly; the (uint64_t)-1 sentinel stays a worker call. */
  if (shim_page_fast()) {
    if (info->si_syscall == SYS_clock_gettime && g[REG_RSI] &&
        (uint64_t)g[REG_RDI] != (uint64_t)-1) {
      int64_t ns = *shim_time_page;
      uint64_t clk = (uint64_t)g[REG_RDI];
      if (clk == 1 || clk == 2 || clk == 3 || clk == 4 || clk == 6 ||
          clk == 7)
        ns -= SHIM_EMULATED_EPOCH_NS; /* MONO_CLOCKS (worker twin) */
      int64_t *tp = (int64_t *)g[REG_RSI];
      tp[0] = ns / 1000000000;
      tp[1] = ns % 1000000000;
      shim_count_class(SHIM_PAGE_CLS_TIME);
      g[REG_RAX] = 0;
      return;
    }
    if (info->si_syscall == SYS_gettimeofday) {
      if (g[REG_RDI]) {
        int64_t ns = *shim_time_page;
        int64_t *tp = (int64_t *)g[REG_RDI];
        tp[0] = ns / 1000000000;
        tp[1] = (ns % 1000000000) / 1000;
      }
      shim_count_class(SHIM_PAGE_CLS_TIME);
      g[REG_RAX] = 0;
      return;
    }
    if (info->si_syscall == SYS_time) {
      int64_t secs = *shim_time_page / 1000000000;
      if (g[REG_RDI]) *(int64_t *)g[REG_RDI] = secs;
      shim_count_class(SHIM_PAGE_CLS_TIME);
      g[REG_RAX] = (greg_t)secs;
      return;
    }
    if (info->si_syscall == SYS_poll || info->si_syscall == SYS_ppoll) {
      int64_t r = shim_poll_local((uint64_t)g[REG_RDI],
                                  (uint64_t)g[REG_RSI],
                                  (uint64_t)g[REG_RDX],
                                  info->si_syscall == SYS_ppoll);
      if (r != INT64_MIN) { g[REG_RAX] = (greg_t)r; return; }
    }
  }
  /* shared-memory pipe fast path (zero round trips when it hits).
   * Covers vfds AND the trapped stdio fds — a shell pipeline dup2's
   * pipe ends onto 0/1, and those reads/writes trap (gen_bpf.py READ /
   * WRITE branches); the mapping's existence is what says "this fd is
   * currently a ring pipe" (offers ride its service replies, and every
   * close / dup2-over / close_range drops the entry). */
  {
    long fd0 = (long)g[REG_RDI];
    if (info->si_syscall == SYS_read &&
        (fd0 == 0 || fd0 >= SHIM_VFD_BASE)) {
      int64_t r = shim_ring_read(fd0, (uint64_t)g[REG_RSI],
                                 (uint64_t)g[REG_RDX], 0);
      if (r != INT64_MIN) { g[REG_RAX] = (greg_t)r; return; }
    } else if (info->si_syscall == SYS_recvfrom &&
               fd0 >= SHIM_VFD_BASE) {
      /* flags: the worker honors MSG_PEEK only and ignores the rest,
       * as does the src-address pair on connected streams — mirror it */
      int64_t r = shim_ring_read(fd0, (uint64_t)g[REG_RSI],
                                 (uint64_t)g[REG_RDX],
                                 ((uint64_t)g[REG_R10] & 2) != 0);
      if (r != INT64_MIN) { g[REG_RAX] = (greg_t)r; return; }
    } else if (info->si_syscall == SYS_write &&
               (fd0 == 1 || fd0 == 2 || fd0 >= SHIM_VFD_BASE)) {
      int64_t r = shim_ring_write(fd0, (uint64_t)g[REG_RSI],
                                  (uint64_t)g[REG_RDX]);
      if (r != INT64_MIN) { g[REG_RAX] = (greg_t)r; return; }
    } else if (info->si_syscall == SYS_sendto && fd0 >= SHIM_VFD_BASE) {
      /* dest-address/flags are ignored by the worker on connected
       * streams (_vfd_send takes fd/buf/len only) — mirror it */
      int64_t r = shim_ring_write(fd0, (uint64_t)g[REG_RSI],
                                  (uint64_t)g[REG_RDX]);
      if (r != INT64_MIN) { g[REG_RAX] = (greg_t)r; return; }
    } else if (info->si_syscall == SYS_close ||
               info->si_syscall == SYS_shutdown) {
      /* close drops both roles; shutdown conservatively drops them too
       * (a SHUT_RD end must EOF instead of serving buffered ring data —
       * subsequent ops forward and the worker owns the semantics) */
      shim_ring_drop(fd0); /* then forward */
    }
  }
  if ((info->si_syscall == SYS_dup2 || info->si_syscall == SYS_dup3) &&
      (long)g[REG_RSI] != (long)g[REG_RDI])
    shim_ring_drop((long)g[REG_RSI]); /* newfd implicitly closed
                                         (dup2(x,x) closes nothing) */
  if (info->si_syscall == SYS_close_range && !((long)g[REG_RDX] & 4)) {
    /* CLOSE_RANGE_CLOEXEC (flag 4) marks without closing */
    for (int i = 0; i < SHIM_RING_MAX; i++)
      if (shim_rings[i].h && shim_rings[i].vfd >= (long)g[REG_RDI] &&
          shim_rings[i].vfd <= (long)g[REG_RSI])
        shim_ring_unmap(i);
  }
  if (info->si_syscall == 9) {
    /* mmap of a virtualized file: the worker replies with the real
     * backing fd (host-tree fd or a memfd snapshot of synthesized
     * content) as SCM_RIGHTS; re-issue the map with it through the
     * gadget, then drop the temporary fd — the mapping holds the file */
    struct shim_req rq = {9, {(uint64_t)g[REG_RDI], (uint64_t)g[REG_RSI],
                              (uint64_t)g[REG_RDX], (uint64_t)g[REG_R10],
                              (uint64_t)g[REG_R8], (uint64_t)g[REG_R9]}};
    int64_t val = -EBADF;
    if (write_all(&rq, sizeof rq) != 0) {
      g[REG_RAX] = (greg_t)(int64_t)-EPIPE;
      return;
    }
    int mfd = shim_recv_fd(&val);
    if (mfd >= 0) {
      shim_gadget_fn reissue = shim_gadget ? shim_gadget : raw6_asm;
      long r = reissue(9, (long)g[REG_RDI], (long)g[REG_RSI],
                       (long)g[REG_RDX], (long)g[REG_R10], mfd,
                       (long)g[REG_R9]);
      raw3(SYS_close, mfd, 0, 0);
      g[REG_RAX] = (greg_t)r;
    } else {
      g[REG_RAX] = (greg_t)val; /* worker errno (no fd attached) */
    }
    return;
  }
  int64_t ret = forward((uint64_t)info->si_syscall, (uint64_t)g[REG_RDI],
                        (uint64_t)g[REG_RSI], (uint64_t)g[REG_RDX],
                        (uint64_t)g[REG_R10], (uint64_t)g[REG_R8],
                        (uint64_t)g[REG_R9]);
  while (ret == SHIM_RET_MAPRING) {
    /* a ring memfd + role follows, then either ANOTHER offer (socket
     * rings arrive as an RX+TX pair) or the real result of this op */
    int64_t role = 0;
    int mfd = shim_recv_fd(&role);
    if (mfd >= 0) shim_ring_install((long)g[REG_RDI], (int)role, mfd);
    int64_t fin = -EPIPE;
    if (read_all(&fin, sizeof fin) != 0) fin = -EPIPE;
    ret = fin;
  }
  if (ret == SHIM_RET_NATIVE) {
    /* the worker chose passthrough for this one (virtual-FS policy) */
    shim_gadget_fn reissue = shim_gadget ? shim_gadget : raw6_asm;
    ret = reissue(info->si_syscall, (long)g[REG_RDI], (long)g[REG_RSI],
                  (long)g[REG_RDX], (long)g[REG_R10], (long)g[REG_R8],
                  (long)g[REG_R9]);
  }
  g[REG_RAX] = (greg_t)ret;
}

/* ---- interposed time family (catches the vDSO paths) ------------------- */

static int64_t emulated_now_ns(void);

/* ---- TSC virtualization (reference analog: SURVEY.md §2 "TSC emulation")
 *
 * prctl(PR_SET_TSC, PR_TSC_SIGSEGV) makes rdtsc/rdtscp fault; this handler
 * decodes the two instruction forms and serves the emulated clock at a
 * fixed nominal 1 GHz (cycles == ns), so even guests that time via the raw
 * TSC — bypassing every syscall and vDSO path — observe simulated time.
 *
 * Guests that install their own SIGSEGV handler must keep working: the
 * shim interposes sigaction()/signal() (libc PLT calls — raw rt_sigaction
 * from a static binary bypasses this, a documented scope limit) and keeps
 * its handler installed, recording the guest's disposition. Non-TSC
 * SIGSEGVs are chained to the guest handler; with none registered, a
 * hardware fault crashes via re-execution under SIG_DFL, and a
 * software-raised SIGSEGV (raise/kill: si_code <= 0) is re-raised
 * explicitly since nothing would re-trigger it on return. */

static struct sigaction guest_segv; /* guest's requested disposition */

static int real_sigaction(int sig, const struct sigaction *act,
                          struct sigaction *old) {
  static int (*real)(int, const struct sigaction *, struct sigaction *);
  if (!real) {
    union { void *p; int (*f)(int, const struct sigaction *,
                              struct sigaction *); } u;
    u.p = dlsym(RTLD_NEXT, "sigaction");
    real = u.f;
  }
  return real(sig, act, old);
}

/* dispatch to the guest's handler under its requested signal mask */
static void chain_guest(int signo, siginfo_t *info, void *vctx) {
  sigset_t old;
  sigprocmask(SIG_BLOCK, &guest_segv.sa_mask, &old);
  if (guest_segv.sa_flags & SA_SIGINFO)
    guest_segv.sa_sigaction(signo, info, vctx);
  else
    guest_segv.sa_handler(signo);
  sigprocmask(SIG_SETMASK, &old, NULL); /* longjmp-outs restore their own */
}

static void sigsegv_handler(int signo, siginfo_t *info, void *vctx) {
  ucontext_t *ctx = vctx;
  greg_t *g = ctx->uc_mcontext.gregs;
  const uint8_t *ip = (const uint8_t *)g[REG_RIP];
  /* rdtsc = 0F 31 ; rdtscp = 0F 01 F9. A bogus RIP makes the ip[] reads
   * fault; SIGSEGV is blocked inside its own handler, so the kernel then
   * force-kills with the default action — the right outcome. */
  if (ip && ip[0] == 0x0f &&
      (ip[1] == 0x31 || (ip[1] == 0x01 && ip[2] == 0xf9))) {
    uint64_t ns = (uint64_t)emulated_now_ns();
    g[REG_RAX] = (greg_t)(ns & 0xffffffffu);
    g[REG_RDX] = (greg_t)(ns >> 32);
    if (ip[1] == 0x31) {
      g[REG_RIP] += 2;
    } else {
      g[REG_RCX] = 0; /* IA32_TSC_AUX: core 0 */
      g[REG_RIP] += 3;
    }
    return;
  }
  int hw_fault = info->si_code > 0; /* <=0: raise()/kill()/sigqueue() */
  if ((guest_segv.sa_flags & SA_SIGINFO) ||
      (guest_segv.sa_handler != SIG_DFL && guest_segv.sa_handler != SIG_IGN &&
       guest_segv.sa_handler != NULL)) {
    chain_guest(signo, info, vctx);
    return;
  }
  if (guest_segv.sa_handler == SIG_IGN && !hw_fault)
    return; /* ignoring a software-raised SIGSEGV is legal */
  /* default action (the kernel also force-kills SIG_IGN on a hardware
   * fault): restore the REAL kernel disposition — the interposed signal()
   * would only record it — then let re-execution (hardware) or an explicit
   * re-raise (software) deliver the fatal signal. */
  struct sigaction dfl;
  memset(&dfl, 0, sizeof dfl);
  dfl.sa_handler = SIG_DFL;
  real_sigaction(SIGSEGV, &dfl, NULL);
  if (!hw_fault)
    raw3(SYS_tgkill, shim_real_pid, shim_real_tid, SIGSEGV);
}

/* sigaction/signal interposition: SIGSEGV dispositions are recorded, not
 * installed — the shim's handler stays first and chains (above). */

static struct sigaction guest_sys; /* guest's requested SIGSYS disposition
                                      (recorded only — the shim's handler
                                      IS the syscall mechanism and must
                                      never be uninstalled; guests bulk-
                                      resetting handlers, e.g. CPython's
                                      subprocess child, would otherwise
                                      die on their next trapped call) */

int sigaction(int sig, const struct sigaction *act, struct sigaction *old) {
  if (!shim_active || (sig != SIGSEGV && sig != SIGSYS))
    return real_sigaction(sig, act, old);
  struct sigaction *slot = (sig == SIGSEGV) ? &guest_segv : &guest_sys;
  if (old) *old = *slot;
  if (act) *slot = *act;
  return 0;
}

sighandler_t signal(int sig, sighandler_t fn) {
  if (!shim_active || sig != SIGSEGV) {
    struct sigaction sa, osa;
    memset(&sa, 0, sizeof sa);
    sa.sa_handler = fn;
    sa.sa_flags = SA_RESTART;
    sigemptyset(&sa.sa_mask);
    if (sigaction(sig, &sa, &osa) != 0) return SIG_ERR;
    return osa.sa_handler;
  }
  sighandler_t prev = guest_segv.sa_handler;
  memset(&guest_segv, 0, sizeof guest_segv);
  guest_segv.sa_handler = fn;
  return prev;
}

static int64_t emulated_now_ns(void) {
  if (shim_time_page) return *shim_time_page;
  struct shim_req unused; (void)unused;
  /* no page mapped: ask the worker (slow path, still deterministic) */
  return forward(SYS_clock_gettime, (uint64_t)-1, 0, 0, 0, 0, 0);
}

/* The simulation boots at 2000-01-01T00:00:00Z; monotonic-family clocks
 * originate at boot == sim start, consistent with sysinfo's sim-second
 * uptime and Linux's near-zero monotonic origin (SHIM_EMULATED_EPOCH_NS
 * is defined beside the ring plane above, which also needs it). */
static int clk_is_monotonic(clockid_t clk) {
  return clk == CLOCK_MONOTONIC || clk == CLOCK_MONOTONIC_RAW ||
         clk == CLOCK_MONOTONIC_COARSE || clk == CLOCK_BOOTTIME ||
         clk == CLOCK_PROCESS_CPUTIME_ID || clk == CLOCK_THREAD_CPUTIME_ID;
}

/* the interposed family completes shim-locally in EVERY mode (it never
 * reaches the worker), so it counts unconditionally — keeping the
 * "syscalls" counter invariant across fast-plane on/off */
int clock_gettime(clockid_t clk, struct timespec *ts) {
  if (!shim_active) return (int)raw3(SYS_clock_gettime, clk, (long)ts, 0);
  int64_t ns = emulated_now_ns();
  if (clk_is_monotonic(clk)) ns -= SHIM_EMULATED_EPOCH_NS;
  ts->tv_sec = ns / 1000000000;
  ts->tv_nsec = ns % 1000000000;
  shim_count_class(SHIM_PAGE_CLS_TIME);
  return 0;
}

int gettimeofday(struct timeval *tv, void *tz) {
  (void)tz;
  if (!shim_active) return (int)raw3(SYS_gettimeofday, (long)tv, 0, 0);
  int64_t ns = emulated_now_ns();
  tv->tv_sec = ns / 1000000000;
  tv->tv_usec = (ns % 1000000000) / 1000;
  shim_count_class(SHIM_PAGE_CLS_TIME);
  return 0;
}

time_t time(time_t *out) {
  if (!shim_active) return (time_t)raw3(SYS_time, (long)out, 0, 0);
  time_t t = (time_t)(emulated_now_ns() / 1000000000);
  if (out) *out = t;
  shim_count_class(SHIM_PAGE_CLS_TIME);
  return t;
}

/* ---- guest threads ------------------------------------------------------
 *
 * Reference analog: ManagedThread (SURVEY.md §2). The worker enforces
 * strict one-runnable-thread turn-taking, so every thread needs its own
 * wakeup channel: pthread_create is interposed; the worker mints a fresh
 * socketpair and hands the guest end back as SCM_RIGHTS ancillary data on
 * the SPAWN reply; the new thread pins it at a reserved fd (995 - slot,
 * inside the seccomp-allowed [932, 995] window), checks in with
 * THREAD_HELLO (its reply is the first turn grant), runs the app start
 * routine, and announces THREAD_EXIT so joiners parked at the worker wake
 * in sim time. CLONE_THREAD clones run natively; futex is trapped and
 * emulated worker-side so lock handoffs between parked threads cannot
 * deadlock the turn-taking. Scope: up to 63 extra threads; raw clone(2)
 * users and fork are still rejected loudly. */

#define SHIM_MAX_THREADS 64
struct shim_tramp { void *(*fn)(void *); void *arg; int fd; };
static pthread_t shim_thread_ids[SHIM_MAX_THREADS]; /* slot -> pthread_t */

static long shim_spawn_channel(void) {
  struct shim_req rq = {SHIM_SPAWN_THREAD, {0, 0, 0, 0, 0, 0}};
  if (write_all(&rq, sizeof rq) != 0) return -1;
  int64_t slot = -1;
  int newfd = shim_recv_fd(&slot);
  if (newfd < 0 || slot < 0 || slot >= SHIM_MAX_THREADS) return -1;
  int want = SHIM_IPC_FD - (int)slot;
  if (newfd != want) {
    raw3(SYS_dup2, newfd, want, 0);
    raw3(SYS_close, newfd, 0, 0);
  }
  return slot;
}

/* trampoline args live in a static per-slot table, NOT a malloc block:
 * free() in the trampoline could contend the malloc arena lock and issue
 * futex(FUTEX_WAIT) either natively before shim_tls_ready (never woken —
 * the holder's FUTEX_WAKE is worker-emulated) or emulated before
 * THREAD_HELLO (protocol violation). Slots are only reused after the
 * prior thread exits, long after it copied its entry. */
static struct shim_tramp shim_tramp_slots[SHIM_MAX_THREADS];

static void *shim_thread_tramp(void *p) {
  struct shim_tramp t = *(struct shim_tramp *)p;
  shim_tls_fd = t.fd;
  shim_tls_ready = 1;
  forward(SHIM_THREAD_HELLO, 0, 0, 0, 0, 0, 0); /* blocks for first turn */
  void *r = t.fn(t.arg);
  forward(SHIM_THREAD_EXIT, (uint64_t)r, 0, 0, 0, 0, 0);
  return r;
}

/* ---- simulated name resolution ------------------------------------------
 *
 * Reference analog: Shadow resolves config host names to simulated IPs
 * for its guests. getaddrinfo is interposed: names the WORKER knows
 * (config host names) resolve to their simulated IPv4 without touching
 * /etc/hosts or DNS; everything else falls through to the real resolver.
 * Results we fabricate live in single-malloc blocks tracked in a small
 * registry so the interposed freeaddrinfo releases ours and forwards the
 * rest. */

#include <netdb.h>
#include <netinet/in.h>

struct shim_ai_block {
  struct addrinfo ai;
  struct sockaddr_in sa;
  char canon[256]; /* AI_CANONNAME storage (freed with the block) */
};

#define SHIM_AI_MAX 64
static struct addrinfo *shim_ai_live[SHIM_AI_MAX];

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
  static int (*real)(const char *, const char *, const struct addrinfo *,
                     struct addrinfo **);
  if (!real) {
    union { void *p; int (*f)(const char *, const char *,
                              const struct addrinfo *,
                              struct addrinfo **); } u;
    u.p = dlsym(RTLD_NEXT, "getaddrinfo");
    real = u.f;
  }
  int family_ok = !hints || hints->ai_family == AF_UNSPEC ||
                  hints->ai_family == AF_INET;
  if (shim_active && node != NULL && family_ok) {
    int64_t ip = forward(SHIM_RESOLVE, (uint64_t)node, 0, 0, 0, 0, 0);
    if (ip >= 0) {
      long port = 0;
      if (service) {
        for (const char *p = service; *p; p++) {
          if (*p < '0' || *p > '9' || port > 65535) { port = -1; break; }
          port = port * 10 + (*p - '0');
        }
        if (port < 0 || port > 65535)
          return EAI_SERVICE; /* named services: not modeled */
      }
      struct shim_ai_block *b = calloc(1, sizeof *b);
      if (!b) return EAI_MEMORY;
      b->sa.sin_family = AF_INET;
      b->sa.sin_port = htons((uint16_t)port);
      b->sa.sin_addr.s_addr = htonl((uint32_t)ip);
      b->ai.ai_family = AF_INET;
      b->ai.ai_socktype = hints && hints->ai_socktype ? hints->ai_socktype
                                                      : SOCK_STREAM;
      b->ai.ai_protocol = 0;
      b->ai.ai_addrlen = sizeof b->sa;
      b->ai.ai_addr = (struct sockaddr *)&b->sa;
      if (hints && (hints->ai_flags & AI_CANONNAME)) {
        strncpy(b->canon, node, sizeof b->canon - 1);
        b->ai.ai_canonname = b->canon;
      }
      /* registry claim must be atomic (threaded resolvers) and must not
       * drop: an unregistered block reaching the REAL freeaddrinfo is
       * undefined behavior on allocator-layout-assuming libcs */
      int claimed = 0;
      for (int i = 0; i < SHIM_AI_MAX && !claimed; i++)
        claimed = __sync_bool_compare_and_swap(&shim_ai_live[i], NULL,
                                               &b->ai);
      if (!claimed) {
        free(b); /* registry full: degrade to the real resolver */
        return real(node, service, hints, res);
      }
      *res = &b->ai;
      return 0;
    }
  }
  return real(node, service, hints, res);
}

void freeaddrinfo(struct addrinfo *ai) {
  static void (*real)(struct addrinfo *);
  if (!real) {
    union { void *p; void (*f)(struct addrinfo *); } u;
    u.p = dlsym(RTLD_NEXT, "freeaddrinfo");
    real = u.f;
  }
  for (int i = 0; i < SHIM_AI_MAX; i++)
    if (__sync_bool_compare_and_swap(&shim_ai_live[i], ai, NULL)) {
      free(ai); /* the whole shim_ai_block in one allocation */
      return;
    }
  real(ai);
}

pid_t vfork(void) {
  /* vfork-as-fork: POSIX permits it, and the fork path (trapped clone ->
   * shim_do_fork) keeps the child managed; the parent just continues
   * instead of suspending. CPython's subprocess and shell spawn idioms
   * land here. */
  static pid_t (*realfork)(void);
  if (!realfork) {
    union { void *p; pid_t (*f)(void); } u;
    u.p = dlsym(RTLD_NEXT, "fork");
    realfork = u.f;
  }
  return realfork();
}

int pthread_create(pthread_t *out, const pthread_attr_t *attr,
                   void *(*fn)(void *), void *arg) {
  static int (*real)(pthread_t *, const pthread_attr_t *,
                     void *(*)(void *), void *);
  if (!real) {
    union { void *p; int (*f)(pthread_t *, const pthread_attr_t *,
                              void *(*)(void *), void *); } u;
    u.p = dlsym(RTLD_NEXT, "pthread_create");
    real = u.f;
  }
  if (!shim_active) return real(out, attr, fn, arg);
  long slot = shim_spawn_channel();
  if (slot < 0) return EAGAIN;
  struct shim_tramp *t = &shim_tramp_slots[slot];
  t->fn = fn;
  t->arg = arg;
  t->fd = SHIM_IPC_FD - (int)slot;
  int rc = real(out, attr, shim_thread_tramp, t);
  if (rc == 0) shim_thread_ids[slot] = *out;
  /* on failure the worker-side slot leaks; the process is dying anyway */
  return rc;
}

int pthread_join(pthread_t th, void **retval) {
  static int (*real)(pthread_t, void **);
  static int (*real_detach)(pthread_t);
  if (!real) {
    union { void *p; int (*f)(pthread_t, void **); } u;
    u.p = dlsym(RTLD_NEXT, "pthread_join");
    real = u.f;
    union { void *p; int (*f)(pthread_t); } v;
    v.p = dlsym(RTLD_NEXT, "pthread_detach");
    real_detach = v.f;
  }
  if (!shim_active) return real(th, retval);
  int slot = -1;
  for (int i = 1; i < SHIM_MAX_THREADS; i++)
    if (shim_thread_ids[i] == th) { slot = i; break; }
  if (slot < 0) return real(th, retval);
  int64_t rv = forward(SHIM_THREAD_JOIN, (uint64_t)slot, 0, 0, 0, 0, 0);
  if (retval) *retval = (void *)rv;
  shim_thread_ids[slot] = 0;
  /* the thread has (or is about to) exit natively; detach instead of a
   * real join — glibc's join would FUTEX_WAIT on the kernel-cleared tid,
   * a wake our trapped-futex emulation cannot observe */
  real_detach(th);
  return 0;
}

void pthread_exit(void *retval) {
  static void (*real)(void *) __attribute__((noreturn));
  if (!real) {
    union { void *p; void (*f)(void *) __attribute__((noreturn)); } u;
    u.p = dlsym(RTLD_NEXT, "pthread_exit");
    real = u.f;
  }
  if (shim_active && shim_tls_fd != SHIM_IPC_FD)
    forward(SHIM_THREAD_EXIT, (uint64_t)retval, 0, 0, 0, 0, 0);
  real(retval);
  __builtin_unreachable();
}

/* ---- seccomp filter ----------------------------------------------------- */

#define BPF_NR (offsetof(struct seccomp_data, nr))
#define BPF_ARG0 (offsetof(struct seccomp_data, args[0]))
#define BPF_ARG4 (offsetof(struct seccomp_data, args[4]))
#define BPF_ARG2LO (offsetof(struct seccomp_data, args[2]))
#define BPF_ARG2HI (offsetof(struct seccomp_data, args[2]) + 4)
#define BPF_ARCHF (offsetof(struct seccomp_data, arch))
#define BPF_IPLO (offsetof(struct seccomp_data, instruction_pointer))
#define BPF_IPHI (offsetof(struct seccomp_data, instruction_pointer) + 4)

#define LD(off) BPF_STMT(BPF_LD | BPF_W | BPF_ABS, (off))
#define RET(v) BPF_STMT(BPF_RET | BPF_K, (v))
#define JEQ(v, t, f) BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, (v), (t), (f))
#define JGE(v, t, f) BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (v), (t), (f))
#define JSET(v, t, f) BPF_JUMP(BPF_JMP | BPF_JSET | BPF_K, (v), (t), (f))

static int install_seccomp(void) {
  /* BEGIN GENERATED BPF (tools/gen_bpf.py) */
  struct sock_filter prog[] = {  /* 132 instructions */
      LD(BPF_ARCHF),
      JEQ(AUDIT_ARCH_X86_64, 0, 129),
      LD(BPF_IPHI),
      JEQ((uint32_t)((uintptr_t)SHIM_GADGET_ADDR >> 32), 0, 3),
      LD(BPF_IPLO),
      JGE((uint32_t)(uintptr_t)SHIM_GADGET_ADDR, 0, 1),
      JGE(((uint32_t)(uintptr_t)SHIM_GADGET_ADDR + 4096), 0, 124),
      LD(BPF_NR),
      JEQ(0, 98, 0),  /* read */
      JEQ(1, 102, 0),  /* write */
      JEQ(3, 111, 0),  /* close */
      JEQ(19, 95, 0),  /* readv */
      JEQ(20, 99, 0),  /* writev */
      JEQ(16, 113, 0),  /* ioctl */
      JEQ(72, 112, 0),  /* fcntl */
      JEQ(32, 111, 0),  /* dup */
      JEQ(5, 110, 0),  /* fstat */
      JEQ(8, 109, 0),  /* lseek */
      JEQ(217, 108, 0),  /* getdents64 */
      JEQ(77, 107, 0),  /* ftruncate */
      JEQ(74, 106, 0),  /* fsync */
      JEQ(75, 105, 0),  /* fdatasync */
      JEQ(81, 104, 0),  /* fchdir */
      JEQ(17, 103, 0),  /* pread64 */
      JEQ(18, 102, 0),  /* pwrite64 */
      JEQ(9, 99, 0),  /* mmap */
      JEQ(35, 103, 0),  /* nanosleep */
      JEQ(230, 102, 0),  /* clock_nanosleep */
      JEQ(228, 101, 0),  /* clock_gettime */
      JEQ(96, 100, 0),  /* gettimeofday */
      JEQ(201, 99, 0),  /* time */
      JEQ(318, 98, 0),  /* getrandom */
      JEQ(7, 97, 0),  /* poll */
      JEQ(271, 96, 0),  /* ppoll */
      JEQ(213, 95, 0),  /* epoll_create */
      JEQ(291, 94, 0),  /* epoll_create1 */
      JEQ(233, 93, 0),  /* epoll_ctl */
      JEQ(232, 92, 0),  /* epoll_wait */
      JEQ(281, 91, 0),  /* epoll_pwait */
      JEQ(288, 90, 0),  /* accept4 */
      JEQ(435, 89, 0),  /* clone3 */
      JEQ(39, 88, 0),  /* getpid */
      JEQ(110, 87, 0),  /* getppid */
      JEQ(186, 86, 0),  /* gettid */
      JEQ(283, 85, 0),  /* timerfd_create */
      JEQ(286, 84, 0),  /* timerfd_settime */
      JEQ(287, 83, 0),  /* timerfd_gettime */
      JEQ(284, 82, 0),  /* eventfd */
      JEQ(290, 81, 0),  /* eventfd2 */
      JEQ(202, 80, 0),  /* futex */
      JEQ(14, 79, 0),  /* rt_sigprocmask */
      JEQ(22, 78, 0),  /* pipe */
      JEQ(293, 77, 0),  /* pipe2 */
      JEQ(61, 76, 0),  /* wait4 */
      JEQ(231, 75, 0),  /* exit_group */
      JEQ(436, 74, 0),  /* close_range */
      JEQ(23, 73, 0),  /* select */
      JEQ(270, 72, 0),  /* pselect6 */
      JEQ(62, 71, 0),  /* kill */
      JEQ(63, 70, 0),  /* uname */
      JEQ(100, 69, 0),  /* times */
      JEQ(229, 68, 0),  /* clock_getres */
      JEQ(204, 67, 0),  /* sched_getaffinity */
      JEQ(99, 66, 0),  /* sysinfo */
      JEQ(98, 65, 0),  /* getrusage */
      JEQ(2, 64, 0),  /* open */
      JEQ(257, 63, 0),  /* openat */
      JEQ(85, 62, 0),  /* creat */
      JEQ(4, 61, 0),  /* stat */
      JEQ(6, 60, 0),  /* lstat */
      JEQ(332, 59, 0),  /* statx */
      JEQ(21, 58, 0),  /* access */
      JEQ(269, 57, 0),  /* faccessat */
      JEQ(439, 56, 0),  /* faccessat2 */
      JEQ(262, 55, 0),  /* newfstatat */
      JEQ(87, 54, 0),  /* unlink */
      JEQ(263, 53, 0),  /* unlinkat */
      JEQ(83, 52, 0),  /* mkdir */
      JEQ(258, 51, 0),  /* mkdirat */
      JEQ(84, 50, 0),  /* rmdir */
      JEQ(82, 49, 0),  /* rename */
      JEQ(264, 48, 0),  /* renameat */
      JEQ(316, 47, 0),  /* renameat2 */
      JEQ(89, 46, 0),  /* readlink */
      JEQ(267, 45, 0),  /* readlinkat */
      JEQ(80, 44, 0),  /* chdir */
      JEQ(79, 43, 0),  /* getcwd */
      JEQ(76, 42, 0),  /* truncate */
      JEQ(33, 41, 0),  /* dup2 */
      JEQ(292, 40, 0),  /* dup3 */
      JEQ(40, 39, 0),  /* sendfile */
      JEQ(131, 38, 0),  /* sigaltstack */
      JEQ(97, 37, 0),  /* getrlimit */
      JEQ(160, 36, 0),  /* setrlimit */
      JEQ(302, 35, 0),  /* prlimit64 */
      JEQ(282, 34, 0),  /* signalfd */
      JEQ(289, 33, 0),  /* signalfd4 */
      JEQ(275, 32, 0),  /* splice */
      JEQ(276, 31, 0),  /* tee */
      JEQ(253, 30, 0),  /* inotify_init */
      JEQ(294, 29, 0),  /* inotify_init1 */
      JEQ(254, 28, 0),  /* inotify_add_watch */
      JEQ(255, 27, 0),  /* inotify_rm_watch */
      JEQ(47, 13, 0),  /* recvmsg */
      JEQ(56, 15, 0),  /* clone */
      JGE(41, 0, 25),  /* socket */
      JGE(60, 24, 23),  /* clone_end */
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 1),
      JGE((SHIM_IPC_FD + 1), 0, 21),
      JEQ(0, 19, 0),  /* read */
      JGE(SHIM_VFD_BASE, 18, 19),
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 1),
      JGE((SHIM_IPC_FD + 1), 0, 16),
      JGE(3, 0, 14),  /* close */
      JGE(SHIM_VFD_BASE, 13, 14),
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 11),
      JGE((SHIM_IPC_FD + 1), 10, 11),
      LD(BPF_ARG0),
      JSET(65536, 9, 8),  /* CLONE_THREAD */
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 4),
      JGE((SHIM_IPC_FD + 1), 3, 5),
      LD(BPF_ARG4),
      JGE(0, 1, 1),  /* read */
      LD(BPF_ARG0),
      JGE(SHIM_VFD_BASE, 0, 2),
      JGE(4294963200, 1, 0),
      RET(SECCOMP_RET_TRAP),
      RET(SECCOMP_RET_ALLOW),
  };
  struct sock_filter prog_audit[] = {  /* 133 instructions */
      LD(BPF_ARCHF),
      JEQ(AUDIT_ARCH_X86_64, 0, 130),
      LD(BPF_IPHI),
      JEQ((uint32_t)((uintptr_t)SHIM_GADGET_ADDR >> 32), 0, 3),
      LD(BPF_IPLO),
      JGE((uint32_t)(uintptr_t)SHIM_GADGET_ADDR, 0, 1),
      JGE(((uint32_t)(uintptr_t)SHIM_GADGET_ADDR + 4096), 0, 125),
      LD(BPF_NR),
      JEQ(15, 123, 0),
      JEQ(0, 98, 0),  /* read */
      JEQ(1, 102, 0),  /* write */
      JEQ(3, 111, 0),  /* close */
      JEQ(19, 95, 0),  /* readv */
      JEQ(20, 99, 0),  /* writev */
      JEQ(16, 113, 0),  /* ioctl */
      JEQ(72, 112, 0),  /* fcntl */
      JEQ(32, 111, 0),  /* dup */
      JEQ(5, 110, 0),  /* fstat */
      JEQ(8, 109, 0),  /* lseek */
      JEQ(217, 108, 0),  /* getdents64 */
      JEQ(77, 107, 0),  /* ftruncate */
      JEQ(74, 106, 0),  /* fsync */
      JEQ(75, 105, 0),  /* fdatasync */
      JEQ(81, 104, 0),  /* fchdir */
      JEQ(17, 103, 0),  /* pread64 */
      JEQ(18, 102, 0),  /* pwrite64 */
      JEQ(9, 99, 0),  /* mmap */
      JEQ(35, 103, 0),  /* nanosleep */
      JEQ(230, 102, 0),  /* clock_nanosleep */
      JEQ(228, 101, 0),  /* clock_gettime */
      JEQ(96, 100, 0),  /* gettimeofday */
      JEQ(201, 99, 0),  /* time */
      JEQ(318, 98, 0),  /* getrandom */
      JEQ(7, 97, 0),  /* poll */
      JEQ(271, 96, 0),  /* ppoll */
      JEQ(213, 95, 0),  /* epoll_create */
      JEQ(291, 94, 0),  /* epoll_create1 */
      JEQ(233, 93, 0),  /* epoll_ctl */
      JEQ(232, 92, 0),  /* epoll_wait */
      JEQ(281, 91, 0),  /* epoll_pwait */
      JEQ(288, 90, 0),  /* accept4 */
      JEQ(435, 89, 0),  /* clone3 */
      JEQ(39, 88, 0),  /* getpid */
      JEQ(110, 87, 0),  /* getppid */
      JEQ(186, 86, 0),  /* gettid */
      JEQ(283, 85, 0),  /* timerfd_create */
      JEQ(286, 84, 0),  /* timerfd_settime */
      JEQ(287, 83, 0),  /* timerfd_gettime */
      JEQ(284, 82, 0),  /* eventfd */
      JEQ(290, 81, 0),  /* eventfd2 */
      JEQ(202, 80, 0),  /* futex */
      JEQ(14, 79, 0),  /* rt_sigprocmask */
      JEQ(22, 78, 0),  /* pipe */
      JEQ(293, 77, 0),  /* pipe2 */
      JEQ(61, 76, 0),  /* wait4 */
      JEQ(231, 75, 0),  /* exit_group */
      JEQ(436, 74, 0),  /* close_range */
      JEQ(23, 73, 0),  /* select */
      JEQ(270, 72, 0),  /* pselect6 */
      JEQ(62, 71, 0),  /* kill */
      JEQ(63, 70, 0),  /* uname */
      JEQ(100, 69, 0),  /* times */
      JEQ(229, 68, 0),  /* clock_getres */
      JEQ(204, 67, 0),  /* sched_getaffinity */
      JEQ(99, 66, 0),  /* sysinfo */
      JEQ(98, 65, 0),  /* getrusage */
      JEQ(2, 64, 0),  /* open */
      JEQ(257, 63, 0),  /* openat */
      JEQ(85, 62, 0),  /* creat */
      JEQ(4, 61, 0),  /* stat */
      JEQ(6, 60, 0),  /* lstat */
      JEQ(332, 59, 0),  /* statx */
      JEQ(21, 58, 0),  /* access */
      JEQ(269, 57, 0),  /* faccessat */
      JEQ(439, 56, 0),  /* faccessat2 */
      JEQ(262, 55, 0),  /* newfstatat */
      JEQ(87, 54, 0),  /* unlink */
      JEQ(263, 53, 0),  /* unlinkat */
      JEQ(83, 52, 0),  /* mkdir */
      JEQ(258, 51, 0),  /* mkdirat */
      JEQ(84, 50, 0),  /* rmdir */
      JEQ(82, 49, 0),  /* rename */
      JEQ(264, 48, 0),  /* renameat */
      JEQ(316, 47, 0),  /* renameat2 */
      JEQ(89, 46, 0),  /* readlink */
      JEQ(267, 45, 0),  /* readlinkat */
      JEQ(80, 44, 0),  /* chdir */
      JEQ(79, 43, 0),  /* getcwd */
      JEQ(76, 42, 0),  /* truncate */
      JEQ(33, 41, 0),  /* dup2 */
      JEQ(292, 40, 0),  /* dup3 */
      JEQ(40, 39, 0),  /* sendfile */
      JEQ(131, 38, 0),  /* sigaltstack */
      JEQ(97, 37, 0),  /* getrlimit */
      JEQ(160, 36, 0),  /* setrlimit */
      JEQ(302, 35, 0),  /* prlimit64 */
      JEQ(282, 34, 0),  /* signalfd */
      JEQ(289, 33, 0),  /* signalfd4 */
      JEQ(275, 32, 0),  /* splice */
      JEQ(276, 31, 0),  /* tee */
      JEQ(253, 30, 0),  /* inotify_init */
      JEQ(294, 29, 0),  /* inotify_init1 */
      JEQ(254, 28, 0),  /* inotify_add_watch */
      JEQ(255, 27, 0),  /* inotify_rm_watch */
      JEQ(47, 13, 0),  /* recvmsg */
      JEQ(56, 15, 0),  /* clone */
      JGE(41, 0, 24),  /* socket */
      JGE(60, 23, 23),  /* clone_end */
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 1),
      JGE((SHIM_IPC_FD + 1), 0, 21),
      JEQ(0, 19, 0),  /* read */
      JGE(SHIM_VFD_BASE, 18, 18),
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 1),
      JGE((SHIM_IPC_FD + 1), 0, 16),
      JGE(3, 0, 14),  /* close */
      JGE(SHIM_VFD_BASE, 13, 13),
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 11),
      JGE((SHIM_IPC_FD + 1), 10, 11),
      LD(BPF_ARG0),
      JSET(65536, 9, 8),  /* CLONE_THREAD */
      LD(BPF_ARG0),
      JGE(SHIM_IPC_LOW, 0, 4),
      JGE((SHIM_IPC_FD + 1), 3, 5),
      LD(BPF_ARG4),
      JGE(0, 1, 1),  /* read */
      LD(BPF_ARG0),
      JGE(SHIM_VFD_BASE, 0, 1),
      JGE(4294963200, 0, 0),
      RET(SECCOMP_RET_TRAP),
      RET(SECCOMP_RET_ALLOW),
  };
  /* END GENERATED BPF */
  struct sock_fprog fprog = {sizeof(prog) / sizeof(prog[0]), prog};
  struct sock_fprog fprog_audit = {
      sizeof(prog_audit) / sizeof(prog_audit[0]), prog_audit};
  if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) return -1;
  return (int)prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER,
                    shim_audit_on ? &fprog_audit : &fprog);
}

/* ---- constructor -------------------------------------------------------- */

__attribute__((constructor)) static void shim_init(void) {
  const char *on = getenv("SHADOW_SHIM");
  if (!on || on[0] != '1') return; /* not under the simulator */
  /* THE GADGET PAGE COMES FIRST: after an execve the previous image's
   * seccomp filter is already live, and it traps file syscalls like the
   * open(2) below — but it ALLOWS any syscall issued from the fixed
   * gadget address, so mapping the gadget (mmap/mprotect are untrapped)
   * and routing raw syscalls through it makes the rest of this ctor
   * filter-proof. */
  shim_map_gadget(); /* shim_gadget stays NULL on failure: raw syscalls
                        fall back to the inline-asm path */
  /* real ids from /proc, NOT raw getpid (trapped: returns vpids) */
  shim_refresh_real_ids();

  const char *shm = getenv("SHADOW_TIME_SHM");
  if (shm) {
    /* RW: the shim reads the clock AND writes the fast-op counter slot
     * (shring.h SHIM_PAGE_FASTOPS). Falls back to RO (counter writes
     * gated on shim_page_rw) if the worker ever hands a sealed fd. */
    int fd = open(shm, O_RDWR);
    if (fd >= 0) {
      void *p = mmap(NULL, 4096, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
      if (p != MAP_FAILED) {
        shim_time_page = (volatile int64_t *)p;
        shim_page_rw = 1;
      }
      close(fd);
    }
    if (!shim_time_page) {
      fd = open(shm, O_RDONLY);
      if (fd >= 0) {
        void *p = mmap(NULL, 4096, PROT_READ, MAP_SHARED, fd, 0);
        if (p != MAP_FAILED) shim_time_page = (volatile int64_t *)p;
        close(fd);
      }
    }
  }

  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_sigaction = sigsys_handler;
  sa.sa_flags = SA_SIGINFO | SA_NODEFER;
  sigemptyset(&sa.sa_mask);
  if (sigaction(SIGSYS, &sa, NULL) != 0) _exit(124);

  /* TSC virtualization: raw rdtsc/rdtscp fault into sigsegv_handler and
   * read simulated time. Best-effort — PR_SET_TSC is x86-64-specific. */
  struct sigaction tsa;
  memset(&tsa, 0, sizeof tsa);
  tsa.sa_sigaction = sigsegv_handler;
  /* SA_ONSTACK: harmless without an altstack, required so guests that
   * sigaltstack() for stack-overflow recovery still get their handler */
  tsa.sa_flags = SA_SIGINFO | SA_ONSTACK;
  sigemptyset(&tsa.sa_mask);
  if (sigaction(SIGSEGV, &tsa, NULL) == 0)
    prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);

  /* the gadget is now LOAD-BEARING (fork replay, RETRY_NATIVE
   * re-issues, audit): without it those paths would re-trap and corrupt
   * the worker protocol — fail loudly instead of running degraded */
  if (shim_gadget == NULL) _exit(122);
  const char *audit = getenv("SHADOW_AUDIT");
  shim_audit_on = audit && audit[0] == '1';

  shim_active = 1;
  shim_tls_ready = 1;
  /* handshake: block until the simulation's spawn event grants the turn */
  if (forward(SHIM_HELLO, (uint64_t)getpid(), 0, 0, 0, 0, 0) != 0) _exit(124);
  if (install_seccomp() != 0) _exit(123);
}
