/* colcore — C fast path for the columnar data plane.
 *
 * Round-4 answer to VERDICT.md item #1 (kill the ~2.3 us/event Python
 * floor).  Design rule: this module accelerates FUNCTIONS, never forks
 * data STRUCTURES.  Egress rows stay the same 12-field Python tuples
 * Host.emit_msg appends; store rows stay the same 13-field tuples in the
 * same StoreBatch/pending deque; the event heap stays EventQueue._heap.
 * Every Python path (mesh plane, fault filters, pcap hosts, managed
 * bridges, round_robin qdisc) therefore interoperates with the C path
 * per-phase with no conversion layer, and the bit-identity obligations
 * (tests/test_colplane.py, test_colcore.py) reduce to "same arithmetic,
 * same order" — which this file replicates operation-for-operation from
 * network/colplane.py, network/fluid.py and host/host.py.
 *
 * What runs in C:
 *   - Core.barrier():   egress collection, uid minting, blackhole filter,
 *                       closed-form token-bucket departures (the exact
 *                       integer math of fluid.TokenBuckets), latency and
 *                       loss-threshold gathers, inline threefry loss
 *                       draws, and sorted store construction.  Batches
 *                       big enough for the device draw plane are handed
 *                       back to Python (the existing dispatch machinery).
 *   - Core.extract():   due-prefix extraction from the pending store into
 *                       per-host C inboxes (per-host (t,key) order).
 *   - Core.run_round(): the per-round host loop: inbox/heap merge, C heap
 *                       pops, ingress-bucket charging, datagram dispatch,
 *                       and the C gossip app; Python callables (timers,
 *                       stream endpoints, plugin callbacks) are invoked
 *                       through the normal C API when a row or event
 *                       isn't C-handled.
 *   - GossipState:      the gossip model's hot half (models/gossip.py
 *                       delegates; peer selection/logging stay Python).
 *
 * Reference analog (SURVEY.md): upstream Shadow's hot path is native
 * (Rust/C) for exactly this reason; the Python plane remains as the
 * readable twin and the oracle for the dual-run tests.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <marshal.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>
#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* Python < 3.12 spells the member API via structmember.h (T_INT/READONLY);
 * 3.12 moved the canonical names into Python.h. Compile against both. */
#ifndef Py_T_INT
#include <structmember.h>
#define Py_T_INT T_INT
#define Py_READONLY READONLY
#endif

static int64_t tm_sect[12];
static int64_t tm_cnt[12];
#ifdef COLCORE_TIMERS
#include <time.h>
static inline int64_t nsnow(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}
#define TM0(i) int64_t _t##i = nsnow()
#define TM1(i) do { tm_sect[i] += nsnow() - _t##i; tm_cnt[i]++; } while (0)
#else
#define TM0(i) do {} while (0)
#define TM1(i) do {} while (0)
#endif

#define NS_PER_SEC 1000000000LL
#define MTU 1500
#define HEADER 40
#define HARD_MAX_PKTS 64
#define PKT_SHIFT 26
#define INF_I64 (((int64_t)1) << 61)
#define T_NEVER_C (((int64_t)1) << 62)
#define KIND_DGRAM 6
#define TX_SIZE 400
/* stream unit kinds (network/unit.py order) */
#define TK_SYN 0
#define TK_SYNACK 1
#define TK_DATA 2
#define TK_ACK 3
#define TK_FIN 4
#define TK_FINACK 5

/* ---- threefry2x32-20 (ops/prng.py twin; Salmon et al. SC'11) ---------- */
static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static void threefry2x32_c(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                           uint32_t *o0, uint32_t *o1) {
  static const int ra[4] = {13, 15, 26, 6}, rb[4] = {17, 29, 16, 24};
  uint32_t ks[3];
  ks[0] = k0; ks[1] = k1; ks[2] = k0 ^ k1 ^ 0x1BD11BDAu;
  uint32_t x0 = c0 + ks[0], x1 = c1 + ks[1];
  for (int g = 0; g < 5; g++) {
    const int *rots = (g % 2 == 0) ? ra : rb;
    for (int i = 0; i < 4; i++) {
      x0 += x1;
      x1 = rotl32(x1, rots[i]);
      x1 ^= x0;
    }
    uint32_t j = (uint32_t)g + 1;
    x0 += ks[j % 3];
    x1 += ks[(j + 1) % 3] + j;
  }
  *o0 = x0; *o1 = x1;
}

/* fluid.loss_flags twin: unit dropped iff any of its first npk per-packet
 * draws lands under the threshold (draw = top 24 bits of x0). */
static int unit_dropped(uint64_t seed, uint64_t uid, int npk, uint32_t th) {
  if (!th) return 0;
  uint32_t k0 = (uint32_t)(seed & 0xFFFFFFFFu);
  uint32_t k1 = (uint32_t)(seed >> 32);
  uint32_t lo = (uint32_t)(uid & 0xFFFFFFFFu);
  uint32_t hi = (uint32_t)(uid >> 32);
  for (int p = 0; p < npk; p++) {
    uint32_t x0, x1;
    threefry2x32_c(k0, k1, lo, hi | ((uint32_t)p << PKT_SHIFT), &x0, &x1);
    if ((x0 >> 8) < th) return 1;
  }
  return 0;
}

/* ---- seen-set: open-addressing hash of short byte strings ------------- */
typedef struct {
  uint64_t *hash;  /* 0 = empty */
  uint32_t *off;
  uint16_t *len;
  size_t cap, count;
  char *arena;
  size_t alen, acap;
} SeenSet;

static uint64_t fnv1a(const char *s, Py_ssize_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (Py_ssize_t i = 0; i < n; i++) {
    h ^= (unsigned char)s[i];
    h *= 1099511628211ULL;
  }
  return h | 1; /* never 0 (0 marks an empty slot) */
}

static int seen_init(SeenSet *s) {
  s->cap = 64; s->count = 0;
  s->hash = calloc(s->cap, sizeof(uint64_t));
  s->off = malloc(s->cap * sizeof(uint32_t));
  s->len = malloc(s->cap * sizeof(uint16_t));
  s->acap = 1024; s->alen = 0;
  s->arena = malloc(s->acap);
  if (!s->hash || !s->off || !s->len || !s->arena) return -1;
  return 0;
}

static void seen_free(SeenSet *s) {
  free(s->hash); free(s->off); free(s->len); free(s->arena);
  memset(s, 0, sizeof *s);
}

static int seen_contains(SeenSet *s, const char *k, Py_ssize_t n) {
  uint64_t h = fnv1a(k, n);
  size_t i = (size_t)h & (s->cap - 1);
  while (s->hash[i]) {
    if (s->hash[i] == h && s->len[i] == (uint16_t)n &&
        memcmp(s->arena + s->off[i], k, (size_t)n) == 0)
      return 1;
    i = (i + 1) & (s->cap - 1);
  }
  return 0;
}

static int seen_grow(SeenSet *s) {
  size_t ncap = s->cap * 2;
  uint64_t *nh = calloc(ncap, sizeof(uint64_t));
  uint32_t *no = malloc(ncap * sizeof(uint32_t));
  uint16_t *nl = malloc(ncap * sizeof(uint16_t));
  if (!nh || !no || !nl) { free(nh); free(no); free(nl); return -1; }
  for (size_t i = 0; i < s->cap; i++) {
    if (!s->hash[i]) continue;
    size_t j = (size_t)s->hash[i] & (ncap - 1);
    while (nh[j]) j = (j + 1) & (ncap - 1);
    nh[j] = s->hash[i]; no[j] = s->off[i]; nl[j] = s->len[i];
  }
  free(s->hash); free(s->off); free(s->len);
  s->hash = nh; s->off = no; s->len = nl; s->cap = ncap;
  return 0;
}

/* add if absent; returns 1 added, 0 already present, -1 on OOM */
static int seen_add(SeenSet *s, const char *k, Py_ssize_t n) {
  if (n > 0xFFFF) return -1;
  uint64_t h = fnv1a(k, n);
  size_t i = (size_t)h & (s->cap - 1);
  while (s->hash[i]) {
    if (s->hash[i] == h && s->len[i] == (uint16_t)n &&
        memcmp(s->arena + s->off[i], k, (size_t)n) == 0)
      return 0;
    i = (i + 1) & (s->cap - 1);
  }
  if (s->alen + (size_t)n > s->acap) {
    size_t ncap = s->acap * 2;
    while (ncap < s->alen + (size_t)n) ncap *= 2;
    char *na = realloc(s->arena, ncap);
    if (!na) return -1;
    s->arena = na; s->acap = ncap;
  }
  memcpy(s->arena + s->alen, k, (size_t)n);
  s->hash[i] = h; s->off[i] = (uint32_t)s->alen; s->len[i] = (uint16_t)n;
  s->alen += (size_t)n;
  s->count++;
  if (s->count * 10 >= s->cap * 7) {
    if (seen_grow(s) < 0) return -1;
  }
  return 1;
}

/* ---- interned attribute names ----------------------------------------- */
static PyObject *S_id, *S_now, *S_inbox, *S_egress_rows, *S_uid_counter,
    *S_emitters, *S_ev_key, *S_min_used_latency, *S_units_sent,
    *S_units_dropped, *S_units_blackholed, *S_bytes_sent, *S_device,
    *S_device_floor, *S_rows, *S_pos, *S_dispatch_row, *S_run_events,
    *S_popleft, *S_append, *S_ingress_deferred_rows, *S_pcap,
    *S_n_emitted, *S_n_delivered, *S_n_dgrams, *S_n_dgrams_recv,
    *S_n_events, *S_dispatch, *S_n_teardown, *S_n_blackholed, *S_down,
    *S_cc_id, *S_seed, *S_bootstrap_end, *S_unit_chunk,
    *S_socket_send_buffer, *S_socket_recv_buffer;

/* cached small objects */
static PyObject *O_zero, *O_one, *O_false, *O_kind_dgram;

/* read an int64 attribute (Python int) */
static int attr_i64(PyObject *o, PyObject *name, int64_t *out) {
  PyObject *v = PyObject_GetAttr(o, name);
  if (!v) return -1;
  *out = PyLong_AsLongLong(v);
  Py_DECREF(v);
  if (*out == -1 && PyErr_Occurred()) return -1;
  return 0;
}

static int attr_set_i64(PyObject *o, PyObject *name, int64_t v) {
  PyObject *pv = PyLong_FromLongLong(v);
  if (!pv) return -1;
  int r = PyObject_SetAttr(o, name, pv);
  Py_DECREF(pv);
  return r;
}

/* add a C delta into an int attribute (no-op for delta 0) */
static int attr_add_i64(PyObject *o, PyObject *name, int64_t d) {
  if (!d) return 0;
  int64_t cur;
  if (attr_i64(o, name, &cur) < 0) return -1;
  return attr_set_i64(o, name, cur + d);
}

/* tuple int helpers (no error checking beyond PyLong; rows are ours) */
static inline int64_t tup_i64(PyObject *t, Py_ssize_t i) {
  return PyLong_AsLongLong(PyTuple_GET_ITEM(t, i));
}

/* ---- per-host C state -------------------------------------------------- */
typedef struct {
  int64_t t, key;
  PyObject *payload; /* owned ref while in the inbox (NULL = no payload) */
  /* dispatch fields, fully packed (round 5: store rows no longer carry
   * a Python tuple at all on the C path; the 13-tuple is materialized
   * lazily only for Python-fallback dispatch / deferred parking) */
  int64_t nbytes, seq; /* stream dispatch: cum-ack / byte offset ride here */
  int32_t size, peer, bport, aport;
  int32_t frag, nfrags;
  int16_t kind;
} IRow;

struct GossipState_s;

/* packed per-row store record (CBatch.recs); field meanings match IRow */
typedef struct {
  int64_t t, key;
  int64_t nbytes, seq;
  int32_t tgt, size, peer, bport, aport;
  int32_t frag, nfrags;
  int16_t kind;
} SRec;

/* one egress row in a host's packed C egress buffer (the Host.emit_msg
 * tuple of the Python plane, without the tuple) */
typedef struct {
  int64_t size, t_emit, nbytes, seq;
  PyObject *payload; /* owned; NULL = None */
  int32_t kind, dst, sport, dport, frag, nfrags;
} ERow;

typedef struct {
  PyObject *host;      /* borrowed: Core->hosts list holds the ref */
  PyObject *id_obj;    /* owned: the host's stable `id` int object */
  PyObject *equeue;    /* owned: host.equeue (C timer push/cancel) */
  PyObject *heap;      /* owned: equeue._heap list */
  PyObject *live;      /* owned: equeue._live set */
  PyObject *cancelled; /* owned: equeue._cancelled set */
  /* cached heap root for the per-round due check: an OWNED ref to the
   * last-seen heap[0] plus its time. Owning the ref makes pointer
   * identity sound (the object cannot be freed and its address reused
   * while cached); if heap[0] is a different object, re-read. A root
   * that was cancelled in place keeps its time — a conservative lower
   * bound on the live head, which only costs a wasted scan, never a
   * missed event. */
  PyObject *head_cache;
  int64_t head_time;
  int py_mode;         /* pcap etc.: dispatch through Python run_events */
  PyObject *egress;    /* owned: host.egress_rows (identity-stable) */
  PyObject *conns;     /* owned: host._conns dict (identity-stable) */
  PyObject *listeners; /* owned: host._listeners dict (identity-stable) */
  PyObject *ack_eps;   /* owned: host._ack_eps dict (identity-stable:
                          cleared in place by the barrier, never rebound) */
  /* fault lifecycle (shadow_tpu/faults.py): crashed-host flag, mirrored
   * from Host.down by Core.host_crash/host_boot so the per-row dispatch
   * can discard arrivals at a dead NIC without an attribute read */
  int down;
  /* C-registered datagram ports (gossip); tiny linear table */
  int nports;
  int port[4];
  struct GossipState_s *gs[4];
  /* C inbox (filled by extract, consumed by run_host) */
  IRow *inbox;
  int inbox_n, inbox_cap, inbox_last_slice, inbox_multi;
  /* packed C egress buffer (emission-order; barrier consumes + clears) */
  ERow *erow;
  int erow_n, erow_cap;
  /* per-round counter deltas, flushed to host attrs after run_host */
  int64_t d_emitted, d_delivered, d_dgrams, d_dgrams_recv, d_events;
  /* stream-transport + routing counter deltas (host.counters keys) */
  int64_t d_sbytes_q, d_sbytes_recv, d_resets, d_unroutable;
  /* fault-accounting deltas (folded into the same attrs/counter keys the
   * Python twin maintains: _n_teardown/_n_blackholed and the
   * faults_active-gated stream recovery counters) */
  int64_t d_teardown, d_blackholed;
  int64_t d_fast_retx, d_rto_retx, d_timeouts, d_sack_retx;
  /* per-host congestion control (Host.cc_id, read at bind): dispatch
   * integer for the CongestionControl twin — endpoints the C SYN accept
   * creates must pick the same algorithm the Python accept would */
  int cc_kind;
} CHost;

typedef struct {
  PyObject_HEAD
  PyObject *plane;   /* borrowed: plane._c owns us (documented cycle-break) */
  PyObject *hosts;   /* owned list */
  PyObject *pending; /* owned deque */
  PyObject *deferred; /* owned set (plane._deferred) */
  PyObject *active;  /* owned set (controller._active), via bind_active */
  PyObject *storebatch_cls; /* owned: colplane.StoreBatch */
  /* numpy arrays: owned refs + raw pointers */
  PyObject *arrs[11];
  int64_t *tokens_down, *tbase, *tokens, *debt, *rate_up, *cap_up, *lat;
  int64_t *rate_down, *cap_down;
  uint32_t *thresh;
  int32_t *hostnode;
  int64_t H, G;
  uint64_t seed;
  int64_t bootstrap_end;
  int64_t unit_chunk; /* fluid quantum payload bytes (Host.unit_chunk) */
  int64_t sock_sbuf, sock_rbuf; /* experimental.socket_*_buffer */
  int mesh_mode; /* hand live batches to Python for the mesh collective */
  /* a faults: section exists (mirrors plane.faults_active): gates the
   * per-host blackhole/teardown accounting and the stream-recovery
   * counters, exactly like the Python twins gate on host.faults_active */
  int faults_active;
  /* multi-process sharding (parallel/shards.py): when shard_n > 1,
   * resolved store rows whose destination host id is not congruent to
   * shard_id (mod shard_n) divert into xout[dst % shard_n] — a Python
   * list of per-shard lists the plane owns — as 13-field store tuples,
   * instead of entering the local pending store. Counting (units_sent /
   * bytes_sent) stays with the RESOLVING shard. */
  int32_t shard_id, shard_n;
  PyObject *xout; /* owned; NULL until bind_shard */
  /* send-side packer (Core_take_xout_packed): when bind_shard receives
   * xout=None, diverted rows accumulate HERE as packed SRec + payload
   * refs and leave as shards.py wire-format byte blocks at the round
   * edge — no 13-field Python tuples on the cross-shard send path
   * (receive side was already packed via cbatch_from_packed). Buffers
   * are drained every round edge, so they are empty at every snapshot
   * boundary. Payload refs are owned (NULL = None). */
  int xpacked;
  SRec **xrecs;     /* [shard_n] growable per-destination-shard arrays */
  PyObject ***xpay; /* [shard_n] parallel payload refs */
  int *xn, *xcap;
  CHost *hs;
  /* scratch buffers reused across barriers */
  struct BRow *brow;
  int brow_cap;
  /* speculative forward windows (fused multi-round device windows): the
   * plane dispatches PREFIX-MIN threefry draws for FUTURE uids under
   * each host's recent npkts classes; the barrier's inline-draw loop
   * consults the installed table — uid-range + exact npkts match, and
   * dropped == (min_draw < thresh) for ANY thresh, so one speculated row
   * serves every destination. A stale or wrong guess falls back to the
   * inline threefry twin and can never change results. */
  struct SpecHost *spec;
  int spec_on;
  int64_t spec_hits, spec_draws; /* drained by Core_spec_stats */
  int32_t *spec_dq; /* demand queue: host ids awaiting a window */
  int spec_dq_n, spec_dq_cap;
  /* cached sorted snapshot of the active set (run_round's iteration
   * order). Valid while its length matches the set: discards happen
   * ONLY inside run_round (which updates both), so between rounds the
   * set can only GROW — a size match proves the contents are identical
   * and the per-round snapshot + qsort can be skipped entirely. */
  int64_t *act_ids;
  int64_t act_n;
  int64_t act_cap;
  /* ids added since the last refresh (extract's touched hosts and the
   * Python-side activate hook both land here): when the set size equals
   * act_n + pend_n, the refresh is a tiny sorted-merge instead of a
   * full iterate + qsort of the whole set */
  int64_t *act_pend;
  int64_t act_pend_n, act_pend_cap;
} CoreObject;

/* per-host speculative window + npkts class tracker. Two generations:
 * the live window [u0, u0+n) plus a staged continuation [nu0, nu0+nn)
 * prefetched when consumption passes 3/4 of the live one, so a steady
 * flow never sees a speculation gap while a wave is in flight. */
typedef struct SpecHost {
  uint64_t u0;          /* first speculated uid (live window) */
  int32_t n;            /* speculated draws per class */
  int32_t npk_a, npk_b; /* the INSTALLED window's npkts classes (bound to
                         * min_a/min_b at install; immutable until then) */
  uint32_t *min_a, *min_b; /* per-uid prefix-min 24-bit draws */
  uint64_t nu0; /* staged continuation window */
  int32_t nn;
  int32_t nnpk_a, nnpk_b;
  uint32_t *nmin_a, *nmin_b;
  uint8_t ready;    /* live mins consultable */
  uint8_t nready;   /* staged mins present */
  uint8_t inflight; /* demanded; a wave is being drawn */
  int32_t tnpk_a, tnpk_b; /* class TRACKER: two most-recent npkts (kept
                           * apart from the window labels — a transient
                           * third class must not invalidate good mins) */
  int32_t run;      /* live-draw momentum (halved on a class change) */
  int32_t want;     /* next window size (doubles on productive exhaust) */
} SpecHost;

#define SPEC_MIN_RUN 16  /* live draws before a host earns speculation */
#define SPEC_WANT0 128   /* first window size (units per class) */
#define SPEC_WANT_MAX 1024
/* classes cheaper than this many packet draws stay inline: a speculative
 * hit saves ~npk packet draws, and the consult itself is not free */
#define SPEC_MIN_NPK 4

/* one barrier row during assembly (all fields packed; `payload` is an
 * owned ref the barrier releases — or hands to the store — when done) */
typedef struct BRow {
  PyObject *payload; /* owned during assembly; NULL = None */
  PyObject *src_obj; /* borrowed (CHost.id_obj) */
  int32_t src, dst;
  int64_t size, t_emit, depart, arrival, key;
  int64_t nbytes, seq;
  uint64_t uid;
  uint32_t th;
  int32_t npk;
  int32_t kind, sport, dport, frag, nfrags;
  uint8_t drop;
} BRow;

/* ---- GossipState ------------------------------------------------------- */
typedef struct GossipState_s {
  PyObject_HEAD
  CoreObject *core; /* owned */
  int hid;
  int port;
  PyObject *port_obj;   /* owned cached PyLong(port) */
  int32_t *peers;
  int npeers;
  SeenSet seen;
  int64_t received_tx;
  int64_t next_dgram;
} GossipState;

/* forward decls */
static int core_emit_dgram(CoreObject *c, CHost *h, int64_t now, int dst,
                           GossipState *g, int dst_port, int64_t nbytes,
                           PyObject *payload);
static int gossip_on_msg_c(CoreObject *c, CHost *h, GossipState *g,
                           int64_t now, PyObject *payload, int64_t src_host);

/* ---- CBatch: a fully packed resolved store batch -----------------------
 * The C-path replacement for colplane.StoreBatch (round 5): no Python
 * row tuples — one SRec + one payload ref per row. Lives in
 * plane.pending next to (and duck-typing) StoreBatch: head_time() and
 * the consumed-prefix `pos` are the whole shared surface. Not
 * GC-tracked: payloads are bytes/None by the emission contract
 * (transport slices, gossip cells, model frames), which cannot form
 * reference cycles. */
typedef struct {
  PyObject_HEAD
  SRec *recs;
  PyObject **pay; /* owned refs; NULL = None */
  int n, pos;
} CBatch;

static void CBatch_dealloc(CBatch *b) {
  for (int i = 0; i < b->n; i++) Py_XDECREF(b->pay[i]);
  free(b->recs);
  free(b->pay);
  Py_TYPE(b)->tp_free((PyObject *)b);
}

static PyObject *CBatch_head_time(CBatch *b, PyObject *noarg) {
  (void)noarg;
  return PyLong_FromLongLong(b->pos < b->n ? b->recs[b->pos].t : T_NEVER_C);
}

static PyObject *CBatch_export_rows(CBatch *b, PyObject *noarg);
static PyObject *CBatch_restore_state(CBatch *b, PyObject *state);

static PyMethodDef CBatch_methods[] = {
    {"head_time", (PyCFunction)CBatch_head_time, METH_NOARGS,
     "earliest undelivered row time (StoreBatch.head_time twin)"},
    {"export_rows", (PyCFunction)CBatch_export_rows, METH_NOARGS,
     "checkpoint export: (pos, [13-tuple store rows]) — the plane-"
     "neutral StoreBatch form"},
    {"_restore_state", (PyCFunction)CBatch_restore_state, METH_O,
     "fill an empty CBatch from (pos, rows) — export_rows' inverse, "
     "also the plain-StoreBatch -> CBatch converter on C-plane resume"},
    {NULL, NULL, 0, NULL}};

static PyMemberDef CBatch_members[] = {
    {"pos", Py_T_INT, offsetof(CBatch, pos), 0, "consumed-prefix cursor"},
    {"n", Py_T_INT, offsetof(CBatch, n), Py_READONLY, "row count"},
    {NULL, 0, 0, 0, NULL}};

static PyTypeObject CBatch_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.CBatch",
    .tp_basicsize = sizeof(CBatch),
    .tp_dealloc = (destructor)CBatch_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = CBatch_methods,
    .tp_members = CBatch_members,
    .tp_doc = "packed resolved store batch (StoreBatch twin, no tuples)",
};

static CBatch *cbatch_new(int n) {
  CBatch *b = PyObject_New(CBatch, &CBatch_Type);
  if (!b) return NULL;
  b->n = n;
  b->pos = 0;
  b->recs = malloc(sizeof(SRec) * (size_t)(n ? n : 1));
  b->pay = calloc((size_t)(n ? n : 1), sizeof(PyObject *));
  if (!b->recs || !b->pay) {
    free(b->recs); free(b->pay);
    b->recs = NULL; b->pay = NULL; b->n = 0;
    Py_DECREF(b);
    PyErr_NoMemory();
    return NULL;
  }
  return b;
}

/* materialize the colplane 13-tuple for one packed row (Python-fallback
 * dispatch, deferred parking, py_mode extraction) */
static PyObject *srec_tuple(const SRec *s, PyObject *payload) {
  PyObject *pl = payload ? payload : Py_None;
  return Py_BuildValue("(LLiiiiiLLiiiO)", (long long)s->t,
                       (long long)s->key, (int)s->tgt, (int)s->kind,
                       (int)s->peer, (int)s->aport, (int)s->bport,
                       (long long)s->nbytes, (long long)s->seq,
                       (int)s->frag, (int)s->nfrags, (int)s->size, pl);
}

static PyObject *irow_tuple(const CHost *h, const IRow *r, int64_t tgt);

/* ---- event-heap ops on EventQueue._heap (a PyList of 5-tuples) --------
 * Entries are (time, band, key, seq, task); (time, band, key, seq) is a
 * total order (seq unique), so any correct heap pops the same sequence as
 * Python's heapq — internal layout cannot affect results. */
static inline int heap_lt(PyObject *a, PyObject *b) {
  for (Py_ssize_t i = 0; i < 4; i++) {
    int64_t x = tup_i64(a, i), y = tup_i64(b, i);
    if (x != y) return x < y;
  }
  return 0;
}

/* pop the root of the heap list; returns an OWNED ref */
static PyObject *heap_pop(PyObject *heap) {
  Py_ssize_t n = PyList_GET_SIZE(heap);
  PyObject *last = PyList_GET_ITEM(heap, n - 1);
  Py_INCREF(last);
  if (PyList_SetSlice(heap, n - 1, n, NULL) < 0) {
    Py_DECREF(last);
    return NULL;
  }
  if (--n == 0) return last;
  PyObject *ret = PyList_GET_ITEM(heap, 0);
  Py_INCREF(ret);
  /* sift `last` down from the root */
  Py_ssize_t pos = 0;
  for (;;) {
    Py_ssize_t child = 2 * pos + 1;
    if (child >= n) break;
    if (child + 1 < n &&
        heap_lt(PyList_GET_ITEM(heap, child + 1), PyList_GET_ITEM(heap, child)))
      child++;
    PyObject *cobj = PyList_GET_ITEM(heap, child);
    if (!heap_lt(cobj, last)) break;
    Py_INCREF(cobj);
    PyList_SetItem(heap, pos, cobj); /* steals */
    pos = child;
  }
  PyList_SetItem(heap, pos, last); /* steals our ref to last */
  return ret;
}

/* heapq.heappush twin: append + sift-up with heap_lt. Steals the entry
 * ref. Identical resulting layout to heapq._siftdown (both shift each
 * passed parent down one level along the path and place the new entry at
 * its final slot). */
static int heap_push(PyObject *heap, PyObject *entry) {
  if (PyList_Append(heap, entry) < 0) { Py_DECREF(entry); return -1; }
  Py_DECREF(entry); /* the list holds it now */
  Py_ssize_t pos = PyList_GET_SIZE(heap) - 1;
  while (pos > 0) {
    Py_ssize_t parent = (pos - 1) >> 1;
    PyObject *pe = PyList_GET_ITEM(heap, parent);
    PyObject *ce = PyList_GET_ITEM(heap, pos);
    if (!heap_lt(ce, pe)) break;
    Py_INCREF(pe);
    Py_INCREF(ce);
    PyList_SetItem(heap, parent, ce); /* steals */
    PyList_SetItem(heap, pos, pe);    /* steals */
    pos = parent;
  }
  return 0;
}

/* EventQueue._drop_cancelled_head twin. Returns borrowed head or NULL
 * (empty); -1 via *err on failure. */
static PyObject *heap_head(CHost *h, int *err) {
  *err = 0;
  while (PyList_GET_SIZE(h->heap)) {
    PyObject *head = PyList_GET_ITEM(h->heap, 0);
    PyObject *seq = PyTuple_GET_ITEM(head, 3);
    int c = PySet_Contains(h->cancelled, seq);
    if (c < 0) { *err = 1; return NULL; }
    if (!c) return head;
    PyObject *popped = heap_pop(h->heap);
    if (!popped) { *err = 1; return NULL; }
    seq = PyTuple_GET_ITEM(popped, 3);
    if (PySet_Discard(h->cancelled, seq) < 0 ||
        PySet_Discard(h->live, seq) < 0) {
      Py_DECREF(popped); *err = 1; return NULL;
    }
    Py_DECREF(popped);
  }
  return NULL;
}

/* ---- emission (C gossip sendto -> egress row tuple) ------------------- */
static int core_emit_dgram_inner(CoreObject *c, CHost *h, int64_t now,
                           int dst, GossipState *g, int dst_port,
                           int64_t nbytes, PyObject *payload);
static int core_emit_dgram(CoreObject *c, CHost *h, int64_t now, int dst,
                           GossipState *g, int dst_port, int64_t nbytes,
                           PyObject *payload) {
  TM0(3);
  int r = core_emit_dgram_inner(c, h, now, dst, g, dst_port, nbytes, payload);
  TM1(3);
  return r;
}
/* packed emission core: append one ERow to the host's C egress buffer.
 * Mirrors Host.emit_msg's columnar branch without the tuple; payload is
 * INCREF'd (NULL/None accepted). */
static int core_emit_fields(CoreObject *c, CHost *h, int64_t now,
                            int kind, int dst, int64_t size, int64_t nbytes,
                            PyObject *payload, int64_t seq, int sport,
                            int dport, int frag, int nfrags) {
  if (h->erow_n == 0 && PyList_GET_SIZE(h->egress) == 0) {
    PyObject *em = PyObject_GetAttr(c->plane, S_emitters);
    if (!em) return -1;
    int r = PyList_Append(em, h->host);
    Py_DECREF(em);
    if (r < 0) return -1;
  }
  if (h->erow_n == h->erow_cap) {
    int ncap = h->erow_cap ? h->erow_cap * 2 : 32;
    ERow *nb = realloc(h->erow, sizeof(ERow) * (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    h->erow = nb;
    h->erow_cap = ncap;
  }
  ERow *e = &h->erow[h->erow_n++];
  e->kind = kind;
  e->dst = dst;
  e->size = size;
  e->t_emit = now;
  e->sport = sport;
  e->dport = dport;
  e->nbytes = nbytes;
  e->seq = seq;
  e->frag = frag;
  e->nfrags = nfrags;
  if (payload == Py_None) payload = NULL;
  Py_XINCREF(payload);
  e->payload = payload;
  h->d_emitted++;
  return 0;
}

static int core_emit_dgram_inner(CoreObject *c, CHost *h, int64_t now,
                           int dst, GossipState *g, int dst_port,
                           int64_t nbytes, PyObject *payload) {
  if (core_emit_fields(c, h, now, KIND_DGRAM, dst, nbytes + HEADER, nbytes,
                       payload, g->next_dgram++, g->port, dst_port, 0,
                       1) < 0)
    return -1;
  h->d_dgrams++;
  return 0;
}

/* egress-format 12-tuple for one ERow (the Python barrier's expected row
 * shape; used by materialize_egress and the device/mesh hand-off) */
static PyObject *erow_tuple(const ERow *e) {
  PyObject *pl = e->payload ? e->payload : Py_None;
  PyObject *t = Py_BuildValue("(iiLLiiLLiiO)", (int)e->kind, (int)e->dst,
                              (long long)e->size, (long long)e->t_emit,
                              (int)e->sport, (int)e->dport,
                              (long long)e->nbytes, (long long)e->seq,
                              (int)e->frag, (int)e->nfrags, pl);
  return t;
}

/* flush every host's packed C egress into its Python egress_rows list
 * (in emission order, ahead of any Python-appended rows? — there are
 * none: with the C engine attached every emission routes through
 * core_emit_fields, so egress_rows is empty until we fill it). Called
 * by colplane before its Python barrier paths (fault_filter rounds,
 * final flush) so those read the same rows they always did. */
static PyObject *Core_materialize_egress(CoreObject *c, PyObject *noarg) {
  (void)noarg;
  for (int64_t i = 0; i < c->H; i++) {
    CHost *h = &c->hs[i];
    if (!h->erow_n) continue;
    for (int j = 0; j < h->erow_n; j++) {
      ERow *e = &h->erow[j];
      PyObject *t = erow_tuple(e);
      if (!t) return NULL;
      int r = PyList_Append(h->egress, t);
      Py_DECREF(t);
      if (r < 0) return NULL;
      Py_XDECREF(e->payload);
      e->payload = NULL;
    }
    h->erow_n = 0;
  }
  Py_RETURN_NONE;
}

/* Python-callable packed emission (Host.emit_msg delegates here when the
 * C engine is attached; pcap capture stays on the Python side) */
static PyObject *Core_emit_row(CoreObject *c, PyObject *args) {
  long long hid, size, t_emit, nbytes, seq;
  int kind, dst, sport, dport, frag, nfrags;
  PyObject *payload;
  if (!PyArg_ParseTuple(args, "LiiLLiiLLiiO", &hid, &kind, &dst, &size,
                        &t_emit, &sport, &dport, &nbytes, &seq, &frag,
                        &nfrags, &payload))
    return NULL;
  if (hid < 0 || hid >= c->H || dst < 0 || dst >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  if (core_emit_fields(c, &c->hs[hid], t_emit, kind, dst, size, nbytes,
                       payload, seq, sport, dport, frag, nfrags) < 0)
    return NULL;
  Py_RETURN_NONE;
}

/* ---- the gossip model's hot half (models/gossip.py twin) --------------- */
static PyObject *msg_bytes(char kind, const char *txid, Py_ssize_t n) {
  PyObject *b = PyBytes_FromStringAndSize(NULL, n + 1);
  if (!b) return NULL;
  char *p = PyBytes_AS_STRING(b);
  p[0] = kind;
  memcpy(p + 1, txid, (size_t)n);
  return b;
}

static int gossip_announce(CoreObject *c, CHost *h, GossipState *g,
                           int64_t now, const char *txid, Py_ssize_t n,
                           int exclude) {
  PyObject *pl = msg_bytes('I', txid, n);
  if (!pl) return -1;
  int64_t nb = (n + 1) > 64 ? (n + 1) : 64;
  for (int i = 0; i < g->npeers; i++) {
    int p = g->peers[i];
    if (p == exclude) continue;
    if (core_emit_dgram(c, h, now, p, g, g->port, nb, pl) < 0) {
      Py_DECREF(pl);
      return -1;
    }
  }
  Py_DECREF(pl);
  return 0;
}

static int gossip_on_msg_c(CoreObject *c, CHost *h, GossipState *g,
                           int64_t now, PyObject *payload, int64_t src_host) {
  if (payload == Py_None) return 0;
  char *buf;
  Py_ssize_t len;
  if (PyBytes_AsStringAndSize(payload, &buf, &len) < 0) return -1;
  if (len < 1) return 0;
  char kind = buf[0];
  const char *txid = buf + 1;
  Py_ssize_t tn = len - 1;
  if (kind == 'I') {
    if (!seen_contains(&g->seen, txid, tn)) {
      PyObject *pl = msg_bytes('G', txid, tn);
      if (!pl) return -1;
      int64_t nb = (tn + 1) > 64 ? (tn + 1) : 64;
      int r = core_emit_dgram(c, h, now, (int)src_host, g, g->port, nb, pl);
      Py_DECREF(pl);
      return r;
    }
  } else if (kind == 'G') {
    PyObject *pl = msg_bytes('T', txid, tn);
    if (!pl) return -1;
    int64_t nb = (tn + 1) > TX_SIZE ? (tn + 1) : TX_SIZE;
    int r = core_emit_dgram(c, h, now, (int)src_host, g, g->port, nb, pl);
    Py_DECREF(pl);
    return r;
  } else if (kind == 'T') {
    int a = seen_add(&g->seen, txid, tn);
    if (a < 0) { PyErr_NoMemory(); return -1; }
    if (a == 1) {
      g->received_tx++;
      return gossip_announce(c, h, g, now, txid, tn, (int)src_host);
    }
  }
  return 0;
}

/* ---- row dispatch (Host.dispatch_row twin) ----------------------------
 * Returns 0 ok, -1 error. `*now` is the host's running clock; kept in C
 * and synced to host._now around any Python call-out. */
static int dispatch_stream(CoreObject *c, CHost *h, int hid, IRow *ir,
                           int64_t *now, int *now_dirty);

static int dispatch_c(CoreObject *c, CHost *h, int hid, IRow *ir,
                      int64_t *now, int *now_dirty) {
  int64_t t = ir->t;
  if (h->down) {
    /* crashed host (Host.dispatch_row twin): the arrival is consumed by
     * the dead NIC — clock advances, no token charge, no delivery */
    if (t > *now) { *now = t; *now_dirty = 1; }
    h->d_teardown++;
    return 0;
  }
  if (ir->kind <= TK_FINACK)
    return dispatch_stream(c, h, hid, ir, now, now_dirty);
  GossipState *g = NULL;
  if (ir->kind == KIND_DGRAM && ir->nfrags == 1) {
    for (int i = 0; i < h->nports; i++)
      if (h->port[i] == (int)ir->bport) { g = h->gs[i]; break; }
  }
  if (!g) {
    TM0(1);
    /* Python fallback: unregistered ports, frags. host.dispatch_row
     * does its own clock/bucket/deliver work (13-tuple materialized
     * here — the fallback is off the hot path by construction). */
    if (*now_dirty) {
      if (attr_set_i64(h->host, S_now, *now) < 0) return -1;
      *now_dirty = 0;
    }
    PyObject *row = irow_tuple(h, ir, hid);
    if (!row) return -1;
    PyObject *r = PyObject_CallMethodObjArgs(h->host, S_dispatch_row,
                                             row, NULL);
    Py_DECREF(row);
    if (!r) return -1;
    Py_DECREF(r);
    if (attr_i64(h->host, S_now, now) < 0) return -1;
    TM1(1);
    return 0;
  }
  if (t > *now) { *now = t; *now_dirty = 1; }
  if (t >= c->bootstrap_end) {
    if (c->tokens_down[hid] >= ir->size) {
      c->tokens_down[hid] -= ir->size;
    } else {
      /* park the whole row in the deferred backlog (Python structures,
       * drained by colplane._drain_deferred) */
      PyObject *dl = PyObject_GetAttr(h->host, S_ingress_deferred_rows);
      if (!dl) return -1;
      PyObject *row = irow_tuple(h, ir, hid);
      if (!row) { Py_DECREF(dl); return -1; }
      int r = PyList_Append(dl, row);
      Py_DECREF(row);
      Py_DECREF(dl);
      if (r < 0) return -1;
      if (PySet_Add(c->deferred, h->host) < 0) return -1;
      return 0;
    }
  }
  h->d_delivered++;
  h->d_dgrams_recv++;
  TM0(2);
  int rr = gossip_on_msg_c(c, h, g, *now,
                           ir->payload ? ir->payload : Py_None, ir->peer);
  TM1(2);
  return rr;
}

/* ---- Host.run_events twin over the C inbox ---------------------------- */
static int64_t run_host_inner(CoreObject *c, CHost *h, int hid, int64_t end);
static int64_t run_host_c(CoreObject *c, CHost *h, int hid, int64_t end) {
  TM0(4);
  int64_t r = run_host_inner(c, h, hid, end);
  TM1(4);
  return r;
}
static int64_t run_host_inner(CoreObject *c, CHost *h, int hid, int64_t end) {
  /* no entry clock read: inbox rows satisfy t >= host._now (rows are
   * extracted with t >= round_start and the clock never passes a round
   * boundary), heap tasks write the attr themselves, and the Python
   * dispatch fallback syncs before/after.  The attr is written back only
   * if a C dispatch advanced it (now_dirty). */
  int64_t now = INT64_MIN;
  int now_dirty = 0;
  int64_t n = 0;
  IRow *rows = h->inbox;
  int pos = 0, ln = h->inbox_n;
  int err = -1;
  /* fast path: no heap events at all */
  while (pos < ln && PyList_GET_SIZE(h->heap) == 0) {
    if (dispatch_c(c, h, hid, &rows[pos], &now, &now_dirty) < 0)
      goto done;
    pos++; n++;
  }
  if (PyList_GET_SIZE(h->heap)) {
    /* the inbox<->heap merge with a CACHED root: an owned ref to the
     * last-validated heap[0] plus its (t, band, key). While the root
     * object is unchanged, its triple is a lower bound on the live head
     * (a later cancel only moves the live head LATER), so a row that
     * beats the cached triple may dispatch without touching the
     * cancelled set; anything else re-validates through heap_head.
     * This turns the per-row cost of the hot merge from a set lookup +
     * four tuple reads into one pointer compare + int compares. */
    PyObject *h0own = NULL; /* owned: validated head at cache time */
    int64_t h0t = 0, h0band = 0, h0key = 0;
    int rcod2 = -1;
    for (;;) {
      if (h0own && pos < ln && PyList_GET_SIZE(h->heap) &&
          PyList_GET_ITEM(h->heap, 0) == h0own) {
        int64_t ti = rows[pos].t;
        if (ti < h0t ||
            (ti == h0t &&
             (0 < h0band || (0 == h0band && rows[pos].key < h0key)))) {
          if (dispatch_c(c, h, hid, &rows[pos], &now, &now_dirty) < 0)
            goto mdone;
          pos++; n++;
          continue;
        }
      }
      int herr;
      PyObject *h0 = heap_head(h, &herr);
      if (herr) goto mdone;
      int hv = 0;
      h0t = 0; h0band = 0; h0key = 0;
      if (h0) {
        Py_INCREF(h0);
        Py_XSETREF(h0own, h0);
        h0t = tup_i64(h0, 0);
        h0band = tup_i64(h0, 1);
        h0key = tup_i64(h0, 2);
        hv = h0t < end;
      } else {
        Py_CLEAR(h0own);
      }
      if (pos < ln) {
        int64_t ti = rows[pos].t;
        /* inbox rows are BAND_NET (0): they win same-time ties unless a
         * heap net event carries a smaller key */
        if (!hv || ti < h0t ||
            (ti == h0t &&
             (0 < h0band || (0 == h0band && rows[pos].key < h0key)))) {
          if (dispatch_c(c, h, hid, &rows[pos], &now, &now_dirty) < 0)
            goto mdone;
          pos++; n++;
          continue;
        }
      }
      if (hv) {
        PyObject *ev = heap_pop(h->heap);
        if (!ev) goto mdone;
        PyObject *seq = PyTuple_GET_ITEM(ev, 3);
        if (PySet_Discard(h->live, seq) < 0) { Py_DECREF(ev); goto mdone; }
        now = tup_i64(ev, 0);
        now_dirty = 0;
        if (attr_set_i64(h->host, S_now, now) < 0) { Py_DECREF(ev); goto mdone; }
        PyObject *res = PyObject_CallNoArgs(PyTuple_GET_ITEM(ev, 4));
        Py_DECREF(ev);
        if (!res) goto mdone;
        Py_DECREF(res);
        if (attr_i64(h->host, S_now, &now) < 0) goto mdone;
        n++;
        continue;
      }
      break;
    }
    rcod2 = 0;
  mdone:
    Py_XDECREF(h0own);
    if (rcod2 < 0) goto done;
  }
  err = 0;
done:;
  TM0(10);
  /* release the consumed prefix AND any unconsumed tail (error paths) */
  for (int i = 0; i < h->inbox_n; i++) Py_XDECREF(h->inbox[i].payload);
  h->inbox_n = 0;
  h->inbox_multi = 0;
  TM1(10);
  if (err) return -1;
  if (now_dirty && attr_set_i64(h->host, S_now, now) < 0) return -1;
  /* counter deltas stay C-side until Core.fold_counters (plane.flush_all):
   * the _n_* attrs are only read at finalize, and Python-path increments
   * commute with the fold */
  h->d_events += n;
  return n;
}

/* ---- Core.run_round: the controller's per-round host loop ------------- */
static int cmp_i64(const void *a, const void *b) {
  int64_t x = *(const int64_t *)a, y = *(const int64_t *)b;
  return (x > y) - (x < y);
}

/* record a newly-activated host id for the next refresh's merge */
static int act_pend_add(CoreObject *c, int64_t hid) {
  if (c->act_pend_n == c->act_pend_cap) {
    int64_t ncap = c->act_pend_cap ? c->act_pend_cap * 2 : 64;
    int64_t *nb = realloc(c->act_pend, sizeof(int64_t) * (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    c->act_pend = nb;
    c->act_pend_cap = ncap;
  }
  c->act_pend[c->act_pend_n++] = hid;
  return 0;
}

/* re-snapshot the active set when membership changed outside run_round.
 * When every addition was recorded in act_pend (extract + the activate
 * hook), the refresh is a merge of the small sorted pend batch into the
 * sorted snapshot; a residual size mismatch (additions that bypassed
 * the hook) falls back to the full iterate + qsort. */
static int act_refresh(CoreObject *c) {
  Py_ssize_t na = PySet_GET_SIZE(c->active);
  if ((int64_t)na == c->act_n) {
    c->act_pend_n = 0; /* pend entries were already merged or stale */
    return 0;
  }
  if (c->act_n >= 0 && (int64_t)na == c->act_n + c->act_pend_n) {
    int64_t pn = c->act_pend_n, an = c->act_n;
    if (an + pn > c->act_cap) {
      int64_t ncap = c->act_cap ? c->act_cap : 256;
      while (ncap < an + pn) ncap *= 2;
      int64_t *nb = realloc(c->act_ids, sizeof(int64_t) * (size_t)ncap);
      if (!nb) { PyErr_NoMemory(); return -1; }
      c->act_ids = nb;
      c->act_cap = ncap;
    }
    qsort(c->act_pend, (size_t)pn, sizeof(int64_t), cmp_i64);
    /* backward two-way merge into act_ids */
    int64_t i = an - 1, j = pn - 1, w = an + pn - 1;
    while (j >= 0) {
      if (i >= 0 && c->act_ids[i] > c->act_pend[j])
        c->act_ids[w--] = c->act_ids[i--];
      else
        c->act_ids[w--] = c->act_pend[j--];
    }
    c->act_n = an + pn;
    c->act_pend_n = 0;
    return 0;
  }
  c->act_pend_n = 0;
  if (na > c->act_cap) {
    int64_t ncap = c->act_cap ? c->act_cap : 256;
    while (ncap < na) ncap *= 2;
    int64_t *nb = realloc(c->act_ids, sizeof(int64_t) * (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    c->act_ids = nb;
    c->act_cap = ncap;
  }
  Py_ssize_t k2 = 0;
  PyObject *it = PyObject_GetIter(c->active);
  if (!it) return -1;
  PyObject *item;
  while ((item = PyIter_Next(it))) {
    if (k2 < na) c->act_ids[k2++] = PyLong_AsLongLong(item);
    Py_DECREF(item);
  }
  Py_DECREF(it);
  if (PyErr_Occurred()) return -1;
  qsort(c->act_ids, (size_t)k2, sizeof(int64_t), cmp_i64);
  c->act_n = k2;
  return 0;
}

/* min pending event time over the active hosts — the skip-ahead path's
 * `min(equeue.next_time() for active)` without a Python genexpr. Drops
 * cancelled heads exactly like EventQueue.next_time, so the returned
 * instant (and hence the round grid) is identical to the Python path. */
static PyObject *Core_next_time(CoreObject *c, PyObject *noarg) {
  (void)noarg;
  if (!c->active) {
    PyErr_SetString(PyExc_RuntimeError, "bind_active() not called");
    return NULL;
  }
  if (act_refresh(c) < 0) return NULL;
  int64_t best = T_NEVER_C;
  for (int64_t i = 0; i < c->act_n; i++) {
    int64_t hid = c->act_ids[i];
    if (hid < 0 || hid >= c->H) continue;
    int err;
    PyObject *head = heap_head(&c->hs[hid], &err);
    if (err) return NULL;
    if (head) {
      int64_t t = tup_i64(head, 0);
      if (t < best) best = t;
    }
  }
  return PyLong_FromLongLong(best);
}

/* ---- store construction (colplane._store_resolved twin) ---------------- */
typedef struct {
  int64_t t, key;
  int32_t idx;   /* index into the BRow array */
} ORow;

static int cmp_orow(const void *a, const void *b) {
  const ORow *x = a, *y = b;
  if (x->t != y->t) return (x->t > y->t) - (x->t < y->t);
  return (x->key > y->key) - (x->key < y->key);
}

/* build the sorted CBatch from resolved BRows (drop flags set);
 * have_flags=0 means every row survives.  Updates plane counters.
 * BRow payload refs are NOT consumed (the batch takes its own). */
static int store_build(CoreObject *c, BRow *rows, int n, int have_flags,
                       int64_t round_end) {
  int64_t sent = 0, dropped = 0, nbytes_total = 0;
  ORow *out = malloc(sizeof(ORow) * (size_t)(n ? n : 1));
  if (!out) { PyErr_NoMemory(); return -1; }
  int m = 0;
  int sh_n = c->shard_n;
  for (int i = 0; i < n; i++) {
    BRow *b = &rows[i];
    if (have_flags && b->drop) {
      dropped++;
    } else {
      sent++;
      nbytes_total += b->size;
      int64_t t = b->arrival;
      if (t < round_end) t = round_end;
      if (sh_n > 1 && b->dst % sh_n != c->shard_id) {
        /* cross-shard destination: divert the fully resolved store row
         * into the per-shard egress buffer the plane ships at the round
         * edge (parallel/shards.py) — packed SRec when the plane bound
         * the packed send path, 13-field tuple otherwise */
        SRec s;
        s.t = t; s.key = b->key; s.tgt = b->dst; s.size = (int32_t)b->size;
        s.peer = b->src; s.bport = b->dport; s.aport = b->sport;
        s.nbytes = b->nbytes; s.seq = b->seq; s.kind = (int16_t)b->kind;
        s.frag = b->frag; s.nfrags = b->nfrags;
        int j = b->dst % sh_n;
        if (c->xpacked) {
          if (c->xn[j] == c->xcap[j]) {
            int nc = c->xcap[j] ? c->xcap[j] * 2 : 256;
            SRec *nr = realloc(c->xrecs[j], sizeof(SRec) * (size_t)nc);
            if (!nr) { free(out); PyErr_NoMemory(); return -1; }
            c->xrecs[j] = nr;
            PyObject **npp =
                realloc(c->xpay[j], sizeof(PyObject *) * (size_t)nc);
            if (!npp) { free(out); PyErr_NoMemory(); return -1; }
            c->xpay[j] = npp;
            c->xcap[j] = nc;
          }
          c->xrecs[j][c->xn[j]] = s;
          Py_XINCREF(b->payload);
          c->xpay[j][c->xn[j]] = b->payload;
          c->xn[j]++;
          continue;
        }
        PyObject *row_t = srec_tuple(&s, b->payload);
        if (!row_t) { free(out); return -1; }
        PyObject *lst = PyList_GET_ITEM(c->xout, j);
        int rc3 = PyList_Append(lst, row_t);
        Py_DECREF(row_t);
        if (rc3 < 0) { free(out); return -1; }
        continue;
      }
      out[m].t = t; out[m].key = b->key; out[m].idx = i;
      m++;
    }
  }
  int rc = -1;
  PyObject *sb = NULL, *ap = NULL;
  if (m) {
    qsort(out, (size_t)m, sizeof(ORow), cmp_orow);
    CBatch *cb = cbatch_new(m);
    if (!cb) goto done;
    sb = (PyObject *)cb;
    for (int i = 0; i < m; i++) {
      BRow *b = &rows[out[i].idx];
      SRec *rc2 = &cb->recs[i];
      rc2->t = out[i].t;
      rc2->key = out[i].key;
      rc2->tgt = b->dst;
      rc2->size = (int32_t)b->size;
      rc2->peer = b->src;
      rc2->bport = b->dport;
      rc2->aport = b->sport;
      rc2->nbytes = b->nbytes;
      rc2->seq = b->seq;
      rc2->kind = (int16_t)b->kind;
      rc2->frag = b->frag;
      rc2->nfrags = b->nfrags;
      Py_XINCREF(b->payload);
      cb->pay[i] = b->payload;
    }
    ap = PyObject_CallMethodObjArgs(c->pending, S_append, sb, NULL);
    if (!ap) goto done;
  }
  if (attr_add_i64(c->plane, S_units_sent, sent) < 0 ||
      attr_add_i64(c->plane, S_units_dropped, dropped) < 0 ||
      attr_add_i64(c->plane, S_bytes_sent, nbytes_total) < 0)
    goto done;
  rc = 0;
done:
  Py_XDECREF(ap);
  Py_XDECREF(sb);
  free(out);
  return rc;
}

/* Python-callable twin of colplane._store_resolved: used by the device
 * flush path (flags arrive from a DrawHandle readback). */
static PyObject *Core_store_resolved(CoreObject *c, PyObject *args) {
  PyObject *rows, *src_l, *arrival_l, *keys_l, *flags;
  long long round_end;
  if (!PyArg_ParseTuple(args, "OOOOOL", &rows, &src_l, &arrival_l, &keys_l,
                        &flags, &round_end))
    return NULL;
  if (!PyList_Check(rows) || !PyList_Check(src_l) || !PyList_Check(arrival_l)
      || !PyList_Check(keys_l)) {
    PyErr_SetString(PyExc_TypeError, "store_resolved expects lists");
    return NULL;
  }
  int n = (int)PyList_GET_SIZE(rows);
  int have_flags = flags != Py_None;
  BRow *br = malloc(sizeof(BRow) * (size_t)(n ? n : 1));
  if (!br) return PyErr_NoMemory();
  for (int i = 0; i < n; i++) {
    PyObject *er = PyList_GET_ITEM(rows, i);
    BRow *b = &br[i];
    /* egress-format tuple -> packed fields (payload ref stays borrowed
     * from the tuple; store_build takes its own) */
    b->kind = (int32_t)tup_i64(er, 0);
    b->src = (int32_t)PyLong_AsLongLong(PyList_GET_ITEM(src_l, i));
    b->dst = (int32_t)tup_i64(er, 1);
    b->size = tup_i64(er, 2);
    b->t_emit = tup_i64(er, 3);
    b->sport = (int32_t)tup_i64(er, 4);
    b->dport = (int32_t)tup_i64(er, 5);
    b->nbytes = tup_i64(er, 6);
    b->seq = tup_i64(er, 7);
    b->frag = (int32_t)tup_i64(er, 8);
    b->nfrags = (int32_t)tup_i64(er, 9);
    PyObject *pl = PyTuple_GET_ITEM(er, 10);
    b->payload = pl == Py_None ? NULL : pl;
    b->arrival = PyLong_AsLongLong(PyList_GET_ITEM(arrival_l, i));
    b->key = PyLong_AsLongLong(PyList_GET_ITEM(keys_l, i));
    if (b->src < 0 || b->src >= c->H) {
      free(br);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_ValueError, "src host id out of range");
      return NULL;
    }
    b->src_obj = c->hs[b->src].id_obj;
    b->drop = 0;
    if (have_flags) {
      int d = PyObject_IsTrue(PyList_GET_ITEM(flags, i));
      if (d < 0) { free(br); return NULL; }
      b->drop = (uint8_t)d;
    }
  }
  if (PyErr_Occurred()) { free(br); return NULL; }
  int rc = store_build(c, br, n, have_flags, round_end);
  free(br);
  if (rc < 0) return NULL;
  Py_RETURN_NONE;
}

/* ---- the round barrier (colplane end_of_round twin, fifo qdisc) -------- */
typedef struct { int64_t hid; PyObject *host; } Emitter;

static int cmp_emitter(const void *a, const void *b) {
  int64_t x = ((const Emitter *)a)->hid, y = ((const Emitter *)b)->hid;
  return (x > y) - (x < y);
}

/* closed-form token buckets (fluid.TokenBuckets twin, lazy rebase like
 * depart_times_scalar — outcome-identical to the full-rebase vector path,
 * see fluid.py docstrings).  brow[] must be sorted by src (it is: the
 * emitters are sorted and each contributes one contiguous segment). */
static void depart_closed_form(CoreObject *c, BRow *br, int n,
                               int64_t t_now) {
  int i = 0;
  while (i < n) {
    int32_t s = br[i].src;
    int64_t rate = c->rate_up[s], cap = c->cap_up[s];
    /* lazy rebase at the barrier instant */
    int64_t dt = t_now - c->tbase[s];
    int64_t q = dt / NS_PER_SEC, r = dt % NS_PER_SEC;
    int64_t avail = c->tokens[s] + rate * q +
                    (int64_t)((uint64_t)rate * (uint64_t)r /
                              (uint64_t)NS_PER_SEC) -
                    c->debt[s];
    if (avail > cap) {
      c->tbase[s] = t_now;
      c->tokens[s] = cap;
      c->debt[s] = 0;
    }
    int64_t tb = c->tbase[s], tok = c->tokens[s], debt = c->debt[s];
    int64_t cum = 0;
    int j = i;
    for (; j < n && br[j].src == s; j++) {
      cum += br[j].size;
      int64_t need = debt + cum - tok;
      int64_t tready = 0;
      if (need > 0) {
        int64_t q2 = need / rate, r2 = need % rate;
        tready = tb + q2 * NS_PER_SEC +
                 (int64_t)(((uint64_t)r2 * (uint64_t)NS_PER_SEC +
                            (uint64_t)rate - 1) /
                           (uint64_t)rate);
      }
      br[j].depart = br[j].t_emit > tready ? br[j].t_emit : tready;
    }
    c->debt[s] = debt + cum;
    i = j;
  }
}

/* ---- speculative forward windows -------------------------------------- */
static void spec_enqueue(CoreObject *c, int32_t hid) {
  SpecHost *s = &c->spec[hid];
  if (s->inflight) return;
  if (c->spec_dq_n == c->spec_dq_cap) {
    int ncap = c->spec_dq_cap ? c->spec_dq_cap * 2 : 256;
    int32_t *nq = realloc(c->spec_dq, sizeof(int32_t) * (size_t)ncap);
    if (!nq) return; /* no memory: simply don't speculate */
    c->spec_dq = nq;
    c->spec_dq_cap = ncap;
  }
  s->inflight = 1;
  c->spec_dq[c->spec_dq_n++] = hid;
}

/* One live unit at the inline-draw point: track its npkts class, consult
 * the host's speculative window (dropped == min_draw < thresh), and file
 * demand when the host has earned a (larger) window. Returns the drop
 * flag (0/1) on a verified hit, -1 on a miss (caller draws inline). */
static inline int spec_consult(CoreObject *c, BRow *b) {
  SpecHost *s = &c->spec[b->src];
  if (s->tnpk_a == b->npk) {
    s->run++;
  } else if (s->tnpk_b == b->npk) {
    /* keep A = most recent: swap so two alternating classes (full data
     * units one way, single-packet acks the other) both stay tracked */
    int32_t tn = s->tnpk_a;
    s->tnpk_a = s->tnpk_b;
    s->tnpk_b = tn;
    s->run++;
  } else {
    s->tnpk_b = s->tnpk_a;
    s->tnpk_a = b->npk;
    s->run >>= 1; /* momentum survives an occasional odd-sized unit */
  }
  if (s->ready && b->uid >= s->u0 + (uint64_t)s->n && s->nready) {
    /* live window exhausted with a staged continuation: promote it */
    free(s->min_a);
    free(s->min_b);
    s->u0 = s->nu0;
    s->n = s->nn;
    s->min_a = s->nmin_a;
    s->min_b = s->nmin_b;
    /* the tracker classes may have drifted since the stage was demanded;
     * consult below matches against the STAGED classes */
    s->npk_a = s->nnpk_a;
    s->npk_b = s->nnpk_b;
    s->nmin_a = s->nmin_b = NULL;
    s->nready = 0;
    if (s->want < SPEC_WANT_MAX) s->want *= 2;
  }
  if (s->ready) {
    uint64_t off = b->uid - s->u0;
    if (off < (uint64_t)s->n) {
      uint32_t *mins = (b->npk == s->npk_a) ? s->min_a
                       : (b->npk == s->npk_b) ? s->min_b
                                              : NULL;
      if (!s->nready && !s->inflight && s->run >= SPEC_MIN_RUN
          && off >= (uint64_t)(s->n - (s->n >> 2)))
        spec_enqueue(c, b->src); /* 3/4 consumed: prefetch continuation */
      if (mins) return mins[off] < b->th;
    } else if (b->uid >= s->u0 + (uint64_t)s->n) {
      /* window exhausted with nothing staged: it produced hits, so
       * double the next one */
      free(s->min_a);
      free(s->min_b);
      s->min_a = s->min_b = NULL;
      s->ready = 0;
      if (s->want < SPEC_WANT_MAX) s->want *= 2;
      if (s->run >= SPEC_MIN_RUN) spec_enqueue(c, b->src);
    }
  } else if (!s->inflight && s->run >= SPEC_MIN_RUN
             && (s->tnpk_a >= SPEC_MIN_NPK
                 || s->tnpk_b >= SPEC_MIN_NPK)) {
    if (!s->want) s->want = SPEC_WANT0;
    spec_enqueue(c, b->src);
  }
  return -1;
}

static PyObject *Core_spec_demand(CoreObject *c, PyObject *args) {
  int min_hosts = 1;
  if (!PyArg_ParseTuple(args, "|i", &min_hosts)) return NULL;
  if (!c->spec) {
    /* first call from the plane turns speculation on (the plane only
     * calls once a device has published) */
    c->spec = calloc((size_t)c->H, sizeof(SpecHost));
    if (!c->spec) return PyErr_NoMemory();
    c->spec_on = 1;
    Py_RETURN_NONE;
  }
  /* demand coalescing: waves amortize a fixed dispatch cost, so hold the
   * queue until a worthwhile cohort forms (the plane forces min_hosts=1
   * on a coarse age cadence so stragglers still get windows) */
  if (c->spec_dq_n < min_hosts) Py_RETURN_NONE;
  int n = c->spec_dq_n;
  npy_intp dims[1] = {n};
  PyObject *hosts = PyArray_SimpleNew(1, dims, NPY_INT32);
  PyObject *u0 = PyArray_SimpleNew(1, dims, NPY_UINT64);
  PyObject *cnt = PyArray_SimpleNew(1, dims, NPY_INT32);
  PyObject *npka = PyArray_SimpleNew(1, dims, NPY_INT32);
  PyObject *npkb = PyArray_SimpleNew(1, dims, NPY_INT32);
  if (!hosts || !u0 || !cnt || !npka || !npkb) {
    Py_XDECREF(hosts); Py_XDECREF(u0); Py_XDECREF(cnt); Py_XDECREF(npka);
    Py_XDECREF(npkb);
    return NULL;
  }
  int32_t *ph = PyArray_DATA((PyArrayObject *)hosts);
  uint64_t *pu = PyArray_DATA((PyArrayObject *)u0);
  int32_t *pn = PyArray_DATA((PyArrayObject *)cnt);
  int32_t *pna = PyArray_DATA((PyArrayObject *)npka);
  int32_t *pnb = PyArray_DATA((PyArrayObject *)npkb);
  int out_n = 0;
  for (int i = 0; i < n; i++) {
    int32_t hid = c->spec_dq[i];
    SpecHost *s = &c->spec[hid];
    if (s->tnpk_a < SPEC_MIN_NPK && s->tnpk_b < SPEC_MIN_NPK) {
      /* classes drifted cheap since enqueue: a wave row would be
       * filtered plane-side and the host's inflight flag would stick —
       * release it here instead so it can re-demand later */
      s->inflight = 0;
      continue;
    }
    if (s->ready) {
      /* prefetch: the staged window continues the live one seamlessly */
      pu[out_n] = s->u0 + (uint64_t)s->n;
    } else {
      int64_t ctr;
      if (attr_i64(c->hs[hid].host, S_uid_counter, &ctr) < 0) {
        Py_DECREF(hosts); Py_DECREF(u0); Py_DECREF(cnt); Py_DECREF(npka);
        Py_DECREF(npkb);
        return NULL;
      }
      /* the window starts at the host's NEXT uid: only future units */
      pu[out_n] = ((uint64_t)hid << 32) | (uint64_t)ctr;
    }
    ph[out_n] = hid;
    pn[out_n] = s->want;
    pna[out_n] = s->tnpk_a;
    pnb[out_n] = s->tnpk_b;
    out_n++;
  }
  c->spec_dq_n = 0;
  if (out_n == 0) {
    Py_DECREF(hosts); Py_DECREF(u0); Py_DECREF(cnt); Py_DECREF(npka);
    Py_DECREF(npkb);
    Py_RETURN_NONE;
  }
  if (out_n < n) {
    /* shrink to the kept cohort (cheap-class hosts were released) */
    PyArray_Dims nd = {.ptr = (npy_intp[]){out_n}, .len = 1};
    PyObject *tmp;
#define SHRINK(arr) \
    tmp = PyArray_Resize((PyArrayObject *)(arr), &nd, 0, NPY_CORDER); \
    if (!tmp) { \
      Py_DECREF(hosts); Py_DECREF(u0); Py_DECREF(cnt); Py_DECREF(npka); \
      Py_DECREF(npkb); \
      return NULL; \
    } \
    Py_DECREF(tmp);
    SHRINK(hosts) SHRINK(u0) SHRINK(cnt) SHRINK(npka) SHRINK(npkb)
#undef SHRINK
  }
  return Py_BuildValue("(NNNNN)", hosts, u0, cnt, npka, npkb);
}

static PyObject *Core_spec_install(CoreObject *c, PyObject *args) {
  PyObject *hosts, *u0, *cnt, *npka, *npkb, *offa, *offb, *mins;
  if (!PyArg_ParseTuple(args, "OOOOOOOO", &hosts, &u0, &cnt, &npka, &npkb,
                        &offa, &offb, &mins))
    return NULL;
  if (!c->spec) Py_RETURN_NONE;
#define DATA(o) PyArray_DATA((PyArrayObject *)(o))
  int n = (int)PyArray_SIZE((PyArrayObject *)hosts);
  int32_t *ph = DATA(hosts);
  uint64_t *pu = DATA(u0);
  int32_t *pn = DATA(cnt);
  int32_t *pna = DATA(npka);
  int32_t *pnb = DATA(npkb);
  int64_t *poa = DATA(offa);
  int64_t *pob = DATA(offb);
  uint32_t *pm = DATA(mins);
  int64_t mlen = (int64_t)PyArray_SIZE((PyArrayObject *)mins);
#undef DATA
  for (int i = 0; i < n; i++) {
    int32_t hid = ph[i];
    if (hid < 0 || hid >= c->H) continue;
    SpecHost *s = &c->spec[hid];
    s->inflight = 0;
    /* the class tracker may have moved on while the wave was in flight;
     * install anyway — consult verifies uid range + npkts per unit, so a
     * stale class simply never hits */
    int64_t ni = pn[i];
    if (ni <= 0) continue;
    uint32_t *ma = NULL, *mb = NULL;
    size_t nbytes = sizeof(uint32_t) * (size_t)ni;
    if (poa[i] >= 0 && poa[i] + ni <= mlen) {
      ma = malloc(nbytes);
      if (ma) memcpy(ma, pm + poa[i], nbytes);
    }
    if (pob[i] >= 0 && pob[i] + ni <= mlen) {
      mb = malloc(nbytes);
      if (mb) memcpy(mb, pm + pob[i], nbytes);
    }
    if (!ma && !mb) continue;
    if (s->ready && pu[i] == s->u0 + (uint64_t)s->n) {
      /* continuation of a still-live window: stage it */
      free(s->nmin_a);
      free(s->nmin_b);
      s->nu0 = pu[i];
      s->nn = (int32_t)ni;
      s->nnpk_a = pna[i];
      s->nnpk_b = pnb[i];
      s->nmin_a = ma;
      s->nmin_b = mb;
      s->nready = 1;
    } else {
      free(s->min_a);
      free(s->min_b);
      free(s->nmin_a);
      free(s->nmin_b);
      s->nmin_a = s->nmin_b = NULL;
      s->nready = 0;
      s->u0 = pu[i];
      s->n = (int32_t)ni;
      s->npk_a = pna[i];
      s->npk_b = pnb[i];
      s->min_a = ma;
      s->min_b = mb;
      s->ready = 1;
    }
  }
  Py_RETURN_NONE;
}

static PyObject *Core_spec_stats(CoreObject *c, PyObject *noarg) {
  (void)noarg;
  PyObject *r = Py_BuildValue("(LL)", (long long)c->spec_hits,
                              (long long)c->spec_draws);
  c->spec_hits = 0;
  c->spec_draws = 0;
  return r;
}

static PyObject *Core_barrier(CoreObject *c, PyObject *args) {
  long long rs_ll, re_ll;
  if (!PyArg_ParseTuple(args, "LL", &rs_ll, &re_ll)) return NULL;
  int64_t round_start = rs_ll, round_end = re_ll;
  PyObject *emitters = PyObject_GetAttr(c->plane, S_emitters);
  if (!emitters) return NULL;
  Py_ssize_t nem = PyList_Check(emitters) ? PyList_GET_SIZE(emitters) : -1;
  if (nem < 0) {
    Py_DECREF(emitters);
    PyErr_SetString(PyExc_TypeError, "plane.emitters is not a list");
    return NULL;
  }
  if (nem == 0) {
    Py_DECREF(emitters);
    Py_RETURN_NONE;
  }
  PyObject *fresh = PyList_New(0);
  if (!fresh) { Py_DECREF(emitters); return NULL; }
  int rc_set = PyObject_SetAttr(c->plane, S_emitters, fresh);
  Py_DECREF(fresh);
  if (rc_set < 0) { Py_DECREF(emitters); return NULL; }

  PyObject *result = NULL; /* NULL = error until set */
  int n = 0;    /* rows collected */
  int nown = 0; /* rows currently OWNED in brow[0..nown) (refcounts) */
  Emitter *ems = malloc(sizeof(Emitter) * (size_t)nem);
  if (!ems) { PyErr_NoMemory(); goto done; }
  for (Py_ssize_t i = 0; i < nem; i++) {
    ems[i].host = PyList_GET_ITEM(emitters, i);
    if (attr_i64(ems[i].host, S_id, &ems[i].hid) < 0) goto done;
  }
  if (nem > 1) qsort(ems, (size_t)nem, sizeof(Emitter), cmp_emitter);

  /* collect rows + mint uids in per-host emission order (the packed C
   * egress buffers; ownership of each payload ref moves to the BRow) */
  for (Py_ssize_t e = 0; e < nem; e++) {
    int64_t hid = ems[e].hid;
    CHost *hstate = &c->hs[hid];
    if (PyList_GET_SIZE(hstate->egress) != 0) {
      /* every emission on the C plane routes through core_emit_fields /
       * emit_row; a tuple here means a writer bypassed the packed path */
      PyErr_SetString(PyExc_RuntimeError,
                      "host.egress_rows is non-empty under the C engine "
                      "(packed-emission protocol violation)");
      goto done;
    }
    Py_ssize_t k = hstate->erow_n;
    if (n + k > c->brow_cap) {
      int ncap = c->brow_cap ? c->brow_cap : 4096;
      while (ncap < n + k) ncap *= 2;
      BRow *nb = realloc(c->brow, sizeof(BRow) * (size_t)ncap);
      if (!nb) { PyErr_NoMemory(); goto done; }
      c->brow = nb;
      c->brow_cap = ncap;
    }
    int64_t ctr;
    if (attr_i64(ems[e].host, S_uid_counter, &ctr) < 0) goto done;
    if (attr_set_i64(ems[e].host, S_uid_counter, ctr + k) < 0) goto done;
    uint64_t base = ((uint64_t)hid << 32) | (uint64_t)ctr;
    for (Py_ssize_t i = 0; i < k; i++) {
      ERow *er = &hstate->erow[i];
      BRow *b = &c->brow[n++];
      b->payload = er->payload; /* ownership moves */
      er->payload = NULL;
      b->src_obj = hstate->id_obj;
      b->src = (int32_t)hid;
      b->dst = er->dst;
      b->size = er->size;
      b->t_emit = er->t_emit;
      b->nbytes = er->nbytes;
      b->seq = er->seq;
      b->kind = er->kind;
      b->sport = er->sport;
      b->dport = er->dport;
      b->frag = er->frag;
      b->nfrags = er->nfrags;
      b->uid = base + (uint64_t)i;
      b->drop = 0;
    }
    hstate->erow_n = 0;
    nown = n;
  }
  if (n == 0) { result = Py_None; Py_INCREF(Py_None); goto done; }

  /* departures on the FULL batch (buckets charge for blackholed units
   * too, matching the host planes) */
  if (round_start < c->bootstrap_end) {
    for (int i = 0; i < n; i++) c->brow[i].depart = c->brow[i].t_emit;
  } else {
    depart_closed_form(c, c->brow, n, round_start);
  }

  /* blackhole filter + latency/threshold gather + keys */
  int64_t key0;
  if (attr_i64(c->plane, S_ev_key, &key0) < 0) goto done;
  int64_t mul;
  if (attr_i64(c->plane, S_min_used_latency, &mul) < 0) goto done;
  int keep = 0;
  int64_t bh = 0;
  int any_live = 0;
  for (int i = 0; i < n; i++) {
    BRow *b = &c->brow[i];
    int32_t sn = c->hostnode[b->src], dn = c->hostnode[b->dst];
    int64_t lat = c->lat[(int64_t)sn * c->G + dn];
    if (lat >= INF_I64) {
      bh++;
      if (c->faults_active) c->hs[b->src].d_blackholed++;
      Py_XDECREF(b->payload); /* blackholed: drop our ref (see `nown`) */
      continue;
    }
    if (lat < mul) mul = lat;
    b->arrival = b->depart + lat;
    /* canonical event key = the uid (placement-independent; the Python
     * planes' twin — engine.py/colplane.py). _ev_key stays a resolved-
     * units counter (hashed by the determinism sentinel). */
    b->key = (int64_t)b->uid;
    b->th = c->thresh[(int64_t)sn * c->G + dn];
    if (b->th) any_live = 1;
    int64_t q = (b->size + MTU - 1) / MTU;
    b->npk = (int32_t)(q < 1 ? 1 : (q > HARD_MAX_PKTS ? HARD_MAX_PKTS : q));
    if (keep != i) c->brow[keep] = *b;
    keep++;
  }
  /* after compaction exactly brow[0..keep) carry owned refs; the stale
   * tail copies must never be released (review r4 finding #1) */
  nown = keep;
  if (attr_set_i64(c->plane, S_ev_key, key0 + keep) < 0) goto done;
  if (attr_add_i64(c->plane, S_units_blackholed, bh) < 0) goto done;
  if (attr_set_i64(c->plane, S_min_used_latency, mul) < 0) goto done;
  if (keep == 0) { result = Py_None; Py_INCREF(Py_None); goto done; }
  /* from here on a non-device barrier returns True ("stored kept rows"),
   * so the Python wrapper ticks the device-floor cooldown only on rounds
   * that actually bypassed the device — matching the vector twin, which
   * never ticks on empty rounds */

  /* hand-off paths: the Python machinery takes over with arrays we
   * build — mesh mode hands EVERY post-bootstrap batch to the lazy
   * collective (plus src/dst arrays); device mode hands big live
   * batches to the draw plane */
  /* dead batches (no loss anywhere) store inline even in mesh mode —
   * the collective would only confirm all-false flags */
  int mesh_off = c->mesh_mode && round_start >= c->bootstrap_end && any_live;
  if (any_live) {
    PyObject *device = PyObject_GetAttr(c->plane, S_device);
    if (!device) goto done;
    int have_dev = device != Py_None;
    Py_DECREF(device);
    if (have_dev || mesh_off) {
      double floor_d = 0.0;
      if (!mesh_off) {
        PyObject *fl = PyObject_GetAttr(c->plane, S_device_floor);
        if (!fl) goto done;
        floor_d = PyFloat_AsDouble(fl);
        Py_DECREF(fl);
        if (floor_d == -1.0 && PyErr_Occurred()) goto done;
      }
      if (mesh_off || (double)keep >= floor_d) {
        npy_intp dims[1] = {keep};
        PyObject *rows_l = PyList_New(keep);
        PyObject *src_l = PyList_New(keep);
        PyObject *keys_l = PyList_New(keep);
        PyObject *arr_t = PyArray_SimpleNew(1, dims, NPY_INT64);
        PyObject *arr_lo = PyArray_SimpleNew(1, dims, NPY_UINT32);
        PyObject *arr_hi = PyArray_SimpleNew(1, dims, NPY_UINT32);
        PyObject *arr_npk = PyArray_SimpleNew(1, dims, NPY_UINT32);
        PyObject *arr_th = PyArray_SimpleNew(1, dims, NPY_UINT32);
        PyObject *arr_src = NULL, *arr_dst = NULL;
        if (mesh_off) {
          arr_src = PyArray_SimpleNew(1, dims, NPY_INT32);
          arr_dst = PyArray_SimpleNew(1, dims, NPY_INT32);
        }
        if (!rows_l || !src_l || !keys_l || !arr_t || !arr_lo || !arr_hi ||
            !arr_npk || !arr_th || (mesh_off && (!arr_src || !arr_dst))) {
          Py_XDECREF(rows_l); Py_XDECREF(src_l); Py_XDECREF(keys_l);
          Py_XDECREF(arr_t); Py_XDECREF(arr_lo); Py_XDECREF(arr_hi);
          Py_XDECREF(arr_npk); Py_XDECREF(arr_th);
          Py_XDECREF(arr_src); Py_XDECREF(arr_dst);
          goto done;
        }
        int64_t *pt = PyArray_DATA((PyArrayObject *)arr_t);
        uint32_t *plo = PyArray_DATA((PyArrayObject *)arr_lo);
        uint32_t *phi = PyArray_DATA((PyArrayObject *)arr_hi);
        uint32_t *pnp = PyArray_DATA((PyArrayObject *)arr_npk);
        uint32_t *pth = PyArray_DATA((PyArrayObject *)arr_th);
        int32_t *psrc = mesh_off
            ? PyArray_DATA((PyArrayObject *)arr_src) : NULL;
        int32_t *pdst = mesh_off
            ? PyArray_DATA((PyArrayObject *)arr_dst) : NULL;
        int fail = 0;
        for (int i = 0; i < keep && !fail; i++) {
          BRow *b = &c->brow[i];
          /* egress-format tuple for the Python device/mesh machinery
           * (amortized by the batch's >= device_floor size) */
          PyObject *row_t = Py_BuildValue(
              "(iiLLiiLLiiO)", (int)b->kind, (int)b->dst,
              (long long)b->size, (long long)b->t_emit, (int)b->sport,
              (int)b->dport, (long long)b->nbytes, (long long)b->seq,
              (int)b->frag, (int)b->nfrags,
              b->payload ? b->payload : Py_None);
          if (!row_t) { fail = 1; break; }
          PyList_SET_ITEM(rows_l, i, row_t);
          Py_INCREF(b->src_obj);
          PyList_SET_ITEM(src_l, i, b->src_obj);
          PyObject *kv = PyLong_FromLongLong(b->key);
          if (!kv) { fail = 1; break; }
          PyList_SET_ITEM(keys_l, i, kv);
          pt[i] = b->arrival;
          plo[i] = (uint32_t)(b->uid & 0xFFFFFFFFu);
          phi[i] = (uint32_t)(b->uid >> 32);
          pnp[i] = (uint32_t)b->npk;
          pth[i] = b->th;
          if (mesh_off) {
            psrc[i] = b->src;
            pdst[i] = b->dst;
          }
        }
        if (fail) {
          Py_DECREF(rows_l); Py_DECREF(src_l); Py_DECREF(keys_l);
          Py_DECREF(arr_t); Py_DECREF(arr_lo); Py_DECREF(arr_hi);
          Py_DECREF(arr_npk); Py_DECREF(arr_th);
          Py_XDECREF(arr_src); Py_XDECREF(arr_dst);
          goto done;
        }
        if (mesh_off)
          result = Py_BuildValue("(NNNNNNNNNN)", rows_l, src_l, arr_t,
                                 keys_l, arr_lo, arr_hi, arr_npk, arr_th,
                                 arr_src, arr_dst);
        else
          result = Py_BuildValue("(NNNNNNNN)", rows_l, src_l, arr_t,
                                 keys_l, arr_lo, arr_hi, arr_npk, arr_th);
        if (!result) goto done;
        goto done; /* row refs now held by rows_l */
      }
    }
  }

  /* inline loss draws (threefry) + store; with speculation on, a live
   * unit first consults its host's speculative window (verified (npk,
   * th) class + uid range — bit-identical by construction) and only
   * draws inline on a miss */
  if (any_live) {
    for (int i = 0; i < keep; i++) {
      BRow *b = &c->brow[i];
      if (!b->th) {
        b->drop = 0;
        continue;
      }
      int sv = c->spec_on ? spec_consult(c, b) : -1;
      if (sv >= 0) {
        b->drop = (uint8_t)sv;
        c->spec_hits++;
      } else {
        b->drop = (uint8_t)unit_dropped(c->seed, b->uid, b->npk, b->th);
        c->spec_draws += c->spec_on;
      }
    }
  }
  if (store_build(c, c->brow, keep, any_live, round_end) < 0) goto done;
  result = Py_True;
  Py_INCREF(Py_True);

done:
  for (int i = 0; i < nown; i++) Py_XDECREF(c->brow[i].payload);
  free(ems);
  Py_DECREF(emitters);
  return result;
}

/* ---- extraction (colplane._extract twin) ------------------------------ */
static int cmp_irow(const void *a, const void *b) {
  const IRow *x = a, *y = b;
  if (x->t != y->t) return (x->t > y->t) - (x->t < y->t);
  return (x->key > y->key) - (x->key < y->key);
}

static int inbox_grow(CHost *h) {
  int ncap = h->inbox_cap ? h->inbox_cap * 2 : 32;
  IRow *nb = realloc(h->inbox, sizeof(IRow) * (size_t)ncap);
  if (!nb) { PyErr_NoMemory(); return -1; }
  h->inbox = nb;
  h->inbox_cap = ncap;
  return 0;
}

static inline void inbox_slice_mark(CHost *h, int slice) {
  if (h->inbox_n == 0) {
    h->inbox_last_slice = slice;
    h->inbox_multi = 0;
  } else if (h->inbox_last_slice != slice) {
    h->inbox_multi = 1;
    h->inbox_last_slice = slice;
  }
}

/* all fields come from the packed record; payload ref is INCREF'd into
 * the IRow (released by run_host's inbox-free loop) */
static int inbox_push_rec(CHost *h, const SRec *s, PyObject *payload,
                          int slice) {
  if (h->inbox_n == h->inbox_cap && inbox_grow(h) < 0) return -1;
  inbox_slice_mark(h, slice);
  IRow *r = &h->inbox[h->inbox_n++];
  r->t = s->t;
  r->key = s->key;
  Py_XINCREF(payload);
  r->payload = payload;
  r->kind = s->kind;
  r->peer = s->peer;
  r->bport = s->bport;
  r->aport = s->aport;
  r->nbytes = s->nbytes;
  r->seq = s->seq;
  r->frag = s->frag;
  r->nfrags = s->nfrags;
  r->size = s->size;
  return 0;
}

/* the colplane 13-tuple for one inbox row (Python-fallback dispatch and
 * deferred parking; tgt is the owning host) */
static PyObject *irow_tuple(const CHost *h, const IRow *r, int64_t tgt) {
  SRec s;
  (void)h;
  s.t = r->t; s.key = r->key; s.tgt = (int32_t)tgt; s.size = r->size;
  s.peer = r->peer; s.bport = r->bport; s.aport = r->aport;
  s.nbytes = r->nbytes; s.seq = r->seq; s.kind = r->kind;
  s.frag = r->frag; s.nfrags = r->nfrags;
  return srec_tuple(&s, r->payload);
}

static PyObject *Core_refill_ingress(CoreObject *c, PyObject *args) {
  /* start_of_round ingress refill (fluid.clamped_refill twin): tokens
   * gain min(bytes_over(rate, dt), cap) clamped at cap — pure int64,
   * one pass, no per-round numpy temporaries */
  long long dt_ll;
  if (!PyArg_ParseTuple(args, "L", &dt_ll)) return NULL;
  int64_t dt = dt_ll;
  int64_t q = dt / NS_PER_SEC, r = dt % NS_PER_SEC;
  for (int64_t i = 0; i < c->H; i++) {
    int64_t rate = c->rate_down[i], cap = c->cap_down[i];
    int64_t add = rate * q +
                  (int64_t)((uint64_t)rate * (uint64_t)r /
                            (uint64_t)NS_PER_SEC);
    if (add > cap) add = cap;
    int64_t room = cap - c->tokens_down[i];
    c->tokens_down[i] += add < room ? add : room;
  }
  Py_RETURN_NONE;
}

static PyObject *Core_extract(CoreObject *c, PyObject *args) {
  long long re_ll;
  if (!PyArg_ParseTuple(args, "L", &re_ll)) return NULL;
  int64_t round_end = re_ll;
  /* touched-host tracking for activation + sorting */
  int64_t *touched = NULL;
  int ntouched = 0, captouched = 0;
  int nslices = 0;
  PyObject *it = PyObject_GetIter(c->pending);
  if (!it) return NULL;
  PyObject *batch;
  while ((batch = PyIter_Next(it))) {
    if (Py_TYPE(batch) != &CBatch_Type) {
      PyErr_SetString(PyExc_TypeError,
                      "C extract expects CBatch store batches only");
      Py_DECREF(batch);
      goto fail;
    }
    CBatch *cb = (CBatch *)batch;
    SRec *recs = cb->recs;
    int pos = cb->pos, ln = cb->n;
    if (pos >= ln || recs[pos].t >= round_end) {
      Py_DECREF(batch);
      continue;
    }
    /* bisect_left by row time for round_end */
    int lo = pos, hi = ln;
    while (lo < hi) {
      int mid = (lo + hi) / 2;
      if (recs[mid].t < round_end) lo = mid + 1;
      else hi = mid;
    }
    for (int i = pos; i < lo; i++) {
      int64_t tgt = recs[i].tgt;
      if (tgt < 0 || tgt >= c->H) {
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_ValueError, "row target out of range");
        Py_DECREF(batch); goto fail;
      }
      CHost *h = &c->hs[tgt];
      if (h->inbox_n == 0) {
        if (ntouched == captouched) {
          captouched = captouched ? captouched * 2 : 64;
          int64_t *nt = realloc(touched,
                                sizeof(int64_t) * (size_t)captouched);
          if (!nt) {
            PyErr_NoMemory();
            Py_DECREF(batch); goto fail;
          }
          touched = nt;
        }
        touched[ntouched++] = tgt;
      }
      if (inbox_push_rec(h, &recs[i], cb->pay[i], nslices) < 0) {
        Py_DECREF(batch); goto fail;
      }
    }
    cb->pos = lo;
    nslices++;
    Py_DECREF(batch);
  }
  Py_DECREF(it);
  it = NULL;
  if (PyErr_Occurred()) goto fail;
  /* pop fully consumed batches off the front of the deque */
  for (;;) {
    Py_ssize_t np = PySequence_Size(c->pending);
    if (np < 0) goto fail;
    if (np == 0) break;
    PyObject *first = PySequence_GetItem(c->pending, 0);
    if (!first) goto fail;
    int done_b = Py_TYPE(first) == &CBatch_Type &&
                 ((CBatch *)first)->pos >= ((CBatch *)first)->n;
    Py_DECREF(first);
    if (!done_b) break;
    PyObject *r = PyObject_CallMethodObjArgs(c->pending, S_popleft, NULL);
    if (!r) goto fail;
    Py_DECREF(r);
  }
  if (ntouched == 0) {
    free(touched);
    Py_RETURN_NONE;
  }
  int multi = nslices > 1;
  for (int i = 0; i < ntouched; i++) {
    CHost *h = &c->hs[touched[i]];
    if (multi && h->inbox_n > 1 && h->inbox_multi)
      qsort(h->inbox, (size_t)h->inbox_n, sizeof(IRow), cmp_irow);
    if (h->py_mode) { /* (see below for the active-set add) */
      /* pcap hosts: hand a plain Python list of 13-tuples to
       * Host.run_events (materialized here; py_mode hosts are rare) */
      PyObject *lst = PyList_New(h->inbox_n);
      if (!lst) goto fail;
      for (int j = 0; j < h->inbox_n; j++) {
        PyObject *t = irow_tuple(h, &h->inbox[j], touched[i]);
        if (!t) { Py_DECREF(lst); goto fail; }
        PyList_SET_ITEM(lst, j, t);
        Py_XDECREF(h->inbox[j].payload);
        h->inbox[j].payload = NULL; /* cleanup passes must not re-release */
      }
      h->inbox_n = 0;
      int r = PyObject_SetAttr(h->host, S_inbox, lst);
      Py_DECREF(lst);
      if (r < 0) goto fail;
    }
    /* activate, recording genuinely-new members for the merge refresh */
    {
      int has = PySet_Contains(c->active, h->id_obj);
      if (has < 0) goto fail;
      if (!has) {
        if (PySet_Add(c->active, h->id_obj) < 0) goto fail;
        if (act_pend_add(c, touched[i]) < 0) goto fail;
      }
    }
  }
  free(touched);
  Py_RETURN_NONE;
fail:
  Py_XDECREF(it);
  free(touched);
  return NULL;
}

/* ---- GossipState type -------------------------------------------------- */
static int Gossip_traverse(GossipState *g, visitproc visit, void *arg) {
  Py_VISIT(g->core);
  return 0;
}

static int Gossip_clear_gc(GossipState *g) {
  Py_CLEAR(g->core);
  return 0;
}

static void Gossip_dealloc(GossipState *g) {
  PyObject_GC_UnTrack(g);
  Py_XDECREF(g->core);
  Py_XDECREF(g->port_obj);
  free(g->peers);
  seen_free(&g->seen);
  Py_TYPE(g)->tp_free((PyObject *)g);
}

static PyObject *Gossip_originate(GossipState *g, PyObject *arg) {
  char *buf;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(arg, &buf, &n) < 0) return NULL;
  if (seen_add(&g->seen, buf, n) < 0) return PyErr_NoMemory();
  CoreObject *c = g->core;
  CHost *h = &c->hs[g->hid];
  int64_t now;
  if (attr_i64(h->host, S_now, &now) < 0) return NULL;
  if (gossip_announce(c, h, g, now, buf, n, -1) < 0) return NULL;
  Py_RETURN_NONE;
}

/* fallback entry (deferred-ingress drains, fragmented datagrams): the
 * Python GossipNode._on_msg delegates here with (payload, src_host, now) */
static PyObject *Gossip_on_msg(GossipState *g, PyObject *args) {
  PyObject *payload;
  long long src_host, now;
  if (!PyArg_ParseTuple(args, "OLL", &payload, &src_host, &now)) return NULL;
  CoreObject *c = g->core;
  CHost *h = &c->hs[g->hid];
  if (gossip_on_msg_c(c, h, g, now, payload, src_host) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *Gossip_stats(GossipState *g, PyObject *noarg) {
  (void)noarg;
  return Py_BuildValue("(Ln)", (long long)g->received_tx,
                       (Py_ssize_t)g->seen.count);
}

static PyObject *Gossip_export_state(GossipState *g, PyObject *noarg);
static PyObject *Gossip_restore_state(GossipState *g, PyObject *state);

static PyMethodDef Gossip_methods[] = {
    {"originate", (PyCFunction)Gossip_originate, METH_O,
     "record a locally-originated txid and announce it to all peers"},
    {"on_msg", (PyCFunction)Gossip_on_msg, METH_VARARGS,
     "Python-fallback message delivery: (payload, src_host, now)"},
    {"stats", (PyCFunction)Gossip_stats, METH_NOARGS,
     "-> (received_tx, seen_count)"},
    {"_export_state", (PyCFunction)Gossip_export_state, METH_NOARGS,
     "checkpoint export: (hid, port, peers, seen, received, next_dgram)"},
    {"_restore_state", (PyCFunction)Gossip_restore_state, METH_O,
     "checkpoint restore (core binding comes via Core.adopt)"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject GossipState_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.GossipState",
    .tp_basicsize = sizeof(GossipState),
    .tp_dealloc = (destructor)Gossip_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)Gossip_traverse,
    .tp_clear = (inquiry)Gossip_clear_gc,
    .tp_methods = Gossip_methods,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C half of the gossip model (models/gossip.py delegates)",
};

/* ---- Core type --------------------------------------------------------- */

/* fetch a numpy array attr, validate dtype/contiguity, return new ref and
 * set *data */
static PyObject *grab_array(PyObject *o, const char *name, int typenum,
                            void **data) {
  PyObject *v = PyObject_GetAttrString(o, name);
  if (!v) return NULL;
  if (!PyArray_Check(v) ||
      PyArray_TYPE((PyArrayObject *)v) != typenum ||
      !PyArray_IS_C_CONTIGUOUS((PyArrayObject *)v)) {
    PyErr_Format(PyExc_TypeError,
                 "%s must be a C-contiguous numpy array of the expected "
                 "dtype", name);
    Py_DECREF(v);
    return NULL;
  }
  *data = PyArray_DATA((PyArrayObject *)v);
  return v;
}

static int Core_traverse(CoreObject *c, visitproc visit, void *arg) {
  Py_VISIT(c->hosts);
  Py_VISIT(c->pending);
  Py_VISIT(c->deferred);
  Py_VISIT(c->active);
  Py_VISIT(c->storebatch_cls);
  Py_VISIT(c->xout);
  for (int i = 0; i < 11; i++) Py_VISIT(c->arrs[i]);
  if (c->hs) {
    for (int64_t i = 0; i < c->H; i++) {
      CHost *h = &c->hs[i];
      Py_VISIT(h->id_obj);
      Py_VISIT(h->equeue);
      Py_VISIT(h->heap);
      Py_VISIT(h->live);
      Py_VISIT(h->cancelled);
      Py_VISIT(h->head_cache);
      Py_VISIT(h->egress);
      Py_VISIT(h->conns);
      Py_VISIT(h->listeners);
      Py_VISIT(h->ack_eps);
      for (int j = 0; j < h->nports; j++) Py_VISIT(h->gs[j]);
      /* inbox payloads / egress payloads are bytes|None (no cycles) */
    }
  }
  return 0;
}

static int Core_clear_gc(CoreObject *c) {
  Py_CLEAR(c->hosts);
  Py_CLEAR(c->pending);
  Py_CLEAR(c->deferred);
  Py_CLEAR(c->active);
  Py_CLEAR(c->storebatch_cls);
  Py_CLEAR(c->xout);
  for (int i = 0; i < 11; i++) Py_CLEAR(c->arrs[i]);
  if (c->hs) {
    for (int64_t i = 0; i < c->H; i++) {
      CHost *h = &c->hs[i];
      Py_CLEAR(h->id_obj);
      Py_CLEAR(h->equeue);
      Py_CLEAR(h->heap);
      Py_CLEAR(h->live);
      Py_CLEAR(h->cancelled);
      Py_CLEAR(h->head_cache);
      Py_CLEAR(h->egress);
      Py_CLEAR(h->conns);
      Py_CLEAR(h->listeners);
      Py_CLEAR(h->ack_eps);
      for (int j = 0; j < h->nports; j++) Py_CLEAR(h->gs[j]);
      h->nports = 0;
      for (int j = 0; j < h->inbox_n; j++) Py_CLEAR(h->inbox[j].payload);
      h->inbox_n = 0;
      for (int j = 0; j < h->erow_n; j++) Py_CLEAR(h->erow[j].payload);
      h->erow_n = 0;
    }
  }
  return 0;
}

static void Core_dealloc(CoreObject *c) {
  PyObject_GC_UnTrack(c);
  if (c->hs) {
    for (int64_t i = 0; i < c->H; i++) {
      CHost *h = &c->hs[i];
      Py_XDECREF(h->id_obj);
      Py_XDECREF(h->equeue);
      Py_XDECREF(h->heap);
      Py_XDECREF(h->live);
      Py_XDECREF(h->cancelled);
      Py_XDECREF(h->head_cache);
      Py_XDECREF(h->egress);
      Py_XDECREF(h->conns);
      Py_XDECREF(h->listeners);
      Py_XDECREF(h->ack_eps);
      for (int j = 0; j < h->inbox_n; j++) Py_XDECREF(h->inbox[j].payload);
      free(h->inbox);
      for (int j = 0; j < h->erow_n; j++) Py_XDECREF(h->erow[j].payload);
      free(h->erow);
      for (int j = 0; j < h->nports; j++) Py_XDECREF(h->gs[j]);
    }
    free(c->hs);
  }
  free(c->brow);
  free(c->act_ids);
  free(c->act_pend);
  if (c->spec) {
    for (int64_t i = 0; i < c->H; i++) {
      free(c->spec[i].min_a);
      free(c->spec[i].min_b);
      free(c->spec[i].nmin_a);
      free(c->spec[i].nmin_b);
    }
    free(c->spec);
  }
  free(c->spec_dq);
  if (c->xrecs) {
    for (int j = 0; j < c->shard_n; j++) {
      for (int i = 0; i < c->xn[j]; i++) Py_XDECREF(c->xpay[j][i]);
      free(c->xrecs[j]);
      free(c->xpay[j]);
    }
    free(c->xrecs);
    free(c->xpay);
    free(c->xn);
    free(c->xcap);
  }
  Py_XDECREF(c->hosts);
  Py_XDECREF(c->pending);
  Py_XDECREF(c->deferred);
  Py_XDECREF(c->active);
  Py_XDECREF(c->storebatch_cls);
  Py_XDECREF(c->xout);
  for (int i = 0; i < 11; i++) Py_XDECREF(c->arrs[i]);
  Py_TYPE(c)->tp_free((PyObject *)c);
}

static int Core_init(CoreObject *c, PyObject *args, PyObject *kwds) {
  (void)kwds;
  PyObject *plane;
  if (!PyArg_ParseTuple(args, "O", &plane)) return -1;
  /* plane._c will own us; we keep a borrowed back-pointer (the plane
   * outlives the core by construction — documented cycle break) */
  c->plane = plane;
  c->hosts = PyObject_GetAttrString(plane, "hosts");
  if (!c->hosts) return -1;
  if (PyList_Check(c->hosts)) {
    c->H = PyList_GET_SIZE(c->hosts);
  } else {
    Py_ssize_t hn = PySequence_Size(c->hosts);
    if (hn < 0) return -1;
    PyObject *asl = PySequence_List(c->hosts);
    if (!asl) return -1;
    Py_SETREF(c->hosts, asl);
    c->H = hn;
  }
  c->pending = PyObject_GetAttrString(plane, "pending");
  if (!c->pending) return -1;
  c->deferred = PyObject_GetAttrString(plane, "_deferred");
  if (!c->deferred) return -1;
  PyObject *params = PyObject_GetAttrString(plane, "params");
  if (!params) return -1;
  PyObject *buckets = PyObject_GetAttrString(plane, "buckets");
  PyObject *graph = PyObject_GetAttrString(plane, "graph");
  int ok = params && buckets && graph;
  if (ok) {
    void *p;
    ok = (c->arrs[0] = grab_array(plane, "tokens_down", NPY_INT64, &p)) != 0;
    c->tokens_down = p;
    if (ok) { c->arrs[1] = grab_array(buckets, "t_base", NPY_INT64, &p);
              c->tbase = p; ok = c->arrs[1] != 0; }
    if (ok) { c->arrs[2] = grab_array(buckets, "tokens", NPY_INT64, &p);
              c->tokens = p; ok = c->arrs[2] != 0; }
    if (ok) { c->arrs[3] = grab_array(buckets, "debt", NPY_INT64, &p);
              c->debt = p; ok = c->arrs[3] != 0; }
    if (ok) { c->arrs[4] = grab_array(params, "rate_up", NPY_INT64, &p);
              c->rate_up = p; ok = c->arrs[4] != 0; }
    if (ok) { c->arrs[5] = grab_array(params, "cap_up", NPY_INT64, &p);
              c->cap_up = p; ok = c->arrs[5] != 0; }
    if (ok) { c->arrs[6] = grab_array(graph, "latency_ns", NPY_INT64, &p);
              c->lat = p; ok = c->arrs[6] != 0; }
    if (ok) { c->arrs[7] = grab_array(params, "drop_thresh", NPY_UINT32, &p);
              c->thresh = p; ok = c->arrs[7] != 0; }
    if (ok) { c->arrs[8] = grab_array(params, "host_node", NPY_INT32, &p);
              c->hostnode = p; ok = c->arrs[8] != 0; }
    if (ok) { c->arrs[9] = grab_array(params, "rate_down", NPY_INT64, &p);
              c->rate_down = p; ok = c->arrs[9] != 0; }
    if (ok) { c->arrs[10] = grab_array(params, "cap_down", NPY_INT64, &p);
              c->cap_down = p; ok = c->arrs[10] != 0; }
    if (ok) {
      c->G = PyArray_DIM((PyArrayObject *)c->arrs[6], 0);
      int64_t seed;
      ok = attr_i64(params, S_seed, &seed) == 0;
      c->seed = (uint64_t)seed;
    }
  }
  Py_XDECREF(params);
  Py_XDECREF(buckets);
  Py_XDECREF(graph);
  if (!ok) return -1;
  if (attr_i64(plane, S_bootstrap_end, &c->bootstrap_end) < 0)
    return -1;
  PyObject *mp = PyObject_GetAttrString(plane, "mesh_plane");
  if (!mp) return -1;
  c->mesh_mode = mp != Py_None;
  Py_DECREF(mp);
  PyObject *fa = PyObject_GetAttrString(plane, "faults_active");
  if (!fa) return -1;
  c->faults_active = PyObject_IsTrue(fa);
  Py_DECREF(fa);
  if (c->faults_active < 0) return -1;
  c->unit_chunk = 0; /* filled from hosts[0] below (config-uniform) */
  PyObject *mod = PyImport_ImportModule("shadow_tpu.network.colplane");
  if (!mod) return -1;
  c->storebatch_cls = PyObject_GetAttrString(mod, "StoreBatch");
  Py_DECREF(mod);
  if (!c->storebatch_cls) return -1;
  c->hs = calloc((size_t)c->H, sizeof(CHost));
  if (!c->hs) { PyErr_NoMemory(); return -1; }
  for (int64_t i = 0; i < c->H; i++) {
    CHost *h = &c->hs[i];
    PyObject *host = PyList_GET_ITEM(c->hosts, i);
    h->host = host;
    h->id_obj = PyObject_GetAttr(host, S_id);
    if (!h->id_obj) return -1;
    if (PyLong_AsLongLong(h->id_obj) != i) {
      PyErr_SetString(PyExc_ValueError, "hosts list not id-ordered");
      return -1;
    }
    PyObject *eq = PyObject_GetAttrString(host, "equeue");
    if (!eq) return -1;
    h->equeue = eq; /* owned */
    h->heap = PyObject_GetAttrString(eq, "_heap");
    h->live = PyObject_GetAttrString(eq, "_live");
    h->cancelled = PyObject_GetAttrString(eq, "_cancelled");
    if (!h->heap || !h->live || !h->cancelled) return -1;
    PyObject *pcap = PyObject_GetAttr(host, S_pcap);
    if (!pcap) return -1;
    h->py_mode = pcap != Py_None;
    Py_DECREF(pcap);
    /* crashed-host flag: nonzero when the core is (re)built over a
     * restored simulation whose checkpoint caught a host mid-downtime */
    PyObject *dv = PyObject_GetAttr(host, S_down);
    if (!dv) return -1;
    h->down = PyObject_IsTrue(dv);
    Py_DECREF(dv);
    if (h->down < 0) return -1;
    h->egress = PyObject_GetAttr(host, S_egress_rows);
    if (!h->egress) return -1;
    if (!PyList_Check(h->egress)) {
      PyErr_SetString(PyExc_TypeError, "host.egress_rows must be a list");
      return -1;
    }
    h->conns = PyObject_GetAttrString(host, "_conns");
    h->listeners = PyObject_GetAttrString(host, "_listeners");
    h->ack_eps = PyObject_GetAttrString(host, "_ack_eps");
    if (!h->conns || !h->listeners || !h->ack_eps) return -1;
    {
      int64_t cc;
      if (attr_i64(host, S_cc_id, &cc) < 0)
        return -1;
      h->cc_kind = (int)cc;
    }
    if (!PyDict_Check(h->ack_eps)) {
      PyErr_SetString(PyExc_TypeError, "host._ack_eps must be a dict");
      return -1;
    }
    if (i == 0) {
      int64_t uc;
      if (attr_i64(host, S_unit_chunk, &uc) < 0)
        return -1;
      c->unit_chunk = uc;
      PyObject *exp = NULL, *ctl2 = PyObject_GetAttrString(host,
                                                           "controller");
      PyObject *cfg2 = ctl2 ? PyObject_GetAttrString(ctl2, "cfg") : NULL;
      exp = cfg2 ? PyObject_GetAttrString(cfg2, "experimental") : NULL;
      int ok2 = exp &&
          attr_i64(exp, S_socket_send_buffer, &c->sock_sbuf) == 0 &&
          attr_i64(exp, S_socket_recv_buffer, &c->sock_rbuf) == 0;
      Py_XDECREF(exp);
      Py_XDECREF(cfg2);
      Py_XDECREF(ctl2);
      if (!ok2) return -1;
    }
  }
  return 0;
}

static PyObject *Core_bind_active(CoreObject *c, PyObject *arg) {
  if (!PySet_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "bind_active expects the active set");
    return NULL;
  }
  Py_INCREF(arg);
  Py_XSETREF(c->active, arg);
  c->act_n = -1; /* invalidate the sorted snapshot cache */
  c->act_pend_n = 0;
  Py_RETURN_NONE;
}

/* the activation hook (controller wires equeue.on_first and
 * plane.activate here when the C engine is attached): set-add + pend
 * record, so the next refresh merges instead of re-snapshotting */
static PyObject *Core_activate(CoreObject *c, PyObject *arg) {
  if (!c->active) {
    PyErr_SetString(PyExc_RuntimeError, "bind_active() not called");
    return NULL;
  }
  int has = PySet_Contains(c->active, arg);
  if (has < 0) return NULL;
  if (!has) {
    int64_t hid = PyLong_AsLongLong(arg);
    if (hid == -1 && PyErr_Occurred()) return NULL;
    if (PySet_Add(c->active, arg) < 0) return NULL;
    if (act_pend_add(c, hid) < 0) return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *Core_gossip_register(CoreObject *c, PyObject *args) {
  long long hid, port;
  PyObject *peers;
  if (!PyArg_ParseTuple(args, "LLO", &hid, &port, &peers)) return NULL;
  if (hid < 0 || hid >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  CHost *h = &c->hs[hid];
  if (h->nports >= 4) {
    PyErr_SetString(PyExc_ValueError, "too many C ports on one host");
    return NULL;
  }
  PyObject *pl = PySequence_List(peers);
  if (!pl) return NULL;
  Py_ssize_t np = PyList_GET_SIZE(pl);
  GossipState *g = PyObject_GC_New(GossipState, &GossipState_Type);
  if (!g) { Py_DECREF(pl); return NULL; }
  Py_INCREF(c);
  g->core = c;
  g->hid = (int)hid;
  g->port = (int)port;
  g->port_obj = PyLong_FromLongLong(port);
  g->peers = malloc(sizeof(int32_t) * (size_t)(np ? np : 1));
  g->npeers = (int)np;
  g->received_tx = 0;
  g->next_dgram = 0;
  memset(&g->seen, 0, sizeof g->seen);
  if (!g->port_obj || !g->peers || seen_init(&g->seen) < 0) {
    Py_DECREF(pl);
    Py_DECREF(g);
    return PyErr_NoMemory();
  }
  for (Py_ssize_t i = 0; i < np; i++)
    g->peers[i] = (int32_t)PyLong_AsLongLong(PyList_GET_ITEM(pl, i));
  Py_DECREF(pl);
  if (PyErr_Occurred()) { Py_DECREF(g); return NULL; }
  h->port[h->nports] = (int)port;
  Py_INCREF(g);
  h->gs[h->nports] = g;
  h->nports++;
  PyObject_GC_Track((PyObject *)g);
  return (PyObject *)g;
}

static PyObject *Core_fold_counters(CoreObject *c, PyObject *noarg) {
  (void)noarg;
  for (int64_t i = 0; i < c->H; i++) {
    CHost *h = &c->hs[i];
    if (attr_add_i64(h->host, S_n_emitted, h->d_emitted) < 0 ||
        attr_add_i64(h->host, S_n_delivered, h->d_delivered) < 0 ||
        attr_add_i64(h->host, S_n_dgrams, h->d_dgrams) < 0 ||
        attr_add_i64(h->host, S_n_dgrams_recv, h->d_dgrams_recv) < 0 ||
        attr_add_i64(h->host, S_n_events, h->d_events) < 0 ||
        attr_add_i64(h->host, S_n_teardown, h->d_teardown) < 0 ||
        attr_add_i64(h->host, S_n_blackholed, h->d_blackholed) < 0)
      return NULL;
    h->d_emitted = h->d_delivered = h->d_dgrams = h->d_dgrams_recv = 0;
    h->d_events = 0;
    h->d_teardown = h->d_blackholed = 0;
    /* stream/routing counters go through host.counters.add (key space
     * shared with the Python transport; the last four are the
     * faults_active-gated recovery counters — the deltas are only ever
     * incremented with faults on, so the fold stays unconditional) */
    static const char *names2[8] = {"stream_bytes_queued",
                                    "stream_bytes_received",
                                    "stream_resets", "units_unroutable",
                                    "stream_fast_retransmits",
                                    "stream_rto_retransmits",
                                    "stream_timeouts",
                                    "stream_sack_retransmits"};
    int64_t *vals[8] = {&h->d_sbytes_q, &h->d_sbytes_recv, &h->d_resets,
                        &h->d_unroutable, &h->d_fast_retx,
                        &h->d_rto_retx, &h->d_timeouts, &h->d_sack_retx};
    PyObject *ctrs = NULL;
    for (int j = 0; j < 8; j++) {
      if (!*vals[j]) continue;
      if (!ctrs) {
        ctrs = PyObject_GetAttrString(h->host, "counters");
        if (!ctrs) return NULL;
      }
      PyObject *r = PyObject_CallMethod(ctrs, "add", "(sL)", names2[j],
                                        (long long)*vals[j]);
      if (!r) { Py_DECREF(ctrs); return NULL; }
      Py_DECREF(r);
      *vals[j] = 0;
    }
    Py_XDECREF(ctrs);
  }
  Py_RETURN_NONE;
}

static PyObject *Core_make_endpoint(CoreObject *c, PyObject *args);
static PyObject *Core_flush_acks(CoreObject *c, PyObject *arg);
static PyObject *Core_run_round(CoreObject *c, PyObject *args);
static PyObject *Core_relay_new(CoreObject *c, PyObject *args);
static PyObject *Core_tor_client_sink(CoreObject *c, PyObject *args);
static PyObject *Core_adopt(CoreObject *c, PyObject *arg);

/* -- fault lifecycle (shadow_tpu/faults.py) ------------------------------ */
static PyObject *Core_bind_shard(CoreObject *c, PyObject *args) {
  /* multi-process sharding: (shard_id, n_shards, xout) where xout is the
   * plane's list of n_shards per-destination-shard row lists — or None,
   * which selects the PACKED send path: diverted rows accumulate in the
   * core's SRec buffers and drain as wire-format blocks via
   * take_xout_packed (no per-row Python tuples). Rebinding (e.g. after
   * take_xout swaps fresh lists in) is the normal pattern. */
  int sid, n;
  PyObject *xout;
  if (!PyArg_ParseTuple(args, "iiO", &sid, &n, &xout)) return NULL;
  if (n < 1 || sid < 0 || sid >= n) {
    PyErr_SetString(PyExc_ValueError, "bind_shard: shard_id/n out of range");
    return NULL;
  }
  if (xout == Py_None) {
    if (!c->xrecs || c->shard_n != n) {
      if (c->xrecs) { /* shard count changed: drop the old buffers */
        for (int j = 0; j < c->shard_n; j++) {
          for (int i = 0; i < c->xn[j]; i++) Py_XDECREF(c->xpay[j][i]);
          free(c->xrecs[j]);
          free(c->xpay[j]);
        }
        free(c->xrecs); free(c->xpay); free(c->xn); free(c->xcap);
      }
      c->xrecs = calloc((size_t)n, sizeof(SRec *));
      c->xpay = calloc((size_t)n, sizeof(PyObject **));
      c->xn = calloc((size_t)n, sizeof(int));
      c->xcap = calloc((size_t)n, sizeof(int));
      if (!c->xrecs || !c->xpay || !c->xn || !c->xcap) {
        free(c->xrecs); free(c->xpay); free(c->xn); free(c->xcap);
        c->xrecs = NULL; c->xpay = NULL; c->xn = NULL; c->xcap = NULL;
        return PyErr_NoMemory();
      }
    }
    c->xpacked = 1;
    c->shard_id = sid;
    c->shard_n = n;
    Py_CLEAR(c->xout);
    Py_RETURN_NONE;
  }
  if (!PyList_Check(xout) || PyList_GET_SIZE(xout) != n) {
    PyErr_SetString(PyExc_TypeError,
                    "bind_shard expects xout as a list of n_shards lists "
                    "or None (packed mode)");
    return NULL;
  }
  c->xpacked = 0;
  c->shard_id = sid;
  c->shard_n = n;
  Py_INCREF(xout);
  Py_XSETREF(c->xout, xout);
  Py_RETURN_NONE;
}

/* drain the packed cross-shard egress buffers (bind_shard(.., None)
 * mode) as a list of per-destination-shard lists of wire-format byte
 * blocks — the exact parallel/shards.py pack_rows layout
 * ([n u64][numeric cols (n,12) i64][payload lens i64][blobs], rows
 * (t,key)-sorted, marshal payloads with negative-length pickle
 * fallback), chunked so no block exceeds max_bytes (a single giant row
 * still forms one block; the worker's ring-capacity guard names it).
 * This closes the send-side half of the packed wire path: the receiver
 * already parses these bytes straight into a CBatch
 * (cbatch_from_packed), and now the sender never materializes 13-field
 * Python tuples either. */
static PyObject *Core_take_xout_packed(CoreObject *c, PyObject *args) {
  long long max_bytes;
  if (!PyArg_ParseTuple(args, "L", &max_bytes)) return NULL;
  if (!c->xpacked || !c->xrecs) {
    PyErr_SetString(PyExc_RuntimeError,
                    "take_xout_packed: packed mode not bound "
                    "(bind_shard(sid, n, None) first)");
    return NULL;
  }
  if (max_bytes < 4096) max_bytes = 4096;
  PyObject *outer = PyList_New(c->shard_n);
  if (!outer) return NULL;
  for (int j = 0; j < c->shard_n; j++) {
    PyObject *blocks = PyList_New(0);
    if (!blocks) { Py_DECREF(outer); return NULL; }
    PyList_SET_ITEM(outer, j, blocks);
  }
  for (int j = 0; j < c->shard_n; j++) {
    int n = c->xn[j];
    if (!n) continue;
    PyObject *blocks = PyList_GET_ITEM(outer, j);
    SRec *recs = c->xrecs[j];
    PyObject **pay = c->xpay[j];
    ORow *ord = malloc(sizeof(ORow) * (size_t)n);
    PyObject **blobs = calloc((size_t)n, sizeof(PyObject *));
    int64_t *lens = malloc(sizeof(int64_t) * (size_t)n);
    int fail = !ord || !blobs || !lens;
    if (!fail) {
      for (int i = 0; i < n; i++) {
        ord[i].t = recs[i].t;
        ord[i].key = recs[i].key;
        ord[i].idx = i;
      }
      qsort(ord, (size_t)n, sizeof(ORow), cmp_orow);
      /* serialize payloads in sorted order (blobs[i] pairs with ord[i]) */
      for (int i = 0; i < n && !fail; i++) {
        PyObject *p = pay[ord[i].idx];
        if (!p) { lens[i] = 0; continue; }
        PyObject *b = PyMarshal_WriteObjectToString(p, Py_MARSHAL_VERSION);
        if (b) {
          lens[i] = (int64_t)PyBytes_GET_SIZE(b);
        } else {
          PyErr_Clear(); /* unmarshallable payload: pickle fallback */
          PyObject *pickle = PyImport_ImportModule("pickle");
          b = pickle ? PyObject_CallMethod(pickle, "dumps", "Oi", p, 4)
                     : NULL;
          Py_XDECREF(pickle);
          if (!b) { fail = 1; break; }
          lens[i] = -(int64_t)PyBytes_GET_SIZE(b);
        }
        blobs[i] = b;
      }
    }
    /* emit chunks of the sorted rows (chunks of a sorted list stay
     * sorted; each becomes its own pending batch at the receiver) */
    int start = 0;
    while (!fail && start < n) {
      int64_t sz = 8;
      int end = start;
      while (end < n) {
        int64_t row = 13 * 8 +
                      (blobs[end] ? (int64_t)PyBytes_GET_SIZE(blobs[end])
                                  : 0);
        if (end > start && sz + row > max_bytes) break;
        sz += row;
        end++;
      }
      int64_t m = end - start;
      PyObject *blk = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)sz);
      if (!blk) { fail = 1; break; }
      char *w = PyBytes_AS_STRING(blk);
      memcpy(w, &m, 8);
      w += 8;
      for (int i = start; i < end; i++) {
        const SRec *s = &recs[ord[i].idx];
        /* pack_rows column order (= the 13-tuple store-row prefix) */
        int64_t cols[12] = {s->t,      s->key,   (int64_t)s->tgt,
                            (int64_t)s->kind,    (int64_t)s->peer,
                            (int64_t)s->aport,   (int64_t)s->bport,
                            s->nbytes, s->seq,   (int64_t)s->frag,
                            (int64_t)s->nfrags,  (int64_t)s->size};
        memcpy(w, cols, 12 * 8);
        w += 12 * 8;
      }
      memcpy(w, lens + start, (size_t)m * 8);
      w += m * 8;
      for (int i = start; i < end; i++) {
        if (blobs[i]) {
          Py_ssize_t bl = PyBytes_GET_SIZE(blobs[i]);
          memcpy(w, PyBytes_AS_STRING(blobs[i]), (size_t)bl);
          w += bl;
        }
      }
      if (PyList_Append(blocks, blk) < 0) { Py_DECREF(blk); fail = 1; }
      else Py_DECREF(blk);
      start = end;
    }
    if (blobs)
      for (int i = 0; i < n; i++) Py_XDECREF(blobs[i]);
    free(blobs);
    free(lens);
    free(ord);
    if (fail) {
      /* fatal, not retryable: shards drained in EARLIER iterations ride
       * the dropped `outer` blocks, so a caller must abort the run (the
       * shard worker does — the error propagates as a worker failure) */
      Py_DECREF(outer);
      if (!PyErr_Occurred()) PyErr_NoMemory();
      return NULL;
    }
    /* drained: release payload refs, reset the buffer */
    for (int i = 0; i < n; i++) Py_XDECREF(pay[i]);
    c->xn[j] = 0;
  }
  return outer;
}

static PyObject *Core_set_faults_active(CoreObject *c, PyObject *arg) {
  int v = PyObject_IsTrue(arg);
  if (v < 0) return NULL;
  c->faults_active = v;
  Py_RETURN_NONE;
}

/* Host.crash's C-side half: mark the CHost down (per-row dispatch
 * discards arrivals), drop the C-registered gossip handlers (a reboot
 * re-registers fresh state via gossip_register), and defensively clear
 * the transient inbox/egress buffers (both are empty at the round
 * starts where faults apply). The Python side of crash() — conns,
 * listeners, timers, parked rows — operates on the SHARED structures
 * this core caches, so it needs no C involvement. */
static PyObject *Core_host_crash(CoreObject *c, PyObject *arg) {
  int64_t hid = PyLong_AsLongLong(arg);
  if (hid == -1 && PyErr_Occurred()) return NULL;
  if (hid < 0 || hid >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  CHost *h = &c->hs[hid];
  h->down = 1;
  for (int j = 0; j < h->nports; j++) Py_CLEAR(h->gs[j]);
  h->nports = 0;
  for (int j = 0; j < h->inbox_n; j++) Py_CLEAR(h->inbox[j].payload);
  h->inbox_n = 0;
  h->inbox_multi = 0;
  for (int j = 0; j < h->erow_n; j++) Py_CLEAR(h->erow[j].payload);
  h->erow_n = 0;
  Py_RETURN_NONE;
}

static PyObject *Core_host_boot(CoreObject *c, PyObject *arg) {
  int64_t hid = PyLong_AsLongLong(arg);
  if (hid == -1 && PyErr_Occurred()) return NULL;
  if (hid < 0 || hid >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  c->hs[hid].down = 0;
  Py_RETURN_NONE;
}

/* transport column snapshot/adopt ABI (PR 11; defined after CEp below) */
static PyObject *Core_transport_columns(CoreObject *c, PyObject *noarg);
static PyObject *Core_adopt_transport_columns(CoreObject *c,
                                              PyObject *cols);

static PyMethodDef Core_methods[] = {
    {"transport_columns", (PyCFunction)Core_transport_columns,
     METH_NOARGS,
     "struct-of-arrays int64 snapshot of every C stream endpoint "
     "(network/devtransport.py COLUMNS twin; canonical host-id + "
     "sorted-connection-key order; pcap hosts' endpoints stay Python "
     "and are omitted — compare snapshots on pcap-free configs)"},
    {"adopt_transport_columns",
     (PyCFunction)Core_adopt_transport_columns, METH_O,
     "(cols dict) -> window-edge writeback of the ADOPT_COLUMNS subset "
     "(cwnd/ssthresh/cubic epoch/backoff) into live C endpoints; "
     "refuses by name when a row matches no live endpoint"},
    {"barrier", (PyCFunction)Core_barrier, METH_VARARGS,
     "end_of_round twin: (round_start, round_end) -> None | device batch"},
    {"extract", (PyCFunction)Core_extract, METH_VARARGS,
     "_extract twin: (round_end)"},
    {"refill_ingress", (PyCFunction)Core_refill_ingress, METH_VARARGS,
     "clamped ingress token refill for an elapsed window: (dt_ns)"},
    {"next_time", (PyCFunction)Core_next_time, METH_NOARGS,
     "min pending event time over the active hosts (skip-ahead)"},
    {"activate", (PyCFunction)Core_activate, METH_O,
     "(host_id) -> None  add a host to the active set (merge-tracked)"},
    {"flush_acks", (PyCFunction)Core_flush_acks, METH_O,
     "(ack_hosts) -> None  flush each host's coalesced barrier acks"},
    {"run_round", (PyCFunction)Core_run_round, METH_VARARGS,
     "per-round host loop over the bound active set: (round_end) -> n"},
    {"emit_row", (PyCFunction)Core_emit_row, METH_VARARGS,
     "packed emission (Host.emit_msg delegate): (hid, kind, dst, size, "
     "t_emit, sport, dport, nbytes, seq, frag, nfrags, payload)"},
    {"materialize_egress", (PyCFunction)Core_materialize_egress,
     METH_NOARGS,
     "flush packed C egress into host.egress_rows tuples (Python-barrier "
     "rounds: fault_filter)"},
    {"store_resolved", (PyCFunction)Core_store_resolved, METH_VARARGS,
     "(rows, src_l, arrival_l, keys_l, flags|None, round_end)"},
    {"bind_active", (PyCFunction)Core_bind_active, METH_O,
     "bind the controller's active-host-id set"},
    {"gossip_register", (PyCFunction)Core_gossip_register, METH_VARARGS,
     "(hid, port, peers) -> GossipState; registers the C dgram handler"},
    {"spec_demand", (PyCFunction)Core_spec_demand, METH_VARARGS,
     "(min_hosts=1) -> drain speculative-window demand once the queued "
     "cohort reaches min_hosts: (hosts, u0, n, npk_a, npk_b) arrays, or "
     "None; the first call enables speculation"},
    {"spec_install", (PyCFunction)Core_spec_install, METH_VARARGS,
     "(hosts, u0, n, npk_a, npk_b, off_a, off_b, mins) -> install one "
     "wave's prefix-min draws into the consult table"},
    {"spec_stats", (PyCFunction)Core_spec_stats, METH_NOARGS,
     "drain (speculative hits, inline draws since speculation enabled)"},
    {"fold_counters", (PyCFunction)Core_fold_counters, METH_NOARGS,
     "flush outstanding per-host counter deltas into host attributes"},
    {"make_endpoint", (PyCFunction)Core_make_endpoint, METH_VARARGS,
     "(hid, lport, rhost, rport, initiator, sbuf, rbuf) -> Endpoint"},
    {"relay_new", (PyCFunction)Core_relay_new, METH_VARARGS,
     "(hid, on_ctrl) -> Relay (C tor-relay data path)"},
    {"tor_client_sink", (PyCFunction)Core_tor_client_sink, METH_VARARGS,
     "(endpoint, on_cell) -> TorSink (C tor-client data path)"},
    {"bind_shard", (PyCFunction)Core_bind_shard, METH_VARARGS,
     "install the multi-process shard filter: (shard_id, n_shards, xout "
     "per-shard row lists — or None for the packed send path); "
     "cross-shard store rows divert into xout / the packed buffers"},
    {"take_xout_packed", (PyCFunction)Core_take_xout_packed, METH_VARARGS,
     "(max_bytes) -> [[bytes blocks] per shard]: drain the packed "
     "cross-shard egress as (t,key)-sorted shards.py wire-format blocks "
     "(the send-side twin of cbatch_from_packed)"},
    {"set_faults_active", (PyCFunction)Core_set_faults_active, METH_O,
     "(flag) -> enable the faults_active-gated accounting (blackhole/"
     "teardown per-host counts, stream recovery counters)"},
    {"host_crash", (PyCFunction)Core_host_crash, METH_O,
     "(hid) -> C-side host crash teardown (Host.crash delegates)"},
    {"host_boot", (PyCFunction)Core_host_boot, METH_O,
     "(hid) -> clear the C-side down flag (Host.reboot delegates)"},
    {"adopt", (PyCFunction)Core_adopt, METH_O,
     "(objs) -> bind checkpoint-restored C objects (endpoints, gossip "
     "states, relays) to this core (Controller._reattach_runtime)"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject Core_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.Core",
    .tp_basicsize = sizeof(CoreObject),
    .tp_dealloc = (destructor)Core_dealloc,
    /* GC-tracked so the endpoint->core->conns->endpoint and
     * gossip-state cycles collect at simulation teardown (review r4) */
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)Core_traverse,
    .tp_clear = (inquiry)Core_clear_gc,
    .tp_methods = Core_methods,
    .tp_init = (initproc)Core_init,
    .tp_new = PyType_GenericNew,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C engine for one ColumnarPlane (plane._c)",
};


/* ======================================================================
 * C stream transport — the exact twin of network/transport.py's
 * StreamEndpoint/StreamSender/StreamReceiver, one object per connection
 * half. App callbacks (on_data, on_connected, ...) stay Python; all
 * protocol bookkeeping (windows, cumulative acks, OOO buffering,
 * retransmission, close handshakes) runs here. Timers go through the
 * host's Python event queue (bound-method tasks), so event identity and
 * ordering match the Python twin exactly.
 * ====================================================================== */

#define MSS_C 1460
#define INIT_CWND_C (10 * MSS_C)
#define MIN_CWND_C (2 * MSS_C)
#define RTO_MIN_NS_C 200000000LL
/* RTO ceiling (transport.py RTO_MAX_NS twin): a connection created
 * across a CUT path sees INF latency, and 2x that both overflows int64
 * and means "never retry" — cap like TCP's conventional 60 s max */
#define RTO_MAX_NS_C 60000000000LL
#define SYN_RETRIES_C 5
#define FIN_RETRIES_C 5
#define DATA_RETRIES_C 8
#define SACK_MAX_BLOCKS_C 4
/* congestion-control ids (transport.py CongestionControl.cc_id twins) */
#define CC_NEWRENO 0
#define CC_CUBIC 1
/* endpoint states (transport.py order) */
#define ST_CLOSED 0
#define ST_SYN_SENT 1
#define ST_ESTABLISHED 2
#define ST_CLOSING 3
#define ST_FIN_SENT 4
#define ST_TIME_WAIT 5

typedef struct { int64_t nbytes; PyObject *payload; } SQEnt;
typedef struct { int64_t seq, n; PyObject *payload; } RtxEnt;

/* grow-able ring (head + count) */
typedef struct { void *buf; int head, count, cap, esz; } Ring;

static int ring_grow(Ring *r) {
  int ncap = r->cap ? r->cap * 2 : 16;
  void *nb = malloc((size_t)ncap * (size_t)r->esz);
  if (!nb) { PyErr_NoMemory(); return -1; }
  for (int i = 0; i < r->count; i++)
    memcpy((char *)nb + (size_t)i * r->esz,
           (char *)r->buf + (size_t)((r->head + i) % r->cap) * r->esz,
           (size_t)r->esz);
  free(r->buf);
  r->buf = nb;
  r->head = 0;
  r->cap = ncap;
  return 0;
}

static inline void *ring_at(Ring *r, int i) {
  return (char *)r->buf + (size_t)((r->head + i) % r->cap) * r->esz;
}

static inline void *ring_push(Ring *r) {
  if (r->count == r->cap && ring_grow(r) < 0) return NULL;
  return ring_at(r, r->count++);
}

static inline void ring_popleft(Ring *r) {
  r->head = (r->head + 1) % r->cap;
  r->count--;
}

/* int64 seq-set over a Ring (the StreamSender sacked/rtx_done set
 * twins): membership is a linear scan — the sets hold at most a few
 * dozen in-flight segment seqs during a loss episode and are empty on
 * clean connections */
static int i64set_has(Ring *r, int64_t v) {
  for (int i = 0; i < r->count; i++)
    if (*(int64_t *)ring_at(r, i) == v) return 1;
  return 0;
}

static int i64set_add(Ring *r, int64_t v) {
  if (i64set_has(r, v)) return 0;
  int64_t *p = ring_push(r);
  if (!p) return -1;
  *p = v;
  return 0;
}

/* drop every member < cum (the cumulative-ack prune of the Python
 * set comprehension) — rebuilds in place, order irrelevant */
static void i64set_prune_below(Ring *r, int64_t cum) {
  int w = 0;
  for (int i = 0; i < r->count; i++) {
    int64_t v = *(int64_t *)ring_at(r, i);
    if (v >= cum) {
      *(int64_t *)ring_at(r, w) = v;
      w++;
    }
  }
  r->count = w;
}

/* tuple(sorted(set)) twin for fingerprint/export (cmp_i64 above) */
static PyObject *i64set_sorted_tuple(Ring *r) {
  int n = r->count;
  int64_t *tmp = n ? malloc((size_t)n * sizeof(int64_t)) : NULL;
  if (n && !tmp) return PyErr_NoMemory();
  for (int i = 0; i < n; i++) tmp[i] = *(int64_t *)ring_at(r, i);
  if (n) qsort(tmp, (size_t)n, sizeof(int64_t), cmp_i64);
  PyObject *t = PyTuple_New(n);
  if (!t) { free(tmp); return NULL; }
  for (int i = 0; i < n; i++) {
    PyObject *v = PyLong_FromLongLong(tmp[i]);
    if (!v) { free(tmp); Py_DECREF(t); return NULL; }
    PyTuple_SET_ITEM(t, i, v);
  }
  free(tmp);
  return t;
}

/* restore from an exported tuple of ints */
static int i64set_restore(Ring *r, PyObject *tup) {
  if (!PyTuple_Check(tup)) {
    PyErr_SetString(PyExc_TypeError, "seq-set restore: want a tuple");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(tup); i++) {
    int64_t v = PyLong_AsLongLong(PyTuple_GET_ITEM(tup, i));
    if (v == -1 && PyErr_Occurred()) return -1;
    if (i64set_add(r, v) < 0) return -1;
  }
  return 0;
}

/* floor integer cube root (transport.py _icbrt twin: same binary
 * search, operands < 2**60 so int64 is exact) */
static int64_t icbrt64(int64_t x) {
  int64_t lo = 0, hi = 1LL << 20;
  while (lo < hi) {
    int64_t mid = (lo + hi + 1) >> 1;
    if (mid * mid * mid <= x) lo = mid;
    else hi = mid - 1;
  }
  return lo;
}

typedef struct CEp {
  PyObject_HEAD
  CoreObject *core; /* owned */
  int hid;
  int local_port, remote_host, remote_port;
  int initiator, state, syn_tries, fin_tries, peer_fin;
  int64_t rto_ns;
  PyObject *ctl_timer; /* owned PyLong handle, or NULL */
  /* opt-in idle timeout (StreamEndpoint.set_idle_timeout twin): rearmed
   * on every arrival, expiry surfaces ETIMEDOUT — the pure-receiver
   * dead-peer detector fault configs rely on (faults.py) */
  int64_t idle_timeout_ns; /* 0 = off */
  PyObject *idle_timer; /* owned PyLong handle, or NULL */
  /* sender */
  int64_t chunk, cwnd, ssthresh, send_buffer, snd_nxt, snd_una, adv_wnd;
  int64_t buffered, bytes_acked;
  int64_t rto_backoff;
  int retries, loss_events;
  PyObject *rto_timer; /* owned PyLong handle, or NULL */
  Ring sendbuf; /* SQEnt */
  Ring rtx;     /* RtxEnt */
  /* receiver */
  int64_t recv_buffer, rcv_nxt, ooo_bytes, bytes_received, last_wnd;
  int dup_acks; /* consecutive duplicate acks (RFC 5681 counting) */
  /* SACK scoreboard + congestion-control seam (StreamSender twins):
   * sacked/rtx_done are int64 seq sets (tiny; linear membership),
   * sack_high the highest SACKed byte since the last RTO, recover the
   * recovery point, w_max/epoch_start the cubic epoch state */
  int cc_kind;
  int in_recovery;
  int64_t recover, sack_high, w_max, epoch_start;
  Ring sacked;   /* int64_t */
  Ring rtx_done; /* int64_t */
  Ring ooo; /* RtxEnt, kept seq-sorted (insertion) */
  PyObject *app_unread; /* callable or NULL */
  /* app callbacks (None when unset) */
  PyObject *on_connected, *on_data, *on_drain, *on_close, *on_error;
  /* C fast sink: when set, data delivery / drain / close route to the
   * C relay machinery instead of the Python callbacks */
  struct CRelayConn *sink;
  /* C tor-client sink (borrowed back-pointer; the sink owns the ep):
   * terminal frame parsing + DATA-body byte counting in C, one Python
   * callback per CONTROL cell (models/tor.py TorClient twin) */
  struct CTorSink *tsink;
  /* C tor-exit stream (OWNED; the stream borrows the ep back): counted
   * server bytes re-framed as circuit DATA cells in C (TorExit twin) */
  PyObject *xsink;
  /* C tgen app (models/tgen.py twin; same opt-in style as the relay
   * sink): 0 = none, 1 = server (parse the 8-byte ASCII request, push
   * counted bytes), 2 = client (count received bytes, fire tgen_cb at
   * completion). Replaces the per-row Python on_data / per-ack
   * on_drain closures with C state; only once-per-transfer events
   * (request seen, transfer complete) call back into Python. */
  int tgen_mode;
  int64_t tgen_pending; /* server: bytes left to push; client: received */
  int64_t tgen_want;    /* client: completion target */
  PyObject *tgen_cb;    /* server: on_request(want); client: cb(now, got) */
  /* telemetry (shadow_tpu/telemetry/): sim time of the first delivered
   * response byte in tgen client mode, -1 until one arrives — the exact
   * twin of the Python model's first-on_data capture (the flow record's
   * TTFB field reads it through the tgen_t_first getter) */
  int64_t tgen_t_first;
} CEp;

static PyTypeObject CEp_Type; /* fwd */
struct CRelayConn;
static int relay_feed(struct CRelayConn *rc, int64_t now, int64_t nbytes,
                      PyObject *payload);
static int relay_pump_conn(struct CRelayConn *rc, int64_t now);
static int relay_drain(struct CRelayConn *rc, int64_t now);
static int relay_conn_closed(struct CRelayConn *rc);

static CHost *cep_h(CEp *e) { return &e->core->hs[e->hid]; }

struct CTorSink;
static int tsink_feed(struct CTorSink *s, int64_t nbytes,
                      PyObject *payload);
static int tsink_pump(struct CTorSink *s, int64_t now);
struct CExitStream;
static int exit_feed(struct CExitStream *s, int64_t now, int64_t nbytes);

/* current sim clock of the owning host: used by timer-driven entry
 * points; row-driven entry points pass `now` explicitly */
static int64_t cep_now(CEp *e, int *err) {
  int64_t v;
  if (attr_i64(cep_h(e)->host, S_now, &v) < 0) { *err = 1; return 0; }
  *err = 0;
  return v;
}

static PyObject *S_schedule_in, *S_cancel_m, *S_rto_fire, *S_syn_fire,
    *S_fin_fire, *S_drop_fire, *S_idle_fire, *S_seq_ctr, *S_on_first;

static int64_t cep_window(CEp *e, int *err) {
  *err = 0;
  int64_t unread = 0;
  if (e->app_unread && e->app_unread != Py_None) {
    PyObject *r = PyObject_CallNoArgs(e->app_unread);
    if (!r) { *err = 1; return 0; }
    unread = PyLong_AsLongLong(r);
    Py_DECREF(r);
    if (unread == -1 && PyErr_Occurred()) { *err = 1; return 0; }
  }
  int64_t w = e->recv_buffer - e->ooo_bytes - unread;
  return w > 0 ? w : 0;
}

static int cep_emit(CEp *e, int64_t now, int kind, int64_t nbytes,
                    PyObject *payload, int64_t seq, int64_t acked,
                    int64_t wnd) {
  return core_emit_fields(
      e->core, cep_h(e), now, kind, e->remote_host, nbytes + HEADER,
      kind == TK_DATA ? nbytes : acked, payload,
      kind == TK_DATA ? seq : wnd, e->local_port, e->remote_port, 0, 1);
}

/* receiver._ack: round-barrier coalesced ack (Host.mark_ack twin) over
 * the cached identity-stable _ack_eps dict */
static int cep_mark_ack(CEp *e) {
  CHost *h = cep_h(e);
  PyObject *aeps = h->ack_eps;
  if (PyDict_GET_SIZE(aeps) == 0) {
    PyObject *al = PyObject_GetAttrString(e->core->plane, "ack_hosts");
    if (!al) return -1;
    int r = PyList_Append(al, h->host);
    Py_DECREF(al);
    if (r < 0) return -1;
  }
  return PyDict_SetItem(aeps, (PyObject *)e, Py_None);
}

/* timers ride the host's Python event queue so seq/order match the twin
 * — but the push itself runs here (EventQueue.push twin over the cached
 * heap/_live/_seq structures): at tor_100k scale the per-unit RTO
 * arm/cancel churn through two Python method calls was a first-order
 * cost of the host loop. The shared _seq counter keeps C and Python
 * pushes on one deterministic sequence. */
static int cep_schedule(CEp *e, int64_t delay, PyObject *meth_name,
                        PyObject **slot) {
  CHost *h = cep_h(e);
  int64_t now;
  if (attr_i64(h->host, S_now, &now) < 0) return -1;
  int64_t seq;
  if (attr_i64(h->equeue, S_seq_ctr, &seq) < 0) return -1;
  PyObject *task = PyObject_GetAttr((PyObject *)e, meth_name);
  if (!task) return -1;
  PyObject *seq_obj = PyLong_FromLongLong(seq);
  /* (time, band=BAND_APP, key=seq, seq, task) — schedule_in's default
   * band/key exactly (key < 0 resolves to seq) */
  PyObject *entry = seq_obj
      ? Py_BuildValue("(LiOOO)", (long long)(now + delay), 1, seq_obj,
                      seq_obj, task)
      : NULL;
  Py_DECREF(task);
  if (!entry) { Py_XDECREF(seq_obj); return -1; }
  int was_empty = PyList_GET_SIZE(h->heap) == 0;
  if (heap_push(h->heap, entry) < 0 ||
      PySet_Add(h->live, seq_obj) < 0 ||
      attr_set_i64(h->equeue, S_seq_ctr, seq + 1) < 0) {
    Py_DECREF(seq_obj);
    return -1;
  }
  if (was_empty) {
    PyObject *of = PyObject_GetAttr(h->equeue, S_on_first);
    if (!of) { Py_DECREF(seq_obj); return -1; }
    if (of != Py_None) {
      PyObject *r = PyObject_CallNoArgs(of);
      Py_DECREF(of);
      if (!r) { Py_DECREF(seq_obj); return -1; }
      Py_DECREF(r);
    } else {
      Py_DECREF(of);
    }
  }
  Py_XSETREF(*slot, seq_obj); /* the handle is the seq int, like push() */
  return 0;
}

static int cep_cancel_timer(CEp *e, PyObject **slot) {
  if (!*slot) return 0;
  CHost *h = cep_h(e);
  /* EventQueue.cancel twin: lazy-cancel iff still live */
  int live = PySet_Contains(h->live, *slot);
  if (live < 0) { Py_CLEAR(*slot); return -1; }
  if (live && PySet_Add(h->cancelled, *slot) < 0) {
    Py_CLEAR(*slot);
    return -1;
  }
  Py_CLEAR(*slot);
  return 0;
}

static int cs_pump(CEp *e, int64_t now);
static int64_t cs_send(CEp *e, int64_t now, int64_t nbytes,
                       PyObject *payload, int64_t off);
static int tgen_push(CEp *e, int64_t now);
static int ce_sender_drained(CEp *e, int64_t now);
static int ce_drop(CEp *e);
static int ce_reset(CEp *e, const char *reason);
static int ce_enter_time_wait(CEp *e, int64_t now);

static int cs_arm_rto(CEp *e, int reset) {
  if (reset && e->rto_timer) {
    if (cep_cancel_timer(e, &e->rto_timer) < 0) return -1;
  }
  if (!e->rto_timer)
    return cep_schedule(e, e->rto_ns * e->rto_backoff, S_rto_fire,
                        &e->rto_timer);
  return 0;
}

static int cs_emit_data(CEp *e, int64_t now, int64_t seq, int64_t nbytes,
                        PyObject *payload) {
  /* recovery comes entirely from duplicate acks, like the Python twin */
  return cep_emit(e, now, TK_DATA, nbytes, payload, seq, 0, 0);
}

static int cs_pump(CEp *e, int64_t now) {
  if (e->state != ST_ESTABLISHED && e->state != ST_CLOSING) return 0;
  int64_t window = e->adv_wnd > MSS_C ? e->adv_wnd : MSS_C;
  if (e->cwnd < window) window = e->cwnd;
  while (e->buffered > 0 && (e->snd_nxt - e->snd_una) < window) {
    int64_t inflight = e->snd_nxt - e->snd_una;
    int64_t usable = window - inflight;
    /* silly-window avoidance (transport.py pump) */
    if (usable < e->chunk && usable < e->buffered && inflight > 0) break;
    int64_t budget = usable < e->chunk ? usable : e->chunk;
    SQEnt *head = ring_at(&e->sendbuf, 0);
    int64_t nbytes = head->nbytes;
    PyObject *chunk_p = NULL;
    if (nbytes <= budget) {
      chunk_p = head->payload; /* transfer ownership */
      ring_popleft(&e->sendbuf);
    } else {
      if (head->payload && head->payload != Py_None) {
        chunk_p = PySequence_GetSlice(head->payload, 0, budget);
        PyObject *rest = PySequence_GetSlice(head->payload, budget,
                                             PyBytes_GET_SIZE(head->payload));
        if (!chunk_p || !rest) {
          Py_XDECREF(chunk_p); Py_XDECREF(rest);
          return -1;
        }
        Py_SETREF(head->payload, rest);
      }
      head->nbytes = nbytes - budget;
      nbytes = budget;
    }
    e->buffered -= nbytes;
    int64_t seq = e->snd_nxt;
    e->snd_nxt += nbytes;
    RtxEnt *re = ring_push(&e->rtx);
    if (!re) { Py_XDECREF(chunk_p); return -1; }
    re->seq = seq;
    re->n = nbytes;
    re->payload = chunk_p; /* owned (may be NULL) */
    if (cs_emit_data(e, now, seq, nbytes, chunk_p) < 0) return -1;
  }
  if (e->snd_nxt - e->snd_una > 0) {
    if (cs_arm_rto(e, 0) < 0) return -1;
  } else if (e->buffered == 0) {
    return ce_sender_drained(e, now);
  }
  return 0;
}

/* ---- congestion control (transport.py CongestionControl twins) --------- */
static void cc_on_ack(CEp *e, int64_t newly, int64_t now) {
  if (e->cwnd < e->ssthresh) {
    e->cwnd += newly < e->cwnd ? newly : e->cwnd; /* slow start (shared) */
    return;
  }
  if (e->cc_kind == CC_CUBIC) {
    if (e->epoch_start == 0) { /* first CA ack with no recorded epoch */
      e->epoch_start = now;
      e->w_max = e->cwnd;
    }
    int64_t t_ms = (now - e->epoch_start) / 1000000LL;
    int64_t wmax_c = e->w_max < (1LL << 32) ? e->w_max : (1LL << 32);
    int64_t k_ms = icbrt64((wmax_c * 3 / (4 * MSS_C)) * 1000000000LL);
    int64_t d = t_ms - k_ms;
    if (d > 200000) d = 200000;
    else if (d < -200000) d = -200000;
    int64_t a = d < 0 ? -d : d;
    int64_t delta = (a * a * a / 1000000LL) * (4 * MSS_C) / 10000LL;
    int64_t target = d < 0 ? e->w_max - delta : e->w_max + delta;
    if (target < MIN_CWND_C) target = MIN_CWND_C;
    else if (target > (1LL << 45)) target = 1LL << 45;
    int64_t nn = newly < (1LL << 20) ? newly : (1LL << 20);
    if (e->cwnd < target) {
      int64_t dd = target - e->cwnd;
      if (dd > (1LL << 40)) dd = 1LL << 40;
      int64_t inc = dd * nn / e->cwnd;
      int64_t nw = e->cwnd + (inc > 1 ? inc : 1);
      e->cwnd = nw < target ? nw : target;
    } else {
      int64_t inc = MSS_C * nn / (100 * e->cwnd);
      e->cwnd += inc > 1 ? inc : 1;
    }
    return;
  }
  int64_t add = MSS_C * newly / e->cwnd;
  e->cwnd += add > 1 ? add : 1; /* newreno AIMD */
}

static void cc_on_loss(CEp *e, int64_t now) {
  if (e->cc_kind == CC_CUBIC) {
    e->w_max = e->cwnd;
    e->epoch_start = now;
    int64_t nc = e->cwnd * 7 / 10;
    e->ssthresh = e->cwnd = nc > MIN_CWND_C ? nc : MIN_CWND_C;
    return;
  }
  int64_t inflight = e->snd_nxt - e->snd_una;
  e->ssthresh = inflight / 2 > MIN_CWND_C ? inflight / 2 : MIN_CWND_C;
  e->cwnd = e->cwnd / 2 > MIN_CWND_C ? e->cwnd / 2 : MIN_CWND_C;
}

static void cc_on_rto(CEp *e, int64_t now) {
  if (e->cc_kind == CC_CUBIC) {
    e->w_max = e->cwnd;
    e->epoch_start = now;
  }
  int64_t inflight = e->snd_nxt - e->snd_una;
  e->ssthresh = inflight / 2 > MIN_CWND_C ? inflight / 2 : MIN_CWND_C;
  e->cwnd = MIN_CWND_C;
}

/* ---- SACK scoreboard (StreamSender twins) ------------------------------ */
/* fold an ack's SACK blocks (big-endian u64 pairs in the payload) into
 * the scoreboard (StreamSender._apply_sack twin) */
static int cs_apply_sack(CEp *e, PyObject *payload) {
  if (!payload || !PyBytes_Check(payload)) return 0;
  const unsigned char *p = (const unsigned char *)PyBytes_AS_STRING(payload);
  Py_ssize_t len = PyBytes_GET_SIZE(payload);
  for (Py_ssize_t off = 0; off + 16 <= len; off += 16) {
    int64_t a = 0, b = 0;
    for (int i = 0; i < 8; i++) a = (a << 8) | p[off + i];
    for (int i = 0; i < 8; i++) b = (b << 8) | p[off + 8 + i];
    if (b > e->sack_high) e->sack_high = b;
    for (int i = 0; i < e->rtx.count; i++) {
      RtxEnt *re = ring_at(&e->rtx, i);
      if (re->seq >= b) break; /* rtx is seq-ascending */
      if (re->seq >= a && re->seq + re->n <= b) {
        if (i64set_add(&e->sacked, re->seq) < 0) return -1;
      }
    }
  }
  return 0;
}

/* retransmit every un-SACKed, not-yet-retransmitted hole below the
 * highest SACKed byte (StreamSender._retransmit_holes twin); returns
 * the emission count or -1 */
static int cs_retransmit_holes(CEp *e, int64_t now, int force_head) {
  int64_t hi = e->sack_high;
  int emitted = 0;
  for (int i = 0; i < e->rtx.count; i++) {
    RtxEnt *re = ring_at(&e->rtx, i);
    if (re->seq >= hi && !(force_head && i == 0)) break;
    if (i64set_has(&e->sacked, re->seq) ||
        i64set_has(&e->rtx_done, re->seq))
      continue;
    if (i64set_add(&e->rtx_done, re->seq) < 0) return -1;
    if (cs_emit_data(e, now, re->seq, re->n, re->payload) < 0) return -1;
    emitted++;
  }
  return emitted;
}

/* the fast-retransmit response (3rd consecutive duplicate ack):
 * multiplicative decrease + retransmit of every known hole + RTO reset
 * (StreamSender._enter_recovery twin) */
static int cs_enter_recovery(CEp *e, int64_t now) {
  e->loss_events++;
  if (e->core->faults_active) cep_h(e)->d_fast_retx++;
  e->in_recovery = 1;
  e->recover = e->snd_nxt;
  e->rtx_done.count = 0;
  cc_on_loss(e, now);
  int emitted = cs_retransmit_holes(e, now, 1);
  if (emitted < 0) return -1;
  if (emitted > 1 && e->core->faults_active)
    cep_h(e)->d_sack_retx += emitted - 1;
  return cs_arm_rto(e, 1);
}

static int cs_on_rto(CEp *e, int64_t now) {
  Py_CLEAR(e->rto_timer);
  if (e->snd_nxt - e->snd_una == 0 || e->state == ST_CLOSED ||
      e->state == ST_TIME_WAIT)
    return 0;
  if (e->adv_wnd > 0) e->retries++;
  if (e->retries > DATA_RETRIES_C) {
    if (e->core->faults_active) cep_h(e)->d_timeouts++;
    return ce_reset(e, "connection timed out (ETIMEDOUT): data retransmission retries exhausted");
  }
  if (e->core->faults_active) cep_h(e)->d_rto_retx++;
  /* scoreboard discarded (renege safety, StreamSender._on_rto twin) */
  e->sacked.count = 0;
  e->rtx_done.count = 0;
  e->sack_high = 0;
  e->in_recovery = 0;
  cc_on_rto(e, now);
  e->rto_backoff = e->rto_backoff * 2 > 64 ? 64 : e->rto_backoff * 2;
  RtxEnt *re = ring_at(&e->rtx, 0);
  if (cs_emit_data(e, now, re->seq, re->n, re->payload) < 0) return -1;
  return cs_arm_rto(e, 0);
}

static int cs_on_ack(CEp *e, int64_t now, int64_t cum_ack, int64_t wnd,
                     PyObject *sack) {
  int64_t prev_wnd = e->adv_wnd;
  e->adv_wnd = wnd;
  if (sack && cs_apply_sack(e, sack) < 0) return -1;
  if (cum_ack > e->snd_una) {
    e->dup_acks = 0;
    int64_t newly = cum_ack - e->snd_una;
    e->snd_una = cum_ack;
    e->bytes_acked += newly;
    while (e->rtx.count) {
      RtxEnt *re = ring_at(&e->rtx, 0);
      if (re->seq + re->n > cum_ack) break;
      Py_XDECREF(re->payload);
      ring_popleft(&e->rtx);
    }
    if (e->sacked.count) i64set_prune_below(&e->sacked, cum_ack);
    if (e->rtx_done.count) i64set_prune_below(&e->rtx_done, cum_ack);
    e->rto_backoff = 1;
    e->retries = 0;
    if (cep_cancel_timer(e, &e->rto_timer) < 0) return -1;
    if (e->snd_nxt - e->snd_una > 0) {
      if (cs_arm_rto(e, 0) < 0) return -1;
    }
    if (e->in_recovery) {
      if (e->snd_una >= e->recover) {
        e->in_recovery = 0;
        e->rtx_done.count = 0;
      } else {
        /* partial ack: NewReno head retransmit + newly exposed holes */
        int n = cs_retransmit_holes(e, now, 1);
        if (n < 0) return -1;
        if (n && e->core->faults_active) cep_h(e)->d_sack_retx += n;
      }
    }
    cc_on_ack(e, newly, now);
    if (e->sink && e->buffered < e->send_buffer) {
      if (relay_drain(e->sink, now) < 0) return -1;
    } else if (e->tsink && e->buffered < e->send_buffer) {
      /* the tor-client control plane's pending-write queue (the
       * _WriteConn on_drain pump twin) */
      if (tsink_pump(e->tsink, now) < 0) return -1;
    } else if (e->tgen_mode == 1 && e->buffered < e->send_buffer) {
      /* TGenServer on_drain twin (push is a no-op with no backlog,
       * exactly like the Python closure called with room) */
      if (tgen_push(e, now) < 0) return -1;
    } else if (e->on_drain && e->on_drain != Py_None &&
        e->buffered < e->send_buffer) {
      PyObject *room = PyLong_FromLongLong(e->send_buffer - e->buffered);
      if (!room) return -1;
      PyObject *r = PyObject_CallOneArg(e->on_drain, room);
      Py_DECREF(room);
      if (!r) return -1;
      Py_DECREF(r);
    }
  } else if (cum_ack == e->snd_una &&
             wnd == prev_wnd && e->snd_nxt - e->snd_una > 0 &&
             e->rtx.count) {
    /* duplicate ack (same cum, same window, data outstanding): 3rd
     * CONSECUTIVE one enters SACK recovery (StreamSender twin) */
    e->dup_acks++;
    if (e->dup_acks == 3 && !e->in_recovery) {
      if (cs_enter_recovery(e, now) < 0) return -1;
    } else if (e->in_recovery && sack) {
      /* later dup acks can expose new holes (higher sack_high) */
      int n = cs_retransmit_holes(e, now, 0);
      if (n < 0) return -1;
      if (n && e->core->faults_active) cep_h(e)->d_sack_retx += n;
    }
  } else {
    e->dup_acks = 0; /* anything else breaks the consecutive run */
  }
  return cs_pump(e, now);
}

/* ---- C tgen app (models/tgen.py twin) ---------------------------------- */
static int tgen_push(CEp *e, int64_t now) {
  /* TGenServer.push twin: offer the whole backlog; the bounded send
   * buffer accepts what fits, the rest streams out via the ack drain */
  if (e->tgen_pending <= 0) return 0;
  int64_t acc = cs_send(e, now, e->tgen_pending, NULL, 0);
  if (acc < 0) return -1;
  e->tgen_pending -= acc;
  return 0;
}

static int is_strip_ws(char ch) {
  /* str.strip()'s ASCII whitespace set (NUL etc. are NOT whitespace) */
  return ch == ' ' || ch == '\t' || ch == '\n' || ch == '\r' ||
         ch == '\v' || ch == '\f';
}

static int tgen_srv_data(CEp *e, int64_t now, PyObject *payload) {
  /* int(payload.decode().strip()) twin: want = 0 on any parse failure,
   * each payload chunk parsed independently (like the Python closure).
   * Exact for ASCII decimal (incl. sign and between-digit underscores)
   * up to int64; declared divergences from Python int(): values beyond
   * int64 and non-ASCII Unicode digits parse as 0 here (a request for
   * >9.2e18 counted bytes is non-physical, and the wire format —
   * str(size).encode() — never produces either). */
  int64_t want = 0;
  if (payload && PyBytes_Check(payload)) {
    const char *s = PyBytes_AS_STRING(payload);
    Py_ssize_t i = 0, j = PyBytes_GET_SIZE(payload);
    while (i < j && is_strip_ws(s[i])) i++;
    while (j > i && is_strip_ws(s[j - 1])) j--;
    Py_ssize_t k = i;
    int neg = 0, last_digit = 0, ok = k < j;
    if (k < j && (s[k] == '+' || s[k] == '-')) { neg = s[k] == '-'; k++; }
    if (k >= j) ok = 0;
    for (; ok && k < j; k++) {
      if (s[k] == '_') {
        /* Python: underscores only BETWEEN digits */
        if (!last_digit || k + 1 >= j) ok = 0;
        last_digit = 0;
        continue;
      }
      if (s[k] < '0' || s[k] > '9') { ok = 0; break; }
      if (want > (INT64_MAX - (s[k] - '0')) / 10) { ok = 0; break; }
      want = want * 10 + (s[k] - '0');
      last_digit = 1;
    }
    if (!ok || !last_digit) want = 0;
    else if (neg) want = -want;
  }
  if (want <= 0) return 0;
  if (e->tgen_cb && e->tgen_cb != Py_None) {
    PyObject *w = PyLong_FromLongLong(want);
    if (!w) return -1;
    PyObject *r = PyObject_CallOneArg(e->tgen_cb, w);
    Py_DECREF(w);
    if (!r) return -1;
    Py_DECREF(r);
  }
  e->tgen_pending += want;
  return tgen_push(e, now);
}

typedef struct { int64_t seq, n; } SackSeg;

static int cmp_sackseg(const void *a, const void *b) {
  int64_t x = ((const SackSeg *)a)->seq, y = ((const SackSeg *)b)->seq;
  return (x > y) - (x < y);
}

/* the receiver's SACK report (StreamReceiver.sack_payload twin): the
 * buffered OOO segments merged into contiguous [start, end) ranges, the
 * lowest SACK_MAX_BLOCKS_C of them, as big-endian u64 pairs. Returns a
 * new bytes ref, NULL with *err=0 when nothing is buffered (no
 * payload — every ack of a loss-free connection), NULL with *err=1 on
 * allocation failure. Byte-identical to the Python builder. */
static PyObject *cr_sack_payload(CEp *e, int *err) {
  *err = 0;
  int n = e->ooo.count;
  if (n == 0) return NULL;
  SackSeg stack_segs[32];
  SackSeg *segs = n <= 32 ? stack_segs
                          : malloc((size_t)n * sizeof(SackSeg));
  if (!segs) { PyErr_NoMemory(); *err = 1; return NULL; }
  for (int i = 0; i < n; i++) {
    RtxEnt *re = ring_at(&e->ooo, i);
    segs[i].seq = re->seq;
    segs[i].n = re->n;
  }
  qsort(segs, (size_t)n, sizeof(SackSeg), cmp_sackseg);
  unsigned char buf[SACK_MAX_BLOCKS_C * 16];
  int nb = 0, nblocks = 0;
  int64_t cs = segs[0].seq, ce = segs[0].seq + segs[0].n;
  for (int i = 1; i <= n && nblocks < SACK_MAX_BLOCKS_C; i++) {
    if (i < n && segs[i].seq == ce) {
      ce = segs[i].seq + segs[i].n;
      continue;
    }
    for (int k = 7; k >= 0; k--) buf[nb++] = (cs >> (8 * k)) & 0xff;
    for (int k = 7; k >= 0; k--) buf[nb++] = (ce >> (8 * k)) & 0xff;
    nblocks++;
    if (i < n) { cs = segs[i].seq; ce = segs[i].seq + segs[i].n; }
  }
  if (segs != stack_segs) free(segs);
  PyObject *r = PyBytes_FromStringAndSize((const char *)buf, nb);
  if (!r) *err = 1;
  return r;
}

/* out-of-order / duplicate / out-of-window data: real TCP acks
 * IMMEDIATELY (RFC 5681 §4.2 — dup acks drive the sender's
 * fast-retransmit counter). Supersedes any coalesced ack queued this
 * round (a same-cum barrier ack would inflate the dup count) — the
 * StreamReceiver._dup_ack twin. */
static int cep_dup_ack(CEp *e, int64_t now) {
  if (e->state == ST_CLOSED || e->state == ST_TIME_WAIT) return 0;
  CHost *h = cep_h(e);
  PyObject *aeps = h->ack_eps;
  int had = PyDict_Contains(aeps, (PyObject *)e);
  if (had < 0) return -1;
  if (had && PyDict_DelItem(aeps, (PyObject *)e) < 0) return -1;
  /* re-advertise last_wnd (NOT the recomputed window): buffering the
   * OOO segment shrinks window() every time, which would defeat the
   * sender's same-window dup test — see StreamReceiver._dup_ack */
  int err;
  PyObject *sp = cr_sack_payload(e, &err);
  if (err) return -1;
  int r = cep_emit(e, now, TK_ACK, 0, sp, 0, e->rcv_nxt, e->last_wnd);
  Py_XDECREF(sp);
  return r;
}

/* ---- receiver (StreamReceiver twin) ------------------------------------ */
static int cr_deliver(CEp *e, int64_t now, int64_t nbytes,
                      PyObject *payload) {
  e->rcv_nxt += nbytes;
  e->bytes_received += nbytes;
  if (e->tsink)
    return tsink_feed(e->tsink, nbytes, payload);
  if (e->xsink)
    return exit_feed((struct CExitStream *)e->xsink, now, nbytes);
  if (e->tgen_mode == 2) {
    if (e->tgen_t_first < 0) e->tgen_t_first = now;
    e->tgen_pending += nbytes;
    if (e->tgen_pending >= e->tgen_want && e->tgen_cb &&
        e->tgen_cb != Py_None) {
      PyObject *r = PyObject_CallFunction(e->tgen_cb, "LL", (long long)now,
                                          (long long)e->tgen_pending);
      if (!r) return -1;
      Py_DECREF(r);
    }
    return 0;
  }
  if (e->tgen_mode == 1)
    return tgen_srv_data(e, now, payload);
  if (e->sink)
    return relay_feed(e->sink, now, nbytes, payload);
  if (e->on_data && e->on_data != Py_None) {
    PyObject *nb = PyLong_FromLongLong(nbytes);
    PyObject *tn = PyLong_FromLongLong(now);
    if (!nb || !tn) { Py_XDECREF(nb); Py_XDECREF(tn); return -1; }
    PyObject *r = PyObject_CallFunctionObjArgs(
        e->on_data, nb, payload ? payload : Py_None, tn, NULL);
    Py_DECREF(nb);
    Py_DECREF(tn);
    if (!r) return -1;
    Py_DECREF(r);
  }
  return 0;
}

static int cr_ooo_find(CEp *e, int64_t seq) {
  for (int i = 0; i < e->ooo.count; i++)
    if (((RtxEnt *)ring_at(&e->ooo, i))->seq == seq) return i;
  return -1;
}

static int cr_on_data(CEp *e, int64_t now, int64_t seq, int64_t n,
                      PyObject *payload) {
  int err;
  if (seq + n <= e->rcv_nxt) return cep_dup_ack(e, now); /* duplicate */
  if (seq > e->rcv_nxt) {
    if (cr_ooo_find(e, seq) < 0) {
      int64_t w = cep_window(e, &err);
      if (err) return -1;
      if (n <= w) {
        RtxEnt *oe = ring_push(&e->ooo);
        if (!oe) return -1;
        oe->seq = seq;
        oe->n = n;
        Py_XINCREF(payload);
        oe->payload = payload;
        e->ooo_bytes += n;
      }
    }
    return cep_dup_ack(e, now); /* duplicate ack: rcv_nxt unchanged */
  }
  int64_t w = cep_window(e, &err);
  if (err) return -1;
  /* beyond-window probe: refuse + COALESCED re-advertisement (not a dup
   * ack — counting probe refusals toward fast retransmit would halve
   * cwnd during a stall where nothing was lost) */
  if (n > w) return cep_mark_ack(e);
  if (cr_deliver(e, now, n, payload) < 0) return -1;
  for (;;) {
    int i = cr_ooo_find(e, e->rcv_nxt);
    if (i < 0) break;
    RtxEnt cp = *(RtxEnt *)ring_at(&e->ooo, i);
    /* remove entry i (order within the ring is irrelevant) */
    *(RtxEnt *)ring_at(&e->ooo, i) =
        *(RtxEnt *)ring_at(&e->ooo, e->ooo.count - 1);
    e->ooo.count--;
    e->ooo_bytes -= cp.n;
    int r = cr_deliver(e, now, cp.n, cp.payload);
    Py_XDECREF(cp.payload);
    if (r < 0) return -1;
  }
  return cep_mark_ack(e);
}

/* ---- endpoint (StreamEndpoint twin) ------------------------------------ */
static int ce_cancel_ctl(CEp *e) { return cep_cancel_timer(e, &e->ctl_timer); }

static int ce_drop(CEp *e) {
  if (ce_cancel_ctl(e) < 0) return -1;
  if (cep_cancel_timer(e, &e->rto_timer) < 0) return -1;
  if (cep_cancel_timer(e, &e->idle_timer) < 0) return -1;
  e->state = ST_CLOSED;
  e->tsink = NULL; /* borrowed back-pointer; the sink still owns us */
  Py_CLEAR(e->xsink); /* the exit stream dies with its server conn */
  /* host.drop_endpoint twin: pop our four-tuple from the cached
   * identity-stable host._conns dict */
  PyObject *conns = cep_h(e)->conns;
  PyObject *key = Py_BuildValue("(iii)", e->local_port, e->remote_host,
                                e->remote_port);
  if (!key) return -1;
  if (PyDict_Contains(conns, key) == 1) {
    if (PyDict_DelItem(conns, key) < 0) { Py_DECREF(key); return -1; }
  }
  Py_DECREF(key);
  return 0;
}

static int ce_reset(CEp *e, const char *reason) {
  cep_h(e)->d_resets++;
  /* sink conns mirror the Python twin exactly: _reset only fires
   * on_error (unset for relay conns) and drops the endpoint — the
   * relay's conn/table entries go stale, with NO teardown cascade */
  PyObject *err_cb = e->on_error;
  Py_XINCREF(err_cb);
  if (ce_drop(e) < 0) { Py_XDECREF(err_cb); return -1; }
  if (err_cb && err_cb != Py_None) {
    PyObject *msg = PyUnicode_FromString(reason);
    if (!msg) { Py_DECREF(err_cb); return -1; }
    PyObject *r = PyObject_CallOneArg(err_cb, msg);
    Py_DECREF(msg);
    Py_DECREF(err_cb);
    if (!r) return -1;
    Py_DECREF(r);
  } else {
    Py_XDECREF(err_cb);
  }
  return 0;
}

static int ce_enter_time_wait(CEp *e, int64_t now) {
  if (e->state == ST_TIME_WAIT) return 0;
  int was_open = e->state == ST_ESTABLISHED || e->state == ST_CLOSING ||
                 e->state == ST_FIN_SENT;
  e->state = ST_TIME_WAIT;
  if (ce_cancel_ctl(e) < 0) return -1;
  if (cep_cancel_timer(e, &e->rto_timer) < 0) return -1;
  if (cep_cancel_timer(e, &e->idle_timer) < 0) return -1;
  /* schedule the final drop WITHOUT tracking a handle (Python twin
   * schedules self._drop unconditionally) */
  PyObject *tmp = NULL;
  if (cep_schedule(e, 2 * e->rto_ns, S_drop_fire, &tmp) < 0) return -1;
  Py_XDECREF(tmp);
  if (was_open && e->sink) return relay_conn_closed(e->sink);
  if (was_open && e->on_close && e->on_close != Py_None) {
    PyObject *tn = PyLong_FromLongLong(now);
    if (!tn) return -1;
    PyObject *r = PyObject_CallOneArg(e->on_close, tn);
    Py_DECREF(tn);
    if (!r) return -1;
    Py_DECREF(r);
  }
  return 0;
}

static int ce_send_fin(CEp *e, int64_t now) {
  e->fin_tries++;
  if (e->fin_tries > FIN_RETRIES_C) return ce_drop(e); /* orphan timeout */
  if (cep_emit(e, now, TK_FIN, 0, NULL, 0, 0, 0) < 0) return -1;
  int64_t mult = 1LL << (e->fin_tries - 1);
  if (mult > 64) mult = 64;
  return cep_schedule(e, e->rto_ns * mult, S_fin_fire, &e->ctl_timer);
}

static int ce_sender_drained(CEp *e, int64_t now) {
  if (e->peer_fin &&
      (e->state == ST_ESTABLISHED || e->state == ST_CLOSING)) {
    if (cep_emit(e, now, TK_FINACK, 0, NULL, 0, 0, 0) < 0) return -1;
    return ce_enter_time_wait(e, now);
  }
  if (e->state == ST_CLOSING) {
    e->state = ST_FIN_SENT;
    return ce_send_fin(e, now);
  }
  return 0;
}

static int ce_send_syn(CEp *e, int64_t now) {
  e->syn_tries++;
  if (e->syn_tries > SYN_RETRIES_C)
    return ce_reset(e, "connection timed out (ETIMEDOUT): SYN retries exhausted");
  int err;
  int64_t w = cep_window(e, &err);
  if (err) return -1;
  if (cep_emit(e, now, TK_SYN, 0, NULL, 0, 0, w) < 0) return -1;
  int64_t mult = 1LL << (e->syn_tries - 1);
  if (mult > 64) mult = 64;
  return cep_schedule(e, e->rto_ns * mult, S_syn_fire, &e->ctl_timer);
}

/* the unit-arrival dispatch (StreamEndpoint.handle_fields twin) */
static int ce_handle_fields(CEp *e, int64_t now, int k, int64_t nbytes,
                            PyObject *payload, int64_t seq) {
  int err;
  if (e->idle_timer) {
    /* any arrival proves the peer is alive (StreamEndpoint twin: the
     * rearm consumes one seq, exactly like _rearm_idle's schedule_in) */
    if (cep_cancel_timer(e, &e->idle_timer) < 0) return -1;
    if (cep_schedule(e, e->idle_timeout_ns, S_idle_fire,
                     &e->idle_timer) < 0)
      return -1;
  }
  if (k == TK_SYN) {
    if (e->state == ST_ESTABLISHED) { /* dup SYN: SYNACK was lost */
      e->adv_wnd = seq;
      int64_t w = cep_window(e, &err);
      if (err) return -1;
      return cep_emit(e, now, TK_SYNACK, 0, NULL, 0, 0, w);
    }
    return 0;
  }
  if (k == TK_SYNACK) {
    if (e->state == ST_SYN_SENT) {
      e->state = ST_ESTABLISHED;
      e->adv_wnd = seq;
      if (ce_cancel_ctl(e) < 0) return -1;
      if (e->on_connected && e->on_connected != Py_None) {
        PyObject *tn = PyLong_FromLongLong(now);
        if (!tn) return -1;
        PyObject *r = PyObject_CallOneArg(e->on_connected, tn);
        Py_DECREF(tn);
        if (!r) return -1;
        Py_DECREF(r);
      }
      return cs_pump(e, now);
    }
    return 0;
  }
  if (k == TK_DATA) {
    if (e->state == ST_CLOSED || e->state == ST_TIME_WAIT) return 0;
    cep_h(e)->d_sbytes_recv += nbytes;
    return cr_on_data(e, now, seq, nbytes, payload);
  }
  if (k == TK_ACK) {
    if (e->state == ST_CLOSED || e->state == ST_TIME_WAIT) return 0;
    return cs_on_ack(e, now, nbytes, seq, payload);
  }
  if (k == TK_FIN) {
    if (e->state == ST_SYN_SENT) {
      if (cep_emit(e, now, TK_FINACK, 0, NULL, 0, 0, 0) < 0) return -1;
      return ce_reset(e, "connection closed by peer");
    }
    if ((e->state == ST_ESTABLISHED || e->state == ST_CLOSING) &&
        (e->buffered > 0 || e->snd_nxt - e->snd_una > 0)) {
      e->peer_fin = 1; /* half-close: FINACK when drained */
      return 0;
    }
    if (cep_emit(e, now, TK_FINACK, 0, NULL, 0, 0, 0) < 0) return -1;
    if (e->state != ST_CLOSED) return ce_enter_time_wait(e, now);
    return 0;
  }
  if (k == TK_FINACK) {
    if (e->state == ST_FIN_SENT) {
      if (ce_cancel_ctl(e) < 0) return -1;
      if (e->sink) {
        if (ce_drop(e) < 0) return -1;
        return relay_conn_closed(e->sink);
      }
      PyObject *close_cb = e->on_close;
      Py_XINCREF(close_cb);
      if (ce_drop(e) < 0) { Py_XDECREF(close_cb); return -1; }
      if (close_cb && close_cb != Py_None) {
        PyObject *tn = PyLong_FromLongLong(now);
        if (!tn) { Py_DECREF(close_cb); return -1; }
        PyObject *r = PyObject_CallOneArg(close_cb, tn);
        Py_DECREF(tn);
        Py_DECREF(close_cb);
        if (!r) return -1;
        Py_DECREF(r);
      } else {
        Py_XDECREF(close_cb);
      }
    }
    return 0;
  }
  return 0;
}

/* ---- CEp Python surface ------------------------------------------------ */
static int CEp_traverse(CEp *e, visitproc visit, void *arg) {
  Py_VISIT(e->core);
  Py_VISIT(e->on_connected);
  Py_VISIT(e->on_data);
  Py_VISIT(e->on_drain);
  Py_VISIT(e->on_close);
  Py_VISIT(e->on_error);
  Py_VISIT(e->app_unread);
  Py_VISIT(e->tgen_cb);
  Py_VISIT(e->xsink);
  return 0;
}

static int CEp_clear_gc(CEp *e) {
  Py_CLEAR(e->core);
  Py_CLEAR(e->on_connected);
  Py_CLEAR(e->on_data);
  Py_CLEAR(e->on_drain);
  Py_CLEAR(e->on_close);
  Py_CLEAR(e->on_error);
  Py_CLEAR(e->app_unread);
  Py_CLEAR(e->tgen_cb);
  Py_CLEAR(e->xsink);
  return 0;
}

static void CEp_dealloc(CEp *e) {
  PyObject_GC_UnTrack(e);
  Py_XDECREF(e->core);
  Py_XDECREF(e->ctl_timer);
  Py_XDECREF(e->rto_timer);
  Py_XDECREF(e->idle_timer);
  for (int i = 0; i < e->sendbuf.count; i++)
    Py_XDECREF(((SQEnt *)ring_at(&e->sendbuf, i))->payload);
  for (int i = 0; i < e->rtx.count; i++)
    Py_XDECREF(((RtxEnt *)ring_at(&e->rtx, i))->payload);
  for (int i = 0; i < e->ooo.count; i++)
    Py_XDECREF(((RtxEnt *)ring_at(&e->ooo, i))->payload);
  free(e->sendbuf.buf);
  free(e->rtx.buf);
  free(e->ooo.buf);
  free(e->sacked.buf);
  free(e->rtx_done.buf);
  Py_XDECREF(e->app_unread);
  Py_XDECREF(e->on_connected);
  Py_XDECREF(e->on_data);
  Py_XDECREF(e->on_drain);
  Py_XDECREF(e->on_close);
  Py_XDECREF(e->on_error);
  Py_XDECREF(e->tgen_cb);
  Py_XDECREF(e->xsink);
  Py_TYPE(e)->tp_free((PyObject *)e);
}

/* app-side send (StreamEndpoint.send + StreamSender.queue twin).
   payload may be NULL (counted bytes); off slices a byte payload's tail.
   Returns accepted count, or -1 on error. */
static int64_t cs_send(CEp *e, int64_t now, int64_t nbytes,
                       PyObject *payload, int64_t off) {
  if (payload) nbytes = PyBytes_GET_SIZE(payload) - off;
  if (nbytes <= 0 || e->state == ST_CLOSING || e->state == ST_FIN_SENT ||
      e->state == ST_TIME_WAIT)
    return 0;
  int64_t room = e->send_buffer - e->buffered;
  int64_t accept = nbytes < room ? nbytes : (room > 0 ? room : 0);
  if (accept <= 0) return 0;
  SQEnt *q = ring_push(&e->sendbuf);
  if (!q) return -1;
  q->nbytes = accept;
  if (payload) {
    q->payload = PySequence_GetSlice(payload, off, off + accept);
    if (!q->payload) { e->sendbuf.count--; return -1; }
  } else {
    q->payload = NULL;
  }
  e->buffered += accept;
  if (cs_pump(e, now) < 0) return -1;
  cep_h(e)->d_sbytes_q += accept;
  return accept;
}

static PyObject *CEp_send(CEp *e, PyObject *args, PyObject *kw) {
  static char *kws[] = {"nbytes", "payload", NULL};
  long long nbytes = 0;
  PyObject *payload = Py_None;
  if (!PyArg_ParseTupleAndKeywords(args, kw, "|LO", kws, &nbytes, &payload))
    return NULL;
  int err;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  int64_t accepted = cs_send(e, now, nbytes,
                             payload == Py_None ? NULL : payload, 0);
  if (accepted < 0) return NULL;
  return PyLong_FromLongLong(accepted);
}

static PyObject *CEp_close(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (e->state == ST_CLOSED || e->state == ST_CLOSING ||
      e->state == ST_FIN_SENT || e->state == ST_TIME_WAIT)
    Py_RETURN_NONE;
  e->state = ST_CLOSING;
  int err;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  if (cs_pump(e, now) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_connect(CEp *e, PyObject *noarg) {
  (void)noarg;
  e->state = ST_SYN_SENT;
  int err;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  if (ce_send_syn(e, now) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_window(CEp *e, PyObject *noarg) {
  (void)noarg;
  int err;
  int64_t w = cep_window(e, &err);
  if (err) return NULL;
  return PyLong_FromLongLong(w);
}

static PyObject *CEp_flush_ack(CEp *e, PyObject *noarg) {
  (void)noarg;
  int err;
  e->last_wnd = cep_window(e, &err);
  if (err) return NULL;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  PyObject *sp = cr_sack_payload(e, &err);
  if (err) return NULL;
  int r = cep_emit(e, now, TK_ACK, 0, sp, 0, e->rcv_nxt, e->last_wnd);
  Py_XDECREF(sp);
  if (r < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_on_app_read(CEp *e, PyObject *noarg) {
  (void)noarg;
  int err;
  if (e->last_wnd < (e->recv_buffer >> 2) && e->state != ST_CLOSED &&
      e->state != ST_TIME_WAIT) {
    int64_t w = cep_window(e, &err);
    if (err) return NULL;
    if (w > e->last_wnd && cep_mark_ack(e) < 0) return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *CEp_handle_fields(CEp *e, PyObject *args) {
  long long k, nbytes, seq, now;
  PyObject *payload;
  if (!PyArg_ParseTuple(args, "LLOLL", &k, &nbytes, &payload, &seq, &now))
    return NULL;
  if (ce_handle_fields(e, now, (int)k, nbytes,
                       payload == Py_None ? NULL : payload, seq) < 0)
    return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_emit(CEp *e, PyObject *args, PyObject *kw) {
  static char *kws[] = {"kind", "nbytes", "payload", "seq", "acked", "wnd",
                        NULL};
  long long kind, nbytes = 0, seq = 0, acked = 0, wnd = 0;
  PyObject *payload = Py_None;
  if (!PyArg_ParseTupleAndKeywords(args, kw, "L|LOLLL", kws, &kind,
                                   &nbytes, &payload, &seq, &acked, &wnd))
    return NULL;
  int err;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  if (cep_emit(e, now, (int)kind, nbytes,
               payload == Py_None ? NULL : payload, seq, acked, wnd) < 0)
    return NULL;
  Py_RETURN_NONE;
}

/* timer entry points (scheduled on the host's Python event queue) */
static PyObject *CEp_rto_fire(CEp *e, PyObject *noarg) {
  (void)noarg;
  int err;
  int64_t now = cep_now(e, &err);
  if (err) return NULL;
  if (cs_on_rto(e, now) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_syn_fire(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (e->state == ST_SYN_SENT) {
    int err;
    int64_t now = cep_now(e, &err);
    if (err) return NULL;
    if (ce_send_syn(e, now) < 0) return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *CEp_fin_fire(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (e->state == ST_FIN_SENT) {
    int err;
    int64_t now = cep_now(e, &err);
    if (err) return NULL;
    if (ce_send_fin(e, now) < 0) return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject *CEp_drop_fire(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (ce_drop(e) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_idle_fire(CEp *e, PyObject *noarg) {
  /* StreamEndpoint._idle_expired twin */
  (void)noarg;
  Py_CLEAR(e->idle_timer);
  if (e->state == ST_CLOSED || e->state == ST_TIME_WAIT) Py_RETURN_NONE;
  if (e->core->faults_active) cep_h(e)->d_timeouts++;
  if (ce_reset(e, "connection timed out (ETIMEDOUT): idle timeout — no "
                  "traffic from peer") < 0)
    return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_set_idle_timeout(CEp *e, PyObject *arg) {
  /* StreamEndpoint.set_idle_timeout twin: arm (or disarm with 0/None) */
  int64_t t = 0;
  if (arg != Py_None) {
    t = PyLong_AsLongLong(arg);
    if (t == -1 && PyErr_Occurred()) return NULL;
  }
  if (cep_cancel_timer(e, &e->idle_timer) < 0) return NULL;
  e->idle_timeout_ns = t > 0 ? t : 0;
  if (e->idle_timeout_ns &&
      cep_schedule(e, e->idle_timeout_ns, S_idle_fire, &e->idle_timer) < 0)
    return NULL;
  Py_RETURN_NONE;
}

/* Host.crash teardown hooks (faults.py): the crash loop duck-types
 * ep._cancel_ctl() / ep.sender._cancel_rto() — identical disarm
 * semantics to the Python endpoint's private methods */
static PyObject *CEp_cancel_ctl_m(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (ce_cancel_ctl(e) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_cancel_rto_m(CEp *e, PyObject *noarg) {
  (void)noarg;
  if (cep_cancel_timer(e, &e->rto_timer) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CEp_fingerprint(CEp *e, PyObject *noarg) {
  /* StreamEndpoint.fingerprint twin for the determinism sentinel: the
   * SAME 28 fields in the same order with the same Python types (bools
   * stay bools — checkpoint._feed encodes them differently from ints),
   * so digest streams are identical with the C engine on and off */
  (void)noarg;
  PyObject *sk = i64set_sorted_tuple(&e->sacked);
  if (!sk) return NULL;
  PyObject *rd = i64set_sorted_tuple(&e->rtx_done);
  if (!rd) { Py_DECREF(sk); return NULL; }
  return Py_BuildValue(
      "(iOiiOLLLLLLiLiiLLLLLiLLiLLNN)", e->state,
      e->initiator ? Py_True : Py_False, e->syn_tries, e->fin_tries,
      e->peer_fin ? Py_True : Py_False, (long long)e->snd_nxt,
      (long long)e->snd_una, (long long)e->cwnd, (long long)e->ssthresh,
      (long long)e->adv_wnd, (long long)e->buffered, e->retries,
      (long long)e->rto_backoff, e->dup_acks, e->loss_events,
      (long long)e->bytes_acked, (long long)e->rcv_nxt,
      (long long)e->ooo_bytes, (long long)e->bytes_received,
      (long long)e->last_wnd,
      /* PR 9: SACK scoreboard + congestion-control seam state */
      e->cc_kind, (long long)e->w_max, (long long)e->epoch_start,
      e->in_recovery ? 1 : 0, (long long)e->recover,
      (long long)e->sack_high, sk, rd);
}

/* opt-in surface for the models/tgen.py fast path; Python-plane
 * endpoints don't have these attrs, so the model falls back to its
 * closure implementation (getattr probe) */
static PyObject *CEp_tgen_serve(CEp *e, PyObject *cb) {
  e->tgen_mode = 1;
  Py_INCREF(cb);
  Py_XSETREF(e->tgen_cb, cb);
  Py_RETURN_NONE;
}

static PyObject *CEp_tgen_client(CEp *e, PyObject *args) {
  long long want;
  PyObject *cb;
  if (!PyArg_ParseTuple(args, "LO", &want, &cb)) return NULL;
  e->tgen_mode = 2;
  e->tgen_want = want;
  e->tgen_pending = 0;
  e->tgen_t_first = -1;
  Py_INCREF(cb);
  Py_XSETREF(e->tgen_cb, cb);
  Py_RETURN_NONE;
}

static PyObject *CEp_get_self(CEp *e, void *u) {
  (void)u;
  Py_INCREF(e);
  return (PyObject *)e;
}

#define CB_GETSET(name)                                       \
  static PyObject *CEp_get_##name(CEp *e, void *u) {          \
    (void)u;                                                  \
    PyObject *v = e->name ? e->name : Py_None;                \
    Py_INCREF(v);                                             \
    return v;                                                 \
  }                                                           \
  static int CEp_set_##name(CEp *e, PyObject *v, void *u) {   \
    (void)u;                                                  \
    Py_XINCREF(v);                                            \
    Py_XSETREF(e->name, v);                                   \
    return 0;                                                 \
  }
CB_GETSET(on_connected)
CB_GETSET(on_data)
CB_GETSET(on_drain)
CB_GETSET(on_close)
CB_GETSET(on_error)
CB_GETSET(app_unread)

#define I64_GETSET(name)                                      \
  static PyObject *CEp_get_##name(CEp *e, void *u) {          \
    (void)u;                                                  \
    return PyLong_FromLongLong(e->name);                      \
  }                                                           \
  static int CEp_set_##name(CEp *e, PyObject *v, void *u) {   \
    (void)u;                                                  \
    int64_t x = PyLong_AsLongLong(v);                         \
    if (x == -1 && PyErr_Occurred()) return -1;               \
    e->name = x;                                              \
    return 0;                                                 \
  }
I64_GETSET(adv_wnd)
I64_GETSET(buffered)
I64_GETSET(send_buffer)
I64_GETSET(recv_buffer)
I64_GETSET(bytes_acked)
I64_GETSET(bytes_received)
I64_GETSET(rcv_nxt)
I64_GETSET(snd_una)
I64_GETSET(snd_nxt)
I64_GETSET(cwnd)
I64_GETSET(rto_ns)
/* telemetry samplers (shadow_tpu/telemetry/collector.py) read the same
 * sender-state fields the Python twin exposes on StreamSender */
I64_GETSET(ssthresh)
I64_GETSET(rto_backoff)

static PyObject *CEp_get_retries(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->retries);
}

static PyObject *CEp_get_tgen_t_first(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLongLong(e->tgen_t_first);
}

static PyObject *CEp_get_state(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->state);
}
static int CEp_set_state(CEp *e, PyObject *v, void *u) {
  (void)u;
  long x = PyLong_AsLong(v);
  if (x == -1 && PyErr_Occurred()) return -1;
  e->state = (int)x;
  return 0;
}
static PyObject *CEp_get_local_port(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->local_port);
}
static PyObject *CEp_get_remote_host(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->remote_host);
}
static PyObject *CEp_get_remote_port(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->remote_port);
}
static PyObject *CEp_get_loss_events(CEp *e, void *u) {
  (void)u;
  return PyLong_FromLong(e->loss_events);
}

static PyGetSetDef CEp_getset[] = {
    {"sender", (getter)CEp_get_self, NULL, "sender half (self)", NULL},
    {"receiver", (getter)CEp_get_self, NULL, "receiver half (self)", NULL},
    {"state", (getter)CEp_get_state, (setter)CEp_set_state, NULL, NULL},
    {"on_connected", (getter)CEp_get_on_connected,
     (setter)CEp_set_on_connected, NULL, NULL},
    {"on_data", (getter)CEp_get_on_data, (setter)CEp_set_on_data, NULL,
     NULL},
    {"on_drain", (getter)CEp_get_on_drain, (setter)CEp_set_on_drain, NULL,
     NULL},
    {"on_close", (getter)CEp_get_on_close, (setter)CEp_set_on_close, NULL,
     NULL},
    {"on_error", (getter)CEp_get_on_error, (setter)CEp_set_on_error, NULL,
     NULL},
    {"app_unread", (getter)CEp_get_app_unread, (setter)CEp_set_app_unread,
     NULL, NULL},
    {"adv_wnd", (getter)CEp_get_adv_wnd, (setter)CEp_set_adv_wnd, NULL,
     NULL},
    {"buffered", (getter)CEp_get_buffered, (setter)CEp_set_buffered, NULL,
     NULL},
    {"send_buffer", (getter)CEp_get_send_buffer,
     (setter)CEp_set_send_buffer, NULL, NULL},
    {"recv_buffer", (getter)CEp_get_recv_buffer,
     (setter)CEp_set_recv_buffer, NULL, NULL},
    {"bytes_acked", (getter)CEp_get_bytes_acked,
     (setter)CEp_set_bytes_acked, NULL, NULL},
    {"bytes_received", (getter)CEp_get_bytes_received,
     (setter)CEp_set_bytes_received, NULL, NULL},
    {"rcv_nxt", (getter)CEp_get_rcv_nxt, (setter)CEp_set_rcv_nxt, NULL,
     NULL},
    {"snd_una", (getter)CEp_get_snd_una, (setter)CEp_set_snd_una, NULL,
     NULL},
    {"snd_nxt", (getter)CEp_get_snd_nxt, (setter)CEp_set_snd_nxt, NULL,
     NULL},
    {"cwnd", (getter)CEp_get_cwnd, (setter)CEp_set_cwnd, NULL, NULL},
    {"rto_ns", (getter)CEp_get_rto_ns, (setter)CEp_set_rto_ns, NULL, NULL},
    {"local_port", (getter)CEp_get_local_port, NULL, NULL, NULL},
    {"remote_host", (getter)CEp_get_remote_host, NULL, NULL, NULL},
    {"remote_port", (getter)CEp_get_remote_port, NULL, NULL, NULL},
    {"loss_events", (getter)CEp_get_loss_events, NULL, NULL, NULL},
    {"ssthresh", (getter)CEp_get_ssthresh, (setter)CEp_set_ssthresh, NULL,
     NULL},
    {"rto_backoff", (getter)CEp_get_rto_backoff,
     (setter)CEp_set_rto_backoff, NULL, NULL},
    {"retries", (getter)CEp_get_retries, NULL, NULL, NULL},
    {"tgen_t_first", (getter)CEp_get_tgen_t_first, NULL, NULL, NULL},
    {NULL, NULL, NULL, NULL, NULL}};

static PyObject *CEp_export_state(CEp *e, PyObject *noarg);
static PyObject *CEp_restore_state(CEp *e, PyObject *state);

static PyMethodDef CEp_methods[] = {
    {"send", (PyCFunction)CEp_send, METH_VARARGS | METH_KEYWORDS, NULL},
    {"close", (PyCFunction)CEp_close, METH_NOARGS, NULL},
    {"connect", (PyCFunction)CEp_connect, METH_NOARGS, NULL},
    {"window", (PyCFunction)CEp_window, METH_NOARGS, NULL},
    {"flush_ack", (PyCFunction)CEp_flush_ack, METH_NOARGS, NULL},
    {"on_app_read", (PyCFunction)CEp_on_app_read, METH_NOARGS, NULL},
    {"handle_fields", (PyCFunction)CEp_handle_fields, METH_VARARGS, NULL},
    {"emit", (PyCFunction)CEp_emit, METH_VARARGS | METH_KEYWORDS, NULL},
    {"tgen_serve", (PyCFunction)CEp_tgen_serve, METH_O,
     "(on_request) -> None  enable the C TGenServer data path"},
    {"tgen_client", (PyCFunction)CEp_tgen_client, METH_VARARGS,
     "(want, on_complete) -> None  enable the C TGenClient data path"},
    {"_rto_fire", (PyCFunction)CEp_rto_fire, METH_NOARGS, NULL},
    {"_syn_fire", (PyCFunction)CEp_syn_fire, METH_NOARGS, NULL},
    {"_fin_fire", (PyCFunction)CEp_fin_fire, METH_NOARGS, NULL},
    {"_drop_fire", (PyCFunction)CEp_drop_fire, METH_NOARGS, NULL},
    {"_idle_fire", (PyCFunction)CEp_idle_fire, METH_NOARGS, NULL},
    {"set_idle_timeout", (PyCFunction)CEp_set_idle_timeout, METH_O,
     "arm (or disarm with 0/None) the idle timeout (transport.py twin)"},
    {"_cancel_ctl", (PyCFunction)CEp_cancel_ctl_m, METH_NOARGS, NULL},
    {"_cancel_rto", (PyCFunction)CEp_cancel_rto_m, METH_NOARGS, NULL},
    {"fingerprint", (PyCFunction)CEp_fingerprint, METH_NOARGS,
     "StreamEndpoint.fingerprint twin (determinism sentinel)"},
    {"_export_state", (PyCFunction)CEp_export_state, METH_NOARGS,
     "checkpoint export: full protocol state as a plain tuple"},
    {"_restore_state", (PyCFunction)CEp_restore_state, METH_O,
     "checkpoint restore: fill an orphan endpoint from _export_state"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject CEp_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.Endpoint",
    .tp_basicsize = sizeof(CEp),
    .tp_dealloc = (destructor)CEp_dealloc,
    /* GC-tracked: the app callbacks ALWAYS form cycles through the
     * endpoint (app holds ep, ep.on_data closes over app) — without
     * traverse/clear every churned connection would leak (review r4) */
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)CEp_traverse,
    .tp_clear = (inquiry)CEp_clear_gc,
    .tp_methods = CEp_methods,
    .tp_getset = CEp_getset,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C stream endpoint (network/transport.py twin)",
};

/* factory shared by Python (Host._make_endpoint) and the C SYN accept */
static CEp *cep_new(CoreObject *c, int hid, int lport, int rhost, int rport,
                    int initiator, int64_t sbuf, int64_t rbuf, int cc) {
  CEp *e = PyObject_GC_New(CEp, &CEp_Type);
  if (!e) return NULL;
  memset(((char *)e) + sizeof(PyObject), 0, sizeof(CEp) - sizeof(PyObject));
  Py_INCREF(c);
  e->core = c;
  e->hid = hid;
  e->local_port = lport;
  e->remote_host = rhost;
  e->remote_port = rport;
  e->initiator = initiator;
  e->state = ST_CLOSED;
  e->cwnd = INIT_CWND_C;
  e->ssthresh = 1LL << 62;
  e->adv_wnd = INIT_CWND_C;
  e->rto_backoff = 1;
  e->tgen_t_first = -1;
  e->cc_kind = cc;
  e->send_buffer = sbuf;
  e->recv_buffer = rbuf;
  e->last_wnd = rbuf;
  e->chunk = c->unit_chunk;
  e->sacked.esz = sizeof(int64_t);
  e->rtx_done.esz = sizeof(int64_t);
  e->sendbuf.esz = sizeof(SQEnt);
  e->rtx.esz = sizeof(RtxEnt);
  e->ooo.esz = sizeof(RtxEnt);
  int32_t sn = c->hostnode[hid], dn = c->hostnode[rhost];
  int64_t rtt = c->lat[(int64_t)sn * c->G + dn] +
                c->lat[(int64_t)dn * c->G + sn];
  /* cap BEFORE doubling: rtt can be 2x INF_I64 on a cut path and 2*rtt
   * would overflow int64 (the Python twin computes in big ints) */
  e->rto_ns = rtt > RTO_MAX_NS_C / 2 ? RTO_MAX_NS_C
              : (2 * rtt > RTO_MIN_NS_C ? 2 * rtt : RTO_MIN_NS_C);
  PyObject_GC_Track((PyObject *)e);
  return e;
}

static PyObject *Core_make_endpoint(CoreObject *c, PyObject *args) {
  long long hid, lport, rhost, rport, sbuf, rbuf, cc = 0;
  int initiator;
  if (!PyArg_ParseTuple(args, "LLLLpLL|L", &hid, &lport, &rhost, &rport,
                        &initiator, &sbuf, &rbuf, &cc))
    return NULL;
  if (hid < 0 || hid >= c->H || rhost < 0 || rhost >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  return (PyObject *)cep_new(c, (int)hid, (int)lport, (int)rhost,
                             (int)rport, initiator, sbuf, rbuf, (int)cc);
}

/* the barrier's coalesced-ack flush loop (colplane._barrier_round twin):
 * `arg` is the id-sorted ack_hosts list; each host's _ack_eps snapshot
 * flushes one cumulative ACK per open endpoint and the dict clears IN
 * PLACE (identity-stable — cep_mark_ack caches it). */
static PyObject *Core_flush_acks(CoreObject *c, PyObject *arg) {
  if (!PyList_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "flush_acks expects a list of hosts");
    return NULL;
  }
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(arg); i++) {
    PyObject *host = PyList_GET_ITEM(arg, i);
    int64_t hid;
    if (attr_i64(host, S_id, &hid) < 0) return NULL;
    if (hid < 0 || hid >= c->H) {
      PyErr_SetString(PyExc_ValueError, "host id out of range");
      return NULL;
    }
    CHost *h = &c->hs[hid];
    if (PyDict_GET_SIZE(h->ack_eps) == 0) continue;
    PyObject *keys = PyDict_Keys(h->ack_eps); /* insertion-order snapshot */
    if (!keys) return NULL;
    PyDict_Clear(h->ack_eps);
    int64_t now = 0;
    int have_now = 0;
    for (Py_ssize_t j = 0; j < PyList_GET_SIZE(keys); j++) {
      PyObject *ep = PyList_GET_ITEM(keys, j);
      if (Py_TYPE(ep) == &CEp_Type) {
        CEp *e = (CEp *)ep;
        if (e->state == ST_CLOSED) continue;
        int err;
        e->last_wnd = cep_window(e, &err);
        if (err) { Py_DECREF(keys); return NULL; }
        if (!have_now) { /* one clock read per host: flushes never move it */
          now = cep_now(e, &err);
          if (err) { Py_DECREF(keys); return NULL; }
          have_now = 1;
        }
        PyObject *sp = cr_sack_payload(e, &err);
        if (err) { Py_DECREF(keys); return NULL; }
        int remit = cep_emit(e, now, TK_ACK, 0, sp, 0, e->rcv_nxt,
                             e->last_wnd);
        Py_XDECREF(sp);
        if (remit < 0) {
          Py_DECREF(keys);
          return NULL;
        }
      } else {
        /* pcap-host Python endpoint: the twin's attribute path */
        PyObject *st = PyObject_GetAttrString(ep, "state");
        if (!st) { Py_DECREF(keys); return NULL; }
        long sv = PyLong_AsLong(st);
        Py_DECREF(st);
        if (sv == -1 && PyErr_Occurred()) { Py_DECREF(keys); return NULL; }
        if (sv == 0) continue; /* CLOSED */
        PyObject *recv = PyObject_GetAttrString(ep, "receiver");
        PyObject *r = recv
            ? PyObject_CallMethod(recv, "flush_ack", NULL) : NULL;
        Py_XDECREF(recv);
        if (!r) { Py_DECREF(keys); return NULL; }
        Py_DECREF(r);
      }
    }
    Py_DECREF(keys);
  }
  Py_RETURN_NONE;
}

static PyObject *Core_run_round(CoreObject *c, PyObject *args) {
  long long end_ll;
  if (!PyArg_ParseTuple(args, "L", &end_ll)) return NULL;
  int64_t end = end_ll;
  if (!c->active) {
    PyErr_SetString(PyExc_RuntimeError, "bind_active() not called");
    return NULL;
  }
  /* sorted active host ids (host-id execution order). The snapshot is
   * CACHED across rounds: membership only shrinks inside this function
   * (which updates the cache in place) and only grows elsewhere, so a
   * set-size match proves the cache is exact and the per-round
   * iterate + qsort — the dominant cost at 10k mostly-parked hosts —
   * is skipped. */
  TM0(6);
  if (act_refresh(c) < 0) return NULL;
  TM1(6);
  tm_cnt[7] += c->act_n;
  int64_t executed = 0;
  int64_t *ids = c->act_ids;
  int64_t k = c->act_n;
  int64_t w = 0; /* write index: survivors stay, discards compact away */
  int64_t i = 0;
  for (; i < k; i++) {
    int64_t hid = ids[i];
    if (hid < 0 || hid >= c->H) continue;
    CHost *h = &c->hs[hid];
    int has_inbox = h->py_mode ? 0 : (h->inbox_n > 0);
    Py_ssize_t hn = PyList_GET_SIZE(h->heap);
    int heap_due = 0;
    if (hn) {
      /* owned-root cache: same object at heap[0] => same (conservative)
       * head time; most parked hosts cost three pointer reads here */
      PyObject *head = PyList_GET_ITEM(h->heap, 0);
      if (head != h->head_cache) {
        Py_INCREF(head);
        Py_XSETREF(h->head_cache, head);
        h->head_time = tup_i64(head, 0);
      }
      heap_due = h->head_time < end; /* conservative (cancelled ok) */
    }
    if (h->py_mode) {
      /* pcap hosts etc.: the Python run_events consumes _inbox lists */
      PyObject *ib = PyObject_GetAttr(h->host, S_inbox);
      int has_py_inbox = ib && ib != Py_None;
      Py_XDECREF(ib);
      if (!has_py_inbox && !heap_due) {
        if (!hn) {
          if (PySet_Discard(c->active, h->id_obj) < 0) goto fail;
          continue; /* compacted out of the snapshot */
        }
        ids[w++] = hid;
        continue;
      }
      PyObject *r = PyObject_CallMethodObjArgs(
          h->host, S_run_events, PyTuple_GET_ITEM(args, 0), NULL);
      if (!r) goto fail;
      executed += PyLong_AsLongLong(r);
      Py_DECREF(r);
      if (PyErr_Occurred()) goto fail;
    } else if (has_inbox || heap_due) {
      int64_t n = run_host_c(c, h, (int)hid, end);
      if (n < 0) goto fail;
      executed += n;
    }
    if (PyList_GET_SIZE(h->heap) == 0) {
      if (PySet_Discard(c->active, h->id_obj) < 0) goto fail;
    } else {
      ids[w++] = hid;
    }
  }
  c->act_n = w;
  return PyLong_FromLongLong(executed);
fail:
  /* keep the untouched tail so the cache still mirrors the set */
  for (; i < k; i++) ids[w++] = ids[i];
  c->act_n = w;
  return NULL;
}


/* ---- stream row dispatch (Host.dispatch_row / _deliver_row twin) ------- */
static int dispatch_stream(CoreObject *c, CHost *h, int hid, IRow *ir,
                           int64_t *now, int *now_dirty) {
  int k = ir->kind;
  PyObject *pl = ir->payload;
  /* data-plane row: clock + ingress charge, then deliver. The clock
   * attr syncs up front — endpoint handlers arm timers via
   * host.schedule_in, which reads host._now. */
  if (ir->t > *now) { *now = ir->t; *now_dirty = 1; }
  if (*now_dirty) {
    if (attr_set_i64(h->host, S_now, *now) < 0) return -1;
    *now_dirty = 0;
  }
  if (ir->t >= c->bootstrap_end) {
    if (c->tokens_down[hid] >= ir->size) {
      c->tokens_down[hid] -= ir->size;
    } else {
      PyObject *dl = PyObject_GetAttr(h->host, S_ingress_deferred_rows);
      if (!dl) return -1;
      PyObject *row = irow_tuple(h, ir, hid);
      if (!row) { Py_DECREF(dl); return -1; }
      int r = PyList_Append(dl, row);
      Py_DECREF(row);
      Py_DECREF(dl);
      if (r < 0) return -1;
      if (PySet_Add(c->deferred, h->host) < 0) return -1;
      return 0;
    }
  }
  h->d_delivered++;
  PyObject *key = Py_BuildValue("(iii)", ir->bport, ir->peer, ir->aport);
  if (!key) return -1;
  PyObject *ep = PyDict_GetItem(h->conns, key);
  if (!ep) {
    if (k != TK_SYN) {
      Py_DECREF(key);
      h->d_unroutable++;
      return 0;
    }
    PyObject *pk = PyLong_FromLong(ir->bport);
    if (!pk) { Py_DECREF(key); return -1; }
    PyObject *on_accept = PyDict_GetItem(h->listeners, pk);
    Py_DECREF(pk);
    if (!on_accept) {
      Py_DECREF(key);
      h->d_unroutable++;
      return 0;
    }
    CEp *ne = cep_new(c, hid, ir->bport, ir->peer, ir->aport, 0,
                      c->sock_sbuf, c->sock_rbuf, h->cc_kind);
    if (!ne) { Py_DECREF(key); return -1; }
    ne->state = ST_ESTABLISHED;
    ne->adv_wnd = ir->seq; /* client window rides the SYN */
    int rset = PyDict_SetItem(h->conns, key, (PyObject *)ne);
    Py_DECREF(key);
    if (rset < 0) { Py_DECREF(ne); return -1; }
    int err;
    int64_t w = cep_window(ne, &err);
    if (err) { Py_DECREF(ne); return -1; }
    if (cep_emit(ne, *now, TK_SYNACK, 0, NULL, 0, 0, w) < 0) {
      Py_DECREF(ne);
      return -1;
    }
    /* on_accept(ep, t) — Python app callback */
    if (*now_dirty) {
      if (attr_set_i64(h->host, S_now, *now) < 0) { Py_DECREF(ne); return -1; }
      *now_dirty = 0;
    }
    PyObject *tn = PyLong_FromLongLong(*now);
    if (!tn) { Py_DECREF(ne); return -1; }
    PyObject *r = PyObject_CallFunctionObjArgs(on_accept, (PyObject *)ne,
                                               tn, NULL);
    Py_DECREF(tn);
    Py_DECREF(ne);
    if (!r) return -1;
    Py_DECREF(r);
    if (attr_i64(h->host, S_now, now) < 0) return -1;
    return 0;
  }
  Py_DECREF(key);
  if (Py_TYPE(ep) == &CEp_Type)
    return ce_handle_fields((CEp *)ep, *now, k, ir->nbytes, pl, ir->seq);
  /* Python endpoint on a C-dispatched host (shouldn't happen in
   * practice, but stay correct): sync the clock and delegate */
  if (*now_dirty) {
    if (attr_set_i64(h->host, S_now, *now) < 0) return -1;
    *now_dirty = 0;
  }
  PyObject *r = PyObject_CallMethod(ep, "handle_fields", "(LLOLL)",
                                    (long long)k, (long long)ir->nbytes,
                                    pl ? pl : Py_None, (long long)ir->seq,
                                    (long long)*now);
  if (!r) return -1;
  Py_DECREF(r);
  if (attr_i64(h->host, S_now, now) < 0) return -1;
  return 0;
}

/* ======================================================================
 * C tor-relay data path (models/tor.py TorRelay twin for the hot flow).
 *
 * The plain relay's steady state is: framed cells arrive on one C
 * endpoint, the circuit table maps (conn, circ) to the spliced peer,
 * and the cell/body forwards out the peer connection — all here, zero
 * Python. The control plane stays Python via one callback (on_ctrl):
 * EXTEND at the circuit head (opens a new connection through the
 * simulated network) — everything else (CREATE/CREATED handshakes,
 * forwarding, teardown cascades, DATA headers, counted bodies) is C.
 * Exits (TorExit) keep the full Python model. Bit-identity with the
 * Python relay is asserted by the colcore A/B suite on the tor config.
 * ====================================================================== */

#define TCELL_HDR 12
#define TC_CREATE 0
#define TC_CREATED 1
#define TC_EXTEND 2
#define TC_EXTENDED 3
#define TC_BEGIN 4
#define TC_DATA 6
#define TC_END 7

typedef struct { PyObject *payload; int64_t a; } PendEnt;
/* payload != NULL: byte frame, a = send offset; NULL: counted, a = left */

typedef struct CRelayConn {
  struct CRelayObj *relay; /* borrowed: relay owns conns[] */
  CEp *ep;                 /* owned */
  int cid;
  int close_after_drain;
  /* re-entrancy guard: a teardown cascade reached from inside this
   * conn's own feed/pump (peer_fin unwinding) must not free it while
   * its frames are on the C stack */
  int busy, dead;
  /* FrameReader state */
  char *buf;
  int64_t buf_len, buf_cap;
  int64_t body_left;
  int body_circ;
  Ring pend; /* PendEnt */
} CRelayConn;

typedef struct CRelayObj {
  PyObject_HEAD
  CoreObject *core; /* owned */
  int hid;
  PyObject *on_ctrl; /* Python callable(cid, ctype, circ, payload) */
  CRelayConn **conns;
  int nconns, conns_cap;
  /* circuit table: open addressing, key = (cid<<32)|circ (+1 so 0 =
   * empty), val = (ncid<<32)|ncirc */
  uint64_t *tk, *tv, *ts; /* keys, values, insertion seq (dict order) */
  uint64_t tseq;
  int tcap, tcount;
  int next_circ;
  int exit_mode; /* BEGIN at the circuit endpoint reaches on_ctrl */
  int64_t cells_relayed, bytes_relayed;
} CRelayObj;

static PyTypeObject CRelay_Type;

/* -- circuit table ------------------------------------------------------- */
static int rtab_grow(CRelayObj *r) {
  int ncap = r->tcap ? r->tcap * 2 : 64;
  uint64_t *nk = calloc((size_t)ncap, sizeof(uint64_t));
  uint64_t *nv = malloc((size_t)ncap * sizeof(uint64_t));
  uint64_t *ns = malloc((size_t)ncap * sizeof(uint64_t));
  if (!nk || !nv || !ns) {
    free(nk); free(nv); free(ns);
    PyErr_NoMemory();
    return -1;
  }
  for (int i = 0; i < r->tcap; i++) {
    if (!r->tk[i]) continue;
    uint64_t h = r->tk[i] * 0x9E3779B97F4A7C15ULL;
    int j = (int)(h & (uint64_t)(ncap - 1));
    while (nk[j]) j = (j + 1) & (ncap - 1);
    nk[j] = r->tk[i];
    nv[j] = r->tv[i];
    ns[j] = r->ts[i];
  }
  free(r->tk);
  free(r->tv);
  free(r->ts);
  r->tk = nk;
  r->tv = nv;
  r->ts = ns;
  r->tcap = ncap;
  return 0;
}

static inline uint64_t rtab_key(int cid, int circ) {
  return (((uint64_t)(uint32_t)cid << 32) | (uint32_t)circ) + 1;
}

static int rtab_get(CRelayObj *r, int cid, int circ, int *ncid, int *ncirc) {
  if (!r->tcap) return 0;
  uint64_t k = rtab_key(cid, circ);
  uint64_t h = k * 0x9E3779B97F4A7C15ULL;
  int i = (int)(h & (uint64_t)(r->tcap - 1));
  while (r->tk[i]) {
    if (r->tk[i] == k) {
      *ncid = (int)(r->tv[i] >> 32);
      *ncirc = (int)(uint32_t)r->tv[i];
      return 1;
    }
    i = (i + 1) & (r->tcap - 1);
  }
  return 0;
}

static int rtab_put(CRelayObj *r, int cid, int circ, int ncid, int ncirc) {
  if (r->tcount * 10 >= r->tcap * 7 && rtab_grow(r) < 0) return -1;
  uint64_t k = rtab_key(cid, circ);
  uint64_t h = k * 0x9E3779B97F4A7C15ULL;
  int i = (int)(h & (uint64_t)(r->tcap - 1));
  while (r->tk[i] && r->tk[i] != k) i = (i + 1) & (r->tcap - 1);
  if (!r->tk[i]) {
    r->tcount++;
    r->ts[i] = r->tseq++; /* dict insertion order; overwrite keeps it */
  }
  r->tk[i] = k;
  r->tv[i] = ((uint64_t)(uint32_t)ncid << 32) | (uint32_t)ncirc;
  return 0;
}

/* -- frames -------------------------------------------------------------- */
static PyObject *build_cell(int ctype, int circ, const char *payload,
                            Py_ssize_t plen);

/* a DATA header announcing `body_len` counted bytes (the len field
 * describes the FOLLOWING body, not an inline payload) */
static PyObject *build_data_hdr(int circ, int64_t body_len) {
  PyObject *hdr = build_cell(TC_DATA, circ, NULL, 0);
  if (!hdr) return NULL;
  char *hp = PyBytes_AS_STRING(hdr);
  hp[3] = (char)((body_len >> 8) & 0xFF);
  hp[4] = (char)(body_len & 0xFF);
  return hdr;
}

static PyObject *build_cell(int ctype, int circ, const char *payload,
                            Py_ssize_t plen) {
  PyObject *b = PyBytes_FromStringAndSize(NULL, TCELL_HDR + plen);
  if (!b) return NULL;
  char *p = PyBytes_AS_STRING(b);
  memset(p, 0, TCELL_HDR);
  p[0] = (char)ctype;
  p[1] = (char)((circ >> 8) & 0xFF);
  p[2] = (char)(circ & 0xFF);
  p[3] = (char)(((uint64_t)plen >> 8) & 0xFF);
  p[4] = (char)((uint64_t)plen & 0xFF);
  if (plen) memcpy(p + TCELL_HDR, payload, (size_t)plen);
  return b;
}

/* -- pending write queue (models/tor.py _Conn twin) ---------------------- */
/* graceful-close idiom shared by CEp_close and the relay teardown
 * paths: no-op unless the endpoint is in an open state */
static int cep_begin_close(CEp *e, int64_t now) {
  if (e->state == ST_CLOSED || e->state == ST_CLOSING ||
      e->state == ST_FIN_SENT || e->state == ST_TIME_WAIT)
    return 0;
  e->state = ST_CLOSING;
  return cs_pump(e, now);
}

static void relay_free_conn(CRelayConn *rc) {
  free(rc->buf);
  for (int i = 0; i < rc->pend.count; i++)
    Py_XDECREF(((PendEnt *)ring_at(&rc->pend, i))->payload);
  free(rc->pend.buf);
  Py_DECREF(rc->ep);
  free(rc);
}

/* detach a conn from its relay; honors the busy guard (an on-stack
 * feed/pump frame frees it at exit instead) */
static void relay_detach_conn(CRelayObj *r, int cid) {
  CRelayConn *rc = r->conns[cid];
  if (!rc) return;
  r->conns[cid] = NULL;
  rc->ep->sink = NULL;
  if (rc->busy)
    rc->dead = 1;
  else
    relay_free_conn(rc);
}

static int relay_pump_conn(CRelayConn *rc, int64_t now) {
  int rcod = 0;
  rc->busy++;
  while (!rc->dead && rc->pend.count) {
    PendEnt *head = ring_at(&rc->pend, 0);
    int64_t sent;
    int done;
    if (head->payload) {
      sent = cs_send(rc->ep, now, 0, head->payload, head->a);
      if (sent < 0) { rcod = -1; goto out; }
      if (rc->dead) goto out; /* send unwound into our own teardown */
      head->a += sent;
      done = head->a >= PyBytes_GET_SIZE(head->payload);
    } else {
      sent = cs_send(rc->ep, now, head->a, NULL, 0);
      if (sent < 0) { rcod = -1; goto out; }
      if (rc->dead) goto out;
      head->a -= sent;
      done = head->a <= 0;
    }
    if (done) {
      Py_XDECREF(head->payload);
      ring_popleft(&rc->pend);
    }
    if (sent == 0 && !done) goto out; /* buffer full; drain resumes */
  }
out:
  if (--rc->busy == 0 && rc->dead) { relay_free_conn(rc); return rcod; }
  return rcod;
}

/* the DRAIN entry point (ack freed buffer space): pump, then act on a
 * deferred close — the Python twin's close_when_drained only closes
 * from a subsequent on_drain, never from the write path's own pump */
static int relay_drain(CRelayConn *rc, int64_t now) {
  rc->busy++;
  int rcod = relay_pump_conn(rc, now);
  if (rcod == 0 && !rc->dead && rc->close_after_drain &&
      rc->pend.count == 0) {
    rc->close_after_drain = 0;
    rcod = cep_begin_close(rc->ep, now);
  }
  if (--rc->busy == 0 && rc->dead) relay_free_conn(rc);
  return rcod;
}

static int relay_write(CRelayConn *rc, int64_t now, PyObject *frame) {
  PendEnt *p = ring_push(&rc->pend);
  if (!p) { Py_DECREF(frame); return -1; }
  p->payload = frame; /* steals */
  p->a = 0;
  return relay_pump_conn(rc, now);
}

static int relay_write_counted(CRelayConn *rc, int64_t now, int64_t n) {
  PendEnt *p = ring_push(&rc->pend);
  if (!p) return -1;
  p->payload = NULL;
  p->a = n;
  return relay_pump_conn(rc, now);
}

/* -- the hot feed (FrameReader + TorRelay forwarding twin) --------------- */
static int relay_on_cell(CRelayObj *r, CRelayConn *rc, int64_t now,
                         int ctype, int circ, const char *pl,
                         Py_ssize_t plen) {
  if (ctype == TC_CREATE) {
    PyObject *f = build_cell(TC_CREATED, circ, NULL, 0);
    if (!f) return -1;
    return relay_write(rc, now, f);
  }
  int ncid, ncirc;
  int hit = rtab_get(r, rc->cid, circ, &ncid, &ncirc);
  if (ctype == TC_CREATED) {
    if (hit && r->conns[ncid]) {
      PyObject *f = build_cell(TC_EXTENDED, ncirc, NULL, 0);
      if (!f) return -1;
      return relay_write(r->conns[ncid], now, f);
    }
    return 0;
  }
  if ((ctype == TC_EXTEND || (r->exit_mode && ctype == TC_BEGIN))
      && !hit) {
    /* circuit head (EXTEND) or exit termination (BEGIN): the control
     * plane — connecting through the simulated network — is Python's */
    PyObject *plo = PyBytes_FromStringAndSize(pl, plen);
    if (!plo) return -1;
    PyObject *res = PyObject_CallFunction(r->on_ctrl, "(iiiO)", rc->cid,
                                          ctype, circ, plo);
    Py_DECREF(plo);
    if (!res) return -1;
    Py_DECREF(res);
    return 0;
  }
  if (!hit || !r->conns[ncid]) return 0; /* no route: drop (twin) */
  r->cells_relayed++;
  PyObject *f = build_cell(ctype, ncirc, pl, plen);
  if (!f) return -1;
  return relay_write(r->conns[ncid], now, f);
}

static int relay_feed(CRelayConn *rc, int64_t now, int64_t nbytes,
                      PyObject *payload) {
  CRelayObj *r = rc->relay;
  if (rc->body_left > 0 && (!payload || payload == Py_None)) {
    int64_t take = nbytes < rc->body_left ? nbytes : rc->body_left;
    rc->body_left -= take;
    int ncid, ncirc;
    if (rtab_get(r, rc->cid, rc->body_circ, &ncid, &ncirc) &&
        r->conns[ncid]) {
      r->bytes_relayed += take;
      rc->busy++;
      int w = relay_write_counted(r->conns[ncid], now, take);
      if (--rc->busy == 0 && rc->dead) { relay_free_conn(rc); return w; }
      if (w < 0 || rc->dead) return w;
    }
    if (nbytes > take) {
      PyErr_SetString(PyExc_ValueError,
                      "framing error: stray counted bytes");
      return -1;
    }
    return 0;
  }
  if (!payload || payload == Py_None) {
    PyErr_SetString(PyExc_ValueError,
                    "framing error: counted bytes outside DATA body");
    return -1;
  }
  char *pb;
  Py_ssize_t pn;
  if (PyBytes_AsStringAndSize(payload, &pb, &pn) < 0) return -1;
  if (rc->buf_len + pn > rc->buf_cap) {
    int64_t ncap = rc->buf_cap ? rc->buf_cap * 2 : 256;
    while (ncap < rc->buf_len + pn) ncap *= 2;
    char *nb = realloc(rc->buf, (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    rc->buf = nb;
    rc->buf_cap = ncap;
  }
  memcpy(rc->buf + rc->buf_len, pb, (size_t)pn);
  rc->buf_len += pn;
  int64_t off = 0;
  int rcod = 0;
  rc->busy++;
  while (!rc->dead && rc->buf_len - off >= TCELL_HDR) {
    unsigned char *b = (unsigned char *)rc->buf + off;
    int ctype = b[0];
    int circ = ((int)b[1] << 8) | b[2];
    int64_t ln = ((int64_t)b[3] << 8) | b[4];
    if (ctype == TC_DATA) {
      off += TCELL_HDR;
      rc->body_left = ln;
      rc->body_circ = circ;
      /* forward the DATA header along the circuit (on_data_hdr twin) */
      int ncid, ncirc;
      if (rtab_get(r, rc->cid, circ, &ncid, &ncirc) && r->conns[ncid]) {
        PyObject *f = build_data_hdr(ncirc, ln);
        if (!f) { rcod = -1; break; }
        if (relay_write(r->conns[ncid], now, f) < 0) { rcod = -1; break; }
      }
      break; /* counted body follows in subsequent chunks */
    }
    if (rc->buf_len - off < TCELL_HDR + ln) break;
    if (relay_on_cell(r, rc, now, ctype, circ,
                      rc->buf + off + TCELL_HDR, (Py_ssize_t)ln) < 0) {
      rcod = -1;
      break;
    }
    off += TCELL_HDR + ln;
  }
  if (!rc->dead && off) {
    memmove(rc->buf, rc->buf + off, (size_t)(rc->buf_len - off));
    rc->buf_len -= off;
  }
  if (--rc->busy == 0 && rc->dead) relay_free_conn(rc);
  return rcod;
}

/* -- teardown cascade (relay _on_conn_close twin) ------------------------ */
static int cmp_peer_seq(const void *a, const void *b) {
  uint64_t x = ((const uint64_t *)a)[0], y = ((const uint64_t *)b)[0];
  return (x > y) - (x < y);
}

static int relay_conn_closed(CRelayConn *rc) {
  CRelayObj *r = rc->relay;
  int cid = rc->cid;
  if (rc->dead) return 0; /* already torn down (re-entrant cascade) */
  r->conns[cid] = NULL;
  rc->ep->sink = NULL;
  /* spliced peers whose KEY side is this cid, in table insertion order
   * (the Python twin iterates its dict) */
  int rcod = 0;
  uint64_t(*peers)[2] =
      malloc(sizeof(uint64_t[2]) * (size_t)(r->tcount ? r->tcount : 1));
  int npeers = 0;
  if (!peers) { PyErr_NoMemory(); rcod = -1; }
  for (int i = 0; rcod == 0 && i < r->tcap; i++) {
    if (!r->tk[i]) continue;
    int kcid = (int)((r->tk[i] - 1) >> 32);
    if (kcid == cid) {
      peers[npeers][0] = r->ts[i];
      peers[npeers][1] = r->tv[i] >> 32;
      npeers++;
    }
  }
  if (npeers > 1)
    qsort(peers, (size_t)npeers, sizeof(uint64_t[2]), cmp_peer_seq);
  /* rebuild the table without entries touching cid, preserving each
   * surviving entry's insertion seq (Python dict-comprehension rebuild
   * keeps the original order) */
  if (rcod == 0) {
    uint64_t *ok = r->tk, *ov = r->tv, *os = r->ts;
    int ocap = r->tcap;
    r->tk = NULL;
    r->tv = NULL;
    r->ts = NULL;
    r->tcap = r->tcount = 0;
    for (int i = 0; i < ocap && rcod == 0; i++) {
      if (!ok[i]) continue;
      int kcid = (int)((ok[i] - 1) >> 32);
      int vcid = (int)(ov[i] >> 32);
      if (kcid == cid || vcid == cid) continue;
      if (rtab_put(r, kcid, (int)(uint32_t)(ok[i] - 1), vcid,
                   (int)(uint32_t)ov[i]) < 0) {
        rcod = -1;
        break;
      }
      /* restore the original seq (rtab_put assigned a fresh one) */
      uint64_t k = ok[i];
      uint64_t h = k * 0x9E3779B97F4A7C15ULL;
      int j = (int)(h & (uint64_t)(r->tcap - 1));
      while (r->tk[j] != k) j = (j + 1) & (r->tcap - 1);
      r->ts[j] = os[i];
    }
    free(ok);
    free(ov);
    free(os);
  }
  int err = 0;
  int64_t now = 0;
  if (rcod == 0) {
    now = cep_now(rc->ep, &err);
    if (err) rcod = -1;
  }
  for (int i = 0; i < npeers && rcod == 0; i++) {
    CRelayConn *pc = r->conns[(int)peers[i][1]];
    if (!pc) continue;
    if (pc->pend.count) {
      pc->close_after_drain = 1;
    } else {
      rcod = cep_begin_close(pc->ep, now);
    }
  }
  free(peers);
  /* free now unless feed/pump frames for this conn are on the stack */
  if (rc->busy)
    rc->dead = 1;
  else
    relay_free_conn(rc);
  return rcod;
}

/* -- the Python-visible CRelay type -------------------------------------- */
static void CRelay_dealloc(CRelayObj *r) {
  PyObject_GC_UnTrack(r);
  for (int i = 0; i < r->nconns; i++) relay_detach_conn(r, i);
  free(r->conns);
  free(r->tk);
  free(r->tv);
  free(r->ts);
  Py_XDECREF(r->core);
  Py_XDECREF(r->on_ctrl);
  Py_TYPE(r)->tp_free((PyObject *)r);
}

static int CRelay_traverse(CRelayObj *r, visitproc visit, void *arg) {
  Py_VISIT(r->core);
  Py_VISIT(r->on_ctrl);
  for (int i = 0; i < r->nconns; i++)
    if (r->conns[i]) Py_VISIT(r->conns[i]->ep);
  return 0;
}

static int CRelay_clear_gc(CRelayObj *r) {
  Py_CLEAR(r->core);
  Py_CLEAR(r->on_ctrl);
  /* a GC collection can run from allocations INSIDE relay_feed (e.g.
   * build_cell), so the busy guard matters here exactly as on the
   * runtime teardown paths (review r4) */
  for (int i = 0; i < r->nconns; i++) relay_detach_conn(r, i);
  return 0;
}

static PyObject *CRelay_add_conn(CRelayObj *r, PyObject *arg) {
  if (Py_TYPE(arg) != &CEp_Type) {
    PyErr_SetString(PyExc_TypeError, "add_conn expects a C endpoint");
    return NULL;
  }
  if (r->nconns == r->conns_cap) {
    int ncap = r->conns_cap ? r->conns_cap * 2 : 16;
    CRelayConn **nc = realloc(r->conns,
                              (size_t)ncap * sizeof(CRelayConn *));
    if (!nc) return PyErr_NoMemory();
    r->conns = nc;
    r->conns_cap = ncap;
  }
  CRelayConn *rc = calloc(1, sizeof(CRelayConn));
  if (!rc) return PyErr_NoMemory();
  rc->relay = r;
  Py_INCREF(arg);
  rc->ep = (CEp *)arg;
  rc->cid = r->nconns;
  rc->pend.esz = sizeof(PendEnt);
  r->conns[r->nconns++] = rc;
  ((CEp *)arg)->sink = rc;
  return PyLong_FromLong(rc->cid);
}

static PyObject *CRelay_splice(CRelayObj *r, PyObject *args) {
  int cid, circ, ncid;
  if (!PyArg_ParseTuple(args, "iii", &cid, &circ, &ncid)) return NULL;
  int ncirc = r->next_circ++;
  if (rtab_put(r, cid, circ, ncid, ncirc) < 0) return NULL;
  if (rtab_put(r, ncid, ncirc, cid, circ) < 0) return NULL;
  return PyLong_FromLong(ncirc);
}

static PyObject *CRelay_write_cell(CRelayObj *r, PyObject *args) {
  int cid, ctype, circ;
  Py_buffer pl = {0};
  if (!PyArg_ParseTuple(args, "iii|y*", &cid, &ctype, &circ, &pl))
    return NULL;
  if (cid < 0 || cid >= r->nconns || !r->conns[cid]) {
    PyBuffer_Release(&pl);
    Py_RETURN_NONE; /* connection already gone */
  }
  PyObject *f = build_cell(ctype, circ, pl.buf, pl.len);
  PyBuffer_Release(&pl);
  if (!f) return NULL;
  int err;
  int64_t now = cep_now(r->conns[cid]->ep, &err);
  if (err) return NULL;
  if (relay_write(r->conns[cid], now, f) < 0) return NULL;
  Py_RETURN_NONE;
}

/* ---- C tor-exit stream (TorExit data path) -----------------------------
 * Attached to the exit's SERVER-side connection: every counted chunk the
 * destination streams back is re-framed as a circuit DATA cell (header +
 * counted body) toward the client, entirely in C; at `want` bytes the
 * server connection closes and an END cell terminates the fetch — the
 * exact order of the Python twin (models/tor.py TorExit._on_cell). The
 * endpoint OWNS the stream (ep->xsink); the stream borrows the ep. */
typedef struct CExitStream {
  PyObject_HEAD
  CEp *ep;          /* borrowed: the owner */
  CRelayObj *relay; /* owned */
  int cid, circ;
  int done;
  int64_t want, got;
} CExitStream;

static PyTypeObject CExitStream_Type;

static int exit_feed(CExitStream *s, int64_t now, int64_t nbytes) {
  CRelayObj *r = s->relay;
  CRelayConn *rc = (s->cid >= 0 && s->cid < r->nconns)
                       ? r->conns[s->cid] : NULL;
  if (rc) {
    PyObject *hdr = build_data_hdr(s->circ, nbytes);
    if (!hdr) return -1;
    if (relay_write(rc, now, hdr) < 0) return -1;
    rc = (s->cid < r->nconns) ? r->conns[s->cid] : NULL; /* may close */
    if (rc && relay_write_counted(rc, now, nbytes) < 0) return -1;
  }
  s->got += nbytes;
  if (s->got >= s->want && !s->done) {
    s->done = 1;
    if (cep_begin_close(s->ep, now) < 0) return -1;
    rc = (s->cid >= 0 && s->cid < r->nconns) ? r->conns[s->cid] : NULL;
    if (rc) {
      PyObject *endc = build_cell(TC_END, s->circ, NULL, 0);
      if (!endc) return -1;
      if (relay_write(rc, now, endc) < 0) return -1;
    }
  }
  return 0;
}

static int CExitStream_traverse(CExitStream *s, visitproc visit,
                                void *arg) {
  Py_VISIT(s->relay);
  return 0;
}

static int CExitStream_clear_gc(CExitStream *s) {
  Py_CLEAR(s->relay);
  return 0;
}

static void CExitStream_dealloc(CExitStream *s) {
  PyObject_GC_UnTrack(s);
  Py_XDECREF(s->relay);
  Py_TYPE(s)->tp_free((PyObject *)s);
}

static PyObject *CExitStream_export_state(CExitStream *s, PyObject *noarg);
static PyObject *CExitStream_restore_state(CExitStream *s, PyObject *state);

static PyMethodDef CExitStream_methods[] = {
    {"_export_state", (PyCFunction)CExitStream_export_state, METH_NOARGS,
     "checkpoint export (the owning endpoint re-links `ep` on restore)"},
    {"_restore_state", (PyCFunction)CExitStream_restore_state, METH_O,
     "checkpoint restore"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject CExitStream_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.ExitStream",
    .tp_basicsize = sizeof(CExitStream),
    .tp_dealloc = (destructor)CExitStream_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)CExitStream_traverse,
    .tp_clear = (inquiry)CExitStream_clear_gc,
    .tp_methods = CExitStream_methods,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C tor-exit reframe stream (models/tor.py TorExit twin)",
};

static PyObject *CRelay_exit_stream(CRelayObj *r, PyObject *args) {
  PyObject *ep_o;
  int cid, circ;
  long long want;
  if (!PyArg_ParseTuple(args, "OiiL", &ep_o, &cid, &circ, &want))
    return NULL;
  if (Py_TYPE(ep_o) != &CEp_Type) {
    PyErr_SetString(PyExc_TypeError, "exit_stream expects a C endpoint");
    return NULL;
  }
  CEp *e = (CEp *)ep_o;
  CExitStream *s = PyObject_GC_New(CExitStream, &CExitStream_Type);
  if (!s) return NULL;
  memset(((char *)s) + sizeof(PyObject), 0,
         sizeof(CExitStream) - sizeof(PyObject));
  s->ep = e;
  Py_INCREF(r);
  s->relay = r;
  s->cid = cid;
  s->circ = circ;
  s->want = want;
  PyObject_GC_Track((PyObject *)s);
  Py_XSETREF(e->xsink, (PyObject *)s); /* the ep owns the stream */
  Py_RETURN_NONE;
}

static PyObject *CRelay_stats(CRelayObj *r, PyObject *noarg) {
  (void)noarg;
  return Py_BuildValue("(LL)", (long long)r->cells_relayed,
                       (long long)r->bytes_relayed);
}

static PyObject *CRelay_export_state(CRelayObj *r, PyObject *noarg);
static PyObject *CRelay_restore_state(CRelayObj *r, PyObject *state);

static PyMethodDef CRelay_methods[] = {
    {"_export_state", (PyCFunction)CRelay_export_state, METH_NOARGS,
     "checkpoint export: conns + circuit table + counters"},
    {"_restore_state", (PyCFunction)CRelay_restore_state, METH_O,
     "checkpoint restore (core binding comes via Core.adopt)"},
    {"add_conn", (PyCFunction)CRelay_add_conn, METH_O,
     "attach a C endpoint as a relay connection -> cid"},
    {"splice", (PyCFunction)CRelay_splice, METH_VARARGS,
     "(cid, circ, ncid) -> ncirc; inserts both circuit-table directions"},
    {"write_cell", (PyCFunction)CRelay_write_cell, METH_VARARGS,
     "(cid, ctype, circ[, payload]) -> queue a control cell"},
    {"exit_stream", (PyCFunction)CRelay_exit_stream, METH_VARARGS,
     "(endpoint, cid, circ, want) -> attach the C exit reframe stream"},
    {"stats", (PyCFunction)CRelay_stats, METH_NOARGS,
     "-> (cells_relayed, bytes_relayed)"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject CRelay_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.Relay",
    .tp_basicsize = sizeof(CRelayObj),
    .tp_dealloc = (destructor)CRelay_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)CRelay_traverse,
    .tp_clear = (inquiry)CRelay_clear_gc,
    .tp_methods = CRelay_methods,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C tor-relay data path (models/tor.py delegates)",
};

static PyObject *Core_relay_new(CoreObject *c, PyObject *args) {
  long long hid;
  PyObject *on_ctrl;
  int exit_mode = 0;
  if (!PyArg_ParseTuple(args, "LO|p", &hid, &on_ctrl, &exit_mode))
    return NULL;
  if (hid < 0 || hid >= c->H) {
    PyErr_SetString(PyExc_ValueError, "host id out of range");
    return NULL;
  }
  CRelayObj *r = PyObject_GC_New(CRelayObj, &CRelay_Type);
  if (!r) return NULL;
  memset(((char *)r) + sizeof(PyObject), 0,
         sizeof(CRelayObj) - sizeof(PyObject));
  Py_INCREF(c);
  r->core = c;
  r->hid = (int)hid;
  Py_INCREF(on_ctrl);
  r->on_ctrl = on_ctrl;
  r->next_circ = 1;
  r->exit_mode = exit_mode;
  PyObject_GC_Track((PyObject *)r);
  return (PyObject *)r;
}

/* ======================================================================
 * C tor-client sink (models/tor.py TorClient data path + control plane).
 *
 * The client's steady state is receiving a stream of DATA cells +
 * counted bodies through its guard connection; this sink owns the frame
 * parsing and body-byte counting in C. Since the circuit-build control
 * plane moved native, it ALSO runs the telescoping state machine: the
 * model hands it the three pre-built advance frames (EXTEND hop2,
 * EXTEND hop3, BEGIN) at creation, and each CREATED/EXTENDED cell
 * advances the stage and writes the next frame through a C pending
 * queue (the bounded-send discipline of the Python twin's _Conn pump).
 * Python sees exactly TWO events per circuit — on_cell fires for the
 * stage-3 EXTENDED (telescoping done; the model records build time) and
 * for END (fetch complete) — instead of every control cell plus every
 * advance write. At tor_100k scale (100,000 clients) this removes the
 * remaining per-circuit Python control-cell handling the same way the
 * relay data path did for relays. Without frames (None) the sink is the
 * pure data path: on_cell fires for every control cell and the model
 * keeps writing through its own conn.
 * ====================================================================== */

typedef struct CTorSink {
  PyObject_HEAD
  CEp *ep;            /* owned; ep->tsink is the borrowed back-pointer */
  PyObject *on_cell;  /* owned: callable(ctype, circ, payload, got) */
  PyObject *frames;   /* owned tuple of 3 advance frames, or NULL */
  int stage;          /* CREATED/EXTENDED cells consumed (twin: stage) */
  Ring pend;          /* PendEnt write queue (_WriteConn pending twin) */
  char *buf;
  int64_t buf_len, buf_cap;
  int64_t body_left;
  int64_t got; /* counted DATA body bytes received (circuit-agnostic,
                  like the Python twin's on_body) */
} CTorSink;

static PyTypeObject CTorSink_Type;

/* the _Conn._pump twin over the C pending ring: offer each frame to the
 * bounded send buffer; a short write parks and resumes on drain */
static int tsink_pump(CTorSink *s, int64_t now) {
  while (s->pend.count) {
    PendEnt *head = ring_at(&s->pend, 0);
    int64_t sent = cs_send(s->ep, now, 0, head->payload, head->a);
    if (sent < 0) return -1;
    head->a += sent;
    int done = head->a >= PyBytes_GET_SIZE(head->payload);
    if (done) {
      Py_XDECREF(head->payload);
      ring_popleft(&s->pend);
    }
    if (sent == 0 && !done) return 0; /* buffer full; drain resumes */
  }
  return 0;
}

/* queue one frame (steals the ref) and pump */
static int tsink_write(CTorSink *s, int64_t now, PyObject *frame) {
  PendEnt *p = ring_push(&s->pend);
  if (!p) { Py_DECREF(frame); return -1; }
  p->payload = frame;
  p->a = 0;
  return tsink_pump(s, now);
}

static int tsink_feed(CTorSink *s, int64_t nbytes, PyObject *payload) {
  if (s->body_left > 0 && (!payload || payload == Py_None)) {
    int64_t take = nbytes < s->body_left ? nbytes : s->body_left;
    s->body_left -= take;
    s->got += take;
    if (nbytes > take) {
      PyErr_SetString(PyExc_ValueError,
                      "framing error: stray counted bytes");
      return -1;
    }
    return 0;
  }
  if (!payload || payload == Py_None) {
    PyErr_SetString(PyExc_ValueError,
                    "framing error: counted bytes outside DATA body");
    return -1;
  }
  char *pb;
  Py_ssize_t pn;
  if (PyBytes_AsStringAndSize(payload, &pb, &pn) < 0) return -1;
  if (s->buf_len + pn > s->buf_cap) {
    int64_t ncap = s->buf_cap ? s->buf_cap * 2 : 256;
    while (ncap < s->buf_len + pn) ncap *= 2;
    char *nb = realloc(s->buf, (size_t)ncap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    s->buf = nb;
    s->buf_cap = ncap;
  }
  memcpy(s->buf + s->buf_len, pb, (size_t)pn);
  s->buf_len += pn;
  int64_t off = 0;
  int rcod = 0;
  Py_INCREF(s); /* the callback may drop the model's last reference */
  while (s->buf_len - off >= TCELL_HDR) {
    unsigned char *b = (unsigned char *)s->buf + off;
    int ctype = b[0];
    int circ = ((int)b[1] << 8) | b[2];
    int64_t ln = ((int64_t)b[3] << 8) | b[4];
    if (ctype == TC_DATA) {
      off += TCELL_HDR;
      s->body_left = ln;
      break; /* counted body follows in subsequent chunks */
    }
    if (s->buf_len - off < TCELL_HDR + ln) break;
    if (s->frames) {
      /* C control plane (TorClient.on_ctrl + advance twin) */
      if (ctype == TC_CREATED || ctype == TC_EXTENDED) {
        s->stage++;
        if (s->stage == 3) {
          /* telescoping done: the ONE mid-build Python event (the model
           * records circuit-build time), then BEGIN goes out below */
          PyObject *pl = PyBytes_FromStringAndSize(
              s->buf + off + TCELL_HDR, (Py_ssize_t)ln);
          if (!pl) { rcod = -1; break; }
          PyObject *r = PyObject_CallFunction(s->on_cell, "iiNL", ctype,
                                              circ, pl, (long long)s->got);
          if (!r) { rcod = -1; break; }
          Py_DECREF(r);
        }
        int idx = s->stage > 3 ? 2 : s->stage - 1;
        PyObject *f = PyTuple_GET_ITEM(s->frames, idx);
        Py_INCREF(f);
        int err;
        int64_t now = cep_now(s->ep, &err);
        if (err) { Py_DECREF(f); rcod = -1; break; }
        if (tsink_write(s, now, f) < 0) { rcod = -1; break; }
        off += TCELL_HDR + ln;
        continue;
      }
      if (ctype != TC_END) { /* CONNECTED etc.: the twin ignores them */
        off += TCELL_HDR + ln;
        continue;
      }
    }
    PyObject *pl = PyBytes_FromStringAndSize(s->buf + off + TCELL_HDR,
                                             (Py_ssize_t)ln);
    if (!pl) { rcod = -1; break; }
    PyObject *r = PyObject_CallFunction(s->on_cell, "iiNL", ctype, circ,
                                        pl, (long long)s->got);
    if (!r) { rcod = -1; break; }
    Py_DECREF(r);
    off += TCELL_HDR + ln;
  }
  if (off && rcod == 0) {
    memmove(s->buf, s->buf + off, (size_t)(s->buf_len - off));
    s->buf_len -= off;
  }
  Py_DECREF(s);
  return rcod;
}

static int CTorSink_traverse(CTorSink *s, visitproc visit, void *arg) {
  Py_VISIT(s->ep);
  Py_VISIT(s->on_cell);
  Py_VISIT(s->frames);
  return 0;
}

static void tsink_clear_pend(CTorSink *s) {
  while (s->pend.count) {
    Py_XDECREF(((PendEnt *)ring_at(&s->pend, 0))->payload);
    ring_popleft(&s->pend);
  }
}

static int CTorSink_clear_gc(CTorSink *s) {
  if (s->ep && s->ep->tsink == s) s->ep->tsink = NULL;
  Py_CLEAR(s->ep);
  Py_CLEAR(s->on_cell);
  Py_CLEAR(s->frames);
  tsink_clear_pend(s);
  return 0;
}

static void CTorSink_dealloc(CTorSink *s) {
  PyObject_GC_UnTrack(s);
  if (s->ep && s->ep->tsink == s) s->ep->tsink = NULL;
  Py_XDECREF(s->ep);
  Py_XDECREF(s->on_cell);
  Py_XDECREF(s->frames);
  tsink_clear_pend(s);
  free(s->pend.buf);
  free(s->buf);
  Py_TYPE(s)->tp_free((PyObject *)s);
}

static PyObject *CTorSink_bytes_received(CTorSink *s, PyObject *noarg) {
  (void)noarg;
  return PyLong_FromLongLong(s->got);
}

static PyObject *CTorSink_write(CTorSink *s, PyObject *arg) {
  /* model-side writes (the initial CREATE cell) ride the same pending
   * queue as the C state machine's advance frames */
  if (!PyBytes_Check(arg)) {
    PyErr_SetString(PyExc_TypeError, "TorSink.write expects bytes");
    return NULL;
  }
  if (!s->ep) {
    PyErr_SetString(PyExc_RuntimeError, "TorSink endpoint is gone");
    return NULL;
  }
  int err;
  int64_t now = cep_now(s->ep, &err);
  if (err) return NULL;
  Py_INCREF(arg);
  if (tsink_write(s, now, arg) < 0) return NULL;
  Py_RETURN_NONE;
}

static PyObject *CTorSink_export_state(CTorSink *s, PyObject *noarg);
static PyObject *CTorSink_restore_state(CTorSink *s, PyObject *state);

static PyMethodDef CTorSink_methods[] = {
    {"bytes_received", (PyCFunction)CTorSink_bytes_received, METH_NOARGS,
     "counted DATA body bytes received so far"},
    {"write", (PyCFunction)CTorSink_write, METH_O,
     "queue one framed cell through the C pending-write queue"},
    {"_export_state", (PyCFunction)CTorSink_export_state, METH_NOARGS,
     "checkpoint export: endpoint + frames + parser + pending queue"},
    {"_restore_state", (PyCFunction)CTorSink_restore_state, METH_O,
     "checkpoint restore (re-links ep->tsink)"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject CTorSink_Type = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "_colcore.TorSink",
    .tp_basicsize = sizeof(CTorSink),
    .tp_dealloc = (destructor)CTorSink_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_traverse = (traverseproc)CTorSink_traverse,
    .tp_clear = (inquiry)CTorSink_clear_gc,
    .tp_methods = CTorSink_methods,
    .tp_free = PyObject_GC_Del,
    .tp_doc = "C tor-client frame sink + circuit-build control plane "
              "(models/tor.py TorClient twin)",
};

static PyObject *Core_tor_client_sink(CoreObject *c, PyObject *args) {
  (void)c;
  PyObject *ep_o, *on_cell, *frames = Py_None;
  if (!PyArg_ParseTuple(args, "OO|O", &ep_o, &on_cell, &frames))
    return NULL;
  if (Py_TYPE(ep_o) != &CEp_Type) {
    PyErr_SetString(PyExc_TypeError, "tor_client_sink expects a C endpoint");
    return NULL;
  }
  if (frames != Py_None &&
      (!PyTuple_Check(frames) || PyTuple_GET_SIZE(frames) != 3 ||
       !PyBytes_Check(PyTuple_GET_ITEM(frames, 0)) ||
       !PyBytes_Check(PyTuple_GET_ITEM(frames, 1)) ||
       !PyBytes_Check(PyTuple_GET_ITEM(frames, 2)))) {
    PyErr_SetString(PyExc_TypeError,
                    "tor_client_sink frames must be a 3-tuple of bytes "
                    "(EXTEND hop2, EXTEND hop3, BEGIN)");
    return NULL;
  }
  CTorSink *s = PyObject_GC_New(CTorSink, &CTorSink_Type);
  if (!s) return NULL;
  memset(((char *)s) + sizeof(PyObject), 0,
         sizeof(CTorSink) - sizeof(PyObject));
  Py_INCREF(ep_o);
  s->ep = (CEp *)ep_o;
  Py_INCREF(on_cell);
  s->on_cell = on_cell;
  if (frames != Py_None) {
    Py_INCREF(frames);
    s->frames = frames;
  }
  s->pend.esz = sizeof(PendEnt);
  s->ep->tsink = s;
  PyObject_GC_Track((PyObject *)s);
  return (PyObject *)s;
}

/* ======================================================================
 * Checkpoint export/restore (shadow_tpu/checkpoint.py).
 *
 * Every C object that can be live at a round boundary — stream
 * endpoints, tor relays/sinks/exit streams, gossip states, packed store
 * batches — exports its COMPLETE state as plain Python structures
 * (ints, bytes, lists, the callbacks themselves), and rebuilds from
 * them: checkpoint._SimPickler reduces each object to
 * (shell(kind), state, _restore_state) so shared references and
 * reference cycles ride the pickle memo exactly like Python objects.
 * Core pointers are NOT exported: Controller._reattach_runtime rebuilds
 * the Core and binds the restored objects via Core.adopt(). The
 * module-level ABI constant ties a checkpoint to this state format —
 * the checkpoint header refuses a mismatch by name.
 * ====================================================================== */

static PyObject *ornone(PyObject *o) { return o ? o : Py_None; }

/* -- ring export/restore helpers ---------------------------------------- */
static PyObject *export_sq(Ring *r) {
  PyObject *l = PyList_New(r->count);
  if (!l) return NULL;
  for (int i = 0; i < r->count; i++) {
    SQEnt *q = ring_at(r, i);
    PyObject *t = Py_BuildValue("(LO)", (long long)q->nbytes,
                                ornone(q->payload));
    if (!t) { Py_DECREF(l); return NULL; }
    PyList_SET_ITEM(l, i, t);
  }
  return l;
}

static PyObject *export_rtx(Ring *r) {
  PyObject *l = PyList_New(r->count);
  if (!l) return NULL;
  for (int i = 0; i < r->count; i++) {
    RtxEnt *q = ring_at(r, i);
    PyObject *t = Py_BuildValue("(LLO)", (long long)q->seq,
                                (long long)q->n, ornone(q->payload));
    if (!t) { Py_DECREF(l); return NULL; }
    PyList_SET_ITEM(l, i, t);
  }
  return l;
}

static PyObject *export_pend(Ring *r) {
  PyObject *l = PyList_New(r->count);
  if (!l) return NULL;
  for (int i = 0; i < r->count; i++) {
    PendEnt *q = ring_at(r, i);
    PyObject *t = Py_BuildValue("(OL)", ornone(q->payload),
                                (long long)q->a);
    if (!t) { Py_DECREF(l); return NULL; }
    PyList_SET_ITEM(l, i, t);
  }
  return l;
}

static int restore_sq(Ring *r, PyObject *l) {
  if (!PyList_Check(l)) {
    PyErr_SetString(PyExc_TypeError, "restore: expected a list");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(l); i++) {
    long long n;
    PyObject *pl;
    if (!PyArg_ParseTuple(PyList_GET_ITEM(l, i), "LO", &n, &pl)) return -1;
    SQEnt *q = ring_push(r);
    if (!q) return -1;
    q->nbytes = n;
    q->payload = pl == Py_None ? NULL : (Py_INCREF(pl), pl);
  }
  return 0;
}

static int restore_rtx(Ring *r, PyObject *l) {
  if (!PyList_Check(l)) {
    PyErr_SetString(PyExc_TypeError, "restore: expected a list");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(l); i++) {
    long long seq, n;
    PyObject *pl;
    if (!PyArg_ParseTuple(PyList_GET_ITEM(l, i), "LLO", &seq, &n, &pl))
      return -1;
    RtxEnt *q = ring_push(r);
    if (!q) return -1;
    q->seq = seq;
    q->n = n;
    q->payload = pl == Py_None ? NULL : (Py_INCREF(pl), pl);
  }
  return 0;
}

static int restore_pend(Ring *r, PyObject *l) {
  if (!PyList_Check(l)) {
    PyErr_SetString(PyExc_TypeError, "restore: expected a list");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(l); i++) {
    long long a;
    PyObject *pl;
    if (!PyArg_ParseTuple(PyList_GET_ITEM(l, i), "OL", &pl, &a)) return -1;
    PendEnt *q = ring_push(r);
    if (!q) return -1;
    q->a = a;
    q->payload = pl == Py_None ? NULL : (Py_INCREF(pl), pl);
  }
  return 0;
}

/* -- shells (empty objects the unpickler fills via _restore_state) ------- */
static CEp *cep_shell(void) {
  CEp *e = PyObject_GC_New(CEp, &CEp_Type);
  if (!e) return NULL;
  memset(((char *)e) + sizeof(PyObject), 0, sizeof(CEp) - sizeof(PyObject));
  e->sendbuf.esz = sizeof(SQEnt);
  e->rtx.esz = sizeof(RtxEnt);
  e->ooo.esz = sizeof(RtxEnt);
  e->sacked.esz = sizeof(int64_t);
  e->rtx_done.esz = sizeof(int64_t);
  e->tgen_t_first = -1;
  PyObject_GC_Track((PyObject *)e);
  return e;
}

static CRelayObj *relay_shell(void) {
  CRelayObj *r = PyObject_GC_New(CRelayObj, &CRelay_Type);
  if (!r) return NULL;
  memset(((char *)r) + sizeof(PyObject), 0,
         sizeof(CRelayObj) - sizeof(PyObject));
  r->next_circ = 1;
  PyObject_GC_Track((PyObject *)r);
  return r;
}

static CTorSink *tsink_shell(void) {
  CTorSink *s = PyObject_GC_New(CTorSink, &CTorSink_Type);
  if (!s) return NULL;
  memset(((char *)s) + sizeof(PyObject), 0,
         sizeof(CTorSink) - sizeof(PyObject));
  s->pend.esz = sizeof(PendEnt);
  PyObject_GC_Track((PyObject *)s);
  return s;
}

static CExitStream *xstream_shell(void) {
  CExitStream *s = PyObject_GC_New(CExitStream, &CExitStream_Type);
  if (!s) return NULL;
  memset(((char *)s) + sizeof(PyObject), 0,
         sizeof(CExitStream) - sizeof(PyObject));
  PyObject_GC_Track((PyObject *)s);
  return s;
}

static GossipState *gossip_shell(void) {
  GossipState *g = PyObject_GC_New(GossipState, &GossipState_Type);
  if (!g) return NULL;
  g->core = NULL;
  g->hid = 0;
  g->port = 0;
  g->port_obj = NULL;
  g->peers = NULL;
  g->npeers = 0;
  memset(&g->seen, 0, sizeof g->seen);
  g->received_tx = 0;
  g->next_dgram = 0;
  PyObject_GC_Track((PyObject *)g);
  return g;
}

/* -- CEp export/restore (55 positional fields; ABI-guarded) -------------- */
static PyObject *CEp_export_state(CEp *e, PyObject *noarg) {
  (void)noarg;
  PyObject *sb = export_sq(&e->sendbuf);
  PyObject *rt = sb ? export_rtx(&e->rtx) : NULL;
  PyObject *oo = rt ? export_rtx(&e->ooo) : NULL;
  PyObject *sk = oo ? i64set_sorted_tuple(&e->sacked) : NULL;
  PyObject *rd = sk ? i64set_sorted_tuple(&e->rtx_done) : NULL;
  if (!rd) {
    Py_XDECREF(sb);
    Py_XDECREF(rt);
    Py_XDECREF(oo);
    Py_XDECREF(sk);
    return NULL;
  }
  return Py_BuildValue(
      "(iiiiOiiiOLOLLLLLLLLLLiiONNLLLLLiNOOOOOOiLLOLOLOiLLiLLNN)",
      e->hid, e->local_port, e->remote_host, e->remote_port,
      e->initiator ? Py_True : Py_False, e->state, e->syn_tries,
      e->fin_tries, e->peer_fin ? Py_True : Py_False,
      (long long)e->rto_ns, ornone(e->ctl_timer), (long long)e->chunk,
      (long long)e->cwnd, (long long)e->ssthresh,
      (long long)e->send_buffer, (long long)e->snd_nxt,
      (long long)e->snd_una, (long long)e->adv_wnd,
      (long long)e->buffered, (long long)e->bytes_acked,
      (long long)e->rto_backoff, e->retries, e->loss_events,
      ornone(e->rto_timer), sb, rt, (long long)e->recv_buffer,
      (long long)e->rcv_nxt, (long long)e->ooo_bytes,
      (long long)e->bytes_received, (long long)e->last_wnd, e->dup_acks,
      oo, ornone(e->app_unread), ornone(e->on_connected),
      ornone(e->on_data), ornone(e->on_drain), ornone(e->on_close),
      ornone(e->on_error), e->tgen_mode, (long long)e->tgen_pending,
      (long long)e->tgen_want, ornone(e->tgen_cb),
      (long long)e->tgen_t_first, ornone(e->xsink),
      (long long)e->idle_timeout_ns, ornone(e->idle_timer),
      e->cc_kind, (long long)e->w_max, (long long)e->epoch_start,
      e->in_recovery ? 1 : 0, (long long)e->recover,
      (long long)e->sack_high, sk, rd);
}

static PyObject *CEp_restore_state(CEp *e, PyObject *state) {
  int hid, lport, rhost, rport, initiator, st, syn_tries, fin_tries,
      peer_fin, retries, loss_events, dup_acks, tgen_mode, cc_kind,
      in_recovery;
  long long rto_ns, chunk, cwnd, ssthresh, sbuf, snd_nxt, snd_una,
      adv_wnd, buffered, bytes_acked, rto_backoff, rbuf, rcv_nxt,
      ooo_bytes, bytes_received, last_wnd, tgen_pending, tgen_want,
      tgen_t_first, idle_ns, w_max, epoch_start, recover, sack_high;
  PyObject *ctl_t, *rto_t, *sb, *rt, *oo, *app_unread, *on_connected,
      *on_data, *on_drain, *on_close, *on_error, *tgen_cb, *xs, *idle_t,
      *sk, *rd;
  if (!PyArg_ParseTuple(
          state, "iiiiiiiiiLOLLLLLLLLLLiiOOOLLLLLiOOOOOOOiLLOLOLOiLLiLLOO",
          &hid, &lport, &rhost, &rport, &initiator, &st, &syn_tries,
          &fin_tries, &peer_fin, &rto_ns, &ctl_t, &chunk, &cwnd,
          &ssthresh, &sbuf, &snd_nxt, &snd_una, &adv_wnd, &buffered,
          &bytes_acked, &rto_backoff, &retries, &loss_events, &rto_t,
          &sb, &rt, &rbuf, &rcv_nxt, &ooo_bytes, &bytes_received,
          &last_wnd, &dup_acks, &oo, &app_unread, &on_connected,
          &on_data, &on_drain, &on_close, &on_error, &tgen_mode,
          &tgen_pending, &tgen_want, &tgen_cb, &tgen_t_first, &xs,
          &idle_ns, &idle_t, &cc_kind, &w_max, &epoch_start,
          &in_recovery, &recover, &sack_high, &sk, &rd))
    return NULL;
  e->hid = hid;
  e->local_port = lport;
  e->remote_host = rhost;
  e->remote_port = rport;
  e->initiator = initiator;
  e->state = st;
  e->syn_tries = syn_tries;
  e->fin_tries = fin_tries;
  e->peer_fin = peer_fin;
  e->rto_ns = rto_ns;
  e->chunk = chunk;
  e->cwnd = cwnd;
  e->ssthresh = ssthresh;
  e->send_buffer = sbuf;
  e->snd_nxt = snd_nxt;
  e->snd_una = snd_una;
  e->adv_wnd = adv_wnd;
  e->buffered = buffered;
  e->bytes_acked = bytes_acked;
  e->rto_backoff = rto_backoff;
  e->retries = retries;
  e->loss_events = loss_events;
  e->recv_buffer = rbuf;
  e->rcv_nxt = rcv_nxt;
  e->ooo_bytes = ooo_bytes;
  e->bytes_received = bytes_received;
  e->last_wnd = last_wnd;
  e->dup_acks = dup_acks;
  e->tgen_mode = tgen_mode;
  e->tgen_pending = tgen_pending;
  e->tgen_want = tgen_want;
  e->tgen_t_first = tgen_t_first;
  e->idle_timeout_ns = idle_ns;
  e->cc_kind = cc_kind;
  e->w_max = w_max;
  e->epoch_start = epoch_start;
  e->in_recovery = in_recovery;
  e->recover = recover;
  e->sack_high = sack_high;
  if (i64set_restore(&e->sacked, sk) < 0) return NULL;
  if (i64set_restore(&e->rtx_done, rd) < 0) return NULL;
#define EP_SLOT(slot, v)                                \
  do {                                                  \
    PyObject *nv = (v) == Py_None ? NULL : (v);         \
    Py_XINCREF(nv);                                     \
    Py_XSETREF(slot, nv);                               \
  } while (0)
  EP_SLOT(e->ctl_timer, ctl_t);
  EP_SLOT(e->rto_timer, rto_t);
  EP_SLOT(e->idle_timer, idle_t);
  EP_SLOT(e->app_unread, app_unread);
  EP_SLOT(e->on_connected, on_connected);
  EP_SLOT(e->on_data, on_data);
  EP_SLOT(e->on_drain, on_drain);
  EP_SLOT(e->on_close, on_close);
  EP_SLOT(e->on_error, on_error);
  EP_SLOT(e->tgen_cb, tgen_cb);
#undef EP_SLOT
  if (restore_sq(&e->sendbuf, sb) < 0) return NULL;
  if (restore_rtx(&e->rtx, rt) < 0) return NULL;
  if (restore_rtx(&e->ooo, oo) < 0) return NULL;
  if (xs != Py_None) {
    if (Py_TYPE(xs) != &CExitStream_Type) {
      PyErr_SetString(PyExc_TypeError,
                      "endpoint restore: xsink is not an ExitStream");
      return NULL;
    }
    Py_INCREF(xs);
    Py_XSETREF(e->xsink, xs);
    ((CExitStream *)xs)->ep = e; /* borrowed back-pointer (owner = us) */
  }
  Py_RETURN_NONE;
}

/* -- CRelay export/restore ------------------------------------------------ */
typedef struct { uint64_t ts, k, v; } TExp;

static int cmp_texp(const void *a, const void *b) {
  uint64_t x = ((const TExp *)a)->ts, y = ((const TExp *)b)->ts;
  return (x > y) - (x < y);
}

static PyObject *CRelay_export_state(CRelayObj *r, PyObject *noarg) {
  (void)noarg;
  PyObject *conns = PyList_New(r->nconns);
  if (!conns) return NULL;
  for (int i = 0; i < r->nconns; i++) {
    CRelayConn *rc = r->conns[i];
    if (!rc) {
      Py_INCREF(Py_None);
      PyList_SET_ITEM(conns, i, Py_None);
      continue;
    }
    PyObject *pend = export_pend(&rc->pend);
    if (!pend) { Py_DECREF(conns); return NULL; }
    PyObject *buf = PyBytes_FromStringAndSize(
        rc->buf ? rc->buf : "", (Py_ssize_t)rc->buf_len);
    if (!buf) { Py_DECREF(pend); Py_DECREF(conns); return NULL; }
    PyObject *t = Py_BuildValue("(OiNLiN)", (PyObject *)rc->ep,
                                rc->close_after_drain, buf,
                                (long long)rc->body_left, rc->body_circ,
                                pend);
    if (!t) { Py_DECREF(conns); return NULL; }
    PyList_SET_ITEM(conns, i, t);
  }
  /* circuit table in dict insertion order (the ts seq) */
  TExp *te = malloc(sizeof(TExp) * (size_t)(r->tcount ? r->tcount : 1));
  if (!te) { Py_DECREF(conns); return PyErr_NoMemory(); }
  int m = 0;
  for (int i = 0; i < r->tcap; i++) {
    if (!r->tk[i]) continue;
    te[m].ts = r->ts[i];
    te[m].k = r->tk[i];
    te[m].v = r->tv[i];
    m++;
  }
  if (m > 1) qsort(te, (size_t)m, sizeof(TExp), cmp_texp);
  PyObject *tab = PyList_New(m);
  if (!tab) { free(te); Py_DECREF(conns); return NULL; }
  for (int i = 0; i < m; i++) {
    uint64_t k = te[i].k - 1;
    PyObject *t = Py_BuildValue("(iiii)", (int)(k >> 32),
                                (int)(uint32_t)k, (int)(te[i].v >> 32),
                                (int)(uint32_t)te[i].v);
    if (!t) { free(te); Py_DECREF(tab); Py_DECREF(conns); return NULL; }
    PyList_SET_ITEM(tab, i, t);
  }
  free(te);
  return Py_BuildValue("(iOiiLLNN)", r->hid, ornone(r->on_ctrl),
                       r->exit_mode, r->next_circ,
                       (long long)r->cells_relayed,
                       (long long)r->bytes_relayed, conns, tab);
}

static PyObject *CRelay_restore_state(CRelayObj *r, PyObject *state) {
  int hid, exit_mode, next_circ;
  long long cells, nbytes;
  PyObject *on_ctrl, *conns, *tab;
  if (!PyArg_ParseTuple(state, "iOiiLLOO", &hid, &on_ctrl, &exit_mode,
                        &next_circ, &cells, &nbytes, &conns, &tab))
    return NULL;
  if (!PyList_Check(conns) || !PyList_Check(tab)) {
    PyErr_SetString(PyExc_TypeError, "relay restore: expected lists");
    return NULL;
  }
  r->hid = hid;
  if (on_ctrl != Py_None) {
    Py_INCREF(on_ctrl);
    Py_XSETREF(r->on_ctrl, on_ctrl);
  }
  r->exit_mode = exit_mode;
  r->next_circ = next_circ;
  r->cells_relayed = cells;
  r->bytes_relayed = nbytes;
  int n = (int)PyList_GET_SIZE(conns);
  r->conns = calloc((size_t)(n ? n : 1), sizeof(CRelayConn *));
  if (!r->conns) return PyErr_NoMemory();
  r->conns_cap = n ? n : 1;
  r->nconns = n;
  for (int i = 0; i < n; i++) {
    PyObject *it = PyList_GET_ITEM(conns, i);
    if (it == Py_None) continue;
    PyObject *ep, *buf, *pend;
    int cad, bcirc;
    long long bleft;
    if (!PyArg_ParseTuple(it, "OiOLiO", &ep, &cad, &buf, &bleft, &bcirc,
                          &pend))
      return NULL;
    if (Py_TYPE(ep) != &CEp_Type || !PyBytes_Check(buf)) {
      PyErr_SetString(PyExc_TypeError,
                      "relay restore: bad conn entry types");
      return NULL;
    }
    CRelayConn *rc = calloc(1, sizeof(CRelayConn));
    if (!rc) return PyErr_NoMemory();
    rc->relay = r;
    Py_INCREF(ep);
    rc->ep = (CEp *)ep;
    rc->cid = i;
    rc->close_after_drain = cad;
    rc->body_left = bleft;
    rc->body_circ = bcirc;
    rc->pend.esz = sizeof(PendEnt);
    r->conns[i] = rc; /* registered first: dealloc cleans up on error */
    Py_ssize_t bl = PyBytes_GET_SIZE(buf);
    if (bl) {
      rc->buf = malloc((size_t)bl);
      if (!rc->buf) return PyErr_NoMemory();
      memcpy(rc->buf, PyBytes_AS_STRING(buf), (size_t)bl);
      rc->buf_len = bl;
      rc->buf_cap = bl;
    }
    if (restore_pend(&rc->pend, pend) < 0) return NULL;
    ((CEp *)ep)->sink = rc;
  }
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(tab); i++) {
    int cid, circ, ncid, ncirc;
    if (!PyArg_ParseTuple(PyList_GET_ITEM(tab, i), "iiii", &cid, &circ,
                          &ncid, &ncirc))
      return NULL;
    if (rtab_put(r, cid, circ, ncid, ncirc) < 0) return NULL;
  }
  Py_RETURN_NONE;
}

/* -- CTorSink / CExitStream export/restore -------------------------------- */
static PyObject *CTorSink_export_state(CTorSink *s, PyObject *noarg) {
  (void)noarg;
  PyObject *pend = export_pend(&s->pend);
  if (!pend) return NULL;
  PyObject *buf = PyBytes_FromStringAndSize(s->buf ? s->buf : "",
                                            (Py_ssize_t)s->buf_len);
  if (!buf) { Py_DECREF(pend); return NULL; }
  return Py_BuildValue("(OOOiNNLL)", ornone((PyObject *)s->ep),
                       ornone(s->on_cell), ornone(s->frames), s->stage,
                       pend, buf, (long long)s->body_left,
                       (long long)s->got);
}

static PyObject *CTorSink_restore_state(CTorSink *s, PyObject *state) {
  PyObject *ep, *on_cell, *frames, *pend, *buf;
  int stage;
  long long bleft, got;
  if (!PyArg_ParseTuple(state, "OOOiOOLL", &ep, &on_cell, &frames,
                        &stage, &pend, &buf, &bleft, &got))
    return NULL;
  if (ep == Py_None || Py_TYPE(ep) != &CEp_Type || !PyBytes_Check(buf)) {
    PyErr_SetString(PyExc_TypeError, "tor-sink restore: bad state types");
    return NULL;
  }
  Py_INCREF(ep);
  Py_XSETREF(s->ep, (CEp *)ep);
  s->ep->tsink = s; /* the borrowed back-pointer the data path follows */
  if (on_cell != Py_None) {
    Py_INCREF(on_cell);
    Py_XSETREF(s->on_cell, on_cell);
  }
  if (frames != Py_None) {
    Py_INCREF(frames);
    Py_XSETREF(s->frames, frames);
  }
  s->stage = stage;
  if (restore_pend(&s->pend, pend) < 0) return NULL;
  Py_ssize_t bl = PyBytes_GET_SIZE(buf);
  if (bl) {
    s->buf = malloc((size_t)bl);
    if (!s->buf) return PyErr_NoMemory();
    memcpy(s->buf, PyBytes_AS_STRING(buf), (size_t)bl);
    s->buf_len = bl;
    s->buf_cap = bl;
  }
  s->body_left = bleft;
  s->got = got;
  Py_RETURN_NONE;
}

static PyObject *CExitStream_export_state(CExitStream *s, PyObject *noarg) {
  (void)noarg;
  return Py_BuildValue("(OiiiLL)", ornone((PyObject *)s->relay), s->cid,
                       s->circ, s->done, (long long)s->want,
                       (long long)s->got);
}

static PyObject *CExitStream_restore_state(CExitStream *s,
                                           PyObject *state) {
  PyObject *relay;
  int cid, circ, done;
  long long want, got;
  if (!PyArg_ParseTuple(state, "OiiiLL", &relay, &cid, &circ, &done,
                        &want, &got))
    return NULL;
  if (relay == Py_None || Py_TYPE(relay) != &CRelay_Type) {
    PyErr_SetString(PyExc_TypeError,
                    "exit-stream restore: relay is not a Relay");
    return NULL;
  }
  Py_INCREF(relay);
  Py_XSETREF(s->relay, (CRelayObj *)relay);
  s->cid = cid;
  s->circ = circ;
  s->done = done;
  s->want = want;
  s->got = got;
  /* s->ep is set by the OWNING endpoint's _restore_state */
  Py_RETURN_NONE;
}

/* -- GossipState export/restore ------------------------------------------- */
typedef struct { uint32_t off; uint16_t len; } SeenExp;

static int cmp_seen_off(const void *a, const void *b) {
  uint32_t x = ((const SeenExp *)a)->off, y = ((const SeenExp *)b)->off;
  return (x > y) - (x < y);
}

static PyObject *Gossip_export_state(GossipState *g, PyObject *noarg) {
  (void)noarg;
  PyObject *peers = PyList_New(g->npeers);
  if (!peers) return NULL;
  for (int i = 0; i < g->npeers; i++) {
    PyObject *v = PyLong_FromLong(g->peers[i]);
    if (!v) { Py_DECREF(peers); return NULL; }
    PyList_SET_ITEM(peers, i, v);
  }
  /* seen keys in ARENA (insertion) order so re-adding reproduces the
   * identical arena layout */
  SeenSet *ss = &g->seen;
  size_t cnt = ss->count;
  SeenExp *se = malloc(sizeof(SeenExp) * (cnt ? cnt : 1));
  if (!se) { Py_DECREF(peers); return PyErr_NoMemory(); }
  size_t m = 0;
  for (size_t i = 0; ss->hash && i < ss->cap; i++) {
    if (!ss->hash[i]) continue;
    se[m].off = ss->off[i];
    se[m].len = ss->len[i];
    m++;
  }
  if (m > 1) qsort(se, m, sizeof(SeenExp), cmp_seen_off);
  PyObject *seen = PyList_New((Py_ssize_t)m);
  if (!seen) { free(se); Py_DECREF(peers); return NULL; }
  for (size_t i = 0; i < m; i++) {
    PyObject *b = PyBytes_FromStringAndSize(ss->arena + se[i].off,
                                            (Py_ssize_t)se[i].len);
    if (!b) { free(se); Py_DECREF(seen); Py_DECREF(peers); return NULL; }
    PyList_SET_ITEM(seen, (Py_ssize_t)i, b);
  }
  free(se);
  return Py_BuildValue("(iiNNLL)", g->hid, g->port, peers, seen,
                       (long long)g->received_tx,
                       (long long)g->next_dgram);
}

static PyObject *Gossip_restore_state(GossipState *g, PyObject *state) {
  int hid, port;
  PyObject *peers, *seen;
  long long rtx, nd;
  if (!PyArg_ParseTuple(state, "iiOOLL", &hid, &port, &peers, &seen,
                        &rtx, &nd))
    return NULL;
  if (!PyList_Check(peers) || !PyList_Check(seen)) {
    PyErr_SetString(PyExc_TypeError, "gossip restore: expected lists");
    return NULL;
  }
  g->hid = hid;
  g->port = port;
  PyObject *po = PyLong_FromLong(port);
  if (!po) return NULL;
  Py_XSETREF(g->port_obj, po);
  Py_ssize_t np = PyList_GET_SIZE(peers);
  free(g->peers);
  g->peers = malloc(sizeof(int32_t) * (size_t)(np ? np : 1));
  if (!g->peers) { g->npeers = 0; return PyErr_NoMemory(); }
  g->npeers = (int)np;
  for (Py_ssize_t i = 0; i < np; i++) {
    g->peers[i] =
        (int32_t)PyLong_AsLongLong(PyList_GET_ITEM(peers, i));
  }
  if (PyErr_Occurred()) return NULL;
  seen_free(&g->seen);
  if (seen_init(&g->seen) < 0) return PyErr_NoMemory();
  for (Py_ssize_t i = 0; i < PyList_GET_SIZE(seen); i++) {
    PyObject *b = PyList_GET_ITEM(seen, i);
    char *kb;
    Py_ssize_t kn;
    if (PyBytes_AsStringAndSize(b, &kb, &kn) < 0) return NULL;
    if (seen_add(&g->seen, kb, kn) < 0) return PyErr_NoMemory();
  }
  g->received_tx = rtx;
  g->next_dgram = nd;
  Py_RETURN_NONE;
}

/* -- CBatch export/restore ------------------------------------------------ */
static PyObject *CBatch_export_rows(CBatch *b, PyObject *noarg) {
  (void)noarg;
  PyObject *rows = PyList_New(b->n);
  if (!rows) return NULL;
  for (int i = 0; i < b->n; i++) {
    PyObject *t = srec_tuple(&b->recs[i], b->pay[i]);
    if (!t) { Py_DECREF(rows); return NULL; }
    PyList_SET_ITEM(rows, i, t);
  }
  return Py_BuildValue("(iN)", b->pos, rows);
}

static PyObject *CBatch_restore_state(CBatch *b, PyObject *state) {
  int pos;
  PyObject *rows;
  if (!PyArg_ParseTuple(state, "iO", &pos, &rows)) return NULL;
  if (!PyList_Check(rows)) {
    PyErr_SetString(PyExc_TypeError, "batch restore: expected a list");
    return NULL;
  }
  int n = (int)PyList_GET_SIZE(rows);
  for (int i = 0; i < b->n; i++) Py_CLEAR(b->pay[i]);
  free(b->recs);
  free(b->pay);
  b->n = 0;
  b->recs = malloc(sizeof(SRec) * (size_t)(n ? n : 1));
  b->pay = calloc((size_t)(n ? n : 1), sizeof(PyObject *));
  if (!b->recs || !b->pay) return PyErr_NoMemory();
  b->n = n;
  b->pos = pos;
  for (int i = 0; i < n; i++) {
    PyObject *r = PyList_GET_ITEM(rows, i);
    if (!PyTuple_Check(r) || PyTuple_GET_SIZE(r) != 13) {
      PyErr_SetString(PyExc_TypeError,
                      "batch restore: rows must be 13-tuples");
      return NULL;
    }
    SRec *s = &b->recs[i];
    s->t = tup_i64(r, 0);
    s->key = tup_i64(r, 1);
    s->tgt = (int32_t)tup_i64(r, 2);
    s->kind = (int16_t)tup_i64(r, 3);
    s->peer = (int32_t)tup_i64(r, 4);
    s->aport = (int32_t)tup_i64(r, 5);
    s->bport = (int32_t)tup_i64(r, 6);
    s->nbytes = tup_i64(r, 7);
    s->seq = tup_i64(r, 8);
    s->frag = (int32_t)tup_i64(r, 9);
    s->nfrags = (int32_t)tup_i64(r, 10);
    s->size = (int32_t)tup_i64(r, 11);
    PyObject *pl = PyTuple_GET_ITEM(r, 12);
    if (pl != Py_None) {
      Py_INCREF(pl);
      b->pay[i] = pl;
    }
  }
  if (PyErr_Occurred()) return NULL;
  Py_RETURN_NONE;
}

/* -- shell factory + adoption --------------------------------------------- */
static PyObject *mod_shell(PyObject *self, PyObject *arg) {
  (void)self;
  const char *k = PyUnicode_AsUTF8(arg);
  if (!k) return NULL;
  if (!strcmp(k, "Endpoint")) return (PyObject *)cep_shell();
  if (!strcmp(k, "Relay")) return (PyObject *)relay_shell();
  if (!strcmp(k, "TorSink")) return (PyObject *)tsink_shell();
  if (!strcmp(k, "ExitStream")) return (PyObject *)xstream_shell();
  if (!strcmp(k, "GossipState")) return (PyObject *)gossip_shell();
  if (!strcmp(k, "CBatch")) return (PyObject *)cbatch_new(0);
  return PyErr_Format(PyExc_ValueError, "unknown colcore shell kind %s",
                      k);
}

/* ======================================================================
 * Transport column snapshot/adopt ABI (PR 11, colcore ABI 4): the C
 * half of the device-resident columnar transport's three-surface
 * contract.  transport_columns exports every C stream endpoint's hot
 * integer state as struct-of-arrays int64 numpy columns — the EXACT
 * field set and canonical order of network/devtransport.py's
 * export_columns (hosts in id order, connections in sorted-key order),
 * so the cross-plane identity gates can diff a C run's columns against
 * a Python run's byte for byte.  adopt_transport_columns is the
 * window-edge writeback: only the pure window/CC arithmetic columns
 * (devtransport.ADOPT_COLUMNS) are writable — never sequence/buffer
 * state, whose ring invariants are owned by the scalar machinery.
 * ====================================================================== */

#define N_TCOLS 28
static const char *TCOL_NAMES[N_TCOLS] = {
    "hid",        "local_port", "remote_host",    "remote_port",
    "state",      "cwnd",       "ssthresh",       "snd_nxt",
    "snd_una",    "adv_wnd",    "buffered",       "bytes_acked",
    "rto_backoff", "retries",   "dup_acks",       "loss_events",
    "cc_id",      "in_recovery", "recover",       "sack_high",
    "w_max",      "epoch_start", "sacked_n",      "rtx_done_n",
    "rcv_nxt",    "ooo_bytes",  "bytes_received", "last_wnd"};

static PyObject *Core_transport_columns(CoreObject *c, PyObject *noarg) {
  (void)noarg;
  /* collect C endpoints in canonical order */
  int cap = 256, n = 0;
  CEp **eps = malloc(sizeof(CEp *) * (size_t)cap);
  if (!eps) return PyErr_NoMemory();
  for (int64_t hid = 0; hid < c->H; hid++) {
    CHost *h = &c->hs[hid];
    if (!h->conns) continue;
    PyObject *keys = PyDict_Keys(h->conns);
    if (!keys || PyList_Sort(keys) < 0) {
      Py_XDECREF(keys);
      free(eps);
      return NULL;
    }
    Py_ssize_t nk = PyList_GET_SIZE(keys);
    for (Py_ssize_t i = 0; i < nk; i++) {
      PyObject *v = PyDict_GetItem(h->conns, PyList_GET_ITEM(keys, i));
      if (!v || Py_TYPE(v) != &CEp_Type) continue; /* pcap hosts stay py */
      if (n == cap) {
        cap *= 2;
        CEp **ne = realloc(eps, sizeof(CEp *) * (size_t)cap);
        if (!ne) {
          Py_DECREF(keys);
          free(eps);
          return PyErr_NoMemory();
        }
        eps = ne;
      }
      eps[n++] = (CEp *)v;
    }
    Py_DECREF(keys);
  }
  PyObject *out = PyDict_New();
  int64_t *p[N_TCOLS];
  if (!out) {
    free(eps);
    return NULL;
  }
  npy_intp dims[1] = {n};
  for (int k = 0; k < N_TCOLS; k++) {
    PyObject *a = PyArray_SimpleNew(1, dims, NPY_INT64);
    if (!a || PyDict_SetItemString(out, TCOL_NAMES[k], a) < 0) {
      Py_XDECREF(a);
      Py_DECREF(out);
      free(eps);
      return NULL;
    }
    p[k] = (int64_t *)PyArray_DATA((PyArrayObject *)a);
    Py_DECREF(a); /* the dict holds it */
  }
  for (int i = 0; i < n; i++) {
    CEp *e = eps[i];
    int k = 0;
    p[k++][i] = e->hid;
    p[k++][i] = e->local_port;
    p[k++][i] = e->remote_host;
    p[k++][i] = e->remote_port;
    p[k++][i] = e->state;
    p[k++][i] = e->cwnd;
    p[k++][i] = e->ssthresh;
    p[k++][i] = e->snd_nxt;
    p[k++][i] = e->snd_una;
    p[k++][i] = e->adv_wnd;
    p[k++][i] = e->buffered;
    p[k++][i] = e->bytes_acked;
    p[k++][i] = e->rto_backoff;
    p[k++][i] = e->retries;
    p[k++][i] = e->dup_acks;
    p[k++][i] = e->loss_events;
    p[k++][i] = e->cc_kind;
    p[k++][i] = e->in_recovery ? 1 : 0;
    p[k++][i] = e->recover;
    p[k++][i] = e->sack_high;
    p[k++][i] = e->w_max;
    p[k++][i] = e->epoch_start;
    p[k++][i] = e->sacked.count;
    p[k++][i] = e->rtx_done.count;
    p[k++][i] = e->rcv_nxt;
    p[k++][i] = e->ooo_bytes;
    p[k++][i] = e->bytes_received;
    p[k++][i] = e->last_wnd;
  }
  free(eps);
  return out;
}

/* the ADOPT_COLUMNS subset (devtransport.py twin) in writeback order */
#define N_TADOPT 7
static const char *TADOPT_NAMES[N_TADOPT] = {
    "cwnd", "ssthresh", "w_max", "epoch_start",
    "rto_backoff", "retries", "dup_acks"};

static PyObject *Core_adopt_transport_columns(CoreObject *c,
                                              PyObject *cols) {
  if (!PyDict_Check(cols)) {
    PyErr_SetString(PyExc_TypeError,
                    "adopt_transport_columns expects the column dict");
    return NULL;
  }
  PyObject *arrs[4 + N_TADOPT];
  const int64_t *dat[4 + N_TADOPT];
  const char *want[4 + N_TADOPT];
  for (int k = 0; k < 4; k++) want[k] = TCOL_NAMES[k]; /* identity join */
  for (int k = 0; k < N_TADOPT; k++) want[4 + k] = TADOPT_NAMES[k];
  Py_ssize_t n = -1;
  for (int k = 0; k < 4 + N_TADOPT; k++) {
    PyObject *a = PyDict_GetItemString(cols, want[k]);
    if (!a) {
      for (int j = 0; j < k; j++) Py_DECREF(arrs[j]);
      return PyErr_Format(PyExc_ValueError,
                          "adopt_transport_columns: missing column %s",
                          want[k]);
    }
    arrs[k] = PyArray_FROM_OTF(a, NPY_INT64, NPY_ARRAY_IN_ARRAY);
    if (!arrs[k]) {
      for (int j = 0; j < k; j++) Py_DECREF(arrs[j]);
      return NULL;
    }
    Py_ssize_t len = PyArray_SIZE((PyArrayObject *)arrs[k]);
    if (n < 0) n = len;
    if (len != n) {
      for (int j = 0; j <= k; j++) Py_DECREF(arrs[j]);
      return PyErr_Format(PyExc_ValueError,
                          "adopt_transport_columns: column %s length %zd"
                          " != %zd", want[k], len, n);
    }
    dat[k] = (const int64_t *)PyArray_DATA(
        (PyArrayObject *)arrs[k]);
  }
  /* two-pass validate-then-write: refusal must be ATOMIC (a partially
   * adopted cohort would be a state no snapshot ever described) */
  CEp **eps = malloc(sizeof(CEp *) * (size_t)(n ? n : 1));
  if (!eps) {
    for (int k = 0; k < 4 + N_TADOPT; k++) Py_DECREF(arrs[k]);
    return PyErr_NoMemory();
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t hid = dat[0][i];
    CEp *e = NULL;
    if (hid >= 0 && hid < c->H && c->hs[hid].conns) {
      PyObject *key = Py_BuildValue("(LLL)", (long long)dat[1][i],
                                    (long long)dat[2][i],
                                    (long long)dat[3][i]);
      if (!key) goto fail;
      PyObject *v = PyDict_GetItem(c->hs[hid].conns, key);
      Py_DECREF(key);
      if (v && Py_TYPE(v) == &CEp_Type) e = (CEp *)v;
    }
    if (!e) {
      PyErr_Format(PyExc_ValueError,
                   "adopt_transport_columns: row %zd (host %lld port %lld"
                   ") names no live C endpoint", i, (long long)hid,
                   (long long)dat[1][i]);
      goto fail;
    }
    eps[i] = e;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    CEp *e = eps[i];
    e->cwnd = dat[4][i];
    e->ssthresh = dat[5][i];
    e->w_max = dat[6][i];
    e->epoch_start = dat[7][i];
    e->rto_backoff = dat[8][i];
    e->retries = (int)dat[9][i];
    e->dup_acks = (int)dat[10][i];
  }
  free(eps);
  for (int k = 0; k < 4 + N_TADOPT; k++) Py_DECREF(arrs[k]);
  Py_RETURN_NONE;
fail:
  free(eps);
  for (int k = 0; k < 4 + N_TADOPT; k++) Py_DECREF(arrs[k]);
  return NULL;
}

static PyObject *Core_adopt(CoreObject *c, PyObject *arg) {
  PyObject *seq = PySequence_Fast(
      arg, "adopt expects a sequence of restored C objects");
  if (!seq) return NULL;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *o = PySequence_Fast_GET_ITEM(seq, i);
    if (Py_TYPE(o) == &CEp_Type) {
      CEp *e = (CEp *)o;
      if (e->hid < 0 || e->hid >= c->H || e->remote_host < 0 ||
          e->remote_host >= c->H) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError,
                        "restored endpoint host id out of range");
        return NULL;
      }
      Py_INCREF(c);
      CoreObject *old = e->core;
      e->core = c;
      Py_XDECREF(old);
    } else if (Py_TYPE(o) == &GossipState_Type) {
      GossipState *g = (GossipState *)o;
      if (g->hid < 0 || g->hid >= c->H) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError,
                        "restored gossip host id out of range");
        return NULL;
      }
      CHost *h = &c->hs[g->hid];
      int have = 0;
      for (int j = 0; j < h->nports; j++)
        if (h->gs[j] == g) have = 1;
      if (!have) {
        if (h->nports >= 4) {
          Py_DECREF(seq);
          PyErr_SetString(PyExc_ValueError,
                          "too many C ports on one host (restore)");
          return NULL;
        }
        h->port[h->nports] = g->port;
        Py_INCREF(g);
        h->gs[h->nports] = g;
        h->nports++;
      }
      Py_INCREF(c);
      CoreObject *old = g->core;
      g->core = c;
      Py_XDECREF(old);
    } else if (Py_TYPE(o) == &CRelay_Type) {
      CRelayObj *r = (CRelayObj *)o;
      if (r->hid < 0 || r->hid >= c->H) {
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError,
                        "restored relay host id out of range");
        return NULL;
      }
      Py_INCREF(c);
      CoreObject *old = r->core;
      r->core = c;
      Py_XDECREF(old);
    }
    /* CBatch / TorSink / ExitStream carry no core pointer */
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

/* ---- module ------------------------------------------------------------ */
static PyObject *mod_unit_dropped(PyObject *self, PyObject *args) {
  (void)self;
  unsigned long long seed, uid;
  int npk;
  unsigned int th;
  if (!PyArg_ParseTuple(args, "KKiI", &seed, &uid, &npk, &th)) return NULL;
  return PyBool_FromLong(unit_dropped(seed, uid, npk, th));
}

static PyObject *mod_perf_dump(PyObject *self, PyObject *noarg) {
  (void)self; (void)noarg;
  PyObject *d = PyDict_New();
  const char *names[12] = {"_", "py_fallback", "gossip", "emit", "run_host",
                           "ctr_flush", "snapshot", "active_total",
                           "now_entry", "dispatch", "inbox_free", ""};
  for (int i = 0; i < 12; i++) {
    if (!tm_cnt[i] && !tm_sect[i]) continue;
    PyObject *v = Py_BuildValue("(dL)", tm_sect[i] / 1e9,
                                (long long)tm_cnt[i]);
    PyDict_SetItemString(d, names[i], v);
    Py_DECREF(v);
    tm_sect[i] = tm_cnt[i] = 0;
  }
  return d;
}

/* parse one packed cross-shard row block (parallel/shards.py wire
 * format: [n u64][numeric cols (n, 12) i64][payload lens (n,) i64]
 * [payload blobs]) straight into a CBatch — the packed ingest path that
 * keeps cross-shard arrivals off the Python tuple path entirely
 * (~26 us/row via tuples + _restore_state vs ~2 us here, measured at
 * the 100k-host tor scale). Payload blobs are marshal (len > 0) with a
 * pickle fallback (len < 0); len == 0 is None. */
static PyObject *mod_cbatch_from_packed(PyObject *self, PyObject *arg) {
  (void)self;
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  const char *buf = view.buf;
  Py_ssize_t len = view.len;
  CBatch *cb = NULL;
  int bad = 0, err = 0;
  int64_t n = 0;
  if (len < 8) { bad = 1; goto done; }
  memcpy(&n, buf, 8);
  if (n < 0 || n > (len - 8) / (13 * 8)) { bad = 1; goto done; }
  cb = cbatch_new((int)n);
  if (!cb) { err = 1; goto done; }
  {
    const char *cols = buf + 8;
    const char *lens = buf + 8 + n * 12 * 8;
    Py_ssize_t off = 8 + n * 13 * 8;
    for (int64_t i = 0; i < n; i++) {
      int64_t r[12], ln;
      memcpy(r, cols + i * 12 * 8, 12 * 8);
      memcpy(&ln, lens + i * 8, 8);
      SRec *s = &cb->recs[i];
      s->t = r[0]; s->key = r[1]; s->tgt = (int32_t)r[2];
      s->kind = (int16_t)r[3]; s->peer = (int32_t)r[4];
      s->aport = (int32_t)r[5]; s->bport = (int32_t)r[6];
      s->nbytes = r[7]; s->seq = r[8]; s->frag = (int32_t)r[9];
      s->nfrags = (int32_t)r[10]; s->size = (int32_t)r[11];
      if (ln == 0) continue;
      int64_t alen = ln > 0 ? ln : -ln;
      if (off + alen > len) { bad = 1; goto done; }
      PyObject *p;
      if (ln > 0) {
        p = PyMarshal_ReadObjectFromString(buf + off, (Py_ssize_t)alen);
      } else {
        PyObject *pickle = PyImport_ImportModule("pickle");
        PyObject *blob = pickle ? PyBytes_FromStringAndSize(buf + off,
                                                            (Py_ssize_t)alen)
                                : NULL;
        p = blob ? PyObject_CallMethod(pickle, "loads", "O", blob) : NULL;
        Py_XDECREF(blob);
        Py_XDECREF(pickle);
      }
      if (!p) { err = 1; goto done; }
      cb->pay[i] = p; /* owned */
      off += alen;
    }
  }
done:
  PyBuffer_Release(&view);
  if (bad) {
    Py_XDECREF(cb);
    PyErr_SetString(PyExc_ValueError, "malformed packed batch");
    return NULL;
  }
  if (err) { Py_XDECREF(cb); return NULL; }
  return (PyObject *)cb;
}

static PyMethodDef module_methods[] = {
    {"cbatch_from_packed", mod_cbatch_from_packed, METH_O,
     "packed cross-shard row block (shards.py wire format) -> CBatch"},
    {"perf_dump", mod_perf_dump, METH_NOARGS, "drain section timers"},
    {"unit_dropped", mod_unit_dropped, METH_VARARGS,
     "(seed, uid, npk, thresh) -> bool  (test hook: fluid.loss_flags twin)"},
    {"shell", mod_shell, METH_O,
     "(type name) -> empty C object for checkpoint restore "
     "(filled via _restore_state; see shadow_tpu/checkpoint.py)"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef colcore_module = {
    PyModuleDef_HEAD_INIT, "_colcore",
    "C fast path for the columnar data plane (see file docstring)", -1,
    module_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit__colcore(void) {
  import_array();
#define INTERN(var, s) \
  if (!(var = PyUnicode_InternFromString(s))) return NULL
  INTERN(S_id, "id");
  INTERN(S_now, "_now");
  INTERN(S_inbox, "_inbox");
  INTERN(S_egress_rows, "egress_rows");
  INTERN(S_uid_counter, "_uid_counter");
  INTERN(S_emitters, "emitters");
  INTERN(S_ev_key, "_ev_key");
  INTERN(S_min_used_latency, "min_used_latency");
  INTERN(S_units_sent, "units_sent");
  INTERN(S_units_dropped, "units_dropped");
  INTERN(S_units_blackholed, "units_blackholed");
  INTERN(S_bytes_sent, "bytes_sent");
  INTERN(S_device, "device");
  INTERN(S_device_floor, "device_floor");
  INTERN(S_rows, "rows");
  INTERN(S_pos, "pos");
  INTERN(S_dispatch_row, "dispatch_row");
  INTERN(S_run_events, "run_events");
  INTERN(S_popleft, "popleft");
  INTERN(S_append, "append");
  INTERN(S_ingress_deferred_rows, "ingress_deferred_rows");
  INTERN(S_pcap, "pcap");
  INTERN(S_n_emitted, "_n_emitted");
  INTERN(S_n_delivered, "_n_delivered");
  INTERN(S_n_dgrams, "_n_dgrams");
  INTERN(S_n_dgrams_recv, "_n_dgrams_recv");
  INTERN(S_n_events, "_n_events");
  INTERN(S_n_teardown, "_n_teardown");
  INTERN(S_n_blackholed, "_n_blackholed");
  INTERN(S_down, "down");
  INTERN(S_cc_id, "cc_id");
  INTERN(S_seed, "seed");
  INTERN(S_bootstrap_end, "bootstrap_end");
  INTERN(S_unit_chunk, "unit_chunk");
  INTERN(S_socket_send_buffer, "socket_send_buffer");
  INTERN(S_socket_recv_buffer, "socket_recv_buffer");
  INTERN(S_dispatch, "dispatch");
  INTERN(S_schedule_in, "schedule_in");
  INTERN(S_cancel_m, "cancel");
  INTERN(S_rto_fire, "_rto_fire");
  INTERN(S_syn_fire, "_syn_fire");
  INTERN(S_fin_fire, "_fin_fire");
  INTERN(S_drop_fire, "_drop_fire");
  INTERN(S_idle_fire, "_idle_fire");
  INTERN(S_seq_ctr, "_seq");
  INTERN(S_on_first, "on_first");
#undef INTERN
  O_zero = PyLong_FromLong(0);
  O_one = PyLong_FromLong(1);
  O_false = Py_False;
  Py_INCREF(O_false);
  O_kind_dgram = PyLong_FromLong(KIND_DGRAM);
  if (!O_zero || !O_one || !O_kind_dgram) return NULL;
  if (PyType_Ready(&Core_Type) < 0 || PyType_Ready(&GossipState_Type) < 0
      || PyType_Ready(&CEp_Type) < 0 || PyType_Ready(&CRelay_Type) < 0
      || PyType_Ready(&CBatch_Type) < 0
      || PyType_Ready(&CTorSink_Type) < 0
      || PyType_Ready(&CExitStream_Type) < 0)
    return NULL;
  PyObject *m = PyModule_Create(&colcore_module);
  if (!m) return NULL;
  /* checkpoint state-format fingerprint (shadow_tpu/checkpoint.py): a
   * checkpoint carrying C-engine state records this value in its header
   * and loading refuses a mismatch by name. Bump on ANY change to the
   * _export_state/_restore_state layouts. */
  /* ABI 3 (PR 9): CEp grew the SACK scoreboard + congestion-control
   * seam (cc_kind, w_max/epoch_start, in_recovery/recover/sack_high,
   * sacked/rtx_done seq sets) in _export_state and the fingerprint —
   * ABI-2 checkpoints restore the wrong field count and must refuse by
   * name. (ABI 2 was the uid canonical-event-key change.)
   * ABI 4 (PR 11): the transport column snapshot/adopt surface
   * (Core.transport_columns / adopt_transport_columns) joined the
   * state contract, paired with checkpoint VERSION 4 (the Python
   * StreamSender scoreboards became sorted lists — the canonical form
   * both column exports and CEp's sorted-tuple export already used). */
  PyModule_AddIntConstant(m, "ABI", 4);
  Py_INCREF(&Core_Type);
  PyModule_AddObject(m, "Core", (PyObject *)&Core_Type);
  Py_INCREF(&GossipState_Type);
  PyModule_AddObject(m, "GossipState", (PyObject *)&GossipState_Type);
  Py_INCREF(&CEp_Type);
  PyModule_AddObject(m, "Endpoint", (PyObject *)&CEp_Type);
  Py_INCREF(&CRelay_Type);
  PyModule_AddObject(m, "Relay", (PyObject *)&CRelay_Type);
  return m;
}
