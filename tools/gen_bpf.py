"""Generate the seccomp BPF program for native/shim/shim.c.

The filter grew past the point where hand-maintained relative jump offsets
are reviewable; this script owns the layout and emits the C table between
the GENERATED-BPF markers. Run after changing the trap sets:

    python tools/gen_bpf.py        # rewrites native/shim/shim.c in place
"""

from __future__ import annotations

from pathlib import Path

SYS = dict(read=0, write=1, open=2, close=3, stat=4, fstat=5, lstat=6,
           mmap=9,
           poll=7, lseek=8, pread64=17, pwrite64=18,
           access=21, getcwd=79, chdir=80, fchdir=81, rename=82, mkdir=83,
           rmdir=84, creat=85, unlink=87, readlink=89, truncate=76,
           ftruncate=77, fsync=74, fdatasync=75, getdents64=217,
           openat=257, mkdirat=258, unlinkat=263, renameat=264,
           readlinkat=267, faccessat=269, renameat2=316, statx=332,
           faccessat2=439,
           rt_sigprocmask=14,
           ioctl=16, readv=19, writev=20, pipe=22, dup=32, dup2=33,
           nanosleep=35,
           getpid=39, socket=41, recvmsg=47, clone=56, clone_end=60,
           fcntl=72, gettimeofday=96, getppid=110, gettid=186, futex=202,
           time=201,
           epoll_create=213, clock_gettime=228, clock_nanosleep=230,
           epoll_wait=232, epoll_ctl=233, ppoll=271, epoll_pwait=281,
           timerfd_create=283, eventfd=284, timerfd_settime=286,
           timerfd_gettime=287, accept4=288, eventfd2=290,
           epoll_create1=291, dup3=292, pipe2=293, getrandom=318,
           newfstatat=262,
           wait4=61, execve=59, exit_group=231, clone3=435,
           close_range=436, select=23, pselect6=270, kill=62,
           uname=63, times=100, clock_getres=229,
           sched_getaffinity=204, sysinfo=99, getrusage=98,
           sendfile=40, sigaltstack=131,
           getrlimit=97, setrlimit=160, prlimit64=302,
           signalfd=282, signalfd4=289, splice=275, tee=276,
           inotify_init=253, inotify_init1=294,
           inotify_add_watch=254, inotify_rm_watch=255)

CLONE_THREAD = 0x10000

#: syscalls trapped unconditionally (beyond the 41..59 socket/clone range)
UNCONDITIONAL = [
    "nanosleep", "clock_nanosleep", "clock_gettime", "gettimeofday", "time",
    "getrandom", "poll", "ppoll", "epoll_create", "epoll_create1",
    "epoll_ctl", "epoll_wait", "epoll_pwait", "accept4", "clone3",
    "getpid", "getppid", "gettid", "timerfd_create", "timerfd_settime",
    "timerfd_gettime", "eventfd", "eventfd2", "futex",
    "rt_sigprocmask", "pipe", "pipe2", "wait4", "exit_group",
    "close_range", "select", "pselect6", "kill", "uname", "times",
    "clock_getres", "sched_getaffinity", "sysinfo", "getrusage",
    # the virtual file surface: path-taking syscalls ALWAYS trap — the
    # worker resolves the path against the per-host virtual FS and either
    # emulates (host data dir, synthesized /etc files) or instructs a
    # native re-issue through the gadget (system paths: /lib, /proc, ...)
    "open", "openat", "creat", "stat", "lstat", "statx", "access",
    "faccessat", "faccessat2", "newfstatat", "unlink", "unlinkat",
    "mkdir", "mkdirat", "rmdir", "rename", "renameat", "renameat2",
    "readlink", "readlinkat", "chdir", "getcwd", "truncate",
    # dup2/dup3 trap ALWAYS: a native dup2 over a fd number that carries a
    # VIRTUAL mapping (a shell restoring its saved stdout after `cmd >
    # file`) must clear the worker's mapping or the two fd tables diverge
    "dup2", "dup3",
    # round 5 syscall-family breadth (SURVEY §2 SyscallHandler): resource
    # limits and signal/file-event fds are part of the deterministic
    # virtual identity; sendfile/splice/tee bridge the virtual file
    # surface into sockets and pipes (all-real-fd cases RETRY_NATIVE)
    "sendfile", "sigaltstack", "getrlimit", "setrlimit", "prlimit64",
    "signalfd", "signalfd4", "splice", "tee",
    "inotify_init", "inotify_init1", "inotify_add_watch",
    "inotify_rm_watch",
]

#: syscalls trapped only when arg0 is a virtual fd
VFD_CONDITIONAL = ["ioctl", "fcntl", "dup",
                   "fstat", "lseek", "getdents64", "ftruncate", "fsync",
                   "fdatasync", "fchdir", "pread64", "pwrite64"]

#: syscalls trapped only when arg4 is a virtual fd (mmap's fd slot;
#: MAP_ANONYMOUS passes fd=-1 which wraps past the negative-fd carve-out)
FD4_CONDITIONAL = ["mmap"]


def build(audit: bool = False):
    """audit=True emits the reality-boundary variant: syscalls are
    allowed ONLY from the shim's fixed-address syscall gadget; everything
    the guest issues itself traps, and the SIGSYS handler counts + relays
    unemulated numbers natively (native/shim/shim.c audit path). The
    functional carve-outs (thread clones, the shim's re-exec, IPC-window
    reads) keep their ALLOW branches; all default/real-fd ALLOWs become
    TRAPs."""
    A = "TRAP" if audit else "ALLOW"  # default disposition
    prog: list = []
    prog.append(("LD_ARCH",))
    prog.append(("JEQ", "ARCH", None, "ALLOW"))
    # syscalls issued from the gadget page run natively in BOTH filters:
    # the worker's RETRY_NATIVE sentinel makes the shim re-issue a trapped
    # syscall through the gadget (virtual-FS passthrough), and audit mode
    # additionally default-traps everything else. The kernel reports the
    # IP AFTER the syscall insn, still inside the page.
    prog.append(("LD_IPHI",))
    prog.append(("JEQ", "GADHI", None, "NRSTART"))
    prog.append(("LD_IPLO",))
    prog.append(("JGE", "GADLO", None, "NRSTART"))
    prog.append(("JGE", "GADEND", "NRSTART", "ALLOW"))
    labels0 = {}
    labels0["NRSTART"] = len(prog)
    prog.append(("LD_NR",))
    if audit:
        # sigreturn must stay native or the SIGSYS handler cannot return
        prog.append(("JEQ", 15, "ALLOW", None))  # rt_sigreturn
    prog.append(("JEQ", SYS["read"], "READ", None))
    prog.append(("JEQ", SYS["write"], "WRITE", None))
    # close traps for vfds AND the reserved IPC window: guests sweeping
    # "all fds" (subprocess close_fds) must not sever their own channels
    prog.append(("JEQ", SYS["close"], "CLOSECHK", None))
    prog.append(("JEQ", SYS["readv"], "READ", None))
    prog.append(("JEQ", SYS["writev"], "WRITE", None))
    for name in VFD_CONDITIONAL:
        prog.append(("JEQ", SYS[name], "VFDCHK", None))
    for name in FD4_CONDITIONAL:
        prog.append(("JEQ", SYS[name], "VFD4CHK", None))
    for name in UNCONDITIONAL:
        prog.append(("JEQ", SYS[name], "TRAP", None))
    # recvmsg on a worker IPC channel runs natively (SCM_RIGHTS receive of
    # per-thread channels); on any other fd it is emulated
    prog.append(("JEQ", SYS["recvmsg"], "IPCRD", None))
    # thread-style clones run natively (pthread_create is interposed);
    # fork-style trap so the worker can reject them loudly
    prog.append(("JEQ", SYS["clone"], "CLONECHK", None))
    prog.append(("JGE", SYS["socket"], None, A))
    prog.append(("JGE", SYS["clone_end"], A, "TRAP"))
    labels = labels0
    labels["READ"] = len(prog)
    prog += [("LD_A0",), ("JGE", "IPCLOW", None, "READCHK"),
             ("JGE", "IPCEND", "READCHK", "ALLOW")]
    labels["READCHK"] = len(prog)
    prog += [("JEQ", 0, "TRAP", None), ("JGE", "VFD", "TRAP", A)]
    labels["WRITE"] = len(prog)
    prog += [("LD_A0",), ("JGE", "IPCLOW", None, "WRITECHK"),
             ("JGE", "IPCEND", "WRITECHK", "ALLOW")]
    labels["WRITECHK"] = len(prog)
    prog += [("JGE", 3, None, "TRAP"), ("JGE", "VFD", "TRAP", A)]
    labels["IPCRD"] = len(prog)
    prog += [("LD_A0",), ("JGE", "IPCLOW", None, "TRAP"),
             ("JGE", "IPCEND", "TRAP", "ALLOW")]
    labels["CLONECHK"] = len(prog)
    # thread-style clones run natively (pthread_create is interposed);
    # everything else traps — the shim's own fork replay rides the gadget
    # IP allowance, so no marker-flag escape hatch exists anymore
    prog += [("LD_A0",), ("JSET", CLONE_THREAD, "ALLOW", "TRAP")]
    labels["CLOSECHK"] = len(prog)
    prog += [("LD_A0",), ("JGE", "IPCLOW", None, "VFDTAIL"),
             ("JGE", "IPCEND", "VFDTAIL", "TRAP")]
    labels["VFD4CHK"] = len(prog)
    prog += [("LD_A4",), ("JGE", 0, "VFDTAIL", "VFDTAIL")]
    labels["VFDCHK"] = len(prog)
    # negative fds (AT_FDCWD = -100 as a newfstatat dirfd) wrap to huge
    # unsigned values: let them through natively
    prog += [("LD_A0",)]
    labels["VFDTAIL"] = len(prog)
    prog += [("JGE", "VFD", None, A),
             ("JGE", 0xFFFFF000, A, "TRAP")]
    labels["TRAP"] = len(prog)
    prog.append(("RET_TRAP",))
    labels["ALLOW"] = len(prog)
    prog.append(("RET_ALLOW",))

    names = {v: k for k, v in SYS.items()}

    def val(v):
        return {"ARCH": "AUDIT_ARCH_X86_64", "IPC": "SHIM_IPC_FD",
                "IPCLOW": "SHIM_IPC_LOW", "IPCEND": "(SHIM_IPC_FD + 1)",
                "GADLO": "(uint32_t)(uintptr_t)SHIM_GADGET_ADDR",
                "GADHI": "(uint32_t)((uintptr_t)SHIM_GADGET_ADDR >> 32)",
                "GADEND": "((uint32_t)(uintptr_t)SHIM_GADGET_ADDR + 4096)",
                "VFD": "SHIM_VFD_BASE"}.get(v, str(v))

    out = []
    for i, ins in enumerate(prog):
        k = ins[0]
        simple = {"LD_ARCH": "LD(BPF_ARCHF),", "LD_NR": "LD(BPF_NR),",
                  "LD_A0": "LD(BPF_ARG0),", "LD_A4": "LD(BPF_ARG4),",
                  "LD_IPLO": "LD(BPF_IPLO),", "LD_IPHI": "LD(BPF_IPHI),",
                  "LD_A2LO": "LD(BPF_ARG2LO),", "LD_A2HI": "LD(BPF_ARG2HI),",
                  "RET_TRAP": "RET(SECCOMP_RET_TRAP),",
                  "RET_ALLOW": "RET(SECCOMP_RET_ALLOW),"}
        if k in simple:
            out.append("      " + simple[k])
            continue
        _, v, t, f = ins

        def off(lbl):
            if lbl is None:
                return 0
            d = labels[lbl] - (i + 1)
            assert 0 <= d < 256, (i, lbl, d)
            return d

        cmt = f"  /* {names.get(v, '')} */" if isinstance(v, int) and v in names else ""
        if k == "JSET":
            cmt = ("  /* CLONE_THREAD */" if v == CLONE_THREAD
                   else "  /* CLONE_IO (shim fork replay) */")
        op = {"JEQ": "JEQ", "JGE": "JGE", "JSET": "JSET"}[k]
        out.append(f"      {op}({val(v)}, {off(t)}, {off(f)}),{cmt}")
    return len(prog), "\n".join(out)


def emu_bitmap():
    """512-bit bitmap of syscall numbers the worker emulates whenever they
    trap with no fd condition (the SIGSYS handler's audit fallback checks
    this; fd-conditional numbers are decided in C)."""
    bits = bytearray(64)
    nrs = [SYS[n] for n in UNCONDITIONAL] + list(range(SYS["socket"],
                                                       SYS["clone_end"]))
    for nr in nrs:
        bits[nr >> 3] |= 1 << (nr & 7)
    rows = []
    for i in range(0, 64, 8):
        rows.append("    " + " ".join(f"0x{b:02x}," for b in bits[i:i + 8]))
    return "\n".join(rows)


def main():
    shim = Path(__file__).resolve().parents[1] / "native" / "shim" / "shim.c"
    src = shim.read_text()
    begin = "  /* BEGIN GENERATED BPF (tools/gen_bpf.py) */\n"
    end = "  /* END GENERATED BPF */"
    n, table = build()
    na, table_a = build(audit=True)
    i, j = src.index(begin) + len(begin), src.index(end)
    src = (src[:i]
           + f"  struct sock_filter prog[] = {{  /* {n} instructions */\n"
           + table + "\n  };\n"
           + f"  struct sock_filter prog_audit[] = {{"
           + f"  /* {na} instructions */\n"
           + table_a + "\n  };\n" + src[j:])
    bbegin = "/* BEGIN GENERATED EMU BITMAP (tools/gen_bpf.py) */\n"
    bend = "/* END GENERATED EMU BITMAP */"
    i, j = src.index(bbegin) + len(bbegin), src.index(bend)
    src = (src[:i] + "static const uint8_t shim_emu_bitmap[64] = {\n"
           + emu_bitmap() + "\n};\n" + src[j:])
    cbegin = "  /* BEGIN GENERATED VFD CASES (tools/gen_bpf.py) */\n"
    cend = "  /* END GENERATED VFD CASES */"
    i, j = src.index(cbegin) + len(cbegin), src.index(cend)
    cases = " ".join(f"case {SYS[n]}:" for n in VFD_CONDITIONAL)
    src = (src[:i] + f"  {cases}  /* {' '.join(VFD_CONDITIONAL)} */\n"
           + src[j:])
    shim.write_text(src)
    print(f"wrote {n}+{na}-instruction filters into {shim}")


if __name__ == "__main__":
    main()
