#!/usr/bin/env python
"""N-seed simulation sweeps with mergeable cross-run statistics.

Thin CLI over :mod:`shadow_tpu.fleet` (the "Once is Never Enough"
workflow — PAPERS.md):

    # run a 10-seed sweep, 2 concurrent members, shared device attach
    python tools/sweep.py config.yaml --seeds 10 --jobs 2

    # continue a partially-completed sweep (per-seed manifests decide)
    python tools/sweep.py config.yaml --seeds 10 --jobs 2 \
        --sweep-dir my.sweep --resume

    # re-reduce + render an existing sweep directory
    python tools/sweep.py --report my.sweep

Equivalent to ``python -m shadow_tpu.fleet sweep ...`` / ``... report``;
see README "Fleet mode" for the output layout and CI semantics.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shadow_tpu import fleet  # noqa: E402


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--report" in argv:
        # tools/sweep.py --report <dir>  ==  fleet report <dir>
        argv.remove("--report")
        return fleet.main(["report"] + argv)
    return fleet.main(["sweep"] + argv)


if __name__ == "__main__":
    sys.exit(main())
