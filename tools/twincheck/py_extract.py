"""AST extractors over the Python twins for the twin-contract auditor.

Counterpart of c_extract.py: pulls the contract-bearing surfaces out of
`network/transport.py` (constants, fingerprint arity, congestion-control
registry, cubic arithmetic literals), `config/schema.py` (enum-name
duplicates), `checkpoint.py` (format VERSION), `network/unit.py` (unit
kinds), and the whole `shadow_tpu/` tree (counter-name string literals,
identifier vocabulary).  Extractors raise ExtractError when an anchor is
missing so a refactor that moves a contract surface fails the audit
loudly instead of silently narrowing it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path


class ExtractError(Exception):
    pass


def parse(path) -> ast.Module:
    return ast.parse(Path(path).read_text(), filename=str(path))


# -- module constants ---------------------------------------------------------

def _eval_const(node: ast.AST, env: dict):
    """Evaluate an int-valued constant expression of literals, names in
    ``env``, and + - * // << >> (the shapes the twins use)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, bool)):
        return int(node.value)
    if isinstance(node, ast.Name):
        v = env.get(node.id)
        return v if isinstance(v, int) else None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _eval_const(node.operand, env)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        a = _eval_const(node.left, env)
        b = _eval_const(node.right, env)
        if a is None or b is None:
            return None
        op = node.op
        if isinstance(op, ast.Mult):
            return a * b
        if isinstance(op, ast.Add):
            return a + b
        if isinstance(op, ast.Sub):
            return a - b
        if isinstance(op, ast.FloorDiv):
            return a // b
        if isinstance(op, ast.LShift):
            return a << b
        if isinstance(op, ast.RShift):
            return a >> b
    return None


def module_constants(tree: ast.Module, env: dict = None) -> dict:
    """Top-level ``NAME = <int expr>`` assignments, evaluated with
    ``env`` as the starting name environment (accumulating, so later
    constants may reference earlier ones)."""
    out = dict(env or {})
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target = node.target.id  # NAME: SomeType = <int expr>
        if target is not None:
            v = _eval_const(node.value, out)
            if v is not None:
                out[target] = v
    return out


def range_enum(tree: ast.Module) -> dict:
    """``A, B, C = range(n)`` at module level -> {"A": 0, "B": 1, ...}
    (network/unit.py's kind enum)."""
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "range"):
            names = [e.id for e in node.targets[0].elts
                     if isinstance(e, ast.Name)]
            return {n: i for i, n in enumerate(names)}
    raise ExtractError("no `A, B, ... = range(n)` enum found")


# -- classes and methods ------------------------------------------------------

def class_def(tree: ast.Module, name: str) -> ast.ClassDef:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    raise ExtractError("class %r not found" % name)


def method_def(cls: ast.ClassDef, name: str) -> ast.FunctionDef:
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    raise ExtractError("method %s.%s not found" % (cls.name, name))


def func_def(tree: ast.Module, name: str) -> ast.FunctionDef:
    """Module-level function def (ops/transport_kernels.py's kernel
    functions — the third twin surface)."""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    raise ExtractError("function %r not found" % name)


def class_attr(cls: ast.ClassDef, attr: str):
    for node in cls.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == attr \
                and isinstance(node.value, ast.Constant):
            return node.value.value
    raise ExtractError("class attr %s.%s not found" % (cls.name, attr))


def return_tuple_arity(fn: ast.FunctionDef) -> int:
    """Element count of the LAST ``return (a, b, ...)`` in the function
    (StreamEndpoint.fingerprint's shape)."""
    rets = [n for n in ast.walk(fn) if isinstance(n, ast.Return)
            and isinstance(n.value, ast.Tuple)]
    if not rets:
        raise ExtractError("%s has no tuple return" % fn.name)
    return len(rets[-1].value.elts)


def dict_literal_keys(tree: ast.Module, name: str) -> dict:
    """``NAME = {"k": Value, ...}`` -> {"k": "Value"} (value = the
    Name id, e.g. the class object assigned)."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, ast.Dict):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Name):
                    out[k.value] = v.id
            return out
    raise ExtractError("dict literal %r not found" % name)


def string_tuple(tree: ast.Module, name: str) -> tuple:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == name \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return tuple(e.value for e in node.value.elts
                         if isinstance(e, ast.Constant))
    raise ExtractError("string tuple %r not found" % name)


def int_literal_set(fn: ast.FunctionDef, env: dict, minval: int = 3) -> set:
    """Set of integer literals >= minval in the method body, with Name
    loads resolved through ``env`` (module constants) — the Python half
    of the cubic-arithmetic cross-check.  Shift amounts appear as their
    raw literal (`1 << 32` contributes 32), matching the C side's
    raw-token view."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool) and node.value >= minval:
            out.add(node.value)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            v = env.get(node.id)
            if isinstance(v, int) and v >= minval:
                out.add(v)
    return out


# -- tree-wide scans ----------------------------------------------------------

def counter_names(py_files) -> set:
    """Every string literal used as ``<x>.add("name", ...)`` first
    argument across the tree — the Python counter-name vocabulary the C
    engine's fold tables must stay inside."""
    names = set()
    for path in py_files:
        try:
            tree = parse(path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                names.add(node.args[0].value)
    return names


_IDENT = re.compile(r"[A-Za-z_]\w*")


def identifier_vocab(py_files) -> set:
    """The identifier vocabulary of the Python tree (cheap regex scan).
    Used to verify every attribute name the C engine interns still
    exists somewhere in the Python twins — catches renames like
    `_uid_counter` -> something that would leave the C side reading a
    stale attribute."""
    vocab = set()
    for path in py_files:
        vocab.update(_IDENT.findall(Path(path).read_text()))
    return vocab
