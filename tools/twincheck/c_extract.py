"""Light C extractor over native/colcore/colcore.c for the twin-contract
auditor (tools/twincheck/twin_audit.py).

This is deliberately NOT a C parser: colcore.c is hand-written in a
narrow, consistent style (K&R braces, one function per `static ...
name(...) {` header, object-like `#define`s, `Py_BuildValue`/
`PyArg_ParseTuple` with adjacent string literals), and the auditor only
needs the contract-bearing surfaces: `#define`d constants, the module
ABI constant, format-string arities, interned-name tables, struct field
lists, and integer literals inside named function bodies.  Every
extractor RAISES ExtractError when its anchor is missing — an audit that
cannot find its subject must fail loudly, not report a clean tree.
"""

from __future__ import annotations

import re


class ExtractError(Exception):
    """An expected anchor (function, define, table) was not found."""


# -- source preparation -------------------------------------------------------

def strip_comments(src: str) -> str:
    """Blank out /* */ and // comments and string/char literals' inner
    text is LEFT ALONE (extractors that need literals run before this or
    use the raw source).  Newlines are preserved so line numbers hold."""
    out = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("".join(ch if ch == "\n" else " " for ch in src[i:j]))
            i = j
        elif c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == '"':
            j = i + 1
            while j < n and src[j] != '"':
                j += 2 if src[j] == "\\" else 1
            out.append(src[i:j + 1])
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


# -- constants ----------------------------------------------------------------

_DEFINE_RE = re.compile(r"^#define\s+([A-Za-z_]\w*)\s+(.+?)\s*$", re.M)


def defines(src: str) -> dict:
    """Object-like `#define NAME value` map (function-like macros are
    skipped).  Values are the raw replacement text."""
    out = {}
    for m in _DEFINE_RE.finditer(strip_comments(src)):
        name, val = m.group(1), m.group(2).strip()
        if name.endswith("("):  # never happens with this regex, guard anyway
            continue
        # function-like macro: NAME(args) — the '(' abuts the name
        probe = src.find("#define " + name)
        if probe >= 0 and src[probe + 8 + len(name):probe + 9 + len(name)] == "(":
            continue
        out[name] = val
    return out


_INT_TOKEN = re.compile(r"^[0-9]+$")


def eval_cexpr(expr: str, env: dict):
    """Evaluate a small constant C expression: integer literals (decimal
    or hex) with L/LL/U suffixes, parentheses, + - * / << >>, and names
    resolvable in ``env``.  Returns None when the expression uses
    anything else."""
    toks = re.findall(
        r"0[xX][0-9a-fA-F]+[uUlL]*|[A-Za-z_]\w*|\d+|<<|>>|[()+\-*/]", expr)
    if "".join(toks) != re.sub(r"\s+", "", expr):
        # token stream lost characters -> unsupported syntax (bit-ops,
        # casts, ternaries): refuse rather than mis-evaluate
        return None
    py = []
    for t in toks:
        if re.match(r"^0[xX][0-9a-fA-F]+[uUlL]*$", t):
            py.append("%d" % int(re.sub(r"[uUlL]+$", "", t), 16))
        elif _INT_TOKEN.match(t):
            py.append(t)
        elif re.match(r"^\d+(?:[uUlL]+)$", t):
            py.append(re.sub(r"[uUlL]+$", "", t))
        elif t in ("(", ")", "+", "-", "*", "<<", ">>"):
            py.append(t)
        elif t == "/":
            py.append("//")  # positive constant division in this codebase
        elif t in env:
            v = env[t]
            if v is None:
                return None
            py.append("(%d)" % v)
        elif re.match(r"^[uUlL]+$", t):
            continue  # literal suffix split off by the tokenizer
        else:
            return None
    try:
        return int(eval(" ".join(py), {"__builtins__": {}}))  # noqa: S307
    except Exception:
        return None


def resolve_defines(src: str) -> dict:
    """defines() with values evaluated to ints where possible (two
    passes so defines may reference earlier defines)."""
    raw = defines(src)
    # strip literal suffixes like 60000000000LL before evaluation
    env: dict = {}
    for _ in range(3):
        for k, v in raw.items():
            if k not in env or env[k] is None:
                env[k] = eval_cexpr(re.sub(r"(\d)[uUlL]+\b", r"\1", v), env)
    return env


def module_int_constant(src: str, name: str) -> int:
    """`PyModule_AddIntConstant(m, "NAME", value)` -> value."""
    m = re.search(
        r'PyModule_AddIntConstant\s*\(\s*\w+\s*,\s*"%s"\s*,\s*([^)]+)\)'
        % re.escape(name), src)
    if not m:
        raise ExtractError("PyModule_AddIntConstant %r not found" % name)
    v = eval_cexpr(m.group(1), {})
    if v is None:
        raise ExtractError("module constant %r is not a literal" % name)
    return v


# -- function bodies ----------------------------------------------------------

def function_body(src: str, name: str) -> str:
    """Body text (between the outermost braces) of the function whose
    definition header contains ``name(``.  Matches the FIRST definition
    (colcore.c forward-declares with `;`, defines once)."""
    clean = strip_comments(src)
    for m in re.finditer(r"\b%s\s*\(" % re.escape(name), clean):
        # find the closing paren of the parameter list
        i = m.end() - 1
        depth = 0
        while i < len(clean):
            if clean[i] == "(":
                depth += 1
            elif clean[i] == ")":
                depth -= 1
                if depth == 0:
                    break
            i += 1
        # definition iff the next non-space char is '{'
        j = i + 1
        while j < len(clean) and clean[j] in " \t\n":
            j += 1
        if j >= len(clean) or clean[j] != "{":
            continue  # declaration or call
        # brace-match the body
        depth, k = 0, j
        while k < len(clean):
            if clean[k] == "{":
                depth += 1
            elif clean[k] == "}":
                depth -= 1
                if depth == 0:
                    return clean[j + 1:k]
            k += 1
        raise ExtractError("unbalanced braces in %s" % name)
    raise ExtractError("function %r not found" % name)


# -- format strings -----------------------------------------------------------

def _call_string_arg(body: str, callee: str) -> str:
    """The leading adjacent-string-literal argument of the first
    ``callee(...)`` call in ``body`` (skipping non-string leading args,
    e.g. PyArg_ParseTuple's object argument)."""
    m = re.search(r"\b%s\s*\(" % re.escape(callee), body)
    if not m:
        raise ExtractError("no %s call found" % callee)
    seg = body[m.end():m.end() + 4000]
    sm = re.search(r'"((?:[^"\\]|\\.)*)"(?:\s*"((?:[^"\\]|\\.)*)")*', seg)
    if not sm:
        raise ExtractError("no string literal in %s call" % callee)
    # re-scan to concatenate every adjacent literal
    parts = re.findall(r'"((?:[^"\\]|\\.)*)"', seg[sm.start():])
    # adjacent literals only: stop at the first token that isn't a string
    out, pos, sub = [], sm.start(), seg[sm.start():]
    for pm in re.finditer(r'\s*"((?:[^"\\]|\\.)*)"', sub):
        if pm.start() != pos - sm.start():
            break
        out.append(pm.group(1))
        pos = sm.start() + pm.end()
    return "".join(out or parts[:1])


def buildvalue_format(src: str, func: str) -> str:
    return _call_string_arg(function_body(src, func), "Py_BuildValue")


def parsetuple_format(src: str, func: str) -> str:
    return _call_string_arg(function_body(src, func), "PyArg_ParseTuple")


def format_codes(fmt: str) -> list:
    """Per-element type codes of a Py_BuildValue/PyArg_ParseTuple format
    (outer parens stripped, separators dropped).  Every code used by
    colcore.c is single-character."""
    fmt = fmt.strip()
    if fmt.startswith("(") and fmt.endswith(")"):
        fmt = fmt[1:-1]
    codes = []
    for ch in fmt:
        if ch in "(),:;| $":
            continue
        codes.append(ch)
    return codes


# -- tables and structs -------------------------------------------------------

def string_array(src: str, var: str) -> list:
    """`static const char *var[N] = {"a", "b", ...}` -> ["a", "b", ...]."""
    m = re.search(r"\*\s*%s\s*\[[^]]*\]\s*=\s*\{" % re.escape(var), src)
    if not m:
        raise ExtractError("string table %r not found" % var)
    seg = src[m.end():src.find("}", m.end())]
    return re.findall(r'"([^"]+)"', seg)


def struct_fields(src: str, name: str) -> list:
    """Field names of `typedef struct name { ... } name;`."""
    clean = strip_comments(src)
    m = re.search(r"typedef\s+struct\s+%s\s*\{" % re.escape(name), clean)
    if not m:
        raise ExtractError("struct %r not found" % name)
    end = clean.find("} %s;" % name, m.end())
    if end < 0:
        raise ExtractError("struct %r not terminated" % name)
    body = clean[m.end():end]
    fields = []
    for stmt in body.split(";"):
        stmt = stmt.strip()
        if not stmt or stmt.startswith("#"):
            continue
        # drop PyObject_HEAD-style macros with no declarator
        if re.fullmatch(r"[A-Za-z_]\w*", stmt):
            continue
        # `type a, b, c` / `struct X *a` / `Ring r` — take the trailing
        # identifiers of each comma-separated declarator
        decl = stmt.split("{")[-1]
        for piece in decl.split(","):
            im = re.search(r"([A-Za-z_]\w*)\s*(?:\[[^]]*\])?\s*$", piece)
            if im:
                fields.append(im.group(1))
    return fields


def intern_calls_outside_init(src: str) -> list:
    """(lineno, line) for every PyUnicode_InternFromString call outside
    the module init function (where the INTERN macro checks the result
    and the reference is intentionally immortal).  Anywhere else the
    call leaks a reference per call and its NULL return is typically
    unchecked — the pattern PR 9 review caught once already."""
    clean = strip_comments(src)
    init = re.search(r"PyMODINIT_FUNC\s+PyInit_\w+\s*\(", clean)
    init_span = (0, 0)
    if init:
        j = clean.find("{", init.end())
        depth, k = 0, j
        while k < len(clean):
            if clean[k] == "{":
                depth += 1
            elif clean[k] == "}":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        init_span = (init.start(), k)
    out = []
    for m in re.finditer(r"PyUnicode_InternFromString", clean):
        if init_span[0] <= m.start() <= init_span[1]:
            continue
        line = clean.count("\n", 0, m.start()) + 1
        text = src.splitlines()[line - 1].strip()
        out.append((line, text))
    return out


def interned_names(src: str) -> list:
    """Every string interned through the module-init INTERN(var, "s")
    table — the C side's attribute-name contract with the Python twins."""
    body = None
    m = re.search(r"PyMODINIT_FUNC\s+PyInit_\w+", src)
    if not m:
        raise ExtractError("module init not found")
    return re.findall(r'INTERN\(\s*\w+\s*,\s*"([^"]+)"\s*\)', src)


def int_literals(src: str, func: str, env: dict, minval: int = 3) -> list:
    """Integer literals (and env-resolvable identifiers) >= minval in
    the body of ``func``, in source order.  Shift amounts count as their
    literal value (both twins write `1 << 32` / `(1LL << 32)` so the
    raw-token view matches)."""
    body = function_body(src, func)
    out = []
    for t in re.findall(r"[A-Za-z_]\w*|\d+", body):
        if t.isdigit():
            v = int(t)
        elif re.fullmatch(r"\d+[uUlL]+", t):
            v = int(re.sub(r"[uUlL]+$", "", t))
        elif t in env and isinstance(env.get(t), int):
            v = env[t]
        else:
            continue
        if v >= minval:
            out.append(v)
    return out
