"""Determinism linter over shadow_tpu/ — AST rules for the discipline
every identity gate depends on: no wall-clock, no global RNG, no
unordered iteration or filesystem-order dependence in anything that can
feed trees/flows/digests, no id()-derived ordering, and no environment
reads outside the documented SHADOW_*/JAX_* surface.

Rules (stable ids, asserted by tests/test_twincheck.py):

  wallclock       `import time` / `import datetime` or calls through
                  them.  The sanctioned escape hatch is the repo
                  convention `import time as _walltime` carrying an
                  inline waiver — one documented line per module makes
                  every deliberate wall-clock consumer auditable, and
                  any NEW `time` import without a written reason fails.
  modrandom       stdlib `random` (global Mersenne state) or numpy
                  global-state RNG (np.random.seed/rand/randint/...).
                  Simulation randomness must come through the
                  counter-based constructions in core/rng.py.
  unordered-iter  iteration/materialization of a set expression, or an
                  os.listdir/glob/iterdir/scandir result, without
                  sorted(...) — set order is hash-seed dependent and
                  directory order is filesystem dependent.  Set
                  iteration is only flagged inside digest/fingerprint/
                  export/serialize functions; filesystem listings are
                  flagged module-wide.
  idorder         id() used as an ordering key (sorted/sort/min/max
                  key=id, or id() under <,>,<=,>=) — CPython addresses
                  change run to run.
  envread         os.environ/os.getenv with a name outside the
                  SHADOW_*/JAX_*/XLA_* allowlist, a non-literal name
                  that doesn't resolve to one, or a whole-environment
                  read.

Waivers: append ``# detlint: ok(<rule>): <reason>`` to the flagged line
(or the line directly above).  A waiver with an empty reason is itself a
finding (`waiver-reason`) — the point is the documented WHY, in place.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from report import Finding

#: functions whose bodies are canonical-serialization / digest paths —
#: set iteration inside them must be sorted
DIGEST_FN_RE = re.compile(
    r"(fingerprint|digest|_feed|export_state|serialize|canonical)",
    re.I)

#: env-name prefixes the simulator may read (the documented config
#: surface; SHADOW_* covers SHADOW_TPU_* and SHADOW_SHIM_*)
ENV_ALLOW_RE = re.compile(r"^(SHADOW_|JAX_|XLA_)")

WALLCLOCK_MODULES = {"time", "datetime"}

NP_GLOBAL_RNG = {"seed", "rand", "randn", "randint", "random", "choice",
                 "shuffle", "permutation", "normal", "uniform",
                 "exponential"}

FS_LIST_CALLS = {"listdir", "scandir", "iterdir", "glob", "rglob",
                 "iglob"}

WAIVER_RE = re.compile(r"#\s*detlint:\s*ok\(([\w-]+)\)\s*:?\s*(.*)$")


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str):
        self.path = path
        self.lines = src.splitlines()
        self.findings: list = []
        self.waivers: list = []  # (line, rule, reason)
        #: local alias -> wallclock module ("time"/"datetime")
        self.clock_aliases: dict = {}
        #: local alias -> "np" for `import numpy as np`
        self.np_aliases: set = set()
        #: module-level str constants (for env-name resolution)
        self.str_consts: dict = {}
        self._fn_stack: list = []
        for ln, text in enumerate(self.lines, 1):
            m = WAIVER_RE.search(text)
            if m:
                self.waivers.append((ln, m.group(1), m.group(2).strip()))

    # -- plumbing ------------------------------------------------------------

    def flag(self, rule: str, node: ast.AST, msg: str):
        line = getattr(node, "lineno", 0)
        for wln, wrule, reason in self.waivers:
            if wrule == rule and wln in (line, line - 1):
                return  # waived in place (reason presence checked globally)
        self.findings.append(Finding(rule, self.path, line, msg))

    def _prescan(self, tree: ast.Module):
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.str_consts[node.targets[0].id] = node.value.value

    # -- imports -------------------------------------------------------------

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            top = alias.name.split(".")[0]
            if top in WALLCLOCK_MODULES:
                self.clock_aliases[alias.asname or top] = top
                self.flag("wallclock", node,
                          "`import %s` in a simulation module — wall "
                          "clocks must never feed sim state; alias as "
                          "_walltime and waive with the reason if this "
                          "is deliberate wall-side telemetry" % alias.name)
            if top == "numpy":
                self.np_aliases.add(alias.asname or top)
            if top == "random":
                self.flag("modrandom", node,
                          "stdlib `random` is global-state Mersenne — "
                          "use the counter-based RNG (core/rng.py)")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        top = (node.module or "").split(".")[0]
        if top in WALLCLOCK_MODULES:
            self.flag("wallclock", node,
                      "`from %s import ...` in a simulation module" %
                      node.module)
        if top == "random":
            self.flag("modrandom", node,
                      "stdlib `random` import — use core/rng.py")
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------------

    def _attr_chain(self, node):
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return list(reversed(parts))
        return None

    def visit_Call(self, node: ast.Call):
        chain = self._attr_chain(node.func) or []
        # (wall-clock coverage is import-site only, by design: the one
        # flagged/waived import line per module documents every call
        # made through its alias)
        # numpy global-state RNG
        if len(chain) == 3 and chain[0] in self.np_aliases \
                and chain[1] == "random" and chain[2] in NP_GLOBAL_RNG:
            self.flag("modrandom", node,
                      "np.random.%s uses numpy global/unseeded RNG state "
                      "— construct an explicit seeded Generator "
                      "(core/rng.py)" % chain[2])
        # default_rng() with no explicit seed draws OS entropy
        if len(chain) == 3 and chain[0] in self.np_aliases \
                and chain[1] == "random" and chain[2] == "default_rng" \
                and not node.args:
            self.flag("modrandom", node,
                      "np.random.default_rng() with no seed draws OS "
                      "entropy — pass an explicit seed")
        # filesystem listing order
        if chain and chain[-1] in FS_LIST_CALLS \
                and not self._sorted_parent(node):
            self.flag("unordered-iter", node,
                      "%s returns entries in filesystem order — wrap in "
                      "sorted(...) before anything that feeds output "
                      "streams" % ".".join(chain))
        # env reads
        if chain[-2:] == ["environ", "get"] or chain[-1:] == ["getenv"]:
            self._check_env_name(node, node.args[0] if node.args else None)
        if chain[-1:] == ["dict"] or (isinstance(node.func, ast.Name)
                                      and node.func.id == "dict"):
            for a in node.args:
                ac = self._attr_chain(a) or []
                if ac[-1:] == ["environ"]:
                    self.flag("envread", node,
                              "whole-environment read — the simulation "
                              "surface is the SHADOW_*/JAX_* allowlist")
        # list(<set>)/tuple(<set>) materialization in digest paths —
        # same hash-seed hazard as iterating the set directly
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("list", "tuple") and node.args \
                and self._in_digest_fn() \
                and self._is_set_expr(node.args[0]) \
                and not self._sorted_parent(node):
            self.flag("unordered-iter", node,
                      "%s() over a set inside a digest/canonical path — "
                      "set order is hash-seed dependent; wrap in "
                      "sorted(...)" % node.func.id)
        # id() as ordering key
        if chain and chain[-1] in ("sorted", "sort", "min", "max"):
            for kw in node.keywords:
                if kw.arg == "key" and isinstance(kw.value, ast.Name) \
                        and kw.value.id == "id":
                    self.flag("idorder", node,
                              "%s(key=id) orders by CPython address — "
                              "never stable across runs" % chain[-1])
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        chain = self._attr_chain(node.value) or []
        if chain[-1:] == ["environ"] and isinstance(node.ctx, ast.Load):
            sl = node.slice
            self._check_env_name(node, sl)
        self.generic_visit(node)

    def _check_env_name(self, node, name_node):
        if isinstance(name_node, ast.Constant) \
                and isinstance(name_node.value, str):
            name = name_node.value
        elif isinstance(name_node, ast.Name) \
                and name_node.id in self.str_consts:
            name = self.str_consts[name_node.id]
        else:
            self.flag("envread", node,
                      "environment read with a name the linter cannot "
                      "resolve — use a literal or module-level constant")
            return
        if not ENV_ALLOW_RE.match(name):
            self.flag("envread", node,
                      "environment read of %r outside the SHADOW_*/JAX_* "
                      "allowlist — env must not steer simulation state" %
                      name)

    # -- comparisons ---------------------------------------------------------

    def visit_Compare(self, node: ast.Compare):
        if any(isinstance(op, (ast.Lt, ast.Gt, ast.LtE, ast.GtE))
               for op in node.ops):
            for operand in [node.left] + node.comparators:
                if isinstance(operand, ast.Call) \
                        and isinstance(operand.func, ast.Name) \
                        and operand.func.id == "id":
                    self.flag("idorder", node,
                              "ordering comparison on id(...) — CPython "
                              "addresses are not stable across runs")
        self.generic_visit(node)

    # -- set iteration in digest paths ---------------------------------------

    def _sorted_parent(self, node) -> bool:
        p = getattr(node, "_dl_parent", None)
        while p is not None:
            if isinstance(p, ast.Call) and isinstance(p.func, ast.Name) \
                    and p.func.id == "sorted":
                return True
            p = getattr(p, "_dl_parent", None)
        return False

    def _is_set_expr(self, node) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("set", "frozenset"):
            return True
        return False

    def _in_digest_fn(self) -> bool:
        return any(DIGEST_FN_RE.search(fn) for fn in self._fn_stack)

    def _check_set_iter(self, node, iter_node):
        if self._in_digest_fn() and self._is_set_expr(iter_node) \
                and not self._sorted_parent(iter_node):
            self.flag("unordered-iter", node,
                      "unsorted set iteration inside a digest/canonical "
                      "path — set order is hash-seed dependent; wrap in "
                      "sorted(...)")

    def visit_For(self, node: ast.For):
        self._check_set_iter(node, node.iter)
        self.generic_visit(node)

    def visit_comprehension_gen(self, node):
        for gen in node.generators:
            self._check_set_iter(node, gen.iter)

    def visit_ListComp(self, node):
        self.visit_comprehension_gen(node)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node):
        self.visit_comprehension_gen(node)
        self.generic_visit(node)

    def visit_DictComp(self, node):
        self.visit_comprehension_gen(node)
        self.generic_visit(node)

    def visit_SetComp(self, node):
        self.visit_comprehension_gen(node)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def _link_parents(tree: ast.Module):
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._dl_parent = parent


def lint_file(path: Path, relpath: str) -> "tuple[list, list]":
    src = path.read_text()
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return ([Finding("parse", relpath, e.lineno or 0, str(e))], [])
    _link_parents(tree)
    linter = _Linter(relpath, src)
    linter._prescan(tree)
    linter.visit(tree)
    out = linter.findings
    # a waiver with no written reason defeats the point of waivers
    for wln, wrule, reason in linter.waivers:
        if not reason:
            out.append(Finding(
                "waiver-reason", relpath, wln,
                "detlint waiver for %r has no written reason — every "
                "deliberate exception must say why, in place" % wrule))
    return out, linter.waivers


def lint(root) -> list:
    findings, _ = lint_with_waivers(root)
    return findings


def lint_with_waivers(root) -> "tuple[list, list]":
    root = Path(root)
    findings: list = []
    waivers: list = []
    for path in sorted((root / "shadow_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = str(path.relative_to(root))
        f, w = lint_file(path, rel)
        findings.extend(f)
        waivers.extend((rel, ln, rule, reason) for ln, rule, reason in w)
    return findings, waivers
