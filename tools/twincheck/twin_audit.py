"""Twin-contract auditor: statically prove the Python/C twin contract.

Every headline identity gate in this repo (byte-identical trees, flows,
digests across the Python and C planes) rests on hand-maintained twin
surfaces: shared constants, the 28-field determinism fingerprint, the
55-field endpoint export, the folded counter-name tables, the interned
attribute names, the congestion-control registry, the cubic arithmetic,
and the checkpoint ABI/VERSION gates.  This module cross-checks those
surfaces between `native/colcore/colcore.c` and the Python twins WITHOUT
running anything, and fails by name on any drift — so a mismatch cannot
merge and wait for a runtime identity matrix to catch it.

Every check emits findings with stable rule ids (asserted by
tests/test_twincheck.py's mutation fixtures):

  const-drift:<NAME>       a shared constant differs between the twins
  fingerprint-arity        StreamEndpoint.fingerprint vs CEp_fingerprint
  export-arity             CEp _export_state vs _restore_state formats
  struct-export:<field>    a CEp struct field neither exported nor exempt
  counter-name:<name>      a C-folded counter name unknown to Python
  attr-name:<name>         an interned C attribute name absent in Python
  cc-enum                  congestion-control registry drift (3 surfaces)
  cubic-arith:<hook>       cubic/newreno literal drift between the twins
  abi-migration            colcore ABI bumped without a MIGRATION entry
  version-migration        checkpoint VERSION bumped without a MIGRATION entry
  c-intern:<line>          PyUnicode_InternFromString outside module init
  kernel-const-drift:<N>   a shared transport constant differs between the
                           scalar twins and ops/transport_kernels.py (the
                           columnar third surface, PR 11)
  kernel-cc-drift:<hook>   congestion-control literal drift between the
                           scalar on_ack twins and the batched kernel
  shim-abi-drift:<NAME>    a shim fast-plane ABI constant (ring header
                           layout, clock-page words, readiness/oplog
                           regions, protocol sentinels) differs between
                           native/shring.h + native/shim/shim.c and the
                           worker twin (shadow_tpu/native/managed.py,
                           vfs.py, core/time.py) — PR 13
  extract:<what>           an audit anchor disappeared (refactor moved a
                           contract surface: update the auditor WITH it)
"""

from __future__ import annotations

from pathlib import Path

import c_extract as C
import py_extract as P
from report import Finding


#: shared constants: (python module key, python name, C define)
CONST_PAIRS = [
    ("transport", "MSS", "MSS_C"),
    ("transport", "INIT_CWND", "INIT_CWND_C"),
    ("transport", "MIN_CWND", "MIN_CWND_C"),
    ("transport", "RTO_MIN_NS", "RTO_MIN_NS_C"),
    ("transport", "RTO_MAX_NS", "RTO_MAX_NS_C"),
    ("transport", "SYN_RETRIES", "SYN_RETRIES_C"),
    ("transport", "FIN_RETRIES", "FIN_RETRIES_C"),
    ("transport", "DATA_RETRIES", "DATA_RETRIES_C"),
    ("transport", "SACK_MAX_BLOCKS", "SACK_MAX_BLOCKS_C"),
    ("fluid", "MTU", "MTU"),
    ("fluid", "HEADER", "HEADER"),
    ("fluid", "HARD_MAX_PKTS", "HARD_MAX_PKTS"),
    ("time", "NS_PER_SEC", "NS_PER_SEC"),
    ("gossip", "TX_SIZE", "TX_SIZE"),
    ("tor", "HDR", "TCELL_HDR"),
]

#: network/unit.py kind enum name -> C define (KIND_DGRAM is the one the
#: C row format carries; TK_* are the stream machine's unit kinds)
KIND_PAIRS = [
    ("SYN", "TK_SYN"), ("SYNACK", "TK_SYNACK"), ("DATA", "TK_DATA"),
    ("ACK", "TK_ACK"), ("FIN", "TK_FIN"), ("FINACK", "TK_FINACK"),
    ("DGRAM", "KIND_DGRAM"),
]

#: models/tor.py cell enum name -> C define (CONNECTED has no C twin:
#: the C sink never originates it)
TOR_CELL_PAIRS = [
    ("CREATE", "TC_CREATE"), ("CREATED", "TC_CREATED"),
    ("EXTEND", "TC_EXTEND"), ("EXTENDED", "TC_EXTENDED"),
    ("BEGIN", "TC_BEGIN"), ("DATA", "TC_DATA"), ("END", "TC_END"),
]

#: shim fast-plane ABI: (python module key, python name, C define).
#: The C side is native/shring.h plus shim.c's own protocol defines;
#: the Python side is the worker twin that packs/reads the same shared
#: pages.  Any drift here silently corrupts the in-shim fast path (the
#: shim and worker would disagree about where a counter or ring field
#: lives), so every mirrored constant is audited by name.
SHIM_ABI_PAIRS = [
    # clock-page u64 word indices + flag bit (shim increments, worker folds)
    ("managed", "SHIM_PAGE_FLAGS", "SHIM_PAGE_FLAGS"),
    ("managed", "SHIM_PAGE_CLS_TIME", "SHIM_PAGE_CLS_TIME"),
    ("managed", "SHIM_PAGE_CLS_IDENT", "SHIM_PAGE_CLS_IDENT"),
    ("managed", "SHIM_PAGE_CLS_RING_R", "SHIM_PAGE_CLS_RING_R"),
    ("managed", "SHIM_PAGE_CLS_RING_W", "SHIM_PAGE_CLS_RING_W"),
    ("managed", "SHIM_PAGE_CLS_READY", "SHIM_PAGE_CLS_READY"),
    ("managed", "SHIM_PAGE_OPLOG_N", "SHIM_PAGE_OPLOG_N"),
    ("managed", "SHIM_PAGE_F_FAST", "SHIM_PAGE_F_FAST"),
    # per-vfd readiness bytes (worker publishes, shim's poll consumes)
    ("managed", "SHIM_READY_OFF", "SHIM_READY_OFF"),
    ("managed", "SHIM_READY_LEN", "SHIM_READY_LEN"),
    ("managed", "SHIM_READY_VALID", "SHIM_READY_VALID"),
    ("managed", "SHIM_READY_IN", "SHIM_READY_IN"),
    ("managed", "SHIM_READY_OUT", "SHIM_READY_OUT"),
    ("managed", "SHIM_READY_HUP", "SHIM_READY_HUP"),
    ("managed", "SHIM_READY_ERR", "SHIM_READY_ERR"),
    # socket-op log (shim appends, worker replays at the round fold)
    ("managed", "SHIM_OPLOG_OFF", "SHIM_OPLOG_OFF"),
    ("managed", "SHIM_OPLOG_MAX", "SHIM_OPLOG_MAX"),
    ("managed", "SHIM_OP_RECV", "SHIM_OP_RECV"),
    ("managed", "SHIM_OP_SEND", "SHIM_OP_SEND"),
    # struct shring socket extensions (flags word + tx write budget)
    ("managed", "SHRING_OFF_FLAGS", "SHRING_OFF_FLAGS"),
    ("managed", "SHRING_OFF_WBUDGET", "SHRING_OFF_WBUDGET"),
    ("managed", "SHRING_F_HUP", "SHRING_F_HUP"),
    ("managed", "SHRING_F_ERR", "SHRING_F_ERR"),
    ("managed", "SHRING_F_SOCK", "SHRING_F_SOCK"),
    ("managed", "SHRING_CAP_MIN", "SHRING_CAP_MIN"),
    ("managed", "SHRING_CAP_MAX", "SHRING_CAP_MAX"),
    # wire protocol sentinels (different spellings across the twins)
    ("managed", "SHIM_IPC_FD", "SHIM_IPC_FD"),
    ("managed", "VFD_BASE", "SHIM_VFD_BASE"),
    ("managed", "MAPRING", "SHIM_RET_MAPRING"),
    ("vfs", "RETRY_NATIVE", "SHIM_RET_NATIVE"),
    ("time", "EMULATED_EPOCH", "SHIM_EMULATED_EPOCH_NS"),
]

#: mmap'd ring layout twins carried as class attributes on the worker
#: side: (python class, attr, C define)
SHIM_RING_ATTR_PAIRS = [
    ("RingPipeBuf", "HDR", "SHRING_HDR"),
    ("RingPipeBuf", "MAGIC", "SHRING_MAGIC"),
    ("PipeBuf", "CAP", "SHRING_CAP"),
]

#: CEp struct fields deliberately NOT in _export_state — rebuild-time
#: wiring, each re-established by the owning object's restore path:
#:   core   Core.adopt() sets it when the endpoint joins a core
#:   sink   the owning CRelay's _restore_state re-links its conns
#:   tsink  the owning CTorSink's _restore_state re-links its client ep
STRUCT_EXPORT_EXEMPT = {"core", "sink", "tsink"}

def _codes_align(export, restore) -> bool:
    """Positional compatibility of an export Py_BuildValue format with
    its restore PyArg_ParseTuple format: N (steal) and O (borrow) both
    parse as O, and a bool exported as an object (O: Py_True/Py_False)
    legitimately parses back as i."""
    if len(export) != len(restore):
        return False
    for e, r in zip(export, restore):
        e = "O" if e == "N" else e
        if e == r or (e == "O" and r == "i"):
            continue
        return False
    return True


def audit(root) -> list:
    root = Path(root)
    findings: list = []

    def fail(rule, path, msg, line=0):
        findings.append(Finding(rule, str(path), line, msg))

    csrc_path = root / "native" / "colcore" / "colcore.c"
    try:
        csrc = csrc_path.read_text()
    except OSError as e:
        fail("extract:colcore", csrc_path, str(e))
        return findings
    cdef = C.resolve_defines(csrc)

    py_files = sorted(p for p in (root / "shadow_tpu").rglob("*.py")
                      if "__pycache__" not in p.parts)

    # Python constant environments, chained through the import graph
    envs = {}
    try:
        envs["time"] = P.module_constants(
            P.parse(root / "shadow_tpu" / "core" / "time.py"))
        envs["fluid"] = P.module_constants(
            P.parse(root / "shadow_tpu" / "network" / "fluid.py"),
            envs["time"])
        transport_tree = P.parse(
            root / "shadow_tpu" / "network" / "transport.py")
        envs["transport"] = P.module_constants(transport_tree, envs["time"])
        envs["gossip"] = P.module_constants(
            P.parse(root / "shadow_tpu" / "models" / "gossip.py"))
        tor_tree = P.parse(root / "shadow_tpu" / "models" / "tor.py")
        envs["tor"] = P.module_constants(tor_tree)
    except (OSError, P.ExtractError, SyntaxError) as e:
        fail("extract:python-consts", root, str(e))
        return findings

    # 1. shared constants ----------------------------------------------------
    for mod, pyname, cname in CONST_PAIRS:
        pv = envs[mod].get(pyname)
        cv = cdef.get(cname)
        if pv is None:
            fail("extract:const", "shadow_tpu", "%s.%s not found" %
                 (mod, pyname))
        elif cv is None:
            fail("extract:const", csrc_path, "#define %s not found" % cname)
        elif pv != cv:
            fail("const-drift:%s" % pyname, csrc_path,
                 "%s=%d (Python %s) but %s=%d (C)" %
                 (pyname, pv, mod, cname, cv))

    # unit kinds + tor cell kinds (range enums vs defines)
    try:
        kinds = P.range_enum(P.parse(
            root / "shadow_tpu" / "network" / "unit.py"))
        for pyname, cname in KIND_PAIRS:
            if kinds.get(pyname) != cdef.get(cname):
                fail("const-drift:%s" % pyname, csrc_path,
                     "unit kind %s=%s (Python) vs %s=%s (C)" %
                     (pyname, kinds.get(pyname), cname, cdef.get(cname)))
        cells = P.range_enum(tor_tree)
        for pyname, cname in TOR_CELL_PAIRS:
            if cells.get(pyname) != cdef.get(cname):
                fail("const-drift:tor.%s" % pyname, csrc_path,
                     "tor cell %s=%s (Python) vs %s=%s (C)" %
                     (pyname, cells.get(pyname), cname, cdef.get(cname)))
    except (P.ExtractError, SyntaxError, OSError) as e:
        fail("extract:kind-enums", root, str(e))

    # 2. fingerprint arity ---------------------------------------------------
    try:
        ep_cls = P.class_def(transport_tree, "StreamEndpoint")
        py_arity = P.return_tuple_arity(P.method_def(ep_cls, "fingerprint"))
        c_codes = C.format_codes(C.buildvalue_format(csrc, "CEp_fingerprint"))
        if py_arity != len(c_codes):
            fail("fingerprint-arity", csrc_path,
                 "StreamEndpoint.fingerprint has %d fields but "
                 "CEp_fingerprint builds %d — the determinism sentinel "
                 "twins diverged" % (py_arity, len(c_codes)))
    except (P.ExtractError, C.ExtractError) as e:
        fail("extract:fingerprint", csrc_path, str(e))

    # 3. CEp export/restore format alignment ---------------------------------
    try:
        exp = C.format_codes(C.buildvalue_format(csrc, "CEp_export_state"))
        res = C.format_codes(C.parsetuple_format(csrc, "CEp_restore_state"))
        if not _codes_align(exp, res):
            fail("export-arity", csrc_path,
                 "CEp_export_state builds %d fields (%s) but "
                 "CEp_restore_state parses %d (%s) — a checkpoint written "
                 "by this build cannot restore" %
                 (len(exp), "".join(exp), len(res), "".join(res)))
    except C.ExtractError as e:
        fail("extract:cep-export", csrc_path, str(e))

    # 4. CEp struct fields all exported or exempt ----------------------------
    try:
        fields = set(C.struct_fields(csrc, "CEp")) - {"PyObject_HEAD"}
        body = C.function_body(csrc, "CEp_export_state")
        import re as _re
        referenced = set(_re.findall(r"e->(\w+)", body))
        for f in sorted(fields - referenced - STRUCT_EXPORT_EXEMPT):
            fail("struct-export:%s" % f, csrc_path,
                 "CEp field %r is neither exported by CEp_export_state "
                 "nor in the documented exempt set — a checkpoint would "
                 "silently drop it" % f)
    except C.ExtractError as e:
        fail("extract:cep-struct", csrc_path, str(e))

    # 5. folded counter names ------------------------------------------------
    try:
        folded = C.string_array(csrc, "names2")
        known = P.counter_names(py_files)
        for name in folded:
            if name not in known:
                fail("counter-name:%s" % name, csrc_path,
                     "C folds counter %r but no Python twin increments a "
                     "counter of that name — rename drift between the "
                     "planes" % name)
    except C.ExtractError as e:
        fail("extract:counter-fold", csrc_path, str(e))

    # 6. interned attribute names -------------------------------------------
    try:
        vocab = P.identifier_vocab(py_files)
        # names the C module itself defines (PyMethodDef/getset tables):
        # the timer-callback methods (_rto_fire & co) are interned to be
        # looked up on C objects, not Python ones
        import re as _re2
        vocab |= set(_re2.findall(r'\{\s*"(\w+)"', csrc))
        for name in C.interned_names(csrc):
            if name not in vocab:
                fail("attr-name:%s" % name, csrc_path,
                     "C interns attribute %r but the identifier no longer "
                     "appears anywhere in shadow_tpu/ — the C engine would "
                     "read a stale attribute" % name)
    except C.ExtractError as e:
        fail("extract:interned", csrc_path, str(e))

    # 7. congestion-control registry -----------------------------------------
    try:
        registry = P.dict_literal_keys(transport_tree, "CONGESTION_CONTROLS")
        schema_names = set(P.string_tuple(
            P.parse(root / "shadow_tpu" / "config" / "schema.py"),
            "CONGESTION_CONTROL_NAMES"))
        if set(registry) != schema_names:
            fail("cc-enum", csrc_path,
                 "transport CONGESTION_CONTROLS keys %s != config-schema "
                 "CONGESTION_CONTROL_NAMES %s" %
                 (sorted(registry), sorted(schema_names)))
        for name, clsname in registry.items():
            cc_id = P.class_attr(P.class_def(transport_tree, clsname),
                                 "cc_id")
            c_id = cdef.get("CC_%s" % name.upper())
            if c_id != cc_id:
                fail("cc-enum", csrc_path,
                     "cc %r: Python cc_id=%s vs C CC_%s=%s" %
                     (name, cc_id, name.upper(), c_id))
    except (P.ExtractError, SyntaxError, OSError) as e:
        fail("extract:cc-enum", root, str(e))

    # 8. congestion-control arithmetic ---------------------------------------
    # The cubic beta/C constants and clamp bounds live as inline integer
    # literals in BOTH twins.  Compare the resolved literal SET (>= 3;
    # 0/1/2 are structural noise) per hook — C merges both algorithms in
    # one cc_* function, so the Python side is the union over the
    # registry classes.
    try:
        env = envs["transport"]
        for hook in ("on_ack", "on_loss", "on_rto"):
            py_lits: set = set()
            for clsname in P.dict_literal_keys(
                    transport_tree, "CONGESTION_CONTROLS").values():
                py_lits |= P.int_literal_set(
                    P.method_def(P.class_def(transport_tree, clsname), hook),
                    env)
            c_lits = set(C.int_literals(csrc, "cc_%s" % hook, cdef))
            if py_lits != c_lits:
                fail("cubic-arith:%s" % hook, csrc_path,
                     "congestion-control literals diverged in %s: "
                     "Python-only %s, C-only %s" %
                     (hook, sorted(py_lits - c_lits) or "{}",
                      sorted(c_lits - py_lits) or "{}"))
    except (P.ExtractError, C.ExtractError) as e:
        fail("extract:cc-arith", csrc_path, str(e))

    # 8b. the columnar kernel twin (ops/transport_kernels.py, PR 11) ---------
    # The batched transport kernels duplicate the scalar constants and
    # the per-CC integer literals DELIBERATELY (a kernel cannot import
    # from the module it must be audited against — the colcore.c
    # argument, applied to the third surface). Cross-check both.
    try:
        ktree = P.parse(
            root / "shadow_tpu" / "ops" / "transport_kernels.py")
        kenv = P.module_constants(ktree)
        tenv = envs["transport"]
        for name in ("MSS", "INIT_CWND", "MIN_CWND"):
            if kenv.get(name) != tenv.get(name):
                fail("kernel-const-drift:%s" % name,
                     root / "shadow_tpu" / "ops" / "transport_kernels.py",
                     "%s=%s (kernel) but %s (transport.py scalar twin)" %
                     (name, kenv.get(name), tenv.get(name)))
        if kenv.get("NS_PER_MS") != envs["time"].get("NS_PER_MS"):
            fail("kernel-const-drift:NS_PER_MS",
                 root / "shadow_tpu" / "ops" / "transport_kernels.py",
                 "NS_PER_MS=%s (kernel) but %s (core/time.py)" %
                 (kenv.get("NS_PER_MS"), envs["time"].get("NS_PER_MS")))
        # cc_id dispatch values vs the transport registry
        for name, clsname in P.dict_literal_keys(
                transport_tree, "CONGESTION_CONTROLS").items():
            cc_id = P.class_attr(P.class_def(transport_tree, clsname),
                                 "cc_id")
            kv = kenv.get("CC_%s" % name.upper())
            if kv != cc_id:
                fail("kernel-const-drift:CC_%s" % name.upper(),
                     root / "shadow_tpu" / "ops" / "transport_kernels.py",
                     "cc %r: kernel CC_%s=%s vs transport cc_id=%s" %
                     (name, name.upper(), kv, cc_id))
        # per-CC on_ack literal sets: the kernel's cc_on_ack merges both
        # algorithms (like colcore's cc_* functions), so compare against
        # the union over the registry classes
        py_lits: set = set()
        for clsname in P.dict_literal_keys(
                transport_tree, "CONGESTION_CONTROLS").values():
            py_lits |= P.int_literal_set(
                P.method_def(P.class_def(transport_tree, clsname),
                             "on_ack"), envs["transport"])
        k_lits = P.int_literal_set(P.func_def(ktree, "cc_on_ack"), kenv)
        if py_lits != k_lits:
            fail("kernel-cc-drift:on_ack",
                 root / "shadow_tpu" / "ops" / "transport_kernels.py",
                 "congestion-control literals diverged between the "
                 "scalar twins and the batched kernel: scalar-only %s, "
                 "kernel-only %s" %
                 (sorted(py_lits - k_lits) or "{}",
                  sorted(k_lits - py_lits) or "{}"))
    except (P.ExtractError, SyntaxError, OSError) as e:
        fail("extract:kernel", root, str(e))

    # 9. ABI / VERSION bumps require a MIGRATION.md entry --------------------
    import re as _re
    try:
        abi = C.module_int_constant(csrc, "ABI")
    except C.ExtractError as e:
        abi = None
        fail("extract:abi", csrc_path, str(e))
    try:
        version = P.module_constants(
            P.parse(root / "shadow_tpu" / "checkpoint.py")).get("VERSION")
    except (OSError, SyntaxError) as e:
        version = None
        fail("extract:version", root / "shadow_tpu" / "checkpoint.py", str(e))
    mig_path = root / "MIGRATION.md"
    mig = mig_path.read_text() if mig_path.exists() else ""
    if abi is not None and not _re.search(
            r"\bABI\b\D{0,40}\b%d\b" % abi, mig):
        fail("abi-migration", mig_path,
             "colcore ABI is %d but MIGRATION.md has no entry mentioning "
             "it — every ABI bump must document what breaks and why "
             "old checkpoints refuse" % abi)
    if version is not None and not _re.search(
            r"(?i)\bversion\b\D{0,40}\b%d\b" % version, mig):
        fail("version-migration", mig_path,
             "checkpoint VERSION is %d but MIGRATION.md has no entry "
             "mentioning it — every format bump must document the break" %
             version)

    # 10. interning discipline ----------------------------------------------
    for line, text in C.intern_calls_outside_init(csrc):
        fail("c-intern:%d" % line, csrc_path,
             "PyUnicode_InternFromString outside module init leaks a "
             "reference per call and its NULL return is typically "
             "unchecked — pre-intern in PyInit (INTERN table): %s" % text,
             line)

    # 11. shim fast-plane ABI ----------------------------------------------
    # native/shring.h + shim.c define the shared-page layout the guest
    # shim writes; shadow_tpu/native/managed.py mirrors every offset,
    # word index and flag bit to read/arm the same pages.  Disagreement
    # is silent corruption (a counter folded from the wrong word, a
    # budget armed at the wrong offset), so the mirror is audited by
    # name.
    shring_path = root / "native" / "shring.h"
    shim_path = root / "native" / "shim" / "shim.c"
    managed_path = root / "shadow_tpu" / "native" / "managed.py"
    shimdef = managed_tree = None
    try:
        shimdef = C.resolve_defines(shring_path.read_text())
        shimdef.update(C.resolve_defines(shim_path.read_text()))
    except OSError as e:
        fail("extract:shim-abi", shring_path, str(e))
    try:
        managed_tree = P.parse(managed_path)
        envs["managed"] = P.module_constants(managed_tree)
        envs["vfs"] = P.module_constants(
            P.parse(root / "shadow_tpu" / "native" / "vfs.py"))
    except (OSError, P.ExtractError, SyntaxError) as e:
        fail("extract:shim-abi", managed_path, str(e))
    if shimdef is not None and managed_tree is not None:
        for mod, pyname, cname in SHIM_ABI_PAIRS:
            pv = envs[mod].get(pyname)
            cv = shimdef.get(cname)
            if pv is None:
                fail("shim-abi-drift:%s" % pyname, managed_path,
                     "shim ABI constant %s not found on the Python side "
                     "(module %r)" % (pyname, mod))
            elif cv is None:
                fail("shim-abi-drift:%s" % pyname, shring_path,
                     "shim ABI constant %s has no C define %s in "
                     "shring.h/shim.c" % (pyname, cname))
            elif pv != cv:
                fail("shim-abi-drift:%s" % pyname, shring_path,
                     "shim ABI drift: Python %s=%d but C %s=%d — shim "
                     "and worker would disagree about the shared-page "
                     "layout" % (pyname, pv, cname, cv))
        for clsname, attr, cname in SHIM_RING_ATTR_PAIRS:
            cv = shimdef.get(cname)
            try:
                pv = P.class_attr(P.class_def(managed_tree, clsname), attr)
            except P.ExtractError as e:
                fail("extract:shim-abi", managed_path, str(e))
                continue
            if cv is None or pv != cv:
                fail("shim-abi-drift:%s" % cname, shring_path,
                     "ring layout drift: %s.%s=%r but C %s=%r" %
                     (clsname, attr, pv, cname, cv))

    return findings
