"""Shared finding record for the auditor and the linter."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self):
        loc = "%s:%d" % (self.path, self.line) if self.line else self.path
        return "%s: %s: %s" % (loc, self.rule, self.message)
