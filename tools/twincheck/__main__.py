"""CLI for the static twin-contract auditor + determinism linter.

    python tools/twincheck audit    # twin-contract audit (C vs Python)
    python tools/twincheck detlint  # determinism lint over shadow_tpu/
    python tools/twincheck all      # both

Exit status 1 when any finding survives (ci.sh gates on this), 0 on a
clean tree.  `--json` emits machine-readable findings; `--waivers`
lists every in-place detlint waiver with its written reason.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import det_lint  # noqa: E402
import twin_audit  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="twincheck")
    ap.add_argument("command", choices=("audit", "detlint", "all"))
    ap.add_argument("--root", default=None,
                    help="repo root (default: two levels above this file)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--waivers", action="store_true",
                    help="also list detlint waivers with reasons")
    args = ap.parse_args(argv)

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parent.parent.parent
    findings = []
    if args.command in ("audit", "all"):
        findings += twin_audit.audit(root)
    waivers = []
    if args.command in ("detlint", "all"):
        f, waivers = det_lint.lint_with_waivers(root)
        findings += f

    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "waivers": [
                {"path": p, "line": ln, "rule": r, "reason": why}
                for p, ln, r, why in waivers],
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f)
        if args.waivers and waivers:
            print("-- waivers --")
            for p, ln, r, why in waivers:
                print("%s:%d: ok(%s): %s" % (p, ln, r, why))
        label = {"audit": "twin audit", "detlint": "determinism lint",
                 "all": "twincheck"}[args.command]
        if findings:
            print("%s: %d finding(s)" % (label, len(findings)))
        else:
            print("%s: clean" % label)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
