"""100k-to-1M-host scale demonstration (BASELINE.md config #5's range).

Builds a large gossip network (64-node random graph, quantity-templated
hosts, 2 originators) — or, with ``--tor``, the tornettools-shaped
relay/client config at the requested host count — and runs it, single
process or partitioned across ``--shards`` worker processes
(shadow_tpu/parallel/shards.py; byte-identical results at any count).

Generation is streamed: the config is a handful of quantity templates
(O(graph nodes), never O(hosts)) and expansion is one linear pass —
nothing materializes host^2 state at ANY count (hosts index into (G, G)
node tables). 1,000,000 hosts is in range: the uid/key packing admits
2**26 hosts (network/unit.py).

``--emit-yaml PATH`` writes the generated config as YAML instead of
running it — ``examples/tor_1m.yaml`` is the committed 1M-host stub:

    python tools/scale_100k.py --tor --hosts 1000000 \\
        --emit-yaml examples/tor_1m.yaml

Measured on one CPU core (2026-07-30, gossip 100k): build ~6 s, run
~146 s for 8 simulated seconds, 2.66M units, 199,919 tx deliveries
(full coverage), 1.1 GB peak RSS.

    python tools/scale_100k.py [--hosts 100000] [--stop 8] [--shards N]
"""

from __future__ import annotations

import argparse
import resource
import time

import numpy as np

MAX_HOSTS = 1 << 26  # uid/key packing bound (network/unit.py)


def gossip_doc(n: int, stop_s: int, rng) -> dict:
    from gen_benchmarks import random_gml

    g = 64
    gml = random_gml(rng, g, min_lat_ms=10, max_lat_ms=120, max_loss=0.002,
                     bw_choices=("50 Mbit", "100 Mbit"))
    hosts = {"origin_": {
        "network_node_id": 0, "quantity": 2,
        "processes": [{"path": "pyapp:shadow_tpu.models.gossip:GossipNode",
                       "args": ["7000", str(n), "8", "1", "2.0"]}]}}
    per, extra = (n - 2) // g, (n - 2) - ((n - 2) // g) * g
    for i in range(g):
        q = per + (extra if i == g - 1 else 0)
        hosts[f"n{i}_"] = {
            "network_node_id": i, "quantity": q,
            "processes": [{
                "path": "pyapp:shadow_tpu.models.gossip:GossipNode",
                "args": ["7000", str(n), "8", "0", "2.0"]}]}
    return {
        "general": {"stop_time": f"{stop_s}s", "seed": 5,
                    "heartbeat_interval": "4s"},
        "network": {"graph": {"type": "gml", "inline": gml}},
        "hosts": hosts,
    }


def tor_doc(n: int, stop_s: int, rng) -> dict:
    """The tornettools-shaped config (bench.py's _tor_doc shape) scaled
    to ``n`` total hosts at the published relay:client ratio (~1:15,
    like config #5's 7,000 relays per 107k hosts) — but generated as
    O(templates) YAML: the contiguous relay0..relayN-1 population is ONE
    quantity template whose ``network_node_ids`` cycle spreads it across
    the graph (config/schema.py), and clients are per-node templates.
    Nothing here is O(hosts), so the 1M-host stub stays a few hundred KB
    and expansion is one linear pass at load."""
    from gen_benchmarks import random_gml

    g = 64
    gml = random_gml(rng, g, min_lat_ms=10, max_lat_ms=120, max_loss=0.002,
                     bw_choices=("50 Mbit", "100 Mbit", "1 Gbit"))
    n_relays = max(16, n // 15)
    n_clients = n - n_relays - 20
    n_exits = max(1, n_relays // 8)  # exits first (TorClient's n_exits)
    hosts = {
        # relay placement cycles a seeded node permutation: round-robin
        # across every graph node, names stay relay0..relayN-1
        "relay": {
            "quantity": n_relays,
            "network_node_ids": [int(x) for x in rng.permutation(g)],
            "processes": [{"path": "pyapp:shadow_tpu.models.tor:TorExit",
                           "args": ["9001"]}]},
        }
    # exit capability is positional (relay0..relay{n_exits-1}), but the
    # template stamps ONE process class — run TorExit everywhere: a
    # TorExit behaves exactly like TorRelay for non-exit circuit
    # positions (BEGIN cells only ever reach it as the last hop)
    for i in range(20):
        hosts[f"web{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{"path": "pyapp:shadow_tpu.models.tgen:TGenServer",
                           "args": ["80"]}]}
    per = n_clients // g
    for i in range(g):
        q = per + (n_clients - per * g if i == g - 1 else 0)
        if q < 1:
            continue  # tiny --hosts: skip empty per-node templates
        hosts[f"u{i}_"] = {
            "network_node_id": i, "quantity": q,
            "processes": [{"path": "pyapp:shadow_tpu.models.tor:TorClient",
                           "args": [str(n_relays), "9001", f"web{i % 20}",
                                    "80", "20 kB", "1", str(n_exits)],
                           "start_time": f"{2000 + i * 150} ms"}]}
    return {"general": {"stop_time": f"{stop_s}s", "seed": 6,
                        "heartbeat_interval": "4s"},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": hosts}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=100_000)
    ap.add_argument("--stop", type=int, default=8, help="sim seconds")
    ap.add_argument("--shards", type=int, default=1,
                    help="partition across N worker processes "
                         "(general.sim_shards; results byte-identical "
                         "at any count)")
    ap.add_argument("--tor", action="store_true",
                    help="generate the tornettools-shaped relay/client "
                         "config instead of the gossip flood")
    ap.add_argument("--emit-yaml", metavar="PATH",
                    help="write the generated config as YAML and exit "
                         "(how examples/tor_1m.yaml is produced)")
    ap.add_argument("--data-directory", default="/tmp/shadow-scale-100k")
    args = ap.parse_args()
    if args.hosts < 2 + 64:
        ap.error("--hosts must be at least 66 (64 node templates + 2 "
                 "originators)")
    if args.hosts >= MAX_HOSTS:
        ap.error(f"--hosts must be below {MAX_HOSTS} (the uid/key "
                 f"packing bound, network/unit.py)")

    import sys
    from pathlib import Path

    here = Path(__file__).resolve().parent
    sys.path.insert(0, str(here.parent))  # repo root: shadow_tpu package
    sys.path.insert(0, str(here))

    from shadow_tpu.config import parse_config

    rng = np.random.default_rng(20260730)
    n = args.hosts
    doc = tor_doc(n, args.stop, rng) if args.tor \
        else gossip_doc(n, args.stop, rng)
    if args.shards > 1:
        doc["general"]["sim_shards"] = args.shards

    if args.emit_yaml:
        import yaml

        kind = "tor" if args.tor else "gossip"
        header = (
            f"# {n}-host {kind} scale config — GENERATED, do not "
            f"hand-edit.\n"
            f"# Regenerate: python tools/scale_100k.py "
            f"{'--tor ' if args.tor else ''}--hosts {n} "
            f"--stop {args.stop} --emit-yaml <path>\n"
            f"# Run it sharded (shadow_tpu/parallel/shards.py):\n"
            f"#   python -m shadow_tpu <path> --shards 4 "
            f"--scheduler-policy tpu_batch\n")
        with open(args.emit_yaml, "w") as f:
            f.write(header)
            yaml.safe_dump(doc, f, default_style=None)
        print(f"wrote {args.emit_yaml} ({n} hosts, "
              f"{len(doc['hosts'])} templates)")
        return

    t0 = time.perf_counter()
    cfg = parse_config(doc, {"general.data_directory": args.data_directory})
    if args.shards > 1:
        from shadow_tpu.parallel.shards import ShardedRun

        runner = ShardedRun(cfg, mirror_log=False)
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        r = runner.run()
        rx = None  # processes live in the workers
    else:
        from shadow_tpu.core.controller import Controller

        c = Controller(cfg, mirror_log=False)
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        r = c.run()
        rx = (sum(p.app.received_tx for h in c.hosts for p in h.processes)
              if not args.tor else None)
    run_s = time.perf_counter() - t0
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    rss = max(rss, r.get("max_rss_mb", 0) / 1024)
    print(f"{n} hosts (shards={args.shards}): build={build_s:.1f}s "
          f"run={run_s:.1f}s "
          f"sim-s/wall-s={r['sim_sec_per_wall_sec']:.3f} "
          f"events={r['events']} units={r['units_sent']} "
          f"dropped={r['units_dropped']} rss={rss:.2f}GB"
          + (f" tx_deliveries={rx}" if rx is not None else ""))


if __name__ == "__main__":
    main()
