"""100k-host scale demonstration (BASELINE.md config #5's host count).

Builds a 100,000-host gossip network in memory (64-node random graph,
quantity-templated hosts, 2 originators) and floods 2 transactions to
every host. Exercises SURVEY.md §7 "Hard parts" #5: nothing in the
engine materializes host² state — hosts index into (G×G) node tables.

Measured on one CPU core (2026-07-30): build ~6 s, run ~146 s for 8
simulated seconds, 2.66M units, 199,919 tx deliveries (full coverage),
1.1 GB peak RSS.

    python tools/scale_100k.py [--hosts 100000] [--stop 8]
"""

from __future__ import annotations

import argparse
import resource
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=100_000)
    ap.add_argument("--stop", type=int, default=8, help="sim seconds")
    ap.add_argument("--data-directory", default="/tmp/shadow-scale-100k")
    args = ap.parse_args()
    if args.hosts < 2 + 64:
        ap.error("--hosts must be at least 66 (64 node templates + 2 "
                 "originators)")

    import sys
    from pathlib import Path

    here = Path(__file__).resolve().parent
    sys.path.insert(0, str(here.parent))  # repo root: shadow_tpu package
    sys.path.insert(0, str(here))
    from gen_benchmarks import random_gml

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    rng = np.random.default_rng(20260730)
    g = 64
    gml = random_gml(rng, g, min_lat_ms=10, max_lat_ms=120, max_loss=0.002,
                     bw_choices=("50 Mbit", "100 Mbit"))
    n = args.hosts
    hosts = {"origin_": {
        "network_node_id": 0, "quantity": 2,
        "processes": [{"path": "pyapp:shadow_tpu.models.gossip:GossipNode",
                       "args": ["7000", str(n), "8", "1", "2.0"]}]}}
    per, extra = (n - 2) // g, (n - 2) - ((n - 2) // g) * g
    for i in range(g):
        q = per + (extra if i == g - 1 else 0)
        hosts[f"n{i}_"] = {
            "network_node_id": i, "quantity": q,
            "processes": [{
                "path": "pyapp:shadow_tpu.models.gossip:GossipNode",
                "args": ["7000", str(n), "8", "0", "2.0"]}]}
    doc = {
        "general": {"stop_time": f"{args.stop}s", "seed": 5,
                    "heartbeat_interval": "4s"},
        "network": {"graph": {"type": "gml", "inline": gml}},
        "hosts": hosts,
    }
    t0 = time.perf_counter()
    cfg = parse_config(doc, {"general.data_directory": args.data_directory})
    c = Controller(cfg, mirror_log=False)
    build_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    r = c.run()
    run_s = time.perf_counter() - t0
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    rx = sum(p.app.received_tx for h in c.hosts for p in h.processes)
    print(f"{n} hosts: build={build_s:.1f}s run={run_s:.1f}s "
          f"sim-s/wall-s={r['sim_sec_per_wall_sec']:.3f} "
          f"events={r['events']} units={r['units_sent']} "
          f"dropped={r['units_dropped']} rss={rss:.2f}GB "
          f"tx_deliveries={rx}")


if __name__ == "__main__":
    main()
