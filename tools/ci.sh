#!/usr/bin/env bash
# The full local CI gate (SURVEY.md §2 "CI", §4): unit+integration tests on
# the 8-virtual-device CPU platform, the multichip dry run, and a 1k-host
# scale determinism check (twice-run, full output-tree hash compare).
set -euo pipefail
cd "$(dirname "$0")/.."

# The static gates run FIRST: twin-contract drift, determinism-discipline
# violations, and sanitizer findings fail in seconds, before the
# expensive identity matrices below ever start.

echo "== twincheck: twin-contract audit (C vs Python surfaces) =="
python tools/twincheck audit

echo "== twincheck: determinism lint (shadow_tpu/ sim-state modules) =="
python tools/twincheck detlint

echo "== sanitize smoke (ASan+UBSan colcore+shim: gossip_churn + web_cdn) =="
make -C native sanitize
ASAN_LIB=$(gcc -print-file-name=libasan.so)
# jax throws C++ exceptions in normal operation; ASan's __cxa_throw
# interceptor needs libstdc++ resolvable at preload time
STDCXX_LIB=$(gcc -print-file-name=libstdc++.so.6)
# the loader override + colplane attach both swallow ImportError into a
# silent Python-plane fallback — probe the sanitized extension imports
# under the exact smoke environment, so the gate can never "pass" while
# sanitizing nothing
LD_PRELOAD="$ASAN_LIB $STDCXX_LIB" \
ASAN_OPTIONS=detect_leaks=0 \
SHADOW_TPU_COLCORE_SO=native/build/asan/_colcore.so \
python -c '
from shadow_tpu.native import _colcore
assert "build/asan" in _colcore.__file__, _colcore.__file__
print("sanitized _colcore imports (ABI %d)" % _colcore.ABI)'
sanrun() {
    rm -rf "/tmp/ci-san-$1"
    LD_PRELOAD="$ASAN_LIB $STDCXX_LIB" \
    LSAN_OPTIONS=exitcode=0 \
    SHADOW_TPU_COLCORE_SO=native/build/asan/_colcore.so \
    JAX_PLATFORMS=cpu \
    python -m shadow_tpu "examples/$1.yaml" --quiet --json-summary \
        --data-directory "/tmp/ci-san-$1" \
        --scheduler-policy tpu_batch \
        --set experimental.native_colcore=true \
        > "/tmp/ci-san-$1.json" 2> "/tmp/ci-san-$1.err"
    # a memory error or unrecovered UB aborts the run above (set -e);
    # exit-time leak reports are CPython/jax noise EXCEPT frames inside
    # the colcore extension — those gate
    if grep -q "colcore" "/tmp/ci-san-$1.err"; then
        echo "sanitize smoke: colcore frames in the sanitizer report:" >&2
        grep -B3 -A12 "colcore" "/tmp/ci-san-$1.err" | head -80 >&2
        exit 1
    fi
    python - "$1" <<'EOF'
import json, sys
d = json.load(open("/tmp/ci-san-%s.json" % sys.argv[1]))
assert d["process_errors"] == [], d["process_errors"]
assert d["events"] > 0, "sanitized run simulated nothing"
print("sanitize smoke OK: %s ran %d events under ASan/UBSan with the "
      "C engine, no colcore-attributed leaks" % (sys.argv[1], d["events"]))
EOF
}
sanrun gossip_churn
sanrun web_cdn

echo "== pytest (CPU JAX, 8 virtual devices) =="
python -m pytest tests/ -q

echo "== multichip dry run (8-shard virtual mesh) =="
GRAFT_NDEV=8 python __graft_entry__.py

echo "== 1k-host scale determinism (twice-run hash compare) =="
export JAX_PLATFORMS=cpu
run() {
    python -m shadow_tpu examples/tgen_1k.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-det-$1" \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-det-$1.json"
    (cd "/tmp/ci-det-$1" && find hosts -type f | sort | xargs sha256sum) \
        > "/tmp/ci-det-$1.hashes"
}
run a
run b
diff /tmp/ci-det-a.json /tmp/ci-det-b.json
diff /tmp/ci-det-a.hashes /tmp/ci-det-b.hashes
echo "determinism OK: $(python -c 'import json;print(json.load(open("/tmp/ci-det-a.json"))["events"])') events bit-identical"

echo "== fused-window smoke (forced device, K=4 vs K=1 determinism + windows served) =="
wrun() {
    python -m shadow_tpu examples/tgen_1k.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-win-$1" \
        --scheduler-policy tpu_batch \
        --set experimental.tpu_device_floor=1 \
        --set "experimental.device_window_rounds=$2" \
        | python -c '
import json, sys
from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS
d = json.load(sys.stdin)
assert d["device_windows_dispatched"] > 0, \
    "forced device serviced zero fused windows"
for k in VOLATILE_SUMMARY_KEYS:
    d.pop(k, None)
print(json.dumps(d, sort_keys=True))' > "/tmp/ci-win-$1.json"
    (cd "/tmp/ci-win-$1" && find hosts -type f | sort | xargs sha256sum) \
        > "/tmp/ci-win-$1.hashes"
}
wrun k1 1
wrun k4 4
diff /tmp/ci-win-k1.json /tmp/ci-win-k4.json
diff /tmp/ci-win-k1.hashes /tmp/ci-win-k4.hashes
echo "fused-window smoke OK: K=4 bit-identical to K=1 with windows served"

echo "== tor C-twin smoke (tor_400relay: C tor control plane vs Python twin hash) =="
trun() {
    python -m shadow_tpu examples/tor_400relay.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-tor-$1" \
        --scheduler-policy tpu_batch \
        --set "experimental.native_colcore=$2" \
        --set general.stop_time=10s \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-tor-$1.json"
    (cd "/tmp/ci-tor-$1" && find hosts -type f | sort | xargs sha256sum) \
        > "/tmp/ci-tor-$1.hashes"
}
trun c true
trun py false
diff /tmp/ci-tor-c.json /tmp/ci-tor-py.json
diff /tmp/ci-tor-c.hashes /tmp/ci-tor-py.hashes
echo "tor C-twin smoke OK: C tor control plane bit-identical to the Python model ($(python -c 'import json;print(json.load(open("/tmp/ci-tor-c.json"))["events"])') events)"

echo "== checkpoint/resume smoke (tgen_100host: snapshot mid-run, resume, tree-hash equality) =="
rm -rf /tmp/ci-ckpt-full /tmp/ci-ckpt-src /tmp/ci-ckpt-resume
python -m shadow_tpu examples/tgen_100host.yaml --quiet \
    --data-directory /tmp/ci-ckpt-full
python -m shadow_tpu examples/tgen_100host.yaml --quiet \
    --data-directory /tmp/ci-ckpt-src --checkpoint-every 5s
ck=$(ls /tmp/ci-ckpt-src/checkpoints/ckpt_*.ckpt | head -1)
echo "resuming from $ck"
python -m shadow_tpu examples/tgen_100host.yaml --quiet \
    --data-directory /tmp/ci-ckpt-resume --resume-from "$ck"
(cd /tmp/ci-ckpt-full && find hosts -type f | sort | xargs sha256sum) \
    > /tmp/ci-ckpt-full.hashes
(cd /tmp/ci-ckpt-resume && find hosts -type f | sort | xargs sha256sum) \
    > /tmp/ci-ckpt-resume.hashes
diff /tmp/ci-ckpt-full.hashes /tmp/ci-ckpt-resume.hashes
echo "checkpoint/resume OK: resumed output tree bit-identical ($(wc -l < /tmp/ci-ckpt-full.hashes) files)"

echo "== fault-injection smoke (gossip_churn: partition heal + degrade + host churn) =="
python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
    --data-directory /tmp/ci-churn \
    | python -c '
import json, sys
d = json.load(sys.stdin)
c = d["counters"]
trans = d["fault_transitions_applied"]
crashes, boots = c.get("host_crashes", 0), c.get("host_boots", 0)
bh, rto = d["units_blackholed"], c.get("stream_rto_retransmits", 0)
assert d["process_errors"] == [], d["process_errors"]
assert crashes > 0 and boots > 0, c
assert bh > 0, "partition cut no traffic"
assert rto > 0, "no transport recovery seen"
print(f"fault smoke OK: {trans} transitions, {crashes} crashes/"
      f"{boots} reboots, {bh} blackholed, {rto} RTO retransmits")
'

echo "== multi-shard smoke (gossip_churn: shards=2 vs shards=1, tree/stream hash diff) =="
shrun() {
    rm -rf "/tmp/ci-shard-$1"
    python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-shard-$1" \
        --scheduler-policy tpu_batch --shards "$2" \
        --set general.stop_time=40s \
        --state-digest-every 100 --sample-every 5s \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-shard-$1.json"
    (cd "/tmp/ci-shard-$1" && find hosts -type f | sort | xargs sha256sum && \
     sha256sum flows.jsonl metrics.jsonl state_digests.jsonl) \
        > "/tmp/ci-shard-$1.hashes"
}
shrun one 1
shrun two 2
diff /tmp/ci-shard-one.json /tmp/ci-shard-two.json
diff /tmp/ci-shard-one.hashes /tmp/ci-shard-two.hashes
echo "multi-shard smoke OK: shards=2 byte-identical to the single-process run (trees + flows + metrics + digests)"

echo "== chaos self-healing smoke (supervised sharded run: 2 worker SIGKILLs + 1 ring-stall wedge auto-recover to the clean run's bytes; fleet: wedged member retried to ok) =="
chrun() {
    rm -rf "/tmp/ci-chaos-$1"
    env SHADOW_TPU_CHAOS="$2" \
        SHADOW_TPU_STALL_FLOOR_S=3 SHADOW_TPU_STALL_MULT=20 \
        python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-chaos-$1" \
        --scheduler-policy tpu_batch --shards 2 \
        --checkpoint-every 2s \
        --set general.stop_time=40s \
        --set "general.supervise={max_restarts: 4, backoff: 0.2}" \
        --state-digest-every 100 --sample-every 5s \
        > "/tmp/ci-chaos-$1.json"
    (cd "/tmp/ci-chaos-$1" && find hosts -type f | sort | xargs sha256sum && \
     sha256sum flows.jsonl metrics.jsonl state_digests.jsonl) \
        > "/tmp/ci-chaos-$1.hashes"
}
chrun clean ""
chrun hurt "s0:kill@r700,s1:kill@r1400,s0:wedge@r2000"
diff /tmp/ci-chaos-clean.hashes /tmp/ci-chaos-hurt.hashes
python - <<'EOF'
import json

d = json.load(open("/tmp/ci-chaos-hurt.json"))
s = d["supervisor"]
assert len(s["restarts"]) == 3, s  # every injection recovered from
reasons = " | ".join(r["reason"] for r in s["restarts"])
assert "died" in reasons, reasons            # the SIGKILLs, named
assert "dead or wedged" in reasons, reasons  # the wedge, named by shard
for r in s["restarts"]:
    assert r["mttr_s"] < 90, r  # bounded detection, never a hang
print(f"chaos self-healing smoke OK: 2 kills + 1 wedge recovered in "
      f"{s['attempts']} attempts (mttr "
      f"{[r['mttr_s'] for r in s['restarts']]}s), bytes == clean run")
EOF
rm -rf /tmp/ci-chaos-fleet
env SHADOW_TPU_FLEET_CHAOS_WEDGE_SEEDS=131 SHADOW_TPU_FLEET_STALL_S=8 \
    python -m shadow_tpu.fleet sweep examples/gossip_churn.yaml \
    --seeds 2 --seed-base 130 --jobs 2 --sweep-dir /tmp/ci-chaos-fleet \
    --set general.stop_time=10s --no-device-service --quiet --json \
    > /tmp/ci-chaos-fleet.json
python - <<'EOF'
import json

d = json.load(open("/tmp/ci-chaos-fleet.json"))
assert d["completed"] == [130, 131], d["failed"]
assert d["failed"] == {}, d["failed"]
assert d["respawns"] >= 1, d  # the wedged member WAS killed + respawned
print("chaos fleet smoke OK: wedged member detected, killed, retried to ok")
EOF

echo "== fleet smoke (3-seed gossip_churn sweep at jobs=2: per-seed identity vs standalone + CIs in sweep_summary) =="
rm -rf /tmp/ci-fleet /tmp/ci-fleet-solo-*
python -m shadow_tpu.fleet sweep examples/gossip_churn.yaml \
    --seeds 3 --seed-base 120 --jobs 2 --sweep-dir /tmp/ci-fleet \
    --set general.stop_time=25s --quiet --json > /tmp/ci-fleet.json
for s in 120 121 122; do
    # standalone twin of each sweep member (same stop + telemetry); the
    # workload may legitimately exit nonzero on process_errors at this
    # truncated stop time — the hash comparison below is the gate
    python -m shadow_tpu examples/gossip_churn.yaml --quiet --seed "$s" \
        --data-directory "/tmp/ci-fleet-solo-$s" \
        --set general.stop_time=25s --sample-every 10s || true
done
python - <<'EOF'
import json
from shadow_tpu import fleet

summary = json.load(open("/tmp/ci-fleet.json"))
assert summary["completed"] == [120, 121, 122], summary["failed"]
for s in (120, 121, 122):
    d = fleet.seed_dir("/tmp/ci-fleet", s)
    man = json.loads((d / fleet.SEED_MANIFEST).read_text())
    solo = f"/tmp/ci-fleet-solo-{s}"
    assert fleet.output_tree_digest(d) == fleet.output_tree_digest(solo), \
        f"seed {s}: in-fleet tree != standalone tree"
    assert fleet._stream_digests(d) == fleet._stream_digests(solo), \
        f"seed {s}: streams diverged"
    assert man["tree_sha256"] == fleet.output_tree_digest(solo)
doc = json.loads((fleet.Path("/tmp/ci-fleet") / fleet.SWEEP_SUMMARY)
                 .read_text())
assert doc["format"] == "shadow_tpu-sweep-summary"
assert doc["flows"], "sweep summary has no flow groups"
for kind, row in doc["flows"].items():
    ci = row["ci95"]["p50_ms"]
    assert ci["n"] == 3 and ci["lo"] <= ci["mean"] <= ci["hi"], (kind, ci)
    assert set(row["pooled"]) >= {"p50_ms", "p99_ms"}
print(f"fleet smoke OK: 3 seeds byte-identical to standalone, "
      f"{len(doc['flows'])} flow group(s) with t-based CI95 in "
      f"sweep_summary.json")
EOF

echo "== fork smoke (gossip_churn: 3-branch what-if fork off a mid-run checkpoint, reducer diff + bisect first-divergence) =="
rm -rf /tmp/ci-fork-trunk /tmp/ci-fork
# the trunk: one checkpointing run; the 10s snapshot is the fork point.
# The workload may legitimately exit nonzero on process_errors at this
# truncated stop time — the branch assertions below are the gate.
python -m shadow_tpu examples/gossip_churn.yaml --quiet \
    --data-directory /tmp/ci-fork-trunk \
    --set general.stop_time=25s --checkpoint-every 10s \
    --state-digest-every 100 --sample-every 5s || true
ck=$(ls /tmp/ci-fork-trunk/checkpoints/ckpt_*.ckpt | head -1)
echo "forking from $ck"
# one branch diverges via an injected live-command script (replayed
# through the commands.jsonl machinery), one changes the seed (an
# honest cold re-run: the seed is part of the config identity)
cat > /tmp/ci-fork-cmds.jsonl <<'EOF'
{"cmd": {"cmd": "link_degrade", "src_nodes": [0], "dst_nodes": [1], "latency_factor": 3.0, "loss_add": 0.05, "bandwidth_scale": 0.5, "duration": "3000000000 ns"}, "round": 0, "seq": 1, "t": 15000000000}
EOF
cat > /tmp/ci-fork-branches.yaml <<'EOF'
branches:
  - name: baseline
  - name: lossy
    command_script: /tmp/ci-fork-cmds.jsonl
  - name: seed9
    seed: 9
EOF
python -m shadow_tpu fork examples/gossip_churn.yaml \
    --from "$ck" --branches /tmp/ci-fork-branches.yaml \
    --fork-dir /tmp/ci-fork --jobs 3 --quiet \
    --set general.stop_time=25s --set general.checkpoint_every=10s \
    --set general.state_digest_every=100 --set telemetry.sample_every=5s \
    > /tmp/ci-fork-report.txt
python tools/compare.py /tmp/ci-fork --json > /tmp/ci-fork-summary.json
python - <<'EOF'
import json
from shadow_tpu import fleet, forks

s = json.load(open("/tmp/ci-fork-summary.json"))
assert s["completed"] == ["baseline", "lossy", "seed9"], s["failed"]
b = s["branches"]
assert b["baseline"]["mode"] == "restore" and b["lossy"]["mode"] == "restore"
assert b["seed9"]["mode"] == "cold" and "seed" in (
    json.loads((forks.branch_dir("/tmp/ci-fork", "seed9")
                / forks.FORK_MANIFEST).read_text())["cold_reason"])
# the honesty gate, spot-checked in CI: the no-divergence restore
# branch IS the trunk run, byte for byte (tree + streams)
assert (fleet.output_tree_digest(forks.branch_dir("/tmp/ci-fork", "baseline"))
        == fleet.output_tree_digest("/tmp/ci-fork-trunk")), \
    "baseline branch tree != trunk tree"
assert (fleet._stream_digests(forks.branch_dir("/tmp/ci-fork", "baseline"))
        == fleet._stream_digests("/tmp/ci-fork-trunk")), \
    "baseline branch streams != trunk streams"
assert s["trunk_flows"], "reducer found no trunk flow telemetry"
report = open("/tmp/ci-fork-report.txt").read()
assert "Δp50" in report and "CI95" in report, report
EOF
# bisect localizes the what-if: the undiverged branch agrees with the
# trunk (exit 0); the command-injected branch names its first divergent
# round, strictly after the fork boundary
python tools/bisect_divergence.py \
    --a /tmp/ci-fork-trunk --b /tmp/ci-fork/branch_baseline
rc=0
python tools/bisect_divergence.py --json \
    --a /tmp/ci-fork-trunk --b /tmp/ci-fork/branch_lossy \
    > /tmp/ci-fork-bisect.json || rc=$?
test "$rc" -eq 1
python - "$ck" <<'EOF'
import json, sys
from shadow_tpu import checkpoint as ckpt

d = json.load(open("/tmp/ci-fork-bisect.json"))
fork_rounds = ckpt.read_header(sys.argv[1])["rounds"]
assert d["kind"] == "digest", d
assert d["round"] > fork_rounds, (d, fork_rounds)
assert d["t"] >= 15_000_000_000, d  # not before the injected command
print(f"fork smoke OK: baseline byte-identical to the trunk, lossy "
      f"branch first diverges at round {d['round']} "
      f"(t={d['t']} ns, fork point round {fork_rounds})")
EOF

echo "== fast+robust smoke (gossip_churn: faults + checkpoints + digests with the C engine ON vs the Python plane) =="
frrun() {
    rm -rf "/tmp/ci-fr-$1"
    python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-fr-$1" \
        --scheduler-policy tpu_batch \
        --set "experimental.native_colcore=$2" \
        --checkpoint-every 10s --state-digest-every 100 --sample-every 5s \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-fr-$1.json"
    (cd "/tmp/ci-fr-$1" && find hosts -type f | sort | xargs sha256sum && \
     sha256sum flows.jsonl metrics.jsonl state_digests.jsonl) \
        > "/tmp/ci-fr-$1.hashes"
}
frrun c true
frrun py false
diff /tmp/ci-fr-c.json /tmp/ci-fr-py.json
diff /tmp/ci-fr-c.hashes /tmp/ci-fr-py.hashes
python - <<'EOF'
from pathlib import Path
from shadow_tpu import checkpoint as ckpt
from shadow_tpu.native import _colcore
paths = sorted(Path('/tmp/ci-fr-c/checkpoints').glob('*.ckpt'))
assert paths, 'C run wrote no checkpoints'
h = ckpt.read_header(paths[0])
assert h['colcore'] == _colcore.ABI, f"checkpoint missing colcore ABI: {h}"
print(f"fast+robust smoke OK: churned+checkpointed+digested C run "
      f"bit-identical to the Python plane ({len(paths)} C-state "
      f"checkpoints, colcore ABI {h['colcore']})")
EOF

echo "== modern-web smoke (web_cdn: cross-policy + C on/off hashes, SACK counters) =="
webrun() {
    rm -rf "/tmp/ci-web-$1"
    python -m shadow_tpu examples/web_cdn.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-web-$1" \
        --scheduler-policy "$2" \
        --set "experimental.native_colcore=$3" \
        --set general.stop_time=26s \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-web-$1.json"
    (cd "/tmp/ci-web-$1" && find hosts -type f | sort | xargs -r sha256sum && \
     sha256sum flows.jsonl metrics.jsonl) > "/tmp/ci-web-$1.hashes"
}
webrun tpc thread_per_core true
webrun tpu tpu_batch true
webrun py tpu_batch false
diff /tmp/ci-web-tpc.json /tmp/ci-web-tpu.json
diff /tmp/ci-web-tpu.json /tmp/ci-web-py.json
diff /tmp/ci-web-tpc.hashes /tmp/ci-web-tpu.hashes
diff /tmp/ci-web-tpu.hashes /tmp/ci-web-py.hashes
python - <<'EOF'
import json
d = json.load(open("/tmp/ci-web-tpu.json"))
c = d["counters"]
flows = d["telemetry"]["flows"]
for kind in ("web.fetch", "web.origin", "dns.resolve"):
    assert flows.get(kind, {}).get("count", 0) > 0, f"no {kind} flows"
assert c.get("stream_fast_retransmits", 0) > 0, "no fast retransmits"
assert c.get("stream_sack_retransmits", 0) > 0, \
    "SACK recovered no extra holes under the lossy degrade window"
print(f"modern-web smoke OK: {d['events']} events bit-identical across "
      f"thread_per_core/tpu_batch and C on/off; "
      f"{flows['web.fetch']['count']} fetches, "
      f"{c['stream_sack_retransmits']} SACK hole retransmits")
EOF

echo "== ABR smoke (abr_1k: C on/off hash + report ABR rows) =="
abrrun() {
    rm -rf "/tmp/ci-abr-$1"
    python -m shadow_tpu examples/abr_1k.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-abr-$1" \
        --scheduler-policy tpu_batch \
        --set "experimental.native_colcore=$2" \
        --set general.stop_time=16s \
        | python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(sys.stdin); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        > "/tmp/ci-abr-$1.json"
    (cd "/tmp/ci-abr-$1" && sha256sum flows.jsonl metrics.jsonl) \
        > "/tmp/ci-abr-$1.hashes"
}
abrrun c true
abrrun py false
diff /tmp/ci-abr-c.json /tmp/ci-abr-py.json
diff /tmp/ci-abr-c.hashes /tmp/ci-abr-py.hashes
python tools/metrics_report.py /tmp/ci-abr-c --json | python -c '
import json, sys
r = json.load(sys.stdin)
assert r["abr"], "report has no ABR rows"
seg = sum(g["segments"] for g in r["abr"])
assert seg > 0, r["abr"]
assert any(g["mean_rate_bps"] > 0 for g in r["abr"])
groups = len(r["abr"])
print(f"ABR smoke OK: C on/off bit-identical, {seg} segments across "
      f"{groups} host-groups in the report")
'

echo "== device-transport smoke (web_cdn: devt on/off identity; web_cdn_100k: 100k-host short leg) =="
devtrun() {
    rm -rf "/tmp/ci-devt-$1"
    python -m shadow_tpu examples/web_cdn.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-devt-$1" \
        --scheduler-policy tpu_batch \
        --set experimental.native_colcore=false \
        --set "experimental.device_transport=$2" \
        --set general.stop_time=26s \
        > "/tmp/ci-devt-$1.raw.json"
    python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(open(sys.argv[1])); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        "/tmp/ci-devt-$1.raw.json" > "/tmp/ci-devt-$1.json"
    (cd "/tmp/ci-devt-$1" && find hosts -type f | sort | xargs -r sha256sum && \
     sha256sum flows.jsonl metrics.jsonl) > "/tmp/ci-devt-$1.hashes"
}
devtrun off false
devtrun on true
diff /tmp/ci-devt-off.json /tmp/ci-devt-on.json
diff /tmp/ci-devt-off.hashes /tmp/ci-devt-on.hashes
# the committed 100k-endpoint config builds and runs end to end with the
# columnar transport engaged (short leg: the full 6s config is the bench
# row's artifact — bench.py web_cdn_100k_row)
rm -rf /tmp/ci-devt-100k
python -m shadow_tpu examples/web_cdn_100k.yaml --quiet --json-summary \
    --data-directory /tmp/ci-devt-100k \
    --scheduler-policy tpu_batch \
    --set experimental.native_colcore=false \
    --set experimental.device_transport=true \
    --stop-time 500ms > /tmp/ci-devt-100k.json
python - <<'EOF'
import json
# vacuity guards: BOTH devt-on runs must actually have advanced cohorts
# through the batched kernel — otherwise the identity diffs above
# compared scalar against scalar and prove nothing
on = json.load(open("/tmp/ci-devt-on.raw.json"))
assert on.get("device_transport_engaged"), \
    "web_cdn on-leg advanced zero cohorts — the identity diff is vacuous"
big = json.load(open("/tmp/ci-devt-100k.json"))
assert big.get("device_transport_engaged"), \
    "100k leg advanced zero cohorts through the batched kernel"
dt = big.get("device_transport", {})
print(f"device-transport smoke OK: web_cdn byte-identical on/off "
      f"({on['device_transport']['cohorts']} cohorts served); "
      f"100k-host leg ran {big['events']} events, "
      f"{dt.get('cohorts')} cohorts / {dt.get('acks_batched')} acks "
      f"batched, {dt.get('misguesses')} misguesses")
EOF

echo "== telemetry smoke (gossip_churn: cross-policy stream hashes + report parse) =="
telrun() {
    python -m shadow_tpu examples/gossip_churn.yaml --quiet \
        --data-directory "/tmp/ci-tel-$1" \
        --scheduler-policy "$2" --sample-every 5s > /dev/null
    sha256sum "/tmp/ci-tel-$1/metrics.jsonl" "/tmp/ci-tel-$1/flows.jsonl" \
        | awk '{print $1}' > "/tmp/ci-tel-$1.hashes"
}
telrun a tpu_batch
telrun b thread_per_core
diff /tmp/ci-tel-a.hashes /tmp/ci-tel-b.hashes
python tools/metrics_report.py /tmp/ci-tel-a --json | python -c '
import json, sys
r = json.load(sys.stdin)
assert r["samples"] > 0, "no telemetry samples"
assert r["flows"] > 0, "no flow records"
assert r["fault_transitions"] > 0, "fault timeline missing from metrics"
assert r["fault_windows"], "no fault windows folded"
print(f"telemetry smoke OK: {r[\"samples\"]} samples, {r[\"flows\"]} flows, "
      f"{r[\"fault_transitions\"]} fault transitions, streams bit-identical "
      f"across tpu_batch/thread_per_core")
'

echo "== managed smoke (managed_smoke.yaml: real binaries, shim fast plane on/off identity) =="
make -C native -s
mrun() {
    rm -rf "/tmp/ci-managed-$1"
    SHADOW_TPU_SHIM_FASTPATH=$2 \
    python -m shadow_tpu examples/managed_smoke.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-managed-$1" \
        > "/tmp/ci-managed-$1.raw.json"
    # shim_fast_* counters are informational (they say WHERE a syscall
    # completed, not WHAT the simulation did) and legitimately differ
    # across the two legs — everything else must be byte-identical
    python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(open(sys.argv[1])); [d.pop(k, None) for k in V]; d["counters"]={k:v for k,v in d["counters"].items() if not k.startswith("shim_fast_")}; print(json.dumps(d,sort_keys=True))' \
        "/tmp/ci-managed-$1.raw.json" > "/tmp/ci-managed-$1.json"
    # *.clock is the process's live shim scratch page (fast-op counters,
    # flags, oplog residue) file-backed into the data dir — plumbing of
    # the same informational class as shim_fast_*, not an observable
    (cd "/tmp/ci-managed-$1" && find hosts -type f ! -name "*.clock" \
        | sort | xargs sha256sum) > "/tmp/ci-managed-$1.hashes"
}
mrun fast 1
mrun slow 0
diff /tmp/ci-managed-fast.json /tmp/ci-managed-slow.json
diff /tmp/ci-managed-fast.hashes /tmp/ci-managed-slow.hashes
python - <<'EOF'
import json
fast = json.load(open("/tmp/ci-managed-fast.raw.json"))
slow = json.load(open("/tmp/ci-managed-slow.raw.json"))
c = fast["counters"]
assert fast["process_errors"] == [], fast["process_errors"]
# vacuity guards: the fast leg must actually have completed a majority
# of its syscalls in-shim, and the slow leg must actually have been slow
# — otherwise the identity diffs above compared like against like
assert c.get("shim_fast_syscalls", 0) * 2 > c["syscalls"], c
assert slow["counters"].get("shim_fast_ring_read", 0) == 0, slow["counters"]
out = open("/tmp/ci-managed-fast/hosts/client/ring_probe.0.stdout").read()
assert "bytes=300000" in out and "eof=1" in out, out
print(f"managed smoke OK: transfer byte-exact both legs, "
      f"{c['shim_fast_syscalls']}/{c['syscalls']} syscalls in-shim on "
      f"the fast leg, observables bit-identical fast on/off")
EOF

echo "== managed-checkpoint smoke (managed_smoke.yaml: reexec snapshot mid-transfer, resume, identity) =="
mckrun() {   # $1 = tag, rest = extra args
    local tag=$1; shift
    rm -rf "/tmp/ci-mckpt-$tag"
    python -m shadow_tpu examples/managed_smoke.yaml --quiet --json-summary \
        --data-directory "/tmp/ci-mckpt-$tag" --state-digest-every 5 "$@" \
        > "/tmp/ci-mckpt-$tag.raw.json"
    python -c 'import json,sys; from shadow_tpu.core.controller import VOLATILE_SUMMARY_KEYS as V; d=json.load(open(sys.argv[1])); [d.pop(k, None) for k in V]; print(json.dumps(d,sort_keys=True))' \
        "/tmp/ci-mckpt-$tag.raw.json" > "/tmp/ci-mckpt-$tag.json"
    # *.clock excluded for the same reason as the managed gate above
    (cd "/tmp/ci-mckpt-$tag" && find hosts -type f ! -name "*.clock" \
        | sort | xargs sha256sum) > "/tmp/ci-mckpt-$tag.hashes"
}
mckrun full
mckrun src --checkpoint-every 500ms
ck=$(ls /tmp/ci-mckpt-src/checkpoints/ckpt_*.ckpt | head -1)
echo "resuming managed run from $ck (re-execution)"
mckrun resume --resume-from "$ck"
# the checkpointing run itself is unperturbed, and the resumed run
# reproduces the uninterrupted one: summaries, host trees, digest stream
diff /tmp/ci-mckpt-full.json /tmp/ci-mckpt-src.json
diff /tmp/ci-mckpt-full.hashes /tmp/ci-mckpt-src.hashes
diff /tmp/ci-mckpt-full.json /tmp/ci-mckpt-resume.json
diff /tmp/ci-mckpt-full.hashes /tmp/ci-mckpt-resume.hashes
cmp /tmp/ci-mckpt-full/state_digests.jsonl /tmp/ci-mckpt-resume/state_digests.jsonl
python - "$ck" <<'EOF'
import json, sys
hdr = json.loads(open(sys.argv[1]).readline())
assert hdr["mode"] == "reexec" and hdr["managed"] is True, hdr
assert hdr["version"] == 5, hdr
payload = json.loads(open(sys.argv[1]).read().splitlines()[1])
assert payload["cursors"], "snapshot carries no guest journal cursors"
for tag in ("full", "src", "resume"):
    s = json.load(open(f"/tmp/ci-mckpt-{tag}.raw.json"))
    assert s["process_errors"] == [], (tag, s["process_errors"])
print(f"managed-checkpoint smoke OK: v5 reexec snapshot "
      f"({len(payload['cursors'])} journal cursor(s)) resumed "
      f"byte-identical — trees, summaries, digest stream")
EOF

echo "== live-ops smoke (gossip_churn: --follow attach + live link_down + replay tree-hash identity) =="
rm -rf /tmp/ci-live /tmp/ci-live-replay /tmp/ci-live.sock
# follower first: it retries the connect until the run binds the socket
python tools/metrics_report.py --follow /tmp/ci-live.sock \
    --follow-timeout 120 > /tmp/ci-live-follow.txt &
follow_pid=$!
python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
    --data-directory /tmp/ci-live --scheduler-policy tpu_batch \
    --set general.stop_time=25s --set general.heartbeat_interval=2s \
    --live-endpoint /tmp/ci-live.sock \
    --state-digest-every 100 --sample-every 5s > /tmp/ci-live.json &
run_pid=$!
# inject a runtime fault into the RUNNING sim; the ack is the gate
python -m shadow_tpu.live send /tmp/ci-live.sock \
    '{"cmd":"link_down","src_nodes":[0],"dst_nodes":[1],"duration":"3s"}' \
    > /tmp/ci-live-ack.json
# the workload may legitimately exit nonzero on process_errors at this
# truncated stop time — the hash comparison below is the gate
wait "$run_pid" || true
wait "$follow_pid"
python -m shadow_tpu examples/gossip_churn.yaml --quiet --json-summary \
    --data-directory /tmp/ci-live-replay --scheduler-policy tpu_batch \
    --set general.stop_time=25s \
    --replay-commands /tmp/ci-live/commands.jsonl \
    --state-digest-every 100 --sample-every 5s > /tmp/ci-live-replay.json \
    || true
for d in /tmp/ci-live /tmp/ci-live-replay; do
    (cd "$d" && find hosts -type f | sort | xargs sha256sum && \
     sha256sum commands.jsonl flows.jsonl metrics.jsonl state_digests.jsonl) \
        > "$d.hashes"
done
diff /tmp/ci-live.hashes /tmp/ci-live-replay.hashes
python - <<'EOF'
import json

ack = json.load(open("/tmp/ci-live-ack.json"))
assert ack["type"] == "ack", ack
follow = open("/tmp/ci-live-follow.txt").read().splitlines()
hbs = [ln for ln in follow if ln.startswith("hb  ")]
samples = [ln for ln in follow if ln.startswith("sample @")]
assert len(hbs) >= 3, f"want >=3 heartbeats, got {len(hbs)}"
assert samples, "no telemetry samples reached the follower"
assert any(ln.startswith("command applied: link_down") for ln in follow), \
    "follower never saw the injected command"
assert any(ln.startswith("run ended:") for ln in follow), \
    "follower missed the end record"
live = json.load(open("/tmp/ci-live.json"))
assert live["exit_reason"] == "completed", live
assert live.get("fault_transitions_applied", 0) >= 2, live
replay = json.load(open("/tmp/ci-live-replay.json"))
assert replay["exit_reason"] == "completed", replay
print(f"live-ops smoke OK: {len(hbs)} heartbeats + {len(samples)} samples "
      f"followed, link_down ack'd + applied, replay-from-commands.jsonl "
      f"byte-identical (trees + flows + metrics + digests + command log)")
EOF

echo "== CI gate passed =="
