#!/usr/bin/env python
"""Compare the branches of a checkpoint fork against their trunk.

The comparative reducer's CLI face (shadow_tpu/forks.py): point it at a
fork directory (``python -m shadow_tpu fork`` / ``python -m
shadow_tpu.fleet sweep --fork-from``) and it k-way merges every branch's
``LogHistogram`` flow states, groups branches (``group:`` in
branches.yaml), and renders per-group flow percentiles diffed against
the trunk run — the mean per-branch (branch − trunk) percentile delta
with its t-based CI95 across the group, starred when the CI excludes
zero ("Once is Never Enough": the per-branch statistic first, the
inference across branches). Cold-run groups (seed / fault / congestion
-control divergence) are tagged ``[cold]``.

Usage:
    python tools/compare.py FORK_DIR            # comparison table
    python tools/compare.py FORK_DIR --full     # branch report + table
    python tools/compare.py FORK_DIR --json     # the summary JSON line

The reduction is idempotent — a pure function of the on-disk branch
manifests and telemetry states — so re-running it after adding branches
(or against a partially failed fork) is always safe. Also reachable as
``python -m shadow_tpu.fleet report FORK_DIR --compare``. To localize
WHERE a branch departed (the first divergent round, not just the
percentile delta), follow up with ``python tools/bisect_divergence.py
--a TRUNK_DIR --b FORK_DIR/branch_<name>``.

Exit status: 0 = all branches ok, 1 = some branch failed, 2 = usage /
not a fork directory.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from shadow_tpu import forks as _forks  # noqa: E402


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    full = "--full" in argv
    argv = [a for a in argv if a not in ("--json", "--full")]
    if len(argv) != 1:
        print(__doc__, file=sys.stderr)
        return 2
    fork_dir = Path(argv[0])
    if (not (fork_dir / _forks.FORK_SUMMARY).is_file()
            and not any(fork_dir.glob("branch_*/" + _forks.FORK_MANIFEST))):
        print(f"compare: {fork_dir} is not a fork directory (no "
              f"{_forks.FORK_SUMMARY} and no branch_*/"
              f"{_forks.FORK_MANIFEST}) — run a fork first: "
              f"python -m shadow_tpu fork cfg.yaml --from CKPT "
              f"--branches branches.yaml", file=sys.stderr)
        return 2
    try:
        summary = _forks.reduce_fork(fork_dir)
    except OSError as exc:
        print(f"compare: {exc}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(summary))
    elif full:
        print(_forks.render_fork_report(summary))
    else:
        print(_forks.render_compare(summary))
    return 0 if not summary["failed"] else 1


if __name__ == "__main__":
    sys.exit(main())
