#!/usr/bin/env python3
"""Chaos harness front-end for supervised runs (shadow_tpu/supervise.py).

Infrastructure-level fault injection — worker SIGKILLs, ring-stall
wedges, in-process failures, guest hangs — at deterministic ROUNDS, so
recovery is proven, not asserted: a supervised run surviving the
injected failures must converge to the same bytes as a failure-free run.
(Complementary to the config `faults:` timeline, which injects
SIMULATED failures the run is supposed to model, not survive.)

Spec grammar (comma list): ``[s<K>:]<kind>@r<N>`` — kind in
kill / wedge / fail / guest_wedge, fired once when shard K (default 0)
reaches round N. Once-only across restarts via O_EXCL markers under
``<data_dir>/chaos/``.

Usage:
    # validate + pretty-print a spec
    python tools/chaos.py --parse 'kill@r500,s1:wedge@r900'

    # run a command with SHADOW_TPU_CHAOS set (exec, no extra process)
    python tools/chaos.py --spec 'kill@r500,s1:wedge@r900' -- \
        python -m shadow_tpu examples/gossip_churn.yaml --shards 2 \
        --checkpoint-every 1s --supervise
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from shadow_tpu.supervise import CHAOS_ENV, parse_chaos  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools/chaos.py",
        description="validate chaos specs / run commands under them")
    p.add_argument("--parse", metavar="SPEC",
                   help="parse SPEC, print the event list as JSON, exit")
    p.add_argument("--spec", metavar="SPEC",
                   help=f"set {CHAOS_ENV}=SPEC and exec the command "
                   f"after '--'")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to exec under --spec (prefix with --)")
    args = p.parse_args(argv)
    if args.parse is None and args.spec is None:
        p.error("one of --parse or --spec is required")
    try:
        events = parse_chaos(args.parse if args.parse is not None
                             else args.spec)
    except ValueError as exc:
        print(f"chaos: {exc}", file=sys.stderr)
        return 2
    if args.parse is not None:
        print(json.dumps(events, indent=1, sort_keys=True))
        return 0
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("--spec needs a command after '--'")
    os.environ[CHAOS_ENV] = args.spec
    try:
        os.execvp(cmd[0], cmd)
    except OSError as exc:
        print(f"chaos: cannot exec {cmd[0]}: {exc}", file=sys.stderr)
        return 127


if __name__ == "__main__":
    sys.exit(main())
