#!/usr/bin/env python
"""Diff two determinism-sentinel digest streams and name the first
divergent round (and the hosts that diverged there).

The sentinel (``general.state_digest_every`` / ``--state-digest-every N``)
writes one JSON record per sampled round boundary to
``<data_dir>/state_digests.jsonl``:

    {"round": R, "t": SIM_NS, "digest": GLOBAL_SHA, "hosts": {name: SHA}}

Two runs of the same config MUST produce identical streams regardless of
scheduler policy or data plane. When a whole-run output hash mismatches,
run both configs again with the sentinel enabled and point this tool at
the two streams: instead of "the trees differ", you get "the first
divergence is at round 1840 on hosts client3, relay7" — a bisection
target instead of a haystack.

Usage:
    python tools/bisect_divergence.py A/state_digests.jsonl B/state_digests.jsonl
    python tools/bisect_divergence.py --a RUN_DIR_A --b RUN_DIR_B
    python tools/bisect_divergence.py --window-rounds K A.jsonl B.jsonl
    python tools/bisect_divergence.py --shard K A_datadir B_datadir
    python tools/bisect_divergence.py --json A.jsonl B.jsonl

``--a DIR --b DIR`` names two run directories instead of two stream
files: each resolves to its ``state_digests.jsonl`` (or its
``state_digests.shard<K>.jsonl`` sidecar under ``--shard K``). This is
the fork-comparison spelling (shadow_tpu/forks.py): point --a at the
trunk run directory and --b at a ``branch_<name>`` directory — the
first divergent round is where the branch's what-if departed from the
trunk; rounds at or before the fork boundary agreeing is the fork's
honesty gate in action.

``--json`` prints ONE machine-readable JSON line instead of the report:
``{"kind": "digest", "round": R, "t": NS, "hosts": [...], "shard": K,
"last_match": R0}`` on divergence (``kind`` is one of digest/missing/
extra/sampling), ``{"kind": "identical", ...}`` on a match. The exit
status is unchanged, and the record feeds the time-travel debugger
directly: ``python -m shadow_tpu.live jump RUN_DIR --from-bisect -``.

``--shard K`` (for runs made with ``general.sim_shards`` > 1) compares
the shard-tagged sidecar streams ``state_digests.shard<K>.jsonl`` the
sharded parent writes beside the merged stream: each covers one shard's
OWNED hosts plus that shard's slice of the global observables, so a
cross-shard divergence is localized to a round AND a shard. Pass the two
data directories (or the sidecar files directly). Without --shard, a
record carrying a "shard" tag still gets it printed in the report.

``--window-rounds K`` (for runs made with a fixed
``experimental.device_window_rounds``) additionally names which fused
device window contained the first divergent round — window W covers
rounds [W*K+1, (W+1)*K] on the gapless grid. Real window boundaries can
drift later than the grid (idle rounds, causal flushes, and busy
pipeline slots all restart the K-count), so treat the annotation as the
EARLIEST window that could have carried the round — the right place to
START re-examining dispatches, not a proof of which one misbehaved.

Exit status: 0 = streams identical, 1 = divergence found (details on
stdout), 2 = usage / unreadable input.
"""

from __future__ import annotations

import json
import sys


def _die(msg: str):
    print(f"bisect_divergence: {msg}", file=sys.stderr)
    raise SystemExit(2)


def load_stream(path: str) -> list[dict]:
    recs = []
    try:
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as exc:
                    _die(f"{path}:{i}: bad JSON ({exc})")
                if "round" not in rec or "digest" not in rec:
                    _die(f"{path}:{i}: not a sentinel record (need "
                         f"'round' and 'digest' keys)")
                recs.append(rec)
    except OSError as exc:
        _die(f"cannot read {path}: {exc}")
    if not recs:
        _die(f"{path}: empty digest stream")
    return recs


def divergent_hosts(a: dict, b: dict) -> list[str]:
    ha, hb = a.get("hosts") or {}, b.get("hosts") or {}
    names = sorted(set(ha) | set(hb))
    return [n for n in names if ha.get(n) != hb.get(n)]


def compare(recs_a: list[dict], recs_b: list[dict]):
    """Returns None if identical, else a dict describing the first
    divergence."""
    by_round_b = {r["round"]: r for r in recs_b}
    last_match = None
    for ra in recs_a:
        rb = by_round_b.get(ra["round"])
        if rb is None:
            return {"kind": "missing", "round": ra["round"], "t": ra.get("t"),
                    "last_match": last_match}
        if ra["digest"] != rb["digest"]:
            hosts = divergent_hosts(ra, rb)
            return {"kind": "digest", "round": ra["round"], "t": ra.get("t"),
                    "hosts": hosts, "last_match": last_match}
        last_match = ra["round"]
    extra = [r["round"] for r in recs_b if r["round"] > recs_a[-1]["round"]]
    if len(recs_b) != len(recs_a) and not extra:
        # same round range but different sampling — config mismatch
        return {"kind": "sampling", "round": None, "t": None,
                "last_match": last_match}
    if extra:
        return {"kind": "extra", "round": extra[0], "t": None,
                "last_match": last_match}
    return None


def window_of(round_no: int, window_rounds: int) -> tuple[int, int, int]:
    """(window index, first round, last round) of the fused device window
    containing ``round_no`` under a fixed device_window_rounds=K. Rounds
    are 1-based in the sentinel stream; windows close every K barriers,
    so window W spans rounds [W*K+1, (W+1)*K]."""
    w = (round_no - 1) // window_rounds
    return w, w * window_rounds + 1, (w + 1) * window_rounds


def _shard_path(path: str, shard: int) -> str:
    """Resolve a --shard argument: a data directory maps to its sidecar
    stream; an explicit file path is taken as-is."""
    import os

    if os.path.isdir(path):
        return os.path.join(path, f"state_digests.shard{shard}.jsonl")
    return path


def _dir_stream(path: str, shard) -> str:
    """Resolve an --a/--b run directory to its digest stream (the shard
    sidecar under --shard)."""
    import os

    if not os.path.isdir(path):
        _die(f"--a/--b expect run directories, and {path!r} is not one "
             f"(pass stream files positionally instead)")
    name = ("state_digests.jsonl" if shard is None
            else f"state_digests.shard{shard}.jsonl")
    return os.path.join(path, name)


def main(argv) -> int:
    window_rounds = 0
    shard = None
    as_json = False
    dir_a = dir_b = None
    while argv and argv[0] in ("--window-rounds", "--shard", "--json",
                               "--a", "--b"):
        flag = argv[0]
        if flag == "--json":
            as_json = True
            argv = argv[1:]
            continue
        if len(argv) < 2:
            print(__doc__, file=sys.stderr)
            return 2
        if flag in ("--a", "--b"):
            if flag == "--a":
                dir_a = argv[1]
            else:
                dir_b = argv[1]
            argv = argv[2:]
            continue
        try:
            val = int(argv[1])
        except ValueError:
            _die(f"{flag} expects an integer, got {argv[1]!r}")
        if flag == "--window-rounds":
            if val < 1:
                _die("--window-rounds must be >= 1 (the fixed K of the "
                     "run)")
            window_rounds = val
        else:
            if val < 0:
                _die("--shard must be >= 0")
            shard = val
        argv = argv[2:]
    if (dir_a is None) != (dir_b is None):
        _die("--a and --b go together (two run directories to diff)")
    if dir_a is not None:
        if argv:
            _die("--a/--b replace the positional stream arguments")
        argv = [_dir_stream(dir_a, shard), _dir_stream(dir_b, shard)]
    elif len(argv) == 2:
        if shard is not None:
            argv = [_shard_path(argv[0], shard),
                    _shard_path(argv[1], shard)]
    else:
        print(__doc__, file=sys.stderr)
        return 2
    recs_a, recs_b = load_stream(argv[0]), load_stream(argv[1])
    d = compare(recs_a, recs_b)
    # the shard a divergence localizes to (sidecar streams carry it)
    shard_tag = shard if shard is not None else (
        recs_a[0].get("shard") if recs_a else None)
    if d is None:
        if as_json:
            print(json.dumps({"kind": "identical", "records": len(recs_a),
                              "last_round": recs_a[-1]["round"],
                              **({"shard": shard_tag}
                                 if shard_tag is not None else {})},
                             sort_keys=True))
        else:
            print(f"identical: {len(recs_a)} sentinel records agree "
                  f"(through round {recs_a[-1]['round']})")
        return 0
    if as_json:
        out = {"kind": d["kind"], "round": d["round"], "t": d.get("t"),
               "hosts": d.get("hosts", []),
               "last_match": d["last_match"],
               **({"shard": shard_tag} if shard_tag is not None else {})}
        if window_rounds and d["kind"] == "digest":
            w, lo, hi = window_of(d["round"], window_rounds)
            out["window"] = {"index": w, "first_round": lo,
                            "last_round": hi}
        print(json.dumps(out, sort_keys=True))
        return 1
    # shard-tagged streams (sim_shards sidecars): name the shard in the
    # report — the first divergent round AND shard, not just the round
    tag = ""
    if shard is not None:
        tag = f" [shard {shard}]"
    elif recs_a and "shard" in recs_a[0]:
        tag = f" [shard {recs_a[0]['shard']}]"
    if d["kind"] == "digest":
        hosts = d["hosts"]
        where = (f"hosts: {', '.join(hosts)}" if hosts
                 else "global engine state only (no per-host divergence)")
        print(f"FIRST DIVERGENT ROUND: {d['round']}{tag} "
              f"(sim t={d['t']} ns)")
        print(f"  last matching round: {d['last_match']}")
        print(f"  divergent {where}")
        if window_rounds:
            w, lo, hi = window_of(d["round"], window_rounds)
            print(f"  fused device window: #{w} (rounds {lo}..{hi} at "
                  f"K={window_rounds}, gapless grid) is the earliest "
                  f"window that could have carried the divergent round")
    elif d["kind"] == "missing":
        print(f"DIVERGED: stream B has no record for round {d['round']} "
              f"(last matching round: {d['last_match']}) — run B ended "
              f"early or sampled differently")
    elif d["kind"] == "extra":
        print(f"DIVERGED: stream B continues past stream A (first extra "
              f"round {d['round']}; last matching round: {d['last_match']}) "
              f"— run A ended early")
    else:
        print("DIVERGED: streams sample different rounds — were both runs "
              "given the same state_digest_every?")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
