#!/usr/bin/env python
"""Render a telemetry run report from metrics.jsonl + flows.jsonl.

The readout half of the telemetry subsystem (shadow_tpu/telemetry/): the
simulator writes deterministic append-only streams; this tool reduces them
to the tables an experiment wants on one screen —

- flow-latency percentiles (p50/p90/p99/p99.9) per host-group and flow
  class, recomputed through the same fixed-layout log histogram the run
  summary uses (shadow_tpu/telemetry/histogram.py), so the two always
  agree;
- per-link (NIC) utilization: egress/ingress token-bucket headroom,
  deferred-ingress backlog, and retransmit pressure per host-group, plus
  the most-saturated individual hosts — "which link's queue saturated in
  round 40k" reads straight off this table;
- the fault timeline folded into windows (down->up, degrade->restore,
  crash->reboot), each annotated with the flow latencies observed inside
  it vs the whole run — "what was fetch p99 during the partition window?"
  is one row here.

Usage:
    python tools/metrics_report.py <data_dir | metrics.jsonl> [--json]
    python tools/metrics_report.py --follow <data_dir | live.sock>

``--json`` emits the machine-readable report dict instead of tables
(tools/ci.sh uses it as a parse gate).

``--follow`` attaches to a RUNNING simulation's live endpoint
(``general.live_endpoint`` / ``--live-endpoint``) and renders the
telemetry stream as it happens: heartbeats (sim/wall rate, per-phase
wall), metrics.jsonl lines as they are written, flow-group percentile
snapshots, per-shard status, and applied runtime commands. The argument
is the run's data directory (its ``live.sock``) or an explicit socket
path. ``--follow-max N`` detaches after N records (CI gates);
``--json`` with ``--follow`` prints the raw records verbatim instead of
rendering. The follower is read-only and never perturbs the simulation.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from shadow_tpu.telemetry.histogram import LogHistogram  # noqa: E402


def _load(path: Path) -> list:
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                out.append(json.loads(ln))
    return out


def group_of(name: str) -> str:
    """Host-group key: the name with its trailing instance digits
    stripped (quantity-expanded templates: client0..clientN -> client)."""
    g = name.rstrip("0123456789")
    return g if g else name


def _quants(hist: LogHistogram) -> dict:
    return hist.quantiles_ns_to_ms() if hist.total else {}


def flow_tables(flows: list) -> dict:
    """(flow class, host group) -> counts + percentiles."""
    out: dict = {}
    for rec in flows:
        key = (rec["flow"], group_of(rec["host"]))
        row = out.get(key)
        if row is None:
            row = out[key] = {"count": 0, "ok": 0, "failed": 0,
                              "hist": LogHistogram()}
        row["count"] += 1
        if rec["status"] == "ok":
            row["ok"] += 1
            row["hist"].add(rec["latency_ns"])
        else:
            row["failed"] += 1
    return out


def abr_tables(flows: list) -> list:
    """ABR roll-up per host-group: mean selected rate (the ``x`` field
    ``abr.segment`` records carry), stall seconds and stall counts (the
    ``abr.stall`` records whose latency IS the stall duration). Empty
    for runs without ABR sessions."""
    acc: dict = {}
    for rec in flows:
        flow = rec["flow"]
        if flow not in ("abr.segment", "abr.stall"):
            continue
        g = group_of(rec["host"])
        row = acc.get(g)
        if row is None:
            row = acc[g] = {"segments": 0, "failed": 0, "rate_sum": 0,
                            "rate_n": 0, "bytes": 0, "stalls": 0,
                            "stall_ns": 0}
        if flow == "abr.segment":
            if rec["status"] == "ok":
                row["segments"] += 1
                row["bytes"] += rec["bytes"]
            else:
                row["failed"] += 1
            x = rec.get("x")
            if x is not None:
                row["rate_sum"] += x
                row["rate_n"] += 1
        else:
            row["stalls"] += 1
            row["stall_ns"] += rec["latency_ns"]
    out = []
    for g in sorted(acc):
        r = acc[g]
        out.append({
            "group": g,
            "segments": r["segments"],
            "failed": r["failed"],
            "mean_rate_bps": (r["rate_sum"] // r["rate_n"]
                              if r["rate_n"] else 0),
            "mbytes": round(r["bytes"] / 1e6, 1),
            "stalls": r["stalls"],
            "stall_s": round(r["stall_ns"] / 1e9, 3),
        })
    return out


def fault_windows(faults: list, t_end: int) -> list:
    """Fold the applied-transition records into [t0, t1) windows. A
    transition that never restores closes at the end of the run."""
    opens: dict = {}
    windows: list = []

    def sig(rec):
        return (tuple(rec.get("src_nodes", ())),
                tuple(rec.get("dst_nodes", ())),
                tuple(rec.get("hosts", ())))

    pairs = {"link_down": "link_up", "link_degrade": "degrade_end",
             "host_down": "host_up"}
    closers = {v: k for k, v in pairs.items()}
    for rec in faults:
        a = rec["action"]
        if a in pairs:
            opens.setdefault((a, sig(rec)), []).append(rec)
        elif a in closers:
            stack = opens.get((closers[a], sig(rec)))
            if stack:
                o = stack.pop(0)
                windows.append({"kind": closers[a], "t0": o["t"],
                                "t1": rec["t"], "detail": o})
    for (kind, _s), stack in opens.items():
        for o in stack:
            windows.append({"kind": kind, "t0": o["t"], "t1": t_end,
                            "detail": o})
    windows.sort(key=lambda w: (w["t0"], w["t1"], w["kind"]))
    return windows


def annotate_windows(windows: list, flows: list) -> None:
    """Per window: latency percentiles of flows that CLOSED inside it."""
    for w in windows:
        hist = LogHistogram()
        n = failed = 0
        for rec in flows:
            if w["t0"] <= rec["t_close"] < w["t1"]:
                n += 1
                if rec["status"] == "ok":
                    hist.add(rec["latency_ns"])
                else:
                    failed += 1
        w["flows_closed"] = n
        w["flows_failed"] = failed
        w.update({f"flow_{k}": v for k, v in _quants(hist).items()})


def link_utilization(meta: dict, samples: list, flows: list) -> list:
    """Per host-group NIC summary: mean egress/ingress token headroom
    over all samples (fraction of capacity — 0 means a saturated/starved
    bucket), peak deferred-ingress backlog, and retransmit totals summed
    from the flow records (the samples' retx column counts LIVE
    connections only, so closed flows' retransmits would vanish from a
    last-sample read). Flow retx is the recording endpoint's sender
    side — download-shaped flows' server retransmits show up in the
    per-sample retx series, not here."""
    names = meta["hosts"]
    cap_up = meta["cap_up"]
    cap_down = meta["cap_down"]
    acc: dict = {}
    for i, name in enumerate(names):
        g = group_of(name)
        row = acc.get(g)
        if row is None:
            row = acc[g] = {"hosts": 0, "up_sum": 0.0, "down_sum": 0.0,
                            "n": 0, "deferred_max": 0, "retx": 0,
                            "down_host_samples": 0, "worst_up": None}
        row["hosts"] += 1
    for s in samples:
        g_up = s["global"]["bucket_up"]
        g_down = s["global"]["tokens_down"]
        h = s["hosts"]
        for i, name in enumerate(names):
            row = acc[group_of(name)]
            up_frac = g_up[i] / cap_up[i] if cap_up[i] else 1.0
            row["up_sum"] += up_frac
            row["down_sum"] += (g_down[i] / cap_down[i]
                                if cap_down[i] else 1.0)
            row["n"] += 1
            if h["deferred"][i] > row["deferred_max"]:
                row["deferred_max"] = h["deferred"][i]
            row["down_host_samples"] += h["down"][i]
            w = row["worst_up"]
            if w is None or up_frac < w[1]:
                row["worst_up"] = (name, up_frac)
    for rec in flows:
        g = acc.get(group_of(rec["host"]))
        if g is not None:
            g["retx"] += rec.get("retx", 0)
    out = []
    for g in sorted(acc):
        row = acc[g]
        n = row["n"] or 1
        out.append({
            "group": g, "hosts": row["hosts"],
            "egress_headroom_mean": round(row["up_sum"] / n, 3),
            "ingress_headroom_mean": round(row["down_sum"] / n, 3),
            "deferred_max": row["deferred_max"],
            "retx_total": row["retx"],
            "down_host_samples": row["down_host_samples"],
            "most_saturated_host": (row["worst_up"][0]
                                    if row["worst_up"] else None),
        })
    return out


def build_report(metrics_path: Path, flows_path: Path) -> dict:
    recs = _load(metrics_path)
    flows = _load(flows_path) if flows_path.exists() else []
    meta = next((r for r in recs if r["kind"] == "meta"), None)
    samples = [r for r in recs if r["kind"] == "sample"]
    faults = [r for r in recs if r["kind"] == "fault"]
    t_end = samples[-1]["t"] if samples else (
        max((f["t_close"] for f in flows), default=0))
    windows = fault_windows(faults, t_end)
    annotate_windows(windows, flows)
    ftab = flow_tables(flows)
    report = {
        "samples": len(samples),
        "flows": len(flows),
        "fault_transitions": len(faults),
        "flow_percentiles": [
            {"flow": k[0], "group": k[1], "count": v["count"],
             "ok": v["ok"], "failed": v["failed"], **_quants(v["hist"])}
            for k, v in sorted(ftab.items())],
        "fault_windows": [
            {k: v for k, v in w.items() if k != "detail"}
            for w in windows],
        "link_utilization": (link_utilization(meta, samples, flows)
                             if meta and samples else []),
        "abr": abr_tables(flows),
    }
    return report


def _fmt_table(rows: list, cols: list) -> str:
    if not rows:
        return "  (none)"
    widths = [max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols]
    lines = ["  " + "  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  " + "  ".join(
            str(r.get(c, "")).ljust(w) for c, w in zip(cols, widths)))
    return "\n".join(lines)


def _render_live(rec: dict, out) -> None:
    """One human line per live record (the --follow renderer)."""
    t = rec.get("type")
    if t == "hello":
        out(f"attached: pid {rec.get('pid')} (protocol v{rec.get('v')})")
    elif t == "hb":
        wall = rec.get("wall") or {}
        shards = rec.get("shards", 1)
        out(f"hb  sim {rec['t'] / 1e9:.1f}s  round {rec['round']}  "
            f"events {rec['events']}  sent {rec['units_sent']}  "
            f"dropped {rec['units_dropped']}"
            + (f"  shards {shards}" if shards != 1 else "")
            + (f"  {wall.get('rate', 0):.2f} sim-s/s" if wall else "")
            + (f"  [{rec['dev']}]" if "dev" in rec else ""))
    elif t == "shard_status":
        out(f"  shard {rec['shard']}: events {rec['events']}  "
            f"sent {rec['units_sent']}  dropped {rec['units_dropped']}"
            + (f"  [{rec['dev']}]" if "dev" in rec else ""))
    elif t == "stream":
        try:
            inner = json.loads(rec["line"])
        except ValueError:
            inner = {}
        kind = inner.get("kind")
        if kind == "fault":
            out(f"fault: {inner.get('action')} at sim "
                f"{inner.get('t', 0) / 1e9:.3f}s "
                f"{({k: v for k, v in inner.items() if k in ('src_nodes', 'dst_nodes', 'hosts')})}")
        elif kind == "sample":
            out(f"sample @ sim {inner.get('t', 0) / 1e9:.1f}s "
                f"({rec['stream']})")
    elif t == "flows_snapshot":
        for name, row in sorted((rec.get("flows") or {}).items()):
            out(f"  flows[{name}]: n {row.get('count', 0)} "
                f"ok {row.get('ok', 0)} failed {row.get('failed', 0)}"
                + (f" p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms"
                   if "p50_ms" in row else ""))
    elif t == "command":
        cmd = rec.get("cmd") or {}
        out(f"command applied: {cmd.get('cmd')} at round "
            f"{rec.get('round')} (sim {rec.get('t', 0) / 1e9:.3f}s, "
            f"seq {rec.get('seq')})")
    elif t == "end":
        out(f"run ended: {rec.get('exit_reason')} after "
            f"{rec.get('rounds')} rounds (sim {rec.get('t', 0) / 1e9:.1f}s)")
    elif t in ("seed_dispatched", "seed_done", "seed_failed"):
        out(f"{t.replace('_', ' ')}: seed {rec.get('seed')}"
            + (f" ({rec.get('error')})" if t == "seed_failed" else ""))


def follow(path: str, max_records=None, as_json: bool = False,
           timeout: float = 30.0, out=print) -> int:
    """Attach to a live endpoint and render its stream until the run
    ends (or ``max_records`` records have been seen)."""
    from shadow_tpu import live as _live

    addr = _live.default_endpoint(path)
    n = 0
    try:
        for rec in _live.stream_records(addr, timeout=timeout):
            if as_json:
                out(json.dumps(rec, sort_keys=True))
            else:
                _render_live(rec, out)
            n += 1
            if rec.get("type") == "end":
                return 0
            if max_records is not None and n >= max_records:
                return 0
    except OSError as exc:
        print(f"metrics_report: cannot attach to {addr}: {exc}",
              file=sys.stderr)
        return 2
    return 0  # endpoint closed (run finished while we were draining)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="run data directory (or metrics.jsonl)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report dict")
    ap.add_argument("--follow", action="store_true",
                    help="attach to a running simulation's live endpoint "
                    "and render the stream (path = data dir or socket)")
    ap.add_argument("--follow-max", type=int, default=None, metavar="N",
                    help="with --follow: detach after N records")
    ap.add_argument("--follow-timeout", type=float, default=30.0,
                    metavar="S", help="with --follow: connect/read "
                    "timeout in wall seconds")
    args = ap.parse_args(argv)
    if args.follow:
        return follow(args.path, max_records=args.follow_max,
                      as_json=args.json, timeout=args.follow_timeout)
    p = Path(args.path)
    if p.is_dir():
        metrics, flows = p / "metrics.jsonl", p / "flows.jsonl"
    else:
        metrics, flows = p, p.parent / "flows.jsonl"
    if not metrics.exists():
        print(f"metrics_report: {metrics} not found (run with a "
              f"telemetry: section or --sample-every)", file=sys.stderr)
        return 2
    report = build_report(metrics, flows)
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 0
    print(f"telemetry report: {report['samples']} samples, "
          f"{report['flows']} flows, "
          f"{report['fault_transitions']} fault transitions\n")
    print("flow latency percentiles (ms) per host-group:")
    print(_fmt_table(report["flow_percentiles"],
                     ["flow", "group", "count", "ok", "failed", "p50_ms",
                      "p90_ms", "p99_ms", "p99_9_ms"]))
    print("\nper-link (NIC) utilization per host-group "
          "(headroom 1.0 = idle bucket, 0.0 = saturated):")
    print(_fmt_table(report["link_utilization"],
                     ["group", "hosts", "egress_headroom_mean",
                      "ingress_headroom_mean", "deferred_max",
                      "retx_total", "down_host_samples",
                      "most_saturated_host"]))
    if report["abr"]:
        print("\nABR sessions per host-group (mean selected rate, "
              "rebuffering stalls):")
        print(_fmt_table(report["abr"],
                         ["group", "segments", "failed", "mean_rate_bps",
                          "mbytes", "stalls", "stall_s"]))
    print("\nfault windows (flow latencies inside each window):")
    wrows = [{**w, "t0_s": round(w["t0"] / 1e9, 3),
              "t1_s": round(w["t1"] / 1e9, 3)}
             for w in report["fault_windows"]]
    print(_fmt_table(wrows,
                     ["kind", "t0_s", "t1_s", "flows_closed",
                      "flows_failed", "flow_p50_ms", "flow_p99_ms"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
