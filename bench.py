#!/usr/bin/env python
"""Benchmark harness (VERDICT.md round-1 item #2; BASELINE.md metric).

Default mode runs the headline benchmark and prints EXACTLY ONE JSON line:

    {"metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch", "value": ...,
     "unit": "sim-sec/wall-sec", "vs_baseline": ...}

where vs_baseline is the ratio against the thread_per_core CPU policy on the
SAME machine and config (BASELINE.md records no absolute reference numbers —
the reference mount was empty — so the baseline is the reference's own
headline CPU policy re-implemented here, per BASELINE.json north_star).

``--all`` additionally measures every committed benchmark config under both
policies plus the raw draw-plane device-vs-numpy throughput, writing
BENCH_DETAIL.json next to this file. Progress goes to stderr; stdout carries
only the single JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_config(path: str, policy: str, tag: str) -> dict:
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config(str(ROOT / path), {
        "experimental.scheduler_policy": policy,
        "general.data_directory": f"/tmp/shadow-bench-{tag}",
    })
    t0 = time.perf_counter()
    result = Controller(cfg, mirror_log=False).run()
    result["total_wall_seconds"] = time.perf_counter() - t0  # incl. build
    if result["process_errors"]:
        log(f"WARNING {tag}: {len(result['process_errors'])} process errors")
    log(
        f"{tag}: {result['sim_sec_per_wall_sec']:.3f} sim-sec/wall-sec "
        f"({result['events']} events, {result['units_sent']} units, "
        f"{result['wall_seconds']:.2f}s loop wall)"
    )
    return result


def draw_plane_throughput(n: int = 1_000_000) -> dict:
    """Raw loss-draw throughput, device vs numpy twin, at a config-#5-scale
    batch — the per-round math a 100k-host simulation would batch."""
    import numpy as np

    from shadow_tpu.network.fluid import MAX_PKTS, loss_flags
    from shadow_tpu.ops.propagate import DeviceDrawPlane

    rng = np.random.default_rng(0)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    hi = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    npk = np.full(n, MAX_PKTS, np.uint32)
    th = np.full(n, 1 << 12, np.uint32)

    plane = DeviceDrawPlane(seed=7, max_batch=1 << 20)
    plane.dispatch(lo, hi, npk, th).read()  # warm/compile the full bucket
    t0 = time.perf_counter()
    dev_flags = plane.dispatch(lo, hi, npk, th).read()
    dev_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    np_flags = loss_flags(7, lo, hi, npk, th)
    np_s = time.perf_counter() - t0
    assert (dev_flags == np_flags).all(), "draw-plane bitmatch violated"
    out = {
        "batch": n,
        "device_units_per_sec": n / dev_s,
        "numpy_units_per_sec": n / np_s,
        "device_speedup": np_s / dev_s,
    }
    log(f"draw-plane @1M units: device {out['device_units_per_sec']:.3g}/s "
        f"vs numpy {out['numpy_units_per_sec']:.3g}/s "
        f"({out['device_speedup']:.1f}x)")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="full matrix + BENCH_DETAIL.json")
    ap.add_argument("--config", default="examples/tgen_1k.yaml",
                    help="headline config (default: BASELINE config #2)")
    args = ap.parse_args()

    detail: dict = {"machine_note": "tpu_batch uses the local JAX default "
                    "device; thread_per_core is the CPU baseline policy"}

    # best-of-2 per policy, INTERLEAVED: shared-machine load drifts on the
    # scale of one run, so grouping a policy's repetitions correlates the
    # noise with the policy and corrupts the ratio
    runs = {"thread_per_core": [], "tpu_batch": []}
    for _ in range(2):
        for pol, tag in (("thread_per_core", "tpc"), ("tpu_batch", "tpu")):
            runs[pol].append(run_config(args.config, pol, tag))
    base = max(runs["thread_per_core"],
               key=lambda r: r["sim_sec_per_wall_sec"])
    tpu = max(runs["tpu_batch"], key=lambda r: r["sim_sec_per_wall_sec"])
    headline = {
        "metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch",
        "value": round(tpu["sim_sec_per_wall_sec"], 4),
        "unit": "sim-sec/wall-sec",
        "vs_baseline": round(
            tpu["sim_sec_per_wall_sec"] / base["sim_sec_per_wall_sec"], 4),
    }
    detail["tgen_1k"] = {"thread_per_core": base, "tpu_batch": tpu}

    # results must be identical across policies — a benchmark that diverged
    # would be measuring two different simulations
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert base[k] == tpu[k], f"policy divergence on {k}"

    if args.all:
        for path, tag in (("examples/tgen_100host.yaml", "tgen_100"),
                          ("examples/tor_400relay.yaml", "tor_400"),
                          ("examples/gossip_10k.yaml", "gossip_10k")):
            detail[tag] = {
                "thread_per_core": run_config(path, "thread_per_core", f"{tag}-tpc"),
                "tpu_batch": run_config(path, "tpu_batch", f"{tag}-tpu"),
            }
            for k in ("events", "units_sent", "units_dropped"):
                assert (detail[tag]["thread_per_core"][k]
                        == detail[tag]["tpu_batch"][k]), (tag, k)
        detail["draw_plane"] = draw_plane_throughput()
        for tag in ("tgen_1k", "tgen_100", "tor_400", "gossip_10k"):
            for pol in detail[tag]:
                detail[tag][pol].pop("counters", None)
                detail[tag][pol].pop("process_errors", None)
        (ROOT / "BENCH_DETAIL.json").write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json")

    print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
