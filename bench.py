#!/usr/bin/env python
"""Benchmark harness (VERDICT.md round-1 item #2; BASELINE.md metric).

Default mode runs the headline benchmark and prints EXACTLY ONE JSON line:

    {"metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch", "value": ...,
     "unit": "sim-sec/wall-sec", "vs_baseline": ...}

where vs_baseline is the ratio against the thread_per_core CPU policy on the
SAME machine and config (BASELINE.md records no absolute reference numbers —
the reference mount was empty — so the baseline is the reference's own
headline CPU policy re-implemented here, per BASELINE.json north_star).

``--all`` additionally measures every committed benchmark config under both
policies plus the raw draw-plane device-vs-numpy throughput, writing
BENCH_DETAIL.json next to this file. Progress goes to stderr; stdout carries
only the single JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- shared median-of-N aggregation discipline (headline + tor rows) --------
def _median_run(rs: list) -> dict:
    """The median run by rate (ties keep the later run, like sorted())."""
    return sorted(rs, key=lambda r: r["sim_sec_per_wall_sec"])[len(rs) // 2]


def _run_rates(rs: list) -> list:
    return [round(r["sim_sec_per_wall_sec"], 3) for r in rs]


def _spread_rel(runs_by_policy: dict) -> dict:
    """(max-min)/median relative spread per policy — the anti-drift
    number published beside every interleaved median."""
    return {
        pol: round((max(v) - min(v)) / max(v[len(v) // 2], 1e-9), 4)
        for pol, v in ((p, sorted(_run_rates(r)))
                       for p, r in runs_by_policy.items())
    }


#: interleaved tpu spread above this is a warm-up-leak advisory (VERDICT
#: r5 weak #1): warm_shapes + the untimed warm-up run should hold the
#: spread at machine noise; raw per-run rates are published either way
SPREAD_ADVISORY = 0.15


def run_config(path: str, policy: str, tag: str, overrides: dict = None,
               collect=None) -> dict:
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    over = {
        "experimental.scheduler_policy": policy,
        "general.data_directory": f"/tmp/shadow-bench-{tag}",
    }
    if overrides:
        over.update(overrides)
    cfg = load_config(str(ROOT / path), over)
    t0 = time.perf_counter()
    ctl = Controller(cfg, mirror_log=False)
    result = ctl.run()
    result["total_wall_seconds"] = time.perf_counter() - t0  # incl. build
    # warm-up = everything outside the measured round loop (controller
    # build, device attach, finalize) — published on every row (VERDICT r4
    # weak #4): headline rates are steady-state loop rates BY DESIGN, and
    # this field keeps the excluded wall visible instead of silent.
    result["warmup_wall_seconds"] = round(
        result["total_wall_seconds"] - result["wall_seconds"], 3)
    if collect is not None:
        result.update(collect(ctl))
    if result["process_errors"]:
        log(f"WARNING {tag}: {len(result['process_errors'])} process errors")
    log(
        f"{tag}: {result['sim_sec_per_wall_sec']:.3f} sim-sec/wall-sec "
        f"({result['events']} events, {result['units_sent']} units, "
        f"{result['wall_seconds']:.2f}s loop wall, "
        f"{result['warmup_wall_seconds']:.1f}s warm-up)"
    )
    return result


def tor_client_stats(ctl) -> dict:
    """Tor latency CDFs + the fetch denominator (VERDICT r4 item #6):
    attempted/completed/failed counts and circuit-build + fetch latency
    percentiles, read from the TorClient apps after a run. The identity
    attempted = completed + failed + in-flight-at-stop holds by
    construction (every _build_circuit bumps attempted; every terminal
    path bumps exactly one of completed/failed) and is asserted."""
    import numpy as np

    clients = [p.app for h in ctl.hosts for p in h.processes
               if type(p.app).__name__ == "TorClient"]
    if not clients:
        return {}
    att = sum(c.attempted for c in clients)
    comp = sum(c.completed for c in clients)
    fail = sum(c.failed for c in clients)
    in_flight = att - comp - fail
    assert in_flight >= 0, (att, comp, fail)

    def pct(samples_ns):
        if not samples_ns:
            return None
        v = np.percentile(np.array(samples_ns, dtype=np.int64),
                          [50, 90, 99]) / 1e6
        return {"p50_ms": round(float(v[0]), 1),
                "p90_ms": round(float(v[1]), 1),
                "p99_ms": round(float(v[2]), 1)}

    fetch = [t for c in clients for t in c.completion_times]
    build = [t for c in clients for t in c.build_times]
    return {"tor_fetches": {
        "attempted": att, "completed": comp, "failed": fail,
        "in_flight_at_stop": in_flight,
        "circuit_build": pct(build), "fetch_e2e": pct(fetch),
    }}


def managed_bench(n_servers: int = 10, n_clients: int = 40,
                  nbytes: int = 100_000) -> dict:
    """Real-executable benchmark (VERDICT r2 item #4): N real C server
    binaries x M real C clients as managed processes under the preload
    shim — measures the native layer itself (spawn cost, syscall
    round-trips/sec, sim-s/wall-s) beside the pyapp configs."""
    import subprocess
    import time as _t

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    build = ROOT / "native" / "build"
    subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                   capture_output=True)
    hosts = {}
    for i in range(n_servers):
        hosts[f"srv{i}"] = {
            "network_node_id": 0, "ip_addr": f"11.0.0.{i + 1}",
            "processes": [{
                "path": str(build / "tgen_srv"),
                "args": ["8080", str(n_clients // n_servers)],
                "expected_final_state": {"exited": 0}}]}
    for i in range(n_clients):
        hosts[f"cli{i}"] = {
            "network_node_id": 1,
            "processes": [{
                "path": str(build / "tgen_cli"),
                "args": [f"11.0.0.{(i % n_servers) + 1}", "8080",
                         str(nbytes)],
                "start_time": f"{1000 + i * 37} ms",
                "expected_final_state": {"exited": 0}}]}
    doc = {
        "general": {"stop_time": "30s", "seed": 11},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 1 latency "20 ms" ]
  edge [ source 0 target 0 latency "2 ms" ]
  edge [ source 1 target 1 latency "2 ms" ]
]"""}},
        "hosts": hosts,
    }
    cfg = parse_config(doc, {
        "general.data_directory": "/tmp/shadow-bench-managed"})
    t0 = _t.perf_counter()
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    wall = _t.perf_counter() - t0
    nproc = n_servers + n_clients
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "processes": nproc,
        "sim_sec_per_wall_sec": res["sim_sec_per_wall_sec"],
        "syscalls": sysc,
        "syscalls_per_wall_sec": round(sysc / res["wall_seconds"], 1),
        "spawn_plus_run_wall_s": round(wall, 3),
        "wall_per_process_ms": round(1000 * wall / nproc, 2),
        "bytes_sent": res["bytes_sent"],
        "errors": len(res["process_errors"]),
    }
    log(f"managed_{nproc}: {out['sim_sec_per_wall_sec']:.2f} sim-s/wall-s, "
        f"{out['syscalls_per_wall_sec']:.0f} syscalls/s, "
        f"{out['wall_per_process_ms']:.1f} ms wall/process")
    return out


def managed_dense_bench(n_procs: int = 4, iters: int = 40000,
                        chunk: int = 512, tag: str = "managed_dense") -> dict:
    """Syscall-DENSE managed benchmark (VERDICT r3 item #5 / weak #4):
    each process does ``iters`` write+read round trips through an
    emulated pipe (>= 30k trapped syscalls/process), so the number is the
    steady-state shim<->worker service rate, not spawn cost. The round-3
    managed_50 figure (1,316 syscalls/s over ~19 syscalls/process) was
    spawn-dominated; this measures the path the shmem fast paths serve."""
    import subprocess
    import time as _t

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    build = ROOT / "native" / "build"
    subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                   capture_output=True)
    doc = {
        "general": {"stop_time": "60s", "seed": 3,
                    "data_directory": _fresh_dir(f"/tmp/shadow-bench-{tag}")},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 0 latency "2 ms" ]
]"""}},
        "hosts": {
            f"box{i}": {"network_node_id": 0, "processes": [
                {"path": str(build / "pump"),
                 "args": [str(iters), str(chunk)],
                 "expected_final_state": {"exited": 0}}]}
            for i in range(n_procs)
        },
    }
    cfg = parse_config(doc, {})
    t0 = _t.perf_counter()
    res = Controller(cfg, mirror_log=False).run()
    wall = _t.perf_counter() - t0
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "processes": n_procs,
        # each serviced syscall is one shim<->worker round trip; a pump
        # iteration is a write + a read = two of them
        "syscall_round_trips_per_process": 2 * iters,
        "syscalls": sysc,
        "syscalls_per_wall_sec": round(sysc / wall, 1),
        "wall_s": round(wall, 3),
        "errors": len(res["process_errors"]),
    }
    log(f"{tag}: {sysc} syscalls / {wall:.2f}s = "
        f"{out['syscalls_per_wall_sec']:.0f}/s steady-state")
    return out


def _count_curl_ok(data_dir: str, n_clients: int, nbytes: int) -> int:
    """Count validated transfers (code=200 + exact byte count) across the
    curl clients' captured stdout. Shared by both real-binary benches."""
    from pathlib import Path as _P

    ok = 0
    for i in range(n_clients):
        out = _P(f"{data_dir}/hosts/cli{i}/curl.0.stdout")
        if out.exists():
            ok += out.read_text().count(f"code=200 bytes={nbytes}")
    return ok


def _fresh_dir(path: str) -> str:
    """Remove-and-return a bench data directory: transfer validation
    counts stdout lines, so stale files from a previous run must not be
    able to satisfy the assertion."""
    import shutil

    shutil.rmtree(path, ignore_errors=True)
    return path


def managed_dense_contended(n_procs: int = 100, iters: int = 4000,
                            chunk: int = 512) -> dict:
    """The contended variant (VERDICT r4 weak #7): 100 concurrent
    managed processes pumping simultaneously, so the number includes
    worker-loop scheduling across many live guests, not just the
    per-round-trip floor the 4-process row measures."""
    return managed_dense_bench(n_procs=n_procs, iters=iters, chunk=chunk,
                               tag="managed_dense_contended")


def real_binary_bench(n_servers: int = 3, n_clients: int = 12,
                      nbytes: int = 400_000) -> dict:
    """Real OFF-THE-SHELF binaries as the workload (VERDICT r3 item #9):
    unmodified CPython http.server instances serve a data file to
    unmodified distro curl clients over the simulated network — the
    whole dynamic-linking / sockets / selectors / file-IO surface of two
    real programs under the shim, validated per run (curl must exit 0
    with the exact byte count; servers must still be running)."""
    import sys as _sys
    import time as _t
    from pathlib import Path as _P

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    if not _P("/usr/bin/curl").exists():
        return {"skipped": "no /usr/bin/curl"}
    docroot = _P("/tmp/shadow-bench-docroot")
    docroot.mkdir(exist_ok=True)
    (docroot / "data.bin").write_bytes(b"x" * nbytes)
    hosts = {}
    for i in range(n_servers):
        hosts[f"web{i}"] = {
            "network_node_id": 0, "ip_addr": f"11.0.0.{i + 1}",
            "processes": [{
                "path": _sys.executable,
                "args": ["-u", "-m", "http.server", "--directory",
                         str(docroot), "--bind", "0.0.0.0", "8080"],
                "expected_final_state": "running"}]}
    for i in range(n_clients):
        url = f"http://11.0.0.{(i % n_servers) + 1}:8080/data.bin"
        hosts[f"cli{i}"] = {
            "network_node_id": 1,
            "processes": [{
                "path": "/usr/bin/curl",
                "args": ["-s", "-o", "/dev/null", "-w",
                         "code=%{http_code} bytes=%{size_download}\\n",
                         url, url],  # two sequential fetches per client
                "start_time": f"{1500 + i * 211} ms",
                "expected_final_state": {"exited": 0}}]}
    doc = {
        "general": {"stop_time": "30s", "seed": 13},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "200 Mbit" host_bandwidth_down "200 Mbit" ]
  node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
  edge [ source 0 target 1 latency "25 ms" ]
  edge [ source 0 target 0 latency "2 ms" ]
  edge [ source 1 target 1 latency "2 ms" ]
]"""}},
        "hosts": hosts,
    }
    cfg = parse_config(doc, {
        "general.data_directory": _fresh_dir("/tmp/shadow-bench-curl")})
    t0 = _t.perf_counter()
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    wall = _t.perf_counter() - t0
    ok = _count_curl_ok("/tmp/shadow-bench-curl", n_clients, nbytes)
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "servers": f"{n_servers}x CPython http.server",
        "clients": f"{n_clients}x /usr/bin/curl (2 fetches each)",
        "transfers_ok": ok,
        "transfers_expected": 2 * n_clients,
        "sim_sec_per_wall_sec": round(res["sim_sec_per_wall_sec"], 3),
        "syscalls": sysc,
        "wall_s": round(wall, 2),
        "errors": len(res["process_errors"]),
    }
    assert ok == 2 * n_clients, (ok, res["process_errors"])
    log(f"real_curl: {ok}/{2*n_clients} transfers, "
        f"{out['sim_sec_per_wall_sec']} sim-s/wall-s, {sysc} syscalls")
    return out


def _device_verdict(tag: str, row: dict, device_x: float) -> bool:
    """Round-5 Weak #5: a sub-1.0 device factor or a device that never
    serviced a window must be LOUD, not buried in a JSON blob. Stamps
    and returns the ``device_engaged`` flag for the row the factor was
    actually measured on."""
    engaged = row.get("device_windows_dispatched", 0) > 0
    row["device_engaged"] = engaged
    if not engaged:
        log(f"WARNING {tag}: device_engaged=false — the tpu_batch run "
            f"serviced ZERO fused windows on the device; the numpy/C twin "
            f"carried the whole run (this is NOT a TPU result)")
    verdict = "WIN" if device_x > 1.0 else (
        "WASH" if device_x >= 0.99 else "LOSS")
    log(f"device is a net {verdict} on {tag}: device_x={device_x} "
        f"(windows={row.get('device_windows_dispatched', 0)}, "
        f"spec_hits={row.get('device', {}).get('spec_hits', 0)})")
    return engaged


def ablation(path: str, tag: str, base: dict, full: dict,
             reps: int = 1, full_rates: list = None) -> dict:
    """Per-config headline decomposition (VERDICT r4 item #1): two extra
    rows isolate what each ingredient of the tpu_batch policy buys —

      tpu_columnar_python_cpu: columnar plane, no C engine, no device
      tpu_columnar_c_cpu:      columnar plane + C engine, no device

    so the published ratio factors as
      total = architecture (columnar-python / per-unit-python)
            x c_engine     (columnar-C / columnar-python)
            x device       (full tpu_batch / columnar-C)
    All rows are asserted result-identical; only wall time moves.

    ``reps`` > 1 measures the ablation rows with the same interleaved
    median-of-N discipline as the headline (shared-machine noise drifts
    on the scale of one run; a single-run device_x is noise-dominated
    exactly where the factor matters). ``full_rates`` carries the
    headline row's raw rates so the published factors' provenance is
    recomputable."""
    cs, ps, fs = [], [], []
    for i in range(reps):
        cs.append(run_config(path, "tpu_batch", f"{tag}-ccpu",
                             {"experimental.tpu_device_floor": -1}))
        # device_x's two sides must share the SAME noise window: a fresh
        # full-path rep rides next to each device-off rep (the headline
        # full rows were measured minutes earlier against the
        # thread_per_core baseline — machine drift between those windows
        # lands straight in the factor otherwise)
        if reps > 1:
            fs.append(run_config(path, "tpu_batch", f"{tag}-devx"))
        ps.append(run_config(path, "tpu_batch", f"{tag}-pycpu",
                             {"experimental.tpu_device_floor": -1,
                              "experimental.native_colcore": False}))

    c_cpu, py_cpu = _median_run(cs), _median_run(ps)
    full_dev = _median_run(fs) if fs else full
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert c_cpu[k] == full[k] and py_cpu[k] == full[k], (tag, k)
        assert full_dev[k] == full[k], (tag, k)

    def x(a, b):
        return round(a["sim_sec_per_wall_sec"] / b["sim_sec_per_wall_sec"], 3)

    out = {
        "tpu_columnar_python_cpu": py_cpu,
        "tpu_columnar_c_cpu": c_cpu,
        "factors": {
            "architecture_x": x(py_cpu, base),
            "c_engine_x": x(c_cpu, py_cpu),
            "device_x": x(full_dev, c_cpu),
            "total_x": x(full, base),
        },
    }
    if reps > 1:
        out["ablation_raw_rates"] = {
            "tpu_columnar_c_cpu": [
                round(r["sim_sec_per_wall_sec"], 3) for r in cs],
            "tpu_batch_devx": [
                round(r["sim_sec_per_wall_sec"], 3) for r in fs],
            "tpu_columnar_python_cpu": [
                round(r["sim_sec_per_wall_sec"], 3) for r in ps],
            "tpu_batch_headline": full_rates or [],
            "aggregation": f"median-of-{reps}, interleaved; device_x = "
                           f"median(tpu_batch_devx)/median(c_cpu), "
                           f"same-window pairs",
        }
    out["device_engaged"] = _device_verdict(
        tag, full_dev if fs else full, out["factors"]["device_x"])
    return out


def _shim_audit_table(ctl, counters, top_n: int = 10) -> dict:
    """Per-syscall-number audit of managed-process servicing: where did
    the round trips go, and what completed in-shim. Slow counts come
    from the controller-scoped census (never in fingerprints); fast
    counts from the per-class shim_fast_* counters."""
    from gen_bpf import SYS as _SYS

    names = {v: k for k, v in _SYS.items()}
    slow = getattr(ctl, "_shim_slow_nrs", {})
    top = sorted(slow.items(), key=lambda kv: (-kv[1], kv[0]))[:top_n]
    fast_classes = {
        k.replace("shim_fast_", ""): v for k, v in counters.items()
        if k.startswith("shim_fast_") and k != "shim_fast_syscalls"}
    total = counters.get("syscalls", 0)
    fast = counters.get("shim_fast_syscalls", 0)
    return {
        "syscalls_total": total,
        "in_shim": fast,
        "fast_ratio": round(fast / total, 3) if total else 0.0,
        "in_shim_by_class": fast_classes,
        "worker_round_trips_top": [
            {"nr": nr, "name": names.get(nr, f"sys_{nr}"), "count": k}
            for nr, k in top],
    }


def _curl_1k_doc(n_servers: int, n_clients: int, fetches: int,
                 nbytes: int) -> dict:
    """The real_curl_1k workload document — shared with
    managed_ckpt_overhead, which A/Bs guest journaling on the exact
    workload whose rate the headline real-binary row publishes."""
    import sys as _sys
    from pathlib import Path as _P

    import numpy as np

    assert n_servers <= 254, "server ips are drawn from one /24"
    _sys.path.insert(0, str(ROOT / "tools"))
    from gen_benchmarks import random_gml

    rng = np.random.default_rng(17)
    g = 64
    gml = random_gml(rng, g, min_lat_ms=5, max_lat_ms=60, max_loss=0.0,
                     bw_choices=("50 Mbit", "100 Mbit", "1 Gbit"))
    docroot = _P("/tmp/shadow-bench-docroot1k")
    docroot.mkdir(exist_ok=True)
    (docroot / "data.bin").write_bytes(b"x" * nbytes)
    hosts = {}
    for i in range(n_servers):
        hosts[f"web{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "ip_addr": f"12.0.0.{i + 1}",
            "processes": [{
                "path": _sys.executable,
                "args": ["-u", "-m", "http.server", "--directory",
                         str(docroot), "--bind", "0.0.0.0", "8080"],
                "expected_final_state": "running"}]}
    for i in range(n_clients):
        urls = [f"http://12.0.0.{(i + k) % n_servers + 1}:8080/data.bin"
                for k in range(fetches)]
        hosts[f"cli{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{
                "path": "/usr/bin/curl",
                "args": (["-s", "-o", "/dev/null", "-w",
                          "code=%{http_code} bytes=%{size_download}\\n"]
                         + urls),
                "start_time": f"{2000 + i * 97} ms",
                "expected_final_state": {"exited": 0}}]}
    return {
        "general": {"stop_time": "60s", "seed": 23},
        "network": {"graph": {"type": "gml", "inline": gml}},
        "hosts": hosts,
    }


def real_curl_1k(n_servers: int = 50, n_clients: int = 200,
                 fetches: int = 5, nbytes: int = 50_000,
                 reps: int = 3) -> dict:
    """Real-binary benchmark at benchmark scale (VERDICT r4 item #5):
    ``n_servers`` unmodified CPython http.server instances serve
    ``n_clients`` unmodified distro curl clients (``fetches`` sequential
    fetches each) over a 64-node random graph — and BOTH benchmark
    policies run it, so the published ratio is architecture-honest for
    managed real-binary workloads too, not just pyapp models. Every
    transfer is validated (code=200 + exact byte count)."""
    import time as _t
    from pathlib import Path as _P

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    if not _P("/usr/bin/curl").exists():
        return {"skipped": "no /usr/bin/curl"}
    doc = _curl_1k_doc(n_servers, n_clients, fetches, nbytes)

    def run(policy, tag):
        cfg = parse_config(doc, {
            "general.data_directory": _fresh_dir(f"/tmp/shadow-bench-{tag}"),
            "experimental.scheduler_policy": policy})
        t0 = _t.perf_counter()
        ctl = Controller(cfg, mirror_log=False)
        res = ctl.run()
        wall = _t.perf_counter() - t0
        ok = _count_curl_ok(f"/tmp/shadow-bench-{tag}", n_clients, nbytes)
        row = {
            "sim_sec_per_wall_sec": round(res["sim_sec_per_wall_sec"], 3),
            "wall_seconds": round(res["wall_seconds"], 2),
            "warmup_wall_seconds": round(wall - res["wall_seconds"], 1),
            "transfers_ok": ok,
            "syscalls": res["counters"].get("syscalls", 0),
            "shim_fast_syscalls": res["counters"].get(
                "shim_fast_syscalls", 0),
            "errors": len(res["process_errors"]),
        }
        assert ok == fetches * n_clients, (
            tag, ok, res["process_errors"][:3])
        log(f"real_curl_1k[{policy}]: {ok} transfers, "
            f"{row['sim_sec_per_wall_sec']} sim-s/wall-s, "
            f"{row['wall_seconds']}s loop wall")
        return row, _shim_audit_table(ctl, res["counters"])

    # interleaved median-of-reps: tpc/tpu alternate within each rep so
    # box drift (thermal, page cache) hits both policies alike
    tpc_rows, tpu_rows = [], []
    for rep in range(reps):
        tpc_rows.append(run("thread_per_core", f"curl1k-tpc-{rep}"))
        tpu_rows.append(run("tpu_batch", f"curl1k-tpu-{rep}"))

    def med(rows):
        rates = sorted(r["sim_sec_per_wall_sec"] for r, _ in rows)
        m = rates[len(rates) // 2]
        row, audit = next((r, a) for r, a in rows
                          if r["sim_sec_per_wall_sec"] == m)
        row = dict(row)
        row["raw_rates"] = rates
        row["spread"] = round(rates[-1] - rates[0], 3)
        return row, audit

    tpc, tpc_audit = med(tpc_rows)
    tpu, tpu_audit = med(tpu_rows)
    ratio = tpu["sim_sec_per_wall_sec"] / tpc["sim_sec_per_wall_sec"]
    out = {
        "servers": f"{n_servers}x CPython http.server",
        "clients": f"{n_clients}x /usr/bin/curl ({fetches} fetches each)",
        "transfers": fetches * n_clients,
        "aggregation": f"median-of-{reps}, interleaved",
        "thread_per_core": tpc,
        "tpu_batch": tpu,
        "ratio_tpu_vs_thread_per_core": round(ratio, 2),
        "shim_audit": {"thread_per_core": tpc_audit,
                       "tpu_batch": tpu_audit},
    }
    for pol, audit in (("thread_per_core", tpc_audit),
                       ("tpu_batch", tpu_audit)):
        if audit["syscalls_total"] and audit["in_shim"] == 0:
            # the device_engaged discipline applied to the shim: a
            # managed row whose fast-path never fired is measuring the
            # round-trip plane, not the one this benchmark advertises
            out.setdefault("warnings", []).append(
                f"{pol}: shim fast-path ratio is 0 "
                f"({audit['syscalls_total']} syscalls all took worker "
                f"round trips) — fast plane disabled or broken")
            log(f"real_curl_1k WARNING: {pol} shim fast-path ratio is 0 "
                f"— every managed syscall took a worker round trip")
    log(f"real_curl_1k ratio: {ratio:.2f}x "
        f"({out['transfers']} validated transfers per side; shim fast "
        f"ratio tpu={tpu_audit['fast_ratio']}, tpc={tpc_audit['fast_ratio']})")
    return out


def managed_ckpt_overhead(n_servers: int = 50, n_clients: int = 200,
                          fetches: int = 5, nbytes: int = 50_000,
                          reps: int = 3) -> dict:
    """What does checkpointability COST a real-binary run? (Checkpoint
    format v5 row.) A/Bs the guest syscall journal — the only per-syscall
    work a v5-checkpointable run adds when it never actually snapshots —
    on the real_curl_1k workload itself, journal forced on vs forced off
    via SHADOW_TPU_GUEST_JOURNAL, interleaved median-of-reps. Then times
    one actual checkpoint->resume cycle on examples/managed_smoke.yaml:
    a reexec snapshot re-buys the prefix (O(prefix) restore by design),
    so the row publishes resume wall beside the uninterrupted wall."""
    import os as _os
    import time as _t
    from pathlib import Path as _P

    from shadow_tpu import checkpoint as _ckpt
    from shadow_tpu.config import load_config, parse_config
    from shadow_tpu.core.controller import Controller

    if not _P("/usr/bin/curl").exists():
        return {"skipped": "no /usr/bin/curl"}
    doc = _curl_1k_doc(n_servers, n_clients, fetches, nbytes)

    def run(journal, tag):
        cfg = parse_config(doc, {
            "general.data_directory": _fresh_dir(f"/tmp/shadow-bench-{tag}"),
            "experimental.scheduler_policy": "tpu_batch"})
        prev = _os.environ.get("SHADOW_TPU_GUEST_JOURNAL")
        _os.environ["SHADOW_TPU_GUEST_JOURNAL"] = "1" if journal else "0"
        try:
            ctl = Controller(cfg, mirror_log=False)
            res = ctl.run()
        finally:
            if prev is None:
                del _os.environ["SHADOW_TPU_GUEST_JOURNAL"]
            else:
                _os.environ["SHADOW_TPU_GUEST_JOURNAL"] = prev
        ok = _count_curl_ok(f"/tmp/shadow-bench-{tag}", n_clients, nbytes)
        assert ok == fetches * n_clients, (tag, ok, res["process_errors"][:3])
        oplogs = list(
            _P(f"/tmp/shadow-bench-{tag}/guest_oplogs").glob("*.jsonl"))
        assert bool(oplogs) == journal, (tag, journal, len(oplogs))
        row = {
            "sim_sec_per_wall_sec": round(res["sim_sec_per_wall_sec"], 3),
            "wall_seconds": round(res["wall_seconds"], 2),
            "transfers_ok": ok,
        }
        if journal:
            row["journal_files"] = len(oplogs)
            row["journal_bytes"] = sum(p.stat().st_size for p in oplogs)
        log(f"managed_ckpt_overhead[journal={'on' if journal else 'off'}]: "
            f"{row['sim_sec_per_wall_sec']} sim-s/wall-s, "
            f"{row['wall_seconds']}s loop wall")
        return row

    # interleaved median-of-reps, off/on alternating within each rep so
    # box drift hits both arms alike (the real_curl_1k discipline)
    off_rows, on_rows = [], []
    for rep in range(reps):
        off_rows.append(run(False, f"ckptov-off-{rep}"))
        on_rows.append(run(True, f"ckptov-on-{rep}"))

    def med(rows):
        rates = sorted(r["sim_sec_per_wall_sec"] for r in rows)
        m = rates[len(rates) // 2]
        row = dict(next(r for r in rows if r["sim_sec_per_wall_sec"] == m))
        row["raw_rates"] = rates
        row["spread"] = round(rates[-1] - rates[0], 3)
        return row

    off, on = med(off_rows), med(on_rows)
    overhead = 1.0 - on["sim_sec_per_wall_sec"] / off["sim_sec_per_wall_sec"]
    out = {
        "workload": f"real_curl_1k shape ({n_servers} http.server x "
                    f"{n_clients} curl, {fetches} fetches each)",
        "aggregation": f"median-of-{reps}, interleaved",
        "journal_off": off,
        "journal_on": on,
        "journal_overhead_rel": round(overhead, 4),
    }
    if overhead > 0.10:
        out.setdefault("warnings", []).append(
            f"guest journaling costs {overhead:.1%} of the real-binary "
            f"rate (> 10%) — the per-reply journal append is leaking into "
            f"the syscall service path")
        log(f"managed_ckpt_overhead WARNING: journaling overhead "
            f"{overhead:.1%} > 10% of the real_curl_1k rate")
    log(f"managed_ckpt_overhead: journaling costs {overhead:+.1%} "
        f"({off['sim_sec_per_wall_sec']} -> {on['sim_sec_per_wall_sec']} "
        f"sim-s/wall-s median)")

    # one real checkpoint->resume cycle: how much wall does a v5 reexec
    # restore re-buy? (managed_smoke: 300 kB tgen fetch, ~1.7 s sim)
    smoke = (ROOT / "examples" / "managed_smoke.yaml").read_text().replace(
        "native/build/", str(ROOT / "native" / "build") + "/")
    smoke_yaml = _P("/tmp/shadow-bench-ckptov-smoke.yaml")
    smoke_yaml.write_text(smoke)
    t0 = _t.perf_counter()
    Controller(load_config(str(smoke_yaml), {
        "general.data_directory": _fresh_dir(
            "/tmp/shadow-bench-ckptov-base")}), mirror_log=False).run()
    base_wall = _t.perf_counter() - t0
    src_dir = _fresh_dir("/tmp/shadow-bench-ckptov-src")
    Controller(load_config(str(smoke_yaml), {
        "general.data_directory": src_dir,
        "general.checkpoint_every": "500 ms"}), mirror_log=False).run()
    cks = sorted(_P(src_dir).glob("checkpoints/ckpt_*.ckpt"))
    assert cks, f"no checkpoints written under {src_dir}"
    t0 = _t.perf_counter()
    ctl, resume_at = _ckpt.load_checkpoint(
        cks[-1], cfg=load_config(str(smoke_yaml), {
            "general.data_directory": _fresh_dir(
                "/tmp/shadow-bench-ckptov-res")}), mirror_log=False)
    res = ctl.run(resume_at=resume_at)
    resume_wall = _t.perf_counter() - t0
    assert res["process_errors"] == [], res["process_errors"]
    hdr = _ckpt.read_header(cks[-1])
    out["resume"] = {
        "config": "examples/managed_smoke.yaml",
        "snapshot_sim_ns": int(hdr["sim_time_ns"]),
        "resume_wall_seconds": round(resume_wall, 3),
        "uninterrupted_wall_seconds": round(base_wall, 3),
        # a reexec restore re-runs the prefix, so ratio ~1 is the design
        # point; >>1 would mean restore machinery is adding real cost
        "resume_vs_uninterrupted": round(resume_wall / base_wall, 2),
    }
    log(f"managed_ckpt_overhead resume: v5 reexec restore from sim "
        f"{hdr['sim_time_ns']} ns took {resume_wall:.2f}s wall vs "
        f"{base_wall:.2f}s uninterrupted "
        f"({out['resume']['resume_vs_uninterrupted']}x)")
    return out


def managed_fidelity_audit(n_clients: int = 24,
                           nbytes: int = 100_000) -> dict:
    """Model-fidelity audit (checkpoint-PR headline row): the SAME
    topology runs the tgen protocol twice — once with the real C binaries
    (tgen_srv streaming to ring_probe under the preload shim), once with
    the Python model twins (models.tgen TGenServer/TGenClient) — and the
    row publishes both fetch-latency distributions side by side. Both
    latencies are sim-time observables, so each leg is deterministic and
    runs once: the real client self-times its fetch through the
    virtualized monotonic clock (``fetch_ns=`` on ring_probe stdout,
    t0 before connect, t1 after EOF drain), the twin records
    ``completion_times`` at the last payload byte. Client starts are
    staggered wide enough that transfers never overlap — tgen_srv
    accepts serially while the twin server is concurrent, and queueing
    skew would otherwise masquerade as protocol infidelity."""
    import re as _re
    import subprocess
    from pathlib import Path as _P

    import numpy as np

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    build = ROOT / "native" / "build"
    subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                   capture_output=True)
    import sys as _sys
    _sys.path.insert(0, str(ROOT / "tools"))
    from gen_benchmarks import random_gml

    rng = np.random.default_rng(7)
    g = 16
    gml = random_gml(rng, g, min_lat_ms=5, max_lat_ms=60, max_loss=0.0,
                     bw_choices=("50 Mbit", "100 Mbit", "1 Gbit"))
    srv_node = int(rng.integers(0, g))
    cli_nodes = [int(rng.integers(0, g)) for _ in range(n_clients)]
    starts = [f"{2000 + i * 400} ms" for i in range(n_clients)]

    def doc(real):
        if real:
            srv = {"path": str(build / "tgen_srv"),
                   "args": ["8080", str(n_clients)],
                   "expected_final_state": {"exited": 0}}
            cli = lambda i: {"path": str(build / "ring_probe"),
                             "args": ["11.0.0.1", "8080", str(nbytes)],
                             "start_time": starts[i],
                             "expected_final_state": {"exited": 0}}
        else:
            srv = {"path": "pyapp:shadow_tpu.models.tgen:TGenServer",
                   "args": ["8080"]}
            cli = lambda i: {
                "path": "pyapp:shadow_tpu.models.tgen:TGenClient",
                "args": [str(nbytes), "1", "serial", "8080", "srv"],
                "start_time": starts[i],
                "expected_final_state": {"exited": 0}}
        hosts = {"srv": {"network_node_id": srv_node,
                         "ip_addr": "11.0.0.1", "processes": [srv]}}
        for i in range(n_clients):
            hosts[f"cli{i}"] = {"network_node_id": cli_nodes[i],
                                "processes": [cli(i)]}
        return {
            "general": {"stop_time": f"{4 + (n_clients * 400) // 1000}s",
                        "seed": 7},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": hosts,
        }

    # real leg: every client prints its self-timed fetch_ns
    d = _fresh_dir("/tmp/shadow-bench-fidelity-real")
    cfg = parse_config(doc(True), {"general.data_directory": d})
    res = Controller(cfg, mirror_log=False).run()
    assert res["process_errors"] == [], res["process_errors"][:3]
    real_ns = []
    for i in range(n_clients):
        out = _P(f"{d}/hosts/cli{i}/ring_probe.0.stdout").read_text()
        assert f"bytes={nbytes}" in out and "eof=1" in out, (i, out)
        real_ns.append(int(_re.search(r"fetch_ns=(\d+)", out).group(1)))

    # model-twin leg: same nodes, same stagger, same byte counts
    d = _fresh_dir("/tmp/shadow-bench-fidelity-model")
    cfg = parse_config(doc(False), {"general.data_directory": d})
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    assert res["process_errors"] == [], res["process_errors"][:3]
    model_ns = []
    for h in ctl.hosts:
        if h.name.startswith("cli"):
            (proc,) = h.processes
            (elapsed,) = proc.app.completion_times
            model_ns.append(int(elapsed))
    assert len(model_ns) == n_clients, len(model_ns)

    def pcts(ns):
        s = sorted(ns)
        p = lambda q: round(s[min(len(s) - 1, int(q * len(s)))] / 1e6, 3)
        return {"p50_ms": p(0.50), "p90_ms": p(0.90), "p99_ms": p(0.99),
                "min_ms": round(s[0] / 1e6, 3),
                "max_ms": round(s[-1] / 1e6, 3)}

    # clients pair 1:1 across legs (same index = same graph node, same
    # start, same byte count), so the per-pair error IS the model gap
    rel = sorted(r / m - 1.0 for r, m in zip(real_ns, model_ns))
    out = {
        "workload": f"{n_clients} single-fetch clients x {nbytes} B, "
                    f"one serial tgen server, {g}-node random graph",
        "real_binaries": pcts(real_ns),
        "model_twin": pcts(model_ns),
        "paired_rel_error": {
            "median": round(rel[len(rel) // 2], 4),
            "worst": round(max(rel, key=abs), 4),
        },
        "semantics": "real = ring_probe connect->EOF self-timed via the "
                     "virtualized clock; twin = TGenClient connect->last "
                     "payload byte (completion_times)",
    }
    if abs(out["paired_rel_error"]["median"]) > 0.25:
        out.setdefault("warnings", []).append(
            f"median real-vs-twin fetch latency gap "
            f"{out['paired_rel_error']['median']:+.1%} (> 25%) — the "
            f"Python twin is drifting from what the real protocol does "
            f"on this transport")
        log(f"managed_fidelity_audit WARNING: median real-vs-twin gap "
            f"{out['paired_rel_error']['median']:+.1%} > 25%")
    log(f"managed_fidelity_audit: real p50 "
        f"{out['real_binaries']['p50_ms']} ms vs twin p50 "
        f"{out['model_twin']['p50_ms']} ms "
        f"(median paired gap {out['paired_rel_error']['median']:+.1%}, "
        f"{n_clients} paired fetches)")
    return out


def _tor_doc(n_relays: int, n_clients: int, stop_s: int,
             fetch: str = "20 kB") -> dict:
    """Config #5 generator (BASELINE.md): onion-routing at tornettools
    shape — TorRelay/TorExit relays, TGen web servers, TorClients
    building 3-hop circuits and fetching through them, on a 64-node
    random graph. Deterministic from the fixed seed."""
    import sys as _sys

    import numpy as np

    _sys.path.insert(0, str(ROOT / "tools"))
    from gen_benchmarks import random_gml

    rng = np.random.default_rng(42)
    g = 64
    gml = random_gml(rng, g, min_lat_ms=10, max_lat_ms=120, max_loss=0.002,
                     bw_choices=("50 Mbit", "100 Mbit", "1 Gbit"))
    hosts = {}
    n_exits = max(1, n_relays // 8)  # exits FIRST: clients draw their last hop
    # from relay0..relay{n_exits-1} (TorClient's n_exits arg)
    for i in range(n_relays):
        cls = "TorExit" if i < n_exits else "TorRelay"
        hosts[f"relay{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{"path": f"pyapp:shadow_tpu.models.tor:{cls}",
                           "args": ["9001"]}]}
    for i in range(20):
        hosts[f"web{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{"path": "pyapp:shadow_tpu.models.tgen:TGenServer",
                           "args": ["80"]}]}
    per = n_clients // g
    for i in range(g):
        q = per + (n_clients - per * g if i == g - 1 else 0)
        hosts[f"u{i}_"] = {
            "network_node_id": i, "quantity": q,
            "processes": [{"path": "pyapp:shadow_tpu.models.tor:TorClient",
                           "args": [str(n_relays), "9001", f"web{i % 20}",
                                    "80", fetch, "1", str(n_exits)],
                           "start_time": f"{2000 + i * 150} ms"}]}
    return {"general": {"stop_time": f"{stop_s}s", "seed": 6},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": hosts}


def _tor_churned_doc(stop_s: int = 8) -> dict:
    """The tor 1/10-scale config under production-realistic adversity:
    a healing bipartite partition, a degrade window, and seeded client
    churn, with ONE mid-run checkpoint (4 sim-s cadence on an 8 sim-s
    run). One snapshot per run is the hourly-equivalent discipline at
    this scale: a snapshot's wall is plane-independent pickling of the
    same object graph (~1.3 s for 10.7k hosts, published as
    phase_wall.checkpoint), so at production cadence — one per hour of
    wall — it amortizes to noise, while a sim-time-scaled cadence at
    bench scale would bill the fast plane 3 orders of magnitude more
    snapshot wall per sim-second than the full-scale run ever pays.
    Deterministic from the fixed seeds like the base doc."""
    doc = _tor_doc(700, 10_000, stop_s)
    doc["faults"] = {
        "events": [
            {"time": "2500 ms", "kind": "link_down",
             "src_nodes": [0, 1, 2, 3], "dst_nodes": [],
             "duration": "1200 ms"},
            {"time": "4500 ms", "kind": "link_degrade",
             "src_nodes": [4, 5, 6, 7], "dst_nodes": [],
             "latency_factor": 1.5, "loss_add": 0.01,
             "bandwidth_scale": 0.8, "duration": "2s"},
        ],
        "churn": [
            {"hosts": ["u1_*", "u2_*", "u3_*"], "mean_uptime": "5s",
             "mean_downtime": "1s", "start_time": "2s"},
        ],
    }
    doc["general"]["checkpoint_every"] = "4s"
    return doc


def tor_churned_ckpt(base_ratio=None) -> dict:
    """The fast-AND-robust row (PR 6 acceptance): the tor 1/10-scale
    config with faults + periodic checkpoints enabled and the C engine
    ON — the production-realistic scenario that previously force-
    disabled the C plane and ran at ~1/7th speed. Interleaved
    median-of-3 subprocess pairs like the base small-scale rows; the
    published robustness tax is the churned ratio relative to the clean
    12.89x row, with the acceptance bar at 15%."""
    import os
    import subprocess
    import time as _t

    import yaml

    import shutil

    doc = _tor_churned_doc(8)
    ypath = "/tmp/shadow-bench-tor10k-churn.yaml"
    with open(ypath, "w") as f:
        yaml.safe_dump(doc, f, default_style=None)

    def sub(policy, tag):
        # a stale data dir would leave old-cadence checkpoints behind and
        # corrupt the checkpoints_written evidence below
        shutil.rmtree(f"/tmp/shadow-bench-{tag}", ignore_errors=True)
        t0 = _t.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", ypath,
             "--scheduler-policy", policy,
             "--data-directory", f"/tmp/shadow-bench-{tag}",
             "--json-summary", "--quiet"],
            capture_output=True, text=True, timeout=3600,
            env=dict(os.environ), cwd=str(ROOT))
        assert r.returncode == 0, (tag, r.stderr[-500:])
        s = json.loads(r.stdout)
        s["subprocess_wall_s"] = round(_t.perf_counter() - t0, 1)
        return s

    N = 3
    reps = {"tpu_batch": [], "thread_per_core": []}
    for i in range(N):
        for pol, tag in (("tpu_batch", "tpu"), ("thread_per_core", "tpc")):
            reps[pol].append(sub(pol, f"tor10kck-{tag}{i}"))
    ref = reps["tpu_batch"][0]
    for pol, rs in reps.items():
        for s in rs:
            for k in ("events", "units_sent", "units_dropped",
                      "bytes_sent", "rounds", "counters",
                      "fault_transitions_applied", "units_blackholed"):
                if pol == "tpu_batch":
                    assert s[k] == ref[k], \
                        f"churned tor determinism: {k} diverged"
                elif k not in ("rounds", "counters"):
                    assert s[k] == ref[k], \
                        f"churned tor policy divergence on {k}"
    # the adversity actually ran, under the C engine, with checkpoints
    assert ref["counters"].get("host_crashes", 0) > 0
    assert ref["units_blackholed"] > 0
    ckpts = sorted(Path("/tmp/shadow-bench-tor10kck-tpu0/checkpoints")
                   .glob("*.ckpt"))
    assert ckpts, "churned tor run wrote no checkpoints"
    sa = _median_run(reps["tpu_batch"])
    sc = _median_run(reps["thread_per_core"])
    ratio = sa["sim_sec_per_wall_sec"] / sc["sim_sec_per_wall_sec"]
    spread = _spread_rel(reps)

    # the snapshot wall is plane-independent (same pickled graph either
    # way), so decompose the ratio: as-measured (snapshot included) and
    # loop-only (snapshot wall excluded on both sides) — the latter is
    # what the C plane is responsible for under adversity
    def _excl_ck(s):
        w = s["wall_seconds"] - s["phase_wall"].get("checkpoint", 0.0)
        return s["sim_seconds"] / w if w > 0 else 0.0

    ratio_loop = _excl_ck(sa) / _excl_ck(sc) if _excl_ck(sc) else 0.0
    out = {
        pol: {
            "sim_sec_per_wall_sec": round(s["sim_sec_per_wall_sec"], 3),
            "events": s["events"],
            "wall_seconds": round(s["wall_seconds"], 2),
            "max_rss_mb": s["max_rss_mb"],
            "phase_wall": s.get("phase_wall"),
            "raw_rates": _run_rates(reps[pol]),
            "spread_rel": spread[pol],
        }
        for pol, s in (("tpu_batch", sa), ("thread_per_core", sc))
    }
    out.update({
        "ratio_tpu_vs_thread_per_core": round(ratio, 2),
        "ratio_excl_checkpoint_wall": round(ratio_loop, 2),
        "checkpoint_wall_seconds": {
            pol: round(s["phase_wall"].get("checkpoint", 0.0), 3)
            for pol, s in (("tpu_batch", sa), ("thread_per_core", sc))},
        "fault_evidence": {
            "fault_transitions_applied": ref["fault_transitions_applied"],
            "host_crashes": ref["counters"].get("host_crashes"),
            "host_boots": ref["counters"].get("host_boots"),
            "units_blackholed": ref["units_blackholed"],
            "units_teardown_dropped": ref["counters"].get(
                "units_teardown_dropped"),
            "checkpoints_written": len(ckpts),
        },
        "aggregation": f"median-of-{N}, interleaved subprocess pairs; "
                       f"ratio = median/median",
        "note": "tor 1/10 scale under partition + degrade + client churn "
                "with one mid-run checkpoint, C engine ON (the scenario "
                "that force-disabled it before PR 6). Snapshot wall is "
                "plane-independent pickling (phase_wall.checkpoint, same "
                "seconds either plane), so the published tax decomposes: "
                "ratio (snapshot included, at this scale's one-per-run "
                "cadence) vs ratio_excl_checkpoint_wall (the adversity "
                "cost the C plane answers for).",
    })
    if base_ratio:
        out["base_ratio_clean"] = base_ratio
        out["robustness_tax_rel"] = round(1 - ratio / base_ratio, 3)
    log(f"tor_1_10_churned_ckpt: tpu {sa['sim_sec_per_wall_sec']:.3f} vs "
        f"tpc {sc['sim_sec_per_wall_sec']:.3f} = {ratio:.2f}x "
        f"({ratio_loop:.2f}x excl. plane-independent snapshot wall; "
        f"clean base {base_ratio}; spread {spread})")
    return out


def tor_400_sweep(n_seeds: int = 10, jobs: int = 2) -> dict:
    """Fleet-mode row (ROADMAP item 5 acceptance): the 10-seed tor_400
    sweep in ONE command vs standalone single runs, interleaved.

    Protocol: 3x (standalone single, 10-seed sweep at jobs=2 with the
    shared draw service) interleaved, plus one no-service sweep (the
    shared-attach ablation) and one jobs=1 sweep (the jobs-efficiency
    leg — on this box's 2 HT vCPUs, packing gains little; amortization
    is the win). Identity evidence rides along at zero extra cost: the
    service and no-service sweeps must agree on every per-seed tree
    hash, and the base seed's in-sweep tree must equal the standalone
    run's tree."""
    import os
    import shutil
    import subprocess
    import time as _t

    from shadow_tpu import fleet as _fleet

    cfg = "examples/tor_400relay.yaml"
    env = dict(os.environ)

    def single(tag):
        d = f"/tmp/shadow-bench-sw-single-{tag}"
        shutil.rmtree(d, ignore_errors=True)
        t0 = _t.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", cfg, "--quiet",
             "--data-directory", d, "--scheduler-policy", "tpu_batch",
             "--sample-every", "10s"],
            capture_output=True, text=True, timeout=3600, env=env,
            cwd=str(ROOT))
        assert r.returncode == 0, (tag, r.stderr[-500:])
        return round(_t.perf_counter() - t0, 2), d

    def sweep(tag, extra):
        d = f"/tmp/shadow-bench-sw-{tag}"
        shutil.rmtree(d, ignore_errors=True)
        t0 = _t.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu.fleet", "sweep", cfg,
             "--seeds", str(n_seeds), "--sweep-dir", d,
             "--set", "experimental.scheduler_policy=tpu_batch",
             "--quiet", "--json"] + extra,
            capture_output=True, text=True, timeout=3600, env=env,
            cwd=str(ROOT))
        assert r.returncode == 0, (tag, r.stderr[-800:])
        s = json.loads(r.stdout)
        assert len(s["completed"]) == n_seeds, (tag, s["failed"])
        return round(_t.perf_counter() - t0, 2), d, s

    # every leg rides the same interleaved median-of-3 discipline: this
    # box's wall noise is +-20% on runs of this length, so a single-shot
    # ablation leg would publish noise as a finding
    singles = []
    sweeps = []
    nosvcs = []
    j1s = []
    for i in range(3):
        singles.append(single(f"i{i}"))
        sweeps.append(sweep(f"svc{i}", ["--jobs", str(jobs)]))
        nosvcs.append(sweep(
            f"nosvc{i}", ["--jobs", str(jobs), "--no-device-service"]))
        j1s.append(sweep(f"j1-{i}", ["--jobs", "1"]))
        log(f"tor_400_sweep rep {i}: single {singles[-1][0]}s, "
            f"sweep {sweeps[-1][0]}s, no-service {nosvcs[-1][0]}s, "
            f"jobs=1 {j1s[-1][0]}s")

    def _med(runs):
        return sorted(w for w, *_ in runs)[len(runs) // 2]

    med_single = _med(singles)
    med_sweep = _med(sweeps)
    nosvc_wall = _med(nosvcs)
    j1_wall = _med(j1s)
    nosvc_dir = nosvcs[0][1]
    med_i = [w for w, _d, _s in sweeps].index(med_sweep)
    med_sum = sweeps[med_i][2]

    # identity evidence: per-seed trees agree between the shared-service
    # and local-attach sweeps (device routing can never change results),
    # and the base seed in-sweep equals the standalone run
    svc_dir = sweeps[0][1]
    base_seed = med_sum["seeds"][0]
    for seed in med_sum["seeds"]:
        a = _fleet.output_tree_digest(_fleet.seed_dir(svc_dir, seed))
        b = _fleet.output_tree_digest(_fleet.seed_dir(nosvc_dir, seed))
        assert a == b, f"sweep seed {seed}: svc vs no-svc tree diverged"
    solo_tree = _fleet.output_tree_digest(singles[0][1])
    fleet_tree = _fleet.output_tree_digest(
        _fleet.seed_dir(svc_dir, base_seed))
    assert solo_tree == fleet_tree, \
        "base seed: in-sweep tree != standalone tree"

    # the statistics the sweep exists for
    flows = med_sum["flows"]
    assert flows, "sweep produced no flow groups"
    k0 = sorted(flows)[0]
    assert flows[k0]["ci95"]["p50_ms"]["n"] == n_seeds

    ratio = med_sweep / med_single
    serial_est = round(n_seeds * med_single, 1)
    out = {
        "n_seeds": n_seeds,
        "jobs": jobs,
        "single_run_wall_s": {"median": med_single,
                              "raw": [w for w, _ in singles]},
        "sweep_wall_s": {"median": med_sweep,
                         "raw": [w for w, _d, _s in sweeps]},
        "sweep_wall_no_service_s": {
            "median": nosvc_wall, "raw": [w for w, *_ in nosvcs]},
        "sweep_wall_jobs1_s": {
            "median": j1_wall, "raw": [w for w, *_ in j1s]},
        "ratio_sweep_vs_single": round(ratio, 2),
        "target_3x_single": round(3 * med_single, 1),
        "target_3x_met": bool(med_sweep < 3 * med_single),
        "serial_10x_estimate_s": serial_est,
        "speedup_vs_serial": round(serial_est / med_sweep, 2),
        "marginal_wall_per_seed_s": round(
            (med_sweep - med_single) / (n_seeds - 1), 2),
        "shared_attach_savings_rel": round(
            1 - med_sweep / nosvc_wall, 3),
        "jobs_efficiency_note": (
            f"jobs=1 {j1_wall}s vs jobs={jobs} {med_sweep}s: this box's "
            f"2 vCPUs are HT siblings (box_parallel_scaling_2proc in "
            f"tor_100k row), so packing adds little over the "
            f"amortization wins (persistent workers, cached config "
            f"parse, ONE shared device attach)"),
        "per_seed_wall_s": med_sum["per_seed_wall_seconds"],
        "draw_service": med_sum.get("draw_service"),
        "identity": {
            "svc_vs_nosvc_trees": "all seeds byte-identical",
            "base_seed_vs_standalone": "byte-identical",
            "full_per-seed standalone matrix":
                "tests/test_fleet.py + ci.sh fleet gate",
        },
        "flow_ci_sample": {k0: flows[k0]["ci95"]},
        "aggregation": "median-of-3 interleaved (single, sweep) "
                       "subprocess pairs; ablations single-shot",
        "note": (
            "The sweep amortizes the single-run fixed wall "
            f"(~{round(med_single - (med_sweep - med_single) / (n_seeds - 1), 1)}s "
            f"of imports/attach/build per standalone run) down to "
            f"~{round((med_sweep - med_single) / (n_seeds - 1), 2)}s "
            f"marginal per seed. The <3x-single target needs ~2x real "
            f"parallel capacity on top of that; this container's two "
            f"HT-sibling vCPUs provide ~1.1-1.3x (published probe), "
            f"which is also why sim_shards=2 is throughput-parity "
            f"here. On a box with two real cores the same command "
            f"meets the target arithmetically: "
            f"{n_seeds}x{round((med_sweep - med_single) / (n_seeds - 1), 2)}s"
            f"/2 + startup << 3x single."),
    }
    log(f"tor_400_sweep_{n_seeds}seed: sweep {med_sweep}s vs single "
        f"{med_single}s = {ratio:.2f}x single ({out['speedup_vs_serial']}x "
        f"faster than {n_seeds}x serial; 3x target "
        f"{'MET' if out['target_3x_met'] else 'MISSED — 2-HT-vCPU box'}; "
        f"shared attach saves {out['shared_attach_savings_rel']:.0%} vs "
        f"per-member attach)")
    return out


#: per-shard busy-wall imbalance (max/min) above which the sharded row
#: carries a straggler advisory: id-modulo placement assumes statistically
#: uniform load, and a config that concentrates hot hosts on one shard
#: shows up here first
STRAGGLER_ADVISORY = 1.5


def _shard_busy_walls(summary: dict) -> list:
    """Per-shard busy wall (phase_wall sum excluding the exchange and
    barrier-sync walls — waiting on peers is the SYMPTOM of imbalance,
    not the cause)."""
    out = []
    for s in summary.get("shards", {}).get("per_shard", []):
        pw = s.get("phase_wall", {})
        out.append(sum(v for k, v in pw.items()
                       if k not in ("exchange", "sync")))
    return out


def tor_sharded(shard_counts=(1, 2, 4), stop_s: int = 8) -> dict:
    """The scale-out row (sim_shards PR acceptance): the tor 1/10-scale
    config at shards=1/2/4, interleaved median-of-3 subprocess rows like
    the other tor small-scale rows. shards=1 is the unchanged
    single-process controller; every repetition at every shard count
    must agree on all result fields (the byte-identity contract,
    summary-level here — tests/test_shards.py carries the stream-level
    gates). Publishes per-shard phase_wall (including the exchange wall)
    and a straggler advisory when the busy-wall imbalance exceeds
    {STRAGGLER_ADVISORY}x."""
    import os
    import subprocess
    import time as _t

    import yaml

    doc = _tor_doc(700, 10_000, stop_s)
    ypath = "/tmp/shadow-bench-tor10k-sharded.yaml"
    with open(ypath, "w") as f:
        yaml.safe_dump(doc, f, default_style=None)

    def sub(shards, tag):
        t0 = _t.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", ypath,
             "--scheduler-policy", "tpu_batch",
             "--shards", str(shards),
             "--data-directory", f"/tmp/shadow-bench-{tag}",
             "--json-summary", "--quiet"],
            capture_output=True, text=True, timeout=3600,
            env=dict(os.environ), cwd=str(ROOT))
        assert r.returncode == 0, (tag, r.stderr[-500:])
        s = json.loads(r.stdout)
        s["subprocess_wall_s"] = round(_t.perf_counter() - t0, 1)
        return s

    N = 3
    reps = {n: [] for n in shard_counts}
    for i in range(N):
        for n in shard_counts:
            reps[n].append(sub(n, f"torshard-{n}-{i}"))
    ref = reps[shard_counts[0]][0]
    for n, rs in reps.items():
        for s in rs:
            for k in ("events", "units_sent", "units_dropped",
                      "bytes_sent", "rounds", "counters"):
                assert s[k] == ref[k], \
                    f"sharded tor determinism: {k} diverged at shards={n}"
    log(f"tor_sharded determinism OK: shards={list(shard_counts)} x {N} "
        f"reps agree ({ref['events']} events)")
    out = {}
    base_rate = None
    for n in shard_counts:
        s = _median_run(reps[n])
        busy = _shard_busy_walls(s)
        row = {
            "sim_sec_per_wall_sec": round(s["sim_sec_per_wall_sec"], 3),
            "wall_seconds": round(s["wall_seconds"], 2),
            "max_rss_mb": s["max_rss_mb"],
            "raw_rates": _run_rates(reps[n]),
            "spread_rel": _spread_rel({n: reps[n]})[n],
            "phase_wall_exchange_per_shard": [
                ps["phase_wall"].get("exchange")
                for ps in s.get("shards", {}).get("per_shard", [])],
            "phase_wall_sync": s["phase_wall"].get("sync"),
        }
        if busy and min(busy) > 0:
            imb = max(busy) / min(busy)
            row["shard_busy_wall_imbalance"] = round(imb, 2)
            if imb > STRAGGLER_ADVISORY:
                row["straggler_advisory"] = (
                    f"max/min shard busy wall {imb:.2f}x > "
                    f"{STRAGGLER_ADVISORY}x — id-modulo placement is "
                    f"unbalanced for this config")
                log(f"WARNING tor_sharded shards={n}: "
                    f"{row['straggler_advisory']}")
        if base_rate is None:
            base_rate = s["sim_sec_per_wall_sec"]
        else:
            row["speedup_vs_shards_1"] = round(
                s["sim_sec_per_wall_sec"] / base_rate, 2)
        out[f"shards_{n}"] = row
    out["aggregation"] = (f"median-of-{N}, interleaved subprocess rows "
                          f"across shard counts; all counts "
                          f"result-identical (asserted)")
    out["note"] = ("tor 1/10 scale, tpu_batch + C engine per shard; "
                   "shards=1 is the unchanged single-process controller. "
                   "The peer-to-peer edge barrier + row exchange is the "
                   "published scale-out overhead "
                   "(phase_wall_exchange_per_shard / coordinate).")
    log("tor_sharded: " + ", ".join(
        f"shards={n} {out[f'shards_{n}']['sim_sec_per_wall_sec']}"
        for n in shard_counts))
    return out


def _parallel_scaling_probe() -> float:
    """How much real CPU parallelism this box gives two processes: run
    one CPU-bound task serial, then two in parallel, and report
    2*serial/parallel. 2.0 = two real cores; ~1.3 = shared execution
    resources (the ceiling any 2-shard speedup can reach here)."""
    import multiprocessing as mp
    import time as _t

    n = 20_000_000
    t0 = _t.perf_counter()
    _burn(n)
    serial = _t.perf_counter() - t0
    ctx = mp.get_context("spawn")
    t0 = _t.perf_counter()
    ps = [ctx.Process(target=_burn, args=(n,)) for _ in range(2)]
    for p in ps:
        p.start()
    for p in ps:
        p.join()
    par = _t.perf_counter() - t0
    return round(2 * serial / par, 2)


def _burn(n: int) -> int:
    s = 0
    for i in range(n):
        s += i * i
    return s


def tor_100k_sharded(stop_s: int = 15, shards: int = 2,
                     reps: int = 2) -> dict:
    """Full-scale config #5 through the shard plane, measured HONESTLY:
    interleaved (single-process, sharded) pairs under today's load, both
    raw rate lists published, plus a measured parallel-scaling probe of
    the box — the ceiling any local sharded speedup can reach. The small
    twin carries the byte-identity gates; this row answers 'does
    partitioning pay on THIS hardware at THIS scale'."""
    import os
    import subprocess
    import time as _t

    import shutil

    from shadow_tpu.config import parse_config
    from shadow_tpu.parallel.shards import run_sharded

    doc = _tor_doc(7000, 100_000, stop_s)
    singles = []
    shardeds = []
    last = None
    for i in range(reps):
        # single-process leg in a subprocess (per-run RSS/allocator)
        r = subprocess.run(
            [sys.executable, "-c", f"""
import sys; sys.path.insert(0, {str(ROOT)!r})
import json
from bench import _tor_doc
from shadow_tpu.config import parse_config
from shadow_tpu.core.controller import Controller
doc = _tor_doc(7000, 100_000, {stop_s})
cfg = parse_config(doc, {{"general.data_directory":
    "/tmp/shadow-bench-tor100k-single{i}",
    "experimental.scheduler_policy": "tpu_batch"}})
r = Controller(cfg, mirror_log=False).run()
print(json.dumps([r["sim_sec_per_wall_sec"], r["events"]]))
"""], capture_output=True, text=True, timeout=3600,
            env=dict(os.environ), cwd=str(ROOT))
        assert r.returncode == 0, r.stderr[-500:]
        rate, events = json.loads(r.stdout.strip().splitlines()[-1])
        singles.append(round(rate, 4))
        tag = f"tor100k-sh{shards}-{i}"
        shutil.rmtree(f"/tmp/shadow-bench-{tag}", ignore_errors=True)
        cfg = parse_config(doc, {
            "general.data_directory": f"/tmp/shadow-bench-{tag}",
            "general.sim_shards": shards,
            "experimental.scheduler_policy": "tpu_batch"})
        rs = run_sharded(cfg, mirror_log=False)
        assert rs["events"] == events, \
            "sharded full-scale events diverged from single-process"
        shardeds.append(round(rs["sim_sec_per_wall_sec"], 4))
        last = rs
    busy = _shard_busy_walls(last)
    scaling = _parallel_scaling_probe()
    out = {
        "relays": 7000, "clients": 100_000, "sim_seconds": stop_s,
        "sim_shards": shards,
        "sim_sec_per_wall_sec": max(shardeds),
        "raw_rates_sharded": shardeds,
        "raw_rates_single_process_interleaved": singles,
        "events": last["events"], "units_sent": last["units_sent"],
        "max_rss_mb_max_shard": last["max_rss_mb"],
        "errors": len(last["process_errors"]),
        "phase_wall_per_shard": [
            ps["phase_wall"]
            for ps in last.get("shards", {}).get("per_shard", [])],
        "shard_busy_wall_imbalance": (
            round(max(busy) / min(busy), 2) if busy and min(busy) > 0
            else None),
        "box_parallel_scaling_2proc": scaling,
        "verdict": (
            "sharded BEATS the contemporaneous single-process rate"
            if max(shardeds) > max(singles) else
            f"sharded LOSES to the contemporaneous single-process rate "
            f"on this box: two parallel CPU-bound processes measure only "
            f"{scaling}x (shared execution resources), below the "
            f"break-even for the barrier+exchange overhead at this "
            f"scale; the byte-identity gates all hold, so the partition "
            f"is a correctness-proven throughput knob awaiting real "
            f"cores (or a second box)"),
        "aggregation": f"interleaved (single, sharded) x{reps}; raw "
                       f"rates published, best-of compared",
    }
    log(f"tor_100k_sharded (shards={shards}): sharded {shardeds} vs "
        f"single {singles} sim-s/wall-s (box 2-proc scaling {scaling}x)")
    return out


def tor_100k(stop_s: int = 15) -> dict:
    """BASELINE config #5 as a real bench row (VERDICT r3 item #6, r4
    item #2): 7,000 relays + 100,000 clients through the columnar plane
    + C engine. Publishes sim-s/wall-s, RSS, events, and the full fetch
    accounting (attempted/completed/failed + latency percentiles).

    The 1/10-scale twin (700 relays + 10k clients) additionally provides
    (a) the determinism gate — every tpu_batch repetition must agree on
    all result fields — and (b) the MEASURED thread_per_core denominator
    the north-star ratio is defined against (VERDICT r4 item #2: config
    #5 had no baseline side). The small rows run INTERLEAVED
    median-of-3 (tpu, tpc, tpu, tpc, ...) in subprocesses, the same
    anti-drift discipline as the headline: shared-machine noise drifts
    on the scale of one run, and per-run subprocesses keep max_rss_mb
    per-run. Each row publishes its raw rates, relative spread, and the
    median run's phase_wall budget (PR 5: the attack on the north-star
    config is measured, not guessed). The full config runs once
    in-process (the machinery is scale-invariant, so the small twin
    carries the gates)."""
    import os
    import resource
    import subprocess
    import time as _t

    import yaml

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    small = _tor_doc(700, 10_000, 8)
    ypath = "/tmp/shadow-bench-tor10k.yaml"
    with open(ypath, "w") as f:
        yaml.safe_dump(small, f, default_style=None)

    def sub(policy, tag):
        t0 = _t.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", ypath,
             "--scheduler-policy", policy,
             "--data-directory", f"/tmp/shadow-bench-{tag}",
             "--json-summary", "--quiet"],
            capture_output=True, text=True, timeout=3600,
            env=dict(os.environ), cwd=str(ROOT))
        assert r.returncode == 0, (tag, r.stderr[-500:])
        s = json.loads(r.stdout)
        s["subprocess_wall_s"] = round(_t.perf_counter() - t0, 1)
        return s

    N = 3
    reps = {"tpu_batch": [], "thread_per_core": []}
    for i in range(N):
        for pol, tag in (("tpu_batch", "tpu"), ("thread_per_core", "tpc")):
            reps[pol].append(sub(pol, f"tor10k-{tag}{i}"))
    # determinism + cross-policy gates over EVERY repetition
    ref = reps["tpu_batch"][0]
    for pol, rs in reps.items():
        for s in rs:
            for k in ("events", "units_sent", "units_dropped",
                      "bytes_sent", "rounds", "counters"):
                if pol == "tpu_batch":
                    assert s[k] == ref[k], f"tor determinism: {k} diverged"
                elif k not in ("rounds", "counters"):
                    assert s[k] == ref[k], f"tor policy divergence on {k}"
    log(f"tor_10k determinism OK across {N} tpu reps ({ref['events']} "
        f"events)")

    sa = _median_run(reps["tpu_batch"])
    sc = _median_run(reps["thread_per_core"])
    ratio = sa["sim_sec_per_wall_sec"] / sc["sim_sec_per_wall_sec"]
    rates = _run_rates
    spread = _spread_rel(reps)
    if spread["tpu_batch"] > SPREAD_ADVISORY:
        log(f"WARNING tor_10k: interleaved tpu spread "
            f"{spread['tpu_batch']} > {SPREAD_ADVISORY} — a one-time "
            f"cost may have escaped the warm-up again (per-run rates "
            f"published)")
    small_rows = {
        pol: {
            "sim_sec_per_wall_sec": round(s["sim_sec_per_wall_sec"], 3),
            "events": s["events"],
            "events_per_wall_sec": round(s["events"] / s["wall_seconds"]),
            "max_rss_mb": s["max_rss_mb"],
            "wall_seconds": round(s["wall_seconds"], 2),
            # NOTE: unlike run_config rows (warm process), this includes
            # the subprocess's Python/JAX cold-start, hence the name
            "warmup_wall_seconds_incl_startup": round(
                s["subprocess_wall_s"] - s["wall_seconds"], 1),
            # the median run's per-phase wall budget: where the
            # remaining tor wall lives (acceptance: the residual is
            # named, not guessed)
            "phase_wall": s.get("phase_wall"),
            "raw_rates": rates(reps[pol]),
            "spread_rel": spread[pol],
        }
        for pol, s in (("tpu_batch", sa), ("thread_per_core", sc))
    }
    log(f"tor_10k ratio: tpu {sa['sim_sec_per_wall_sec']:.3f} vs "
        f"tpc {sc['sim_sec_per_wall_sec']:.3f} = {ratio:.2f}x "
        f"(median-of-{N} interleaved; spread {spread})")

    def run(doc, tag):
        cfg = parse_config(doc, {
            "general.data_directory": f"/tmp/shadow-bench-{tag}",
            "experimental.scheduler_policy": "tpu_batch"})
        t0 = _t.perf_counter()  # warm-up includes the 107k-host build
        ctl = Controller(cfg, mirror_log=False)
        r = ctl.run()
        wall = _t.perf_counter() - t0
        r.update(tor_client_stats(ctl))
        return r, wall

    # ru_maxrss is a process-wide high-water mark; under --all this
    # process already ran the smaller benches, so publish the pre-run
    # floor beside the peak — if the peak clearly exceeds the floor, the
    # 100k build owns it (the small twins' RSS rows are per-run above)
    rss_before = resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss / (1024 * 1024)
    doc = _tor_doc(7000, 100_000, stop_s)
    r, wall = run(doc, "tor100k")
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1024 * 1024)
    out = {
        "relays": 7000, "clients": 100_000, "sim_seconds": stop_s,
        "wall_s": round(wall, 1),
        "warmup_wall_seconds": round(wall - r["wall_seconds"], 1),
        "sim_sec_per_wall_sec": round(r["sim_sec_per_wall_sec"], 4),
        "events": r["events"], "units_sent": r["units_sent"],
        "fetches": r.get("tor_fetches"),
        "rss_gib_process_peak": round(rss, 2),
        "rss_gib_floor_before_run": round(rss_before, 2),
        "errors": len(r["process_errors"]),
        "small_scale_1_10": {
            **small_rows,
            "ratio_tpu_vs_thread_per_core": round(ratio, 2),
            "aggregation": f"median-of-{N}, interleaved subprocess "
                           f"pairs; ratio = median/median",
            "note": "700 relays + 10k clients, 8 sim-s; the north-star "
                    "denominator measured at 1/10 scale (subprocess rows, "
                    "per-run RSS)",
        },
        # fast AND robust (PR 6): the same 1/10 config under faults +
        # periodic checkpoints with the C engine on, published beside the
        # clean row so the robustness tax is a measured number
        "tor_1_10_churned_ckpt": tor_churned_ckpt(
            base_ratio=round(ratio, 2)),
    }
    f = out["fetches"] or {}
    log(f"tor_100k: {out['sim_sec_per_wall_sec']} sim-s/wall-s, "
        f"{out['events']} events, {f.get('completed')} fetches "
        f"of {f.get('attempted')} attempted, "
        f"{out['rss_gib_process_peak']} GiB peak RSS")
    return out


def web_cdn_row(reps: int = 3) -> dict:
    """The modern-web family enters the perf trajectory (PR 9): the
    committed examples/web_cdn.yaml (clients -> edge caches -> origin
    over a DNS chain, with a partition + lossy degrade window driving
    SACK recovery) measured with the same interleaved median-of-N
    discipline as the headline rows — (tpu, tpc) pairs so both sides
    share each noise window — plus the standard ablation legs for
    device_engaged. Result fields are asserted identical across every
    leg (the row doubles as a cross-policy identity gate under faults),
    and the flow-latency roll-up (web.fetch/web.origin/dns.resolve
    percentiles) rides along so regressions in the workload itself — not
    just the simulator — show up in BENCH_DETAIL."""
    path = "examples/web_cdn.yaml"
    tpus, tpcs = [], []
    for i in range(reps):
        tpus.append(run_config(path, "tpu_batch", f"webcdn-tpu{i}"))
        tpcs.append(run_config(path, "thread_per_core", f"webcdn-tpc{i}"))
    tpu, tpc = _median_run(tpus), _median_run(tpcs)
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert tpu[k] == tpc[k], ("web_cdn", k)
    flows = {
        kind: {k: v[k] for k in ("count", "ok", "failed", "p50_ms",
                                 "p99_ms") if k in v}
        for kind, v in tpu.get("telemetry", {}).get("flows", {}).items()}
    d = {
        "thread_per_core": tpc,
        "tpu_batch": tpu,
        "ratio_tpu_vs_tpc": round(
            tpu["sim_sec_per_wall_sec"] / tpc["sim_sec_per_wall_sec"], 2),
        "raw_rates": {"tpu_batch": _run_rates(tpus),
                      "thread_per_core": _run_rates(tpcs)},
        "spread_rel": _spread_rel({"tpu_batch": tpus,
                                   "thread_per_core": tpcs}),
        "flows": flows,
        "stream_recovery": {
            k: tpu.get("counters", {}).get(k, 0)
            for k in ("stream_fast_retransmits", "stream_sack_retransmits",
                      "stream_rto_retransmits", "stream_timeouts")},
        "aggregation": f"median-of-{reps}, interleaved (tpu, tpc) pairs",
    }
    d.update(ablation(path, "web_cdn", tpc, tpu))
    log(f"web_cdn: tpu {d['raw_rates']['tpu_batch']} vs tpc "
        f"{d['raw_rates']['thread_per_core']} sim-s/wall-s "
        f"(ratio {d['ratio_tpu_vs_tpc']}x, "
        f"device_engaged={d['device_engaged']})")
    return d


def web_cdn_100k_row(reps: int = 3, stop_time: str = "1500ms") -> dict:
    """The device-transport row (PR 11): the committed
    examples/web_cdn_100k.yaml — 100k page-loop clients behind a CDN
    tier, the regime where per-endpoint ticks dominate the round loop —
    measured with ``experimental.device_transport`` on vs off,
    interleaved median-of-N pairs on the Python columnar plane (where
    the columnar transport engages), plus a scalar-C reference leg.
    ``stop_time`` trims the committed config's 6 s to keep a 9-leg
    interleaved row tractable on one box; the config itself is the
    deeper-run artifact.

    Honesty contract (ISSUE 11 acceptance): `phase_wall.transport_tick`
    is published before/after, `device_transport_engaged` gets the same
    loud-fallback warning `device_engaged` got in PR 3, and if this
    box's batched kernel cannot beat the scalar twins the verdict line
    says so plainly — the break-even economics keep the feature a no-op
    by default either way."""
    path = "examples/web_cdn_100k.yaml"
    ov = {"general.stop_time": stop_time}
    offs, ons, cs = [], [], []
    for i in range(reps):
        # interleaved (off, on, C) triples: all three legs share each
        # noise window
        offs.append(run_config(path, "tpu_batch", f"w100k-off{i}", {
            **ov, "experimental.native_colcore": False}))
        ons.append(run_config(path, "tpu_batch", f"w100k-on{i}", {
            **ov, "experimental.native_colcore": False,
            "experimental.device_transport": True}))
        cs.append(run_config(path, "tpu_batch", f"w100k-c{i}", ov))
    off, on, c = _median_run(offs), _median_run(ons), _median_run(cs)
    # the row doubles as a 100k-endpoint identity gate: every leg must
    # be the same simulation
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert off[k] == on[k] == c[k], ("web_cdn_100k", k)
    devt = on.get("device_transport", {})
    engaged = bool(on.get("device_transport_engaged"))
    if not engaged:
        log("WARNING web_cdn_100k: device_transport_engaged=false — the "
            "device-transport run advanced ZERO cohorts through the "
            "batched kernel; the scalar twin carried the whole run "
            "(this is NOT a columnar-transport result)")
    devt_x = round(on["sim_sec_per_wall_sec"]
                   / off["sim_sec_per_wall_sec"], 3)
    vs_c = round(on["sim_sec_per_wall_sec"]
                 / c["sim_sec_per_wall_sec"], 3)
    verdict = ("columnar transport is a net WIN vs the scalar Python "
               "twin" if devt_x > 1.0 else
               "columnar transport is a WASH vs the scalar Python twin"
               if devt_x >= 0.99 else
               "columnar transport is a net LOSS vs the scalar Python "
               "twin on this box")
    verdict += ("; it does NOT beat the scalar C twin (colcore remains "
                "the fast plane here)" if vs_c < 1.0 else
                "; it ALSO beats the scalar C twin")
    d = {
        "config": f"{path} @ {stop_time} (committed config is 6s)",
        "scalar_c": c,
        "py_columnar_devt_off": off,
        "py_columnar_devt_on": on,
        "devt_x_vs_python_scalar": devt_x,
        "devt_x_vs_scalar_c": vs_c,
        "device_transport_engaged": engaged,
        "device_transport": devt,
        "transport_tick_wall": {
            "devt_on": on.get("phase_wall", {}).get("transport_tick"),
            "devt_off": off.get("phase_wall", {}).get("transport_tick"),
            "events_wall_on": on.get("phase_wall", {}).get("events"),
            "events_wall_off": off.get("phase_wall", {}).get("events"),
        },
        "raw_rates": {"devt_off": _run_rates(offs),
                      "devt_on": _run_rates(ons),
                      "scalar_c": _run_rates(cs)},
        "spread_rel": _spread_rel({"devt_off": offs, "devt_on": ons,
                                   "scalar_c": cs}),
        "verdict": verdict,
        "aggregation": f"median-of-{reps}, interleaved (off, on, C) "
                       f"triples",
    }
    log(f"web_cdn_100k: devt on {d['raw_rates']['devt_on']} vs off "
        f"{d['raw_rates']['devt_off']} vs C {d['raw_rates']['scalar_c']} "
        f"sim-s/wall-s (devt_x={devt_x}, vs_c={vs_c}, "
        f"engaged={engaged}, cohorts={devt.get('cohorts')}, "
        f"acks={devt.get('acks_batched')})")
    log(f"web_cdn_100k verdict: {verdict}")
    return d


def mesh_scaling(config: str = "examples/tgen_100host.yaml",
                 force_collective: bool = False) -> dict:
    """tpu_mesh scaling table (VERDICT r2 item #2): the whole-round
    sharded program over 1/2/4/8 shards of an 8-virtual-device CPU mesh
    (the image has one real chip; the driver validates the same path via
    dryrun_multichip). Results are bit-identical across shard counts —
    only wall time moves — so each run also cross-checks the previous."""
    import json as _json
    import os
    import subprocess

    env = dict(os.environ)
    # the image pins the platform at jax import (sitecustomize), so env
    # vars alone don't switch it; shadow_tpu honors this knob via a
    # jax.config update before backend init (ops/jaxcfg.configure)
    env["SHADOW_FORCE_CPU_DEVICES"] = "8"
    out = {}
    if force_collective:
        # tpu_mesh_floor=1: EVERY window takes the sharded collective
        # (the adaptive floor would route small windows to the numpy
        # twin), so the per-window breakdown attributes the shard tail
        out["note"] = ("tpu_mesh_floor=1 — collective forced on every "
                       "window to expose its wall breakdown; results "
                       "identical to the adaptive run by construction")
    prev = None
    for shards in (1, 2, 4, 8):
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", str(ROOT / config),
             "--scheduler-policy", "tpu_mesh",
             "--set", f"experimental.tpu_mesh_shards={shards}",
             *(["--set", "experimental.tpu_mesh_floor=1"]
               if force_collective else []),
             "--data-directory", f"/tmp/shadow-bench-mesh{shards}",
             "--json-summary", "--quiet"],
            env=env, capture_output=True, text=True, timeout=1200)
        if r.returncode != 0:
            out[f"shards_{shards}"] = {"error": r.stderr[-300:]}
            continue
        s = _json.loads(r.stdout)
        pw = s.get("phase_wall", {})
        out[f"shards_{shards}"] = {
            "sim_sec_per_wall_sec": round(s["sim_sec_per_wall_sec"], 3),
            "units_sent": s["units_sent"],
            "events": s["events"],
            # per-window collective attribution (VERDICT r4 item #7):
            # where the wall goes as shard count grows
            "collective_wall": {
                k.removeprefix("mesh_"): pw[k]
                for k in ("mesh_build", "mesh_dispatch", "mesh_readback",
                          "mesh_windows") if k in pw},
            "events_wall": pw.get("events"),
            "barrier_wall": pw.get("barrier"),
        }
        if prev is not None:
            for k in ("units_sent", "events"):
                assert s[k] == prev[k], f"shard-count divergence on {k}"
        prev = s
        log(f"tpu_mesh shards={shards}: "
            f"{s['sim_sec_per_wall_sec']:.2f} sim-s/wall-s")
    return out


def draw_plane_throughput(n: int = 1_000_000) -> dict:
    """Raw loss-draw throughput, device vs numpy twin, at a config-#5-scale
    batch — the per-round math a 100k-host simulation would batch."""
    import numpy as np

    from shadow_tpu.network.fluid import MAX_PKTS, loss_flags
    from shadow_tpu.ops.propagate import DeviceDrawPlane

    rng = np.random.default_rng(0)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    hi = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    npk = np.full(n, MAX_PKTS, np.uint32)
    th = np.full(n, 1 << 12, np.uint32)

    plane = DeviceDrawPlane(seed=7, max_batch=1 << 20)
    plane.dispatch(lo, hi, npk, th).read()  # warm/compile the full bucket
    t0 = time.perf_counter()
    dev_flags = plane.dispatch(lo, hi, npk, th).read()
    dev_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    np_flags = loss_flags(7, lo, hi, npk, th)
    np_s = time.perf_counter() - t0
    assert (dev_flags == np_flags).all(), "draw-plane bitmatch violated"
    # the per-PROGRAM floor: dispatch+readback of a minimal batch — this
    # is the physics behind the ~1.0 device factor on committed configs
    # (a simulation round carries tens-to-hundreds of units; one program
    # round trip on a tunneled chip costs the same as numpy-ing
    # thousands), and why wins need batch size (below) or multi-chip
    # collectives, not per-round offload
    k = 512
    plane.dispatch(lo[:k], hi[:k], npk[:k], th[:k]).read()  # warm shape
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        plane.dispatch(lo[:k], hi[:k], npk[:k], th[:k]).read()
    rt_ms = (time.perf_counter() - t0) / reps * 1000
    out = {
        "batch": n,
        "device_units_per_sec": n / dev_s,
        "numpy_units_per_sec": n / np_s,
        "device_speedup": np_s / dev_s,
        "device_round_trip_ms_small_batch": round(rt_ms, 3),
        "numpy_breakeven_units": int(rt_ms / 1000 / max(
            np_s / n, 1e-12)),
    }
    log(f"draw-plane @1M units: device {out['device_units_per_sec']:.3g}/s "
        f"vs numpy {out['numpy_units_per_sec']:.3g}/s "
        f"({out['device_speedup']:.1f}x)")
    return out


def fork_amortization(n_branches: int = 10) -> dict:
    """The scenario-multiverse row (shadow_tpu/forks.py): how much wall
    does restoring ONE trunk checkpoint into N what-if branches re-buy
    over N cold-start runs of the same (config, commands, seed) tuples?

    web_cdn at stop 20s forked from its 15s checkpoint: every branch is
    restore-mode (divergence by injected command only), so each re-buys
    the 15s trunk prefix and simulates only the 5s suffix — the ideal
    amortization is ~4x, and anything under 2x means the fork machinery
    (prefix stream copy, pickle restore, per-branch worker dispatch) is
    eating the prefix it saved. Both arms run serially (jobs=1 vs an
    in-process loop, which if anything flatters the cold arm — no
    worker IPC), and the row spot-checks the honesty gate: branch 0's
    output tree and streams byte-equal its cold twin's."""
    from shadow_tpu import fleet as _fleet
    from shadow_tpu import forks as _forks
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    base = str(ROOT / "examples" / "web_cdn.yaml")
    common = {"general.stop_time": "20s",
              "general.checkpoint_every": "15s",
              "general.state_digest_every": 200}
    trunk = _fresh_dir("/tmp/shadow-bench-fork-trunk")
    t0 = time.perf_counter()
    Controller(load_config(base, {**common,
                                  "general.data_directory": trunk}),
               mirror_log=False).run()
    trunk_wall = time.perf_counter() - t0
    cks = sorted(Path(trunk).glob("checkpoints/ckpt_*.ckpt"))
    assert cks, f"trunk wrote no checkpoints under {trunk}"
    # N restore-mode branches: each injects one degrade window after the
    # fork point with a different severity (a realistic what-if sweep)
    branches = [{"name": f"w{i}", "commands": [
        {"t": "16s", "cmd": "link_degrade", "src_nodes": [0, 1],
         "dst_nodes": [6, 7], "latency_factor": 1.25 + 0.25 * i,
         "loss_add": 0.004 * i, "bandwidth_scale": 1.0,
         "duration": "3s"}]} for i in range(n_branches)]
    fork_dir = Path(_fresh_dir("/tmp/shadow-bench-fork"))
    plan = _forks.plan_fork(base, cks[0], branches, fork_dir,
                            overrides=dict(common))
    t0 = time.perf_counter()
    summary = _fleet.FleetRunner(base, plan["order"], jobs=1,
                                 sweep_dir=fork_dir,
                                 overrides=dict(common), fork=plan,
                                 quiet=True).run()
    fork_wall = time.perf_counter() - t0
    assert not summary["failed"], summary["failed"]
    log(f"fork_amortization: {n_branches}-branch fork {fork_wall:.1f}s "
        f"(trunk {trunk_wall:.1f}s); running the cold arm")
    cold_wall = 0.0
    cold0 = None
    for i in range(n_branches):
        d = _fresh_dir(f"/tmp/shadow-bench-fork-cold-{i}")
        replay = _forks.branch_dir(fork_dir, f"w{i}") / _forks.REPLAY_FILE
        t0 = time.perf_counter()
        Controller(load_config(base, {
            **common, "general.data_directory": d,
            "general.replay_commands": str(replay)}),
            mirror_log=False).run()
        cold_wall += time.perf_counter() - t0
        if i == 0:
            cold0 = d
    # the honesty spot check: forked == cold-started, byte for byte
    man0 = json.loads((_forks.branch_dir(fork_dir, "w0")
                       / _forks.FORK_MANIFEST).read_text())
    assert man0["tree_sha256"] == _fleet.output_tree_digest(cold0), \
        "branch w0 tree != its cold twin — amortization measured a lie"
    assert all(man0["streams_sha256"][k] == v for k, v in
               _fleet._stream_digests(cold0).items()), "w0 streams diverged"
    speedup = cold_wall / fork_wall
    row = {
        "workload": f"web_cdn.yaml, {n_branches} what-if branches forked "
                    f"from the 15s checkpoint of a 20s trunk",
        "n_branches": n_branches,
        "trunk_wall_seconds": round(trunk_wall, 2),
        "fork_wall_seconds": round(fork_wall, 2),
        "cold_wall_seconds": round(cold_wall, 2),
        "per_branch_wall_seconds": summary["per_branch_wall_seconds"],
        "speedup_fork_vs_cold": round(speedup, 2),
        "speedup_incl_trunk": round(cold_wall / (fork_wall + trunk_wall),
                                    2),
        "identity_spot_check": "w0 tree+streams == cold twin",
    }
    if speedup < 2.0:
        row.setdefault("warnings", []).append(
            f"fork amortization {speedup:.2f}x < 2x — the restore path "
            f"(prefix stream copy + pickle load + worker dispatch) is "
            f"eating the trunk prefix it was supposed to re-buy")
        log(f"fork_amortization WARNING: {speedup:.2f}x < 2x — restore "
            f"overhead is swallowing the amortization win")
    log(f"fork_amortization: {n_branches} branches forked in "
        f"{fork_wall:.1f}s vs {cold_wall:.1f}s cold ({speedup:.2f}x; "
        f"{row['speedup_incl_trunk']}x counting the trunk run)")
    return row


def ensure_native() -> None:
    """Build the native pieces (shim + colcore) the benchmarks rely on;
    the C engine degrades to the Python twin if absent, which would turn
    the headline into a measurement of the wrong implementation."""
    import subprocess

    try:
        subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                       capture_output=True)
    except Exception as exc:  # keep benching; colplane falls back
        log(f"WARNING: native build failed ({exc}); C engine may be absent")


def main() -> None:
    ensure_native()
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="full matrix + BENCH_DETAIL.json")
    ap.add_argument("--config", default="examples/tgen_1k.yaml",
                    help="headline config (default: BASELINE config #2)")
    ap.add_argument("--tor-churned", action="store_true",
                    help="measure ONLY the tor_1_10_churned_ckpt row and "
                         "merge it into BENCH_DETAIL.json (base ratio "
                         "taken from the published small_scale_1_10 row)")
    ap.add_argument("--sharded", action="store_true",
                    help="measure ONLY the scale-out rows (tor_1_10 at "
                         "shards=1/2/4, interleaved median-of-3, plus the "
                         "full-scale tor_100k at shards=2) and merge them "
                         "into BENCH_DETAIL.json")
    ap.add_argument("--fleet", action="store_true",
                    help="measure ONLY the fleet-mode row (10-seed "
                         "tor_400 sweep vs standalone singles, "
                         "interleaved, with shared-attach and jobs "
                         "ablations) and merge it into BENCH_DETAIL.json")
    ap.add_argument("--fork", action="store_true",
                    help="measure ONLY the fork-amortization row "
                         "(10-branch web_cdn what-if fork vs 10 "
                         "cold-start runs) and merge it into "
                         "BENCH_DETAIL.json")
    args = ap.parse_args()

    if args.fork:
        detail_path = ROOT / "BENCH_DETAIL.json"
        detail = json.loads(detail_path.read_text())
        row = fork_amortization()
        detail["fork_amortization"] = row
        detail_path.write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json (fork_amortization)")
        print(json.dumps({
            "metric": "fork_amortization_speedup_vs_cold",
            "value": row["speedup_fork_vs_cold"],
            "n_branches": row["n_branches"],
            "speedup_incl_trunk": row["speedup_incl_trunk"],
            "warnings": row.get("warnings", []),
        }), flush=True)
        return

    if args.fleet:
        detail_path = ROOT / "BENCH_DETAIL.json"
        detail = json.loads(detail_path.read_text())
        row = tor_400_sweep()
        detail["tor_400_sweep_10seed"] = row
        detail_path.write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json (tor_400_sweep_10seed)")
        print(json.dumps({
            "metric": "tor_400_sweep_10seed_ratio_vs_single",
            "value": row["ratio_sweep_vs_single"],
            "speedup_vs_serial": row["speedup_vs_serial"],
            "target_3x_met": row["target_3x_met"],
            "shared_attach_savings_rel":
                row["shared_attach_savings_rel"],
        }), flush=True)
        return

    if args.sharded:
        detail_path = ROOT / "BENCH_DETAIL.json"
        detail = json.loads(detail_path.read_text())
        row = tor_sharded()
        detail.setdefault("tor_100k", {})["tor_1_10_sharded"] = row
        full = tor_100k_sharded(shards=2)
        detail["tor_100k"]["full_scale_sharded"] = full
        detail_path.write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json (tor_1_10_sharded + "
            "full_scale_sharded)")
        print(json.dumps({
            "metric": "tor_100k_sharded_sim_sec_per_wall_sec",
            "value": full["sim_sec_per_wall_sec"],
            "sim_shards": full["sim_shards"],
            "published_single_process": detail["tor_100k"].get(
                "sim_sec_per_wall_sec"),
        }), flush=True)
        return

    if args.tor_churned:
        detail_path = ROOT / "BENCH_DETAIL.json"
        detail = json.loads(detail_path.read_text())
        base = (detail.get("tor_100k", {}).get("small_scale_1_10", {})
                .get("ratio_tpu_vs_thread_per_core"))
        row = tor_churned_ckpt(base_ratio=base)
        detail.setdefault("tor_100k", {})["tor_1_10_churned_ckpt"] = row
        detail_path.write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json (tor_1_10_churned_ckpt)")
        print(json.dumps({
            "metric": "tor_1_10_churned_ckpt_ratio",
            "value": row["ratio_tpu_vs_thread_per_core"],
            "base_ratio_clean": row.get("base_ratio_clean"),
            "robustness_tax_rel": row.get("robustness_tax_rel"),
        }), flush=True)
        return

    detail: dict = {"machine_note": "tpu_batch uses the local JAX default "
                    "device; thread_per_core is the CPU baseline policy"}

    # untimed warm-up pass per policy BEFORE the measured repetitions
    # (VERDICT r5 weak #1): the first tpu run of a process pays one-time
    # costs the steady-state loop never sees again — device attach/floor
    # calibration finishing mid-run, JAX/XLA compile, numpy/module import,
    # allocator growth — which made measured run 1 ~2x slower than runs
    # 2-3 while warmup_wall_seconds (build-phase wall only) reported
    # 0.2-0.7 s. One full throwaway run per policy moves ALL of that
    # outside the measurement; its wall is published, not hidden.
    warmup_runs = {}
    for pol, tag in (("thread_per_core", "tpc"), ("tpu_batch", "tpu")):
        r = run_config(args.config, pol, f"{tag}-warmup")
        warmup_runs[pol] = round(r["total_wall_seconds"], 3)
    log(f"untimed warm-up runs done: {warmup_runs} (excluded from medians)")

    # median-of-3 per policy, INTERLEAVED (VERDICT r3 weak #1): shared-
    # machine load drifts on the scale of one run, so grouping a policy's
    # repetitions correlates the noise with the policy and corrupts the
    # ratio; best-of-N overstates whichever policy got the quiet slot.
    # The ratio of record is median/median, and the raw rates ship in the
    # headline's neighborhood so any reviewer can recompute it.
    N = 3
    runs = {"thread_per_core": [], "tpu_batch": []}
    for _ in range(N):
        for pol, tag in (("thread_per_core", "tpc"), ("tpu_batch", "tpu")):
            runs[pol].append(run_config(args.config, pol, tag))

    med, rates = _median_run, _run_rates
    base, tpu = med(runs["thread_per_core"]), med(runs["tpu_batch"])
    spread = _spread_rel(runs)
    log(f"raw rates (interleaved x{N}): "
        f"tpc={rates(runs['thread_per_core'])} "
        f"tpu={rates(runs['tpu_batch'])} spread={spread}")
    if spread["tpu_batch"] > SPREAD_ADVISORY:
        log(f"WARNING tgen_1k: interleaved tpu spread "
            f"{spread['tpu_batch']} > {SPREAD_ADVISORY} — a one-time "
            f"cost may have escaped the warm-up (see "
            f"first_rep_excess_rel)")
    headline = {
        "metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch",
        "value": round(tpu["sim_sec_per_wall_sec"], 4),
        "unit": "sim-sec/wall-sec",
        "vs_baseline": round(
            tpu["sim_sec_per_wall_sec"] / base["sim_sec_per_wall_sec"], 4),
        "raw_tpu": rates(runs["tpu_batch"]),
        "raw_baseline": rates(runs["thread_per_core"]),
        "aggregation": f"median-of-{N}, interleaved, after one untimed "
                       f"full warm-up run per policy",
        "warmup_run_wall_s": warmup_runs,
    }
    detail["tgen_1k"] = {
        "thread_per_core": base, "tpu_batch": tpu,
        "raw_rates": {p: rates(r) for p, r in runs.items()},
        "spread_rel": spread,
        "warmup_run_wall_s": warmup_runs,
        # VERDICT r5 item #7: the warm-up leak itemized per policy — the
        # first MEASURED rep's shortfall vs the median rep. With every
        # device program shape pre-compiled at attach (DeviceDrawPlane.
        # warm_shapes) plus the untimed full warm-up run, this should sit
        # at machine noise; a recurring large positive value here means a
        # one-time cost escaped the warm-up again.
        "first_rep_excess_rel": {
            pol: round(1 - rates(r)[0] / max(
                sorted(rates(r))[len(r) // 2], 1e-9), 4)
            for pol, r in runs.items()},
    }

    # telemetry overhead on the headline config (telemetry PR acceptance:
    # <= 5% wall at the default sampling cadence; published, not hidden).
    # Two measures: phase_wall["telemetry"] is the directly-attributed
    # in-band cost (exact, noise-free); the wall delta vs the headline
    # median rides shared-machine noise and is published for honesty.
    telr = run_config(args.config, "tpu_batch", "tpu-tel",
                      {"telemetry": {}})
    tel_wall = telr["phase_wall"].get("telemetry", 0.0)
    detail["tgen_1k"]["telemetry_overhead"] = {
        "telemetry_wall_seconds": round(tel_wall, 4),
        "telemetry_pct_of_loop": round(
            100 * tel_wall / telr["wall_seconds"], 2),
        "wall_seconds_with_telemetry": round(telr["wall_seconds"], 3),
        "wall_seconds_median_without": round(tpu["wall_seconds"], 3),
        "wall_delta_pct_noisy": round(
            100 * (telr["wall_seconds"] / tpu["wall_seconds"] - 1), 1),
        "samples": telr.get("telemetry", {}).get("samples", 0),
        "flows_recorded": telr.get("telemetry", {}).get(
            "flows_recorded", 0),
    }
    to = detail["tgen_1k"]["telemetry_overhead"]
    log(f"telemetry overhead on tgen_1k: "
        f"{to['telemetry_pct_of_loop']}% of loop wall attributed "
        f"({to['telemetry_wall_seconds']}s; noisy run-delta "
        f"{to['wall_delta_pct_noisy']}%; {to['samples']} samples, "
        f"{to['flows_recorded']} flows)")

    # live endpoint overhead on the headline config (live-ops PR
    # acceptance: an attached follower must be ~free — the endpoint is a
    # wall-clock plane with drop-oldest queues, so a slow reader sheds
    # records instead of stalling rounds). Same convention as the
    # telemetry row: published on every run, loud when it regresses.
    import threading as _threading

    live_sock = "/tmp/shadow-bench-live.sock"
    live_drained = [0]

    def _live_drain():
        from shadow_tpu import live as _live_mod
        try:
            for _ in _live_mod.stream_records(live_sock, timeout=60):
                live_drained[0] += 1
        except OSError:
            pass

    _live_reader = _threading.Thread(target=_live_drain, daemon=True)
    _live_reader.start()
    liver = run_config(args.config, "tpu_batch", "tpu-live",
                       {"general.live_endpoint": live_sock,
                        "general.heartbeat_interval": "2s"})
    _live_reader.join(timeout=10)
    live_rel = liver["wall_seconds"] / tpu["wall_seconds"] - 1
    detail["tgen_1k"]["live_overhead"] = {
        "live_overhead_rel": round(live_rel, 4),
        "wall_seconds_with_live": round(liver["wall_seconds"], 3),
        "wall_seconds_median_without": round(tpu["wall_seconds"], 3),
        "records_streamed": live_drained[0],
    }
    if live_rel > 0.05:
        log(f"WARNING tgen_1k: live endpoint overhead {live_rel:.1%} > 5% "
            f"— the wall-clock plane is leaking into the round loop "
            f"(an attached follower should be ~free under drop-oldest)")
    log(f"live endpoint overhead on tgen_1k: {live_rel:+.1%} wall vs "
        f"detached median ({live_drained[0]} records streamed to an "
        f"attached follower)")

    # supervised-run overhead on the headline config (self-healing PR
    # acceptance: supervision is a wall-clock wrapper — a liveness page
    # stamped per round plus a restart loop AROUND the same Controller —
    # so a failure-free supervised run must cost ~nothing; loud above
    # 3%). Same convention as the telemetry/live rows: published on
    # every run, loud when it regresses.
    from shadow_tpu.config import load_config as _load_cfg
    from shadow_tpu.supervise import CHAOS_ENV as _CHAOS_ENV
    from shadow_tpu.supervise import run_supervised as _run_sup

    supr = None
    sup_dir = "/tmp/shadow-bench-tpu-sup"
    shutil.rmtree(sup_dir, ignore_errors=True)
    sup_cfg = _load_cfg(str(ROOT / args.config), {
        "experimental.scheduler_policy": "tpu_batch",
        "general.data_directory": sup_dir,
        "general.supervise": {"max_restarts": 2, "backoff": 0.2},
    })
    supr = _run_sup(sup_cfg, mirror_log=False)
    sup_rel = supr["wall_seconds"] / tpu["wall_seconds"] - 1
    detail["tgen_1k"]["supervise_overhead"] = {
        "supervise_overhead_rel": round(sup_rel, 4),
        "wall_seconds_supervised": round(supr["wall_seconds"], 3),
        "wall_seconds_median_without": round(tpu["wall_seconds"], 3),
        "attempts": supr["supervisor"]["attempts"],
        "restarts": len(supr["supervisor"]["restarts"]),
    }
    if sup_rel > 0.03:
        log(f"WARNING tgen_1k: supervised-run overhead {sup_rel:.1%} > 3% "
            f"— the supervisor is a wall-clock wrapper and a failure-free "
            f"supervised run must track the bare run (liveness stamping "
            f"or the watchdog poll is leaking into the round loop)")
    log(f"supervised-run overhead on tgen_1k: {sup_rel:+.1%} wall vs "
        f"bare median (failure-free, "
        f"{supr['supervisor']['attempts']} attempt)")

    # MTTR under real failure: a short supervised 2-shard gossip_churn
    # with one injected worker SIGKILL (the chaos harness), measuring
    # detection -> first post-restart round ready. Published so recovery
    # latency is a tracked number, not a test-only property.
    mttr_dir = "/tmp/shadow-bench-mttr"
    shutil.rmtree(mttr_dir, ignore_errors=True)
    mttr_cfg = _load_cfg(str(ROOT / "examples/gossip_churn.yaml"), {
        "experimental.scheduler_policy": "tpu_batch",
        "general.data_directory": mttr_dir,
        "general.stop_time": "12s",
        "general.sim_shards": 2,
        "general.checkpoint_every": "2s",
        "general.state_digest_every": 500,
        "general.sample_every": "5s",
        "general.supervise": {"max_restarts": 2, "backoff": 0.1},
    })
    os.environ[_CHAOS_ENV] = "s0:kill@r700"
    try:
        mr = _run_sup(mttr_cfg, mirror_log=False)
    finally:
        os.environ.pop(_CHAOS_ENV, None)
    mrs = mr["supervisor"]["restarts"]
    assert len(mrs) == 1, mrs  # the one injected kill, recovered once
    detail["supervised_recovery"] = {
        "workload": "gossip_churn 2-shard, 12s stop, ckpt every 2s",
        "injected": "s0:kill@r700",
        "mttr_s": mrs[0]["mttr_s"],
        "resume": mrs[0]["resume"],
        "restarts": len(mrs),
    }
    log(f"supervised recovery MTTR (gossip_churn, worker SIGKILL): "
        f"{mrs[0]['mttr_s']}s detection->first-round-ready, resumed "
        f"from {mrs[0]['resume']}")

    # results must be identical across policies — a benchmark that diverged
    # would be measuring two different simulations
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert base[k] == tpu[k], f"policy divergence on {k}"

    # headline-config ablation (VERDICT r4 item #1): decompose the ratio.
    # The ablation rows run median-of-3 interleaved like the headline
    # (round-5 Weak #5: a single-run device_x is noise where it matters).
    detail["tgen_1k"].update(ablation(args.config, "tgen_1k", base, tpu,
                                      reps=N, full_rates=rates(
                                          runs["tpu_batch"])))
    headline["factors"] = detail["tgen_1k"]["factors"]
    headline["device_engaged"] = detail["tgen_1k"]["device_engaged"]
    log(f"tgen_1k factors: {headline['factors']}")

    if args.all:
        for path, tag, collect in (
                ("examples/tgen_100host.yaml", "tgen_100", None),
                ("examples/tor_400relay.yaml", "tor_400", tor_client_stats),
                ("examples/gossip_10k.yaml", "gossip_10k", None)):
            d = {
                "thread_per_core": run_config(
                    path, "thread_per_core", f"{tag}-tpc", collect=collect),
                "tpu_batch": run_config(
                    path, "tpu_batch", f"{tag}-tpu", collect=collect),
            }
            for k in ("events", "units_sent", "units_dropped"):
                assert d["thread_per_core"][k] == d["tpu_batch"][k], (tag, k)
            d.update(ablation(path, tag, d["thread_per_core"],
                              d["tpu_batch"]))
            detail[tag] = d
        detail["web_cdn"] = web_cdn_row()
        detail["web_cdn_100k"] = web_cdn_100k_row()
        detail["managed_50"] = managed_bench()
        detail["managed_dense"] = managed_dense_bench()
        detail["managed_dense_contended"] = managed_dense_contended()
        detail["real_curl"] = real_binary_bench()
        detail["real_curl_1k"] = real_curl_1k()
        detail["managed_ckpt_overhead"] = managed_ckpt_overhead()
        detail["managed_fidelity_audit"] = managed_fidelity_audit()
        detail["tor_100k"] = tor_100k()
        detail["tor_100k"]["tor_1_10_sharded"] = tor_sharded()
        detail["tor_400_sweep_10seed"] = tor_400_sweep()
        detail["tpu_mesh_scaling"] = mesh_scaling()
        detail["tpu_mesh_scaling_forced_collective"] = mesh_scaling(
            force_collective=True)
        # the forced-collective note claims result identity: CHECK it
        for sh in ("shards_1", "shards_2", "shards_4", "shards_8"):
            a = detail["tpu_mesh_scaling"].get(sh)
            b = detail["tpu_mesh_scaling_forced_collective"].get(sh)
            if a and b and "error" not in a and "error" not in b:
                for k in ("units_sent", "events"):
                    assert a[k] == b[k], ("mesh_floor divergence", sh, k)
        detail["draw_plane"] = draw_plane_throughput()
        for tag in ("tgen_1k", "tgen_100", "tor_400", "gossip_10k",
                    "web_cdn"):
            for pol in detail[tag]:
                if isinstance(detail[tag][pol], dict):
                    detail[tag][pol].pop("counters", None)
                    detail[tag][pol].pop("process_errors", None)
        (ROOT / "BENCH_DETAIL.json").write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json")

    print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
