#!/usr/bin/env python
"""Benchmark harness (VERDICT.md round-1 item #2; BASELINE.md metric).

Default mode runs the headline benchmark and prints EXACTLY ONE JSON line:

    {"metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch", "value": ...,
     "unit": "sim-sec/wall-sec", "vs_baseline": ...}

where vs_baseline is the ratio against the thread_per_core CPU policy on the
SAME machine and config (BASELINE.md records no absolute reference numbers —
the reference mount was empty — so the baseline is the reference's own
headline CPU policy re-implemented here, per BASELINE.json north_star).

``--all`` additionally measures every committed benchmark config under both
policies plus the raw draw-plane device-vs-numpy throughput, writing
BENCH_DETAIL.json next to this file. Progress goes to stderr; stdout carries
only the single JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_config(path: str, policy: str, tag: str) -> dict:
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config(str(ROOT / path), {
        "experimental.scheduler_policy": policy,
        "general.data_directory": f"/tmp/shadow-bench-{tag}",
    })
    t0 = time.perf_counter()
    result = Controller(cfg, mirror_log=False).run()
    result["total_wall_seconds"] = time.perf_counter() - t0  # incl. build
    if result["process_errors"]:
        log(f"WARNING {tag}: {len(result['process_errors'])} process errors")
    log(
        f"{tag}: {result['sim_sec_per_wall_sec']:.3f} sim-sec/wall-sec "
        f"({result['events']} events, {result['units_sent']} units, "
        f"{result['wall_seconds']:.2f}s loop wall)"
    )
    return result


def managed_bench(n_servers: int = 10, n_clients: int = 40,
                  nbytes: int = 100_000) -> dict:
    """Real-executable benchmark (VERDICT r2 item #4): N real C server
    binaries x M real C clients as managed processes under the preload
    shim — measures the native layer itself (spawn cost, syscall
    round-trips/sec, sim-s/wall-s) beside the pyapp configs."""
    import subprocess
    import time as _t

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    build = ROOT / "native" / "build"
    subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                   capture_output=True)
    hosts = {}
    for i in range(n_servers):
        hosts[f"srv{i}"] = {
            "network_node_id": 0, "ip_addr": f"11.0.0.{i + 1}",
            "processes": [{
                "path": str(build / "tgen_srv"),
                "args": ["8080", str(n_clients // n_servers)],
                "expected_final_state": {"exited": 0}}]}
    for i in range(n_clients):
        hosts[f"cli{i}"] = {
            "network_node_id": 1,
            "processes": [{
                "path": str(build / "tgen_cli"),
                "args": [f"11.0.0.{(i % n_servers) + 1}", "8080",
                         str(nbytes)],
                "start_time": f"{1000 + i * 37} ms",
                "expected_final_state": {"exited": 0}}]}
    doc = {
        "general": {"stop_time": "30s", "seed": 11},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 1 latency "20 ms" ]
  edge [ source 0 target 0 latency "2 ms" ]
  edge [ source 1 target 1 latency "2 ms" ]
]"""}},
        "hosts": hosts,
    }
    cfg = parse_config(doc, {
        "general.data_directory": "/tmp/shadow-bench-managed"})
    t0 = _t.perf_counter()
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    wall = _t.perf_counter() - t0
    nproc = n_servers + n_clients
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "processes": nproc,
        "sim_sec_per_wall_sec": res["sim_sec_per_wall_sec"],
        "syscalls": sysc,
        "syscalls_per_wall_sec": round(sysc / res["wall_seconds"], 1),
        "spawn_plus_run_wall_s": round(wall, 3),
        "wall_per_process_ms": round(1000 * wall / nproc, 2),
        "bytes_sent": res["bytes_sent"],
        "errors": len(res["process_errors"]),
    }
    log(f"managed_{nproc}: {out['sim_sec_per_wall_sec']:.2f} sim-s/wall-s, "
        f"{out['syscalls_per_wall_sec']:.0f} syscalls/s, "
        f"{out['wall_per_process_ms']:.1f} ms wall/process")
    return out


def managed_dense_bench(n_procs: int = 4, iters: int = 15000,
                        chunk: int = 512) -> dict:
    """Syscall-DENSE managed benchmark (VERDICT r3 item #5 / weak #4):
    each process does ``iters`` write+read round trips through an
    emulated pipe (>= 30k trapped syscalls/process), so the number is the
    steady-state shim<->worker service rate, not spawn cost. The round-3
    managed_50 figure (1,316 syscalls/s over ~19 syscalls/process) was
    spawn-dominated; this measures the path the shmem fast paths serve."""
    import subprocess
    import time as _t

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    build = ROOT / "native" / "build"
    subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                   capture_output=True)
    doc = {
        "general": {"stop_time": "60s", "seed": 3,
                    "data_directory": "/tmp/shadow-bench-pump"},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 0 latency "2 ms" ]
]"""}},
        "hosts": {
            f"box{i}": {"network_node_id": 0, "processes": [
                {"path": str(build / "pump"),
                 "args": [str(iters), str(chunk)],
                 "expected_final_state": {"exited": 0}}]}
            for i in range(n_procs)
        },
    }
    cfg = parse_config(doc, {})
    t0 = _t.perf_counter()
    res = Controller(cfg, mirror_log=False).run()
    wall = _t.perf_counter() - t0
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "processes": n_procs,
        # each serviced syscall is one shim<->worker round trip; a pump
        # iteration is a write + a read = two of them
        "syscall_round_trips_per_process": 2 * iters,
        "syscalls": sysc,
        "syscalls_per_wall_sec": round(sysc / wall, 1),
        "wall_s": round(wall, 3),
        "errors": len(res["process_errors"]),
    }
    log(f"managed_dense: {sysc} syscalls / {wall:.2f}s = "
        f"{out['syscalls_per_wall_sec']:.0f}/s steady-state")
    return out


def real_binary_bench(n_servers: int = 3, n_clients: int = 12,
                      nbytes: int = 400_000) -> dict:
    """Real OFF-THE-SHELF binaries as the workload (VERDICT r3 item #9):
    unmodified CPython http.server instances serve a data file to
    unmodified distro curl clients over the simulated network — the
    whole dynamic-linking / sockets / selectors / file-IO surface of two
    real programs under the shim, validated per run (curl must exit 0
    with the exact byte count; servers must still be running)."""
    import sys as _sys
    import time as _t
    from pathlib import Path as _P

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    if not _P("/usr/bin/curl").exists():
        return {"skipped": "no /usr/bin/curl"}
    docroot = _P("/tmp/shadow-bench-docroot")
    docroot.mkdir(exist_ok=True)
    (docroot / "data.bin").write_bytes(b"x" * nbytes)
    hosts = {}
    for i in range(n_servers):
        hosts[f"web{i}"] = {
            "network_node_id": 0, "ip_addr": f"11.0.0.{i + 1}",
            "processes": [{
                "path": _sys.executable,
                "args": ["-u", "-m", "http.server", "--directory",
                         str(docroot), "--bind", "0.0.0.0", "8080"],
                "expected_final_state": "running"}]}
    for i in range(n_clients):
        url = f"http://11.0.0.{(i % n_servers) + 1}:8080/data.bin"
        hosts[f"cli{i}"] = {
            "network_node_id": 1,
            "processes": [{
                "path": "/usr/bin/curl",
                "args": ["-s", "-o", "/dev/null", "-w",
                         "code=%{http_code} bytes=%{size_download}\\n",
                         url, url],  # two sequential fetches per client
                "start_time": f"{1500 + i * 211} ms",
                "expected_final_state": {"exited": 0}}]}
    doc = {
        "general": {"stop_time": "30s", "seed": 13},
        "network": {"graph": {"type": "gml", "inline": """graph [
  directed 0
  node [ id 0 host_bandwidth_up "200 Mbit" host_bandwidth_down "200 Mbit" ]
  node [ id 1 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
  edge [ source 0 target 1 latency "25 ms" ]
  edge [ source 0 target 0 latency "2 ms" ]
  edge [ source 1 target 1 latency "2 ms" ]
]"""}},
        "hosts": hosts,
    }
    cfg = parse_config(doc, {
        "general.data_directory": "/tmp/shadow-bench-curl"})
    t0 = _t.perf_counter()
    ctl = Controller(cfg, mirror_log=False)
    res = ctl.run()
    wall = _t.perf_counter() - t0
    ok = 0
    for i in range(n_clients):
        out = _P(f"/tmp/shadow-bench-curl/hosts/cli{i}/curl.0.stdout")
        if out.exists():
            ok += out.read_text().count(f"code=200 bytes={nbytes}")
    sysc = res["counters"].get("syscalls", 0)
    out = {
        "servers": f"{n_servers}x CPython http.server",
        "clients": f"{n_clients}x /usr/bin/curl (2 fetches each)",
        "transfers_ok": ok,
        "transfers_expected": 2 * n_clients,
        "sim_sec_per_wall_sec": round(res["sim_sec_per_wall_sec"], 3),
        "syscalls": sysc,
        "wall_s": round(wall, 2),
        "errors": len(res["process_errors"]),
    }
    assert ok == 2 * n_clients, (ok, res["process_errors"])
    log(f"real_curl: {ok}/{2*n_clients} transfers, "
        f"{out['sim_sec_per_wall_sec']} sim-s/wall-s, {sysc} syscalls")
    return out


def _tor_doc(n_relays: int, n_clients: int, stop_s: int,
             fetch: str = "20 kB") -> dict:
    """Config #5 generator (BASELINE.md): onion-routing at tornettools
    shape — TorRelay/TorExit relays, TGen web servers, TorClients
    building 3-hop circuits and fetching through them, on a 64-node
    random graph. Deterministic from the fixed seed."""
    import sys as _sys

    import numpy as np

    _sys.path.insert(0, str(ROOT / "tools"))
    from gen_benchmarks import random_gml

    rng = np.random.default_rng(42)
    g = 64
    gml = random_gml(rng, g, min_lat_ms=10, max_lat_ms=120, max_loss=0.002,
                     bw_choices=("50 Mbit", "100 Mbit", "1 Gbit"))
    hosts = {}
    n_exits = max(1, n_relays // 8)  # exits FIRST: clients draw their last hop
    # from relay0..relay{n_exits-1} (TorClient's n_exits arg)
    for i in range(n_relays):
        cls = "TorExit" if i < n_exits else "TorRelay"
        hosts[f"relay{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{"path": f"pyapp:shadow_tpu.models.tor:{cls}",
                           "args": ["9001"]}]}
    for i in range(20):
        hosts[f"web{i}"] = {
            "network_node_id": int(rng.integers(0, g)),
            "processes": [{"path": "pyapp:shadow_tpu.models.tgen:TGenServer",
                           "args": ["80"]}]}
    per = n_clients // g
    for i in range(g):
        q = per + (n_clients - per * g if i == g - 1 else 0)
        hosts[f"u{i}_"] = {
            "network_node_id": i, "quantity": q,
            "processes": [{"path": "pyapp:shadow_tpu.models.tor:TorClient",
                           "args": [str(n_relays), "9001", f"web{i % 20}",
                                    "80", fetch, "1", str(n_exits)],
                           "start_time": f"{2000 + i * 150} ms"}]}
    return {"general": {"stop_time": f"{stop_s}s", "seed": 6},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "hosts": hosts}


def tor_100k(stop_s: int = 15) -> dict:
    """BASELINE config #5 as a real bench row (VERDICT r3 item #6):
    7,000 relays + 100,000 clients through the columnar plane + C
    engine. Publishes sim-s/wall-s, RSS, events, completed fetches.
    Determinism gate: a 1/10-scale twin (700 relays + 10k clients) runs
    TWICE and must match on every result field (the full config once is
    ~5-8 min on one core; twice would double the bench for no extra
    information — the machinery is scale-invariant)."""
    import resource
    import time as _t

    from shadow_tpu.config import parse_config
    from shadow_tpu.core.controller import Controller

    def run(doc, tag):
        cfg = parse_config(doc, {
            "general.data_directory": f"/tmp/shadow-bench-{tag}",
            "experimental.scheduler_policy": "tpu_batch"})
        ctl = Controller(cfg, mirror_log=False)
        t0 = _t.perf_counter()
        r = ctl.run()
        wall = _t.perf_counter() - t0
        fetches = sum(p.app.completed for h in ctl.hosts
                      for p in h.processes
                      if type(p.app).__name__ == "TorClient")
        return r, wall, fetches

    small = _tor_doc(700, 10_000, 8)
    a, _, fa = run(small, "tor10k-a")
    b, _, fb = run(small, "tor10k-b")
    for k in ("events", "units_sent", "units_dropped", "bytes_sent",
              "rounds", "counters"):
        assert a[k] == b[k], f"tor determinism: {k} diverged"
    assert fa == fb
    log(f"tor_10k determinism OK ({a['events']} events, {fa} fetches)")

    doc = _tor_doc(7000, 100_000, stop_s)
    r, wall, fetches = run(doc, "tor100k")
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    out = {
        "relays": 7000, "clients": 100_000, "sim_seconds": stop_s,
        "wall_s": round(wall, 1),
        "sim_sec_per_wall_sec": round(r["sim_sec_per_wall_sec"], 4),
        "events": r["events"], "units_sent": r["units_sent"],
        "fetches_completed": fetches,
        "rss_gb": round(rss, 2),
        "errors": len(r["process_errors"]),
    }
    log(f"tor_100k: {out['sim_sec_per_wall_sec']} sim-s/wall-s, "
        f"{out['events']} events, {fetches} fetches, {out['rss_gb']} GB RSS")
    return out


def mesh_scaling(config: str = "examples/tgen_100host.yaml") -> dict:
    """tpu_mesh scaling table (VERDICT r2 item #2): the whole-round
    sharded program over 1/2/4/8 shards of an 8-virtual-device CPU mesh
    (the image has one real chip; the driver validates the same path via
    dryrun_multichip). Results are bit-identical across shard counts —
    only wall time moves — so each run also cross-checks the previous."""
    import json as _json
    import os
    import subprocess

    env = dict(os.environ)
    # the image pins the platform at jax import (sitecustomize), so env
    # vars alone don't switch it; shadow_tpu honors this knob via a
    # jax.config update before backend init (ops/jaxcfg.configure)
    env["SHADOW_FORCE_CPU_DEVICES"] = "8"
    out = {}
    prev = None
    for shards in (1, 2, 4, 8):
        r = subprocess.run(
            [sys.executable, "-m", "shadow_tpu", str(ROOT / config),
             "--scheduler-policy", "tpu_mesh",
             "--set", f"experimental.tpu_mesh_shards={shards}",
             "--data-directory", f"/tmp/shadow-bench-mesh{shards}",
             "--json-summary", "--quiet"],
            env=env, capture_output=True, text=True, timeout=1200)
        if r.returncode != 0:
            out[f"shards_{shards}"] = {"error": r.stderr[-300:]}
            continue
        s = _json.loads(r.stdout)
        out[f"shards_{shards}"] = {
            "sim_sec_per_wall_sec": round(s["sim_sec_per_wall_sec"], 3),
            "units_sent": s["units_sent"],
            "events": s["events"],
        }
        if prev is not None:
            for k in ("units_sent", "events"):
                assert s[k] == prev[k], f"shard-count divergence on {k}"
        prev = s
        log(f"tpu_mesh shards={shards}: "
            f"{s['sim_sec_per_wall_sec']:.2f} sim-s/wall-s")
    return out


def draw_plane_throughput(n: int = 1_000_000) -> dict:
    """Raw loss-draw throughput, device vs numpy twin, at a config-#5-scale
    batch — the per-round math a 100k-host simulation would batch."""
    import numpy as np

    from shadow_tpu.network.fluid import MAX_PKTS, loss_flags
    from shadow_tpu.ops.propagate import DeviceDrawPlane

    rng = np.random.default_rng(0)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    hi = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)
    npk = np.full(n, MAX_PKTS, np.uint32)
    th = np.full(n, 1 << 12, np.uint32)

    plane = DeviceDrawPlane(seed=7, max_batch=1 << 20)
    plane.dispatch(lo, hi, npk, th).read()  # warm/compile the full bucket
    t0 = time.perf_counter()
    dev_flags = plane.dispatch(lo, hi, npk, th).read()
    dev_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    np_flags = loss_flags(7, lo, hi, npk, th)
    np_s = time.perf_counter() - t0
    assert (dev_flags == np_flags).all(), "draw-plane bitmatch violated"
    out = {
        "batch": n,
        "device_units_per_sec": n / dev_s,
        "numpy_units_per_sec": n / np_s,
        "device_speedup": np_s / dev_s,
    }
    log(f"draw-plane @1M units: device {out['device_units_per_sec']:.3g}/s "
        f"vs numpy {out['numpy_units_per_sec']:.3g}/s "
        f"({out['device_speedup']:.1f}x)")
    return out


def ensure_native() -> None:
    """Build the native pieces (shim + colcore) the benchmarks rely on;
    the C engine degrades to the Python twin if absent, which would turn
    the headline into a measurement of the wrong implementation."""
    import subprocess

    try:
        subprocess.run(["make", "-C", str(ROOT / "native")], check=True,
                       capture_output=True)
    except Exception as exc:  # keep benching; colplane falls back
        log(f"WARNING: native build failed ({exc}); C engine may be absent")


def main() -> None:
    ensure_native()
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="full matrix + BENCH_DETAIL.json")
    ap.add_argument("--config", default="examples/tgen_1k.yaml",
                    help="headline config (default: BASELINE config #2)")
    args = ap.parse_args()

    detail: dict = {"machine_note": "tpu_batch uses the local JAX default "
                    "device; thread_per_core is the CPU baseline policy"}

    # median-of-3 per policy, INTERLEAVED (VERDICT r3 weak #1): shared-
    # machine load drifts on the scale of one run, so grouping a policy's
    # repetitions correlates the noise with the policy and corrupts the
    # ratio; best-of-N overstates whichever policy got the quiet slot.
    # The ratio of record is median/median, and the raw rates ship in the
    # headline's neighborhood so any reviewer can recompute it.
    N = 3
    runs = {"thread_per_core": [], "tpu_batch": []}
    for _ in range(N):
        for pol, tag in (("thread_per_core", "tpc"), ("tpu_batch", "tpu")):
            runs[pol].append(run_config(args.config, pol, tag))

    def med(rs):
        s = sorted(rs, key=lambda r: r["sim_sec_per_wall_sec"])
        return s[len(s) // 2]

    def rates(rs):
        return [round(r["sim_sec_per_wall_sec"], 3) for r in rs]

    base, tpu = med(runs["thread_per_core"]), med(runs["tpu_batch"])
    spread = {
        pol: round((max(v) - min(v)) / max(v[len(v) // 2], 1e-9), 4)
        for pol, v in ((p, sorted(rates(r))) for p, r in runs.items())
    }
    log(f"raw rates (interleaved x{N}): "
        f"tpc={rates(runs['thread_per_core'])} "
        f"tpu={rates(runs['tpu_batch'])} spread={spread}")
    headline = {
        "metric": "sim_sec_per_wall_sec_tgen1k_tpu_batch",
        "value": round(tpu["sim_sec_per_wall_sec"], 4),
        "unit": "sim-sec/wall-sec",
        "vs_baseline": round(
            tpu["sim_sec_per_wall_sec"] / base["sim_sec_per_wall_sec"], 4),
        "raw_tpu": rates(runs["tpu_batch"]),
        "raw_baseline": rates(runs["thread_per_core"]),
        "aggregation": f"median-of-{N}, interleaved",
    }
    detail["tgen_1k"] = {
        "thread_per_core": base, "tpu_batch": tpu,
        "raw_rates": {p: rates(r) for p, r in runs.items()},
        "spread_rel": spread,
    }

    # results must be identical across policies — a benchmark that diverged
    # would be measuring two different simulations
    for k in ("events", "units_sent", "units_dropped", "bytes_sent"):
        assert base[k] == tpu[k], f"policy divergence on {k}"

    if args.all:
        for path, tag in (("examples/tgen_100host.yaml", "tgen_100"),
                          ("examples/tor_400relay.yaml", "tor_400"),
                          ("examples/gossip_10k.yaml", "gossip_10k")):
            detail[tag] = {
                "thread_per_core": run_config(path, "thread_per_core", f"{tag}-tpc"),
                "tpu_batch": run_config(path, "tpu_batch", f"{tag}-tpu"),
            }
            for k in ("events", "units_sent", "units_dropped"):
                assert (detail[tag]["thread_per_core"][k]
                        == detail[tag]["tpu_batch"][k]), (tag, k)
        detail["managed_50"] = managed_bench()
        detail["managed_dense"] = managed_dense_bench()
        detail["real_curl"] = real_binary_bench()
        detail["tor_100k"] = tor_100k()
        detail["tpu_mesh_scaling"] = mesh_scaling()
        detail["draw_plane"] = draw_plane_throughput()
        for tag in ("tgen_1k", "tgen_100", "tor_400", "gossip_10k"):
            for pol in detail[tag]:
                if isinstance(detail[tag][pol], dict):
                    detail[tag][pol].pop("counters", None)
                    detail[tag][pol].pop("process_errors", None)
        (ROOT / "BENCH_DETAIL.json").write_text(json.dumps(detail, indent=2))
        log("wrote BENCH_DETAIL.json")

    print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
