"""Supervised self-healing runs: liveness, automatic recovery, chaos.

The multi-process planes (parallel/shards.py workers, fleet.py members)
and the managed-guest plane can each lose a participant mid-run: a
SIGKILLed shard worker, a wedged member spinning without progress, a
guest stalled in-shim. Before this module, the failure mode was an
indefinite hang (the marker barrier waited 3600 s; a wedged fleet member
held its slot forever). This module turns single-component failure into
a *named, bounded, recoverable* event, three layers:

**Liveness.** Every shard worker stamps a monotone progress word — its
round counter plus a wall stamp — into a per-run ``ProgressPage``
(one SharedMemory segment, one cache-line slot per shard, single writer
per slot). Waiters derive stall deadlines from the observed round-wall
EMA (``max(SHADOW_TPU_STALL_FLOOR_S, SHADOW_TPU_STALL_MULT x EMA)``), so
a dead peer is named by shard id, last round, and stamp age instead of
hanging every survivor. The fleet dispatch loop applies the same policy
to member seeds using completed-seed wall EMAs.

**Recovery.** ``run_supervised`` wraps a run (single-process or sharded)
with a bounded restart budget (``general.supervise: {max_restarts,
backoff}`` / ``--supervise``): on a recoverable failure it tears the run
down coherently (workers are terminated by the plane's own error path;
managed guests are reaped through the ``guest_pids.jsonl`` registry),
rolls the append-mode output streams back to the newest complete
checkpoint boundary, and resumes from that checkpoint — producing final
trees/flows/digests byte-identical to an uninterrupted run. With no
usable checkpoint it re-runs from scratch (fresh-run truncation already
regenerates every stream). When the budget is exhausted it salvages what
is on disk, writes a structured ``crash_report.json`` (reason, attempt,
digest cursor, rlimit/RSS snapshot) and raises ``SupervisorGaveUp`` — a
named exit, never a hang and never a bare traceback from the CLI.

**Chaos.** ``SHADOW_TPU_CHAOS="kill@r500,s1:wedge@r900,..."`` (and
``tools/chaos.py``) injects worker SIGKILLs, ring-stall wedges, named
failures, and managed-guest hangs at deterministic rounds. Every event
fires at most once per data directory (an O_EXCL marker file under
``<data_dir>/chaos/``), so the recovered attempt sails past the
injection point and the run converges — which is what lets CI *prove*
recovery by hashing the chaos run against the clean run
(tests/test_supervise.py, tools/ci.sh).

Determinism note: everything here is wall-clock policy. Progress stamps,
deadlines, restarts, and crash reports never touch simulation state; the
byte-identity of a recovered run is inherited from the checkpoint
plane's identity guarantee plus the stream rollback below.
"""

from __future__ import annotations

import json
import os
import signal
import struct
import sys
import time as _walltime  # detlint: ok(wallclock): liveness stamps, stall deadlines, restart backoff
from pathlib import Path

#: chaos harness spec (parse_chaos below); shared by the controller round
#: loop and the shard workers — each process fires only its own events
CHAOS_ENV = "SHADOW_TPU_CHAOS"
#: stall-deadline knobs: deadline = max(FLOOR, MULT x round-wall EMA).
#: The defaults are deliberately generous (CI boxes stall for seconds
#: under load); chaos tests tighten them per-run through the environment.
STALL_FLOOR_ENV = "SHADOW_TPU_STALL_FLOOR_S"
STALL_MULT_ENV = "SHADOW_TPU_STALL_MULT"
DEFAULT_STALL_FLOOR_S = 10.0
DEFAULT_STALL_MULT = 64.0
#: absolute ceiling: even a pathological EMA never waits longer than the
#: old fixed barrier timeout did
STALL_CEILING_S = 3600.0

CRASH_REPORT = "crash_report.json"
REPORT_FORMAT = "shadow_tpu-crash-report"
#: defaults for general.supervise (config/schema.py validates the keys)
DEFAULT_MAX_RESTARTS = 3
DEFAULT_BACKOFF_S = 1.0

#: duplicated literals from parallel/shards.py — supervise is imported BY
#: shards (ProgressPage), so it cannot import shards at module top
_SHARD_MANIFEST_SUFFIX = ".shards.json"
_SHARD_MANIFEST_FORMAT = "shadow_tpu-shard-manifest"


class ChaosFailure(RuntimeError):
    """An injected in-process failure (chaos ``fail@rN``)."""


class GuestStallError(RuntimeError):
    """A managed guest stalled past its watchdog deadline while the run
    is supervised: escalated to the supervisor for checkpoint recovery
    instead of the unsupervised host_down conversion (native/managed.py
    _watchdog_fire)."""


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted (or the failure is not
    recoverable): crash_report.json is on disk; exit by name."""


def stall_deadline_s(ema_s) -> float:
    """THE stall-deadline policy, one source of truth for shard workers,
    the parent coordinator, and the fleet dispatch loop."""
    floor = float(os.environ.get(STALL_FLOOR_ENV, DEFAULT_STALL_FLOOR_S))
    mult = float(os.environ.get(STALL_MULT_ENV, DEFAULT_STALL_MULT))
    return min(max(floor, mult * float(ema_s or 0.0)), STALL_CEILING_S)


# -- the progress page ---------------------------------------------------------

def progress_name(tag: str) -> str:
    return f"stpu_{tag}_prog"


class ProgressPage:
    """Per-run liveness board: one 64-byte slot per shard in a shared
    SharedMemory segment. Slot k is written ONLY by shard k (single
    writer — no locks, no fences needed beyond x86-TSO, the same
    platform contract the ShmRing already imposes):

        [round u64][wall stamp (monotonic ns) u64][48 bytes pad]

    Readers (peers waiting at the marker barrier, the parent
    coordinator) use the stamp's age to distinguish a *slow* shard
    (stamp fresh, keep waiting) from a *dead or wedged* one (stamp stale
    past the deadline — name it and fail fast). Torn reads are benign:
    both words only ever feed staleness heuristics, never results."""

    SLOT = 64

    def __init__(self, name: str, n: int, create: bool = False) -> None:
        from multiprocessing import shared_memory

        self.n = int(n)
        size = self.SLOT * self.n
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size)
            self.shm.buf[:size] = b"\x00" * size
        else:
            # attach without resource_tracker registration: the creator
            # owns the lifetime (the ShmRing attach discipline)
            from multiprocessing import resource_tracker

            orig = resource_tracker.register
            resource_tracker.register = lambda *a, **k: None
            try:
                self.shm = shared_memory.SharedMemory(name=name)
            finally:
                resource_tracker.register = orig
        self.buf = self.shm.buf

    def stamp(self, k: int, rnd: int) -> None:
        struct.pack_into("<QQ", self.buf, k * self.SLOT,
                         rnd & 0xFFFFFFFFFFFFFFFF,
                         _walltime.monotonic_ns())

    def read(self, k: int):
        """-> (round, wall_stamp_ns); (0, 0) = never stamped."""
        return struct.unpack_from("<QQ", self.buf, k * self.SLOT)

    def age_s(self, k: int) -> float:
        """Seconds since shard k last stamped; +inf if it never did."""
        _rnd, ns = self.read(k)
        if ns == 0:
            return float("inf")
        return max(0.0, (_walltime.monotonic_ns() - ns) / 1e9)

    def snapshot(self) -> tuple:
        return tuple(self.read(k) for k in range(self.n))

    def close(self) -> None:
        self.buf = None
        self.shm.close()

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


# -- the chaos harness ---------------------------------------------------------

CHAOS_KINDS = ("kill", "wedge", "fail", "guest_wedge")


def parse_chaos(spec: str) -> list:
    """``[s<K>:]<kind>@r<N>[,...]`` -> [{"shard", "kind", "round"}].

    Kinds: ``kill`` (SIGKILL the worker process), ``wedge`` (stop
    draining/stamping forever — a ring-stall), ``fail`` (raise
    ChaosFailure), ``guest_wedge`` (SIGSTOP the newest managed guest so
    the guest watchdog path fires). Shard defaults to 0 (also the
    single-process controller's id)."""
    events = []
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        shard = 0
        body = item
        if body.startswith("s") and ":" in body:
            pre, body = body.split(":", 1)
            try:
                shard = int(pre[1:])
            except ValueError as exc:
                raise ValueError(f"bad chaos shard prefix in {item!r}") from exc
        if "@" not in body:
            raise ValueError(
                f"bad chaos event {item!r}: expected [s<K>:]<kind>@r<N>")
        kind, at = body.split("@", 1)
        if kind not in CHAOS_KINDS:
            raise ValueError(
                f"bad chaos kind {kind!r} in {item!r}: one of {CHAOS_KINDS}")
        if not at.startswith("r"):
            raise ValueError(
                f"bad chaos trigger {at!r} in {item!r}: expected r<round>")
        try:
            rnd = int(at[1:])
        except ValueError as exc:
            raise ValueError(
                f"bad chaos round in {item!r}") from exc
        events.append({"shard": shard, "kind": kind, "round": rnd})
    return events


class ChaosInjector:
    """Fires this process's chaos events at round tops. Each event fires
    AT MOST ONCE per data directory: the O_EXCL marker under
    ``<data_dir>/chaos/`` is claimed *before* firing, so the supervised
    re-run passes the injection round untouched and converges."""

    def __init__(self, events: list, data_dir, shard: int = 0,
                 in_process: bool = False) -> None:
        self.events = [e for e in events if e["shard"] == int(shard)]
        self.shard = int(shard)
        self.in_process = bool(in_process)
        self.dir = Path(data_dir) / "chaos"

    @classmethod
    def from_env(cls, data_dir, shard: int = 0, in_process: bool = False):
        spec = os.environ.get(CHAOS_ENV, "")
        if not spec:
            return None
        inj = cls(parse_chaos(spec), data_dir, shard=shard,
                  in_process=in_process)
        return inj if inj.events else None

    def _claim(self, ev: dict) -> bool:
        self.dir.mkdir(parents=True, exist_ok=True)
        marker = self.dir / (
            f"{ev['kind']}@r{ev['round']}.s{ev['shard']}.fired")
        try:
            os.close(os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        except FileExistsError:
            return False
        return True

    def maybe_fire(self, rnd: int, controller=None) -> None:
        for ev in self.events:
            # >= not ==: a resume may land past the exact round (skip-
            # ahead); the marker file is what makes firing once-only
            if rnd < ev["round"]:
                continue
            if not self._claim(ev):
                continue
            self._fire(ev, controller)

    def _fire(self, ev: dict, controller) -> None:
        kind = ev["kind"]
        print(f"chaos: firing {kind}@r{ev['round']} on shard "
              f"{ev['shard']} (pid {os.getpid()})",
              file=sys.stderr, flush=True)
        if kind == "fail":
            raise ChaosFailure(
                f"chaos fail@r{ev['round']} injected on shard {ev['shard']}")
        if kind == "kill":
            if self.in_process and controller is not None \
                    and getattr(controller, "_supervised", False):
                # an in-process SIGKILL would take the supervisor down
                # with the run: model the crash as a raised failure
                raise ChaosFailure(
                    f"chaos kill@r{ev['round']} injected in-process on "
                    f"shard {ev['shard']}")
            os.kill(os.getpid(), signal.SIGKILL)
            return  # unreachable
        if kind == "wedge":
            # a genuine ring-stall: stop draining, stop stamping, never
            # return. The process stays SIGTERM-able so the coordinator's
            # teardown (or the operator) can still reap it.
            while True:
                _walltime.sleep(3600)
        if kind == "guest_wedge":
            pid = _newest_guest_pid(
                controller.data_dir if controller is not None
                else self.dir.parent)
            if pid is None:
                raise ChaosFailure(
                    f"chaos guest_wedge@r{ev['round']}: no live guest pid "
                    f"in guest_pids.jsonl to wedge")
            try:
                os.kill(pid, signal.SIGSTOP)
            except (ProcessLookupError, PermissionError) as exc:
                raise ChaosFailure(
                    f"chaos guest_wedge@r{ev['round']}: SIGSTOP {pid} "
                    f"failed ({exc})") from exc


def _newest_guest_pid(data_dir):
    p = Path(data_dir) / "guest_pids.jsonl"
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        pid = rec.get("pid")
        if pid and Path(f"/proc/{pid}").is_dir():
            return int(pid)
    return None


# -- checkpoint discovery + stream rollback ------------------------------------

def find_restart_checkpoint(cfg):
    """Newest COMPLETE checkpoint for a restart of ``cfg``: the shard
    manifest whose per-shard files all exist (sharded), or the newest
    single checkpoint file (single-process; writes are atomic via
    os.replace, so existence is completeness). None = restart from
    scratch."""
    ckpt_dir = (Path(cfg.general.checkpoint_dir)
                if cfg.general.checkpoint_dir
                else Path(cfg.general.data_directory) / "checkpoints")
    if not ckpt_dir.is_dir():
        return None
    if cfg.general.sim_shards > 1:
        # ckpt_t<20-digit sim time>: lexicographic == chronological
        for man in sorted(ckpt_dir.glob("*" + _SHARD_MANIFEST_SUFFIX),
                          reverse=True):
            try:
                doc = json.loads(man.read_text())
            except (OSError, ValueError):
                continue
            if doc.get("format") != _SHARD_MANIFEST_FORMAT:
                continue
            if all((man.parent / f).is_file() for f in doc["files"]):
                return str(man)
        return None
    cands = sorted(p for p in ckpt_dir.glob("ckpt_t*.ckpt")
                   if ".shard" not in p.name)
    return str(cands[-1]) if cands else None


def _restart_boundary(resume_path):
    """(rounds, sim_time_ns, managed) of a restart checkpoint."""
    from shadow_tpu import checkpoint as _ckpt

    p = Path(resume_path)
    if p.name.endswith(_SHARD_MANIFEST_SUFFIX):
        doc = json.loads(p.read_text())
        return int(doc["rounds"]), int(doc["sim_time_ns"]), False
    header = _ckpt.read_header(p)
    return (int(header["rounds"]), int(header["sim_time_ns"]),
            bool(header.get("managed")))


def _filter_jsonl(path: Path, keep) -> None:
    """Atomically rewrite a .jsonl file keeping only records ``keep``
    accepts (unparseable lines are kept — never silently destroy)."""
    if not path.is_file():
        return
    out = []
    with open(path) as f:
        for line in f:
            s = line.rstrip("\n")
            if not s:
                continue
            try:
                rec = json.loads(s)
            except ValueError:
                out.append(s)
                continue
            if keep(rec):
                out.append(s)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text("".join(x + "\n" for x in out))
    os.replace(tmp, path)


def stream_prefix_keep(ckpt_rounds: int, t0_ns: int) -> dict:
    """Per-stream keep predicates for truncating output streams at a
    checkpoint boundary (round ``ckpt_rounds``, sim time ``t0_ns``) so a
    resumed run's appends continue them byte-identically. Shared by the
    supervisor's in-place rollback and the fork runner's prefix copy
    (shadow_tpu/forks.py) — one set of rules, two consumers.

    The rules mirror the round-boundary order (commands -> checkpoint ->
    fault transitions -> round -> digest/telemetry):

    - digests + flow records: ``round <= ckpt_rounds`` (emitted before
      the boundary's checkpoint; later rounds re-emit on resume)
    - commands: ``t <= t0`` (applied before the same-boundary snapshot,
      so their effects are in the restored state and resume skips them)
    - metrics: meta records always stay; samples keep ``t <= t0`` (the
      sampler cursor restores past them); fault records keep ``t < t0``
      (transitions at the boundary apply AFTER the snapshot and re-emit)
    """
    by_round = lambda rec: int(rec.get("round", 0)) <= ckpt_rounds

    def keep_metric(rec):
        kind = rec.get("kind")
        if kind == "meta":
            return True
        if kind == "fault":
            return int(rec.get("t", 0)) < t0_ns
        if "t" in rec:
            return int(rec["t"]) <= t0_ns
        return True

    return {
        "state_digests.jsonl": by_round,
        "flows.jsonl": by_round,
        "commands.jsonl": lambda rec: int(rec.get("t", 0)) <= t0_ns,
        "metrics.jsonl": keep_metric,
    }


def rollback_streams(cfg, ckpt_rounds: int, t0_ns: int) -> None:
    """Trim the append-mode output streams back to the checkpoint
    boundary in place (keep rules: ``stream_prefix_keep``) so the
    resumed run's appends continue them byte-identically."""
    data_dir = Path(cfg.general.data_directory)
    tel = cfg.telemetry
    mdir = (Path(tel.metrics_dir) if tel is not None and tel.metrics_dir
            else data_dir)

    keeps = stream_prefix_keep(ckpt_rounds, t0_ns)
    _filter_jsonl(data_dir / "state_digests.jsonl",
                  keeps["state_digests.jsonl"])
    for p in sorted(data_dir.glob("state_digests.shard*.jsonl")):
        _filter_jsonl(p, keeps["state_digests.jsonl"])
    _filter_jsonl(mdir / "flows.jsonl", keeps["flows.jsonl"])
    for p in sorted(mdir.glob("flows.shard*.jsonl")):
        _filter_jsonl(p, keeps["flows.jsonl"])
    _filter_jsonl(data_dir / "commands.jsonl", keeps["commands.jsonl"])
    _filter_jsonl(mdir / "metrics.jsonl", keeps["metrics.jsonl"])


# -- crash reports -------------------------------------------------------------

def _digest_cursor(data_dir):
    """(last digest round, line count) of state_digests.jsonl."""
    last, n = None, 0
    try:
        with open(Path(data_dir) / "state_digests.jsonl") as f:
            for line in f:
                if not line.strip():
                    continue
                n += 1
                try:
                    last = json.loads(line).get("round", last)
                except ValueError:
                    pass
    except OSError:
        pass
    return last, n


def write_crash_report(data_dir, reason: str, exc=None, attempt: int = 0,
                       max_restarts: int = 0, extra: dict = None):
    """Structured post-mortem at ``<data_dir>/crash_report.json``: what
    failed, how far the run got (digest cursor), and the resource
    envelope at give-up time."""
    import resource

    data_dir = Path(data_dir)
    data_dir.mkdir(parents=True, exist_ok=True)
    last_round, cursor = _digest_cursor(data_dir)
    try:
        with open("/proc/self/statm") as f:
            rss_mb = round(int(f.read().split()[1])
                           * os.sysconf("SC_PAGE_SIZE") / (1 << 20), 1)
    except (OSError, ValueError, IndexError):
        rss_mb = None
    rep = {
        "format": REPORT_FORMAT,
        "reason": reason,
        "exc_type": type(exc).__name__ if exc is not None else None,
        "exc_message": str(exc) if exc is not None else None,
        "attempt": int(attempt),
        "max_restarts": int(max_restarts),
        "last_digest_round": last_round,
        "digest_cursor": cursor,
        "rlimit_nofile": list(resource.getrlimit(resource.RLIMIT_NOFILE)),
        "rlimit_as": list(resource.getrlimit(resource.RLIMIT_AS)),
        "rss_mb": rss_mb,
        **(extra or {}),
    }
    path = data_dir / CRASH_REPORT
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(rep, sort_keys=True, indent=1) + "\n")
    os.replace(tmp, path)
    return path


# -- the supervisor ------------------------------------------------------------

def supervise_options(cfg) -> dict:
    opts = {"max_restarts": DEFAULT_MAX_RESTARTS,
            "backoff": DEFAULT_BACKOFF_S}
    s = getattr(cfg.general, "supervise", None)
    if isinstance(s, dict):
        opts.update(s)
    return opts


def _reap_guests(data_dir) -> int:
    """Reap managed guest processes left behind by a dead attempt —
    the fleet's pid-reuse-safe registry walk (guest_pids.jsonl +
    /proc/<pid>/environ identity check)."""
    from shadow_tpu.fleet import _reap_stale_guests

    return _reap_stale_guests(Path(data_dir))


def _is_recoverable(exc, sharded: bool) -> bool:
    if isinstance(exc, (ChaosFailure, GuestStallError)):
        return True
    if sharded:
        from shadow_tpu.parallel.shards import _PeerDied, _ShardError

        # _ShardError covers every worker death: SIGKILL (pipe EOF),
        # wedge (peer stall detection), and in-worker exceptions
        return isinstance(exc, (_ShardError, _PeerDied))
    return False


def run_supervised(cfg, mirror_log: bool = True, resume_from=None) -> dict:
    """Run ``cfg`` under supervision: bounded automatic restarts from the
    newest complete checkpoint on recoverable failure. Returns the run
    summary with a ``supervisor`` key (attempts, restart records with
    per-restart MTTR); raises SupervisorGaveUp (crash_report.json on
    disk) when the budget is exhausted."""
    opts = supervise_options(cfg)
    max_restarts = int(opts.get("max_restarts", DEFAULT_MAX_RESTARTS))
    backoff = float(opts.get("backoff", DEFAULT_BACKOFF_S))
    data_dir = Path(cfg.general.data_directory)
    sharded = cfg.general.sim_shards > 1
    restarts: list = []
    attempt = 0
    resume = resume_from
    while True:
        runner = None
        try:
            if sharded:
                from shadow_tpu.parallel.shards import ShardedRun

                runner = ShardedRun(cfg, mirror_log=mirror_log,
                                    resume_from=resume)
            else:
                if resume is not None:
                    from shadow_tpu import checkpoint as _ckpt

                    runner, resume_at = _ckpt.load_checkpoint(
                        resume, cfg, mirror_log=mirror_log)
                else:
                    from shadow_tpu.core.controller import Controller

                    runner = Controller(cfg, mirror_log=mirror_log)
                    resume_at = None
                runner._supervised = True
                runner.t_first_ready = _walltime.monotonic()
            if restarts and getattr(runner, "live", None) is not None:
                rec = {k: v for k, v in restarts[-1].items()
                       if not k.startswith("_")}
                runner.live.publish(
                    {"type": "supervisor", "event": "restart", **rec})
            result = (runner.run() if sharded
                      else runner.run(resume_at=resume_at))
            _note_mttr(restarts, runner)
            result["supervisor"] = {
                "attempts": attempt + 1,
                "max_restarts": max_restarts,
                "restarts": [{k: v for k, v in r.items()
                              if not k.startswith("_")} for r in restarts],
            }
            return result
        except KeyboardInterrupt:
            raise  # the operator's interrupt is never "recovered"
        except Exception as exc:
            t_detect = _walltime.monotonic()
            _note_mttr(restarts, runner)
            attempt += 1
            reason = f"{type(exc).__name__}: {exc}"
            recoverable = _is_recoverable(exc, sharded)
            reaped = _reap_guests(data_dir)
            if reaped:
                print(f"supervisor: reaped {reaped} stale guest "
                      f"process(es)", file=sys.stderr, flush=True)
            if not recoverable or attempt > max_restarts:
                why = ("failure is not recoverable" if not recoverable
                       else f"restart budget exhausted "
                            f"({max_restarts} restart(s))")
                path = write_crash_report(
                    data_dir, f"{why}: {reason}", exc=exc, attempt=attempt,
                    max_restarts=max_restarts,
                    extra={"restarts": [
                        {k: v for k, v in r.items()
                         if not k.startswith("_")} for r in restarts]})
                raise SupervisorGaveUp(
                    f"supervisor gave up after {attempt} attempt(s): "
                    f"{why} — {reason} (report: {path})") from exc
            resume = find_restart_checkpoint(cfg)
            if resume is not None:
                rounds, t0, managed = _restart_boundary(resume)
                if managed:
                    # managed re-execution restore: run(resume_at=None)
                    # regenerates every stream fresh from round 0 — there
                    # is nothing to roll back
                    pass
                else:
                    rollback_streams(cfg, rounds, t0)
                where = f"checkpoint {resume} (round {rounds})"
            else:
                where = "scratch (no complete checkpoint)"
            wait = backoff * (2 ** (attempt - 1))
            print(f"supervisor: attempt {attempt}/{max_restarts} — "
                  f"{reason}; restarting from {where} in {wait:.1f}s",
                  file=sys.stderr, flush=True)
            restarts.append({"attempt": attempt, "reason": reason,
                             "resume": resume or "scratch",
                             "_t_detect": t_detect})
            if wait > 0:
                _walltime.sleep(min(wait, 60.0))


def _note_mttr(restarts: list, runner) -> None:
    """Record mean-time-to-recovery for the newest restart: wall seconds
    from failure detection to the recovered attempt reaching ready."""
    if not restarts or runner is None:
        return
    rec = restarts[-1]
    tfr = getattr(runner, "t_first_ready", None)
    if tfr is not None and "mttr_s" not in rec and "_t_detect" in rec:
        rec["mttr_s"] = round(max(0.0, tfr - rec["_t_detect"]), 3)
