"""Deterministic random number generation.

The reference seeds one master RNG from config and derives a per-host RNG so
that host behavior is independent of scheduling order (SURVEY.md §2 "Host",
§2 parallelism item 5).  We use numpy's Philox counter-based generator keyed
by (master_seed, host_id): per-host streams are statistically independent and
reproducible regardless of which worker or round touches them.

Device-side packet-loss sampling does NOT use these streams — it uses JAX
threefry keyed on (seed, round, element index) so the CPU and TPU network
backends can reproduce each other bit-for-bit (SURVEY.md §7 phase 2).
"""

from __future__ import annotations

import numpy as np


def master_rng(seed: int) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(key=np.uint64(seed)))


def host_rng(seed: int, host_id: int) -> np.random.Generator:
    """Per-host deterministic stream, independent of scheduling order."""
    return np.random.Generator(
        np.random.Philox(key=(np.uint64(seed) << np.uint64(16)) ^ np.uint64(host_id))
    )


def fault_rng(seed: int, stream: int) -> np.random.Generator:
    """Counter-based stream for fault-timeline draws (shadow_tpu/faults.py
    churn schedules), keyed on (master seed, stream id) in a domain separate
    from the host streams. Schedules are materialized once at startup from
    these draws, so they are reproducible and independent of scheduler
    policy, data plane, and event interleaving."""
    key = ((np.uint64(seed) << np.uint64(16)) ^ np.uint64(stream)
           ^ np.uint64(0xFA17 << 48))
    return np.random.Generator(np.random.Philox(key=key))
