"""Core runtime: simulated time, events, RNG, scheduler, controller.

Mirrors the responsibilities of the reference's ``src/main/core`` layer
(SURVEY.md §1 layer 3-4) with a TPU-first data plane.
"""
