"""Scheduler policies: parallel host execution within rounds.

Mirrors the reference's scheduler crate (SURVEY.md §1 layer 4, §2
"Scheduler (policies)") with three policies behind one interface:

- ``thread_per_core``: a fixed pool of worker threads; hosts are sharded
  across them each round (the reference's CPU baseline policy).
- ``thread_per_host``: one persistent thread per host, parked between
  rounds (cache-locality policy for small host counts).
- ``tpu_batch``: hosts run on the main thread; the per-round network data
  plane runs as JAX kernels on the device (this package's reason to exist;
  see shadow_tpu/parallel/).

Correctness note: within a round, a host's events touch only that host's
state; cross-host effects flow exclusively through the engine at the round
barrier. So any assignment of hosts to threads yields identical results —
the determinism tests (tests/test_e2e_phase1.py) assert this across
policies. Multi-process sharding (shadow_tpu/parallel/shards.py) is the
same argument one level up: each shard worker builds its scheduler over
its OWNED host subset only (Controller._sched_hosts), and the id-modulo
partition of hosts across processes can no more change results than the
id-modulo partition across threads below — tests/test_shards.py asserts
byte-identity at any shard count, including under thread_per_core
inside the workers.

CPython's GIL means thread policies don't add real CPU parallelism for pure-
Python workloads; they exist for structural parity with the reference and
become genuinely parallel in phase 4 when hosts block on native managed-
process IPC (GIL released in ctypes/syscall waits, SURVEY.md §7 phase 4).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from shadow_tpu.core.time import SimTime


def _run_hosts(hosts, round_end: SimTime) -> int:
    """Run one round for a set of hosts. The inline heap peek matters: at
    10k+ hosts most queues are empty most rounds, and a Python call into
    run_events per (host, round) costs more than the whole round's real
    work (measured: ~30% of the gossip-10k wall). A cancelled head with an
    earlier timestamp makes the peek conservatively true — run_events then
    discards it correctly. Inside run_events the per-host inbox merges
    with the timer heap against a cached head (one identity check per hot
    row; host.py run_events), and the C engine's run_round applies the
    same two disciplines natively plus a cached sorted active-set
    snapshot — heap churn at 100k-host tor scale made both first-order
    costs (PR 5)."""
    n = 0
    for h in hosts:
        heap = h.equeue._heap
        if (heap and heap[0][0] < round_end) or h._inbox is not None:
            n += h.run_events(round_end)
    return n


class SerialScheduler:
    """Hosts executed in host-id order on the calling thread."""

    name = "serial"

    def __init__(self, hosts: Sequence) -> None:
        self.hosts = hosts

    def run_round(self, round_end: SimTime, active: Sequence = None) -> int:
        return _run_hosts(self.hosts if active is None else active, round_end)

    def shutdown(self) -> None:
        pass


class ThreadPerCoreScheduler:
    """Fixed worker pool; hosts chunked across it each round."""

    name = "thread_per_core"

    def __init__(self, hosts: Sequence, nthreads: int) -> None:
        self.hosts = hosts
        self.nthreads = max(1, nthreads)
        self.pool = ThreadPoolExecutor(
            max_workers=self.nthreads, thread_name_prefix="shadow-worker"
        )
        # static host -> shard assignment (reference: fixed sharding keeps
        # determinism trivially; work stealing is unnecessary because the
        # engine barrier dominates imbalance at realistic host counts)
        self.shards = [list(hosts[i :: self.nthreads]) for i in range(self.nthreads)]

    def _run_shard(self, shard, round_end: SimTime) -> int:
        return _run_hosts(shard, round_end)

    def run_round(self, round_end: SimTime, active: Sequence = None) -> int:
        if active is not None:
            # shard only the hosts that can have work this round; a single
            # populated shard runs inline (no pool round trip)
            shards = [[] for _ in range(self.nthreads)]
            for h in active:
                shards[h.id % self.nthreads].append(h)
            shards = [s for s in shards if s]
            if not shards:
                return 0
            if len(shards) == 1:
                return _run_hosts(shards[0], round_end)
        else:
            shards = [s for s in self.shards if s]
        futs = [self.pool.submit(self._run_shard, s, round_end) for s in shards]
        return sum(f.result() for f in futs)

    def shutdown(self) -> None:
        self.pool.shutdown(wait=False)


class ThreadPerHostScheduler:
    """One persistent parked thread per host, woken each round."""

    name = "thread_per_host"

    def __init__(self, hosts: Sequence) -> None:
        self.hosts = hosts
        self._round_end: SimTime = 0
        self._go = [threading.Event() for _ in hosts]
        self._done = [threading.Event() for _ in hosts]
        self._stop = False
        self._counts = [0] * len(hosts)
        self._errors: list = [None] * len(hosts)
        self._index = {h.id: i for i, h in enumerate(hosts)}
        self._threads = [
            threading.Thread(
                target=self._loop, args=(i,), name=f"shadow-host-{h.name}", daemon=True
            )
            for i, h in enumerate(hosts)
        ]
        for t in self._threads:
            t.start()

    def _loop(self, i: int) -> None:
        while True:
            self._go[i].wait()
            self._go[i].clear()
            if self._stop:
                return
            try:
                self._counts[i] = _run_hosts((self.hosts[i],), self._round_end)
            except BaseException as exc:  # propagate instead of hanging
                self._errors[i] = exc
            self._done[i].set()

    def run_round(self, round_end: SimTime, active: Sequence = None) -> int:
        idx = (list(range(len(self.hosts))) if active is None
               else [self._index[h.id] for h in active])
        self._round_end = round_end
        for i in idx:
            self._errors[i] = None
            self._counts[i] = 0
            self._go[i].set()
        total = 0
        for i in idx:
            self._done[i].wait()
            self._done[i].clear()
            if self._errors[i] is not None:
                raise self._errors[i]
            total += self._counts[i]
        return total

    def shutdown(self) -> None:
        self._stop = True
        for ev in self._go:
            ev.set()


def make_scheduler(policy: str, hosts: Sequence, parallelism: int):
    if policy == "thread_per_core":
        import os

        n = parallelism if parallelism > 0 else (os.cpu_count() or 1)
        return ThreadPerCoreScheduler(hosts, n)
    if policy == "thread_per_host":
        if len(hosts) > 2048:
            raise ValueError(
                f"thread_per_host with {len(hosts)} hosts would create too many "
                "OS threads; use thread_per_core or tpu_batch"
            )
        return ThreadPerHostScheduler(hosts)
    if policy in ("tpu_batch", "tpu_mesh"):
        # host events run serially on the main thread; the data plane is
        # on the device. Event execution overlaps device work through
        # dispatch asynchrony, not Python threads: the columnar plane
        # dispatches ONE fused program per multi-round window (two
        # in-flight windows, deferred readbacks at causal deadlines —
        # network/devroute.py), so the device computes window N while
        # this thread runs the events and barriers of window N+1.
        return SerialScheduler(hosts)
    raise ValueError(f"unknown scheduler policy {policy!r}")
