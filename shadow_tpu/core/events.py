"""Per-host event queues.

Mirrors the reference's ``src/main/core/work`` event machinery (SURVEY.md §2
"Event queue / events"): an event is (time, task) on a specific host; each
host owns a priority queue; determinism comes from a total order on
(time, host_id, sequence-number-of-insertion).

Events never move between hosts: cross-host interactions (packets) are always
scheduled onto the destination host's queue at a time >= one round ahead, the
conservative-PDES invariant (SURVEY.md §2 "Parallelism strategies" item 4).
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional

from shadow_tpu.core.time import SimTime, T_NEVER


#: heap ordering bands for same-time ties: network events (arrivals, loss
#: notifications) execute before application events at the same instant.
#: Network events carry an explicit ``key`` assigned at the emission barrier
#: in canonical batch order, which makes the total event order independent
#: of WHEN the engine physically inserts them — the deferred device-readback
#: path (shadow_tpu/network/engine.py) inserts arrivals rounds later than
#: the inline numpy path, yet both yield the same execution order.
BAND_NET = 0
BAND_APP = 1
#: fault-subsystem band (shadow_tpu/faults.py): host lifecycle transitions
#: (process respawn after a reboot) execute before any network arrival at
#: the same instant, so a rebooted host's listeners exist before the first
#: same-tick SYN — identically under every scheduler policy.
BAND_FAULT = -1


class EventQueue:
    """Min-heap of (time, band, key, seq, task) for one host.

    ``seq`` is a per-queue monotonically increasing insertion counter; it
    breaks ties deterministically (FIFO among same-time events) and makes the
    heap ordering total without comparing task callables. ``band``/``key``
    impose a canonical order on same-time ties that is stable across
    scheduler policies and data-plane backends (see BAND_NET above).
    """

    __slots__ = ("_heap", "_seq", "_live", "_cancelled", "on_first")

    def __init__(self) -> None:
        self._heap: list[tuple[SimTime, int, int, int, Callable[[], None]]] = []
        self._seq = 0
        self._live: set[int] = set()  # seqs pushed and not yet popped
        self._cancelled: set[int] = set()
        #: fired on the empty->nonempty transition; the controller uses it
        #: to maintain the active-host set (per-round work is then O(active
        #: hosts), not O(all hosts) — the difference at 10k+ mostly-idle
        #: hosts)
        self.on_first = None

    def push(self, time: SimTime, task: Callable[[], None],
             band: int = BAND_APP, key: int = -1) -> int:
        """Schedule ``task`` at ``time``; returns a handle usable with cancel()."""
        seq = self._seq
        self._seq += 1
        was_empty = not self._heap
        heapq.heappush(self._heap, (time, band, key if key >= 0 else seq, seq, task))
        self._live.add(seq)
        if was_empty and self.on_first is not None:
            self.on_first()
        return seq

    def cancel(self, handle: int) -> None:
        """Lazily cancel a scheduled event (e.g. a disarmed timer). A no-op
        if the event already ran — cancelling a fired timer is the normal
        disarm pattern and must not corrupt the queue."""
        if handle in self._live:
            self._cancelled.add(handle)

    def clear_band(self, band: int) -> int:
        """Lazily cancel every pending event in ``band`` (host crash: app
        timers die with the host, while BAND_NET arrivals stay queued and
        are discarded at delivery — keeping event counts identical to the
        columnar plane, whose resolved arrivals live outside the heap).
        Returns the number of events cancelled."""
        n = 0
        for entry in self._heap:
            seq = entry[3]
            if (entry[1] == band and seq in self._live
                    and seq not in self._cancelled):
                self._cancelled.add(seq)
                n += 1
        return n

    def next_time(self) -> SimTime:
        """Time of the earliest pending event, or T_NEVER if empty."""
        self._drop_cancelled_head()
        return self._heap[0][0] if self._heap else T_NEVER

    def head(self):
        """The earliest pending entry (time, band, key, seq, task), or
        None — the columnar plane's inbox-merge peek."""
        self._drop_cancelled_head()
        return self._heap[0] if self._heap else None

    def pop_until(self, end: SimTime) -> Optional[tuple[SimTime, Callable[[], None]]]:
        """Pop the earliest event with time < end, else None."""
        self._drop_cancelled_head()
        if self._heap and self._heap[0][0] < end:
            time, _band, _key, seq, task = heapq.heappop(self._heap)
            self._live.discard(seq)
            return time, task
        return None

    def _drop_cancelled_head(self) -> None:
        while self._heap and self._heap[0][3] in self._cancelled:
            seq = heapq.heappop(self._heap)[3]
            self._cancelled.discard(seq)
            self._live.discard(seq)

    def live_times(self, exclude_band: Optional[int] = None) -> list:
        """Sorted (time, band) of every pending non-cancelled event,
        optionally excluding one band — determinism-sentinel fodder
        (shadow_tpu/checkpoint.py): the multiset of pending timers is
        plane-independent once BAND_NET is excluded (the per-unit plane
        queues in-flight arrivals in the heap; the columnar plane holds
        them in its pending store)."""
        out = [(e[0], e[1]) for e in self._heap
               if e[3] not in self._cancelled and e[1] != exclude_band]
        out.sort()
        return out

    def live_count(self, exclude_band: Optional[int] = None) -> int:
        """Count of pending non-cancelled events outside ``exclude_band``
        — live_times without materializing the sorted list (the telemetry
        sampler calls this once per host per sample)."""
        cancelled = self._cancelled
        n = 0
        for e in self._heap:
            if e[1] != exclude_band and e[3] not in cancelled:
                n += 1
        return n

    def __len__(self) -> int:
        return len(self._heap) - len(self._cancelled)
