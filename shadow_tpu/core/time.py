"""Simulated time types.

The reference keeps two clocks (SURVEY.md §2 "Timers & time"):

- ``SimulationTime``: nanoseconds since the simulation started.
- ``EmulatedTime``: nanoseconds since the UNIX epoch as seen by managed code;
  the simulation boots at a fixed, deterministic wall-clock instant so that
  applications reading the clock see identical values across runs.

We model both as plain ``int`` nanoseconds (Python ints are arbitrary
precision, so no overflow concerns CPU-side).  Device-side kernels use int32
nanoseconds *relative to the current round start* so that no int64 math is
needed on the TPU (see shadow_tpu/ops/propagate.py).
"""

from __future__ import annotations

import re

# Type aliases: both are int nanoseconds. Kept distinct in signatures for
# readability; there is deliberately no class wrapper on the hot path.
SimTime = int  # ns since simulation start
EmulatedTime = int  # ns since UNIX epoch

NS_PER_US = 1_000
NS_PER_MS = 1_000_000
NS_PER_SEC = 1_000_000_000

#: The simulation boots at 2000-01-01 00:00:00 UTC, a deterministic instant
#: (946684800 s since the epoch). Managed code reading the clock sees
#: EMULATED_EPOCH + sim_time.
EMULATED_EPOCH: EmulatedTime = 946_684_800 * NS_PER_SEC

#: Sentinel "never" time (far future, still fits comfortably in int64).
T_NEVER: SimTime = (1 << 62)


def emulated(sim_time: SimTime) -> EmulatedTime:
    """Convert simulation-relative time to the emulated wall clock."""
    return EMULATED_EPOCH + sim_time


def parse_time(value) -> SimTime:
    """Parse a config time value into ns.

    Accepts ints (seconds, matching the reference YAML's bare-number
    convention for ``stop_time``), floats (seconds), or strings with units:
    "10 ms", "1 min", "30s", "500 us", "100 ns", "1 h".
    """
    if isinstance(value, bool):
        raise ValueError(f"not a time value: {value!r}")
    if isinstance(value, int):
        return value * NS_PER_SEC
    if isinstance(value, float):
        return int(round(value * NS_PER_SEC))
    if not isinstance(value, str):
        raise ValueError(f"not a time value: {value!r}")

    s = value.strip().lower()
    m = re.fullmatch(r"([0-9.eE+-]+)\s*([a-zμ]*)", s)
    if m is None:
        raise ValueError(f"cannot parse time value {value!r}")
    num, unit = m.group(1), m.group(2)
    if unit.endswith("s") and unit not in ("s", "ns", "us", "μs", "ms"):
        unit = unit[:-1]  # strip plural: "seconds" -> "second"
    units = {
        "": NS_PER_SEC,  # bare numeric string: seconds
        "ns": 1, "nanosecond": 1,
        "us": NS_PER_US, "μs": NS_PER_US, "microsecond": NS_PER_US,
        "ms": NS_PER_MS, "msec": NS_PER_MS, "millisecond": NS_PER_MS,
        "s": NS_PER_SEC, "sec": NS_PER_SEC, "second": NS_PER_SEC,
        "m": 60 * NS_PER_SEC, "min": 60 * NS_PER_SEC, "minute": 60 * NS_PER_SEC,
        "h": 3600 * NS_PER_SEC, "hr": 3600 * NS_PER_SEC, "hour": 3600 * NS_PER_SEC,
    }
    if unit not in units:
        raise ValueError(f"unknown time unit in {value!r}")
    return int(round(float(num) * units[unit]))


def format_time(t: SimTime) -> str:
    """Human-readable rendering of a sim time (for logs)."""
    if t >= NS_PER_SEC:
        return f"{t / NS_PER_SEC:.6f}s"
    if t >= NS_PER_MS:
        return f"{t / NS_PER_MS:.3f}ms"
    if t >= NS_PER_US:
        return f"{t / NS_PER_US:.3f}us"
    return f"{t}ns"
