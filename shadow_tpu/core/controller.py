"""The Controller: owns and drives one simulation.

Reference analog: ``Controller::run()`` -> ``Manager::run()`` -> round loop
(SURVEY.md §3.1). Responsibilities: load the topology, compute the
conservative lookahead (round width = min edge latency, overridable with
``experimental.runahead``), build hosts and their processes, drive the
round loop through the configured scheduler policy, and produce the output
tree + end-of-run summary.

Round-loop structure (the conservative PDES core):

    while now < stop:
        engine.start_of_round(now, end)   # flush due draws, ingress refills
        scheduler.run_round(round_end)    # per-host events, parallel-safe
        engine.end_of_round(now, end)     # the barrier: batched data plane
        now = round_end (or skip ahead through provably idle time)

Skip-ahead: when a round executed zero events and the engine holds no
deferred ingress, the controller jumps the clock to the next scheduled
event (or the earliest event an in-flight draw batch can produce) — idle
sim time costs nothing (the closed-form token buckets account elapsed time
exactly, so results are identical to grinding through empty rounds).
"""

from __future__ import annotations

from functools import partial

import time as _walltime  # detlint: ok(wallclock): phase_wall + heartbeat wall costs
from pathlib import Path

import numpy as np

from shadow_tpu.config.schema import ConfigOptions
from shadow_tpu.core.scheduler import make_scheduler
from shadow_tpu.core.time import NS_PER_SEC, NS_PER_US, SimTime, T_NEVER, format_time
from shadow_tpu.host.host import Host
from shadow_tpu.host.process import PluginProcess
from shadow_tpu.network.engine import NetworkEngine
from shadow_tpu.network.fluid import NetParams
from shadow_tpu.network.graph import load_graph
from shadow_tpu.utils.counters import Counters
from shadow_tpu.utils.logging import SimLogger
from shadow_tpu.utils.units import parse_bandwidth

DEFAULT_BANDWIDTH = parse_bandwidth("1 Gbit")
#: rounds between explicit gc.collect() calls while auto-GC is suspended
_GC_EVERY_ROUNDS = 5000

#: run-summary keys that are wall-clock / routing telemetry rather than
#: simulation state — strip these when diffing summaries for determinism
#: (the single source of truth for tests and tools/ci.sh; WHICH windows
#: the device served legitimately varies run to run while output trees
#: stay bit-identical)
#: "sim_shards"/"shards" are the scale-out plane's run-shape telemetry
#: (parallel/shards.py): which partition executed a simulation is as
#: immaterial to its results as which windows the device served
VOLATILE_SUMMARY_KEYS = ("wall_seconds", "sim_sec_per_wall_sec",
                         "phase_wall", "max_rss_mb", "device",
                         "device_windows_dispatched", "sim_shards",
                         "shards", "device_transport",
                         "device_transport_engaged", "supervisor")


class Controller:
    #: multi-process sharding (shadow_tpu/parallel/shards.py): the shard
    #: worker subclass overrides these INSTANCE attrs before calling
    #: __init__; the base controller owns every host. owns() gates which
    #: hosts get processes, scheduler slots, fault lifecycle transitions,
    #: telemetry columns, and digest fingerprints.
    shard_id = 0
    n_shards = 1

    #: live-operations plane (shadow_tpu/live.py). Class-level defaults
    #: keep checkpoints from before the plane restorable: an old snapshot
    #: simply inherits "no live state". ``live`` (the endpoint server) and
    #: ``on_stop_round`` (the time-travel inspector hook) are runtime-only
    #: and nulled by __getstate__.
    live = None
    stop_after_round = None
    on_stop_round = None
    _ckpt_now = False
    _live_paused = False
    _live_seq = 0
    _replay_cmds = ()
    _replay_idx = 0

    #: supervision plane (shadow_tpu/supervise.py). ``_supervised`` is
    #: set by run_supervised: guest-watchdog stalls then escalate to the
    #: supervisor (GuestStallError via ``_stall_escalate`` at the next
    #: boundary) instead of the unsupervised host_down conversion.
    #: ``_chaos`` is the env-armed fault injector (wall-clock plane;
    #: class defaults keep old checkpoints restorable).
    _supervised = False
    _stall_escalate = None
    _chaos = None

    def owns(self, hid: int) -> bool:
        return self.n_shards == 1 or hid % self.n_shards == self.shard_id

    def _sched_hosts(self) -> list:
        """The hosts this controller's scheduler executes: all of them,
        or the owned subset on a shard worker (a scheduler policy's
        host→thread placement cannot change results, so neither can the
        shard partition — same argument, one level up)."""
        if self.n_shards == 1:
            return self.hosts
        return [h for h in self.hosts if self.owns(h.id)]

    def _log_name(self) -> str:
        return "shadow.log"

    def __init__(self, cfg: ConfigOptions, mirror_log: bool = True) -> None:
        self.cfg = cfg
        if cfg.general.checkpoint_every:
            # fail at build, not at the first checkpoint boundary 40
            # minutes in (shadow_tpu/checkpoint.py owns the policy)
            from shadow_tpu.checkpoint import validate_config_checkpointable

            validate_config_checkpointable(cfg)
        self.data_dir = Path(cfg.general.data_directory)
        self.log = SimLogger(cfg.general.log_level,
                             self.data_dir / self._log_name(),
                             mirror_stderr=mirror_log)
        self.graph = load_graph(cfg.network["graph"])

        # conservative lookahead: round width <= min latency keeps every
        # cross-host arrival at least one round in the future (SURVEY.md §2
        # parallelism item 4). An explicit runahead overrides (arrivals then
        # clamp to the next round boundary — coarser, faster, still causal).
        w = self.graph.min_latency_ns
        if cfg.experimental.runahead is not None:
            w = cfg.experimental.runahead
        self.round_ns: SimTime = max(int(w), NS_PER_US)

        self.hosts: list[Host] = []
        self._by_name: dict[str, int] = {}
        self._by_ip: dict[str, int] = {}
        rate_up = np.zeros(len(cfg.hosts), dtype=np.int64)
        rate_down = np.zeros(len(cfg.hosts), dtype=np.int64)
        host_node = np.zeros(len(cfg.hosts), dtype=np.int32)
        for hid, hopts in enumerate(cfg.hosts):
            node_gml_id = hopts.network_node_id
            if node_gml_id not in self.graph.node_id_map:
                raise ValueError(
                    f"host {hopts.name!r}: network_node_id {node_gml_id} not in graph"
                )
            node = self.graph.node_id_map[node_gml_id]
            defaults = self.graph.node_defaults[node]
            up = hopts.bandwidth_up or defaults.bandwidth_up
            down = hopts.bandwidth_down or defaults.bandwidth_down
            if up is None or down is None:
                self.log.warning(
                    f"host {hopts.name!r}: no bandwidth configured on host or "
                    f"graph node; defaulting to 1 Gbit"
                )
                up = up or DEFAULT_BANDWIDTH
                down = down or DEFAULT_BANDWIDTH
            ip = hopts.ip_addr or _default_ip(hid)
            host = Host(hid, hopts.name, ip, node, cfg.general.seed, self,
                        cc=hopts.congestion_control)
            host.log_level = hopts.log_level or cfg.general.log_level
            if hopts.pcap_enabled:
                from shadow_tpu.utils.pcap import PcapWriter

                d = self.data_dir / "hosts" / hopts.name
                d.mkdir(parents=True, exist_ok=True)
                host.pcap = PcapWriter(d / f"{hopts.name}.pcap",
                                       hopts.pcap_capture_size)
            self.hosts.append(host)
            self._by_name[hopts.name] = hid
            self._by_ip[ip] = hid
            rate_up[hid] = up
            rate_down[hid] = down
            host_node[hid] = node

        from shadow_tpu.network.fluid import MTU

        #: fault injection (shadow_tpu/faults.py) runs on EVERY plane,
        #: including the C engine: the injector mutates the effective
        #: latency/loss/rate matrices and bucket arrays IN PLACE, and the
        #: C core holds raw pointers into those same arrays, so a
        #: transition is visible to all planes atomically at the next
        #: barrier. Crash/reboot teardown has explicit C hooks
        #: (Core.host_crash/host_boot); cross-policy and C-on/off
        #: determinism under churn is asserted by tests/test_faults.py.
        #: Checkpoints and the determinism sentinel likewise no longer
        #: force the Python planes — C state exports to plain Python
        #: structures for the pickler, and the digest walk reads only
        #: plane-independent observables the C twin exposes identically.
        faults_cfg = cfg.faults
        have_faults = faults_cfg is not None and (
            faults_cfg.events or faults_cfg.churn)

        params = NetParams.build(
            host_node=host_node,
            rate_up=rate_up,
            rate_down=rate_down,
            latency_ns=self.graph.latency_ns,
            reliability=self.graph.reliability,
            seed=cfg.general.seed,
            round_ns=self.round_ns,
            max_unit=cfg.experimental.unit_mtus * MTU,
        )
        policy = cfg.experimental.scheduler_policy
        backend = {"tpu_batch": "tpu", "tpu_mesh": "mesh"}.get(policy, "numpy")
        # active-host tracking: per-round work is O(hosts with pending
        # events), not O(all hosts) — the difference at 10k mostly-idle
        # hosts. A host (re)activates on its queue's empty->nonempty edge.
        self._active: set = set()  # host IDS (ints sort at C speed)
        if backend in ("tpu", "mesh"):
            # the tpu policies run the array-native columnar plane
            # (network/colplane.py); thread policies keep the per-unit
            # plane as the reference-architecture baseline. Results are
            # bit-identical across planes (tests/test_colplane.py).
            from shadow_tpu.network.colplane import ColumnarPlane

            self.engine = ColumnarPlane(
                self.graph, params, self.hosts, self.round_ns,
                backend=backend, tpu_options=cfg.experimental,
                bootstrap_end=cfg.general.bootstrap_end_time,
            )
            self.engine.activate = self._active.add
        else:
            self.engine = NetworkEngine(
                self.graph, params, self.hosts, self.round_ns,
                backend=backend, tpu_options=cfg.experimental,
                bootstrap_end=cfg.general.bootstrap_end_time,
            )
        for h in self.hosts:
            h.engine = self.engine
            h.equeue.on_first = partial(self._active.add, h.id)
        self.scheduler = make_scheduler(policy, self._sched_hosts(),
                                        cfg.general.parallelism)
        # C engine (native colcore): owns the per-round host loop and
        # maintains the active set directly
        self._c_core = getattr(self.engine, "_c", None)
        if self._c_core is not None:
            self._c_core.bind_active(self._active)
            # route activations through the C core so its sorted
            # active-set snapshot can merge new members incrementally
            # instead of re-snapshotting the whole set every round
            act = self._c_core.activate
            self.engine.activate = act
            for h in self.hosts:
                h.equeue.on_first = partial(act, h.id)

        # processes: pyapp: plugins run in-process; any other path is a real
        # executable run under the native preload shim (SURVEY.md §7 phase 4)
        # (sharded workers build processes only for their OWNED hosts — a
        # non-owned host is pure topology here, its simulation lives on
        # the owning shard)
        self.processes: list = []
        for host, hopts in zip(self.hosts, cfg.hosts):
            if not self.owns(host.id):
                continue
            for i, popts in enumerate(hopts.processes):
                if PluginProcess.is_plugin_path(popts.path):
                    proc = PluginProcess(host, popts, i)
                else:
                    from shadow_tpu.native.managed import ManagedProcess, _shim_lib

                    # fail fast at build time, not inside a scheduler event
                    if not Path(popts.path).is_file():
                        raise ValueError(
                            f"host {hopts.name!r}: managed executable "
                            f"{popts.path!r} does not exist")
                    if not _shim_lib().exists():
                        raise ValueError(
                            f"native shim {_shim_lib()} missing — build it "
                            f"first: make -C native")
                    proc = ManagedProcess(host, popts, i)
                host.processes.append(proc)
                self.processes.append(proc)
                host.schedule(popts.start_time, proc.spawn)
                if popts.shutdown_time is not None:
                    host.schedule(popts.shutdown_time, proc.shutdown)

        self.faults = None
        if have_faults:
            from shadow_tpu.faults import FaultInjector

            self.engine.faults_active = True
            for h in self.hosts:
                h.faults_active = True
            if self._c_core is not None:
                # the C core was built before this flag existed: enable
                # its per-host blackhole/teardown accounting and the
                # faults-gated stream recovery counters
                self._c_core.set_faults_active(True)
            self.faults = FaultInjector(self)
            self.log.info(
                f"fault timeline: {len(self.faults.actions)} transitions "
                f"({len(faults_cfg.events)} configured events, "
                f"{len(faults_cfg.churn)} churn groups)")

        #: deterministic simulation telemetry (shadow_tpu/telemetry/): a
        #: telemetry: section builds the collector; hosts carry a direct
        #: reference so flow records cost one attribute check when off.
        #: Unlike faults/checkpoint, telemetry does NOT force the Python
        #: planes — the samplers read only plane-independent observables
        #: (shared numpy arrays, folded C counters, endpoint getters that
        #: the C twin exposes), and the streams are asserted byte-identical
        #: with the C engine on and off (tests/test_telemetry.py).
        self.telemetry = None
        if cfg.telemetry is not None:
            from shadow_tpu.telemetry import TelemetryCollector

            self.telemetry = TelemetryCollector(cfg.telemetry)
            for h in self.hosts:
                h.telemetry = self.telemetry
            if self.faults is not None:
                self.faults.on_apply = self.telemetry.record_fault

        self.counters = Counters()
        self.rounds = 0
        self.events = 0
        self.wall_seconds = 0.0
        self._events_wall = 0.0  # scheduler.run_round wall (phase timing)
        self._ckpt_wall = 0.0  # save_checkpoint wall (phase timing)
        # checkpoint/restore + determinism sentinel (shadow_tpu/checkpoint.py)
        self.ckpt_every: SimTime = cfg.general.checkpoint_every or 0
        self.ckpt_dir = (Path(cfg.general.checkpoint_dir)
                         if cfg.general.checkpoint_dir
                         else self.data_dir / "checkpoints")
        self.digest_every = cfg.general.state_digest_every
        #: managed re-execution snapshots (checkpoint format v5): arm the
        #: per-guest observation journal whenever this run could write a
        #: snapshot (grid cadence, or live checkpoint_now via the
        #: endpoint) so every snapshot carries verifiable guest cursors.
        #: The journal is a pure side-plane recorder — it never feeds sim
        #: state — so SHADOW_TPU_GUEST_JOURNAL=1/0 may force it on or off
        #: (the bench's journaling-overhead A/B) without touching results.
        self._reexec_verify = None
        self.guest_journal_dir = None
        self._has_managed = any(
            not PluginProcess.is_plugin_path(p.path)
            for h in cfg.hosts for p in h.processes)
        if self._has_managed:
            import os as _os

            jr = _os.environ.get("SHADOW_TPU_GUEST_JOURNAL")  # detlint: ok(envread): side-plane artifact toggle
            if jr != "0" and (jr == "1" or self.ckpt_every
                              or cfg.general.live_endpoint):
                self.guest_journal_dir = self.data_dir / "guest_oplogs"
        #: set by the SIGINT/SIGTERM handler: the round loop finishes the
        #: current round, writes a final checkpoint (when enabled), and
        #: finalizes a valid partial summary instead of dying mid-round
        self._interrupt = None
        self._partial = False
        self._init_live()
        for w in cfg.warnings:
            self.log.warning(w)

    def _init_live(self) -> None:
        """Build the live-operations plane (shadow_tpu/live.py): load the
        replay command log and bind the endpoint. Both config keys are
        volatile — the plane is pure wall-clock; commands only touch sim
        state via the recorded commands.jsonl. Shard workers never bind:
        the parent owns the socket and feeds commands through the shard-0
        marker path so all workers apply them at the same round."""
        from shadow_tpu import live as _live

        gen = self.cfg.general
        self._replay_cmds = ()
        self._replay_idx = 0
        if gen.replay_commands:
            self._replay_cmds = tuple(
                _live.load_command_log(gen.replay_commands))
            self.log.info(
                f"replaying {len(self._replay_cmds)} recorded command(s) "
                f"from {gen.replay_commands}")
        self.live = None
        if gen.live_endpoint and self.n_shards == 1:
            self.live = _live.LiveServer(
                _live.resolve_endpoint(gen.live_endpoint, self.data_dir),
                log=self.log)

    # -- checkpoint/restore (shadow_tpu/checkpoint.py) --------------------
    def __getstate__(self):
        """Snapshot-time state: everything except runtime plumbing. The
        scheduler (worker threads) and the C core are rebuilt by
        _reattach_runtime on restore; both are result-transparent."""
        d = self.__dict__.copy()
        d["scheduler"] = None
        d["_c_core"] = None
        # live plane: the server, inspector hook, and replay cursor are
        # runtime plumbing rebuilt by _init_live from the RESUME
        # invocation's (volatile) config keys
        d["live"] = None
        d["on_stop_round"] = None
        d["_live_paused"] = False
        d["_replay_cmds"] = ()
        d["_replay_idx"] = 0
        return d

    def _reattach_runtime(self, mirror_log: bool = True) -> None:
        """Rebuild the runtime-only pieces after a checkpoint restore:
        output location, logger mirroring, scheduler threads, the device
        draw plane, and the C engine (honoring the resume invocation's
        ``experimental.native_colcore`` — a volatile config key).
        Everything simulation-semantic came back through the pickle; any
        checkpoint-restored C objects (endpoints, gossip states, relays)
        are bound to the fresh core via ``checkpoint.finish_colcore_adopt``."""
        from shadow_tpu.utils.logging import LEVELS

        cfg = self.cfg
        self.data_dir = Path(cfg.general.data_directory)
        self.log.path = self.data_dir / self._log_name()
        self.log.mirror = mirror_log
        # log_level is a volatile config key: honor the resume invocation's
        # value on the main log and on hosts without a per-host override
        self.log.level = LEVELS[cfg.general.log_level]
        for h, hopts in zip(self.hosts, cfg.hosts):
            h.log_level = hopts.log_level or cfg.general.log_level
        self.ckpt_every = cfg.general.checkpoint_every or 0
        self.ckpt_dir = (Path(cfg.general.checkpoint_dir)
                         if cfg.general.checkpoint_dir
                         else self.data_dir / "checkpoints")
        self.digest_every = cfg.general.state_digest_every
        self._init_live()
        self.scheduler = make_scheduler(
            cfg.experimental.scheduler_policy, self._sched_hosts(),
            cfg.general.parallelism)
        self.engine.reattach_device(cfg.experimental)
        # C engine: rebuild over the restored structures and REWIRE the
        # activation hooks — the pickled hooks may reference the dead
        # core's placeholder (checkpoint._DeadCoreHandle)
        self._c_core = None
        attach = getattr(self.engine, "attach_colcore", None)
        core = attach(cfg.experimental) if attach is not None else None
        if core is not None:
            self._c_core = core
            core.bind_active(self._active)
            act = core.activate
            self.engine.activate = act
            for h in self.hosts:
                h.equeue.on_first = partial(act, h.id)
            if self.faults is not None:
                core.set_faults_active(True)
        else:
            if hasattr(self.engine, "emitters"):  # columnar Python paths
                self.engine.activate = self._active.add
            for h in self.hosts:
                h.equeue.on_first = partial(self._active.add, h.id)
        from shadow_tpu import checkpoint as _ckpt

        attach_dt = getattr(self.engine, "attach_devtransport", None)
        if attach_dt is not None:
            # after attach_colcore: the transport engine yields to an
            # attached C core (experimental.device_transport is volatile
            # wall-clock policy, like native_colcore)
            attach_dt(cfg.experimental)
        _ckpt.finish_colcore_adopt(self)

    # -- managed re-execution restore (checkpoint format v5) --------------
    def guest_journal_cursors(self) -> dict:
        """Per-guest observation-journal cursors for a re-execution
        snapshot: ``{"host/proc": {"n": entries, "sha": running-hash}}``.
        Empty when journaling is off (no managed guests, or neither a
        checkpoint cadence nor a live endpoint armed the journal)."""
        out = {}
        for p in self.processes:
            j = getattr(p, "_journal", None)
            if j is not None:
                out[f"{p.host.name}/{p.name}"] = j.cursor()
        return out

    def note_guest_pid(self, proc) -> None:
        """Side-plane registry of live guest OS pids
        (``<data_dir>/guest_pids.jsonl``, one record per spawn/exec/fork).
        Never part of the determinism surface — fleet's ``--resume`` reads
        a dead run's registry to reap stale guests before re-running the
        seed (the pid is verified against the record's clock-page path in
        /proc/<pid>/environ first, so pid reuse cannot kill a stranger)."""
        import json as _json

        pid = proc.proc.pid if proc.proc is not None else proc.real_pid
        if pid is None:
            return
        # fork children borrow the parent's clock page; their environ
        # still carries the parent's SHADOW_TIME_SHM, so identity checks
        # use the nearest ancestor's page path
        shm, p = proc._time_path, proc
        while shm is None and getattr(p, "parent_proc", None) is not None:
            p = p.parent_proc
            shm = p._time_path
        rec = {"pid": int(pid), "host": proc.host.name, "proc": proc.name,
               "shm": str(shm) if shm else None}
        self.data_dir.mkdir(parents=True, exist_ok=True)
        with open(self.data_dir / "guest_pids.jsonl", "a") as f:
            f.write(_json.dumps(rec, sort_keys=True) + "\n")

    def _verify_reexec(self, now: SimTime) -> None:
        """The deterministic re-execution of a restored managed run has
        reached the snapshot boundary: verify the recomputed state digest
        and every guest's journal cursor against the checkpoint record.
        Any mismatch means the prefix did NOT reproduce the checkpointed
        run — fail by name instead of continuing a silently different
        simulation."""
        info, self._reexec_verify = self._reexec_verify, None
        from shadow_tpu.checkpoint import CheckpointError, state_digest

        if now != info["t"] or self.rounds != info["rounds"]:
            raise CheckpointError(
                f"re-execution diverged from {info['path']}: expected "
                f"round {info['rounds']} at sim {info['t']} ns, but the "
                f"round grid reached sim {now} ns at round {self.rounds} "
                f"— this environment does not reproduce the checkpointed "
                f"run")
        g, _hosts = state_digest(self, now)
        if g != info["digest"]:
            raise CheckpointError(
                f"re-execution diverged from {info['path']}: state digest "
                f"at round {self.rounds} is {g[:16]}, checkpoint recorded "
                f"{info['digest'][:16]} — bisect with "
                f"tools/bisect_divergence.py against the original "
                f"state_digests.jsonl")
        want = info.get("cursors") or {}
        cur = self.guest_journal_cursors()
        if want and cur != want:
            bad = sorted(k for k in set(want) | set(cur)
                         if want.get(k) != cur.get(k))
            raise CheckpointError(
                f"re-execution diverged from {info['path']}: guest "
                f"journal cursor mismatch for {bad} — the re-executed "
                f"guests did not observe the recorded syscall stream")
        self.log.info(
            f"re-execution reached the snapshot boundary (round "
            f"{self.rounds}, sim {format_time(now)}): state digest and "
            f"{len(want)} guest journal cursor(s) verified; continuing")

    def _on_signal(self, signum, frame) -> None:
        """SIGINT/SIGTERM: request a graceful stop at the next round
        boundary. A second signal aborts immediately (the operator means
        it)."""
        import signal as _signal

        if self._interrupt is not None:
            raise KeyboardInterrupt
        self._interrupt = _signal.Signals(signum).name

    # -- naming -----------------------------------------------------------
    def resolve(self, name_or_ip) -> int:
        if isinstance(name_or_ip, int):
            return name_or_ip
        hid = self._by_name.get(name_or_ip)
        if hid is None:
            hid = self._by_ip.get(name_or_ip)
        if hid is None:
            raise KeyError(f"unknown host {name_or_ip!r}")
        return hid

    # -- main loop --------------------------------------------------------
    def run(self, resume_at: SimTime = None) -> dict:
        """Drive the simulation to stop_time. ``resume_at`` (set by
        checkpoint.load_checkpoint) re-enters the round loop at a saved
        round boundary; all loop-carried state (engine, queues, fault
        cursor, active set, counters) came back through the snapshot, so
        the continuation is byte-identical to the uninterrupted run."""
        cfg = self.cfg
        stop = cfg.general.stop_time
        w = self.round_ns
        now: SimTime = resume_at if resume_at is not None else 0
        self.log.info(
            f"simulation {'resuming' if resume_at is not None else 'starting'}: "
            f"{len(self.hosts)} hosts, "
            f"{self.graph.n_nodes} graph nodes, round width {format_time(w)}, "
            f"policy {cfg.experimental.scheduler_policy}, stop {format_time(stop)}"
        )
        hb_interval = cfg.general.heartbeat_interval
        next_hb = ((now // hb_interval) + 1) * hb_interval \
            if hb_interval else T_NEVER
        prog_step = max(stop // 100, 1)
        next_prog = now + prog_step if cfg.general.progress else T_NEVER
        ck_every = self.ckpt_every
        dig = self.digest_every
        _ckpt = None
        if ck_every or dig or self.live is not None or self._replay_cmds:
            # the live plane needs the checkpoint module for the
            # checkpoint_now command even when grid checkpointing is off
            from shadow_tpu import checkpoint as _ckpt
        if (self.live is not None or cfg.general.replay_commands) \
                and resume_at is None:
            # fresh run: a stale command log would concatenate with this
            # run's records and break replay (resumes keep appending — the
            # continuation of one log, same discipline as the digests)
            from shadow_tpu import live as _live
            _live.command_log_path(self.data_dir).unlink(missing_ok=True)
        if resume_at is not None and self._replay_cmds:
            # commands at or before the snapshot boundary are already in
            # the restored state (the command hook runs before the
            # checkpoint write at a shared boundary): skip them without
            # re-applying or re-logging
            while (self._replay_idx < len(self._replay_cmds)
                   and self._replay_cmds[self._replay_idx]["t"] <= resume_at):
                self._replay_idx += 1
        if dig and resume_at is None:
            # fresh run: a stale sentinel stream from a previous run into
            # this data_directory would concatenate and confuse
            # tools/bisect_divergence.py (resumes keep appending — the
            # continuation of one stream)
            (self.data_dir / _ckpt.DIGEST_FILE).unlink(missing_ok=True)
        if resume_at is None and self._has_managed:
            # fresh-run discipline for the managed side planes: stale
            # guest journals or a dead run's pid registry must not
            # concatenate with this run's records (a re-execution restore
            # is a fresh run here — its artifacts regenerate 0..end, which
            # is exactly what makes them comparable to the originals)
            (self.data_dir / "guest_pids.jsonl").unlink(missing_ok=True)
            if self.guest_journal_dir is not None:
                import shutil as _shutil

                _shutil.rmtree(self.guest_journal_dir, ignore_errors=True)
        tel = self.telemetry
        if tel is not None and resume_at is None:
            # same discipline for the telemetry streams: fresh runs
            # truncate + write the meta record; resumes keep appending
            tel.start_fresh(self)
        next_ckpt = ((now // ck_every) + 1) * ck_every if ck_every \
            else T_NEVER
        # graceful shutdown: SIGINT/SIGTERM finish the current round, write
        # a final checkpoint (when enabled), and produce a valid partial
        # summary (main thread only — signals cannot be hooked elsewhere)
        import signal as _signal
        import threading as _threading

        self._partial = False
        self._interrupt = None  # a resumed final-checkpoint carries the
        #                         old signal name; this run starts clean
        installed = {}
        if _threading.current_thread() is _threading.main_thread():
            for s in (_signal.SIGINT, _signal.SIGTERM):
                try:
                    installed[s] = _signal.signal(s, self._on_signal)
                except (ValueError, OSError):
                    pass
        # the round loop allocates millions of short-lived objects (units,
        # arrival closures, heap entries); generational GC scanning them
        # costs ~40% of wall at 10k-host scale (measured, gossip config).
        # Collect at fixed round intervals instead — reference cycles (e.g.
        # endpoint<->sender) from closed connections still get reclaimed.
        import gc as _gc

        gc_was_enabled = _gc.isenabled()
        _gc.disable()
        next_gc = _GC_EVERY_ROUNDS
        # chaos harness (shadow_tpu/supervise.py): deterministic-round
        # fault injection, armed only through the environment — one dict
        # probe per run when off, one int compare per round when on
        import os as _os

        if _os.environ.get("SHADOW_TPU_CHAOS"):
            from shadow_tpu.supervise import ChaosInjector

            self._chaos = ChaosInjector.from_env(
                self.data_dir, shard=self.shard_id, in_process=True)
        t0 = _walltime.perf_counter()
        dyn = cfg.experimental.use_dynamic_runahead
        faults = self.faults
        try:
            now = self._round_loop(now, stop, w, dyn, faults, next_hb,
                                   hb_interval, next_prog, prog_step,
                                   next_gc, next_ckpt, ck_every, dig,
                                   _ckpt, tel, t0)
        finally:
            for s, old in installed.items():
                _signal.signal(s, old)
        self._partial = self._interrupt is not None and now < stop
        if self._partial:
            self.log.warning(
                f"{self._interrupt} received: stopped gracefully at round "
                f"boundary {format_time(now)} ({self.rounds} rounds); "
                f"summary is partial")
            if ck_every:
                path = _ckpt.save_checkpoint(self, now)
                self.log.info(f"final checkpoint written: {path}")
                if self.live is not None:
                    self.live.publish({"type": "checkpoint",
                                       "path": str(path), "t": now,
                                       "round": self.rounds})
        if gc_was_enabled:
            _gc.enable()
        _gc.collect()
        self.engine.flush_all()  # finalize counters for in-flight batches
        if cfg.general.progress:
            import sys as _sys

            print(file=_sys.stderr)  # end the \r status line
        self.wall_seconds = _walltime.perf_counter() - t0
        self.scheduler.shutdown()
        result = self._finalize(min(now, stop))
        if self.live is not None:
            self.live.publish({"type": "end",
                               "exit_reason": result["exit_reason"],
                               "rounds": self.rounds, "t": min(now, stop)})
            self.live.close()
        return result

    def _round_loop(self, now, stop, w, dyn, faults, next_hb, hb_interval,
                    next_prog, prog_step, next_gc, next_ckpt, ck_every,
                    dig, _ckpt, tel, t0) -> SimTime:
        """The conservative round loop (split from run() so the signal
        try/finally stays readable). Returns the final sim time."""
        import gc as _gc

        # device transport (network/devtransport.py): deferred host
        # rounds replay inside end_of_round; their event counts fold
        # back into `executed` so the skip-ahead decision, the events
        # total, and the round grid are identical to the scalar twin's
        devt = getattr(self.engine, "devt", None)
        while now < stop:
            if self._chaos is not None:
                self._chaos.maybe_fire(self.rounds, self)
            if self._stall_escalate is not None:
                # a managed guest stalled past its watchdog deadline
                # under supervision: surface it at this boundary (before
                # anything is emitted for the next round) so the
                # supervisor can tear down and recover by re-execution
                from shadow_tpu.supervise import GuestStallError

                msg, self._stall_escalate = self._stall_escalate, None
                raise GuestStallError(msg)
            if self.live is not None \
                    or self._replay_idx < len(self._replay_cmds):
                # live-operations command plane (shadow_tpu/live.py):
                # due replayed commands, then live client commands, all
                # quantized to THIS boundary and logged — before the
                # interrupt check (a stop command IS the interrupt) and
                # before the checkpoint write (so a same-boundary
                # snapshot already contains the commands' effects)
                faults = self._live_boundary(now, faults)
            if self._interrupt is not None:
                # graceful shutdown: the signal arrived during the last
                # round; stop at this (consistent) round boundary
                break
            if self._reexec_verify is not None \
                    and now >= self._reexec_verify["t"]:
                # managed re-execution restore: the deterministic prefix
                # has reached the snapshot boundary — verify digest +
                # guest cursors HERE, exactly where the original run
                # wrote the snapshot (after the boundary's commands,
                # before fault transitions apply)
                self._verify_reexec(now)
            if now >= next_ckpt or self._ckpt_now:
                self._ckpt_now = False
                t_ck = _walltime.perf_counter()
                if tel is not None:
                    tel.sync(self)  # streams complete at the boundary
                path = _ckpt.save_checkpoint(self, now)
                self.log.info(
                    f"checkpoint written: {path} "
                    f"(sim {format_time(now)}, round {self.rounds})")
                if self.live is not None:
                    # the checkpoint_now ack precedes application (it
                    # confirms receipt, not effect) — this post-save
                    # record is how a live client learns the PATH, e.g.
                    # to fork it (shadow_tpu/forks.py)
                    self.live.publish({"type": "checkpoint",
                                       "path": str(path), "t": now,
                                       "round": self.rounds})
                if ck_every:
                    next_ckpt = ((now // ck_every) + 1) * ck_every
                # snapshot wall is attributed like any other phase: it is
                # plane-independent (the pickler walks the same graph fast
                # plane or slow), so naming it keeps the benchmark's
                # robustness-tax decomposition honest
                self._ckpt_wall += _walltime.perf_counter() - t_ck
            if faults is not None:
                # fault transitions apply at round starts: an action at
                # time t takes effect at the first boundary >= t — the
                # same quantization the conservative barrier imposes on
                # every cross-host effect, so it is policy-independent
                faults.apply_due(now)
            if dyn:
                # widen to the smallest latency traffic has actually used
                # (never narrower than the static conservative window)
                w = max(self.round_ns,
                        min(self.engine.min_used_latency, 10 * self.round_ns))
            round_end = min(now + w, stop)
            self.engine.start_of_round(now, round_end)
            hosts = self.hosts
            t_ev = _walltime.perf_counter()
            if self._c_core is not None:
                # the C loop snapshots + sorts the active set, merges each
                # host's inbox/heap, and discards drained hosts itself
                executed = self._c_core.run_round(round_end)
            else:
                active = [hosts[i] for i in sorted(self._active)]
                executed = self.scheduler.run_round(round_end, active)
                for h in active:
                    if not h.equeue._heap:
                        self._active.discard(h.id)
            self._events_wall += _walltime.perf_counter() - t_ev
            self.engine.end_of_round(now, round_end)
            if devt is not None:
                executed += devt.take_executed()
            self.rounds += 1
            self.events += executed
            if dig and self.rounds % dig == 0:
                # determinism sentinel: canonical state digest at this
                # round boundary (flushes in-flight draws first — result-
                # identical, so digesting runs stay byte-identical)
                _ckpt.emit_digest(self, round_end)
            if tel is not None and (tel.dirty
                                    or round_end >= tel.next_sample):
                # telemetry: flush this round's flow closes + fault
                # annotations; take a sample when the sim-time grid says
                # so (the round grid is policy-independent, so the
                # streams are too). One None check when off; idle rounds
                # of a telemetry run skip the call entirely.
                tel.on_round_end(self, round_end)
            if (self.stop_after_round is not None
                    and self.rounds >= self.stop_after_round):
                # time-travel inspection (shadow_tpu/live.py jump): halt
                # AT this boundary — digest/telemetry for the round are
                # already emitted — and hand the inspector the controller
                if self.on_stop_round is not None:
                    self.on_stop_round(self, round_end)
                now = round_end
                break
            if round_end >= next_hb:
                self._heartbeat(round_end, t0)
                # grid-snap, not +=: skip-ahead can cross several
                # intervals at once, and heartbeats must stay ON the
                # sim-time grid to be shard-mergeable (the cadence is
                # sim-round-driven; wall time appears only in the
                # emitted record)
                next_hb = ((round_end // hb_interval) + 1) * hb_interval
            if round_end >= next_prog:
                self._progress(round_end, stop, t0)
                next_prog = round_end + prog_step
            if self.rounds >= next_gc:
                next_gc = self.rounds + _GC_EVERY_ROUNDS
                _gc.collect()
            if executed == 0 and not self.engine.has_immediate_work():
                # provably idle: materialize any in-flight draw batch that
                # could produce an event before the next queued one, then
                # skip to the next event. Flushing here (instead of waking a
                # round at the batch deadline) keeps the round grid — and
                # hence 'rounds' and bucket rebase instants — identical to a
                # run whose flags were computed inline (test_bitmatch.py::
                # test_device_floor_cannot_change_results). The columnar
                # plane's resolved-but-undelivered store rows count as
                # queued events here (pending_head). The C core computes
                # the same min natively (identical instants — it drops
                # cancelled heads exactly like next_time, so the round
                # grid cannot move).
                def _next_queued():
                    if self._c_core is not None:
                        nq = self._c_core.next_time()
                    else:
                        nq = min((hosts[i].equeue.next_time()
                                  for i in self._active), default=T_NEVER)
                    return min(nq, self.engine.pending_head())

                nt = _next_queued()
                if faults is not None:
                    # a pending fault transition is a wake-up: skip-ahead
                    # must not jump over it (a reboot creates new events)
                    nt = min(nt, faults.next_time())
                while self.engine.earliest_outstanding() < nt:
                    self.engine.flush_due(nt)
                    nt = _next_queued()
                    if faults is not None:
                        nt = min(nt, faults.next_time())
                if nt >= T_NEVER:
                    self.log.info(
                        f"no further events at {format_time(round_end)}; ending early"
                    )
                    now = stop
                    break
                now = max(round_end, nt)
            else:
                now = round_end
        return now

    def _live_boundary(self, now: SimTime, faults):
        """Drain the command plane at the round boundary ``now``: due
        replayed commands first, then live client commands. Every
        sim-visible command applies HERE with sim timestamp ``now`` and
        is appended to commands.jsonl, so an interactively driven run and
        its replay-from-log execute identical fault timelines — wall time
        only decides WHICH boundary a live command lands on, and that
        choice is recorded. Returns the (possibly just-created) fault
        injector."""
        from shadow_tpu import live as _live

        lines: list = []
        replay = self._replay_cmds
        while self._replay_idx < len(replay) \
                and replay[self._replay_idx]["t"] <= now:
            rec = replay[self._replay_idx]
            self._replay_idx += 1
            if rec.get("wall_only"):
                continue  # pause/resume never touched sim state
            faults = self._apply_cmd(rec["cmd"], now, rec["seq"], lines,
                                     faults, replayed=True)
        srv = self.live
        if srv is not None:
            batch = srv.poll_commands()
            while batch or self._live_paused:
                if not batch:
                    # paused: wall-block at this boundary, sim state
                    # untouched; commands arriving meanwhile still apply
                    # at THIS boundary
                    if self._interrupt is not None:
                        break
                    batch = srv.poll_commands(timeout=0.25)
                    continue
                norm = batch.pop(0)
                self._live_seq += 1
                faults = self._apply_cmd(norm, now, self._live_seq, lines,
                                         faults, replayed=False)
        if lines:
            _live.append_command_lines(self.data_dir, lines)
        return faults

    def _apply_cmd(self, norm, now: SimTime, seq: int, lines: list,
                   faults, replayed: bool):
        """Apply one normalized command at the boundary ``now``, log it,
        and publish it to live followers."""
        from shadow_tpu import live as _live

        kind = norm["cmd"]
        was_paused = self._live_paused
        wall_only = kind in ("pause", "resume")
        applied = True
        if kind == "pause":
            self._live_paused = True
            applied = not was_paused
        elif kind == "resume":
            self._live_paused = False
            applied = was_paused
        elif kind == "stop":
            self._live_paused = False
            self._interrupt = "live_stop"
        elif kind == "checkpoint_now":
            try:
                from shadow_tpu.checkpoint import \
                    validate_config_checkpointable
                validate_config_checkpointable(self.cfg)
                self._ckpt_now = True
            except ValueError as exc:
                self.log.warning(f"live checkpoint_now refused: {exc}")
                applied = False
        else:
            try:
                faults = _live.apply_command(self, norm, now)
            except ValueError as exc:
                # resolution failure against THIS topology (unknown node/
                # host, managed executable): refuse, never half-apply
                self.log.warning(f"live command {kind!r} refused: {exc}")
                applied = False
        if applied:
            lines.append(_live.format_command_record(
                norm, seq, self.rounds, now, wall_only=wall_only))
            self.log.info(
                f"live command {kind!r} applied at round {self.rounds} "
                f"(t={format_time(now)}, seq {seq}"
                f"{', replayed' if replayed else ''})")
            if self.live is not None:
                self.live.publish({"type": "command", "cmd": norm,
                                   "round": self.rounds, "seq": seq,
                                   "t": now, "replayed": replayed,
                                   "paused": self._live_paused})
        return faults

    def _progress(self, sim_now: SimTime, stop: SimTime, t0: float) -> None:
        """Terminal status line (reference: the status bar, SURVEY.md §2)."""
        import sys as _sys

        wall = _walltime.perf_counter() - t0
        pct = 100 * sim_now // stop
        rate = (sim_now / NS_PER_SEC) / wall if wall > 0 else 0.0
        eta = (stop - sim_now) / NS_PER_SEC / rate if rate > 0 else 0.0
        print(f"\r[{pct:3d}%] sim {format_time(sim_now)} / "
              f"{format_time(stop)}  {rate:.2f} sim-s/s  eta {eta:.0f}s   ",
              end="", file=_sys.stderr, flush=True)

    def _heartbeat(self, sim_now: SimTime, t0: float) -> None:
        wall = _walltime.perf_counter() - t0
        rate = (sim_now / NS_PER_SEC) / wall if wall > 0 else 0.0
        # the device-window routing decision rides the heartbeat so a
        # silently clamped/starved device is visible mid-run, not only in
        # the final summary (round-5 Weak #5)
        note = getattr(self.engine, "heartbeat_note", None)
        if self.live is not None:
            # sim-keyed heartbeat record: cadence and ordering are pure
            # sim-time (shard-mergeable); wall cost and the device note
            # ride INSIDE the record and never feed back into the sim
            self.live.publish({
                "type": "hb", "t": sim_now, "round": self.rounds,
                "events": self.events,
                "units_sent": self.engine.units_sent,
                "units_dropped": self.engine.units_dropped,
                "shards": 1,
                **({"dev": note()} if note is not None else {}),
                "wall": {
                    "seconds": round(wall, 3), "rate": round(rate, 3),
                    "phase": {
                        "events": round(self._events_wall, 4),
                        **{k: round(v, 4)
                           for k, v in self.engine.phase_wall.items()},
                    },
                },
            })
        self.log.info(
            f"heartbeat: sim {format_time(sim_now)} wall {wall:.1f}s "
            f"({rate:.2f} sim-sec/wall-sec) rounds {self.rounds} "
            f"events {self.events} units sent {self.engine.units_sent} "
            f"dropped {self.engine.units_dropped}"
            + (f" {note()}" if note is not None else "")
        )

    def _finalize(self, end_time: SimTime) -> dict:
        if self.telemetry is not None:
            # flush the final round's flow closes before processes are
            # reaped (records already buffered; reaping adds none)
            self.telemetry.finalize(self)
        errors = []
        for p in self.processes:
            err = p.check_final_state()
            if err is not None:
                errors.append(err)
                self.log.error(err)
        for p in self.processes:  # reference §3.5: kill remaining managed
            reap = getattr(p, "reap", None)
            if reap is not None:
                reap()
            j = getattr(p, "_journal", None)
            if j is not None:
                # crash-killed guests that were never rebooted still hold
                # an open journal stream; flush + close it here
                j.close()
        for h in self.hosts:  # merge AFTER reaping so its counters land
            h.fold_counters()
            self.counters.merge(h.counters)
        close = getattr(self.engine, "close", None)
        if close is not None:
            close()  # join the device-init thread before teardown
        sim_sec = end_time / NS_PER_SEC
        rate = sim_sec / self.wall_seconds if self.wall_seconds > 0 else float("inf")
        self.log.info(
            f"simulation finished: sim {format_time(end_time)} in "
            f"{self.wall_seconds:.2f}s wall ({rate:.2f} sim-sec/wall-sec), "
            f"{self.rounds} rounds, {self.events} events, "
            f"{self.engine.units_sent} units delivered, "
            f"{self.engine.units_dropped} dropped"
        )
        self.log.info(self.counters.summary())
        self.data_dir.mkdir(parents=True, exist_ok=True)
        for h in self.hosts:
            h.flush_logs(self.data_dir)
            if h.pcap is not None:
                h.pcap.close()
        self.log.flush()
        import resource

        return {
            "sim_seconds": sim_sec,
            "wall_seconds": self.wall_seconds,
            "sim_sec_per_wall_sec": rate,
            # graceful-shutdown contract: an interrupted run still emits a
            # VALID summary, marked partial, instead of dying mid-round
            "exit_reason": "interrupted" if self._partial else "completed",
            "partial": self._partial,
            **({"interrupt_signal": self._interrupt}
               if self._partial else {}),
            # linux ru_maxrss is KiB; the process-wide high-water mark, so
            # it is only per-run when each run owns its process (bench.py's
            # subprocess rows rely on this)
            "max_rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
            "rounds": self.rounds,
            "events": self.events,
            "units_sent": self.engine.units_sent,
            "units_dropped": self.engine.units_dropped,
            # previously a silent bare attribute (VERDICT: blackholed units
            # discarded without surfacing); per-host counts additionally
            # land in the counters under fault injection
            "units_blackholed": self.engine.units_blackholed,
            "bytes_sent": self.engine.bytes_sent,
            "counters": self.counters.as_dict(),
            "process_errors": errors,
            # per-phase wall breakdown (VERDICT r2 item #7): events =
            # host event execution; the engine contributes its own phases
            # (columnar plane: barrier / draw_flush / extract / ...)
            "phase_wall": {
                "events": round(self._events_wall, 4),
                **{k: round(v, 4)
                   for k, v in self.engine.phase_wall.items()},
                **({"telemetry": round(self.telemetry.wall, 4)}
                   if self.telemetry is not None else {}),
                **({"checkpoint": round(self._ckpt_wall, 4)}
                   if self._ckpt_wall else {}),
            },
            # fused device windows (round-5 Weak #5): zero here on a
            # tpu_batch run means the device never serviced a window —
            # the numpy/C twin carried the whole run. bench.py turns this
            # into a loud per-config device_engaged verdict. Wall-clock
            # routing telemetry only: never simulation state, so runs
            # that differ here still produce identical output trees.
            "device_windows_dispatched": getattr(
                self.engine, "dev_windows", 0),
            **({"device": self.engine.device_summary()}
               if hasattr(self.engine, "device_summary") else {}),
            # device transport (PR 11): wall-clock routing telemetry for
            # the columnar endpoint ticks; engaged = at least one cohort
            # actually advanced through the batched kernel (bench.py
            # turns a silent fallback into a loud warning, the
            # device_engaged discipline)
            **(lambda dt: {} if dt is None else {
                "device_transport_engaged": dt.cohorts > 0,
                "device_transport": dt.summary(),
            })(getattr(self.engine, "devt", None)),
            **({"fault_transitions_applied": self.faults.applied}
               if self.faults is not None else {}),
            # flow-latency percentiles + sample counts (telemetry/):
            # deterministic reductions of sim-time state — intentionally
            # NOT in VOLATILE_SUMMARY_KEYS, so the determinism gates cover
            # them too
            **({"telemetry": self.telemetry.summary()}
               if self.telemetry is not None else {}),
        }


def _default_ip(host_id: int) -> str:
    # 11.0.0.0/8, sequential, skipping .0 and .255 host-octet edge cases
    n = host_id + 1
    a = 11 + (n >> 24)
    return f"{a}.{(n >> 16) & 0xFF}.{(n >> 8) & 0xFF}.{n & 0xFF}"
