"""Shadow-compatible YAML configuration schema.

Mirrors the reference's config layer (SURVEY.md §1 layer 2, §5.6): a single
YAML file with ``general``, ``network``, ``experimental``, and ``hosts``
sections; every option overridable from the CLI. The new backend slots in as
``experimental.scheduler_policy: tpu_batch`` beside the reference's
``thread_per_core`` / ``thread_per_host`` policies (BASELINE.json north_star).

Extensions over the reference schema (documented, all optional):
- ``hosts.<name>.quantity``: stamp out N numbered copies of a host template
  (``client`` -> ``client0..clientN-1``), for large generated benchmarks.
- process ``path`` may be ``pyapp:<module>:<Class>`` to run an in-process
  Python workload plugin instead of a real managed executable (real
  executables are the phase-4 native path, SURVEY.md §7).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from shadow_tpu.core.time import SimTime, parse_time
from shadow_tpu.utils.units import parse_bandwidth, parse_size

SCHEDULER_POLICIES = ("thread_per_core", "thread_per_host", "tpu_batch",
                      "tpu_mesh")
LOG_LEVELS = ("error", "warning", "info", "debug", "trace")
FAULT_KINDS = ("link_down", "link_up", "link_degrade", "host_down",
               "host_up")
#: congestion-control algorithms (network/transport.py
#: CONGESTION_CONTROLS keys, duplicated here so config validation does
#: not import the transport module)
CONGESTION_CONTROL_NAMES = ("newreno", "cubic")
#: the registered Python-twin workload models (process path
#: ``pyapp:<module>:<Class>``): every committed example and generated
#: benchmark draws from this roster. Paths into the
#: ``shadow_tpu.models`` namespace are validated against it at parse
#: time (a typo'd model name fails at config load with the roster,
#: not at process spawn time mid-build); pyapp paths OUTSIDE the
#: namespace still load dynamically (user workloads stay free).
MODEL_REGISTRY = ("tgen", "gossip", "tor", "echo", "httpd", "web",
                  "dns", "abr")


@dataclass
class ProcessOptions:
    path: str
    args: list[str] = field(default_factory=list)
    environment: dict[str, str] = field(default_factory=dict)
    start_time: SimTime = 0
    shutdown_time: Optional[SimTime] = None
    shutdown_signal: str = "SIGTERM"
    expected_final_state: Any = None  # {"exited": 0} | "running" | None


@dataclass
class HostOptions:
    name: str
    network_node_id: int = 0
    ip_addr: Optional[str] = None
    bandwidth_up: Optional[int] = None  # bytes/sec; None -> graph node default
    bandwidth_down: Optional[int] = None
    log_level: Optional[str] = None
    pcap_enabled: bool = False
    pcap_capture_size: int = 65535
    #: per-host congestion-control override (None = the
    #: experimental.congestion_control default)
    congestion_control: Optional[str] = None
    processes: list[ProcessOptions] = field(default_factory=list)


@dataclass
class GeneralOptions:
    stop_time: SimTime = 0
    seed: int = 1
    parallelism: int = 0  # 0 = auto (ncores)
    bootstrap_end_time: SimTime = 0
    data_directory: str = "shadow.data"
    log_level: str = "info"
    heartbeat_interval: Optional[SimTime] = None
    progress: bool = False
    model_unblocked_syscall_latency: bool = False
    #: checkpoint/restore (shadow_tpu/checkpoint.py): snapshot the complete
    #: simulation state every this much SIM time, at a round boundary.
    #: None = off. Resumed runs are byte-identical to uninterrupted ones.
    checkpoint_every: Optional[SimTime] = None
    #: where checkpoints land; default <data_directory>/checkpoints
    checkpoint_dir: Optional[str] = None
    #: determinism sentinel: emit a canonical per-round state digest every
    #: N rounds to <data_directory>/state_digests.jsonl (0 = off). Streams
    #: are comparable across scheduler policies and data planes; diff two
    #: with tools/bisect_divergence.py.
    state_digest_every: int = 0
    #: multi-process host partitioning (shadow_tpu/parallel/shards.py):
    #: partition the host set across N worker processes (static id-modulo
    #: placement), each running its own scheduler + engine over its
    #: subset, coordinated by a parent running the conservative
    #: min-latency lookahead barrier across shards. Results are
    #: byte-identical at ANY shard count (tests/test_shards.py); 1 = the
    #: single-process controller, unchanged.
    sim_shards: int = 1
    #: live operations plane (shadow_tpu/live.py): bind an AF_UNIX live
    #: endpoint streaming heartbeats/metrics/flow snapshots and accepting
    #: runtime fault commands. "auto" = <data_directory>/live.sock.
    #: Volatile: a pure wall-clock plane with zero effect on results
    #: (commands act only via the recorded commands.jsonl).
    live_endpoint: Optional[str] = None
    #: replay a recorded commands.jsonl: each command re-applies at the
    #: same round boundary it originally hit, so an interactively driven
    #: run replays byte-identically from config + command log. Volatile.
    replay_commands: Optional[str] = None
    #: supervised self-healing (shadow_tpu/supervise.py): run under a
    #: supervisor that detects dead/wedged workers and stalled guests,
    #: auto-resumes from the newest complete checkpoint with a bounded
    #: restart budget, and writes crash_report.json when the budget is
    #: exhausted. ``{}`` / ``true`` = defaults (max_restarts 3, backoff
    #: 1.0 s); None = off. Volatile: pure wall-clock policy — a
    #: recovered run is byte-identical to an uninterrupted one.
    supervise: Optional[dict] = None


@dataclass
class ExperimentalOptions:
    scheduler_policy: str = "thread_per_core"
    runahead: Optional[SimTime] = None  # explicit round width override
    use_dynamic_runahead: bool = False
    socket_send_buffer: int = 131072
    socket_recv_buffer: int = 174760
    strace_logging_mode: str = "off"  # off | standard | deterministic
    #: reality-boundary audit for managed processes: the shim traps EVERY
    #: guest syscall (gadget-IP seccomp filter), counts the unemulated
    #: numbers it passes through natively, and the summary reports them.
    #: Diagnostic mode: adds a trap per native syscall (execve works —
    #: the worker-mediated respawn gives the new image fresh filters).
    native_audit: bool = False
    interface_qdisc: str = "fifo"
    max_unapplied_cpu_latency: SimTime = 0
    #: fluid quantum width in MTUs (1..64). Wider units mean fewer events
    #: per byte (faster at scale) at coarser loss/scheduling granularity;
    #: congestion control is byte-counted, so dynamics are size-invariant.
    unit_mtus: int = 10
    # tpu_batch knobs (ours):
    tpu_max_batch: int = 65536  # max units per device draw dispatch
    tpu_device_floor: int = 0  # min batch to engage device; 0=calibrate, -1=off
    #: fused multi-round device windows (network/devroute.py): how many
    #: rounds of loss-draw batches may fuse into ONE device dispatch.
    #: "auto" (stored as 0) sizes windows from live break-even telemetry
    #: and enables speculative forward windows under the C engine; K >= 1
    #: closes the deferred window after K rounds (K=1 = legacy per-round
    #: dispatch). Routing is pure wall-clock policy: results are
    #: bit-identical for every K (tests/test_device_windows.py).
    device_window_rounds: int = 0
    tpu_mesh_shards: int = 0  # 0 = all local devices
    #: tpu_mesh: min due-window units for the collective program; smaller
    #: windows take the bit-identical numpy twin
    tpu_mesh_floor: int = 2048
    #: C engine for the columnar plane (native/colcore). Bit-identical to
    #: the Python paths; off forces the pure-Python twin (test oracle).
    native_colcore: bool = True
    #: device-resident columnar transport (network/devtransport.py):
    #: ack-dominated host rounds defer to the barrier and whole cohorts
    #: of endpoints advance through ONE batched integer kernel
    #: (ops/transport_kernels.py) instead of per-ack scalar callbacks.
    #: Bit-identical on/off (tests/test_devtransport.py); engagement is
    #: pure wall-clock policy with break-even hysteresis, so the default
    #: stays off and a losing box measures it as a no-op. No-op with the
    #: C engine attached (colcore is the scalar fast path) and on the
    #: thread policies (per-unit plane).
    device_transport: bool = False
    #: stream loss recovery: "sack" — RFC 2018-shaped block recovery over
    #: the 3-duplicate-ack trigger (receiver reports its buffered ranges
    #: on every out-of-order ack; the sender retransmits ALL holes per
    #: RTT), the only model since PR 9. The pre-PR-9 "dupack"
    #: one-retransmit-per-RTT model and the round 2-4 engine-notification
    #: oracle are both retired; selecting either is a config error that
    #: names the removal. The knob survives so configs stay explicit
    #: about which recovery model produced their results.
    stream_loss_recovery: str = "sack"
    #: congestion control for stream endpoints: "newreno" (RFC 5681
    #: slow start + AIMD, the extracted default) or "cubic" (integer
    #: CUBIC-shaped variant). Overridable per host via
    #: hosts.<name>.congestion_control — both run bit-identically on the
    #: Python and C endpoint twins (network/transport.py
    #: CongestionControl).
    congestion_control: str = "newreno"
    #: guest watchdog (native/managed.py): wall-clock seconds a managed
    #: process may hold its turn without making a syscall before it is
    #: killed and converted to a host_down fault (0 = off). Catches the
    #: spin-wait livelock README declares as a limitation, instead of
    #: hanging the whole simulator.
    guest_turn_timeout: float = 0.0


@dataclass
class FaultEventOptions:
    """One entry of the ``faults.events`` timeline (shadow_tpu/faults.py)."""

    time: SimTime
    kind: str  # one of FAULT_KINDS
    src_nodes: list[int] = field(default_factory=list)  # GML node ids
    dst_nodes: list[int] = field(default_factory=list)  # empty = all others
    hosts: list[str] = field(default_factory=list)  # names; trailing * globs
    latency_factor: float = 1.0  # link_degrade: multiplies path latency
    loss_add: float = 0.0  # link_degrade: added loss probability
    bandwidth_scale: float = 1.0  # link_degrade: scales attached-host NICs
    duration: Optional[SimTime] = None  # auto-heal/restore after this long


@dataclass
class ChurnOptions:
    """Seeded random up/down cycling for a set of hosts: alternating
    exponential uptime/downtime draws from the counter-based fault RNG
    (core/rng.py::fault_rng), materialized once at startup."""

    hosts: list[str]
    mean_uptime: SimTime
    mean_downtime: SimTime
    start_time: SimTime = 0


@dataclass
class FaultsOptions:
    events: list[FaultEventOptions] = field(default_factory=list)
    churn: list[ChurnOptions] = field(default_factory=list)


@dataclass
class TelemetryOptions:
    """The ``telemetry:`` section (shadow_tpu/telemetry/): sim-time
    samplers + flow records + streaming percentiles, exported as
    append-only ``metrics.jsonl`` / ``flows.jsonl``. Presence of the
    section enables collection; sampling cadence is simulated time, so
    the streams are byte-identical across scheduler policies, data
    planes, and the Python/C twins. Telemetry is result-transparent
    (never simulation state), so it is NOT part of the checkpoint config
    digest — a resume may change it like other volatile keys."""

    #: snapshot per-host/per-NIC state every this much SIM time, at the
    #: first round boundary past each grid point (the 10s default keeps
    #: telemetry within its <=5% wall budget on the tgen_1k bench row —
    #: BENCH_DETAIL telemetry_overhead; dense series want an explicit
    #: sample_every)
    sample_every: SimTime = 10_000_000_000  # 10s
    #: where metrics.jsonl/flows.jsonl land; None = data_directory
    metrics_dir: Optional[str] = None


@dataclass
class ConfigOptions:
    general: GeneralOptions = field(default_factory=GeneralOptions)
    network: dict = field(default_factory=lambda: {"graph": {"type": "1_gbit_switch"}})
    experimental: ExperimentalOptions = field(default_factory=ExperimentalOptions)
    hosts: list[HostOptions] = field(default_factory=list)
    faults: Optional[FaultsOptions] = None
    telemetry: Optional[TelemetryOptions] = None
    #: accepted-but-unimplemented options the user actually set; the
    #: controller logs each (silently ignoring a knob is a correctness trap)
    warnings: list[str] = field(default_factory=list)


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"config error: {msg}")


def _parse_process(p: dict) -> ProcessOptions:
    _require(isinstance(p, dict), f"process entry must be a mapping, got {p!r}")
    _require("path" in p, f"process entry missing 'path': {p!r}")
    args = p.get("args", [])
    if isinstance(args, str):
        args = args.split()
    env = p.get("environment", {}) or {}
    _require(isinstance(env, dict), "process environment must be a mapping")
    opts = ProcessOptions(
        path=str(p["path"]),
        args=[str(a) for a in args],
        environment={str(k): str(v) for k, v in env.items()},
        start_time=parse_time(p.get("start_time", 0)),
        shutdown_time=(parse_time(p["shutdown_time"]) if p.get("shutdown_time") is not None else None),
        shutdown_signal=str(p.get("shutdown_signal", "SIGTERM")),
        expected_final_state=p.get("expected_final_state"),
    )
    _require(opts.start_time >= 0, f"process start_time must be >= 0: {p!r}")
    _require(
        opts.shutdown_time is None or opts.shutdown_time > opts.start_time,
        f"process shutdown_time must be after start_time: {p!r}",
    )
    if opts.path.startswith("pyapp:shadow_tpu.models."):
        parts = opts.path.split(":")
        _require(len(parts) == 3,
                 f"bad pyapp path {opts.path!r} (want pyapp:module:Class)")
        mod = parts[1]
        _require(mod.removeprefix("shadow_tpu.models.") in MODEL_REGISTRY,
                 f"unknown workload model {mod!r} "
                 f"(registered: {sorted(MODEL_REGISTRY)})")
    return opts


def _parse_host(name: str, h: dict) -> HostOptions:
    _require(isinstance(h, dict), f"host {name!r} must be a mapping")
    opts = HostOptions(name=name)
    opts.network_node_id = int(h.get("network_node_id", 0))
    opts.ip_addr = h.get("ip_addr")
    if h.get("bandwidth_up") is not None:
        opts.bandwidth_up = parse_bandwidth(h["bandwidth_up"])
        _require(opts.bandwidth_up > 0, f"host {name!r} bandwidth_up must be > 0")
    if h.get("bandwidth_down") is not None:
        opts.bandwidth_down = parse_bandwidth(h["bandwidth_down"])
        _require(opts.bandwidth_down > 0, f"host {name!r} bandwidth_down must be > 0")
    if h.get("log_level") is not None:
        opts.log_level = str(h["log_level"]).lower()
        _require(opts.log_level in LOG_LEVELS, f"bad log_level {opts.log_level!r}")
    opts.pcap_enabled = bool(h.get("pcap_enabled", False))
    opts.pcap_capture_size = parse_size(h.get("pcap_capture_size", 65535))
    if h.get("congestion_control") is not None:
        opts.congestion_control = str(h["congestion_control"])
        _require(opts.congestion_control in CONGESTION_CONTROL_NAMES,
                 f"host {name!r} congestion_control must be one of "
                 f"{CONGESTION_CONTROL_NAMES}, got "
                 f"{opts.congestion_control!r}")
    procs = h.get("processes", [])
    _require(isinstance(procs, list), f"host {name!r} processes must be a list")
    opts.processes = [_parse_process(p) for p in procs]
    return opts


def _parse_fault_event(e: dict) -> FaultEventOptions:
    _require(isinstance(e, dict), f"faults.events entry must be a mapping: {e!r}")
    _require("time" in e and "kind" in e,
             f"faults.events entry needs 'time' and 'kind': {e!r}")
    kind = str(e["kind"])
    _require(kind in FAULT_KINDS,
             f"faults.events kind must be one of {FAULT_KINDS}, got {kind!r}")
    ev = FaultEventOptions(time=parse_time(e["time"]), kind=kind)
    _require(ev.time >= 0, f"faults.events time must be >= 0: {e!r}")
    ev.src_nodes = [int(n) for n in (e.get("src_nodes") or [])]
    ev.dst_nodes = [int(n) for n in (e.get("dst_nodes") or [])]
    ev.hosts = [str(h) for h in (e.get("hosts") or [])]
    if kind in ("link_down", "link_up", "link_degrade"):
        _require(len(ev.src_nodes) > 0,
                 f"faults {kind} needs src_nodes: {e!r}")
        _require(not ev.hosts, f"faults {kind} takes nodes, not hosts: {e!r}")
    else:
        _require(len(ev.hosts) > 0, f"faults {kind} needs hosts: {e!r}")
        _require(not ev.src_nodes and not ev.dst_nodes,
                 f"faults {kind} takes hosts, not nodes: {e!r}")
    if kind == "link_degrade":
        ev.latency_factor = float(e.get("latency_factor", 1.0))
        ev.loss_add = float(e.get("loss_add", 0.0))
        ev.bandwidth_scale = float(e.get("bandwidth_scale", 1.0))
        _require(1.0 <= ev.latency_factor <= 1e6,
                 f"latency_factor must be in [1, 1e6]: {e!r}")
        _require(0.0 <= ev.loss_add <= 1.0,
                 f"loss_add must be in [0, 1]: {e!r}")
        _require(0.0 < ev.bandwidth_scale <= 1.0,
                 f"bandwidth_scale must be in (0, 1]: {e!r}")
        _require(ev.latency_factor != 1.0 or ev.loss_add != 0.0
                 or ev.bandwidth_scale != 1.0,
                 f"link_degrade with no effect: {e!r}")
    else:
        for k in ("latency_factor", "loss_add", "bandwidth_scale"):
            _require(k not in e, f"faults {kind} does not take {k}: {e!r}")
    if e.get("duration") is not None:
        _require(kind in ("link_down", "link_degrade", "host_down"),
                 f"faults {kind} does not take a duration: {e!r}")
        ev.duration = parse_time(e["duration"])
        _require(ev.duration > 0, f"faults duration must be > 0: {e!r}")
    return ev


def _parse_churn(c: dict) -> ChurnOptions:
    _require(isinstance(c, dict), f"faults.churn entry must be a mapping: {c!r}")
    for k in ("hosts", "mean_uptime", "mean_downtime"):
        _require(k in c, f"faults.churn entry needs {k!r}: {c!r}")
    opts = ChurnOptions(
        hosts=[str(h) for h in (c["hosts"] or [])],
        mean_uptime=parse_time(c["mean_uptime"]),
        mean_downtime=parse_time(c["mean_downtime"]),
        start_time=parse_time(c.get("start_time", 0)),
    )
    _require(len(opts.hosts) > 0, f"faults.churn needs hosts: {c!r}")
    _require(opts.mean_uptime > 0 and opts.mean_downtime > 0,
             f"faults.churn means must be > 0: {c!r}")
    _require(opts.start_time >= 0, f"faults.churn start_time must be >= 0: {c!r}")
    return opts


def _parse_faults(doc: dict) -> FaultsOptions:
    _require(isinstance(doc, dict), "faults must be a mapping")
    for k in doc:
        _require(k in ("events", "churn"),
                 f"unknown faults key {k!r} (want events/churn)")
    f = FaultsOptions()
    events = doc.get("events") or []
    _require(isinstance(events, list), "faults.events must be a list")
    f.events = [_parse_fault_event(e) for e in events]
    churn = doc.get("churn") or []
    _require(isinstance(churn, list), "faults.churn must be a list")
    f.churn = [_parse_churn(c) for c in churn]
    _require(f.events or f.churn, "faults section is present but empty")
    return f


def _parse_telemetry(doc) -> TelemetryOptions:
    """``telemetry:`` — a bare key (None) enables with defaults, which is
    what the CLI's --sample-every/--metrics-dir overrides rely on."""
    t = TelemetryOptions()
    if doc is None:
        return t
    _require(isinstance(doc, dict), "telemetry must be a mapping")
    for k in doc:
        _require(k in ("sample_every", "metrics_dir"),
                 f"unknown telemetry key {k!r} (want sample_every/"
                 f"metrics_dir)")
    if doc.get("sample_every") is not None:
        t.sample_every = parse_time(doc["sample_every"])
        _require(t.sample_every > 0, "telemetry.sample_every must be > 0")
    if doc.get("metrics_dir") is not None:
        t.metrics_dir = str(doc["metrics_dir"])
    return t


def parse_config(doc: dict, overrides: Optional[dict] = None) -> ConfigOptions:
    """Parse a loaded YAML document (plus dotted-key CLI overrides) into
    validated ConfigOptions.

    ``overrides`` maps dotted paths to raw values, e.g.
    ``{"general.stop_time": "30s", "experimental.scheduler_policy": "tpu_batch"}``.
    """
    doc = copy.deepcopy(doc) if doc else {}
    _require(isinstance(doc, dict), "top-level config must be a mapping")
    for key, val in (overrides or {}).items():
        parts = key.split(".")
        cur = doc
        for p in parts[:-1]:
            nxt = cur.setdefault(p, {})
            if nxt is None:
                # a bare section key (`telemetry:` / `faults:` with no
                # body) parses as None; a dotted override into it means
                # "that section, with this key set"
                nxt = cur[p] = {}
            cur = nxt
            _require(isinstance(cur, dict), f"cannot override {key!r}")
        cur[parts[-1]] = val

    cfg = ConfigOptions()

    gen = doc.get("general", {}) or {}
    _require("stop_time" in gen, "general.stop_time is required")
    g = cfg.general
    g.stop_time = parse_time(gen["stop_time"])
    _require(g.stop_time > 0, "general.stop_time must be > 0")
    g.seed = int(gen.get("seed", 1))
    _require(0 <= g.seed < (1 << 63), "general.seed must be in [0, 2**63)")
    g.parallelism = int(gen.get("parallelism", 0))
    _require(g.parallelism >= 0, "general.parallelism must be >= 0")
    g.bootstrap_end_time = parse_time(gen.get("bootstrap_end_time", 0))
    _require(g.bootstrap_end_time >= 0, "general.bootstrap_end_time must be >= 0")
    g.data_directory = str(gen.get("data_directory", "shadow.data"))
    g.log_level = str(gen.get("log_level", "info")).lower()
    _require(g.log_level in LOG_LEVELS, f"bad general.log_level {g.log_level!r}")
    if gen.get("heartbeat_interval") is not None:
        g.heartbeat_interval = parse_time(gen["heartbeat_interval"])
        _require(g.heartbeat_interval > 0, "general.heartbeat_interval must be > 0")
    g.progress = bool(gen.get("progress", False))
    g.model_unblocked_syscall_latency = bool(gen.get("model_unblocked_syscall_latency", False))
    if gen.get("checkpoint_every") is not None:
        g.checkpoint_every = parse_time(gen["checkpoint_every"])
        _require(g.checkpoint_every > 0,
                 "general.checkpoint_every must be > 0")
    if gen.get("checkpoint_dir") is not None:
        g.checkpoint_dir = str(gen["checkpoint_dir"])
    g.state_digest_every = int(gen.get("state_digest_every", 0))
    _require(g.state_digest_every >= 0,
             "general.state_digest_every must be >= 0")
    g.sim_shards = int(gen.get("sim_shards", 1))
    _require(1 <= g.sim_shards <= 64,
             "general.sim_shards must be in [1, 64]")
    if gen.get("live_endpoint") is not None:
        g.live_endpoint = str(gen["live_endpoint"])
        _require(bool(g.live_endpoint),
                 "general.live_endpoint must be a socket path or 'auto'")
    if gen.get("replay_commands") is not None:
        g.replay_commands = str(gen["replay_commands"])
    if gen.get("supervise") is not None:
        sup = gen["supervise"]
        if sup is True:
            sup = {}
        elif sup is False:
            sup = None
        if sup is not None:
            _require(isinstance(sup, dict),
                     "general.supervise must be a mapping (or true/false)")
            unknown = set(sup) - {"max_restarts", "backoff"}
            _require(not unknown,
                     f"unknown general.supervise key(s) {sorted(unknown)}; "
                     f"known: max_restarts, backoff")
            sup = {"max_restarts": int(sup.get("max_restarts", 3)),
                   "backoff": float(sup.get("backoff", 1.0))}
            _require(sup["max_restarts"] >= 0,
                     "general.supervise.max_restarts must be >= 0")
            _require(sup["backoff"] >= 0,
                     "general.supervise.backoff must be >= 0")
        g.supervise = sup

    if doc.get("network"):
        cfg.network = doc["network"]
    _require("graph" in cfg.network, "network.graph is required")

    exp = doc.get("experimental", {}) or {}
    e = cfg.experimental
    e.scheduler_policy = str(exp.get("scheduler_policy", "thread_per_core"))
    _require(
        e.scheduler_policy in SCHEDULER_POLICIES,
        f"scheduler_policy must be one of {SCHEDULER_POLICIES}, got {e.scheduler_policy!r}",
    )
    if exp.get("runahead") is not None:
        e.runahead = parse_time(exp["runahead"])
        _require(e.runahead > 0, "experimental.runahead must be > 0")
    e.use_dynamic_runahead = bool(exp.get("use_dynamic_runahead", False))
    e.socket_send_buffer = parse_size(exp.get("socket_send_buffer", e.socket_send_buffer))
    e.socket_recv_buffer = parse_size(exp.get("socket_recv_buffer", e.socket_recv_buffer))
    e.strace_logging_mode = str(exp.get("strace_logging_mode", "off"))
    e.native_audit = bool(exp.get("native_audit", False))
    e.interface_qdisc = str(exp.get("interface_qdisc", "fifo"))
    e.max_unapplied_cpu_latency = parse_time(exp.get("max_unapplied_cpu_latency", 0))
    _require(e.max_unapplied_cpu_latency >= 0,
             "experimental.max_unapplied_cpu_latency must be >= 0")
    _require(e.interface_qdisc in ("fifo", "round_robin"),
             f"experimental.interface_qdisc must be fifo or round_robin, "
             f"got {e.interface_qdisc!r}")
    e.unit_mtus = int(exp.get("unit_mtus", 10))
    _require(1 <= e.unit_mtus <= 64,
             "experimental.unit_mtus must be in [1, 64]")
    e.tpu_max_batch = int(exp.get("tpu_max_batch", 65536))
    e.tpu_device_floor = int(exp.get("tpu_device_floor", 0))
    dwr = exp.get("device_window_rounds", "auto")
    if str(dwr).lower() == "auto":
        e.device_window_rounds = 0  # internal sentinel for auto
    else:
        e.device_window_rounds = int(dwr)
        _require(e.device_window_rounds >= 1,
                 "experimental.device_window_rounds must be >= 1 or 'auto'")
    e.tpu_mesh_shards = int(exp.get("tpu_mesh_shards", 0))
    e.tpu_mesh_floor = int(exp.get("tpu_mesh_floor", 2048))
    e.native_colcore = bool(exp.get("native_colcore", True))
    e.device_transport = bool(exp.get("device_transport", False))
    e.stream_loss_recovery = str(exp.get("stream_loss_recovery", "sack"))
    _require(e.stream_loss_recovery == "sack",
             "experimental.stream_loss_recovery must be sack (PR 9 "
             "replaced the one-retransmit-per-RTT dupack model with "
             "SACK-style block recovery; the engine-notification oracle "
             "was removed earlier per COMPONENTS.md #13), "
             f"got {e.stream_loss_recovery!r}")
    e.congestion_control = str(exp.get("congestion_control", "newreno"))
    _require(e.congestion_control in CONGESTION_CONTROL_NAMES,
             f"experimental.congestion_control must be one of "
             f"{CONGESTION_CONTROL_NAMES}, got {e.congestion_control!r}")
    e.guest_turn_timeout = float(exp.get("guest_turn_timeout", 0.0))
    _require(e.guest_turn_timeout >= 0,
             "experimental.guest_turn_timeout must be >= 0")

    if "telemetry" in doc:  # bare `telemetry:` enables with defaults
        cfg.telemetry = _parse_telemetry(doc["telemetry"])

    if doc.get("faults") is not None:  # `faults:` left empty = absent
        cfg.faults = _parse_faults(doc["faults"])

    hosts_doc = doc.get("hosts", {}) or {}
    _require(isinstance(hosts_doc, dict), "hosts must be a mapping of name -> options")
    _require(len(hosts_doc) > 0, "at least one host is required")
    for name in hosts_doc:  # dict preserves YAML order -> deterministic host ids
        h = hosts_doc[name] or {}
        qty = int(h.pop("quantity", 1)) if isinstance(h, dict) else 1
        # quantity templates may cycle placement: copy i lands on
        # network_node_ids[i % len] — what keeps a 1M-host generated
        # config (examples/tor_1m.yaml) at O(templates) YAML instead of
        # one stanza per host while still spreading a contiguous-named
        # population (relay0..relayN-1) across the whole graph
        node_cycle = h.pop("network_node_ids", None) \
            if isinstance(h, dict) else None
        if node_cycle is not None:
            _require(isinstance(node_cycle, list) and len(node_cycle) > 0,
                     f"host {name!r} network_node_ids must be a non-empty "
                     f"list")
        if qty == 1 and node_cycle is None:
            cfg.hosts.append(_parse_host(str(name), h))
        else:
            _require(qty >= 1, f"host {name!r} quantity must be >= 1")
            for i in range(qty):
                ho = _parse_host(f"{name}{i}", h)
                if node_cycle is not None:
                    ho.network_node_id = int(node_cycle[i % len(node_cycle)])
                cfg.hosts.append(ho)
    names = [h.name for h in cfg.hosts]
    _require(len(set(names)) == len(names), "duplicate host names after expansion")
    return cfg


#: composed-YAML memo for load_yaml_doc(cache=True): a fleet worker runs
#: many seeds of ONE config in one interpreter, and composing a
#: multi-hundred-host document costs ~1.7 s (tor_400) — by far the
#: biggest per-seed fixed cost once the round loop is subsecond. Keyed
#: on (abspath, mtime_ns, size) so an edited file re-parses.
_DOC_CACHE: dict = {}


def load_yaml_doc(path: str, cache: bool = False) -> dict:
    """Read + compose the YAML document at ``path``. ``cache=True``
    memoizes the composed doc (callers must not mutate it —
    parse_config deep-copies before applying overrides)."""
    import os

    if not cache:
        with open(path, "r") as f:
            return yaml.safe_load(f)
    st = os.stat(path)
    key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
    doc = _DOC_CACHE.get(key)
    if doc is None:
        with open(path, "r") as f:
            doc = yaml.safe_load(f)
        _DOC_CACHE.clear()  # one config per process is the fleet shape
        _DOC_CACHE[key] = doc
    return doc


def load_config(path: str, overrides: Optional[dict] = None,
                cache_doc: bool = False) -> ConfigOptions:
    import os

    doc = load_yaml_doc(path, cache=cache_doc)
    cfg = parse_config(doc, overrides)
    # a network.graph file reference resolves relative to the CONFIG file
    # (the reference convention; lets committed configs carry committed
    # topology fixtures)
    g = cfg.network.get("graph", {})
    f = g.get("file")
    fpath = f.get("path") if isinstance(f, dict) else f
    if fpath and not os.path.isabs(fpath):
        resolved = os.path.join(os.path.dirname(os.path.abspath(path)),
                                fpath)
        if os.path.exists(resolved):
            if isinstance(f, dict):
                f["path"] = resolved
            else:
                g["file"] = resolved
    # process binary paths resolve the same way (managed processes spawn
    # with cwd inside the data directory, so a committed config's
    # relative "native/build/foo" would otherwise depend on the caller's
    # cwd). First try relative to the config file, then the caller's cwd;
    # pyapp: entries and absolute paths pass through untouched.
    for h in cfg.hosts:
        for p in h.processes:
            if p.path.startswith("pyapp:") or os.path.isabs(p.path):
                continue
            for base in (os.path.dirname(os.path.abspath(path)),
                         os.getcwd()):
                resolved = os.path.join(base, p.path)
                if os.path.exists(resolved):
                    p.path = resolved
                    break
    return cfg
