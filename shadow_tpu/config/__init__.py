"""Configuration: Shadow-compatible YAML schema + CLI overrides."""

from shadow_tpu.config.schema import (  # noqa: F401
    ConfigOptions,
    GeneralOptions,
    ExperimentalOptions,
    HostOptions,
    ProcessOptions,
    load_config,
    parse_config,
)
