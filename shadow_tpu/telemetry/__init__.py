"""Deterministic simulation telemetry (see collector.py for the design)."""

from shadow_tpu.telemetry.collector import (  # noqa: F401
    FLOWS_FILE,
    METRICS_FILE,
    TelemetryCollector,
)
from shadow_tpu.telemetry.histogram import LogHistogram  # noqa: F401
