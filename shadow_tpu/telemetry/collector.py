"""Deterministic simulation telemetry: samplers, flow records, percentiles.

This is the run-level observability layer the coarse totals (counters,
heartbeat) cannot provide: "what was fetch p99 during the partition
window?", "which NIC's queue saturated in round 40k?". It follows the
design of upstream Shadow's tornettools result extraction and NS-3's
FlowMonitor (PAPERS.md), but lives *inside* the simulator and is held to
the repo's determinism bar: both output streams are byte-identical across
scheduler policies, data planes, and the Python/C twins, and a resumed
checkpoint continues the streams bit-exactly — so telemetry doubles as a
cross-plane correctness gate, the same trick ``state_digests.jsonl``
proved out.

Two append-only JSONL streams land in the metrics directory (default: the
run's data_directory):

``metrics.jsonl``
    - one ``meta`` record at fresh-run start (host names, NIC rates/caps,
      the sample cadence) so readers need no side channel;
    - one ``fault`` record per applied fault transition (the fault
      timeline, in application order — what lets reports annotate
      windows);
    - one ``sample`` record every ``telemetry.sample_every`` of simulated
      time, taken at the first round boundary past each grid point:
      global counters plus per-host columns (egress/ingress token-bucket
      levels, deferred-ingress backlog, live app timers, connection
      cwnd/ssthresh/RTO aggregates, in-flight bytes, retransmit counts,
      down/blackhole status).

``flows.jsonl``
    - one lifecycle record per application flow (tgen fetches, gossip
      INV->GETDATA->TX fetches, tor circuit fetches), emitted at flow
      close with open time, time-to-first-byte, bytes, completion
      latency, retransmits, and terminal status. ``retx`` counts the
      RECORDING endpoint's sender-side loss events; for download-shaped
      flows (tgen, tor) the server half's retransmits surface in the
      sample stream's per-host ``retx`` column instead (reading the
      remote endpoint at close time would race the thread policies).

Determinism rules (the whole design hangs on these):
- Everything is keyed off SIM time and canonical event order — never wall
  clock. Samples happen at round boundaries; the round grid is identical
  across policies and planes.
- Before a sample, ``engine.flush_all()`` materializes in-flight draw
  batches (result-identical by construction — the determinism-sentinel
  discipline), so both planes sit at the same resolution frontier.
- Flow records buffer host-locally during a round (host event execution
  may be parallel) and flush at the round end in host-id order; within a
  host, records follow event execution order, which is canonical.
- Only plane-independent observables are sampled — the same contract as
  ``Host.state_fingerprint``: capped bucket levels (the vector and scalar
  bucket twins rebase differently), no BAND_NET heap entries, no columnar
  pending store.
- Serialization is canonical JSON (sorted keys, fixed separators, ints
  only) — byte-comparable with sha256, no float formatting hazards.

When telemetry is off, ``controller.telemetry is None`` and nothing here
runs: no per-event work, no per-round work beyond one None check.
"""

from __future__ import annotations

import json
import time as _walltime  # detlint: ok(wallclock): collector overhead accounting (wall field)
from pathlib import Path

from shadow_tpu.telemetry.histogram import LogHistogram

METRICS_FILE = "metrics.jsonl"
FLOWS_FILE = "flows.jsonl"

#: StreamSender.ssthresh init (transport.py / colcore.c): "not yet set"
_SSTHRESH_INF = 1 << 62


def _dumps(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _arr(v) -> str:
    return "[%s]" % ",".join(map(str, v))


def format_sample_line(g: dict, cols: dict, rounds: int, t: int) -> str:
    """THE canonical sample-record serialization (hand-rolled sorted-key
    JSON, byte-identical to json.dumps of the same mapping). Module-level
    so the sharded parent can assemble the exact line a single-process
    run would have written from merged per-shard columns."""
    return (
        '{"global":{"bucket_up":%s,"bytes_sent":%d,"events":%d,'
        '"tokens_down":%s,"units_blackholed":%d,"units_dropped":%d,'
        '"units_sent":%d},'
        '"hosts":{"blackholed":%s,"conns":%s,"cwnd":%s,"deferred":%s,'
        '"delivered":%s,"down":%s,"emitted":%s,"inflight":%s,'
        '"retx":%s,"rto_backoff_max":%s,"rto_retries":%s,'
        '"ssthresh_min":%s,"timers":%s},'
        '"kind":"sample","round":%d,"t":%d}'
        % (_arr(g["bucket_up"]), g["bytes_sent"], g["events"],
           _arr(g["tokens_down"]), g["units_blackholed"],
           g["units_dropped"], g["units_sent"],
           _arr(cols["blackholed"]), _arr(cols["conns"]),
           _arr(cols["cwnd"]), _arr(cols["deferred"]),
           _arr(cols["delivered"]), _arr(cols["down"]),
           _arr(cols["emitted"]), _arr(cols["inflight"]),
           _arr(cols["retx"]), _arr(cols["rto_backoff_max"]),
           _arr(cols["rto_retries"]), _arr(cols["ssthresh_min"]),
           _arr(cols["timers"]),
           rounds, t))


def host_columns(hosts) -> dict:
    """Per-host sampler columns for ``hosts`` (in the given order). The
    single-process sampler passes all hosts in id order; a shard worker
    passes its owned subset and the parent interleaves by host id."""
    from shadow_tpu.core.events import BAND_NET

    c_def, c_tmr, c_cn, c_inf, c_cwnd = [], [], [], [], []
    c_ss, c_retx, c_rtr, c_bkf = [], [], [], []
    c_em, c_dl, c_down, c_bh = [], [], [], []
    for h in hosts:
        c_def.append(len(h.ingress_deferred)
                     + len(h.ingress_deferred_rows))
        c_tmr.append(h.equeue.live_count(exclude_band=BAND_NET))
        conns = h._conns
        inflight = cwnd = retx = retries = 0
        backoff_max = 0
        ss_min = 0
        if conns:
            for ep in conns.values():
                s = ep.sender
                inflight += int(s.snd_nxt) - int(s.snd_una)
                cwnd += int(s.cwnd)
                retx += int(s.loss_events)
                retries += int(s.retries)
                b = int(s.rto_backoff)
                if b > backoff_max:
                    backoff_max = b
                ss = int(s.ssthresh)
                if ss < _SSTHRESH_INF and (ss_min == 0 or ss < ss_min):
                    ss_min = ss
        c_cn.append(len(conns))
        c_inf.append(inflight)
        c_cwnd.append(cwnd)
        c_ss.append(ss_min)
        c_retx.append(retx)
        c_rtr.append(retries)
        c_bkf.append(backoff_max)
        c_em.append(h._n_emitted)
        c_dl.append(h._n_delivered)
        c_down.append(1 if h.down else 0)
        c_bh.append(h._n_blackholed)
    return {"blackholed": c_bh, "conns": c_cn, "cwnd": c_cwnd,
            "deferred": c_def, "delivered": c_dl, "down": c_down,
            "emitted": c_em, "inflight": c_inf, "retx": c_retx,
            "rto_backoff_max": c_bkf, "rto_retries": c_rtr,
            "ssthresh_min": c_ss, "timers": c_tmr}


class TelemetryCollector:
    """Owns the telemetry state of one run; hangs off the controller and
    rides its checkpoint pickle (histograms, sample cursor, flow counters
    — everything needed for a resumed run's streams to continue
    bit-exactly). Holds no open files: writes open-append-close per
    flush, like the determinism sentinel."""

    def __init__(self, tel_cfg) -> None:
        self.sample_every = int(tel_cfg.sample_every)
        self.metrics_dir = tel_cfg.metrics_dir  # None = data_directory
        self.next_sample = self.sample_every
        self.samples = 0
        self.flows_written = 0
        #: wall seconds spent inside telemetry (sampling + flow flushes)
        #: — surfaces as phase_wall["telemetry"] so the <=5% budget is
        #: directly attributable, independent of shared-machine noise
        self.wall = 0.0
        #: anything buffered for the next round-end flush (flow records,
        #: fault annotations). THE contract with the controller's round
        #: loop: every producer of pending records sets this, and the
        #: loop calls on_round_end whenever it is set (or a sample is
        #: due) — so new record kinds only need to set dirty
        self.dirty = False
        #: hosts holding unflushed flow records this round (appends are
        #: GIL-atomic under the thread policies; sorted by id at flush —
        #: the ack_hosts discipline)
        self.flow_hosts: list = []
        self._fault_pending: list = []  # fault records applied this round
        self.hist: dict[str, LogHistogram] = {}  # flow kind -> latencies
        self.flow_counts: dict[str, dict] = {}  # kind -> {ok, failed}
        self._fh: dict = {}  # cached append handles (runtime-only)
        self._enc: dict = {}  # value -> canonical JSON string (names etc.)
        #: serialized flow lines awaiting a file write — flushed to disk
        #: at samples, checkpoints, and run end (content and order are
        #: fixed at serialization time, so write batching cannot change
        #: the stream, only the syscall count)
        self._flow_lines: list = []
        #: multi-process sharding (parallel/shards.py): (shard_id, N) on
        #: a worker, else None. A sharded collector never writes
        #: metrics.jsonl itself: fault records and sample partials queue
        #: in _out_partials for the worker loop to ship to the parent,
        #: and flow lines land in a per-shard flows.shard<k>.jsonl the
        #: parent merges by (round, hid) at run end.
        self.shard = None
        self._out_partials: list = []

    # -- checkpoint/restore (shadow_tpu/checkpoint.py) ---------------------
    def __getstate__(self):
        d = self.__dict__.copy()
        d["_fh"] = {}  # open files never ride a snapshot; reopened lazily
        return d

    # -- paths -------------------------------------------------------------
    def _dir(self, controller) -> Path:
        d = (Path(self.metrics_dir) if self.metrics_dir
             else controller.data_dir)
        d.mkdir(parents=True, exist_ok=True)
        return d

    def _append(self, controller, name: str, lines: list) -> None:
        # handles are opened once and cached: an open()+mkdir per flush
        # measurably dragged the <=5% overhead budget on tgen_1k
        f = self._fh.get(name)
        if f is None:
            f = self._fh[name] = open(self._dir(controller) / name, "a")
        f.write("\n".join(lines) + "\n")
        # live-operations tee (shadow_tpu/live.py): followers receive the
        # artifact lines verbatim as they are written. Wall-clock plane
        # only — publish never blocks and drops on slow readers, so the
        # on-disk streams stay the source of truth
        srv = getattr(controller, "live", None)
        if srv is not None:
            srv.publish_stream(name, lines)

    def _flows_name(self) -> str:
        return (FLOWS_FILE if self.shard is None
                else f"flows.shard{self.shard[0]}.jsonl")

    def sync(self, controller) -> None:
        """Flush buffered flow lines + cached handles to disk (checkpoint
        boundaries, samples, run end): the on-disk streams are complete
        at every graceful stop point."""
        if self._flow_lines:
            lines, self._flow_lines = self._flow_lines, []
            self._append(controller, self._flows_name(), lines)
        for f in self._fh.values():
            f.flush()

    def drain_partials(self) -> list:
        """Shard worker: pending fault-record lines + sample partials for
        the parent (in production order)."""
        out, self._out_partials = self._out_partials, []
        return out

    def export_merge_state(self) -> dict:
        """Shard worker finalize: the mergeable reduction state (bucket
        histograms + flow counts) the parent folds into the run summary."""
        return {"samples": self.samples,
                "flows_written": self.flows_written,
                "hist": {k: h.state() for k, h in self.hist.items()},
                "flow_counts": self.flow_counts}

    def export_state_json(self) -> str:
        """Canonical JSON of export_merge_state() — the per-seed sidecar
        fleet mode writes (shadow_tpu/fleet.py telemetry_state.json) so a
        sweep reducer can k-way merge histogram states across seeds
        without re-parsing flows.jsonl."""
        return _dumps(self.export_merge_state())

    def close_files(self) -> None:
        for f in self._fh.values():
            f.close()
        self._fh = {}

    # -- run lifecycle -----------------------------------------------------
    def start_fresh(self, controller) -> None:
        """Fresh run (not a resume): truncate stale streams from a prior
        run into this directory and write the meta record readers key on
        (resumes append — the continuation of one stream)."""
        d = self._dir(controller)
        if self.shard is not None:
            # worker: own only the per-shard flow stream; the parent owns
            # metrics.jsonl (meta record included — shard 0 ships the
            # line, its params arrays are identical on every shard)
            (d / self._flows_name()).unlink(missing_ok=True)
            if self.shard[0] == 0:
                self._out_partials.append(
                    {"kind": "meta", "line": self._meta_line(controller)})
            return
        (d / METRICS_FILE).unlink(missing_ok=True)
        (d / FLOWS_FILE).unlink(missing_ok=True)
        self._append(controller, METRICS_FILE,
                     [self._meta_line(controller)])

    def _meta_line(self, controller) -> str:
        p = controller.engine.params
        return _dumps({
            "kind": "meta",
            "version": 1,
            "sample_every": self.sample_every,
            "seed": controller.cfg.general.seed,
            "hosts": [h.name for h in controller.hosts],
            "node": p.host_node.tolist(),
            "rate_up": p.rate_up.tolist(),
            "rate_down": p.rate_down.tolist(),
            "cap_up": p.cap_up.tolist(),
            "cap_down": p.cap_down.tolist(),
        })

    # -- flow records (called from model code via Host.record_flow) --------
    def note_flow_host(self, host) -> None:
        self.flow_hosts.append(host)
        self.dirty = True

    # -- fault annotations (FaultInjector.on_apply) ------------------------
    def record_fault(self, now, rounds, action) -> None:
        rec = {"kind": "fault", "t": now, "round": rounds,
               "action": action.kind, "scheduled_t": action.t}
        if action.kind in ("link_degrade", "degrade_end"):
            ref = action.ref if action.kind == "degrade_end" else action
            rec["latency_factor"] = ref.latency_factor
            rec["loss_add"] = ref.loss_add
            rec["bandwidth_scale"] = ref.bandwidth_scale
        if action.host_ids:
            rec["hosts"] = list(action.host_ids)
        if action.src is not None:
            rec["src_nodes"] = action.src.tolist()
        if action.dst is not None:
            rec["dst_nodes"] = action.dst.tolist()
        self._fault_pending.append(rec)
        self.dirty = True

    # -- per-round hook (controller round loop) ----------------------------
    def on_round_end(self, controller, round_end) -> None:
        t0 = _walltime.perf_counter()
        self.dirty = False
        if self._fault_pending:
            recs, self._fault_pending = self._fault_pending, []
            if self.shard is not None:
                # fault application order is deterministic and identical
                # on every shard; only shard 0's collector has the
                # on_apply hook wired, and its records ship to the parent
                # (which writes them before any same-round sample — the
                # single-process on_round_end order)
                self._out_partials.extend(
                    {"kind": "fault", "line": _dumps(r)} for r in recs)
            else:
                self._append(controller, METRICS_FILE,
                             [_dumps(r) for r in recs])
        if self.flow_hosts:
            self._flush_flows(controller)
        if round_end >= self.next_sample:
            self._sample(controller, round_end)
            self.next_sample = (
                (round_end // self.sample_every) + 1) * self.sample_every
        self.wall += _walltime.perf_counter() - t0

    def _enc_str(self, v) -> str:
        """Canonical JSON encoding of a (small-cardinality) value — host
        names, peer ids, flow kinds — cached so per-record serialization
        stays off json.dumps (measured against the <=5% wall budget)."""
        s = self._enc.get(v)
        if s is None:
            s = self._enc[v] = _dumps(v)
        return s

    def _flush_flows(self, controller) -> None:
        hosts, self.flow_hosts = self.flow_hosts, []
        if len(hosts) > 1:
            hosts.sort(key=lambda h: h.id)
        rounds = controller.rounds
        counts = self.flow_counts
        lines = []
        for h in hosts:
            buf, h._flow_buf = h._flow_buf, []
            hid = h.id
            name_j = self._enc_str(h.name)
            for (kind, peer, t_open, t_close, ttfb, nbytes, status,
                 retx, x) in buf:
                lat = t_close - t_open
                if status == "ok":
                    hist = self.hist.get(kind)
                    if hist is None:
                        hist = self.hist[kind] = LogHistogram()
                    hist.add(lat)
                c = counts.get(kind)
                if c is None:
                    c = counts[kind] = {"ok": 0, "failed": 0}
                c["ok" if status == "ok" else "failed"] += 1
                if x is not None:
                    # model-defined metric (e.g. ABR selected bitrate):
                    # mergeable sum/count so the summary, the sharded
                    # parent, and the fleet reducer all derive the same
                    # mean (keys appear only for kinds that carry x)
                    c["x_sum"] = c.get("x_sum", 0) + x
                    c["x_n"] = c.get("x_n", 0) + 1
                # hand-rolled canonical JSON (keys in sorted order, the
                # _dumps separators) — byte-identical to json.dumps of
                # the same mapping, at a fraction of its cost; "x" sorts
                # last and appears only when the model provided one
                lines.append(
                    '{"bytes":%d,"flow":%s,"hid":%d,"host":%s,'
                    '"latency_ns":%d,"peer":%s,"retx":%d,"round":%d,'
                    '"status":%s,"t_close":%d,"t_open":%d,"ttfb_ns":%s%s}'
                    % (nbytes, self._enc_str(kind), hid, name_j, lat,
                       self._enc_str(peer), retx, rounds,
                       self._enc_str(status), t_close, t_open,
                       "null" if ttfb is None else "%d" % ttfb,
                       "" if x is None else ',"x":%d' % x))
            self.flows_written += len(buf)
        self._flow_lines.extend(lines)

    # -- samplers ----------------------------------------------------------
    def _sample(self, controller, t) -> None:
        eng = controller.engine
        # materialize in-flight draws so both planes (and the lazy
        # coalescing inside each) sit at the same resolution frontier;
        # result-identical, so sampling runs stay byte-identical to
        # non-sampling runs. Under the C engine this also folds the
        # C-side counter deltas into the Python attrs read below.
        eng.flush_all()
        self.samples += 1
        if self.shard is not None:
            # shard worker: gather this shard's slice — owned hosts'
            # columns + this engine's counter/bucket partials — and ship
            # it to the parent, which interleaves the per-shard slices
            # into the byte-exact single-process sample line
            own = [h for h in controller.hosts if controller.owns(h.id)]
            ids = [h.id for h in own]
            levels = eng.buckets.levels(t)
            self._out_partials.append({
                "kind": "sample", "t": t, "ids": ids,
                "cols": host_columns(own),
                "g": {"units_sent": eng.units_sent,
                      "units_dropped": eng.units_dropped,
                      "units_blackholed": eng.units_blackholed,
                      "bytes_sent": eng.bytes_sent,
                      "events": controller.events,
                      "bucket_up": levels[ids].tolist(),
                      "tokens_down": eng.tokens_down[ids].tolist()},
            })
            self.sync(controller)  # flow lines land before the sample
            return
        g = eng.telemetry_sample(t)
        g["events"] = controller.events
        # column-building stays a tight local-alias loop: the sampler runs
        # once per sample grid point over EVERY host, and its wall rides
        # the <=5% telemetry budget on the bench row
        line = format_sample_line(g, host_columns(controller.hosts),
                                  controller.rounds, t)
        self.sync(controller)  # flows land before the sample's write
        self._append(controller, METRICS_FILE, [line])
        srv = getattr(controller, "live", None)
        if srv is not None:
            # flow-group percentile snapshot at the sample grid point:
            # the same reduction as the end-of-run summary, so a follower
            # watches the distributions converge live
            srv.publish({"type": "flows_snapshot", "t": t,
                         "round": controller.rounds,
                         "flows": self.summary()["flows"]})

    # -- end of run --------------------------------------------------------
    def finalize(self, controller) -> None:
        """Flush anything still buffered (the last round's flow closes and
        fault transitions) and close the stream handles."""
        if self._fault_pending:
            recs, self._fault_pending = self._fault_pending, []
            if self.shard is not None:
                self._out_partials.extend(
                    {"kind": "fault", "line": _dumps(r)} for r in recs)
            else:
                self._append(controller, METRICS_FILE,
                             [_dumps(r) for r in recs])
        if self.flow_hosts:
            self._flush_flows(controller)
        self.sync(controller)
        self.close_files()

    def summary(self) -> dict:
        """The run-summary reduction: per-flow-class counts and streaming
        latency percentiles. Deterministic — safe for summary-equality
        gates (never in VOLATILE_SUMMARY_KEYS)."""
        flows = {}
        for kind in sorted(self.flow_counts):
            c = self.flow_counts[kind]
            row = {"count": c["ok"] + c["failed"], "ok": c["ok"],
                   "failed": c["failed"]}
            if c.get("x_n"):
                # model metric mean (ABR: mean selected bitrate, b/s)
                row["x_mean"] = c["x_sum"] // c["x_n"]
            hist = self.hist.get(kind)
            if hist is not None and hist.total:
                row.update(hist.quantiles_ns_to_ms())
            flows[kind] = row
        return {"samples": self.samples, "flows_recorded": self.flows_written,
                "flows": flows}
