"""Streaming log-bucket histograms for deterministic percentiles.

The fixed bucket layout is the whole point: every run, plane, and policy
that observes the same sample multiset produces the same bucket counts,
so histogram state can be merged (bucket-wise addition), hashed, carried
through a checkpoint, and reduced to percentiles with pure integer math —
no stored sample lists, no float accumulation order to diverge.

Layout (HDR-histogram shaped, integers >= 0):
- values below ``2**(SUB_BITS + 1)`` are exact (one bucket per value);
- above that, each power-of-two octave splits into ``2**SUB_BITS``
  sub-buckets, giving a fixed ~``2**-SUB_BITS`` relative resolution
  (~3% at the default SUB_BITS = 5) at any magnitude.

Percentiles report the LOWER BOUND of the bucket containing the target
rank — a deterministic, conservative convention (the true quantile lies
within one bucket width above it). Ranks use ceil(q * n) in exact integer
arithmetic, the "nearest-rank" definition.
"""

from __future__ import annotations

SUB_BITS = 5
_SUB = 1 << SUB_BITS
_EXACT = _SUB << 1  # values below this get exact buckets


def bucket_index(v: int) -> int:
    """Map a non-negative integer sample to its fixed bucket index."""
    if v < _EXACT:
        return v
    e = v.bit_length() - 1  # e >= SUB_BITS + 1
    sub = (v >> (e - SUB_BITS)) & (_SUB - 1)
    return _EXACT + (e - SUB_BITS - 1) * _SUB + sub


def bucket_lower_bound(idx: int) -> int:
    """Smallest value mapping to bucket ``idx`` (inverse of bucket_index
    at bucket granularity)."""
    if idx < _EXACT:
        return idx
    g, sub = divmod(idx - _EXACT, _SUB)
    e = g + SUB_BITS + 1
    return (1 << e) + (sub << (e - SUB_BITS))


class LogHistogram:
    """Sparse fixed-layout log histogram of non-negative integers."""

    __slots__ = ("counts", "total")

    def __init__(self) -> None:
        self.counts: dict[int, int] = {}
        self.total = 0

    def add(self, v: int) -> None:
        if v < 0:
            v = 0
        idx = bucket_index(v)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.total += 1

    def merge(self, other: "LogHistogram") -> None:
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.total += other.total

    def percentile(self, num: int, den: int) -> int:
        """Value at quantile num/den (e.g. 999, 1000 for p99.9): the lower
        bound of the bucket holding the ceil(q * total)-th sample (1-based
        nearest rank). Returns 0 for an empty histogram."""
        if self.total == 0:
            return 0
        rank = (self.total * num + den - 1) // den
        if rank < 1:
            rank = 1
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                return bucket_lower_bound(idx)
        return bucket_lower_bound(max(self.counts))  # unreachable

    def quantiles_ns_to_ms(self) -> dict:
        """The standard latency reduction: p50/p90/p99/p99.9 of ns samples
        reported in milliseconds (3 decimals, deterministic rounding)."""
        out = {}
        for label, num, den in (("p50", 50, 100), ("p90", 90, 100),
                                ("p99", 99, 100), ("p99_9", 999, 1000)):
            out[f"{label}_ms"] = round(self.percentile(num, den) / 1e6, 3)
        return out

    def state(self) -> dict:
        """Canonical serializable state (sorted bucket -> count)."""
        return {"total": self.total,
                "counts": {str(k): self.counts[k]
                           for k in sorted(self.counts)}}

    @classmethod
    def from_state(cls, d: dict) -> "LogHistogram":
        """Rebuild from state() output — the cross-process merge path
        (shard workers ship their histogram states to the parent, which
        merges them bucket-wise; mergeable by construction)."""
        h = cls()
        h.total = int(d["total"])
        h.counts = {int(k): int(v) for k, v in d["counts"].items()}
        return h

    @classmethod
    def merged(cls, states: list) -> "LogHistogram":
        """K-way merge of many state() dicts into one histogram — the
        cross-SEED reduction (shadow_tpu/fleet.py): pooled percentiles
        over every seed's samples. Bucket-wise addition is commutative
        and associative, so the merge order cannot change the result
        (tests/test_fleet.py asserts shuffled orders byte-identical)."""
        h = cls()
        for st in states:
            h.merge(cls.from_state(st))
        return h
