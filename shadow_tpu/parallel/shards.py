"""Multi-process host partitioning with conservative cross-shard windows.

This is the scale-out plane (ROADMAP open item 2): the host set is
partitioned across N worker processes by static id-modulo placement
(``hid % N`` — the same discipline ``thread_per_core`` uses for threads),
each worker running its own scheduler + engine (Python columnar, per-unit,
or C colcore) over its owned subset, coordinated by a parent process that
runs the SAME conservative min-latency lookahead loop the single-process
controller runs — extended across processes, which is exactly Shadow's
worker-thread barrier (Jansen & Hopper, NDSS'12) lifted to Chandy–Misra
conservative lookahead between OS processes (PAPERS.md).

The causal window is one round (round width <= min path latency), so every
cross-shard effect of round R lands at round >= R+1: a worker resolves its
own hosts' emissions completely at its barrier (closed-form departures,
threefry loss draws, arrival times, canonical keys — all pure functions of
sender-local state and unit identity), diverts rows whose destination host
lives on another shard into per-shard egress buffers, and ships them over
pickle-free shared-memory ring buffers at the round edge, followed by an
EDGE MARKER carrying that shard's reduction inputs. Workers synchronize
PEER-TO-PEER: each waits for all peers' markers of the same round and
computes the identical global decision (skip-ahead target, dynamic round
width, early end, graceful stop) the single-process loop computes from
local state — the parent process never gates a round (a pipe wake-up
costs ~0.7 ms on a loaded box; it was the dominant scale-out overhead),
it only consumes asynchronous streams: digest/telemetry partials to
merge, heartbeat stats, checkpoint notices, stop forwarding. The
receiving shard merges shipped rows into its pending store at its next
round top, in canonical (t, key) order — with the C engine attached they
parse straight into a packed CBatch (no per-row Python tuples).

Why byte-identity at ANY shard count is structural, not incidental:

- **Loss draws** are counter-based threefry keyed on (seed, uid, packet):
  placement-independent by construction.
- **Canonical event keys ARE uids** ((src << 32) | per-src seq): two
  same-instant arrivals at one destination order identically no matter
  which process resolved them (the PR that added this plane changed the
  key scheme in all three planes from a global dense counter — a
  placement-DEPENDENT quantity — to the uid).
- **Egress buckets** are per-source (owned by the emitting shard);
  **ingress buckets** are charged at the destination in canonical event
  order (owned by the delivering shard).
- **The round grid** is decided identically on every worker from the
  same marker reductions (executed counts, next-event minima including
  in-flight cross-shard arrivals, fault wake-ups) — the same decisions
  the single-process loop makes from local state.
- **Fault timelines** are pure functions of (config, seed) and broadcast:
  every shard applies every matrix rewrite; host lifecycle transitions
  mutate only owned hosts.

Output streams merge canonically: host log trees are disjoint by
ownership; sentinel digests and telemetry samples are assembled by the
parent from per-shard partials into byte-exact single-process records;
flow records merge by (round, host id) at run end. ``sim_shards: 1`` is
the unchanged single-process controller; tests/test_shards.py gates
byte-identity of trees, flows, metrics, and digest streams at 1/2/4
shards with the C engine on and off.

Checkpoints: each worker snapshots its shard at the same round boundary;
the parent writes a ``.shards.json`` manifest beside them. The shard
count rides the checkpoint header — same-count resume is byte-identical,
a mismatched count refuses by name (re-run from scratch at the new count
reproduces the same simulation anyway, by the identity above).
"""

from __future__ import annotations

import json
import marshal
import os
import pickle
import struct
import time as _walltime  # detlint: ok(wallclock): ring polling + straggler wall telemetry
from pathlib import Path

import numpy as np

from shadow_tpu.core.controller import Controller, _GC_EVERY_ROUNDS
from shadow_tpu.core.time import NS_PER_SEC, NS_PER_US, T_NEVER, format_time
from shadow_tpu.host.process import PluginProcess
from shadow_tpu.supervise import (STALL_CEILING_S, ChaosInjector,
                                  ProgressPage, progress_name,
                                  stall_deadline_s)
from shadow_tpu.utils.counters import Counters
from shadow_tpu.utils.logging import SimLogger

#: shared-memory ring capacity per directed shard pair (bytes); a round
#: edge whose packed rows exceed the free space blocks the writer (which
#: keeps draining its own inbound rings, so the pair always makes
#: progress). Override: SHADOW_TPU_RING_BYTES.
DEFAULT_RING_BYTES = 4 << 20

_NUM_FIELDS = 12  # numeric fields of a 13-field store row (payload apart)

MANIFEST_SUFFIX = ".shards.json"
MANIFEST_FORMAT = "shadow_tpu-shard-manifest"


def validate_config_shardable(cfg) -> None:
    """Build-time policy for sim_shards > 1 — named refusals only."""
    import platform

    if platform.machine() not in ("x86_64", "AMD64", "i686", "i386"):
        # the ShmRing SPSC protocol relies on x86-TSO store ordering
        # (data stores before the tail store, no explicit fence — see
        # ShmRing); a weakly-ordered CPU could observe a tail before the
        # block bytes and silently corrupt the exchange. Refuse by name
        # until the ring carries real barriers.
        raise ValueError(
            f"sim_shards > 1 requires an x86-TSO host (the shared-memory "
            f"ring protocol orders its stores by program order, not "
            f"fences); this machine is {platform.machine()!r}")
    if cfg.experimental.scheduler_policy == "tpu_mesh":
        raise ValueError(
            "sim_shards > 1 is unsupported with scheduler_policy tpu_mesh "
            "(the mesh collective plane is single-process); use tpu_batch "
            "— the shard workers run the same columnar/C engine")
    for hopts in cfg.hosts:
        if hopts.pcap_enabled:
            raise ValueError(
                f"sim_shards > 1 is unsupported with pcap capture: host "
                f"{hopts.name!r} has pcap_enabled; disable one of the two")
        for popts in hopts.processes:
            if not PluginProcess.is_plugin_path(popts.path):
                raise ValueError(
                    f"sim_shards > 1 is unsupported with managed native "
                    f"processes: host {hopts.name!r} runs {popts.path!r}; "
                    f"use pyapp: workloads or sim_shards: 1")


# -- row packing (the shared-memory wire format) ------------------------------
#
# One block per (sender, receiver, round edge): little-endian
#   [n_rows u64][numeric cols (n, 12) int64][payload lens (n,) int64][blobs]
# Payloads are marshal-encoded (bytes / str / tuples / ints / None — the
# model payload vocabulary); a negative length marks the rare pickle
# fallback. No per-row pickling on the hot path.

def pack_rows(rows: list) -> bytes:
    n = len(rows)
    if n == 0:
        return struct.pack("<q", 0)
    arr = np.empty((n, _NUM_FIELDS), dtype=np.int64)
    lens = np.empty(n, dtype=np.int64)
    blobs = []
    for i, r in enumerate(rows):
        arr[i] = r[:_NUM_FIELDS]
        p = r[_NUM_FIELDS]
        if p is None:
            lens[i] = 0
        else:
            try:
                b = marshal.dumps(p)
                lens[i] = len(b)
            except ValueError:
                b = pickle.dumps(p, protocol=4)
                lens[i] = -len(b)
            blobs.append(b)
    return b"".join([struct.pack("<q", n), arr.tobytes(), lens.tobytes()]
                    + blobs)


def unpack_rows(buf: bytes) -> list:
    (n,) = struct.unpack_from("<q", buf, 0)
    if n == 0:
        return []
    off = 8
    arr = np.frombuffer(buf, dtype=np.int64, count=n * _NUM_FIELDS,
                        offset=off).reshape(n, _NUM_FIELDS)
    off += n * _NUM_FIELDS * 8
    lens = np.frombuffer(buf, dtype=np.int64, count=n, offset=off)
    off += n * 8
    nums = arr.tolist()  # C-speed conversion to Python ints
    lens_l = lens.tolist()
    rows = []
    for i in range(n):
        ln = lens_l[i]
        if ln == 0:
            p = None
        elif ln > 0:
            p = marshal.loads(buf[off:off + ln])
            off += ln
        else:
            p = pickle.loads(buf[off:off - ln])
            off += -ln
        rows.append((*nums[i], p))
    return rows


# -- shared-memory rings ------------------------------------------------------

class ShmRing:
    """One directed shard-pair SPSC ring over a SharedMemory segment.

    Layout: [head u64][tail u64][data (cap bytes)]; blocks are
    [len u64][bytes], with a len = -1 pad marker skipping to the buffer
    end when a block would straddle the wrap point. ``head`` is owned by
    the single reader, ``tail`` by the single writer (absolute, ever-
    increasing offsets; position = offset % cap), so the two sides never
    write the same word — the writer may append round R's blocks WHILE
    the reader drains round R-1's (workers run rounds concurrently; the
    parent barrier only guarantees the previous edge's blocks are
    complete). The reader snapshots ``tail`` once: blocks appended after
    the snapshot are simply picked up at the next round start — they
    carry arrivals at least one full round ahead, so early ingestion is
    result-identical. Data stores precede the tail store in program
    order (x86-TSO keeps them ordered; the one-word header fields are
    naturally aligned). write() returns False when the ring is full —
    the worker's blocking wrapper (_write_block) drains its own inbound
    rings and retries, which is what guarantees pairwise progress.
    """

    HDR = 16

    def __init__(self, name: str, size: int = 0, create: bool = False):
        from multiprocessing import shared_memory

        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size + self.HDR)
        else:
            # attach WITHOUT resource_tracker registration: the creator
            # (parent) owns the segment's lifetime; a tracked attach
            # fights the shared tracker process over unregistration at
            # exit (cpython#82300 family)
            from multiprocessing import resource_tracker

            orig = resource_tracker.register
            resource_tracker.register = lambda *a, **k: None
            try:
                self.shm = shared_memory.SharedMemory(name=name)
            finally:
                resource_tracker.register = orig
        self.buf = self.shm.buf
        self.cap = len(self.buf) - self.HDR
        if create:
            struct.pack_into("<qq", self.buf, 0, 0, 0)

    def _pos(self, off: int) -> int:
        return self.HDR + off % self.cap

    def write(self, data: bytes) -> bool:
        (head,) = struct.unpack_from("<q", self.buf, 0)
        (tail,) = struct.unpack_from("<q", self.buf, 8)
        need = 8 + len(data)
        free = self.cap - (tail - head)
        pos = tail % self.cap
        if pos + need > self.cap:
            # pad to the wrap point so the block stays contiguous
            pad = self.cap - pos
            if need + pad > free:
                return False
            if pad >= 8:
                struct.pack_into("<q", self.buf, self.HDR + pos, -1)
            tail += pad
            struct.pack_into("<q", self.buf, 8, tail)
            pos = 0
            free -= pad
        if need > free:
            return False
        struct.pack_into("<q", self.buf, self.HDR + pos, len(data))
        self.buf[self.HDR + pos + 8:self.HDR + pos + need] = data
        struct.pack_into("<q", self.buf, 8, tail + need)
        return True

    def read_all(self) -> list:
        (head,) = struct.unpack_from("<q", self.buf, 0)
        (tail,) = struct.unpack_from("<q", self.buf, 8)  # snapshot once
        out = []
        while head < tail:
            pos = head % self.cap
            if pos + 8 > self.cap:
                head += self.cap - pos
                continue
            (ln,) = struct.unpack_from("<q", self.buf, self.HDR + pos)
            if ln < 0:  # pad marker: skip to the wrap point
                head += self.cap - pos
                continue
            start = self.HDR + pos + 8
            out.append(bytes(self.buf[start:start + ln]))
            head += 8 + ln
        struct.pack_into("<q", self.buf, 0, head)
        return out

    def close(self) -> None:
        self.buf = None
        self.shm.close()

    def unlink(self) -> None:
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


def _ring_name(tag: str, src: int, dst: int) -> str:
    return f"stpu_{tag}_{src}_{dst}"


# -- the shard worker ---------------------------------------------------------

class ShardController(Controller):
    """One worker's controller: full topology, owned-subset execution."""

    def __init__(self, cfg, shard_id: int, n_shards: int) -> None:
        self.shard_id = shard_id
        self.n_shards = n_shards
        super().__init__(cfg, mirror_log=False)
        self.engine.bind_shard(shard_id, n_shards)
        if self.telemetry is not None:
            self.telemetry.shard = (shard_id, n_shards)
        if self.faults is not None and self.telemetry is not None \
                and shard_id != 0:
            # fault application is identical on every shard; only shard
            # 0's collector annotates the timeline (the parent writes it)
            self.faults.on_apply = None

    def _log_name(self) -> str:
        return f"shadow.shard{self.shard_id}.log"


class _PeerDied(RuntimeError):
    pass


class _ShardWorker:
    """The worker side: a FREE-RUNNING conservative round loop.

    Workers synchronize peer-to-peer through the rings, not through the
    parent: each round edge ships the cross-shard rows plus a MARKER
    block carrying this shard's reduction inputs (executed count,
    immediate-work flag, next-event minimum, shipped-row minimum, fault
    wake-up, min-used-latency, stop request). Every worker waits for all
    peers' markers of the same round and computes the IDENTICAL global
    decision the single-process loop computes locally — next `now`,
    skip-ahead target, dynamic round width, early end, graceful stop.
    The parent never gates a round (a pipe wake-up costs ~0.7 ms on
    this class of box — it was the dominant sharding overhead); it only
    consumes asynchronous streams (digest/telemetry partials, heartbeat
    stats, checkpoint notices) and forwards stop requests.

    Waiting is drain-and-yield polling on the rings: while waiting (or
    blocked on a full outbound ring) a worker keeps draining its inbound
    rings — which is what guarantees the peer's blocked writes always
    make progress (no write-write deadlock). Workers can be at most one
    round apart (the marker barrier), so early-arriving next-round rows
    are bounded and result-identical to ingest (arrival times are
    clamped past their emitting round's end)."""

    def __init__(self, ctl, conn, shard_id: int, n_shards: int,
                 ring_tag: str, ring_bytes: int) -> None:
        self.ctl = ctl
        self.conn = conn
        self.k = shard_id
        self.n = n_shards
        self.rings_out = {}
        self.rings_in = {}
        for j in range(n_shards):
            if j == shard_id:
                continue
            self.rings_out[j] = ShmRing(_ring_name(ring_tag, shard_id, j))
            self.rings_in[j] = ShmRing(_ring_name(ring_tag, j, shard_id))
        self._exchange_wall = 0.0
        self._sync_wall = 0.0
        self._next_gc = _GC_EVERY_ROUNDS
        #: liveness (shadow_tpu/supervise.py): this shard's slot on the
        #: shared progress page — stamped at every round top, inside the
        #: marker wait, and inside blocked ring writes, so only a shard
        #: that is truly frozen ever goes stale (a shard merely blocked
        #: on a wedged peer keeps stamping and the right one gets named)
        self._prog = ProgressPage(progress_name(ring_tag), n_shards)
        #: observed round-wall EMA, the base of the stall deadline
        self._round_ema = 0.0
        self._t_round_top = None
        #: chaos harness (supervise.py): env-armed, this shard's events
        self._chaos = ChaosInjector.from_env(ctl.data_dir, shard=shard_id)
        #: packed ingest (C engine attached): ring bytes parse straight
        #: into a CBatch — no tuple materialization per row
        self._packed_ingest = None
        if getattr(ctl.engine, "_c", None) is not None:
            from shadow_tpu.native import _colcore

            if hasattr(_colcore, "cbatch_from_packed"):
                self._packed_ingest = _colcore.cbatch_from_packed
        #: packed SEND (C engine with the send-side packer): the core
        #: hands back ready wire blocks — BRow -> ring bytes in C, no
        #: 13-field tuples before the wire. None falls back to
        #: take_xout() + pack_rows (Python plane, older builds).
        self._take_packed = getattr(ctl.engine, "take_xout_packed", None)
        self._max_block = (min(r.cap for r in self.rings_out.values())
                           // 2 - 64) if self.rings_out else 1 << 20
        #: markers received but not yet consumed: round -> {shard: dict}
        self._markers: dict = {}
        #: row blocks received but not yet ingested: (round, rows). A
        #: block from the peer's round-r edge is ingested only once WE
        #: have completed round r (the consistent-cut rule): a fast peer
        #: may ship round r+1 rows while we sit at the r+1 boundary, and
        #: a checkpoint there must not capture rows the restored peer
        #: will re-emit (double delivery on resume).
        self._pending_rows: list = []
        self._stop_req = False  # parent asked for a graceful stop
        #: live-operations plane (shadow_tpu/live.py): commands arrive
        #: on the parent pipe (shard 0 only), ride shard 0's NEXT round
        #: marker so every worker holds the identical list, and apply at
        #: the following round boundary — the same round everywhere
        self._pending_cmds: list = []  # from the parent pipe (shard 0)
        self._marker_cmds: list = []   # from shard 0's marker, due next top
        self._cmd_stop = False         # a live `stop` command ended the run

    # -- lifecycle ---------------------------------------------------------
    def serve(self, resume_at=None) -> None:
        import gc as _gc
        import signal as _signal

        # the parent owns signal policy: a terminal Ctrl-C reaches the
        # whole process group, and a worker dying mid-protocol would turn
        # a graceful stop into a hang
        try:
            _signal.signal(_signal.SIGINT, _signal.SIG_IGN)
        except (ValueError, OSError):
            pass
        ctl = self.ctl
        tel = ctl.telemetry
        if tel is not None and resume_at is None:
            tel.start_fresh(ctl)
        if resume_at is not None and ctl._replay_cmds:
            # commands at boundaries <= resume_at were applied BEFORE the
            # checkpoint snapshot (boundary order: commands, then
            # checkpoint) — their effects are in the restored state
            while (ctl._replay_idx < len(ctl._replay_cmds)
                   and ctl._replay_cmds[ctl._replay_idx]["t"] <= resume_at):
                ctl._replay_idx += 1
        gc_was_enabled = _gc.isenabled()
        _gc.disable()
        self.conn.send(("ready", {
            "round_ns": ctl.round_ns,
            "n_hosts": len(ctl.hosts),
            "rounds": ctl.rounds,
            "events": ctl.events,
            "mul": ctl.engine.min_used_latency,
            "tel_partials": (tel.drain_partials()
                            if tel is not None else []),
        }))
        try:
            op, m = self.conn.recv()
            if op != "run":
                raise RuntimeError(f"expected run command, got {op!r}")
            self._free_run(m)
            while True:
                msg = self.conn.recv()
                if msg[0] == "finalize":
                    break
                if msg[0] != "stop":  # a stop racing our normal finish
                    raise RuntimeError(
                        f"expected finalize, got {msg[0]!r}")
            self.conn.send(("final", self._finalize(msg[1])))
        finally:
            if gc_was_enabled:
                _gc.enable()
            for r in self.rings_out.values():
                r.close()
            for r in self.rings_in.values():
                r.close()
            self._prog.close()

    # -- the free-running round loop ---------------------------------------
    def _free_run(self, m: dict) -> None:
        import gc as _gc

        ctl = self.ctl
        eng = ctl.engine
        cfg = ctl.cfg
        stop = cfg.general.stop_time
        now = m["now"]
        mul = m["mul0"]  # globally-reduced min_used_latency (resume)
        base_w = ctl.round_ns
        w = base_w
        dyn = cfg.experimental.use_dynamic_runahead
        dig = ctl.digest_every
        ck_every = ctl.ckpt_every
        next_ckpt = ((now // ck_every) + 1) * ck_every if ck_every \
            else T_NEVER
        hb = cfg.general.heartbeat_interval or 0
        next_hb = ((now // hb) + 1) * hb if hb else T_NEVER
        tel = ctl.telemetry
        faults = ctl.faults
        interrupted = False
        from shadow_tpu import checkpoint as _ckpt

        while now < stop:
            # liveness: stamp the progress word FIRST (round + wall),
            # then update the round-wall EMA the stall deadline derives
            # from — chaos fires after the stamp so a wedge freezes an
            # honest last-progress record for the peers to name
            t_top = _walltime.monotonic()
            if self._t_round_top is not None:
                dt = t_top - self._t_round_top
                self._round_ema = (dt if self._round_ema == 0.0
                                   else 0.85 * self._round_ema + 0.15 * dt)
            self._t_round_top = t_top
            self._prog.stamp(self.k, ctl.rounds)
            if self._chaos is not None:
                self._chaos.maybe_fire(ctl.rounds, ctl)
            # ingest rows shipped at edges we have barrier-passed (the
            # markers for round `ctl.rounds` were consumed last
            # iteration, and rows precede markers in ring FIFO order,
            # so every edge <= ctl.rounds is fully drained or stashed)
            self._drain_rings()
            self._ingest_ready(ctl.rounds)
            if self._marker_cmds or ctl._replay_idx < len(ctl._replay_cmds):
                # round-boundary command application (live.py contract):
                # marker-delivered live commands and due replay-log
                # entries — identical inputs on every worker, so the
                # fault timeline mutates identically everywhere
                faults = self._apply_boundary_cmds(now, faults)
                if self._cmd_stop:
                    interrupted = True
                    break
            if (ck_every and now >= next_ckpt) or ctl._ckpt_now:
                ctl._ckpt_now = False
                self._checkpoint(now)
                if ck_every:
                    next_ckpt = ((now // ck_every) + 1) * ck_every
            if faults is not None:
                faults.apply_due(now)
            if dyn:
                # the globally-reduced minimum, exactly the value the
                # single-process loop reads from its own engine
                w = max(base_w, min(mul, 10 * base_w))
            round_end = min(now + w, stop)
            eng.start_of_round(now, round_end)
            t_ev = _walltime.perf_counter()
            if ctl._c_core is not None:
                executed = ctl._c_core.run_round(round_end)
            else:
                hosts = ctl.hosts
                active = [hosts[i] for i in sorted(ctl._active)]
                executed = ctl.scheduler.run_round(round_end, active)
                for h in active:
                    if not h.equeue._heap:
                        ctl._active.discard(h.id)
            ctl._events_wall += _walltime.perf_counter() - t_ev
            eng.end_of_round(now, round_end)
            devt = getattr(eng, "devt", None)
            if devt is not None:
                # columnar-transport replays ran inside end_of_round:
                # fold them into this shard's executed count BEFORE the
                # marker reduction, exactly like Controller._round_loop
                executed += devt.take_executed()
            ctl.rounds += 1
            ctl.events += executed

            # the round edge: resolve EVERY outstanding draw batch
            # (early resolution is result-identical — flags are pure
            # functions of unit identity — and a cross-shard row must be
            # on the wire before its arrival round starts anywhere),
            # ship the diverted rows, then publish this round's marker
            t1 = _walltime.perf_counter()
            eng.flush_due(T_NEVER + 1)
            xmin = T_NEVER
            packed = (self._take_packed(self._max_block)
                      if self._take_packed is not None else None)
            if packed is not None:
                # C send-side packer: blocks are already (t, key)-sorted
                # wire bytes chunked to fit the ring; the first numeric
                # column of row 0 (offset 8) is the block's min t
                for j, blocks in enumerate(packed):
                    if j == self.k:
                        continue
                    for data in blocks:
                        (bt,) = struct.unpack_from("<q", data, 8)
                        if bt < xmin:
                            xmin = bt
                        self._write_packed(j, data)
            else:
                xout = eng.take_xout()
                for j, rows in enumerate(xout):
                    if j == self.k or not rows:
                        continue
                    if rows[0][0] < xmin:
                        xmin = rows[0][0]  # (t, key)-sorted: [0] is min t
                    self._write_rows(j, rows)
            # the next-event minimum is only consumed by the global
            # skip-ahead reduction, which requires EVERY shard to have
            # executed zero events — so a shard that executed anything
            # can ship a placeholder and skip the active-set scan (the
            # single-process loop likewise only scans on quiet rounds;
            # scanning every round cost ~13 ms/round at 100k hosts)
            if executed == 0:
                if ctl._c_core is not None:
                    nq = ctl._c_core.next_time()
                else:
                    nq = min((ctl.hosts[i].equeue.next_time()
                              for i in ctl._active), default=T_NEVER)
                nq = min(nq, eng.pending_head())
            else:
                nq = T_NEVER
            while self.conn.poll(0):
                pm = self.conn.recv()
                if pm[0] == "stop":
                    self._stop_req = True
                elif pm[0] == "cmd":
                    self._pending_cmds.append(pm[1])
                elif pm[0] == "abort":
                    raise _PeerDied("parent aborted the run")
            stats = {
                "executed": executed,
                "imm": bool(eng.has_immediate_work()),
                "next": nq,
                "xmin": xmin,
                "fnext": (faults.next_time() if faults is not None
                          else T_NEVER),
                "mul": eng.min_used_latency,
                "stop": self._stop_req,
            }
            if self.k == 0 and self._pending_cmds:
                # live commands ride shard 0's marker: every worker reads
                # the SAME list at the same round and applies it at the
                # next boundary (the parent only ever feeds shard 0)
                stats["cmds"] = self._pending_cmds
                self._pending_cmds = []
            for j in self.rings_out:
                self._write_block(j, b"M" + marshal.dumps(
                    (ctl.rounds, self.k, stats)))
            self._exchange_wall += _walltime.perf_counter() - t1

            # asynchronous streams to the parent (never round-gating)
            if dig and ctl.rounds % dig == 0:
                self.conn.send(("dig", ctl.rounds, round_end,
                                _ckpt.shard_digest_partial(ctl, round_end)))
            if tel is not None and (tel.dirty
                                    or round_end >= tel.next_sample):
                tel.on_round_end(ctl, round_end)
                parts = tel.drain_partials()
                if parts:
                    self.conn.send(("tel", ctl.rounds, parts))
            if hb and round_end >= next_hb:
                note = getattr(eng, "heartbeat_note", None)
                self.conn.send(("hb", ctl.rounds, round_end, {
                    "events": ctl.events,
                    "units_sent": eng.units_sent,
                    "units_dropped": eng.units_dropped,
                    **({"dev": note()} if note is not None else {}),
                    "phase_wall": {
                        "events": round(ctl._events_wall, 4),
                        **{pk: round(pv, 4)
                           for pk, pv in eng.phase_wall.items()}}}))
                # grid-snap (not +=): skip-ahead can jump several
                # heartbeat periods; the next beat lands on the grid so
                # every shard fires on the same sim-time cadence
                next_hb = ((round_end // hb) + 1) * hb
            if ctl.rounds >= self._next_gc:
                self._next_gc = ctl.rounds + _GC_EVERY_ROUNDS
                _gc.collect()

            # the cross-shard barrier + the global reduction: identical
            # inputs on every worker -> identical decisions
            t2 = _walltime.perf_counter()
            peers = self._wait_markers(ctl.rounds)
            self._sync_wall += _walltime.perf_counter() - t2
            s0 = stats if self.k == 0 else peers[0]
            cmds = s0.get("cmds")
            if cmds:
                # due at the NEXT loop top — after `now` advances, so the
                # command's recorded t is the boundary it applies at
                self._marker_cmds.extend(cmds)
            allm = list(peers.values())
            allm.append(stats)
            for pm2 in allm:
                if pm2["mul"] < mul:
                    mul = pm2["mul"]
            if (sum(pm2["executed"] for pm2 in allm) == 0
                    and not any(pm2["imm"] for pm2 in allm)):
                nt = min(min(pm2["next"] for pm2 in allm),
                         min(pm2["xmin"] for pm2 in allm),
                         min(pm2["fnext"] for pm2 in allm))
                if nt >= T_NEVER:
                    if self.k == 0:
                        self.conn.send(("early_end", round_end))
                    now = stop
                    break
                now = max(round_end, nt)
            else:
                now = round_end
            # graceful stop AFTER advancing now: the single-process loop
            # sees the signal at the next iteration top, with `now`
            # already at the post-round boundary — the state the final
            # checkpoint must correspond to
            if any(pm2["stop"] for pm2 in allm):
                interrupted = True
                break

        interrupted = interrupted and now < stop
        if interrupted and ck_every:
            # the graceful-stop final checkpoint, like the single-process
            # loop's post-loop snapshot (the stop reduction happened at
            # round ctl.rounds on every worker, so no later edge exists)
            self._drain_rings()
            self._ingest_ready(ctl.rounds)
            self._checkpoint(now)
        self.conn.send(("done", {
            "now": now, "rounds": ctl.rounds, "events": ctl.events,
            "interrupted": interrupted,
            **({"stop_reason": "live_stop"} if self._cmd_stop else {})}))

    def _apply_boundary_cmds(self, now: int, faults):
        """Apply round-boundary commands exactly like the single-process
        Controller._live_boundary: due replay-log entries first, then
        live commands that arrived through shard 0's round marker (the
        identical list on every worker, so every shard applies them at
        the same boundary with the same seq). Only shard 0 ships the
        canonical commands.jsonl lines to the parent — the single
        writer."""
        ctl = self.ctl
        lines: list = []
        replay = ctl._replay_cmds
        while ctl._replay_idx < len(replay) \
                and replay[ctl._replay_idx]["t"] <= now:
            rec = replay[ctl._replay_idx]
            ctl._replay_idx += 1
            if rec.get("wall_only"):
                continue  # pause/resume never touched sim state
            faults = ctl._apply_cmd(rec["cmd"], now, rec["seq"], lines,
                                    faults, replayed=True)
        for norm in self._marker_cmds:
            ctl._live_seq += 1
            faults = ctl._apply_cmd(norm, now, ctl._live_seq, lines,
                                    faults, replayed=False)
        self._marker_cmds = []
        if ctl._interrupt == "live_stop":
            self._cmd_stop = True
            ctl._interrupt = None  # the parent owns the summary's signal
        if lines and self.k == 0:
            self.conn.send(("cmdlog", lines))
        return faults

    # -- ring plumbing -----------------------------------------------------
    def _drain_rings(self) -> None:
        """Drain every inbound ring: stash row blocks (by emitting
        round) and marker blocks (by round). Ingestion happens at round
        tops via _ingest_ready — the consistent-cut rule above."""
        for ring in self.rings_in.values():
            for blob in ring.read_all():
                if blob[0:1] == b"R":
                    (rnd,) = struct.unpack_from("<q", blob, 1)
                    self._pending_rows.append((rnd, blob[9:]))
                else:
                    rnd, src, stats = marshal.loads(blob[1:])
                    self._markers.setdefault(rnd, {})[src] = stats

    def _ingest_ready(self, limit_round: int) -> None:
        """Ingest every stashed row block whose emitting round we have
        completed ourselves (<= limit_round): those are exactly the rows
        the single-process twin would hold resolved at this boundary.
        The marker barrier bounds peers to one round ahead, so the
        stash never grows past one round of traffic."""
        if not self._pending_rows:
            return
        eng = self.ctl.engine
        fast = self._packed_ingest
        keep = []
        for rnd, blob in self._pending_rows:
            if rnd > limit_round:
                keep.append((rnd, blob))
            elif fast is not None and getattr(eng, "_c", None) is not None:
                # packed C path: wire bytes -> CBatch, no row tuples
                eng.pending.append(fast(blob))
            else:
                eng.ingest_remote(unpack_rows(blob))
        self._pending_rows = keep

    def _write_block(self, j: int, data: bytes) -> None:
        """Blocking ring write: while the peer's ring is full, keep
        draining our own inbound rings (the peer may itself be blocked
        writing to us — draining is what guarantees global progress).
        Stamping while blocked keeps this shard's liveness word fresh,
        so a shard stuck behind a WEDGED peer is never misnamed as the
        failure itself."""
        import os as _os

        ring = self.rings_out[j]
        spins = 0
        while not ring.write(data):
            self._drain_rings()
            spins += 1
            if spins & 1023 == 0:
                self._prog.stamp(self.k, self.ctl.rounds)
            _os.sched_yield()

    def _write_rows(self, j: int, rows: list) -> None:
        """Ship rows to shard j tagged with the emitting round, chunked
        so every block fits the ring (chunks of a (t, key)-sorted list
        stay sorted; each becomes its own pending batch)."""
        data = pack_rows(rows)
        if 9 + len(data) > self.rings_out[j].cap // 2 and len(rows) > 1:
            mid = len(rows) // 2
            self._write_rows(j, rows[:mid])
            self._write_rows(j, rows[mid:])
            return
        if 9 + len(data) + 8 > self.rings_out[j].cap:
            # a SINGLE row bigger than the ring can never ship: fail by
            # name instead of spinning in _write_block forever (the peer
            # would only see a 3600 s barrier timeout)
            raise _PeerDied(
                f"shard {self.k}: one cross-shard row packs to "
                f"{len(data)} bytes, larger than the "
                f"{self.rings_out[j].cap}-byte ring — raise "
                f"SHADOW_TPU_RING_BYTES")
        self._write_block(
            j, b"R" + struct.pack("<q", self.ctl.rounds) + data)

    def _write_packed(self, j: int, data: bytes) -> None:
        """Ship one C-packed wire block (sorted + chunked at the packer)
        tagged with the emitting round."""
        if 9 + len(data) + 8 > self.rings_out[j].cap:
            # the packer chunks at half the ring, so only a SINGLE row
            # bigger than the ring lands here: fail by name (the
            # _write_rows discipline)
            raise _PeerDied(
                f"shard {self.k}: one packed cross-shard block is "
                f"{len(data)} bytes, larger than the "
                f"{self.rings_out[j].cap}-byte ring — raise "
                f"SHADOW_TPU_RING_BYTES")
        self._write_block(
            j, b"R" + struct.pack("<q", self.ctl.rounds) + data)

    def _wait_markers(self, rnd: int) -> dict:
        """Spin (drain + sched_yield) until every peer's marker for
        ``rnd`` arrived. Checks the parent pipe for aborts on a coarse
        cadence. The wait is DEADLINED (supervise.stall_deadline_s over
        the observed round-wall EMA): on expiry the missing peers'
        progress stamps decide — a peer whose stamp is stale past the
        deadline is dead or wedged and gets NAMED (shard id, last round,
        stamp age); peers still stamping are merely slow and the wait
        extends, up to the absolute ceiling."""
        import os as _os

        want = self.n - 1
        deadline_s = stall_deadline_s(self._round_ema)
        t_wait0 = _walltime.monotonic()
        deadline = t_wait0 + deadline_s
        spins = 0
        while True:
            got = self._markers.get(rnd)
            if got is not None and len(got) == want:
                return self._markers.pop(rnd)
            self._drain_rings()
            spins += 1
            if spins & 1023 == 0:
                self._prog.stamp(self.k, rnd)  # waiting IS liveness
                while self.conn.poll(0):
                    pm = self.conn.recv()
                    if pm[0] == "stop":
                        self._stop_req = True
                    elif pm[0] == "cmd":
                        self._pending_cmds.append(pm[1])
                    elif pm[0] == "abort":
                        raise _PeerDied("parent aborted the run")
                now_w = _walltime.monotonic()
                if now_w > deadline:
                    stale = self._stale_peers(rnd, deadline_s)
                    if stale:
                        raise _PeerDied(
                            f"shard {self.k}: no round-{rnd} marker "
                            f"after {now_w - t_wait0:.1f}s (deadline "
                            f"{deadline_s:.1f}s) — " + "; ".join(stale))
                    if now_w - t_wait0 > STALL_CEILING_S:
                        raise _PeerDied(
                            f"shard {self.k}: no round-{rnd} marker "
                            f"within {STALL_CEILING_S:.0f}s despite live "
                            f"peer stamps — marker livelock")
                    # every missing peer stamped recently: slow, not
                    # dead — extend one deadline and keep waiting
                    deadline = now_w + deadline_s
            _os.sched_yield()

    def _stale_peers(self, rnd: int, deadline_s: float) -> list:
        """Name the peers whose round-``rnd`` marker is missing AND whose
        progress stamp is stale past the deadline."""
        got = self._markers.get(rnd) or {}
        out = []
        for j in self.rings_in:
            if j in got:
                continue
            age = self._prog.age_s(j)
            if age > deadline_s:
                r_j, _ns = self._prog.read(j)
                out.append(
                    f"shard {j} last stamped round {r_j} "
                    f"{'never' if age == float('inf') else f'{age:.1f}s ago'}"
                    f" (dead or wedged)")
        return out

    def _checkpoint(self, now: int) -> None:
        from shadow_tpu import checkpoint as _ckpt

        ctl = self.ctl
        # ring-resident cross-shard arrivals are part of this shard's
        # state at the boundary: _drain_rings ran just before, so the
        # pending store is complete (the single-process twin has them in
        # its store already)
        if ctl.telemetry is not None:
            ctl.telemetry.sync(ctl)
        t0 = _walltime.perf_counter()
        self._prog.stamp(self.k, ctl.rounds)  # a long save is not a stall
        path = _ckpt.save_checkpoint(ctl, now)
        self._prog.stamp(self.k, ctl.rounds)
        ctl._ckpt_wall += _walltime.perf_counter() - t0
        self.conn.send(("ckpt_done", ctl.rounds, now, str(path)))

    def _finalize(self, end_time: int) -> dict:
        ctl = self.ctl
        eng = ctl.engine
        eng.flush_all()
        telp = []
        tel_state = None
        if ctl.telemetry is not None:
            ctl.telemetry.finalize(ctl)
            telp = ctl.telemetry.drain_partials()
            tel_state = ctl.telemetry.export_merge_state()
        errors = []
        for p in ctl.processes:
            err = p.check_final_state()
            if err is not None:
                errors.append((p.host.id, err))
                ctl.log.error(err)
        for p in ctl.processes:
            reap = getattr(p, "reap", None)
            if reap is not None:
                reap()
        for h in ctl.hosts:
            if not ctl.owns(h.id):
                continue
            h.fold_counters()
            ctl.counters.merge(h.counters)
        close = getattr(eng, "close", None)
        if close is not None:
            close()
        ctl.data_dir.mkdir(parents=True, exist_ok=True)
        for h in ctl.hosts:
            if ctl.owns(h.id):
                h.flush_logs(ctl.data_dir)
        ctl.log.info(ctl.counters.summary())
        ctl.log.flush()
        import resource

        phase = {
            "events": round(ctl._events_wall, 4),
            **{k: round(v, 4) for k, v in eng.phase_wall.items()},
            "exchange": round(self._exchange_wall, 4),
            "sync": round(self._sync_wall, 4),
            **({"telemetry": round(ctl.telemetry.wall, 4)}
               if ctl.telemetry is not None else {}),
            **({"checkpoint": round(ctl._ckpt_wall, 4)}
               if ctl._ckpt_wall else {}),
        }
        return {
            "events": ctl.events,
            "rounds": ctl.rounds,
            "units_sent": eng.units_sent,
            "units_dropped": eng.units_dropped,
            "units_blackholed": eng.units_blackholed,
            "bytes_sent": eng.bytes_sent,
            "counters": dict(ctl.counters.c),
            "process_errors": errors,
            "phase_wall": phase,
            "max_rss_mb": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024,
                1),
            "fault_transitions_applied": (ctl.faults.applied
                                          if ctl.faults is not None
                                          else None),
            "tel": telp,
            "tel_state": tel_state,
        }


def _worker_main(conn, cfg, shard_id: int, n_shards: int, ring_tag: str,
                 ring_bytes: int, resume_path) -> None:
    """Worker process entry (multiprocessing spawn target)."""
    try:
        # the device draw plane stays off in workers: draw routing is
        # pure wall-clock policy (bit-identical either way), and N
        # workers each attaching a JAX platform would serialize on the
        # one device anyway. The numpy/C twins carry the draws.
        cfg.experimental.tpu_device_floor = -1
        if resume_path is not None:
            from shadow_tpu import checkpoint as _ckpt

            ctl, resume_at = _ckpt.load_checkpoint(resume_path, cfg,
                                                   mirror_log=False)
            if not isinstance(ctl, ShardController):
                raise _ckpt.CheckpointError(
                    f"{resume_path}: not a shard checkpoint")
            if ctl.telemetry is not None:
                ctl.telemetry.shard = (shard_id, n_shards)
        else:
            ctl = ShardController(cfg, shard_id, n_shards)
            resume_at = None
        worker = _ShardWorker(ctl, conn, shard_id, n_shards, ring_tag,
                              ring_bytes)
        worker.serve(resume_at)
    except BaseException as exc:
        import traceback

        try:
            conn.send(("error", str(exc), traceback.format_exc()))
        except Exception:
            pass
        raise


# -- the parent coordinator ---------------------------------------------------

class _ShardError(RuntimeError):
    pass


class ShardedRun:
    """Parent process: spawns N workers, drives the global round loop
    (the exact decision twin of Controller._round_loop), merges output
    streams, and assembles the run summary."""

    #: process-wide spawn counter: uniquifies ring/page tags across the
    #: restart attempts a supervisor makes inside one wall second
    _spawn_seq = 0

    def __init__(self, cfg, mirror_log: bool = True,
                 resume_from=None) -> None:
        validate_config_shardable(cfg)
        self.cfg = cfg
        self.n = int(cfg.general.sim_shards)
        self.data_dir = Path(cfg.general.data_directory)
        self.data_dir.mkdir(parents=True, exist_ok=True)
        self.log = SimLogger(cfg.general.log_level,
                             self.data_dir / "shadow.log",
                             mirror_stderr=mirror_log)
        from shadow_tpu.network.graph import load_graph

        self.graph = load_graph(cfg.network["graph"])
        w = self.graph.min_latency_ns
        if cfg.experimental.runahead is not None:
            w = cfg.experimental.runahead
        self.round_ns = max(int(w), NS_PER_US)
        self.rounds = 0
        self.events = 0
        self._interrupt = None
        self._partial = False
        self.resume_at = None
        self._resume_paths = None
        if resume_from is not None:
            self._prepare_resume(resume_from)
        self.ckpt_dir = (Path(cfg.general.checkpoint_dir)
                         if cfg.general.checkpoint_dir
                         else self.data_dir / "checkpoints")
        self._metrics_fh = None
        # live-operations plane (shadow_tpu/live.py): the PARENT owns
        # the socket — workers never bind. Commands are forwarded to
        # shard 0 and ride its round marker so every worker applies
        # them at the same boundary; shard 0 ships the canonical
        # commands.jsonl lines back and the parent is the single writer.
        self.live = None
        if cfg.general.live_endpoint:
            from shadow_tpu import live as _live

            self.live = _live.LiveServer(
                _live.resolve_endpoint(cfg.general.live_endpoint,
                                       self.data_dir),
                log=self.log, refuse=self._refuse_cmd)

    @staticmethod
    def _refuse_cmd(norm):
        if norm["cmd"] in ("pause", "resume"):
            # free-running workers synchronize peer-to-peer; the parent
            # cannot wall-block them at a shared boundary without a
            # round-gating channel the design deliberately lacks
            return (f"{norm['cmd']!r} is single-process only: sharded "
                    f"workers free-run and cannot wall-block at a "
                    f"shared round boundary")
        return None

    # -- resume ------------------------------------------------------------
    def _prepare_resume(self, resume_from) -> None:
        from shadow_tpu import checkpoint as _ckpt

        p = Path(resume_from)
        if p.name.endswith(MANIFEST_SUFFIX):
            try:
                doc = json.loads(p.read_text())
            except (OSError, ValueError) as exc:
                raise _ckpt.CheckpointError(
                    f"{p}: unreadable shard manifest ({exc})") from exc
            if doc.get("format") != MANIFEST_FORMAT:
                raise _ckpt.CheckpointError(
                    f"{p}: not a shard-checkpoint manifest")
            files = [p.parent / f for f in doc["files"]]
            n = int(doc["sim_shards"])
        else:
            header = _ckpt.read_header(p)
            n = int(header.get("sim_shards", 1))
            shard = header.get("shard")
            if n == 1 or shard is None:
                raise _ckpt.CheckpointError(
                    f"{p}: single-process checkpoint (sim_shards=1) but "
                    f"this invocation has sim_shards={self.n} — the host "
                    f"partition is part of the snapshot's identity; "
                    f"resume with sim_shards=1 or re-run from scratch")
            stem = p.name.replace(f".shard{shard}.ckpt", "")
            files = [p.parent / f"{stem}.shard{k}.ckpt" for k in range(n)]
        if n != self.n:
            raise _ckpt.CheckpointError(
                f"{resume_from}: checkpoint written with sim_shards={n} "
                f"but this invocation has sim_shards={self.n} — resume "
                f"with general.sim_shards={n} (results are byte-identical "
                f"at any shard count, so a from-scratch run at the new "
                f"count reproduces the same simulation)")
        for f in files:
            if not f.is_file():
                raise _ckpt.CheckpointError(
                    f"shard checkpoint set incomplete: {f} missing")
        header = _ckpt.read_header(files[0])
        self.resume_at = int(header["sim_time_ns"])
        self.rounds = int(header["rounds"])
        self._resume_paths = [str(f) for f in files]

    # -- worker management -------------------------------------------------
    def _spawn(self):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        ring_bytes = int(os.environ.get("SHADOW_TPU_RING_BYTES",
                                        DEFAULT_RING_BYTES))
        # the spawn sequence keeps tags unique across supervised restart
        # attempts inside one parent second (ring + page names collide
        # otherwise if a prior attempt's unlink was lost)
        seq = ShardedRun._spawn_seq
        ShardedRun._spawn_seq += 1
        self._ring_tag = (f"{os.getpid():x}"
                          f"{int(_walltime.time()) & 0xFFFF:x}{seq:x}")
        # liveness board (shadow_tpu/supervise.py): created before the
        # workers so their attach in _ShardWorker.__init__ always finds it
        self._prog = ProgressPage(progress_name(self._ring_tag), self.n,
                                  create=True)
        self._rings = []
        for i in range(self.n):
            for j in range(self.n):
                if i != j:
                    self._rings.append(ShmRing(
                        _ring_name(self._ring_tag, i, j), ring_bytes,
                        create=True))
        self._conns = []
        self._procs = []
        for k in range(self.n):
            parent_conn, child_conn = ctx.Pipe()
            p = ctx.Process(
                target=_worker_main,
                args=(child_conn, self.cfg, k, self.n, self._ring_tag,
                      ring_bytes,
                      (self._resume_paths[k] if self._resume_paths
                       else None)),
                name=f"shadow-shard-{k}", daemon=True)
            p.start()
            child_conn.close()
            self._conns.append(parent_conn)
            self._procs.append(p)

    def _recv(self, k: int):
        """Receive one protocol message from worker k, surfacing worker
        errors (and worker death) as named failures."""
        conn = self._conns[k]
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                raise _ShardError(
                    f"shard worker {k} died (exit code "
                    f"{self._procs[k].exitcode})")
            if msg[0] == "error":
                raise _ShardError(
                    f"shard worker {k} failed: {msg[1]}\n{msg[2]}")
            return msg

    def _broadcast(self, msg) -> None:
        for conn in self._conns:
            conn.send(msg)

    def _teardown(self) -> None:
        if getattr(self, "live", None) is not None:
            self.live.close()  # idempotent; covers the error paths
        for p in getattr(self, "_procs", []):
            if p.is_alive():
                p.terminate()
        for p in getattr(self, "_procs", []):
            p.join(timeout=5)
        for r in getattr(self, "_rings", []):
            r.close()
            r.unlink()
        if getattr(self, "_prog", None) is not None:
            self._prog.close()
            self._prog.unlink()
            self._prog = None

    # -- stream assembly ---------------------------------------------------
    def _metrics_append(self, lines: list) -> None:
        from shadow_tpu.telemetry.collector import METRICS_FILE

        if self._metrics_fh is None:
            d = (Path(self.cfg.telemetry.metrics_dir)
                 if self.cfg.telemetry.metrics_dir else self.data_dir)
            d.mkdir(parents=True, exist_ok=True)
            self._metrics_fh = open(d / METRICS_FILE, "a")
        self._metrics_fh.write("\n".join(lines) + "\n")
        if self.live is not None:
            # tee the merged stream to live followers (the wall-clock
            # plane: record ordering equals file ordering)
            self.live.publish_stream(METRICS_FILE, lines)

    def _handle_tel_partials(self, parts_by_shard: list,
                             rounds: int) -> None:
        """Write one round's metrics records in single-process order:
        shard 0's meta/fault lines first, then the assembled sample."""
        from shadow_tpu.telemetry.collector import format_sample_line

        lines = []
        samples = []  # (shard, partial)
        for k, parts in enumerate(parts_by_shard):
            for p in parts or ():
                if p["kind"] in ("meta", "fault"):
                    lines.append(p["line"])
                else:
                    samples.append((k, p))
        if samples:
            H = self._n_hosts
            # column names come from the shipped partial itself (the
            # host_columns contract), so a new sampler column cannot be
            # silently dropped at the merge
            names = sorted(samples[0][1]["cols"])
            cols = {nm: [0] * H for nm in names}
            bucket = [0] * H
            tokens = [0] * H
            g = {"units_sent": 0, "units_dropped": 0,
                 "units_blackholed": 0, "bytes_sent": 0, "events": 0}
            t = samples[0][1]["t"]
            for _k, p in samples:
                ids = p["ids"]
                for nm in names:
                    col = cols[nm]
                    vals = p["cols"][nm]
                    for i, hid in enumerate(ids):
                        col[hid] = vals[i]
                pg = p["g"]
                for i, hid in enumerate(ids):
                    bucket[hid] = pg["bucket_up"][i]
                    tokens[hid] = pg["tokens_down"][i]
                for key in g:
                    g[key] += pg[key]
            g["bucket_up"] = bucket
            g["tokens_down"] = tokens
            lines.append(format_sample_line(g, cols, rounds, t))
        if lines:
            self._metrics_append(lines)

    def _merge_flows(self) -> None:
        """K-way merge of the per-shard flow streams into the canonical
        flows.jsonl, ordered by (round, host id) — the single-process
        flush order (records of one host stay in their shard-local
        order, which is event-execution order)."""
        from shadow_tpu.telemetry.collector import FLOWS_FILE

        d = (Path(self.cfg.telemetry.metrics_dir)
             if self.cfg.telemetry.metrics_dir else self.data_dir)
        recs = []
        for k in range(self.n):
            f = d / f"flows.shard{k}.jsonl"
            if not f.is_file():
                continue
            with open(f) as fh:
                for i, line in enumerate(fh):
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    rec = json.loads(line)
                    recs.append((rec["round"], rec["hid"], i, line))
        recs.sort(key=lambda r: (r[0], r[1], r[2]))
        with open(d / FLOWS_FILE, "w") as out:
            for _r, _h, _i, line in recs:
                out.write(line + "\n")

    def _emit_digest(self, parts: list, round_end, rounds: int) -> None:
        from shadow_tpu import checkpoint as _ckpt

        g, hosts = _ckpt.merge_shard_digests(parts, round_end,
                                             rounds, self._n_hosts)
        rec = {"round": rounds, "t": round_end, "digest": g,
               "hosts": hosts}
        with open(self.data_dir / _ckpt.DIGEST_FILE, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        # shard-tagged sidecar streams (tools/bisect_divergence.py
        # --shard K): one sentinel stream per shard over its OWNED hosts
        # plus a digest of its slice of the global observables — when a
        # cross-shard run ever diverges, the bisection names the first
        # divergent round AND shard, not just the merged record
        for k, p in enumerate(parts):
            pg = _ckpt._digest({
                "counters": [p["events"], p["units_sent"],
                             p["units_dropped"], p["units_blackholed"],
                             p["bytes_sent"], p["ev_key"],
                             p["last_refill"]],
                "tokens_down": p["tokens_down"],
                "bucket_avail": p["bucket_avail"],
                "faults": p["faults"],
                "hosts": p["hosts"],
            })
            srec = {"round": rounds, "t": round_end, "shard": k,
                    "digest": pg, "hosts": p["hosts"]}
            with open(self.data_dir
                      / f"state_digests.shard{k}.jsonl", "a") as f:
                f.write(json.dumps(srec, sort_keys=True) + "\n")

    # -- signals -----------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        import signal as _signal

        if self._interrupt is not None:
            raise KeyboardInterrupt
        self._interrupt = _signal.Signals(signum).name

    # -- the run -----------------------------------------------------------
    def run(self) -> dict:
        self._spawn()
        try:
            return self._run_inner()
        except _ShardError:
            # one worker failed: tell the others to stop spinning at
            # their marker barrier (best effort — teardown terminates
            # whatever does not listen)
            for conn in self._conns:
                try:
                    conn.send(("abort",))
                except (OSError, ValueError):
                    pass
            raise
        finally:
            if self._metrics_fh is not None:
                self._metrics_fh.close()
            self._teardown()

    def _run_inner(self) -> dict:
        import signal as _signal
        import threading as _threading
        from shadow_tpu import checkpoint as _ckpt

        cfg = self.cfg
        stop = cfg.general.stop_time
        w = self.round_ns
        now = self.resume_at if self.resume_at is not None else 0
        dig = cfg.general.state_digest_every
        if dig and self.resume_at is None:
            (self.data_dir / _ckpt.DIGEST_FILE).unlink(missing_ok=True)
            for p in sorted(self.data_dir.glob("state_digests.shard*.jsonl")):
                p.unlink()
        if (self.live is not None or cfg.general.replay_commands) \
                and self.resume_at is None:
            # fresh run: commands.jsonl is an output artifact (replay
            # reads from wherever general.replay_commands points)
            from shadow_tpu import live as _live

            _live.command_log_path(self.data_dir).unlink(missing_ok=True)
        tel = cfg.telemetry
        if tel is not None and self.resume_at is None:
            # fresh run: truncate stale streams BEFORE the ready
            # partials land (shard 0 ships the meta record in its ready)
            from shadow_tpu.telemetry.collector import (FLOWS_FILE,
                                                        METRICS_FILE)

            d = (Path(tel.metrics_dir) if tel.metrics_dir
                 else self.data_dir)
            if d.is_dir():
                (d / METRICS_FILE).unlink(missing_ok=True)
                (d / FLOWS_FILE).unlink(missing_ok=True)
        readies = [self._recv(k)[1] for k in range(self.n)]
        # the run clock starts when every worker is built and ready: the
        # parallel worker builds are warm-up (the single-process summary
        # likewise excludes Controller construction from wall_seconds)
        t0 = _walltime.perf_counter()
        #: supervision MTTR anchor (supervise.run_supervised): the wall
        #: instant this attempt reached ready, monotonic clock
        self.t_first_ready = _walltime.monotonic()
        self._n_hosts = readies[0]["n_hosts"]
        self.events = sum(r["events"] for r in readies)
        mul = min(r["mul"] for r in readies)
        startup_tel = [r["tel_partials"] for r in readies]
        if any(startup_tel):
            self._handle_tel_partials(startup_tel, self.rounds)
        self.log.info(
            f"simulation {'resuming' if self.resume_at is not None else 'starting'}: "
            f"{self._n_hosts} hosts over {self.n} shard processes "
            f"(id-modulo placement), round width {format_time(w)}, "
            f"policy {cfg.experimental.scheduler_policy}, "
            f"stop {format_time(stop)}")
        self._partial = False
        self._interrupt = None
        installed = {}
        if _threading.current_thread() is _threading.main_thread():
            for s in (_signal.SIGINT, _signal.SIGTERM):
                try:
                    installed[s] = _signal.signal(s, self._on_signal)
                except (ValueError, OSError):
                    pass
        # release the free-running workers: they synchronize peer-to-peer
        # through the rings (edge markers carry the reduction inputs) and
        # compute the global round decisions themselves; this loop only
        # consumes their asynchronous streams
        self._broadcast(("run", {"now": now, "mul0": mul}))
        from multiprocessing.connection import wait as _mpwait

        done = [None] * self.n
        digbuf: dict = {}   # round -> (t, {shard: partial})
        ckptbuf: dict = {}  # round -> (now, {shard: path})
        hbbuf: dict = {}    # round -> (t, {shard: stats})
        telbuf: dict = {}   # round -> {shard: parts}
        self._last_seen = [self.rounds] * self.n
        stop_sent = [False] * self.n
        # full-ring-stall backstop: peers name a SINGLE dead/wedged shard
        # themselves (the _wait_markers deadline); only a stall of EVERY
        # worker at once leaves no survivor to raise — the parent detects
        # that from a frozen progress-page snapshot. The floor is larger
        # than the worker-side one: simultaneous long checkpoints are the
        # legitimate all-quiet case.
        prog_snap = self._prog.snapshot()
        prog_wall = _walltime.monotonic()
        prog_ema = 0.0
        parent_floor = float(os.environ.get(
            "SHADOW_TPU_PARENT_STALL_FLOOR_S", "120"))
        try:
            while any(d is None for d in done):
                snap = self._prog.snapshot()
                now_w = _walltime.monotonic()
                if snap != prog_snap:
                    dt = now_w - prog_wall
                    prog_ema = (dt if prog_ema == 0.0
                                else 0.85 * prog_ema + 0.15 * dt)
                    prog_snap, prog_wall = snap, now_w
                elif (now_w - prog_wall
                      > max(stall_deadline_s(prog_ema), parent_floor)):
                    raise _ShardError(
                        f"full-ring stall: no shard has stamped progress "
                        f"for {now_w - prog_wall:.1f}s (rounds "
                        f"{[r for r, _s in snap]}) — every worker is "
                        f"wedged or the box is livelocked")
                if self._interrupt is not None:
                    for k in range(self.n):
                        if done[k] is None and not stop_sent[k]:
                            self._conns[k].send(("stop",))
                            stop_sent[k] = True
                if self.live is not None and done[0] is None \
                        and not stop_sent[0]:
                    for norm in self.live.poll_commands():
                        self._conns[0].send(("cmd", norm))
                ready = _mpwait(self._conns, timeout=0.25)
                for conn in ready:
                    k = self._conns.index(conn)
                    if done[k] is not None:
                        continue
                    msg = self._recv(k)
                    op = msg[0]
                    if op == "dig":
                        _r, t, part = msg[1], msg[2], msg[3]
                        slot = digbuf.setdefault(_r, (t, {}))
                        slot[1][k] = part
                        self._note_round(k, _r)
                        if len(slot[1]) == self.n:
                            digbuf.pop(_r)
                            self._emit_digest(
                                [slot[1][i] for i in range(self.n)],
                                t, _r)
                    elif op == "tel":
                        _r, parts = msg[1], msg[2]
                        telbuf.setdefault(_r, {})[k] = parts
                        self._note_round(k, _r)
                    elif op == "hb":
                        _r, t, stats = msg[1], msg[2], msg[3]
                        slot = hbbuf.setdefault(_r, (t, {}))
                        slot[1][k] = stats
                        self._note_round(k, _r)
                        if len(slot[1]) == self.n:
                            hbbuf.pop(_r)
                            self._heartbeat(_r, t, slot[1], t0)
                    elif op == "ckpt_done":
                        _r, t, path = msg[1], msg[2], msg[3]
                        slot = ckptbuf.setdefault(_r, (t, {}))
                        slot[1][k] = path
                        self._note_round(k, _r)
                        if len(slot[1]) == self.n:
                            ckptbuf.pop(_r)
                            self._write_manifest(
                                [slot[1][i] for i in range(self.n)],
                                t, _r)
                    elif op == "cmdlog":
                        # shard 0 applied round-boundary commands: the
                        # parent is the single commands.jsonl writer and
                        # the live broadcaster
                        from shadow_tpu import live as _live

                        _live.append_command_lines(self.data_dir, msg[1])
                        if self.live is not None:
                            for ln in msg[1]:
                                self.live.publish(
                                    {"type": "command", **json.loads(ln)})
                    elif op == "early_end":
                        self.log.info(
                            f"no further events at "
                            f"{format_time(msg[1])}; ending early")
                    elif op == "done":
                        done[k] = msg[1]
                        self._last_seen[k] = 1 << 62
                    else:
                        raise _ShardError(
                            f"unexpected worker message {op!r}")
                self._flush_tel(telbuf)
        finally:
            for s, old in installed.items():
                _signal.signal(s, old)
        self._flush_tel(telbuf, force=True)
        # every worker computed the same global decisions: verify
        for d in done[1:]:
            if (d["now"], d["rounds"]) != (done[0]["now"],
                                           done[0]["rounds"]):
                raise _ShardError(
                    f"shard decision divergence: {done[0]} vs {d}")
        now = done[0]["now"]
        self.rounds = done[0]["rounds"]
        self.events = sum(d["events"] for d in done)
        self._partial = done[0]["interrupted"]
        if self._partial and self._interrupt is None:
            # a live `stop` command ended the run inside the workers;
            # surface it as the summary's interrupt_signal
            self._interrupt = done[0].get("stop_reason")
        if self._partial:
            self.log.warning(
                f"{self._interrupt or 'stop'} received: stopped "
                f"gracefully at round boundary {format_time(now)} "
                f"({self.rounds} rounds); summary is partial")
        end_time = min(now, stop)
        self._broadcast(("finalize", end_time))
        finals = [self._recv(k)[1] for k in range(self.n)]
        wall = _walltime.perf_counter() - t0
        result = self._summary(finals, end_time, wall)
        if self.live is not None:
            self.live.publish({"type": "end",
                               "exit_reason": result["exit_reason"],
                               "rounds": self.rounds, "t": end_time})
            self.live.close()
        return result

    def _note_round(self, k: int, rnd: int) -> None:
        if rnd > self._last_seen[k]:
            self._last_seen[k] = rnd

    def _flush_tel(self, telbuf: dict, force: bool = False) -> None:
        """Write buffered telemetry rounds in order. A round is ready
        when its sample is complete (all N partials — samples fire on
        the same round grid everywhere) or when every worker's stream
        has demonstrably passed it (fault-line-only rounds); later
        rounds never flush past a pending earlier one."""
        if not telbuf:
            return
        floor = min(self._last_seen)
        for rnd in sorted(telbuf):
            parts = telbuf[rnd]
            n_samples = sum(1 for ps in parts.values()
                            for p in ps if p["kind"] == "sample")
            if not (force or rnd <= floor or n_samples == self.n):
                break
            telbuf.pop(rnd)
            self._handle_tel_partials(
                [parts.get(i) for i in range(self.n)], rnd)

    def _heartbeat(self, rnd: int, t, stats: dict, t0: float) -> None:
        wall = _walltime.perf_counter() - t0
        rate = (t / NS_PER_SEC) / wall if wall else 0.0
        ev = sum(s["events"] for s in stats.values())
        sent = sum(s["units_sent"] for s in stats.values())
        drop = sum(s["units_dropped"] for s in stats.values())
        if self.live is not None:
            # merged heartbeat (same shape as the single-process record)
            # plus one shard_status per worker with its wall-phase and
            # device-note detail — followers see per-shard skew live
            self.live.publish({
                "type": "hb", "t": t, "round": rnd,
                "events": ev, "units_sent": sent, "units_dropped": drop,
                "shards": self.n,
                "wall": {"seconds": round(wall, 3),
                         "rate": round(rate, 3)},
            })
            for k in sorted(stats):
                s = stats[k]
                self.live.publish({
                    "type": "shard_status", "shard": k, "t": t,
                    "round": rnd, "events": s["events"],
                    "units_sent": s["units_sent"],
                    "units_dropped": s["units_dropped"],
                    **({"dev": s["dev"]} if "dev" in s else {}),
                    **({"phase_wall": s["phase_wall"]}
                       if "phase_wall" in s else {}),
                })
        self.log.info(
            f"heartbeat: sim {format_time(t)} wall {wall:.1f}s "
            f"({rate:.2f} sim-sec/wall-sec) rounds {rnd} events {ev} "
            f"units sent {sent} dropped {drop} shards {self.n}")
        if self.cfg.general.progress:
            self._progress(t, self.cfg.general.stop_time, t0)

    def _write_manifest(self, paths: list, now, rnd: int) -> None:
        paths = [Path(p) for p in paths]
        manifest = paths[0].parent / (
            paths[0].name.replace(".shard0.ckpt", "") + MANIFEST_SUFFIX)
        manifest.write_text(json.dumps({
            "format": MANIFEST_FORMAT,
            "sim_shards": self.n,
            "sim_time_ns": now,
            "rounds": rnd,
            "files": [p.name for p in paths],
        }, sort_keys=True, indent=1))
        self.log.info(
            f"checkpoint written: {manifest} ({self.n} shard files, "
            f"sim {format_time(now)}, round {rnd})")

    def _progress(self, sim_now, stop, t0) -> None:
        import sys as _sys

        wall = _walltime.perf_counter() - t0
        pct = 100 * sim_now // stop
        rate = (sim_now / NS_PER_SEC) / wall if wall > 0 else 0.0
        eta = (stop - sim_now) / NS_PER_SEC / rate if rate > 0 else 0.0
        print(f"\r[{pct:3d}%] sim {format_time(sim_now)} / "
              f"{format_time(stop)}  {rate:.2f} sim-s/s  eta {eta:.0f}s   ",
              end="", file=_sys.stderr, flush=True)

    # -- summary -----------------------------------------------------------
    def _summary(self, finals: list, end_time, wall: float) -> dict:
        import resource

        counters = Counters()
        for f in finals:
            c = Counters()
            c.c.update(f["counters"])
            counters.merge(c)
        errors = []
        for f in finals:
            errors.extend(f["process_errors"])
        errors.sort(key=lambda e: e[0])
        error_strs = [e[1] for e in errors]
        sim_sec = end_time / NS_PER_SEC
        rate = sim_sec / wall if wall > 0 else float("inf")
        units_sent = sum(f["units_sent"] for f in finals)
        units_dropped = sum(f["units_dropped"] for f in finals)
        self.log.info(
            f"simulation finished: sim {format_time(end_time)} in "
            f"{wall:.2f}s wall ({rate:.2f} sim-sec/wall-sec), "
            f"{self.rounds} rounds, {self.events} events, "
            f"{units_sent} units delivered, {units_dropped} dropped, "
            f"{self.n} shard processes")
        self.log.info(counters.summary())
        self.log.flush()
        phase: dict = {}
        for f in finals:
            for k2, v in f["phase_wall"].items():
                phase[k2] = round(phase.get(k2, 0.0) + v, 4)
        tel_summary = None
        if finals[0]["tel_state"] is not None:
            tel_summary = _merge_tel_summaries(
                [f["tel_state"] for f in finals])
            self._merge_flows()
        out = {
            "sim_seconds": sim_sec,
            "wall_seconds": wall,
            "sim_sec_per_wall_sec": rate,
            "exit_reason": "interrupted" if self._partial else "completed",
            "partial": self._partial,
            **({"interrupt_signal": self._interrupt}
               if self._partial else {}),
            "max_rss_mb": round(max(
                [f["max_rss_mb"] for f in finals]
                + [resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss / 1024]), 1),
            "rounds": self.rounds,
            "events": self.events,
            "units_sent": units_sent,
            "units_dropped": units_dropped,
            "units_blackholed": sum(f["units_blackholed"] for f in finals),
            "bytes_sent": sum(f["bytes_sent"] for f in finals),
            "counters": counters.as_dict(),
            "process_errors": error_strs,
            "phase_wall": phase,
            "device_windows_dispatched": 0,
            **({"fault_transitions_applied":
                finals[0]["fault_transitions_applied"]}
               if finals[0]["fault_transitions_applied"] is not None
               else {}),
            **({"telemetry": tel_summary} if tel_summary is not None
               else {}),
            # volatile scale-out detail (VOLATILE_SUMMARY_KEYS): per-shard
            # walls for the bench straggler advisory
            "sim_shards": self.n,
            "shards": {
                "n": self.n,
                "per_shard": [
                    {"events": f["events"],
                     "max_rss_mb": f["max_rss_mb"],
                     "phase_wall": f["phase_wall"]}
                    for f in finals],
            },
        }
        return out


def _merge_tel_summaries(states: list) -> dict:
    """Fold per-shard telemetry reduction states into the exact summary
    the single-process collector would produce (log-bucket histograms are
    mergeable by construction; counts are disjoint sums)."""
    from shadow_tpu.telemetry.histogram import LogHistogram

    hist: dict = {}
    counts: dict = {}
    for st in states:
        for kind, hs in st["hist"].items():
            h = LogHistogram.from_state(hs)
            if kind in hist:
                hist[kind].merge(h)
            else:
                hist[kind] = h
        for kind, c in st["flow_counts"].items():
            tgt = counts.setdefault(kind, {"ok": 0, "failed": 0})
            tgt["ok"] += c["ok"]
            tgt["failed"] += c["failed"]
            if c.get("x_n"):
                tgt["x_sum"] = tgt.get("x_sum", 0) + c["x_sum"]
                tgt["x_n"] = tgt.get("x_n", 0) + c["x_n"]
    flows = {}
    for kind in sorted(counts):
        c = counts[kind]
        row = {"count": c["ok"] + c["failed"], "ok": c["ok"],
               "failed": c["failed"]}
        if c.get("x_n"):
            row["x_mean"] = c["x_sum"] // c["x_n"]
        h = hist.get(kind)
        if h is not None and h.total:
            row.update(h.quantiles_ns_to_ms())
        flows[kind] = row
    return {"samples": states[0]["samples"],
            "flows_recorded": sum(st["flows_written"] for st in states),
            "flows": flows}


def run_sharded(cfg, mirror_log: bool = True, resume_from=None) -> dict:
    """Entry point (cli.py): run ``cfg`` partitioned across
    ``cfg.general.sim_shards`` worker processes. Returns the merged run
    summary — the same shape Controller.run() produces."""
    return ShardedRun(cfg, mirror_log=mirror_log,
                      resume_from=resume_from).run()
