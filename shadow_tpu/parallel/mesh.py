"""The multi-chip data plane: one simulation round as an SPMD mesh program.

This is the scale-out architecture for the north star (SURVEY.md §5.8, §7
phase 3): hosts are sharded round-robin over a ``jax.sharding.Mesh`` axis;
each shard owns its hosts' closed-form egress buckets (the same integer
semantics as shadow_tpu/network/fluid.py::TokenBuckets — asserted bit-equal
in tests/test_multichip.py) and each round executes ONE collective program:

    per-shard closed-form departures  (local bucket state, no communication)
    -> APSP latency gather            (replicated (G,G) table)
    -> per-packet threefry loss draws (pure function of unit identity)
    -> lax.all_to_all                 (route arrivals to their dst shards, ICI)
    -> all_gather + min               (the conservative-lookahead barrier)
    -> lax.psum                       (global sent/dropped counters)

The reference's analog of the pmin barrier is the pthread round barrier in
its scheduler (SURVEY.md §2 "Parallelism strategies" item 4); the all_to_all
replaces its shared-memory cross-host event push. Neither has reference
code to mirror — upstream is single-machine — so this layer is pure design
freedom exercised the JAX way: shard_map over a named mesh axis, collectives
riding ICI, static shapes (per-shard unit slots and a full-width exchange
table) so the whole round is one XLA program.

Determinism: all math is integer (int64 times, uint32 hashes); collectives
permute data but every value is a pure function of unit identity, so any
shard count yields bit-identical simulations (tested vs the host plane).

Scale notes: the exchange table is (N, C, 4) int64 per shard with C = the
per-shard unit-slot count — worst-case capacity (every unit to one shard).
At pod scale C stays bounded by the per-round emission budget per shard, and
the table rides ICI, not HBM-resident state; per-shard bucket state is O(H/N).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shadow_tpu.core.time import NS_PER_SEC
from shadow_tpu.network.fluid import MAX_PKTS, MIN_CAP, MTU, PKT_SHIFT, NetParams
from shadow_tpu.ops.jaxcfg import configure
from shadow_tpu.ops.prng import threefry2x32

AXIS = "shard"

#: field order in the exchange table (int64): destination local host id,
#: arrival time (ns), uid (packed 64-bit), flags (bit0 dropped, bit1 valid)
F_DST, F_TARR, F_UID, F_FLAGS = range(4)


def _bytes_over(rate, dt):
    q, r = dt // NS_PER_SEC, dt % NS_PER_SEC
    frac = (rate.astype(jnp.uint64) * r.astype(jnp.uint64)
            // jnp.uint64(NS_PER_SEC)).astype(jnp.int64)
    return rate * q + frac


def _ceil_ns(need, rate):
    q, r = need // rate, need % rate
    frac = ((r.astype(jnp.uint64) * jnp.uint64(NS_PER_SEC)
             + rate.astype(jnp.uint64) - jnp.uint64(1))
            // rate.astype(jnp.uint64)).astype(jnp.int64)
    return q * NS_PER_SEC + frac


def _round_step(n_shards, seed, max_pkts, state, units, tables, t_now):
    """One shard's view of the round. All ``units`` arrays are (1, C) blocks
    (shard_map splits the global (N, C)); state is (1, Hs). tables
    (host_node, lat, thresh, rate, cap) are replicated. ``rok`` marks
    routable units: blackholed ones (no route in the APSP) still charge
    their source bucket — matching the host planes, which filter AFTER the
    closed-form commit — but produce no arrival row."""
    t_base, tokens, debt = (s[0] for s in state)
    src_l, dst_g, size, t_emit, uid, rok = (u[0] for u in units)
    host_node, lat_ns, thresh, rate_all, cap_all = tables
    me = lax.axis_index(AXIS)
    hs = t_base.shape[0]
    c = src_l.shape[0]
    valid = src_l < hs

    # my hosts' global ids: h = local * N + me; parameters gathered from the
    # replicated tables (padded hosts carry rate 1 / cap MIN_CAP upstream)
    my_global = jnp.arange(hs, dtype=jnp.int64) * n_shards + me
    rate = rate_all[my_global]
    cap = cap_all[my_global]

    # lazy saturation rebase at the barrier (fluid.TokenBuckets.rebase)
    avail = tokens + _bytes_over(rate, t_now - t_base) - debt
    sat = avail > cap
    t_base = jnp.where(sat, t_now, t_base)
    tokens = jnp.where(sat, cap, tokens)
    debt = jnp.where(sat, 0, debt)

    # per-source FIFO cumulative bytes (src-sorted; padding sorts last)
    size_m = jnp.where(valid, size, 0)
    csum = jnp.cumsum(size_m)
    prev = jnp.concatenate([jnp.full((1,), -1, src_l.dtype), src_l[:-1]])
    seg_first = src_l != prev
    seg_base = jax.lax.cummax(jnp.where(seg_first, csum - size_m, 0))
    cum_in_seg = csum - seg_base

    sl = jnp.minimum(src_l, hs - 1)
    need = debt[sl] + cum_in_seg - tokens[sl]
    t_ready = jnp.where(need > 0, t_base[sl] + _ceil_ns(need, rate[sl]), 0)
    t_dep = jnp.maximum(t_emit, t_ready)

    drained = jax.ops.segment_sum(size_m, sl, num_segments=hs,
                                  indices_are_sorted=True)
    debt = debt + drained

    # latency + loss threshold gather
    src_g = sl.astype(jnp.int64) * n_shards + me
    sn = host_node[jnp.minimum(src_g, host_node.shape[0] - 1)]
    dn = host_node[jnp.minimum(dst_g, host_node.shape[0] - 1)]
    lat = lat_ns[sn, dn]
    th = thresh[sn, dn]
    t_arr = t_dep + lat

    # per-packet threefry draws — identical integer math to fluid.loss_flags
    uid_lo = (uid & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    uid_hi = ((uid >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    npkts = jnp.minimum(jnp.maximum(1, -(-size // MTU)), max_pkts)
    pkt = jnp.arange(max_pkts, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (c, max_pkts))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    draws, _ = threefry2x32(jnp.uint32(seed & 0xFFFFFFFF),
                            jnp.uint32((seed >> 32) & 0xFFFFFFFF),
                            c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < th[:, None]) & (pkt < npkts.astype(jnp.uint32)[:, None])
    route = valid & (rok != 0)
    dropped = jnp.any(hit, axis=1) & route

    # route arrivals to their destination shards: stable-sort by dst shard,
    # rank within group, scatter into the (N, C) exchange table, all_to_all
    dst_shard = jnp.where(route, dst_g % n_shards, n_shards)  # pad -> drop
    order = jnp.argsort(dst_shard, stable=True)
    ds = dst_shard[order]
    first = jnp.searchsorted(ds, ds, side="left")
    rank = jnp.arange(c) - first
    flags = (dropped.astype(jnp.int64) | (route.astype(jnp.int64) << 1))
    payload = jnp.stack(
        [(dst_g // n_shards).astype(jnp.int64), t_arr, uid, flags], axis=-1
    )[order]
    table = jnp.zeros((n_shards, c, 4), dtype=jnp.int64)
    table = table.at[ds, rank].set(payload, mode="drop")
    received = lax.all_to_all(table, AXIS, split_axis=0, concat_axis=0)

    # the conservative-lookahead barrier: global earliest arrival (pmin) —
    # the controller's next-round window bound in a multi-controller setup
    inf = jnp.int64(1) << jnp.int64(62)
    local_min = jnp.min(jnp.where(route, t_arr, inf))
    # min-reduce via all_gather + local min: some TPU AOT toolchains lower
    # only Sum all-reduces (observed on the tunneled v5e compile helper);
    # AllGather lowers everywhere and the result is identical
    g_min = jnp.min(lax.all_gather(local_min, AXIS))

    sent_ct = lax.psum(jnp.sum(route & ~dropped), AXIS)
    drop_ct = lax.psum(jnp.sum(dropped), AXIS)

    state_out = (t_base[None], tokens[None], debt[None])
    return (received[None], state_out, g_min, jnp.stack([sent_ct, drop_ct]))


class MeshDataPlane:
    """Host-sharded data plane over a device mesh.

    Usage: build with NetParams (+ graph tables), feed per-round unit
    batches with ``round_step``; state lives sharded on the devices.
    """

    def __init__(self, params: NetParams, n_shards: int | None = None,
                 units_per_shard: int = 1024, devices=None,
                 max_pkts: int = MAX_PKTS) -> None:
        configure()
        import jax as _jax

        # int64 simulation times flow through this plane; scoped here (not
        # in jaxcfg) so embedding apps that never build a mesh keep default
        # 32-bit JAX semantics. Process-global once a mesh is constructed.
        _jax.config.update("jax_enable_x64", True)
        devices = devices if devices is not None else jax.devices()
        n = n_shards or len(devices)
        if n > len(devices):
            raise ValueError(f"{n} shards > {len(devices)} devices")
        self.n_shards = n
        self.units_per_shard = int(units_per_shard)
        self.mesh = Mesh(np.array(devices[:n]), (AXIS,))
        self.params = params

        h = params.rate_up.shape[0]
        self.h_pad = -(-h // n) * n
        self.hs = self.h_pad // n
        rate = np.ones(self.h_pad, dtype=np.int64)
        cap = np.full(self.h_pad, MIN_CAP, dtype=np.int64)
        rate[:h] = params.rate_up
        cap[:h] = params.cap_up
        node = np.zeros(self.h_pad, dtype=np.int64)
        node[:h] = params.host_node
        self._tables = (
            jnp.asarray(node),
            jnp.asarray(params.latency_ns),
            jnp.asarray(params.drop_thresh),
            jnp.asarray(rate),
            jnp.asarray(cap),
        )
        # sharded bucket state, (N, Hs): row i = shard i's hosts (h % N == i)
        shard = NamedSharding(self.mesh, P(AXIS))

        def shard_state(vals):
            arr = np.zeros((n, self.hs), dtype=np.int64)
            for i in range(n):
                row = vals[i::n]
                arr[i, : row.shape[0]] = row
            return jax.device_put(jnp.asarray(arr), shard)

        self.t_base = shard_state(np.zeros(h, dtype=np.int64))
        self.tokens = shard_state(params.cap_up)
        self.debt = shard_state(np.zeros(h, dtype=np.int64))

        self._step = jax.jit(
            jax.shard_map(
                partial(_round_step, n, int(params.seed), int(max_pkts)),
                mesh=self.mesh,
                in_specs=((P(AXIS), P(AXIS), P(AXIS)),
                          (P(AXIS),) * 6,
                          (P(), P(), P(), P(), P()),
                          P()),
                out_specs=(P(AXIS), (P(AXIS), P(AXIS), P(AXIS)), P(), P()),
                # the barrier min is computed as all_gather+min (value-
                # replicated, but not statically inferable as such)
                check_vma=False,
            ),
            static_argnums=(),
        )

    def shard_units(self, src, dst, size, t_emit, uid, rok=None):
        """Pack a (src-sorted FIFO) host batch into per-shard padded slots.
        ``rok`` (optional bool array) marks routable units; unroutable ones
        charge buckets but produce no arrival. Returns the (N, C) int64
        arrays ``round_step`` consumes."""
        n, c, hs = self.n_shards, self.units_per_shard, self.hs
        out_src = np.full((n, c), hs, dtype=np.int64)  # hs = invalid sentinel
        out_dst = np.zeros((n, c), dtype=np.int64)
        out_size = np.zeros((n, c), dtype=np.int64)
        out_emit = np.zeros((n, c), dtype=np.int64)
        out_uid = np.zeros((n, c), dtype=np.int64)
        out_rok = np.zeros((n, c), dtype=np.int64)
        sh = np.asarray(src, dtype=np.int64) % n
        counts = np.bincount(sh, minlength=n)
        if counts.max(initial=0) > c:
            raise ValueError("units_per_shard slot overflow")
        order = np.argsort(sh, kind="stable")  # per-shard FIFO preserved
        if order.size:
            rank = np.concatenate(
                [np.arange(k, dtype=np.int64) for k in counts])
            shs, ks = sh[order], rank
            out_src[shs, ks] = np.asarray(src, dtype=np.int64)[order] // n
            out_dst[shs, ks] = np.asarray(dst, dtype=np.int64)[order]
            out_size[shs, ks] = np.asarray(size, dtype=np.int64)[order]
            out_emit[shs, ks] = np.asarray(t_emit, dtype=np.int64)[order]
            out_uid[shs, ks] = np.asarray(uid, dtype=np.int64)[order]
            if rok is None:
                out_rok[shs, ks] = 1
            else:
                out_rok[shs, ks] = np.asarray(rok, dtype=np.int64)[order]
        return tuple(jnp.asarray(a) for a in
                     (out_src, out_dst, out_size, out_emit, out_uid,
                      out_rok))

    def round_step_async(self, units, t_now: int):
        """Run one round; bucket state advances ON DEVICE and only the
        scalar barrier min is read synchronously. Returns (received_dev,
        g_min): the (N, N, C, 4) exchange table stays on device with its
        host copy streaming in the background — the caller materializes
        it when the simulation clock reaches g_min (the causal deadline,
        exactly the single-chip plane's deferred-readback discipline)."""
        received, state, g_min, _counters = self._step(
            (self.t_base, self.tokens, self.debt), units, self._tables,
            jnp.int64(t_now))
        self.t_base, self.tokens, self.debt = state
        try:
            received.copy_to_host_async()
        except AttributeError:
            pass
        return received, int(g_min)

    def round_step(self, units, t_now: int):
        """Synchronous round (tests): returns (received, g_min, counters)
        with ``received`` materialized — received[i, j, c] = the c-th
        arrival shard j routed to shard i (see F_* field order)."""
        received, state, g_min, counters = self._step(
            (self.t_base, self.tokens, self.debt), units, self._tables,
            jnp.int64(t_now))
        self.t_base, self.tokens, self.debt = state
        return (np.asarray(received), int(g_min), np.asarray(counters))
