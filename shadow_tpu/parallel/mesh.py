"""The multi-chip data plane: one simulation round as an SPMD mesh program.

This is the scale-out architecture for the north star (SURVEY.md §5.8, §7
phase 3): hosts are sharded round-robin over a ``jax.sharding.Mesh`` axis.
Three programs, one per granularity:

1. ``_round_step`` — ONE round as a collective program, per-shard bucket
   state device-resident (the multi-controller round primitive):

    per-shard closed-form departures  (local bucket state, no communication)
    -> APSP latency gather            (replicated (G,G) table)
    -> per-packet threefry loss draws (pure function of unit identity)
    -> lax.all_to_all                 (route arrivals to their dst shards, ICI)
    -> all_gather + min               (the conservative-lookahead barrier)
    -> lax.psum                       (global sent/dropped counters)

2. ``_scan_rounds`` — K rounds fused as ONE program: bucket state is the
   ``lax.scan`` carry, exchange tables stack as scan outputs; one dispatch
   and one readback per K rounds (VERDICT r3 item #2).

3. ``_exchange_rounds`` — the in-simulation collective behind
   ``scheduler_policy: tpu_mesh``: departures are closed form and
   bit-equal host/device (tests assert it), so the plane computes them
   host-side where emissions originate and batches the deferrable rest —
   draws + arrival exchange + pmin — across a whole causal window in one
   program, however many rounds that window spans. This is what removed
   the round-3 per-barrier dispatch bottleneck (0.14-0.23 -> ~17
   sim-s/wall-s on config #2).

The reference's analog of the pmin barrier is the pthread round barrier in
its scheduler (SURVEY.md §2 "Parallelism strategies" item 4); the all_to_all
replaces its shared-memory cross-host event push. Neither has reference
code to mirror — upstream is single-machine — so this layer is pure design
freedom exercised the JAX way: shard_map over a named mesh axis, collectives
riding ICI, static shapes (per-shard unit slots and a full-width exchange
table) so the whole round is one XLA program.

Determinism: all math is integer (int64 times, uint32 hashes); collectives
permute data but every value is a pure function of unit identity, so any
shard count yields bit-identical simulations (tested vs the host plane).

Scale notes: the exchange table is (N, C, 4) int64 per shard with C = the
per-shard unit-slot count — worst-case capacity (every unit to one shard).
At pod scale C stays bounded by the per-round emission budget per shard, and
the table rides ICI, not HBM-resident state; per-shard bucket state is O(H/N).
"""

from __future__ import annotations

import time as _walltime  # detlint: ok(wallclock): attach/compile wall telemetry
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.38 exports shard_map at top level
    _shard_map_impl = jax.shard_map
except AttributeError:  # older jax: the experimental location
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across jax versions: the replication-check kwarg was
    renamed check_rep -> check_vma; forward to whichever this jax has."""
    try:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=check_vma)
    except TypeError:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=check_vma)

from shadow_tpu.core.time import NS_PER_SEC
from shadow_tpu.network.fluid import MAX_PKTS, MIN_CAP, MTU, PKT_SHIFT, NetParams
from shadow_tpu.ops.jaxcfg import configure
from shadow_tpu.ops.prng import threefry2x32

AXIS = "shard"

#: field order in the exchange table (int64): destination local host id,
#: arrival time (ns), uid (packed 64-bit), flags (bit0 dropped, bit1 valid)
F_DST, F_TARR, F_UID, F_FLAGS = range(4)


def _bytes_over(rate, dt):
    q, r = dt // NS_PER_SEC, dt % NS_PER_SEC
    frac = (rate.astype(jnp.uint64) * r.astype(jnp.uint64)
            // jnp.uint64(NS_PER_SEC)).astype(jnp.int64)
    return rate * q + frac


def _ceil_ns(need, rate):
    q, r = need // rate, need % rate
    frac = ((r.astype(jnp.uint64) * jnp.uint64(NS_PER_SEC)
             + rate.astype(jnp.uint64) - jnp.uint64(1))
            // rate.astype(jnp.uint64)).astype(jnp.int64)
    return q * NS_PER_SEC + frac


def _round_step(n_shards, seed, max_pkts, state, units, tables, t_now):
    """One shard's view of the round. All ``units`` arrays are (1, C) blocks
    (shard_map splits the global (N, C)); state is (1, Hs). tables
    (host_node, lat, thresh, rate, cap) are replicated. ``rok`` marks
    routable units: blackholed ones (no route in the APSP) still charge
    their source bucket — matching the host planes, which filter AFTER the
    closed-form commit — but produce no arrival row."""
    t_base, tokens, debt = (s[0] for s in state)
    src_l, dst_g, size, t_emit, uid, rok = (u[0] for u in units)
    host_node, lat_ns, thresh, rate_all, cap_all = tables
    me = lax.axis_index(AXIS)
    hs = t_base.shape[0]
    c = src_l.shape[0]
    valid = src_l < hs

    # my hosts' global ids: h = local * N + me; parameters gathered from the
    # replicated tables (padded hosts carry rate 1 / cap MIN_CAP upstream)
    my_global = jnp.arange(hs, dtype=jnp.int64) * n_shards + me
    rate = rate_all[my_global]
    cap = cap_all[my_global]

    # lazy saturation rebase at the barrier (fluid.TokenBuckets.rebase)
    avail = tokens + _bytes_over(rate, t_now - t_base) - debt
    sat = avail > cap
    t_base = jnp.where(sat, t_now, t_base)
    tokens = jnp.where(sat, cap, tokens)
    debt = jnp.where(sat, 0, debt)

    # per-source FIFO cumulative bytes (src-sorted; padding sorts last)
    size_m = jnp.where(valid, size, 0)
    csum = jnp.cumsum(size_m)
    prev = jnp.concatenate([jnp.full((1,), -1, src_l.dtype), src_l[:-1]])
    seg_first = src_l != prev
    seg_base = jax.lax.cummax(jnp.where(seg_first, csum - size_m, 0))
    cum_in_seg = csum - seg_base

    sl = jnp.minimum(src_l, hs - 1)
    need = debt[sl] + cum_in_seg - tokens[sl]
    t_ready = jnp.where(need > 0, t_base[sl] + _ceil_ns(need, rate[sl]), 0)
    t_dep = jnp.maximum(t_emit, t_ready)

    drained = jax.ops.segment_sum(size_m, sl, num_segments=hs,
                                  indices_are_sorted=True)
    debt = debt + drained

    # latency + loss threshold gather
    src_g = sl.astype(jnp.int64) * n_shards + me
    sn = host_node[jnp.minimum(src_g, host_node.shape[0] - 1)]
    dn = host_node[jnp.minimum(dst_g, host_node.shape[0] - 1)]
    lat = lat_ns[sn, dn]
    th = thresh[sn, dn]
    t_arr = t_dep + lat

    # per-packet threefry draws — identical integer math to fluid.loss_flags
    uid_lo = (uid & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    uid_hi = ((uid >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    npkts = jnp.minimum(jnp.maximum(1, -(-size // MTU)), max_pkts)
    pkt = jnp.arange(max_pkts, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (c, max_pkts))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    draws, _ = threefry2x32(jnp.uint32(seed & 0xFFFFFFFF),
                            jnp.uint32((seed >> 32) & 0xFFFFFFFF),
                            c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < th[:, None]) & (pkt < npkts.astype(jnp.uint32)[:, None])
    route = valid & (rok != 0)
    dropped = jnp.any(hit, axis=1) & route

    # route arrivals to their destination shards: stable-sort by dst shard,
    # rank within group, scatter into the (N, C) exchange table, all_to_all
    dst_shard = jnp.where(route, dst_g % n_shards, n_shards)  # pad -> drop
    order = jnp.argsort(dst_shard, stable=True)
    ds = dst_shard[order]
    first = jnp.searchsorted(ds, ds, side="left")
    rank = jnp.arange(c) - first
    flags = (dropped.astype(jnp.int64) | (route.astype(jnp.int64) << 1))
    payload = jnp.stack(
        [(dst_g // n_shards).astype(jnp.int64), t_arr, uid, flags], axis=-1
    )[order]
    table = jnp.zeros((n_shards, c, 4), dtype=jnp.int64)
    table = table.at[ds, rank].set(payload, mode="drop")
    received = lax.all_to_all(table, AXIS, split_axis=0, concat_axis=0)

    # the conservative-lookahead barrier: global earliest arrival (pmin) —
    # the controller's next-round window bound in a multi-controller setup
    inf = jnp.int64(1) << jnp.int64(62)
    local_min = jnp.min(jnp.where(route, t_arr, inf))
    # min-reduce via all_gather + local min: some TPU AOT toolchains lower
    # only Sum all-reduces (observed on the tunneled v5e compile helper);
    # AllGather lowers everywhere and the result is identical
    g_min = jnp.min(lax.all_gather(local_min, AXIS))

    sent_ct = lax.psum(jnp.sum(route & ~dropped), AXIS)
    drop_ct = lax.psum(jnp.sum(dropped), AXIS)

    state_out = (t_base[None], tokens[None], debt[None])
    return (received[None], state_out, g_min, jnp.stack([sent_ct, drop_ct]))


def _scan_rounds(n_shards, seed, max_pkts, state, units_k, tables, t_now_k):
    """K fused rounds as ONE shard_map program (VERDICT r3 item #2): the
    bucket state is the lax.scan carry (device-resident across barriers),
    each step is a full _round_step (departures, draws, all_to_all, pmin),
    and the per-round exchange tables accumulate as stacked scan outputs —
    one dispatch and one readback per K rounds instead of per round.
    Padded steps carry only invalid units: they add no debt and the lazy
    rebase is idempotent, so state is untouched (see fluid.py)."""

    def body(st, x):
        t_now = x[-1]
        received, st2, g_min, counters = _round_step(
            n_shards, seed, max_pkts, st, tuple(x[:-1]), tables, t_now)
        return st2, (received, g_min, counters)

    st_f, (recv_k, gmin_k, ct_k) = lax.scan(
        body, state, tuple(units_k) + (t_now_k,))
    return recv_k, st_f, gmin_k, ct_k


def _exchange_rounds(n_shards, seed, max_pkts, w, units):
    """The in-simulation collective (colplane tpu_mesh): per-packet loss
    draws + the all_to_all arrival exchange + the pmin lookahead barrier
    for a WHOLE causal window of rounds in ONE program. Departures are
    closed-form and bit-equal on host and device (tests assert it), so the
    in-sim plane computes them host-side where emissions originate and
    batches everything deferrable — draws are pure functions of unit
    identity, and arrivals only need to materialize at the window's
    earliest-arrival deadline. One dispatch per window, not per round;
    the state-carrying per-round program (_round_step/_scan_rounds)
    remains the standalone multi-controller API."""
    dst_g, t_arr, uid, npk_in, th, valid_in = (u[0] for u in units)
    m = dst_g.shape[0]
    valid = valid_in != 0
    uid_lo = (uid & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    uid_hi = ((uid >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    npkts = jnp.minimum(npk_in, max_pkts)
    pkt = jnp.arange(max_pkts, dtype=jnp.uint32)[None, :]
    c0 = jnp.broadcast_to(uid_lo[:, None], (m, max_pkts))
    c1 = uid_hi[:, None] | (pkt << jnp.uint32(PKT_SHIFT))
    draws, _ = threefry2x32(jnp.uint32(seed & 0xFFFFFFFF),
                            jnp.uint32((seed >> 32) & 0xFFFFFFFF),
                            c0, c1, xp=jnp)
    draws = (draws >> jnp.uint32(8)).astype(jnp.uint32)
    hit = (draws < th.astype(jnp.uint32)[:, None]) \
        & (pkt < npkts.astype(jnp.uint32)[:, None])
    dropped = jnp.any(hit, axis=1) & valid

    dst_shard = jnp.where(valid, dst_g % n_shards, n_shards)
    order = jnp.argsort(dst_shard, stable=True)
    ds = dst_shard[order]
    first = jnp.searchsorted(ds, ds, side="left")
    rank = jnp.arange(m) - first
    flags = (dropped.astype(jnp.int64) | (valid.astype(jnp.int64) << 1))
    payload = jnp.stack(
        [(dst_g // n_shards).astype(jnp.int64), t_arr, uid, flags], axis=-1
    )[order]
    table = jnp.zeros((n_shards, m, 4), dtype=jnp.int64)
    table = table.at[ds, rank].set(payload, mode="drop")
    received = lax.all_to_all(table, AXIS, split_axis=0, concat_axis=0)
    # compact to w rows: one destination can receive at most the whole
    # slice — bounded by the global slice width AND the table capacity
    # n*m — never by the per-SOURCE width m (review r4: destination-skewed
    # traffic would truncate)
    flat = received.reshape(n_shards * m, 4)
    ok = flat[:, F_FLAGS] >= 2
    received = flat[jnp.argsort(~ok, stable=True)[:w]]

    inf = jnp.int64(1) << jnp.int64(62)
    g_min = jnp.min(lax.all_gather(
        jnp.min(jnp.where(valid, t_arr, inf)), AXIS))
    return received[None], g_min


class MeshDataPlane:
    """Host-sharded data plane over a device mesh.

    Usage: build with NetParams (+ graph tables), feed per-round unit
    batches with ``round_step``, or fuse K rounds per dispatch with
    ``scan_rounds``; state lives sharded on the devices.
    """

    def __init__(self, params: NetParams, n_shards: int | None = None,
                 units_per_shard: int = 1024, devices=None,
                 max_pkts: int = MAX_PKTS) -> None:
        configure()
        import jax as _jax

        # int64 simulation times flow through this plane; scoped here (not
        # in jaxcfg) so embedding apps that never build a mesh keep default
        # 32-bit JAX semantics. Process-global once a mesh is constructed.
        _jax.config.update("jax_enable_x64", True)
        devices = devices if devices is not None else jax.devices()
        n = n_shards or len(devices)
        if n > len(devices):
            raise ValueError(f"{n} shards > {len(devices)} devices")
        self.n_shards = n
        self.units_per_shard = int(units_per_shard)
        self.mesh = Mesh(np.array(devices[:n]), (AXIS,))
        self.params = params
        #: per-window wall attribution for the collective (exchange_rounds)
        self.phase = {"build": 0.0, "dispatch": 0.0, "readback": 0.0,
                      "windows": 0}

        h = params.rate_up.shape[0]
        self.h_pad = -(-h // n) * n
        self.hs = self.h_pad // n
        rate = np.ones(self.h_pad, dtype=np.int64)
        cap = np.full(self.h_pad, MIN_CAP, dtype=np.int64)
        rate[:h] = params.rate_up
        cap[:h] = params.cap_up
        node = np.zeros(self.h_pad, dtype=np.int64)
        node[:h] = params.host_node
        self._tables = (
            jnp.asarray(node),
            jnp.asarray(params.latency_ns),
            jnp.asarray(params.drop_thresh),
            jnp.asarray(rate),
            jnp.asarray(cap),
        )
        # sharded bucket state, (N, Hs): row i = shard i's hosts (h % N == i)
        shard = NamedSharding(self.mesh, P(AXIS))

        def shard_state(vals):
            arr = np.zeros((n, self.hs), dtype=np.int64)
            for i in range(n):
                row = vals[i::n]
                arr[i, : row.shape[0]] = row
            return jax.device_put(jnp.asarray(arr), shard)

        self.t_base = shard_state(np.zeros(h, dtype=np.int64))
        self.tokens = shard_state(params.cap_up)
        self.debt = shard_state(np.zeros(h, dtype=np.int64))

        self._step = jax.jit(
            _shard_map(
                partial(_round_step, n, int(params.seed), int(max_pkts)),
                mesh=self.mesh,
                in_specs=((P(AXIS), P(AXIS), P(AXIS)),
                          (P(AXIS),) * 6,
                          (P(), P(), P(), P(), P()),
                          P()),
                out_specs=(P(AXIS), (P(AXIS), P(AXIS), P(AXIS)), P(), P()),
                # the barrier min is computed as all_gather+min (value-
                # replicated, but not statically inferable as such)
                check_vma=False,
            ),
            static_argnums=(),
        )
        self._seed = int(params.seed)
        self._max_pkts = int(max_pkts)
        self._scan_cache: dict = {}  # K -> jitted fused program
        self._pad_chunk = None  # cached all-invalid packed chunk

    #: fused-dispatch cap: scan programs compile per power-of-two K up to
    #: this; longer backlogs run as sequential scans (state carries on
    #: device between them)
    SCAN_KMAX = 32

    def _get_scan(self, k: int):
        f = self._scan_cache.get(k)
        if f is None:
            f = jax.jit(
                _shard_map(
                    partial(_scan_rounds, self.n_shards, self._seed,
                            self._max_pkts),
                    mesh=self.mesh,
                    in_specs=((P(AXIS), P(AXIS), P(AXIS)),
                              (P(None, AXIS),) * 6,
                              (P(), P(), P(), P(), P()),
                              P()),
                    out_specs=(P(None, AXIS),
                               (P(AXIS), P(AXIS), P(AXIS)), P(), P()),
                    check_vma=False,
                ))
            self._scan_cache[k] = f
        return f

    def scan_rounds(self, chunks):
        """Fused execution of a backlog of round chunks.

        ``chunks``: list of ((src,dst,size,t_emit,uid,rok) packed numpy
        (N, C) arrays from shard_units_np, t_now) in simulation order.
        Pads each group to a power-of-two K (<= SCAN_KMAX) with invalid
        units and runs ONE scan program per group. Returns the list of
        materialized exchange tables ((N, N, C, 4) numpy) aligned with
        ``chunks``."""
        out = []
        i = 0
        n = len(chunks)
        while i < n:
            part = chunks[i:i + self.SCAN_KMAX]
            k = len(part)
            # three K buckets only (1, 8, KMAX): scan programs compile
            # once each; padded steps are cheap after compaction
            K = 1 if k == 1 else (8 if k <= 8 else self.SCAN_KMAX)
            if self._pad_chunk is None:
                self._pad_chunk = self.shard_units_np([], [], [], [], [])
            pads = K - k
            t_last = part[-1][1]
            arrs = tuple(
                np.stack([p[0][j] for p in part]
                         + [self._pad_chunk[j]] * pads)
                for j in range(6))
            t_nows = np.array([p[1] for p in part] + [t_last] * pads,
                              dtype=np.int64)
            recv_k, state, _gmin, _ct = self._get_scan(K)(
                (self.t_base, self.tokens, self.debt), arrs, self._tables,
                jnp.asarray(t_nows))
            self.t_base, self.tokens, self.debt = state
            recv = np.asarray(recv_k)
            out.extend(recv[j] for j in range(k))
            i += k
        return out

    #: window-slice widths for the exchange program: smallest bucket that
    #: fits the per-shard slot demand wins; bigger backlogs run as
    #: multiple slices (still one program each, amortized per window)
    EXCHANGE_BUCKETS = (256, 1024, 4096, 16384)

    def _get_exchange(self, m: int, w: int):
        key = ("x", m, w)
        f = self._scan_cache.get(key)
        if f is None:
            f = jax.jit(
                _shard_map(
                    partial(_exchange_rounds, self.n_shards, self._seed,
                            self._max_pkts, w),
                    mesh=self.mesh,
                    in_specs=((P(AXIS),) * 6,),
                    out_specs=(P(AXIS), P()),
                    check_vma=False,
                ))
            self._scan_cache[key] = f
        return f

    def exchange_rounds(self, src, dst, t_arr, uid, npk, th):
        """Resolve a causal window's units: draws + all_to_all exchange in
        as few programs as the slot buckets allow. Inputs are 1-D numpy
        arrays over ALL the window's (post-blackhole) units, in emission
        order. Returns a list of materialized (N*, 4) exchange tables
        covering every unit (F_* field order; F_FLAGS bit1 marks valid
        rows)."""
        n = self.n_shards
        out = []
        total = len(src)
        if total == 0:
            return out
        i = 0
        step = self.EXCHANGE_BUCKETS[-1]
        ph = self.phase
        while i < total:
            t0 = _walltime.perf_counter()
            j = min(total, i + step)
            sl = slice(i, j)
            sh = np.asarray(src[sl], dtype=np.int64) % n
            counts = np.bincount(sh, minlength=n)
            need = int(counts.max(initial=1))
            m = next(b for b in self.EXCHANGE_BUCKETS if b >= need)
            # destination capacity: the whole slice could land on one
            # shard; round the slice width up to a bucket for shape reuse
            wneed = min(n * m, int(j - i))
            w = min(n * m,
                    next(b for b in self.EXCHANGE_BUCKETS if b >= wneed))
            packed = np.zeros((6, n, m), dtype=np.int64)
            order = np.argsort(sh, kind="stable")
            if order.size:
                rank = np.concatenate(
                    [np.arange(k, dtype=np.int64) for k in counts])
                shs = sh[order]
                packed[0, shs, rank] = np.asarray(dst[sl], np.int64)[order]
                packed[1, shs, rank] = np.asarray(t_arr[sl], np.int64)[order]
                packed[2, shs, rank] = np.asarray(uid[sl], np.int64)[order]
                packed[3, shs, rank] = np.asarray(npk[sl], np.int64)[order]
                packed[4, shs, rank] = np.asarray(th[sl], np.int64)[order]
                packed[5, shs, rank] = 1
            t1 = _walltime.perf_counter()
            handle = self._get_exchange(m, w)(
                tuple(jnp.asarray(packed[k]) for k in range(6)))
            # async dispatch: without this barrier the device's execution
            # wall would land in the readback bucket and the published
            # attribution would blame the wrong phase
            jax.block_until_ready(handle)
            t2 = _walltime.perf_counter()
            recv, _gmin = handle
            out.append(np.asarray(recv).reshape(-1, 4))
            t3 = _walltime.perf_counter()
            # per-window wall attribution (VERDICT r4 item #7): host-side
            # build/compact vs program dispatch vs result readback —
            # published per shard count so the 4/8-shard tail-off is
            # evidence, not assertion
            ph["build"] += t1 - t0
            ph["dispatch"] += t2 - t1
            ph["readback"] += t3 - t2
            ph["windows"] += 1
            i = j
        return out

    def shard_units(self, src, dst, size, t_emit, uid, rok=None):
        """shard_units_np, converted to device arrays (per-round API)."""
        return tuple(jnp.asarray(a) for a in
                     self.shard_units_np(src, dst, size, t_emit, uid, rok))

    def shard_units_np(self, src, dst, size, t_emit, uid, rok=None):
        """Pack a (src-sorted FIFO) host batch into per-shard padded slots.
        ``rok`` (optional bool array) marks routable units; unroutable ones
        charge buckets but produce no arrival. Returns the (N, C) int64
        arrays ``round_step`` consumes."""
        n, c, hs = self.n_shards, self.units_per_shard, self.hs
        out_src = np.full((n, c), hs, dtype=np.int64)  # hs = invalid sentinel
        out_dst = np.zeros((n, c), dtype=np.int64)
        out_size = np.zeros((n, c), dtype=np.int64)
        out_emit = np.zeros((n, c), dtype=np.int64)
        out_uid = np.zeros((n, c), dtype=np.int64)
        out_rok = np.zeros((n, c), dtype=np.int64)
        sh = np.asarray(src, dtype=np.int64) % n
        counts = np.bincount(sh, minlength=n)
        if counts.max(initial=0) > c:
            raise ValueError("units_per_shard slot overflow")
        order = np.argsort(sh, kind="stable")  # per-shard FIFO preserved
        if order.size:
            rank = np.concatenate(
                [np.arange(k, dtype=np.int64) for k in counts])
            shs, ks = sh[order], rank
            out_src[shs, ks] = np.asarray(src, dtype=np.int64)[order] // n
            out_dst[shs, ks] = np.asarray(dst, dtype=np.int64)[order]
            out_size[shs, ks] = np.asarray(size, dtype=np.int64)[order]
            out_emit[shs, ks] = np.asarray(t_emit, dtype=np.int64)[order]
            out_uid[shs, ks] = np.asarray(uid, dtype=np.int64)[order]
            if rok is None:
                out_rok[shs, ks] = 1
            else:
                out_rok[shs, ks] = np.asarray(rok, dtype=np.int64)[order]
        return (out_src, out_dst, out_size, out_emit, out_uid, out_rok)

    def round_step(self, units, t_now: int):
        """Synchronous round (tests): returns (received, g_min, counters)
        with ``received`` materialized — received[i, j, c] = the c-th
        arrival shard j routed to shard i (see F_* field order)."""
        received, state, g_min, counters = self._step(
            (self.t_base, self.tokens, self.debt), units, self._tables,
            jnp.int64(t_now))
        self.t_base, self.tokens, self.debt = state
        return (np.asarray(received), int(g_min), np.asarray(counters))
