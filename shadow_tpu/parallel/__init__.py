from shadow_tpu.parallel.mesh import MeshDataPlane  # noqa: F401
