"""The per-host virtual file surface for managed processes.

Reference analog: the reference's descriptor table serves regular files to
guests (SURVEY.md §2 "Descriptor table & file objects", "passthrough to
real FS under data dir"). Round 3 gives the worker a real file surface
(VERDICT r2 missing #2): every path-taking syscall traps (tools/gen_bpf.py
UNCONDITIONAL file set) and resolves here against a three-way policy:

- **synthesized** — ``/etc/hosts`` and ``/etc/resolv.conf`` are generated
  from the simulation config (every host name with its simulated IPv4), so
  unmodified binaries that read resolver files see the simulated network;
- **host tree** — paths under the host's data directory (where the guest's
  cwd starts) are served by the WORKER against the real directory: reads,
  writes, directory listings, renames — all deterministic because only
  this simulation writes there, with stat times drawn from the simulated
  clock and deterministic inode numbers;
- **native** — everything else (/lib, /usr, /proc, ...) returns the
  RETRY_NATIVE sentinel and the shim re-issues the syscall through its
  gadget: dynamic linking, imports, and host-file reads behave exactly as
  before, but now by explicit policy instead of a filter default.

Guest-visible fds for virtualized files are ordinary vfds (VSocket kind
"file"/"dir"); read/write/lseek/fstat/getdents64/close flow through the
worker with offsets tracked worker-side. mmap works: the fd slot of a
trapped mmap carries a vfd, the worker replies with a real kernel fd
(the host-tree backing fd, or a memfd snapshot of synthesized content)
over SCM_RIGHTS, and the shim re-issues the map through its gadget
(managed.py::_mmap_vfd) — Tor-style consensus-document mapping included.

A minimal /proc is synthesized consistently with the virtual machine
identity (1 CPU, 2 GB, simulated uptime, vpids): /proc/cpuinfo,
/proc/meminfo, /proc/uptime, and /proc/<self>/{stat,status,maps}; every
other /proc path stays native by policy.
"""

from __future__ import annotations

import errno
import os
import stat as statmod
import struct
from pathlib import Path

from shadow_tpu.core.time import NS_PER_SEC, emulated

#: worker reply that makes the shim re-issue the syscall via its gadget
RETRY_NATIVE = -1000000

AT_FDCWD = -100  # dispatch sign-extends the raw u64 fd args (managed._sfd)
AT_EMPTY_PATH = 0x1000
AT_SYMLINK_NOFOLLOW = 0x100

O_ACCMODE = 0o3
O_CREAT = 0o100
O_EXCL = 0o200
O_TRUNC = 0o1000
O_APPEND = 0o2000
O_DIRECTORY = 0o200000

ENOENT = errno.ENOENT
ENOTDIR = errno.ENOTDIR
EEXIST = errno.EEXIST
EACCES = errno.EACCES
EISDIR = errno.EISDIR
EBADF = errno.EBADF
EINVAL = errno.EINVAL
ENOTEMPTY = errno.ENOTEMPTY
EROFS = errno.EROFS


def _det_ino(path: str) -> int:
    """Deterministic inode number: stable across runs and machines."""
    h = 1469598103934665603
    for b in path.encode():
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFF
    return h | 1


class VFile:
    """Worker-side state of one virtualized open file/directory."""

    __slots__ = ("path", "vpath", "fd", "data", "off", "flags", "is_dir",
                 "dents", "dent_pos")

    def __init__(self, vpath: str, path: str, fd, data, flags: int,
                 is_dir: bool = False, dents=None):
        self.vpath = vpath  # guest-visible absolute path
        self.path = path  # real backing path ("" for synthesized)
        self.fd = fd  # os-level fd (None for synthesized content)
        self.data = data  # bytes for synthesized read-only files
        self.off = 0
        self.flags = flags
        self.is_dir = is_dir
        self.dents = dents  # sorted [(name, d_type, ino)] snapshot
        self.dent_pos = 0


class HostVFS:
    """One managed process's view of the virtual file surface. The cwd is
    tracked per process (fork children copy it); the synthesized /etc
    files are built once per simulation from the controller's host list."""

    #: inotify event bits delivered through on_mutate
    IN_MODIFY, IN_MOVED_FROM, IN_MOVED_TO = 0x2, 0x40, 0x80
    IN_CREATE, IN_DELETE, IN_ISDIR = 0x100, 0x200, 0x40000000

    def __init__(self, proc) -> None:
        self.proc = proc
        self.root = str(proc.host.controller.data_dir / "hosts"
                        / proc.host.name)
        self.cwd = self.root
        #: inotify bridge: called (real_path, mask, cookie) after a
        #: successful mutation of the worker-served tree
        self.on_mutate = None
        self._mv_cookie = 0

    def _mutated(self, real: str, mask: int, cookie: int = 0) -> None:
        if self.on_mutate is not None:
            self.on_mutate(real, mask, cookie)

    # -- path resolution ----------------------------------------------------
    def _synth(self, path: str):
        if path == "/etc/hosts":
            ctl = self.proc.host.controller
            lines = ["127.0.0.1 localhost\n"]
            for h in ctl.hosts:
                lines.append(f"{h.ip} {h.name}\n")
            return "".join(lines).encode()
        if path == "/etc/resolv.conf":
            return b"nameserver 127.0.0.53\noptions edns0\n"
        if path.startswith("/proc"):
            return self._synth_proc(path)
        if path in ("/sys/devices/system/cpu/online",
                    "/sys/devices/system/cpu/possible",
                    "/sys/devices/system/cpu/present"):
            # glibc's sysconf(_SC_NPROCESSORS_ONLN) — hence os.cpu_count —
            # reads these before falling back to /proc/stat
            return f"0-{self.SIM_CPUS - 1}\n".encode()
        return None

    # -- synthesized /proc (the virtual machine identity) -------------------
    from shadow_tpu.native.identity import SIM_CPUS, SIM_RAM  # one source

    def _synth_proc(self, path: str):
        """A minimal /proc consistent with the virtual identity: guests
        reading cpu/memory/self topology see the same deterministic
        machine on every host (VERDICT r3 item #8). Anything not listed
        stays native by policy (resolve() returns None).

        The SHIM's own /proc/self/stat read (shim_refresh_real_ids, which
        must learn REAL ids after fork/exec) rides the syscall gadget —
        IP-allowed by the filter, so it never traps and never reaches
        this synthesis; only guest-issued opens land here."""
        proc = self.proc
        if path == "/proc/cpuinfo":
            blocks = []
            for i in range(self.SIM_CPUS):
                blocks.append(
                    f"processor\t: {i}\n"
                    "vendor_id\t: ShadowTPU\n"
                    "model name\t: Shadow Virtual CPU @ 1.00GHz\n"
                    "cpu MHz\t\t: 1000.000\n"
                    "cache size\t: 1024 KB\n"
                    "physical id\t: 0\n"
                    f"core id\t\t: {i}\n"
                    f"cpu cores\t: {self.SIM_CPUS}\n"
                    "flags\t\t: fpu tsc cx8 cmov\n"
                    "bogomips\t: 2000.00\n"
                    "address sizes\t: 48 bits physical, 48 bits virtual\n"
                    "\n")
            return "".join(blocks).encode()
        if path == "/proc/meminfo":
            total_kb = self.SIM_RAM // 1024
            free_kb = (self.SIM_RAM - (256 << 20)) // 1024
            return (f"MemTotal:       {total_kb} kB\n"
                    f"MemFree:        {free_kb} kB\n"
                    f"MemAvailable:   {free_kb} kB\n"
                    "Buffers:               0 kB\n"
                    "Cached:                0 kB\n"
                    "SwapTotal:             0 kB\n"
                    "SwapFree:              0 kB\n").encode()
        if path == "/proc/uptime":
            # boot-origin simulated uptime (the monotonic clock family)
            up = proc.host.now / NS_PER_SEC
            return f"{up:.2f} {up * self.SIM_CPUS:.2f}\n".encode()
        parts = path.split("/")
        # /proc/self/X and /proc/<own vpid>/X
        if (len(parts) == 4 and parts[1] == "proc"
                and (parts[2] == "self" or parts[2] == str(proc.vpid))):
            leaf = parts[3]
            comm = Path(proc.opts.path).name[:15]
            vpid = proc.vpid
            threads = getattr(proc, "threads", None)
            nth = (sum(1 for t in threads.values() if not t.dead)
                   if threads else 1)
            ticks = proc.host.now * 100 // NS_PER_SEC  # 100 Hz jiffies
            if leaf == "stat":
                rest = [0] * 36  # fields 17..52 zeroed (deterministic)
                rest[2] = nth  # num_threads (field 20)
                return (f"{vpid} ({comm}) R 1 {vpid} {vpid} 0 -1 4194304 "
                        f"0 0 0 0 {ticks} 0 0 0 "
                        + " ".join(str(v) for v in rest) + "\n").encode()
            if leaf == "status":
                return (f"Name:\t{comm}\n"
                        "Umask:\t0022\n"
                        "State:\tR (running)\n"
                        f"Tgid:\t{vpid}\n"
                        "Ngid:\t0\n"
                        f"Pid:\t{vpid}\n"
                        "PPid:\t1\n"
                        "TracerPid:\t0\n"
                        "Uid:\t1000\t1000\t1000\t1000\n"
                        "Gid:\t1000\t1000\t1000\t1000\n"
                        "FDSize:\t64\n"
                        f"Threads:\t{nth}\n"
                        "VmSize:\t  131072 kB\n"
                        "VmRSS:\t   16384 kB\n").encode()
            if leaf == "maps":
                exe = proc.opts.path
                return (
                    "00400000-00600000 r-xp 00000000 00:00 "
                    f"{_det_ino(exe)} {exe}\n"
                    "00600000-00800000 rw-p 00200000 00:00 "
                    f"{_det_ino(exe)} {exe}\n"
                    "10000000-18000000 rw-p 00000000 00:00 0 [heap]\n"
                    "7ffe00000000-7ffe00100000 rw-p 00000000 00:00 0 "
                    "[stack]\n").encode()
        return None

    def resolve(self, dirfd: int, path: str):
        """Classify a path:
        ("synth", bytes) | ("host", realpath) | ("wnative", abspath) |
        None (shim re-issues natively). Relative paths ALWAYS absolutize
        against the WORKER-TRACKED cwd: a relative path landing outside
        the virtual root is served worker-side against that absolute path
        ("wnative") instead of re-issuing the original relative args —
        the real process's kernel cwd therefore never matters, and
        chdir/fchdir are purely virtual. Host classification keeps paths
        INSIDE the root (no .. escape)."""
        rel = not path.startswith("/")
        if rel:
            if dirfd == AT_FDCWD:
                base = self.cwd
            else:
                vs = self.proc.fds.get(dirfd)
                if (vs is not None and vs.kind == "dir"
                        and vs.vfile is not None):
                    base = vs.vfile.path
                else:
                    return None  # relative to a native dirfd: native
            path = base + "/" + path if path else base
        path = os.path.normpath(path)
        s = self._synth(path)
        if s is not None:
            return ("synth", s)
        root = self.root
        if path == root or path.startswith(root + "/"):
            return ("host", path)
        return ("wnative", path) if rel else None

    def _path_arg(self, ptr: int) -> str | None:
        if not ptr:
            return ""
        raw = self.proc._read_cstr(ptr)
        return raw

    # -- open ---------------------------------------------------------------
    def openat(self, dirfd: int, path_ptr: int, flags: int, mode: int):
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            if flags & O_ACCMODE != 0 or flags & (O_CREAT | O_TRUNC):
                return -EACCES  # synthesized files are read-only
            vf = VFile(os.path.normpath(path), "", None, tgt, flags)
            return self._install(vf, flags)
        real = tgt  # host tree or worker-served native (both absolute)
        acc = flags & O_ACCMODE
        try:
            st = os.lstat(real)
            exists = True
            isdir = statmod.S_ISDIR(st.st_mode)
        except FileNotFoundError:
            exists = False
            isdir = False
        if flags & O_DIRECTORY or (exists and isdir):
            if not exists:
                return -ENOENT
            if not isdir:
                return -ENOTDIR
            if acc != 0:
                return -EISDIR
            dents = self._snapshot_dir(real)
            vf = VFile(real, real, None, None, flags, is_dir=True,
                       dents=dents)
            return self._install(vf, flags)
        if not exists and not (flags & O_CREAT):
            return -ENOENT
        if exists and (flags & O_CREAT) and (flags & O_EXCL):
            return -EEXIST
        try:
            fd = os.open(real, flags & ~O_DIRECTORY, mode & 0o777 or 0o644)
        except OSError as e:
            return -e.errno
        if not exists:  # O_CREAT made it
            self._mutated(real, self.IN_CREATE)
        vf = VFile(real, real, fd, None, flags)
        if flags & O_APPEND:
            vf.off = os.fstat(fd).st_size
        return self._install(vf, flags)

    def _install(self, vf: VFile, flags: int) -> int:
        from shadow_tpu.native.managed import VSocket

        proc = self.proc
        vfd = proc._next_vfd
        proc._next_vfd += 1
        vs = VSocket(vfd, "dir" if vf.is_dir else "file")
        vs.vfile = vf
        proc.fds[vfd] = vs
        if flags & 0o2000000:  # O_CLOEXEC
            proc.fd_cloexec.add(vfd)
        return vfd

    def _snapshot_dir(self, real: str):
        try:
            names = sorted(os.listdir(real))
        except OSError as e:
            return -e.errno
        out = [(".", 4, _det_ino(real)),
               ("..", 4, _det_ino(os.path.dirname(real) or "/"))]
        for n in names:
            full = real + "/" + n
            try:
                st = os.lstat(full)
                dt = (4 if statmod.S_ISDIR(st.st_mode)
                      else 10 if statmod.S_ISLNK(st.st_mode) else 8)
            except OSError:
                dt = 0
            out.append((n, dt, _det_ino(full)))
        return out

    # -- fd ops (dispatched from managed.py on kind file/dir) ---------------
    def read(self, vs, n: int) -> bytes | int:
        vf = vs.vfile
        if vf.is_dir:
            return -EISDIR
        if vf.data is not None:
            chunk = vf.data[vf.off:vf.off + n]
        else:
            if vf.flags & O_ACCMODE == 0o1:  # O_WRONLY
                return -EBADF
            try:
                chunk = os.pread(vf.fd, n, vf.off)
            except OSError as e:
                return -e.errno
        vf.off += len(chunk)
        return chunk

    def pread(self, vs, n: int, off: int) -> bytes | int:
        vf = vs.vfile
        if vf.is_dir:
            return -EISDIR
        if off < 0:
            return -EINVAL
        if vf.data is not None:
            return vf.data[off:off + n]
        if vf.flags & O_ACCMODE == 0o1:  # O_WRONLY
            return -EBADF
        try:
            return os.pread(vf.fd, n, off)
        except OSError as e:
            return -e.errno

    def pwrite(self, vs, data: bytes, off: int) -> int:
        vf = vs.vfile
        if vf.is_dir or vf.data is not None:
            return -EBADF
        if vf.flags & O_ACCMODE == 0:  # O_RDONLY
            return -EBADF
        try:
            k = os.pwrite(vf.fd, data, off)
        except OSError as e:
            return -e.errno
        if k:
            self._mutated(vf.path, self.IN_MODIFY)
        return k

    def write(self, vs, data: bytes) -> int:
        vf = vs.vfile
        if vf.is_dir or vf.data is not None:
            return -EBADF
        if vf.flags & O_ACCMODE == 0:  # O_RDONLY
            return -EBADF
        try:
            if vf.flags & O_APPEND:
                vf.off = os.fstat(vf.fd).st_size
            k = os.pwrite(vf.fd, data, vf.off)
        except OSError as e:
            return -e.errno
        vf.off += k
        if k:
            self._mutated(vf.path, self.IN_MODIFY)
        return k

    def lseek(self, vs, off: int, whence: int) -> int:
        vf = vs.vfile
        if off >= 1 << 63:
            off -= 1 << 64
        if vf.is_dir:
            # rewinddir/seekdir: d_off values are snapshot indices
            if whence != 0 or off < 0:
                return -EINVAL
            vf.dent_pos = min(off, len(vf.dents)
                              if isinstance(vf.dents, list) else 0)
            vf.off = off
            return off
        if whence == 0:
            new = off
        elif whence == 1:
            new = vf.off + off
        elif whence == 2:
            size = (len(vf.data) if vf.data is not None
                    else os.fstat(vf.fd).st_size if vf.fd is not None
                    else 0)
            new = size + off
        else:
            return -EINVAL
        if new < 0:
            return -EINVAL
        vf.off = new
        return new

    def fstat_bytes(self, vs) -> bytes:
        vf = vs.vfile
        if vf.data is not None:
            return self._stat_bytes(vf.vpath, size=len(vf.data),
                                    mode=statmod.S_IFREG | 0o444)
        st = os.fstat(vf.fd) if vf.fd is not None else os.lstat(vf.path)
        return self._stat_bytes(vf.vpath, size=st.st_size,
                                mode=st.st_mode)

    def getdents64(self, vs, bufsize: int) -> bytes | int:
        vf = vs.vfile
        if not vf.is_dir:
            return -ENOTDIR
        if isinstance(vf.dents, int):
            return vf.dents
        out = b""
        while vf.dent_pos < len(vf.dents):
            name, dt, ino = vf.dents[vf.dent_pos]
            nb = name.encode()
            reclen = (19 + len(nb) + 1 + 7) & ~7
            if len(out) + reclen > bufsize:
                break
            vf.dent_pos += 1
            rec = struct.pack("<QqHB", ino, vf.dent_pos, reclen, dt)
            rec += nb + b"\0"
            rec += b"\0" * (reclen - len(rec))
            out += rec
        return out

    def close(self, vs) -> int:
        vf = vs.vfile
        if vf is not None and vf.fd is not None:
            try:
                os.close(vf.fd)
            except OSError:
                pass
            vf.fd = None
        return 0

    def ftruncate(self, vs, length: int) -> int:
        vf = vs.vfile
        if vf.is_dir or vf.data is not None or vf.fd is None:
            return -EBADF
        try:
            os.ftruncate(vf.fd, length)
        except OSError as e:
            return -e.errno
        return 0

    # -- path ops ------------------------------------------------------------
    def _stat_bytes(self, vpath: str, size: int, mode: int) -> bytes:
        """Deterministic struct stat (x86-64): sim-clock times, synthetic
        dev/ino/uid, real size/mode."""
        now = emulated(self.proc.host.now)
        sec, nsec = now // NS_PER_SEC, now % NS_PER_SEC
        st = bytearray(144)
        struct.pack_into("<QQQ", st, 0, 42, _det_ino(vpath), 1)
        struct.pack_into("<III", st, 24, mode, 1000, 1000)
        struct.pack_into("<qqq", st, 40, 0, size, 4096)
        struct.pack_into("<q", st, 64, (size + 511) // 512)
        struct.pack_into("<qqqqqq", st, 72, sec, nsec, sec, nsec, sec, nsec)
        return bytes(st)

    def statat(self, dirfd: int, path_ptr: int, buf: int,
               flags: int = 0) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        if path == "" and flags & AT_EMPTY_PATH:
            vs = self.proc.fds.get(dirfd)
            if vs is not None and vs.kind in ("file", "dir"):
                self.proc.mem.write(buf, self.fstat_bytes(vs))
                return 0
            if vs is not None:  # socket/pipe/timer vfd: the fstat shape
                return self.proc._fstat(dirfd, buf)
            return RETRY_NATIVE
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            self.proc.mem.write(buf, self._stat_bytes(
                os.path.normpath(path), len(tgt),
                statmod.S_IFREG | 0o444))
            return 0
        try:
            st = (os.lstat(tgt) if flags & AT_SYMLINK_NOFOLLOW
                  else os.stat(tgt))
        except OSError as e:
            return -e.errno
        self.proc.mem.write(buf, self._stat_bytes(tgt, st.st_size,
                                                  st.st_mode))
        return 0

    def statx(self, dirfd: int, path_ptr: int, flags: int, buf: int) -> int:
        """struct statx (256 bytes): same deterministic fields as stat."""
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        if path == "" and flags & AT_EMPTY_PATH:
            vs = self.proc.fds.get(dirfd)
            if vs is None or vs.kind not in ("file", "dir"):
                return RETRY_NATIVE
            vf = vs.vfile
            size = (len(vf.data) if vf.data is not None
                    else os.fstat(vf.fd).st_size if vf.fd is not None
                    else 0)
            mode = (statmod.S_IFDIR | 0o755 if vf.is_dir
                    else statmod.S_IFREG | 0o644)
            self.proc.mem.write(buf, self._statx_bytes(vf.vpath, size,
                                                       mode))
            return 0
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            self.proc.mem.write(buf, self._statx_bytes(
                os.path.normpath(path), len(tgt), statmod.S_IFREG | 0o444))
            return 0
        try:
            st = (os.lstat(tgt) if flags & AT_SYMLINK_NOFOLLOW
                  else os.stat(tgt))
        except OSError as e:
            return -e.errno
        self.proc.mem.write(buf, self._statx_bytes(tgt, st.st_size,
                                                   st.st_mode))
        return 0

    def _statx_bytes(self, vpath: str, size: int, mode: int) -> bytes:
        now = emulated(self.proc.host.now)
        sec, nsec = now // NS_PER_SEC, now % NS_PER_SEC
        sx = bytearray(256)
        struct.pack_into("<IIQ", sx, 0, 0xFFF, 4096, 0)  # mask, blksize
        struct.pack_into("<IIIHxxQQQQ", sx, 16,
                         1, 1000, 1000, mode & 0xFFFF,
                         _det_ino(vpath), size, (size + 511) // 512, 0)
        for off in (64, 80, 96, 112):  # btime/atime/ctime/mtime
            struct.pack_into("<qI", sx, off, sec, nsec)
        return bytes(sx)

    def access(self, dirfd: int, path_ptr: int, mode: int) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            return 0 if not (mode & 2) else -EACCES  # W_OK denied
        if not os.path.exists(tgt):
            return -ENOENT
        m = ((os.R_OK if mode & 4 else 0) | (os.W_OK if mode & 2 else 0)
             | (os.X_OK if mode & 1 else 0))
        return 0 if os.access(tgt, m) else -EACCES

    def unlinkat(self, dirfd: int, path_ptr: int, flags: int) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            return -EROFS
        try:
            if flags & 0x200:  # AT_REMOVEDIR
                os.rmdir(tgt)
                self._mutated(tgt, self.IN_DELETE | self.IN_ISDIR)
            else:
                os.unlink(tgt)
                self._mutated(tgt, self.IN_DELETE)
        except OSError as e:
            return -e.errno
        return 0

    def mkdirat(self, dirfd: int, path_ptr: int, mode: int) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            return -EEXIST
        try:
            os.mkdir(tgt, mode & 0o777)
            self._mutated(tgt, self.IN_CREATE | self.IN_ISDIR)
        except OSError as e:
            return -e.errno
        return 0

    def renameat(self, olddirfd: int, old_ptr: int, newdirfd: int,
                 new_ptr: int) -> int:
        old = self._path_arg(old_ptr)
        new = self._path_arg(new_ptr)
        if old is None or new is None:
            return -errno.EFAULT
        ro = self.resolve(olddirfd, old)
        rn = self.resolve(newdirfd, new)
        if ro is None and rn is None:
            return RETRY_NATIVE
        if ro is None or rn is None or ro[0] == "synth" or rn[0] == "synth":
            return -errno.EXDEV  # across the virtualization boundary
        try:
            os.rename(ro[1], rn[1])
            self._mv_cookie += 1
            self._mutated(ro[1], self.IN_MOVED_FROM, self._mv_cookie)
            self._mutated(rn[1], self.IN_MOVED_TO, self._mv_cookie)
        except OSError as e:
            return -e.errno
        return 0

    def readlinkat(self, dirfd: int, path_ptr: int, buf: int,
                   bufsize: int) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(dirfd, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            return -EINVAL  # not a symlink
        try:
            link = os.readlink(tgt)
        except OSError as e:
            return -e.errno
        data = link.encode()[:bufsize]
        self.proc.mem.write(buf, data)
        return len(data)

    def chdir(self, path_ptr: int) -> int:
        """Purely virtual: the worker-tracked cwd is the only one that
        matters (every relative path absolutizes against it in resolve),
        so the real process's kernel cwd can stay stale."""
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(AT_FDCWD, path)
        if r is None:
            tgt = os.path.normpath(path)
        else:
            kind, tgt = r
            if kind == "synth":
                return -ENOTDIR
        if not os.path.isdir(tgt):
            return -ENOENT if not os.path.exists(tgt) else -ENOTDIR
        self.cwd = tgt
        return 0

    def fchdir(self, vs) -> int:
        vf = vs.vfile
        if vf is None or not vf.is_dir:
            return -ENOTDIR
        self.cwd = vf.path
        return 0

    def getcwd(self, buf: int, size: int) -> int:
        data = self.cwd.encode() + b"\0"
        if len(data) > size:
            return -errno.ERANGE
        self.proc.mem.write(buf, data)
        return len(data)

    def truncate(self, path_ptr: int, length: int) -> int:
        path = self._path_arg(path_ptr)
        if path is None:
            return -errno.EFAULT
        r = self.resolve(AT_FDCWD, path)
        if r is None:
            return RETRY_NATIVE
        kind, tgt = r
        if kind == "synth":
            return -EACCES
        try:
            os.truncate(tgt, length)
        except OSError as e:
            return -e.errno
        return 0
