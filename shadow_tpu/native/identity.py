"""The deterministic virtual machine identity — defined ONCE.

Every guest-visible surface derives from these: the sched_getaffinity
mask, sysinfo, the synthesized /proc/cpuinfo, /proc/meminfo and
/sys/devices/system/cpu files (native/vfs.py), and the uptime family.

SIM_CPUS is 1 ON PURPOSE: glibc treats nprocs>1 as SMP and spin-waits on
contended locks natively; under one-runnable-thread-at-a-time turn-taking
a spinner never yields and the lock holder never runs (reproduced with
CPython threading the moment /sys reported 2 CPUs). On one CPU every
contended lock futex-waits immediately — which is emulated."""

SIM_CPUS = 1
SIM_RAM = 2 << 30  # bytes; sysinfo reports 256 MB of it in use
