"""Managed-process memory access — the MemoryManager equivalent.

Reference analog: SURVEY.md §2 "MemoryManager" (reads/writes managed-process
memory for syscall arguments). The reference maps guest memory; we use the
kernel's cross-address-space copy syscalls (process_vm_readv/writev) via
ctypes — no /proc parsing, one syscall per access, and the shim stays
completely ignorant of argument semantics.
"""

from __future__ import annotations

import ctypes
import ctypes.util

_libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)


class _IoVec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


def _vm_call(fn, pid: int, local_buf, remote_addr: int, n: int) -> int:
    local = _IoVec(ctypes.cast(local_buf, ctypes.c_void_p), n)
    remote = _IoVec(ctypes.c_void_p(remote_addr), n)
    got = fn(pid, ctypes.byref(local), 1, ctypes.byref(remote), 1, 0)
    if got < 0:
        raise OSError(ctypes.get_errno(), f"process_vm op failed (pid {pid})")
    return got


class ProcessMemory:
    """Read/write one managed process's address space."""

    def __init__(self, pid: int) -> None:
        self.pid = pid

    def read(self, addr: int, n: int) -> bytes:
        if n <= 0:
            return b""
        buf = ctypes.create_string_buffer(n)
        got = _vm_call(_libc.process_vm_readv, self.pid, buf, addr, n)
        return buf.raw[:got]

    def write(self, addr: int, data: bytes) -> int:
        if not data:
            return 0
        buf = ctypes.create_string_buffer(data, len(data))
        return _vm_call(_libc.process_vm_writev, self.pid, buf, addr, len(data))

    def read_cstr(self, addr: int, limit: int = 4096) -> bytes:
        """NUL-terminated guest string, read page-by-page: process_vm_readv
        fails wholesale if any page is unmapped, so never read past the
        page holding the terminator."""
        out = b""
        while len(out) < limit:
            avail = min(4096 - ((addr + len(out)) & 4095), limit - len(out))
            chunk = self.read(addr + len(out), avail)
            if b"\0" in chunk:
                return out + chunk.split(b"\0", 1)[0]
            out += chunk
        return out
