"""Native pieces: the managed-process layer and the _colcore C engine.

SHADOW_TPU_COLCORE_SO points the loader at an alternate _colcore build
— the ci.sh sanitize-smoke gate runs the whole simulator against the
ASan/UBSan build in native/build/asan/ without touching the optimized
extension the rest of the tree imports.  The override must be installed
before anything imports the packaged submodule, and every import of
shadow_tpu.native._colcore passes through this package first.
"""

import importlib.util as _ilu
import os as _os
import sys as _sys

_so = _os.environ.get("SHADOW_TPU_COLCORE_SO")
if _so and "shadow_tpu.native._colcore" not in _sys.modules:
    _spec = _ilu.spec_from_file_location("shadow_tpu.native._colcore", _so)
    _mod = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_mod)
    _sys.modules["shadow_tpu.native._colcore"] = _mod
    _colcore = _mod  # `from shadow_tpu.native import _colcore` resolves here
del _ilu, _os, _so, _sys

from shadow_tpu.native.managed import ManagedProcess  # noqa: E402,F401
