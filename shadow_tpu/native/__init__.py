from shadow_tpu.native.managed import ManagedProcess  # noqa: F401
